"""Fixed-slot metric counters — parity with
``apps/emqx/src/emqx_metrics.erl``.

The reference allocates one BEAM ``counters`` array (C, per-scheduler
striped) at boot with a fixed name→index map kept in ``persistent_term``
(emqx_metrics.erl:338-384,541-542). Here: one numpy int64 array + a
frozen name→slot dict built at construction; ``inc`` is a single
in-place array add under the GIL. Dynamic late registration appends to a
spillover dict (the reference forbids it; we allow it for rule/bridge
metrics which the reference hosts in emqx_metrics_worker instead — see
``MetricsWorker`` below).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Iterable, Optional

import numpy as np

# emqx_metrics.hrl name set (bytes/packets/messages/delivery/client/
# session/authz slices), trimmed of reserved-for-future slots
BYTES = ["bytes.received", "bytes.sent"]
PACKETS = [
    "packets.received", "packets.sent",
    "packets.connect.received", "packets.connack.sent",
    "packets.publish.received", "packets.publish.sent",
    "packets.publish.error", "packets.publish.auth_error",
    "packets.publish.dropped",
    "packets.puback.received", "packets.puback.sent",
    "packets.puback.missed",
    "packets.pubrec.received", "packets.pubrec.sent",
    "packets.pubrec.missed",
    "packets.pubrel.received", "packets.pubrel.sent",
    "packets.pubrel.missed",
    "packets.pubcomp.received", "packets.pubcomp.sent",
    "packets.pubcomp.missed",
    "packets.subscribe.received", "packets.suback.sent",
    "packets.subscribe.error", "packets.subscribe.auth_error",
    "packets.unsubscribe.received", "packets.unsuback.sent",
    "packets.unsubscribe.error",
    "packets.pingreq.received", "packets.pingresp.sent",
    "packets.disconnect.received", "packets.disconnect.sent",
    "packets.auth.received", "packets.auth.sent",
    "packets.connect.error", "packets.connect.auth_error",
]
MESSAGES = [
    "messages.received", "messages.sent",
    "messages.qos0.received", "messages.qos0.sent",
    "messages.qos1.received", "messages.qos1.sent",
    "messages.qos2.received", "messages.qos2.sent",
    "messages.publish", "messages.dropped",
    "messages.dropped.await_pubrel_timeout", "messages.dropped.no_subscribers",
    "messages.forward", "messages.retained", "messages.delayed",
    "messages.delivered", "messages.acked",
    # forward-lane split (ISSUE 4 satellite): .native counts trunked
    # legs (C++ trunk plane, folded by native_server._merge_fast_
    # metrics), .slow the Python forward_fn lane; messages.forward
    # stays the total. Fixed slots so both render at zero and ride the
    # $SYS metrics heartbeat before the first cross-node leg.
    "messages.forward.native", "messages.forward.slow",
]
DELIVERY = [
    "delivery.dropped", "delivery.dropped.no_local",
    "delivery.dropped.too_large", "delivery.dropped.qos0_msg",
    "delivery.dropped.queue_full", "delivery.dropped.expired",
]
# native (below-the-GIL) fast-path counters, folded in batches by
# broker/native_server.py: per-qos publish splits, batched ack-plane
# completions, and the per-topic device-lane overload drop (distinct
# from delivery backpressure by design — VERDICT r5 satellite)
NATIVE = [
    "messages.native.received",
    "messages.native.qos1.received", "messages.native.qos2.received",
    "messages.native.acked",
    "messages.native.lane_topic_overflow",
    # device-path batches served from the host oracle after a model
    # failure (broker._device_failover) — a fixed slot so it renders at
    # zero in prometheus/$SYS instead of appearing only after the first
    # failover (PR 2 counted it; nothing surfaced it)
    "messages.device_failover",
    # durable-session plane (round 10): .stored counts markers written
    # for publishes the C++ host persisted below the GIL (kind-10
    # reconciliation), .replayed counts messages drained from the
    # native store on clean_start=false resume, .settled counts
    # markers spent at the SETTLE seam — subscriber ack / qos0 write /
    # final drop, the round-18 consume-on-ack contract. Fixed slots:
    # all render at zero and ride the $SYS metrics heartbeat.
    "messages.durable.stored", "messages.durable.replayed",
    "messages.durable.settled",
    # degradation ledger (round 13): one fixed slot per ladder-decision
    # reason (DegradationLedger folds both the C++ kind-12 ledger
    # entries and the Python-plane decisions here), so every reason
    # renders at zero in prometheus and rides the $SYS heartbeat before
    # the first degradation ever happens.
    "messages.ledger.ring_full", "messages.ledger.trunk_punt",
    "messages.ledger.shed", "messages.ledger.fault",
    "messages.ledger.accept_shed", "messages.ledger.coap_giveup",
    "messages.ledger.device_failover",
    "messages.ledger.store_degraded",
    # conn-scale plane (round 16): hibernation + accept-storm shedding.
    # Cumulative event counters folded from the host's stat slots by
    # native_server._merge_fast_metrics — fixed so all three render at
    # zero and ride the $SYS metrics heartbeat before the first park.
    "conns.parked", "conns.inflated", "conns.shed",
]
# faultline (round 15): one fixed slot per fault-injection site, so
# every faults.<site> counter renders at zero in prometheus/$SYS before
# the first injection — canonical site order mirrors native/__init__.py
# FAULT_SITES (test_stats_lint pins the pair against the fault.h enum)
FAULT_SITES = ("conn_read", "conn_write", "conn_accept",
               "trunk_read", "trunk_write", "trunk_accept",
               "trunk_connect", "store_msync", "store_seg_open",
               "ring_seal", "ring_doorbell", "housekeep_clock")
FAULTS = [f"faults.{s}" for s in FAULT_SITES]
CLIENT = [
    "client.connect", "client.connack", "client.connected",
    "client.authenticate", "client.auth.anonymous", "client.authorize",
    "client.subscribe", "client.unsubscribe", "client.disconnected",
]
SESSION = [
    "session.created", "session.resumed", "session.takenover",
    "session.discarded", "session.terminated",
]
AUTHZ = ["authorization.allow", "authorization.deny",
         "authorization.cache_hit", "authorization.cache_miss"]
OLP = ["olp.delay.ok", "olp.delay.timeout", "olp.hbn", "olp.gc",
       "olp.new_conn"]

# kernel plane (ISSUE 18): device-router observability. Fixed slots so
# every counter renders at zero in prometheus and rides the $SYS metrics
# heartbeat before the first batch. messages.kernel.hostmatch counts
# batches the cpu host-matcher served (RouterModel.host_match_count,
# promoted from an ad-hoc attribute); kernel.uploads/upload_patches
# mirror the full-upload and incremental-scatter counts the same way.
# The two messages.ledger.* slots back the kernel_overflow /
# kernel_hostmatch degradation reasons (appended at the END of
# LEDGER_REASONS — Python-plane reasons, so the C++ enum stays a prefix).
KERNEL = [
    "messages.kernel.hostmatch",
    "kernel.uploads", "kernel.upload_patches",
    "messages.ledger.kernel_overflow",
    "messages.ledger.kernel_hostmatch",
]

ALL_NAMES: list[str] = (BYTES + PACKETS + MESSAGES + DELIVERY + NATIVE
                        + FAULTS + CLIENT + SESSION + AUTHZ + OLP
                        + KERNEL)


# ---------------------------------------------------------------------------
# latency histograms (native telemetry plane)
#
# HDR-histogram-style log-bucketed capture: 64 fixed buckets at
# ~power-of-√2 spacing, mirroring host.cc HistBucket EXACTLY — the C++
# poll thread bumps plain uint64 arrays and ships per-cycle deltas
# (event kind 8); this class is the Python accumulator those deltas
# fold into, and the percentile/exposition surface for prometheus,
# $SYS, and bench.py.


def _hist_edges() -> tuple:
    """Upper bucket edges in ns. Bucket 0 = [0,2); for MSB position
    e >= 1, bucket 2e-1 tops at √2·2^e (1448/1024 fixed-point, the C++
    comparison) and bucket 2e at 2^(e+1); bucket 63 = +inf."""
    edges: list[float] = [2.0]
    for e in range(1, 32):
        edges.append((1448 << e) / 1024.0)
        edges.append(float(1 << (e + 1)))
    return tuple(edges + [float("inf")])  # 63 finite edges + inf


HIST_EDGES_NS: tuple = _hist_edges()


def hist_bucket(ns: int) -> int:
    """Python mirror of host.cc HistBucket (differential-tested)."""
    ns = int(ns)
    if ns < 2:
        return 0
    e = ns.bit_length() - 1
    if e >= 32:
        return 63
    return 2 * e - 1 + (1 if (ns << 10) >= (1448 << e) else 0)


class LatencyHistogram:
    """Fixed 64-bucket log-scale latency histogram (sum/count carried
    alongside, prometheus-histogram shaped). Not thread-safe: owners
    feed it from one thread (the native poll thread's _on_telemetry)
    and readers tolerate torn-but-monotone snapshots like the counter
    array above."""

    __slots__ = ("counts", "sum_ns", "count", "exemplars")

    def __init__(self) -> None:
        self.counts = np.zeros(64, dtype=np.int64)
        self.sum_ns = 0
        self.count = 0
        # OpenMetrics exemplars (round 13): bucket index -> (trace_id,
        # value_ns, unix_ts) — the most recent sampled trace whose
        # measured duration landed in that bucket; prometheus renders
        # them on the _bucket lines so operators can jump from a
        # histogram spike straight to a stitched trace timeline
        self.exemplars: dict = {}

    def observe(self, ns: int) -> None:
        self.counts[hist_bucket(ns)] += 1
        self.sum_ns += int(ns)
        self.count += 1

    def observe_delta(self, count_d: int, sum_d: int,
                      bucket_deltas: dict[int, int]) -> None:
        """Fold one kind-8 per-cycle delta record in."""
        self.count += count_d
        self.sum_ns += sum_d
        for idx, d in bucket_deltas.items():
            self.counts[idx] += d

    def put_exemplar(self, trace_id: int, value_ns: int) -> None:
        """Hang a trace id off the bucket ``value_ns`` falls into."""
        self.exemplars[hist_bucket(value_ns)] = (
            int(trace_id), int(value_ns), time.time())

    def percentile(self, q: float) -> float:
        """q in [0,1] -> ns, linearly interpolated inside the bucket
        (the +inf bucket reports its lower edge)."""
        if self.count <= 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i in range(64):
            c = int(self.counts[i])
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= target:
                lo = HIST_EDGES_NS[i - 1] if i else 0.0
                hi = HIST_EDGES_NS[i]
                if hi == float("inf"):
                    return lo
                return lo + (hi - lo) * max(0.0, target - prev) / c
        return 0.0

    def summary(self) -> dict:
        """p50/p99/p999 in µs + count/sum — the bench artifact shape."""
        return {
            "count": int(self.count),
            "sum_ms": round(self.sum_ns / 1e6, 3),
            "p50_us": round(self.percentile(0.5) / 1e3, 2),
            "p99_us": round(self.percentile(0.99) / 1e3, 2),
            "p999_us": round(self.percentile(0.999) / 1e3, 2),
        }


# ---------------------------------------------------------------------------
# degradation ledger (round 13)
#
# Every native-plane degradation-ladder decision — ring-full→punt,
# trunk→punt, kHighWater shed — and every Python-plane one — device
# failover, durable-store degradation — used to be visible only as bare
# counters: when `trunk_punts` ticked up at 3am there was no record of
# WHICH messages degraded or WHY. The ledger holds a bounded in-memory
# event ring (surfaced via $SYS and the mgmt API) next to per-reason
# FIXED metric slots (messages.ledger.*), each event carrying the
# deciding shard, the reason, and the active trace id when the decision
# hit a sampled publish.

# canonical reason set — must match native/__init__.py LEDGER_REASONS
# (test_stats_lint pins the pair; the C++ LedgerReason enum is a prefix:
# "fault" is a faultline injection firing, round 15). kernel_overflow /
# kernel_hostmatch (ISSUE 18) are Python-plane reasons folded at the
# broker's publish_batch_collect seam — appended at the END so the C++
# prefix is preserved.
LEDGER_REASONS = ("ring_full", "trunk_punt", "shed", "fault",
                  "accept_shed", "coap_giveup",
                  "device_failover", "store_degraded",
                  "kernel_overflow", "kernel_hostmatch")


class DegradationLedger:
    """Bounded ring of structured degradation events + per-reason
    totals folded into the fixed ``messages.ledger.*`` metric slots.
    Thread-safe: C++ ledger entries arrive on N poll threads while
    Python-plane sources (broker device failover, the durable-store
    degradation watch) record from theirs."""

    def __init__(self, metrics: Optional["Metrics"] = None,
                 maxlen: int = 256) -> None:
        self._events: deque = deque(maxlen=maxlen)
        self._totals: dict[str, int] = {r: 0 for r in LEDGER_REASONS}
        self._metrics = metrics
        self._lock = threading.Lock()

    def record(self, reason: str, count: int = 1, *, shard: int = 0,
               trace_id: int = 0, aux: int = 0,
               detail: str = "") -> None:
        with self._lock:
            self._totals[reason] = self._totals.get(reason, 0) + count
            self._events.append({
                "ts_ms": int(time.time() * 1000), "reason": reason,
                "count": int(count), "shard": int(shard),
                "trace_id": int(trace_id), "aux": int(aux),
                "detail": detail,
            })
        if self._metrics is not None and reason in LEDGER_REASONS:
            self._metrics.inc(f"messages.ledger.{reason}", count)

    def totals(self) -> dict[str, int]:
        with self._lock:
            return dict(self._totals)

    def recent(self, limit: int = 64) -> list[dict]:
        """Newest-last event dicts (the mgmt/$SYS surface)."""
        with self._lock:
            ev = list(self._events)
        return ev[-limit:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


class Metrics:
    def __init__(self, names: Optional[Iterable[str]] = None) -> None:
        names = list(names) if names is not None else list(ALL_NAMES)
        self._idx: dict[str, int] = {n: i for i, n in enumerate(names)}
        self._c = np.zeros(len(names), dtype=np.int64)
        self._dyn: dict[str, int] = {}
        self._hists: dict[str, LatencyHistogram] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: int = 1) -> None:
        i = self._idx.get(name)
        if i is not None:
            self._c[i] += n
            return
        with self._lock:
            self._dyn[name] = self._dyn.get(name, 0) + n

    def val(self, name: str) -> int:
        i = self._idx.get(name)
        if i is not None:
            return int(self._c[i])
        return self._dyn.get(name, 0)

    def all(self) -> dict[str, int]:
        out = {n: int(self._c[i]) for n, i in self._idx.items()}
        out.update(self._dyn)
        return out

    def reset(self) -> None:
        self._c[:] = 0
        with self._lock:
            self._dyn.clear()
            for h in self._hists.values():
                h.counts[:] = 0
                h.sum_ns = h.count = 0
                h.exemplars.clear()

    # -- latency histograms -------------------------------------------------

    def register_hist(self, name: str) -> LatencyHistogram:
        """Idempotent: one LatencyHistogram per name (e.g.
        ``latency.native.ingress_route``), shared by all callers."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram()
            return h

    def hist(self, name: str) -> Optional[LatencyHistogram]:
        return self._hists.get(name)

    def hists(self) -> dict[str, LatencyHistogram]:
        return dict(self._hists)

    # -- convenience used by the packet host --------------------------------

    def inc_recv_packet(self, type_name: str) -> None:
        self.inc("packets.received")
        self.inc(f"packets.{type_name}.received")

    def inc_sent_packet(self, type_name: str) -> None:
        self.inc("packets.sent")
        self.inc(f"packets.{type_name}.sent")

    def inc_msg(self, direction: str, qos: int) -> None:
        self.inc(f"messages.{direction}")
        if qos in (0, 1, 2):
            self.inc(f"messages.qos{qos}.{direction}")


class MetricsWorker:
    """Per-resource dynamic counters + EWMA rates — parity with
    ``apps/emqx/src/emqx_metrics_worker.erl`` (rule-engine / bridge
    metrics). Each (id, name) holds a counter and a 5s-EWMA rate."""

    TAU = 5.0

    def __init__(self) -> None:
        self._c: dict[str, dict[str, int]] = {}
        self._rate: dict[str, dict[str, tuple[float, float, int]]] = {}
        # rate entry: (ewma_per_s, last_ts, last_count)

    def create_metrics(self, id_: str,
                       names: Iterable[str] = ()) -> None:
        self._c.setdefault(id_, {n: 0 for n in names})
        self._rate.setdefault(id_, {})

    def clear_metrics(self, id_: str) -> None:
        self._c.pop(id_, None)
        self._rate.pop(id_, None)

    def inc(self, id_: str, name: str, n: int = 1) -> None:
        d = self._c.setdefault(id_, {})
        d[name] = d.get(name, 0) + n

    def get(self, id_: str, name: str) -> int:
        return self._c.get(id_, {}).get(name, 0)

    def get_counters(self, id_: str) -> dict[str, int]:
        return dict(self._c.get(id_, {}))

    def tick(self, now: Optional[float] = None) -> None:
        """Advance EWMA rates (the reference's per-second timer)."""
        now = time.time() if now is None else now
        for id_, counters in self._c.items():
            rates = self._rate.setdefault(id_, {})
            for name, count in counters.items():
                ewma, last_ts, last_count = rates.get(
                    name, (0.0, now, count))
                dt = max(now - last_ts, 1e-9)
                inst = (count - last_count) / dt
                alpha = 1.0 - pow(2.718281828, -dt / self.TAU)
                rates[name] = (ewma + alpha * (inst - ewma), now, count)

    def get_rate(self, id_: str, name: str) -> float:
        return self._rate.get(id_, {}).get(name, (0.0, 0.0, 0))[0]
