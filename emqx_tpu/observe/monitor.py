"""Dashboard rate monitor — ``emqx_dashboard_monitor.erl`` analogue.

Periodically samples the broker's counter/gauge surface into a bounded
time-series ring; the dashboard reads back N seconds of history plus a
"current rates" view (deltas per sampling interval → msg/s).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

# counters sampled for rate derivation (matches the reference's
# ?SAMPLER_LIST: received/sent/dropped + conn/sub/topic gauges)
RATE_COUNTERS = ("messages.received", "messages.sent", "messages.dropped")
GAUGES = ("connections.count", "subscriptions.count", "topics.count",
          "retained.count")

DEFAULT_RETENTION_S = 7 * 24 * 3600
DEFAULT_INTERVAL_S = 10.0


class DashboardMonitor:
    def __init__(self, app, interval_s: float = DEFAULT_INTERVAL_S,
                 retention_s: float = DEFAULT_RETENTION_S) -> None:
        self.app = app
        self.interval_s = interval_s
        self.maxlen = max(1, int(retention_s / interval_s))
        self.samples: deque = deque(maxlen=self.maxlen)
        self._last_counters: Optional[dict[str, int]] = None
        self._last_sample_at = 0.0
        self._lock = threading.RLock()

    def _read(self, tick_stats: bool = False
              ) -> tuple[dict[str, int], dict[str, int]]:
        m = self.app.metrics
        counters = {k: m.val(k) for k in RATE_COUNTERS}
        if tick_stats:
            # only when nothing else refreshed the gauges (REST reads);
            # the housekeeping path ticks stats right before monitor.tick
            self.app.stats.tick()
        s = self.app.stats.all()
        gauges = {k: s.get(k, 0) for k in GAUGES}
        return counters, gauges

    def sample(self, now: Optional[float] = None) -> dict:
        """Take one sample (idempotent within the interval via tick())."""
        now = time.time() if now is None else now
        with self._lock:
            counters, gauges = self._read()
            rates = {}
            if self._last_counters is not None:
                dt = max(now - self._last_sample_at, 1e-9)
                for k in RATE_COUNTERS:
                    delta = counters[k] - self._last_counters[k]
                    rates[k.replace("messages.", "") + "_rate"] = round(
                        max(delta, 0) / dt, 3)
            self._last_counters = counters
            self._last_sample_at = now
            point = {"time_stamp": int(now * 1000), **counters, **gauges,
                     **rates}
            self.samples.append(point)
            return point

    def tick(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        if now - self._last_sample_at < self.interval_s:
            return False
        self.sample(now)
        return True

    def history(self, latest_s: Optional[float] = None) -> list[dict]:
        with self._lock:
            if latest_s is None:
                return list(self.samples)
            cutoff = (time.time() - latest_s) * 1000
            return [p for p in self.samples if p["time_stamp"] >= cutoff]

    def current(self) -> dict:
        """The dashboard's headline card: live gauges + latest rates."""
        with self._lock:
            counters, gauges = self._read(tick_stats=True)
            latest = self.samples[-1] if self.samples else {}
            return {
                **counters, **gauges,
                **{k: v for k, v in latest.items() if k.endswith("_rate")},
            }
