"""$SYS heartbeat topics — parity with ``apps/emqx/src/emqx_sys.erl``.

Publishes retained broker liveness under ``$SYS/brokers[/<node>/...]``
(version/uptime/datetime/sysdescr, emqx_sys.erl:80-120) on a heartbeat
interval, plus stats and metrics trees on a (slower) tick. $SYS messages
are produced broker-internally and routed like any publish — wildcard
root filters never see them ($SYS exclusion in the trie matcher).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from emqx_tpu.core.message import Message

VERSION = "0.1.0"
SYSDESCR = "emqx_tpu broker"


class SysHeartbeat:
    def __init__(self, node: str, publish_fn: Callable[[Message], None],
                 metrics=None, stats=None, ledger=None, kernel=None,
                 heartbeat_s: float = 30.0, tick_s: float = 60.0) -> None:
        self.node = node
        self.publish_fn = publish_fn
        self.metrics = metrics
        self.stats = stats
        self.ledger = ledger    # DegradationLedger (round 13), optional
        self.kernel = kernel    # DeviceMetricsFold (round 19), optional
        self.heartbeat_s = heartbeat_s
        self.tick_s = tick_s
        self.started_at = time.time()
        self._last_heartbeat = 0.0
        self._last_tick = 0.0

    def uptime_s(self) -> float:
        return time.time() - self.started_at

    def _pub(self, subtopic: str, payload: str) -> None:
        self.publish_fn(Message(
            topic=f"$SYS/brokers/{self.node}/{subtopic}",
            payload=payload.encode(), qos=0, from_="$SYS",
            flags={"retain": True, "sys": True},
        ))

    def heartbeat(self) -> None:
        self.publish_fn(Message(
            topic="$SYS/brokers", payload=self.node.encode(), qos=0,
            from_="$SYS", flags={"retain": True, "sys": True}))
        self._pub("version", VERSION)
        self._pub("sysdescr", SYSDESCR)
        self._pub("uptime", str(int(self.uptime_s())))
        self._pub("datetime",
                  time.strftime("%Y-%m-%dT%H:%M:%S%z", time.localtime()))

    def publish_stats(self) -> None:
        if self.stats is not None:
            for name, val in self.stats.all().items():
                self._pub(f"stats/{name}", str(val))

    def publish_metrics(self) -> None:
        if self.metrics is not None:
            for name, val in self.metrics.all().items():
                self._pub(f"metrics/{name}", str(val))

    def publish_latency(self) -> None:
        """Latency heartbeat from the telemetry plane's histograms:
        ``$SYS/brokers/<node>/latency/<stage>/p50|p99|p999`` in ms
        (plus ``.../count``). Histogram names like
        ``latency.native.ingress_route`` map to
        ``latency/native/ingress_route``; stages with no observations
        publish nothing."""
        hists = getattr(self.metrics, "hists", None)
        if not callable(hists):
            return
        for name, h in hists().items():
            if h.count <= 0:
                continue
            base = name.replace(".", "/")
            if not base.startswith("latency/"):
                base = "latency/" + base
            for q, v in (("p50", h.percentile(0.5)),
                         ("p99", h.percentile(0.99)),
                         ("p999", h.percentile(0.999))):
                self._pub(f"{base}/{q}", f"{v / 1e6:.3f}")
            self._pub(f"{base}/count", str(int(h.count)))

    def publish_ledger(self) -> None:
        """Degradation-ledger heartbeat (round 13):
        ``$SYS/brokers/<node>/ledger/<reason>`` = total decisions per
        reason, plus ``ledger/last`` = the newest structured event —
        the $SYS face of the bounded event ring the mgmt API pages."""
        if self.ledger is None:
            return
        for reason, total in self.ledger.totals().items():
            self._pub(f"ledger/{reason}", str(total))
        recent = self.ledger.recent(1)
        if recent:
            import json

            self._pub("ledger/last", json.dumps(recent[-1]))

    def publish_kernel(self) -> None:
        """Kernel-plane heartbeat (round 19):
        ``$SYS/brokers/<node>/kernel/<stage>/p50|p99`` in ms plus
        ``.../count`` for every device-path stage histogram
        (submit/step/decode). Unlike publish_latency this publishes
        UNCONDITIONALLY — a kernel stage that never observed anything
        is itself a signal (the device plane is dark), so the fixed
        stage set renders at zero."""
        if self.kernel is None:
            return
        for stage, h in self.kernel.stage_hists().items():
            for q, v in (("p50", h.percentile(0.5)),
                         ("p99", h.percentile(0.99))):
                self._pub(f"kernel/{stage}/{q}", f"{v / 1e6:.3f}")
            self._pub(f"kernel/{stage}/count", str(int(h.count)))

    def tick(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        if now - self._last_heartbeat >= self.heartbeat_s:
            self._last_heartbeat = now
            self.heartbeat()
        if now - self._last_tick >= self.tick_s:
            self._last_tick = now
            self.publish_stats()
            self.publish_metrics()
            self.publish_latency()
            self.publish_ledger()
            self.publish_kernel()
