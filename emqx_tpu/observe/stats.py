"""Gauges with high-water marks — parity with
``apps/emqx/src/emqx_stats.erl``.

``setstat(stat, max_stat, val)`` updates a gauge and ratchets its
companion ``*.max``; updater funs registered with ``set_updater`` run on
the housekeeping tick (the reference's periodic ``update_interval``
casts from broker/cm/router helpers).
"""

from __future__ import annotations

from typing import Callable, Optional

NAMES = [
    "connections.count", "connections.max",
    "live_connections.count", "live_connections.max",
    "sessions.count", "sessions.max",
    "topics.count", "topics.max",
    "suboptions.count", "suboptions.max",
    "subscribers.count", "subscribers.max",
    "subscriptions.count", "subscriptions.max",
    "subscriptions.shared.count", "subscriptions.shared.max",
    "retained.count", "retained.max",
    "delayed.count", "delayed.max",
]


class Stats:
    def __init__(self) -> None:
        self._v: dict[str, int] = {n: 0 for n in NAMES}
        self._updaters: dict[str, Callable[[], int]] = {}

    def setstat(self, stat: str, val: int,
                max_stat: Optional[str] = None) -> None:
        self._v[stat] = val
        if max_stat is not None and val > self._v.get(max_stat, 0):
            self._v[max_stat] = val

    def getstat(self, stat: str) -> int:
        return self._v.get(stat, 0)

    def all(self) -> dict[str, int]:
        return dict(self._v)

    def set_updater(self, stat: str, fn: Callable[[], int],
                    max_stat: Optional[str] = None) -> None:
        self._updaters[stat] = fn
        if max_stat is not None:
            self._max_of = getattr(self, "_max_of", {})
            self._max_of[stat] = max_stat

    def tick(self) -> None:
        max_of = getattr(self, "_max_of", {})
        for stat, fn in self._updaters.items():
            try:
                self.setstat(stat, int(fn()), max_of.get(stat))
            except Exception:
                pass
