"""Observability (SURVEY.md §1 L12): counters, gauges, alarms,
$SYS heartbeats, Prometheus exposition, slow-subscriber tracking."""
