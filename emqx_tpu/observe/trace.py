"""Client/topic trace — the ``apps/emqx/src/emqx_trace/`` analogue.

The reference installs filtered ``logger_disk_log_h`` handlers per trace
(filter_clientid | filter_topic | filter_ip_address,
emqx_trace_handler.erl:89-145) over scheduled start/stop records kept in
mnesia (emqx_trace.erl:152,295-364). Here each trace is a filter + ring
buffer (optionally mirrored to a file) fed from the broker hookpoints;
the management API exposes list/start/stop/download.

TPU note: device-side match batches are traced at batch granularity by
the router model's stats; this module covers the host-side per-client
flight recorder the operator actually greps.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from emqx_tpu.core import topic as T


@dataclass
class Trace:
    name: str
    filter_type: str            # clientid | topic | ip_address
    filter_value: str
    start_at: float
    end_at: Optional[float] = None          # None = until stopped
    status: str = "running"                 # running | stopped
    max_lines: int = 10_000
    # clientid traces only (round 13): "punt" forces the traced conn's
    # publishes through the Python plane (full hook fidelity — every
    # message logged, at slow-path cost); "native" keeps the conn on
    # the fast path and logs the 1-in-N SAMPLED publishes' span
    # timelines instead (SPAN lines fed by the native server), so
    # tracing a production workload no longer turns off the thing
    # being observed.
    mode: str = "punt"
    lines: deque = field(default_factory=deque)

    def matches(self, clientid: str, topic: Optional[str],
                peername: str) -> bool:
        if self.filter_type == "clientid":
            return clientid == self.filter_value
        if self.filter_type == "topic":
            return topic is not None and T.match(topic, self.filter_value)
        if self.filter_type == "ip_address":
            return peername.split(":")[0] == self.filter_value
        return False

    def log(self, event: str, detail: str) -> None:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        self.lines.append(f"{ts} [{event}] {detail}")
        while len(self.lines) > self.max_lines:
            self.lines.popleft()


class TraceManager:
    """Start/stop-scheduled traces fed from hookpoints."""

    def __init__(self, max_traces: int = 32) -> None:
        self.max_traces = max_traces
        self.traces: dict[str, Trace] = {}
        self._lock = threading.RLock()
        # fired after start/stop/delete — the native host flushes its
        # publish permits here so a new trace sees topics that were
        # already on the fast path (broker/native_server.py); without
        # this a fresh trace could miss up to permit-TTL of messages
        self.on_topology_change: list = []

    # -- lifecycle -----------------------------------------------------------

    def start(self, name: str, filter_type: str, filter_value: str,
              duration_s: Optional[float] = None,
              mode: str = "punt") -> Trace:
        if filter_type not in ("clientid", "topic", "ip_address"):
            raise ValueError(f"bad trace filter type {filter_type}")
        if mode not in ("punt", "native"):
            raise ValueError(f"bad trace mode {mode}")
        with self._lock:
            if name in self.traces:
                raise ValueError(f"trace {name} already exists")
            if len(self.traces) >= self.max_traces:
                raise ValueError("too many traces")
            now = time.time()
            tr = Trace(name=name, filter_type=filter_type,
                       filter_value=filter_value, start_at=now,
                       end_at=now + duration_s if duration_s else None,
                       mode=mode)
            self.traces[name] = tr
        for cb in self.on_topology_change:
            cb()
        return tr

    def stop(self, name: str) -> bool:
        with self._lock:
            tr = self.traces.get(name)
            if tr is None:
                return False
            tr.status = "stopped"
        for cb in self.on_topology_change:
            cb()
        return True

    def running(self) -> list:
        """Snapshot of running traces — safe to iterate off-thread
        (the permit-grant path reads this from the broker poll loop
        while REST threads mutate the table)."""
        with self._lock:
            return [t for t in self.traces.values()
                    if t.status == "running"]

    def delete(self, name: str) -> bool:
        with self._lock:
            hit = self.traces.pop(name, None) is not None
        if hit:
            for cb in self.on_topology_change:
                cb()
        return hit

    def list(self) -> list[dict]:
        with self._lock:
            return [{
                "name": t.name, "type": t.filter_type,
                "value": t.filter_value, "status": t.status,
                "mode": t.mode, "lines": len(t.lines),
            } for t in self.traces.values()]

    def log_lines(self, name: str) -> list[str]:
        with self._lock:
            tr = self.traces.get(name)
            return list(tr.lines) if tr else []

    def tick(self, now: Optional[float] = None) -> None:
        """Expire scheduled traces (the reference's trace scheduler)."""
        now = time.time() if now is None else now
        expired = 0
        with self._lock:
            for tr in self.traces.values():
                if (tr.status == "running" and tr.end_at is not None
                        and now >= tr.end_at):
                    tr.status = "stopped"
                    expired += 1
        if expired:
            # same eager flush as an explicit stop(): the slow-path
            # penalty must not outlive the trace by a permit TTL
            for cb in self.on_topology_change:
                cb()

    # -- event feed (hook callbacks) -----------------------------------------

    def log_for_client(self, clientid: str, event: str,
                       detail: str) -> None:
        """Append one line to every running clientid trace matching
        ``clientid`` — the native plane's entry point for attaching a
        connection's flight-recorder tail (broker/native_server.py
        _on_telemetry) to the trace the operator is watching."""
        for tr in self.running():
            if (tr.filter_type == "clientid"
                    and tr.filter_value == clientid):
                tr.log(event, detail)

    def _active(self):
        return self.running()

    def trace(self, event: str, clientid: str, topic: Optional[str],
              peername: str, detail: str) -> None:
        for tr in self._active():
            if tr.matches(clientid, topic, peername):
                tr.log(event, detail)

    def attach(self, hooks) -> None:
        """Wire onto the standard hookpoints (?TRACE call sites:
        emqx_broker.erl:224 publish, channel connect/subscribe)."""
        hooks.add("message.publish", self._on_publish, priority=-900)
        hooks.add("client.connected", self._on_connected, priority=-900)
        hooks.add("client.disconnected", self._on_disconnected,
                  priority=-900)
        hooks.add("session.subscribed", self._on_subscribed, priority=-900)
        hooks.add("session.unsubscribed", self._on_unsubscribed,
                  priority=-900)

    def _on_publish(self, msg):
        if not msg.sys:
            self.trace("PUBLISH", msg.from_, msg.topic,
                       str(msg.headers.get("peername", "")),
                       f"{msg.topic} qos{msg.qos} {len(msg.payload)}B")
        return None

    def _on_connected(self, ci) -> None:
        cid = getattr(ci, "clientid", None) or (
            ci.get("clientid", "") if isinstance(ci, dict) else "")
        peer = getattr(ci, "peername", None) or (
            ci.get("peername", "") if isinstance(ci, dict) else "")
        self.trace("CONNECT", cid, None, str(peer), f"client {cid} up")

    def _on_disconnected(self, ci, reason) -> None:
        cid = getattr(ci, "clientid", None) or (
            ci.get("clientid", "") if isinstance(ci, dict) else "")
        peer = getattr(ci, "peername", None) or (
            ci.get("peername", "") if isinstance(ci, dict) else "")
        self.trace("DISCONNECT", cid, None, str(peer),
                   f"client {cid} down: {reason}")

    def _on_subscribed(self, sid, topic, opts, is_new=True) -> None:
        self.trace("SUBSCRIBE", sid, topic, "", f"{sid} subscribed {topic}")

    def _on_unsubscribed(self, sid, topic) -> None:
        self.trace("UNSUBSCRIBE", sid, topic, "",
                   f"{sid} unsubscribed {topic}")


# ---------------------------------------------------------------------------
# distributed-tracing span collector (round 13)


class SpanCollector:
    """Stitches kind-12 span events (and Python-emitted replay spans)
    into per-message timelines.

    A sampled publish's 64-bit trace id propagates through every native
    seam — cross-shard ring entries, trunk BATCH records, durable
    MSG-BATCH records — and each plane emits compact span points
    (stage, t_ns, shard, aux). This class assembles them, bounded to
    the last ``max_traces`` distinct ids (the queryable span ring the
    mgmt API serves). Thread-safe: N poll threads feed it when sharded.

    Span tuples are ``(t_ns, stage, shard, node, aux)``; t_ns is
    CLOCK_MONOTONIC, so ordering is meaningful per machine (and across
    the in-process multi-node tests)."""

    def __init__(self, max_traces: int = 512,
                 max_spans_per_trace: int = 64) -> None:
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._traces: "dict[int, list]" = {}
        self._order: deque = deque()
        self._lock = threading.Lock()

    def record(self, trace_id: int, stage: str, t_ns: int,
               shard: int = 0, aux: int = 0, node: str = "") -> None:
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                spans = self._traces[trace_id] = []
                self._order.append(trace_id)
                while len(self._order) > self.max_traces:
                    old = self._order.popleft()
                    self._traces.pop(old, None)
            elif len(spans) >= self.max_spans_per_trace:
                return      # a megafan-out must not grow one timeline
            spans.append((int(t_ns), stage, int(shard), node, int(aux)))

    def trace(self, trace_id: int) -> list:
        """One assembled timeline, sorted by t_ns ([] = unknown id)."""
        with self._lock:
            return sorted(self._traces.get(trace_id, ()))

    def stages(self, trace_id: int) -> list:
        """The stage names of one timeline in t_ns order."""
        return [s for _t, s, _sh, _n, _a in self.trace(trace_id)]

    def recent(self, limit: int = 32) -> list:
        """Newest-first ``(trace_id, sorted spans)`` pairs."""
        limit = max(1, int(limit))   # a negative slice would invert
        with self._lock:
            ids = list(self._order)[-limit:][::-1]
            return [(tid, sorted(self._traces.get(tid, ())))
                    for tid in ids]

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)
