"""Statsd push — ``apps/emqx_statsd/`` analogue.

Flattens the same metric surface Prometheus exports into statsd gauge
lines (``emqx.<name>:<value>|g``) and pushes them over UDP on a flush
interval. The socket is injectable so tests capture lines without a
collector.
"""

from __future__ import annotations

import socket
import time
from typing import Callable, Optional


def render_lines(metrics, stats, prefix: str = "emqx") -> list[str]:
    lines = []
    for name, val in metrics.all().items():
        lines.append(f"{prefix}.{name}:{val}|g")
    for name, val in stats.all().items():
        lines.append(f"{prefix}.{name}:{val}|g")
    return lines


class StatsdPusher:
    def __init__(self, app, host: str = "127.0.0.1", port: int = 8125,
                 flush_interval_s: float = 30.0, prefix: str = "emqx",
                 enable: bool = False,
                 send_fn: Optional[Callable[[bytes], None]] = None) -> None:
        self.app = app
        self.addr = (host, port)
        self.flush_interval_s = flush_interval_s
        self.prefix = prefix
        self.enable = enable
        self._send_fn = send_fn
        self._sock: Optional[socket.socket] = None
        self._last_flush = 0.0
        self.pushes = 0

    def _send(self, payload: bytes) -> None:
        if self._send_fn is not None:
            self._send_fn(payload)
            return
        if self._sock is None:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            self._sock.sendto(payload, self.addr)
        except OSError:
            pass                          # fire-and-forget, like statsd

    def flush(self) -> int:
        """Push one datagram batch; returns number of lines."""
        self.app.stats.tick()
        lines = render_lines(self.app.metrics, self.app.stats, self.prefix)
        # statsd datagrams should stay under the MTU: chunk by ~1400B
        chunk: list[str] = []
        size = 0
        for line in lines:
            if size + len(line) + 1 > 1400 and chunk:
                self._send("\n".join(chunk).encode())
                chunk, size = [], 0
            chunk.append(line)
            size += len(line) + 1
        if chunk:
            self._send("\n".join(chunk).encode())
        self.pushes += 1
        return len(lines)

    def tick(self, now: Optional[float] = None) -> bool:
        if not self.enable:
            return False
        now = time.monotonic() if now is None else now
        if now - self._last_flush < self.flush_interval_s:
            return False
        self._last_flush = now
        self.flush()
        return True

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
