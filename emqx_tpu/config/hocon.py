"""HOCON-subset parser — the config file format
(reference dep ``hocon`` 0.34; files like ``etc/emqx.conf``).

Supported surface (what EMQX configs actually use):

- ``key = value`` / ``key: value``; dotted path keys ``a.b.c = 1``
- nested objects ``a { b = 1 }``; objects merge (later wins per leaf)
- arrays ``[1, 2, 3]`` incl. arrays of objects
- strings bare or quoted (single/double), triple-quoted blocks
- numbers, booleans, null; durations ``10s/5m/1h/100ms`` → seconds;
  byte sizes ``100MB/16KB/1GB`` → bytes; percentages ``80%`` → 0.8
- comments ``#`` and ``//``; trailing commas; ``include`` is NOT
  supported (single-file loads; the layering lives in ConfigStore)
- ``${path}`` substitutions resolved against the same document
"""

from __future__ import annotations

import re
from typing import Any, Optional

_DUR = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)$")
_SIZE = re.compile(r"^(\d+(?:\.\d+)?)(kb|mb|gb|b)$", re.IGNORECASE)
_PCT = re.compile(r"^(\d+(?:\.\d+)?)%$")
_NUM = re.compile(r"^-?\d+(\.\d+)?([eE][+-]?\d+)?$")

_DUR_MULT = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
_SIZE_MULT = {"b": 1, "kb": 1024, "mb": 1024**2, "gb": 1024**3}


class HoconError(ValueError):
    pass


class Duration(float):
    """Seconds, parsed from '10s'/'100ms' — distinct type so schema
    fields can require it."""


class ByteSize(int):
    """Bytes, parsed from '16KB'/'1GB'."""


def _convert_scalar(tok: str) -> Any:
    if tok == "true":
        return True
    if tok == "false":
        return False
    if tok in ("null", "undefined"):
        return None
    if _NUM.match(tok):
        return float(tok) if ("." in tok or "e" in tok or "E" in tok) \
            else int(tok)
    m = _DUR.match(tok)
    if m:
        return Duration(float(m.group(1)) * _DUR_MULT[m.group(2)])
    m = _SIZE.match(tok)
    if m:
        return ByteSize(int(float(m.group(1))
                        * _SIZE_MULT[m.group(2).lower()]))
    m = _PCT.match(tok)
    if m:
        return float(m.group(1)) / 100.0
    return tok                               # bare string


class _Parser:
    def __init__(self, text: str) -> None:
        self.s = text
        self.i = 0
        self.n = len(text)

    # -- low-level ----------------------------------------------------------

    def _ws(self, newlines: bool = True) -> None:
        while self.i < self.n:
            c = self.s[self.i]
            if c == "#" or self.s.startswith("//", self.i):
                while self.i < self.n and self.s[self.i] != "\n":
                    self.i += 1
            elif c in " \t\r" or (newlines and c == "\n"):
                self.i += 1
            else:
                return

    def _peek(self) -> str:
        return self.s[self.i] if self.i < self.n else ""

    def _err(self, msg: str) -> HoconError:
        line = self.s.count("\n", 0, self.i) + 1
        return HoconError(f"line {line}: {msg}")

    # -- values -------------------------------------------------------------

    def parse_document(self) -> dict:
        self._ws()
        if self._peek() == "{":
            obj = self.parse_object()
        else:
            obj = self.parse_object_body(top=True)
        self._ws()
        if self.i < self.n:
            raise self._err(f"trailing content {self.s[self.i:self.i+10]!r}")
        return obj

    def parse_object(self) -> dict:
        assert self._peek() == "{"
        self.i += 1
        obj = self.parse_object_body(top=False)
        if self._peek() != "}":
            raise self._err("expected '}'")
        self.i += 1
        return obj

    def parse_object_body(self, top: bool) -> dict:
        obj: dict = {}
        while True:
            self._ws()
            if self.i >= self.n:
                if top:
                    return obj
                raise self._err("unexpected EOF in object")
            if self._peek() == "}":
                if top:
                    raise self._err("unexpected '}'")
                return obj
            if self._peek() == ",":
                self.i += 1
                continue
            key = self._parse_key()
            self._ws(newlines=False)
            c = self._peek()
            if c == "{":                      # 'a { ... }' implicit assign
                val = self.parse_object()
            elif c in "=:":
                self.i += 1
                self._ws(newlines=False)
                val = self.parse_value()
            else:
                raise self._err(f"expected '=' after key {key!r}")
            self._merge_path(obj, key.split("."), val)

    def _parse_key(self) -> str:
        if self._peek() in "\"'":
            return self._parse_quoted()
        j = self.i
        while self.i < self.n and (self.s[self.i].isalnum()
                                   or self.s[self.i] in "_.-$"):
            self.i += 1
        if j == self.i:
            raise self._err(f"bad key at {self.s[self.i:self.i+10]!r}")
        return self.s[j:self.i]

    def _parse_quoted(self) -> str:
        q = self.s[self.i]
        if self.s.startswith(q * 3, self.i):   # triple-quoted block
            end = self.s.find(q * 3, self.i + 3)
            if end < 0:
                raise self._err("unterminated triple-quoted string")
            out = self.s[self.i + 3:end]
            self.i = end + 3
            return out
        self.i += 1
        out = []
        while self.i < self.n:
            c = self.s[self.i]
            if c == "\\" and self.i + 1 < self.n:
                nxt = self.s[self.i + 1]
                out.append({"n": "\n", "t": "\t", "r": "\r"}.get(nxt, nxt))
                self.i += 2
                continue
            if c == q:
                self.i += 1
                return "".join(out)
            if c == "\n":
                raise self._err("newline in string")
            out.append(c)
            self.i += 1
        raise self._err("unterminated string")

    def parse_value(self) -> Any:
        c = self._peek()
        if not c:
            raise self._err("expected value, got EOF")
        if c == "{":
            return self.parse_object()
        if c == "[":
            return self._parse_array()
        if c in "\"'":
            return self._parse_quoted()
        if self.s.startswith("${", self.i):
            end = self.s.find("}", self.i)
            if end < 0:
                raise self._err("unterminated substitution")
            ref = self.s[self.i + 2:end]
            self.i = end + 1
            return _Subst(ref)
        # bare scalar: up to newline/comma/}/]/comment
        j = self.i
        while self.i < self.n and self.s[self.i] not in "\n,}]#":
            if self.s.startswith("//", self.i):
                break
            self.i += 1
        tok = self.s[j:self.i].strip()
        if not tok:
            raise self._err("empty value")
        return _convert_scalar(tok)

    def _parse_array(self) -> list:
        assert self._peek() == "["
        self.i += 1
        out = []
        while True:
            self._ws()
            if self._peek() == "]":
                self.i += 1
                return out
            if self._peek() == ",":
                self.i += 1
                continue
            out.append(self.parse_value())

    @staticmethod
    def _merge_path(obj: dict, path: list[str], val: Any) -> None:
        for k in path[:-1]:
            nxt = obj.get(k)
            if not isinstance(nxt, dict):
                nxt = obj[k] = {}
            obj = nxt
        k = path[-1]
        if isinstance(val, dict) and isinstance(obj.get(k), dict):
            deep_merge(obj[k], val)
        else:
            obj[k] = val


class _Subst:
    def __init__(self, ref: str) -> None:
        self.ref = ref


def deep_merge(base: dict, over: dict) -> dict:
    """Merge ``over`` into ``base`` in place; objects merge per-leaf,
    everything else (incl. arrays) replaces — HOCON semantics."""
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            deep_merge(base[k], v)
        else:
            base[k] = v
    return base


def _resolve(node: Any, root: dict) -> Any:
    if isinstance(node, _Subst):
        cur: Any = root
        for part in node.ref.split("."):
            if not isinstance(cur, dict) or part not in cur:
                raise HoconError(f"unresolved substitution ${{{node.ref}}}")
            cur = cur[part]
        return _resolve(cur, root)
    if isinstance(node, dict):
        return {k: _resolve(v, root) for k, v in node.items()}
    if isinstance(node, list):
        return [_resolve(v, root) for v in node]
    return node


def loads(text: str) -> dict:
    doc = _Parser(text).parse_document()
    return _resolve(doc, doc)


def load(path: str) -> dict:
    with open(path) as f:
        return loads(f.read())
