"""Layered config store — parity with
``apps/emqx/src/emqx_config.erl`` + ``emqx_config_handler.erl``.

Layers merge in the reference's order (emqx_config.erl:309-337):

    base file → cluster override → local override

then the merged raw conf is schema-checked and the *checked* tree is
held for lock-free reads (the reference parks it in ``persistent_term``;
here a plain dict reference swap — readers see either the old or the
new complete tree, never a partial write).

Runtime updates (``put``) go through per-path handlers
(emqx_config_handler): the deepest registered handler for the path may
validate/transform, the raw overlay is recorded in the chosen override
layer, the full tree re-checks, and only then does the swap happen —
a failing update leaves config untouched.

Zones (emqx_schema zones): named overlay dicts over the root ``mqtt``
section; ``get_zone_conf(zone, path)`` falls back to the global value.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Optional

from emqx_tpu.config import hocon
from emqx_tpu.config.hocon import deep_merge
from emqx_tpu.config.schema import Struct, root_schema

Path = tuple[str, ...]


def _path(p: "str | Path") -> Path:
    if isinstance(p, str):
        return tuple(k for k in p.split(".") if k)
    return tuple(p)


class ConfigError(ValueError):
    pass


class Config:
    def __init__(self, schema: Optional[Struct] = None) -> None:
        self.schema = schema or root_schema()
        self._base: dict = {}
        self._cluster_override: dict = {}
        self._local_override: dict = {}
        self._checked: dict = self.schema.check({})
        self._handlers: dict[Path, Callable] = {}
        self._listeners: list[Callable[[Path, Any], None]] = []
        # cluster seam: when a ClusterNode binds this config, cluster-layer
        # writes route through the replicated txn log (emqx_cluster_rpc);
        # signature: cluster_fn(kind, path_tuple, value) -> applied value
        self.cluster_fn: Optional[Callable] = None

    # -- load (emqx_config:init_load) ---------------------------------------

    def init_load(self, text: str = "",
                  cluster_override: Optional[dict] = None,
                  local_override: Optional[dict] = None) -> None:
        self._base = hocon.loads(text) if text else {}
        self._cluster_override = copy.deepcopy(cluster_override or {})
        self._local_override = copy.deepcopy(local_override or {})
        self._recheck()

    def load_file(self, path: str) -> None:
        with open(path) as f:
            self.init_load(f.read())

    def _merged_raw(self) -> dict:
        raw = copy.deepcopy(self._base)
        deep_merge(raw, copy.deepcopy(self._cluster_override))
        deep_merge(raw, copy.deepcopy(self._local_override))
        return raw

    def _recheck(self) -> None:
        self._checked = self.schema.check(self._merged_raw())

    # -- reads (emqx:get_config) --------------------------------------------

    def get(self, path: "str | Path" = (), default: Any = None) -> Any:
        cur: Any = self._checked
        for k in _path(path):
            if not isinstance(cur, dict) or k not in cur:
                return default
            cur = cur[k]
        return cur

    def get_raw(self, path: "str | Path" = (), default: Any = None) -> Any:
        cur: Any = self._merged_raw()
        for k in _path(path):
            if not isinstance(cur, dict) or k not in cur:
                return default
            cur = cur[k]
        return cur

    def get_zone_conf(self, zone: str, path: "str | Path",
                      default: Any = None) -> Any:
        """Zone override falling back to global (emqx_config:get_zone_conf).
        ``path`` is relative to the ``mqtt`` section."""
        p = _path(path)
        zones = self.get(("zones",), {}) or {}
        cur: Any = zones.get(zone)
        for k in p:
            if not isinstance(cur, dict) or k not in cur:
                cur = None
                break
            cur = cur[k]
        if cur is not None:
            return cur
        return self.get(("mqtt",) + p, default)

    # -- update handlers (emqx_config_handler) ------------------------------

    def add_handler(self, path: "str | Path",
                    handler: Callable[[Path, Any, dict], Any]) -> None:
        """handler(path, new_raw_value, old_checked_root) → value to
        store (may transform) or raise to reject."""
        self._handlers[_path(path)] = handler

    def add_listener(self, fn: Callable[[Path, Any], None]) -> None:
        """Post-commit notification (config change broadcast seam)."""
        self._listeners.append(fn)

    def _handler_for(self, path: Path) -> Optional[tuple[Path, Callable]]:
        # deepest matching prefix wins (emqx_config_handler walks up)
        for ln in range(len(path), -1, -1):
            h = self._handlers.get(path[:ln])
            if h is not None:
                return path[:ln], h
        return None

    # -- writes (emqx_config:update / emqx_conf:update) ---------------------

    def put(self, path: "str | Path", value: Any,
            layer: str = "cluster", local: bool = False) -> Any:
        """Runtime update: handler → overlay → recheck → swap → notify.
        Returns the new checked value at ``path``.

        With a cluster seam bound, cluster-layer writes become
        cluster-wide transactions (the reference's ``emqx_conf:update``
        → ``emqx_cluster_rpc:multicall``); ``local=True`` is the
        txn-apply path itself (and node-local maintenance)."""
        p = _path(path)
        if not p:
            raise ConfigError("empty update path")
        if self.cluster_fn is not None and layer == "cluster" and not local:
            return self.cluster_fn("put", p, value)
        found = self._handler_for(p)
        if found is not None:
            _hpath, handler = found
            value = handler(p, value, self._checked)
        over = (self._cluster_override if layer == "cluster"
                else self._local_override)
        node = over
        for k in p[:-1]:
            nxt = node.get(k)
            if not isinstance(nxt, dict):
                nxt = node[k] = {}
            node = nxt
        old = node.get(p[-1], "__missing__")
        node[p[-1]] = copy.deepcopy(value)
        try:
            self._recheck()
        except Exception:
            # roll the overlay back; config stays consistent
            if old == "__missing__":
                del node[p[-1]]
            else:
                node[p[-1]] = old
            raise
        new_val = self.get(p)
        for fn in self._listeners:
            fn(p, new_val)
        return new_val

    def remove(self, path: "str | Path", layer: str = "cluster",
               local: bool = False) -> None:
        p = _path(path)
        if self.cluster_fn is not None and layer == "cluster" and not local:
            self.cluster_fn("remove", p, None)
            return
        over = (self._cluster_override if layer == "cluster"
                else self._local_override)
        node: Any = over
        for k in p[:-1]:
            node = node.get(k)
            if not isinstance(node, dict):
                return
        node.pop(p[-1], None)
        self._recheck()
        for fn in self._listeners:
            fn(p, self.get(p))

    def adopt_cluster_override(self, raw: dict) -> None:
        """Replace the cluster override wholesale (split-brain re-merge:
        the autoheal loser adopts the winner's replicated layer)."""
        old = self._cluster_override
        self._cluster_override = copy.deepcopy(raw)
        try:
            self._recheck()
        except Exception:
            self._cluster_override = old
            self._recheck()
            raise
        # notify per affected top-level section: listeners dispatch on
        # path prefixes (e.g. BrokerApp._on_config_change), which an
        # empty path would never match
        for key in sorted(set(old) | set(raw)):
            for fn in self._listeners:
                fn((key,), self.get((key,)))

    # -- persistence of the override layers ---------------------------------

    def overrides(self) -> tuple[dict, dict]:
        """(cluster, local) — what the reference persists to
        ``cluster-override.conf`` / ``local-override.conf``."""
        return (copy.deepcopy(self._cluster_override),
                copy.deepcopy(self._local_override))
