"""Config & control plane (SURVEY.md §1 L11): HOCON-subset parser,
typed schema, layered config store with per-path update handlers."""
