"""Typed config schema — parity with ``emqx_schema.erl`` + typerefl.

A schema is a tree of ``Field``s (leaf types with defaults/validators)
and ``Struct``s (nested maps). ``check`` validates + fills defaults and
returns the *checked* config; unknown keys error (the reference's
strict HOCON check). The same schema objects drive doc/swagger
generation in the management API (emqx_dashboard_swagger analogue:
``to_doc``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from emqx_tpu.config.hocon import ByteSize, Duration


class SchemaError(ValueError):
    def __init__(self, path: str, msg: str) -> None:
        super().__init__(f"{path or '<root>'}: {msg}")
        self.path = path


class Field:
    """Leaf field: type ∈ bool/int/float/string/duration/bytesize/
    enum/array/map (map = free-form dict)."""

    def __init__(self, type_: str = "string", default: Any = None,
                 required: bool = False, enum: Optional[list] = None,
                 validator: Optional[Callable[[Any], bool]] = None,
                 item: Optional["Field | Struct"] = None,
                 desc: str = "") -> None:
        self.type = type_
        self.default = default
        self.required = required
        self.enum = enum
        self.validator = validator
        self.item = item               # element schema for arrays
        self.desc = desc

    def check(self, val: Any, path: str) -> Any:
        if val is None:
            if self.required:
                raise SchemaError(path, "required field missing")
            return self.default
        t = self.type
        if t == "bool":
            if not isinstance(val, bool):
                raise SchemaError(path, f"expected bool, got {val!r}")
        elif t == "int":
            if isinstance(val, bool) or not isinstance(val, int):
                # durations/bytesizes coerce onto int fields
                if isinstance(val, (Duration, ByteSize)):
                    val = int(val)
                elif isinstance(val, float) and val.is_integer():
                    val = int(val)
                else:
                    raise SchemaError(path, f"expected int, got {val!r}")
        elif t == "float":
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise SchemaError(path, f"expected number, got {val!r}")
            val = float(val)
        elif t == "string":
            if not isinstance(val, str):
                raise SchemaError(path, f"expected string, got {val!r}")
        elif t == "duration":
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                val = Duration(val)
            else:
                raise SchemaError(path, f"expected duration, got {val!r}")
        elif t == "bytesize":
            if isinstance(val, int) and not isinstance(val, bool):
                val = ByteSize(val)
            else:
                raise SchemaError(path, f"expected bytesize, got {val!r}")
        elif t == "enum":
            if val not in (self.enum or []):
                raise SchemaError(path,
                                  f"expected one of {self.enum}, got {val!r}")
        elif t == "array":
            if not isinstance(val, list):
                raise SchemaError(path, f"expected array, got {val!r}")
            if self.item is not None:
                val = [self.item.check(v, f"{path}[{i}]")
                       for i, v in enumerate(val)]
        elif t == "map":
            if not isinstance(val, dict):
                raise SchemaError(path, f"expected object, got {val!r}")
            if self.item is not None:      # value schema (e.g. listeners)
                val = {k: self.item.check(v, f"{path}.{k}")
                       for k, v in val.items()}
        else:
            raise SchemaError(path, f"unknown field type {t!r}")
        if self.validator is not None and not self.validator(val):
            raise SchemaError(path, f"validation failed for {val!r}")
        return val

    def to_doc(self) -> dict:
        d: dict[str, Any] = {"type": self.type}
        if self.default is not None:
            d["default"] = self.default
        if self.enum:
            d["enum"] = self.enum
        if self.required:
            d["required"] = True
        if self.desc:
            d["desc"] = self.desc
        return d


class Struct:
    """Nested object of named fields/structs. ``open=True`` tolerates
    unknown keys (for extension points like zones/listeners)."""

    def __init__(self, fields: dict[str, "Field | Struct"],
                 open: bool = False, desc: str = "") -> None:
        self.fields = fields
        self.open = open
        self.desc = desc

    def check(self, val: Any, path: str = "") -> dict:
        if val is None:
            val = {}
        if not isinstance(val, dict):
            raise SchemaError(path, f"expected object, got {val!r}")
        out: dict[str, Any] = {}
        for k, v in val.items():
            sub = self.fields.get(k)
            kp = f"{path}.{k}" if path else k
            if sub is None:
                if self.open:
                    out[k] = v
                    continue
                raise SchemaError(kp, "unknown config key")
            out[k] = sub.check(v, kp)
        for k, sub in self.fields.items():
            if k not in out:
                kp = f"{path}.{k}" if path else k
                out[k] = sub.check(None, kp)
        return out

    def to_doc(self) -> dict:
        return {"type": "object",
                "fields": {k: f.to_doc() for k, f in self.fields.items()},
                **({"desc": self.desc} if self.desc else {})}


# -- the broker's root schema (emqx_schema.erl, trimmed to what the
#    runtime consumes today; widened as features land) --------------------

def mqtt_schema() -> Struct:
    """Zone-overridable MQTT caps (emqx_schema 'mqtt' section)."""
    return Struct({
        "max_packet_size": Field("bytesize", default=1 << 20),
        "max_clientid_len": Field("int", default=65535),
        "max_topic_levels": Field("int", default=128),
        "max_qos_allowed": Field("int", default=2,
                                 validator=lambda v: 0 <= v <= 2),
        "max_topic_alias": Field("int", default=65535),
        "retain_available": Field("bool", default=True),
        "wildcard_subscription": Field("bool", default=True),
        "shared_subscription": Field("bool", default=True),
        "exclusive_subscription": Field("bool", default=False),
        "ignore_loop_deliver": Field("bool", default=False),
        "session_expiry_interval": Field("duration", default=7200.0),
        "max_awaiting_rel": Field("int", default=100),
        "await_rel_timeout": Field("duration", default=300.0),
        "max_subscriptions": Field("int", default=0),   # 0 = infinity
        "upgrade_qos": Field("bool", default=False),
        "keepalive_backoff": Field("float", default=0.75),
        "max_inflight": Field("int", default=32),
        "retry_interval": Field("duration", default=30.0),
        "max_mqueue_len": Field("int", default=1000),
        "mqueue_store_qos0": Field("bool", default=True),
    })


def ssl_options_schema() -> Struct:
    """esockd ssl_options surface (emqx_listeners.erl:196-238,
    emqx_schema.erl ssl defaults)."""
    return Struct({
        "certfile": Field("string", default=""),
        "keyfile": Field("string", default=""),
        "password": Field("string", default=""),
        "cacertfile": Field("string", default=""),
        "verify": Field("enum", enum=["verify_none", "verify_peer"],
                        default="verify_none"),
        "fail_if_no_peer_cert": Field("bool", default=False),
        "versions": Field("array", default=["tlsv1.2", "tlsv1.3"],
                          item=Field("enum", enum=[
                              "tlsv1", "tlsv1.1", "tlsv1.2", "tlsv1.3"])),
        "ciphers": Field("array", default=[], item=Field("string")),
        "handshake_timeout": Field("duration", default=15.0),
        "enable_psk": Field("bool", default=False),
    }, open=True)


def listener_schema() -> Struct:
    return Struct({
        # "native" = the C++ epoll host with the QoS0/1 publish data
        # plane (broker/native_server.py); fast_path turns the data
        # plane off while keeping C++ socket IO
        "type": Field("enum",
                      enum=["tcp", "ssl", "ws", "wss", "quic", "native"],
                      default="tcp"),
        "fast_path": Field("bool", default=True),
        "bind": Field("string", default="0.0.0.0:1883"),
        "enabled": Field("bool", default=True),
        "max_connections": Field("int", default=1_000_000),
        "mountpoint": Field("string", default=""),
        "zone": Field("string", default="default"),
        "proxy_protocol": Field("bool", default=False),
        "websocket_path": Field("string", default="/mqtt"),
        "peer_cert_as_username": Field(
            "enum", enum=["disabled", "cn", "dn"], default="disabled"),
        "peer_cert_as_clientid": Field(
            "enum", enum=["disabled", "cn", "dn"], default="disabled"),
        "ssl_options": ssl_options_schema(),
    }, open=True)


def root_schema() -> Struct:
    return Struct({
        "node": Struct({
            "name": Field("string", default="emqx_tpu@127.0.0.1"),
            "cookie": Field("string", default="emqxsecretcookie"),
            "data_dir": Field("string", default="data"),
        }),
        "cluster": Struct({
            "name": Field("string", default="emqxcl"),
            "discovery_strategy": Field(
                "enum", enum=["manual", "static", "dns"], default="manual"),
            "static": Struct({
                "seeds": Field("array", default=[], item=Field("string")),
            }),
        }, open=True),
        "mqtt": mqtt_schema(),
        "zones": Field("map", default={}),       # name → mqtt overrides
        # name → listener conf, each checked against listener_schema
        "listeners": Field("map", default={}, item=listener_schema()),
        "authentication": Field("array", default=[], item=Field("map")),
        "authorization": Struct({
            "no_match": Field("enum", enum=["allow", "deny"],
                              default="allow"),
            "deny_action": Field("enum", enum=["ignore", "disconnect"],
                                 default="ignore"),
            "cache": Struct({
                "enable": Field("bool", default=True),
                "max_size": Field("int", default=32),
                "ttl": Field("duration", default=60.0),
            }),
            "sources": Field("array", default=[], item=Field("map")),
        }),
        "retainer": Struct({
            "enable": Field("bool", default=True),
            "max_retained_messages": Field("int", default=0),
            "msg_expiry_interval": Field("duration", default=0.0),
        }, open=True),
        "delayed": Struct({
            "enable": Field("bool", default=True),
            "max_delayed_messages": Field("int", default=0),
        }),
        # durable-session plane (round 10): the host-side message store
        # the C++ data plane appends to below the GIL (store.h) plus the
        # PersistentSessions service backing resume. enable=false keeps
        # CONFIG-BUILT apps persistence-less (persistent sessions punt,
        # the pre-round-10 shape); an app constructed with an explicit
        # persistent_store gets the native plane by default regardless —
        # EMQX_DURABLE_STORE=0 is the runtime escape hatch for both.
        "durable": Struct({
            "enable": Field("bool", default=False),
            # "" → <node.data_dir>/durable/store for the native message
            # log (+ /durable/sessions for the Python session store)
            "store_dir": Field("string", default=""),
            "segment_bytes": Field("bytesize", default=4 * 1024 * 1024),
            # never = page cache only; batch = msync per flushed batch
            # (PUBACK-after-store gives real qos1 durability);
            # interval = ~100ms cadence
            "fsync": Field("enum", enum=["never", "batch", "interval"],
                           default="batch"),
            # global cap on stored-session retention; 0 = each
            # session's own Session-Expiry-Interval governs
            "session_expiry": Field("duration", default=0.0),
        }),
        "router": Struct({
            # the TPU device router on the serving path: subscriptions
            # compile into the HBM trie + subscriber bitmaps; publishes
            # coalesce into batched match kernel launches
            "device": Struct({
                "enable": Field("bool", default=False),
                "n_sub_slots": Field("int", default=1024),
                "batch_max": Field("int", default=512),
                # publish batches smaller than this answer from the
                # host oracle instead of paying a device round trip
                # (SURVEY §7 hard part (b): the latency knee).
                # -1 = adaptive: the pipeline estimates the knee from
                # measured device RTT and host-oracle cost EMAs
                "min_batch": Field("int", default=-1),
                # in-flight kernel launches the pipeline keeps (service
                # rate ≈ depth × batch_max / device RTT)
                "pipeline_depth": Field("int", default=4),
                # queue-sojourn bound (ms) before a batch spills to the
                # host oracle; -1 = adaptive (3 × measured RTT)
                "spill_ms": Field("int", default=-1),
                "max_levels": Field("int", default=16),
                "frontier_k": Field("int", default=32),
                "match_cap": Field("int", default=128),
                # device→host columns returned per topic; topics
                # matching more fall back to the host oracle
                "return_cap": Field("int", default=16),
            }),
        }),
        "shared_subscription_strategy": Field(
            "enum", enum=["random", "round_robin", "round_robin_per_group",
                          "sticky", "local", "hash_clientid", "hash_topic"],
            default="round_robin"),
        "flapping_detect": Struct({
            "enable": Field("bool", default=False),
            "max_count": Field("int", default=15),
            "window_time": Field("duration", default=60.0),
            "ban_time": Field("duration", default=300.0),
        }),
        "force_gc": Struct({
            "enable": Field("bool", default=True),
            "count": Field("int", default=16000),
            "bytes": Field("bytesize", default=16 * 1024 * 1024),
        }),
        "sysmon": Struct({
            "os": Struct({
                "cpu_high_watermark": Field("float", default=0.80),
                "cpu_low_watermark": Field("float", default=0.60),
                "mem_high_watermark": Field("float", default=0.70),
            }),
        }, open=True),
        "sys_topics": Struct({
            "sys_msg_interval": Field("duration", default=60.0),
            "sys_heartbeat_interval": Field("duration", default=30.0),
        }),
        "log": Struct({
            "level": Field("enum",
                           enum=["debug", "info", "warning", "error"],
                           default="warning"),
            "to": Field("enum", enum=["console", "file", "both"],
                        default="console"),
            "file": Field("string", default="log/emqx.log"),
            # emqx_logger_jsonfmt vs textfmt (emqx_conf_schema
            # log.console.formatter)
            "formatter": Field("enum", enum=["text", "json"],
                               default="text"),
        }),
        "prometheus": Struct({
            "enable": Field("bool", default=False),
            "port": Field("int", default=18083),
        }, open=True),
        "rule_engine": Field("map", default={}),
        "bridges": Field("map", default={}),
        "gateway": Field("map", default={}),
        "rewrite": Field("array", default=[], item=Field("map")),
        "auto_subscribe": Struct({
            "topics": Field("array", default=[], item=Field("map")),
        }),
        "telemetry": Struct({
            "enable": Field("bool", default=False),
        }),
        # emqx_exhook_schema: out-of-process hook providers; url scheme
        # grpc:// = real HookProvider service, framed:// = the
        # documented JSON framing (exhook/proto.py)
        "exhook": Struct({
            "servers": Field("array", default=[], item=Field("map")),
        }),
        "statsd": Struct({
            "enable": Field("bool", default=False),
            "server": Field("string", default="127.0.0.1:8125"),
            "flush_time_interval": Field("duration", default=30.0),
        }),
        "psk_authentication": Struct({
            "enable": Field("bool", default=False),
            "init_file": Field("string", default=""),
            "separator": Field("string", default=":"),
        }),
        "slow_subs": Struct({
            "enable": Field("bool", default=True),
            "threshold": Field("duration", default=0.5),
            "top_k_num": Field("int", default=10),
            "expire_interval": Field("duration", default=300.0),
        }),
        "api": Struct({
            "enable": Field("bool", default=False),
            "bind": Field("string", default="127.0.0.1:18083"),
        }, open=True),
        "limiter": Field("map", default={}),
    })
