from emqx_tpu.models.router_model import RouterModel

__all__ = ["RouterModel"]
