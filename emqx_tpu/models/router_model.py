"""RouterModel — the flagship device program: match → compact → fan-out.

One jittable step replaces the reference's entire per-message read path
``emqx_router:match_routes/1`` → ``emqx_trie:match/1`` → subscriber-table
lookups → pid fan-out loop (emqx_router.erl:141-157,
emqx_broker.erl:546-579) with a single batched XLA program over HBM-
resident tables:

    tokens [B, L] ──trie match──► cand [B, S] ──compact──► fids [B, M]
                                                  │
               subscriber bitmaps [F, W] ──OR────►└─► fanout [B, W], counts

Sharding (see emqx_tpu.parallel.mesh): match runs with B over the full
dp×tp mesh; fids then reshard to dp-only (XLA inserts an all-gather of the
small [B, M] tensor along tp) so fan-out can keep W sharded over tp.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from emqx_tpu.ops import fanout as fo
from emqx_tpu.ops import trie_match as tm
from emqx_tpu.parallel import mesh as pmesh
from emqx_tpu.router.index import TrieIndex


def router_step(
    trie: tm.DeviceTrie,
    bitmaps: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    sys_flags: jax.Array,
    *,
    K: int = 32,
    M: int = 128,
    max_probes: int = 8,
    shardings: Optional[dict[str, NamedSharding]] = None,
):
    """The full publish-batch routing step (pure, jittable).

    Returns (fids [B, M], fanout [B, W], counts [B], overflow [B]).
    """
    cand, overflow = tm.match_batch(
        trie, tokens, lengths, sys_flags, K=K, max_probes=max_probes
    )
    fids, truncated = tm.compact_fids(cand, M=M)
    if shardings is not None:
        # reshard the compacted fids to dp-only before the tp-sharded OR
        fids = jax.lax.with_sharding_constraint(fids, shardings["batch_dp"])
    out = fo.fanout_bitmaps(bitmaps, fids)
    if shardings is not None:
        out = jax.lax.with_sharding_constraint(out, shardings["fanout_out"])
    counts = fo.bitmap_to_counts(out)
    return fids, out, counts, overflow | truncated


class RouterModel:
    """Host wrapper: TrieIndex + subscriber bitmaps + the jitted step.

    The broker layer registers subscribers into per-filter bitmap rows
    (slot = subscriber id from the connection manager); ``publish_batch``
    tokenizes topics, runs the device step, and reports matches.
    """

    def __init__(
        self,
        index: Optional[TrieIndex] = None,
        *,
        n_sub_slots: int = 1024,
        K: int = 32,
        M: int = 128,
        mesh: Optional[Mesh] = None,
    ) -> None:
        self.index = index or TrieIndex()
        self.n_sub_slots = n_sub_slots
        self.K, self.M = K, M
        self.mesh = mesh
        self.shardings = pmesh.router_shardings(mesh) if mesh else None
        self._subs: dict[int, set[int]] = {}      # fid -> subscriber slots
        self._trie_dev: Optional[tm.DeviceTrie] = None
        self._bitmaps_dev: Optional[jax.Array] = None
        self._dirty = True
        self._step = jax.jit(
            functools.partial(
                router_step,
                K=K,
                M=M,
                max_probes=self.index.max_probes,
                shardings=self.shardings,
            )
        )

    # -- subscription surface (driven by the broker layer) -----------------

    def subscribe(self, filt: str, slot: int) -> int:
        if not 0 <= slot < self.n_sub_slots:
            raise ValueError(
                f"subscriber slot {slot} out of range [0, {self.n_sub_slots})"
            )
        fid = self.index.insert(filt)
        slots = self._subs.setdefault(fid, set())
        if slot not in slots:
            slots.add(slot)
            self._dirty = True
        return fid

    def unsubscribe(self, filt: str, slot: int) -> None:
        fid = self.index.fid_of(filt)
        if fid is None:
            return
        slots = self._subs.get(fid)
        if slots and slot in slots:
            slots.discard(slot)
            if not slots:
                self._subs.pop(fid, None)
                self.index.delete(filt)
            self._dirty = True

    # -- device refresh (double-buffered full rebuild, round-1 policy) -----

    @property
    def bitmap_words(self) -> int:
        return max(1, (self.n_sub_slots + 31) // 32)

    def build_bitmaps(self) -> np.ndarray:
        W = self.bitmap_words
        F = max(1, len(self.index.filters))   # fid slots incl. freelist holes
        bm = np.zeros((F, W), np.uint32)
        if self._subs:
            fids = np.fromiter(
                (f for f, ss in self._subs.items() for _ in ss), np.int64
            )
            slots = np.fromiter(
                (s for ss in self._subs.values() for s in ss), np.int64
            )
            np.bitwise_or.at(
                bm, (fids, slots // 32),
                (np.uint32(1) << (slots % 32).astype(np.uint32)),
            )
        return bm

    def refresh(self) -> None:
        arrays = self.index.ensure()
        trie_dev = tm.device_trie(arrays)
        bitmaps = self.build_bitmaps()
        if self.shardings is not None:
            trie_dev = jax.device_put(trie_dev, self.shardings["replicated"])
            bitmaps = jax.device_put(bitmaps, self.shardings["bitmaps"])
        else:
            bitmaps = jnp.asarray(bitmaps)
        self._trie_dev, self._bitmaps_dev = trie_dev, bitmaps
        self._dirty = False

    # -- the hot path ------------------------------------------------------

    def publish_batch(self, topics: Sequence[str]):
        """Route a batch of publish topics.

        Returns (matched_filters: list[list[str]], sub_slots: list[list[int]]).
        Topics flagged overflow/too-long fall back to the host oracle path
        upstream (router.match_filters) — reported via the third element.
        """
        if self._dirty or self._trie_dev is None:
            self.refresh()
        n = len(topics)
        # pad the batch to a pow2 bucket (≥64) — keeps the set of compiled
        # program shapes small, the {active,N}-style batching discipline
        B = 64
        while B < n:
            B *= 2
        padded = list(topics) + [""] * (B - n)
        tokens, lengths, sys_flags, too_long = self.index.tokenize(padded)
        too_long = [b for b in too_long if b < n]
        # padding rows: length 0 + sys flag so even the root '#'/'+' filters
        # (which match an empty prefix) cannot emit for them
        lengths[n:] = 0
        sys_flags[n:] = True
        args = (tokens, lengths, sys_flags)
        if self.shardings is not None:
            args = jax.device_put(args, self.shardings["batch_full"])
        fids, fanout, counts, overflow = self._step(
            self._trie_dev, self._bitmaps_dev, *args
        )
        fids = np.asarray(fids)
        fan = np.asarray(fanout)
        overflow = np.asarray(overflow)
        matched: list[list[str]] = []
        slots: list[list[int]] = []
        for b in range(len(topics)):
            row = fids[b][fids[b] >= 0]
            matched.append([self.index.filters[f] for f in row])
            bits = fan[b]
            (word_idx,) = np.nonzero(bits)
            out = []
            for w in word_idx:
                v = int(bits[w])
                while v:
                    low = v & -v
                    out.append(int(w) * 32 + low.bit_length() - 1)
                    v ^= low
            slots.append(out)
        fallback = sorted(set(too_long) | set(np.nonzero(overflow)[0].tolist()))
        return matched, slots, fallback
