"""RouterModel — the flagship device program: match → compact → fan-out.

One jittable step replaces the reference's entire per-message read path
``emqx_router:match_routes/1`` → ``emqx_trie:match/1`` → subscriber-table
lookups → pid fan-out loop (emqx_router.erl:141-157,
emqx_broker.erl:546-579) with a single batched XLA program over HBM-
resident tables:

    tokens [B, L] ──trie match──► cand [B, S] ──compact──► fids [B, M]
                                                  │
               subscriber bitmaps [F, W] ──OR────►└─► fanout [B, W], counts

Sharding (see emqx_tpu.parallel.mesh): match runs with B over the full
dp×tp mesh; fids then reshard to dp-only (XLA inserts an all-gather of the
small [B, M] tensor along tp) so fan-out can keep W sharded over tp.
"""

from __future__ import annotations

import functools
import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from emqx_tpu.ops import fanout as fo
from emqx_tpu.ops import trie_match as tm
from emqx_tpu.parallel import mesh as pmesh
from emqx_tpu.router.index import TrieIndex


def router_step(
    trie: tm.DeviceTrie,
    bitmaps: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    sys_flags: jax.Array,
    *,
    K: int = 32,
    M: int = 128,
    max_probes: int = 8,
    shardings: Optional[dict[str, NamedSharding]] = None,
):
    """The full publish-batch routing step (pure, jittable).

    Returns (fids [B, M], fanout [B, W], counts [B], overflow [B]).
    """
    cand, overflow = tm.match_batch(
        trie, tokens, lengths, sys_flags, K=K, max_probes=max_probes
    )
    fids, truncated = tm.compact_fids(cand, M=M)
    if shardings is not None:
        # reshard the compacted fids to dp-only before the tp-sharded OR
        fids = jax.lax.with_sharding_constraint(fids, shardings["batch_dp"])
    out = fo.fanout_bitmaps(bitmaps, fids)
    if shardings is not None:
        out = jax.lax.with_sharding_constraint(out, shardings["fanout_out"])
    counts = fo.bitmap_to_counts(out)
    return fids, out, counts, overflow | truncated


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _apply_patches(trie: tm.DeviceTrie, bm: jax.Array,
                   tupd: dict, bm_upd: tuple) -> tuple:
    """ONE dispatch applying every pending element update to the donated
    HBM buffers (XLA reuses the donated allocations, so the work is
    O(#updates), not O(table); one launch keeps the subscribe→routable
    path at a single host→device round trip)."""
    new = {}
    for name in tm.DeviceTrie._fields:
        arr = getattr(trie, name)
        idx, vals = tupd[name]
        new[name] = arr.at[idx].set(vals)
    rows, cols, vals = bm_upd
    return tm.DeviceTrie(**new), bm.at[rows, cols].set(vals)


def _patch_bucket(n: int) -> int:
    """Shared pad size for ALL update vectors of one _apply_patches call:
    a 4×-stepped ladder so the jit compiles a handful of variants total
    (per-array pow2 pads would make the cross product of shapes explode
    into a fresh ~100ms compile almost every refresh — measured)."""
    cap = 64
    while cap < n:
        cap *= 4
    return cap


def _pad_to(cap: int, idx: np.ndarray, vals: np.ndarray):
    """Pad update vectors to cap by repeating the first element —
    a duplicate scatter of an identical value is a no-op."""
    pad = cap - len(idx)
    return (np.concatenate([idx, np.repeat(idx[:1], pad)]),
            np.concatenate([vals, np.repeat(vals[:1], pad)]))


class RouterModel:
    """Host wrapper: TrieIndex + subscriber bitmaps + the jitted step.

    The broker layer registers subscribers into per-filter bitmap rows
    (slot = subscriber id from the connection manager); ``publish_batch``
    tokenizes topics, runs the device step, and reports matches.

    Mutations are applied to the device arrays *incrementally*: the
    TrieIndex patches its host arrays in place and records dirty indices;
    ``refresh`` scatters just those elements into HBM with donated jits
    (subscribe→routable is O(topic-depth)).  A full re-upload happens
    only when the index signals structural growth (``needs_rebuild``) or
    the bitmap capacity changes — the emqx_trie.erl:113-144 incremental
    insert/delete semantics, device-resident.
    """

    def __init__(
        self,
        index: Optional[TrieIndex] = None,
        *,
        n_sub_slots: int = 1024,
        K: int = 32,
        M: int = 128,
        mesh: Optional[Mesh] = None,
    ) -> None:
        self.index = index or TrieIndex()
        self.n_sub_slots = n_sub_slots
        self.K, self.M = K, M
        self.mesh = mesh
        self.shardings = pmesh.router_shardings(mesh) if mesh else None
        self._subs: dict[int, set[int]] = {}      # fid -> subscriber slots
        # One lock over index mutation, pending-update drain, device
        # refresh AND the step launch: subscribes arrive on the server's
        # event-loop thread while the pipeline flushes on a worker
        # thread — an unsynchronized drain could scatter a half-applied
        # insert (torn trie) into HBM, and a refresh mid-launch would
        # donate away buffers the step still reads.  The serialization
        # mirrors the reference's per-topic router_pool discipline
        # (emqx_router.erl:200-204) at model granularity.
        self._mlock = threading.RLock()
        self._trie_dev: Optional[tm.DeviceTrie] = None
        self._bitmaps_dev: Optional[jax.Array] = None
        self._bm_host: Optional[np.ndarray] = None   # [F_cap, W] uint32
        self._bm_dirty: set[tuple[int, int]] = set() # dirty (fid, word)
        self._dirty = True
        self.upload_count = 0      # full device uploads (test/obs hook)
        self.patch_count = 0       # incremental scatter flushes
        self.launch_count = 0      # publish_batch kernel launches
        self._step = jax.jit(
            functools.partial(
                router_step,
                K=K,
                M=M,
                max_probes=self.index.max_probes,
                shardings=self.shardings,
            )
        )

    # -- subscription surface (driven by the broker layer) -----------------

    def subscribe(self, filt: str, slot: int) -> int:
        if not 0 <= slot < self.n_sub_slots:
            raise ValueError(
                f"subscriber slot {slot} out of range [0, {self.n_sub_slots})"
            )
        with self._mlock:
            fid = self.index.insert(filt)
            slots = self._subs.setdefault(fid, set())
            if slot not in slots:
                slots.add(slot)
                self._set_bit(fid, slot, on=True)
                self._dirty = True
            return fid

    def unsubscribe(self, filt: str, slot: int) -> None:
        with self._mlock:
            fid = self.index.fid_of(filt)
            if fid is None:
                return
            slots = self._subs.get(fid)
            if slots and slot in slots:
                slots.discard(slot)
                self._set_bit(fid, slot, on=False)
                if not slots:
                    self._subs.pop(fid, None)
                    self.index.delete(filt)
                self._dirty = True

    def _set_bit(self, fid: int, slot: int, *, on: bool) -> None:
        bm = self._bm_host
        if bm is None or fid >= bm.shape[0] or slot // 32 >= bm.shape[1]:
            self._bm_host = None          # capacity growth → full rebuild
            return
        if on:
            bm[fid, slot // 32] |= np.uint32(1) << np.uint32(slot % 32)
        else:
            bm[fid, slot // 32] &= ~(np.uint32(1) << np.uint32(slot % 32))
        self._bm_dirty.add((fid, slot // 32))

    # -- device refresh ----------------------------------------------------

    @property
    def bitmap_words(self) -> int:
        return max(1, (self.n_sub_slots + 31) // 32)

    def build_bitmaps(self) -> np.ndarray:
        W = self.bitmap_words
        # capacity rows beyond the live fid range so freshly-inserted
        # filters land inside the allocated bitmap
        live = max(1, len(self.index.filters))
        F = 64
        while F < live + live // 2:
            F *= 2
        bm = np.zeros((F, W), np.uint32)
        if self._subs:
            fids = np.fromiter(
                (f for f, ss in self._subs.items() for _ in ss), np.int64
            )
            slots = np.fromiter(
                (s for ss in self._subs.values() for s in ss), np.int64
            )
            np.bitwise_or.at(
                bm, (fids, slots // 32),
                (np.uint32(1) << (slots % 32).astype(np.uint32)),
            )
        return bm

    def refresh(self) -> None:
        """Bring the device arrays up to date: one fused scatter dispatch
        when possible, full upload on structural growth."""
        with self._mlock:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        full_trie = (self.index.needs_rebuild or self.index.arrays is None
                     or self._trie_dev is None)
        if full_trie:
            arrays = self.index.ensure()
            trie_dev = tm.device_trie(arrays)
            if self.shardings is not None:
                trie_dev = jax.device_put(
                    trie_dev, self.shardings["replicated"])
            self._trie_dev = trie_dev
            self.index.drain_updates()    # superseded by the upload
            self.upload_count += 1

        full_bm = (self._bm_host is None
                   or self._bitmaps_dev is None
                   or self._bm_host.shape[1] != self.bitmap_words)
        if full_bm:
            self._bm_host = self.build_bitmaps()
            bitmaps = self._bm_host
            if self.shardings is not None:
                bitmaps = jax.device_put(bitmaps, self.shardings["bitmaps"])
            else:
                bitmaps = jnp.asarray(bitmaps)
            self._bitmaps_dev = bitmaps
            self._bm_dirty.clear()

        updates = {} if full_trie else self.index.drain_updates()
        bm_dirty = [] if full_bm else sorted(self._bm_dirty)
        if updates or bm_dirty:
            cap = _patch_bucket(max(
                max((len(v) for v in updates.values()), default=0),
                len(bm_dirty)))
            arrays = self.index.arrays
            tupd = {}
            for name in tm.DeviceTrie._fields:
                idxs = updates.get(name)
                host = getattr(arrays, name)
                if idxs:
                    idx = np.asarray(idxs, np.int32)
                else:
                    idx = np.zeros(1, np.int32)    # no-op self-write
                vals = host[idx]
                idx, vals = _pad_to(cap, idx, vals)
                tupd[name] = (jnp.asarray(idx), jnp.asarray(vals))
            if bm_dirty:
                rows = np.asarray([r for r, _ in bm_dirty], np.int32)
                cols = np.asarray([c for _, c in bm_dirty], np.int32)
            else:
                rows = np.zeros(1, np.int32)
                cols = np.zeros(1, np.int32)
            vals = self._bm_host[rows, cols]
            # pad rows/cols/vals with the SAME (row0, col0, val0) triple:
            # a duplicate write of the identical value is a no-op
            rows, vals = _pad_to(cap, rows, vals)
            cols, _ = _pad_to(cap, cols, cols)
            self._trie_dev, self._bitmaps_dev = _apply_patches(
                self._trie_dev, self._bitmaps_dev, tupd,
                (jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals)))
            self._bm_dirty.clear()
            self.patch_count += 1
        self._dirty = False

    # -- the hot path ------------------------------------------------------

    def publish_batch(self, topics: Sequence[str]):
        """Route a batch of publish topics.

        Returns (matched_filters: list[list[str]], sub_slots: list[list[int]]).
        Topics flagged overflow/too-long fall back to the host oracle path
        upstream (router.match_filters) — reported via the third element.
        """
        with self._mlock:
            return self._publish_batch_locked(topics)

    def _publish_batch_locked(self, topics: Sequence[str]):
        if self._dirty or self._trie_dev is None:
            self._refresh_locked()
        self.launch_count += 1
        n = len(topics)
        # pad the batch to a pow2 bucket (≥64) — keeps the set of compiled
        # program shapes small, the {active,N}-style batching discipline
        B = 64
        while B < n:
            B *= 2
        padded = list(topics) + [""] * (B - n)
        tokens, lengths, sys_flags, too_long = self.index.tokenize(padded)
        too_long = [b for b in too_long if b < n]
        # padding rows: length 0 + sys flag so even the root '#'/'+' filters
        # (which match an empty prefix) cannot emit for them
        lengths[n:] = 0
        sys_flags[n:] = True
        args = (tokens, lengths, sys_flags)
        if self.shardings is not None:
            args = jax.device_put(args, self.shardings["batch_full"])
        fids, fanout, counts, overflow = self._step(
            self._trie_dev, self._bitmaps_dev, *args
        )
        fids = np.asarray(fids)
        fan = np.asarray(fanout)
        overflow = np.asarray(overflow)
        matched: list[list[str]] = []
        slots: list[list[int]] = []
        for b in range(len(topics)):
            row = fids[b][fids[b] >= 0]
            matched.append([self.index.filters[f] for f in row])
            bits = fan[b]
            (word_idx,) = np.nonzero(bits)
            out = []
            for w in word_idx:
                v = int(bits[w])
                while v:
                    low = v & -v
                    out.append(int(w) * 32 + low.bit_length() - 1)
                    v ^= low
            slots.append(out)
        fallback = sorted(set(too_long) | set(np.nonzero(overflow)[0].tolist()))
        return matched, slots, fallback
