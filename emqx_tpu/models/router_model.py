"""RouterModel — the flagship device program: match → compact → fan-out.

One jittable step replaces the reference's entire per-message read path
``emqx_router:match_routes/1`` → ``emqx_trie:match/1`` → subscriber-table
lookups → pid fan-out loop (emqx_router.erl:141-157,
emqx_broker.erl:546-579) with a single batched XLA program over HBM-
resident tables:

    tokens [B, L] ──trie match──► cand [B, S] ──compact──► fids [B, M]
                                                  │
          dense pool [P, W] + rowmap [F] ──OR────►└─► fanout [B, W], counts

Fan-out is HYBRID (the emqx_broker_helper.erl:55,82-92 sharding
discipline, TPU-shaped): subscriber slots are a FIXED shard space
(SlotRegistry hashes past capacity), per-filter slot sets live host-side
in a refcounted dict, and only HIGH-degree filters (broadcast topics,
degree > dense_threshold) get a row in the device dense pool — the OR
aggregation is exactly the regime where it pays.  A dense [F, W] bitmap
would cost 16 GB at 10M filters (round-1 weak #2, BASELINE config 3);
the pool costs P·W for the few filters that need it, and the structures
never grow with subscriber count.

Sharding (see emqx_tpu.parallel.mesh): match runs with B over the full
dp×tp mesh; fids then reshard to dp-only (XLA inserts an all-gather of the
small [B, M] tensor along tp) so fan-out can keep W sharded over tp.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from emqx_tpu.ops import fanout as fo
from emqx_tpu.ops import trie_match as tm
from emqx_tpu.parallel import mesh as pmesh
from emqx_tpu.router.index import ShardedTrieIndex, TrieIndex


def router_step(
    trie: tm.DeviceTrie,
    rowmap: jax.Array,
    pool: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    sys_flags: jax.Array,
    *,
    K: int = 32,
    M: int = 128,
    max_probes: int = 8,
    ret_cap: Optional[int] = None,
    shardings: Optional[dict[str, NamedSharding]] = None,
    with_counters: bool = False,
):
    """The full publish-batch routing step (pure, jittable).

    Returns (fids [B, ret_cap or M], fanout [B, W], overflow [B],
    fan_any [], counters); fanout covers the dense-pool (high-degree)
    filters, low-degree slots decode host-side from the subscription
    dict.

    ``ret_cap`` trims the RETURNED fid columns: device→host transfer is
    the serving path's dominant cost (a tunneled TPU pays ~90 ms/RTT and
    bandwidth per flush), and mean matches/topic is ~1.7 against M=128
    buffered columns. Topics matching more than ret_cap filters are
    flagged overflow and take the host-oracle fallback upstream —
    correctness never depends on the trim. ``fan_any`` (scalar) lets the
    host skip fetching the [B, W] fanout block entirely when no
    dense-pool row matched (the common case below the dense threshold).

    ``with_counters`` adds the kernel-plane counters vector (ISSUE 18):
    a [C] int32 pack in tm.KERNEL_COUNTER_FIELDS order, computed by the
    same program with elementwise reductions and fetched in the SAME
    publish_batch_collect device_get — no extra sync. ``counters`` is
    None when disabled (a dropped pytree leaf, so callers unpack a
    5-tuple either way). The ret_cap trim's spill is NOT a counter — it
    rides ``overflow`` into the broker's fallback/ledger seam.
    """
    cand, overflow, mstats = tm.match_batch(
        trie, tokens, lengths, sys_flags, K=K, max_probes=max_probes
    )
    fids, truncated = tm.compact_fids(cand, M=M)
    counters = None
    if with_counters:
        occ = jnp.sum((fids >= 0).astype(jnp.int32), axis=1)   # [B]
        counters = tm.pack_counters(
            frontier_peak=mstats["frontier_peak"],
            probe_iters=mstats["probe_iters"],
            cand_pre=mstats["cand_pre"],
            cand_post=jnp.sum(occ),
            compact_peak=jnp.max(occ),
            overflow_rows=mstats["overflow_rows"],
            trunc_rows=jnp.sum(truncated.astype(jnp.int32)),
        )
    if shardings is not None:
        # reshard the compacted fids to dp-only before the tp-sharded OR
        fids = jax.lax.with_sharding_constraint(fids, shardings["batch_dp"])
    out = fo.fanout_pool(rowmap, pool, fids)
    if shardings is not None:
        out = jax.lax.with_sharding_constraint(out, shardings["fanout_out"])
    fan_any = jnp.any(out != 0)
    overflow = overflow | truncated
    if ret_cap is not None and ret_cap < M:
        overflow = overflow | (jnp.sum(fids >= 0, axis=1) > ret_cap)
        fids = fids[:, :ret_cap]
    return fids, out, overflow, fan_any, counters


def router_step_sharded(
    trie: tm.DeviceTrie,   # fields [S, H] / [S, N] — shard axis over tp
    rowmap: jax.Array,
    pool: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    sys_flags: jax.Array,
    *,
    n_shards: int,
    K: int = 32,
    M: int = 128,
    max_probes: int = 8,
    ret_cap: Optional[int] = None,
    shardings: Optional[dict[str, NamedSharding]] = None,
    with_counters: bool = False,
):
    """The routing step over a subscription-sharded trie.

    Layout: the trie's shard axis is partitioned over ``tp`` (each
    device holds its fid-range slice), the topic batch over ``dp`` only
    (tp-replicated — every shard must see every topic).  Each shard
    matches and compacts its own slice to M shard-local fids, local
    fids translate to the interleaved global namespace, and the [B,
    S·M] shard-major merge is the ONLY tensor the tp collective moves —
    compacted ids, never the [S, B, (L+1)·2K] candidate block and never
    the bitmaps.  After the merge the step is exactly ``router_step``:
    one more compact, then the tp-sharded dense-pool OR over GLOBAL
    fids.

    n_shards=1 degenerates bit-identically to ``router_step`` on the
    flat trie (identity fid translation, no-op second compact).

    ``with_counters`` packs a PER-SHARD [S, C] counters block (tm.
    KERNEL_COUNTER_FIELDS order): match-side fields come per shard from
    the vmapped walk, compact-side fields from each shard's own M
    compact (pre-merge — the shard-skew signal).  The merged second
    compact's spill rides ``overflow`` to the broker fallback seam, not
    the counters.
    """
    cand, overflow, mstats = tm.match_batch_sharded(
        trie, tokens, lengths, sys_flags, K=K, max_probes=max_probes
    )
    S, B, _ = cand.shape
    per, trunc = jax.vmap(lambda c: tm.compact_fids(c, M=M))(cand)
    counters = None
    if with_counters:
        occ = jnp.sum((per >= 0).astype(jnp.int32), axis=2)    # [S, B]
        counters = tm.pack_counters(
            frontier_peak=mstats["frontier_peak"],
            probe_iters=mstats["probe_iters"],
            cand_pre=mstats["cand_pre"],
            cand_post=jnp.sum(occ, axis=1),
            compact_peak=jnp.max(occ, axis=1),
            overflow_rows=mstats["overflow_rows"],
            trunc_rows=jnp.sum(trunc.astype(jnp.int32), axis=1),
        )
    shard_ids = jnp.arange(S, dtype=per.dtype)[:, None, None]
    per = jnp.where(per >= 0, per * n_shards + shard_ids, -1)
    merged = jnp.moveaxis(per, 0, 1).reshape(B, S * M)
    if shardings is not None:
        # the tp all-gather: [B, S*M] compacted global fids to dp-only
        merged = jax.lax.with_sharding_constraint(
            merged, shardings["batch_dp"])
    fids, trunc2 = tm.compact_fids(merged, M=M)
    truncated = jnp.any(trunc, axis=0) | trunc2
    out = fo.fanout_pool(rowmap, pool, fids)
    if shardings is not None:
        out = jax.lax.with_sharding_constraint(out, shardings["fanout_out"])
    fan_any = jnp.any(out != 0)
    overflow = overflow | truncated
    if ret_cap is not None and ret_cap < M:
        overflow = overflow | (jnp.sum(fids >= 0, axis=1) > ret_cap)
        fids = fids[:, :ret_cap]
    return fids, out, overflow, fan_any, counters


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _apply_patches(trie: tm.DeviceTrie, rowmap: jax.Array, pool: jax.Array,
                   tupd: dict, rowmap_upd: tuple, pool_upd: tuple) -> tuple:
    """ONE dispatch applying every pending element update to the donated
    HBM buffers (XLA reuses the donated allocations, so the work is
    O(#updates), not O(table); one launch keeps the subscribe→routable
    path at a single host→device round trip)."""
    new = {}
    for name in tm.DeviceTrie._fields:
        arr = getattr(trie, name)
        # idx is a 1-D index array (flat trie) or a (shard_idx, elem_idx)
        # pair (sharded [S, ...] trie) — .at[] takes both
        idx, vals = tupd[name]
        new[name] = arr.at[idx].set(vals)
    ridx, rvals = rowmap_upd
    rows, cols, vals = pool_upd
    return (tm.DeviceTrie(**new), rowmap.at[ridx].set(rvals),
            pool.at[rows, cols].set(vals))


def _patch_bucket(n: int) -> int:
    """Shared pad size for ALL update vectors of one _apply_patches call:
    a 4×-stepped ladder so the jit compiles a handful of variants total
    (per-array pow2 pads would make the cross product of shapes explode
    into a fresh ~100ms compile almost every refresh — measured)."""
    cap = 64
    while cap < n:
        cap *= 4
    return cap


def _pad_to(cap: int, idx: np.ndarray, vals: np.ndarray):
    """Pad update vectors to cap by repeating the first element —
    a duplicate scatter of an identical value is a no-op."""
    pad = cap - len(idx)
    return (np.concatenate([idx, np.repeat(idx[:1], pad)]),
            np.concatenate([vals, np.repeat(vals[:1], pad)]))


class _HostMatcher:
    """CPU-platform serving path: an exact host matcher keyed by fid.

    BENCH_r05 measured the XLA kernel at 11.9k topics/s on CPU against
    2.07M/s for the C++ SubTable on the same box — a 0.1x
    ``vs_host_oracle`` regression the model used to serve whenever the
    resolved platform was cpu.  When active (see
    ``RouterModel._resolve_host_dispatch``) ``publish_batch`` routes
    through this mirror instead of dispatching the XLA program.

    Backend: the C++ ``NativeSubTable`` (owner = fid) when the native
    plane built, else the pure-python host-oracle ``Trie``.  Entries are
    guarded by a fid→filter dict so refcount drift in either backend is
    impossible (adds/removes are idempotent per fid).
    """

    def __init__(self) -> None:
        self._fids: dict[int, str] = {}
        self._native = None
        self._trie = None
        self._by_filt: dict[str, int] = {}
        from emqx_tpu import native
        if native.available():
            self._native = native.NativeSubTable()
        else:
            from emqx_tpu.router.trie import Trie
            self._trie = Trie()
        self.backend = "native" if self._native is not None else "oracle"

    def add(self, fid: int, filt: str) -> None:
        if fid in self._fids:
            return
        self._fids[fid] = filt
        if self._native is not None:
            self._native.add(fid, filt)
        else:
            self._trie.insert(filt)
            self._by_filt[filt] = fid

    def remove(self, fid: int) -> None:
        filt = self._fids.pop(fid, None)
        if filt is None:
            return
        if self._native is not None:
            self._native.remove(fid, filt)
        else:
            self._trie.delete(filt)
            self._by_filt.pop(filt, None)

    def match(self, topic: str) -> list[int]:
        if self._native is not None:
            fids = list(self._native.match(topic))
        else:
            fids = [self._by_filt[f] for f in self._trie.match(topic)
                    if f in self._by_filt]
        if topic.startswith("$"):
            # MQTT-3.7.2-1: a root-level wildcard must not match a
            # $-topic.  The oracle Trie enforces this itself; the C++
            # SubTable does not, so filter uniformly here (matches the
            # device kernel's sys_block lane kill at level 0)
            fids = [f for f in fids
                    if self._fids[f].split("/", 1)[0] not in ("+", "#")]
        return fids

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
            self._native = None


class RouterModel:
    """Host wrapper: TrieIndex + subscriber bitmaps + the jitted step.

    The broker layer registers subscribers into per-filter bitmap rows
    (slot = subscriber id from the connection manager); ``publish_batch``
    tokenizes topics, runs the device step, and reports matches.

    Mutations are applied to the device arrays *incrementally*: the
    TrieIndex patches its host arrays in place and records dirty indices;
    ``refresh`` scatters just those elements into HBM with donated jits
    (subscribe→routable is O(topic-depth)).  A full re-upload happens
    only when the index signals structural growth (``needs_rebuild``) or
    the bitmap capacity changes — the emqx_trie.erl:113-144 incremental
    insert/delete semantics, device-resident.
    """

    def __init__(
        self,
        index: Optional[Union[TrieIndex, ShardedTrieIndex]] = None,
        *,
        n_sub_slots: int = 8192,
        K: int = 32,
        M: int = 128,
        ret_cap: int = 16,
        dense_threshold: int = 64,
        mesh: Optional[Mesh] = None,
        trie_shards: Optional[int] = None,
        kernel_telemetry: Optional[bool] = None,
    ) -> None:
        if index is None:
            index = (ShardedTrieIndex(trie_shards) if trie_shards
                     else TrieIndex())
        elif trie_shards is not None and (
                getattr(index, "n_shards", 1) != trie_shards):
            raise ValueError(
                f"trie_shards={trie_shards} conflicts with the supplied "
                f"index ({getattr(index, 'n_shards', 1)} shard(s))")
        self.index = index
        self._sharded = isinstance(index, ShardedTrieIndex)
        self.n_shards = index.n_shards if self._sharded else 1
        self.n_sub_slots = n_sub_slots
        self.K, self.M = K, M
        self.ret_cap = min(ret_cap, M)
        self.dense_threshold = dense_threshold
        self.mesh = mesh
        self.shardings = pmesh.router_shardings(mesh) if mesh else None
        if self._sharded and mesh is not None:
            tp_ext = mesh.shape[pmesh.TP]
            if self.n_shards % tp_ext:
                raise ValueError(
                    f"trie shard count {self.n_shards} must be a multiple "
                    f"of the tp mesh extent {tp_ext} — the stacked [S, ...]"
                    f" buffers partition their shard axis evenly over tp")
        # fid → {slot: refcount} — slots are SHARDS (SlotRegistry may
        # hash many sids into one), so a slot stays set while any local
        # subscriber of the filter lives in it
        self._subs: dict[int, dict[int, int]] = {}
        # fid → refcount for AUXILIARY filters (rule-engine FROM filters
        # co-batched with router match, BASELINE config 5): they live in
        # the same device trie but own no subscriber slots; the batch
        # decode reports them separately so fan-out and rule matching
        # both ride one kernel launch (emqx_rule_engine.erl:198-205)
        self._aux_refs: dict[int, int] = {}
        # fid-indexed bool masks mirroring _subs/_aux_refs membership:
        # the batch decode classifies whole [B, M] fid blocks with two
        # vectorized gathers instead of per-fid dict lookups
        self._sub_mask = np.zeros(64, bool)
        self._aux_mask = np.zeros(64, bool)
        # high-degree filters promoted into the device dense pool
        self._dense_row: dict[int, int] = {}      # fid → pool row
        self._row_free: list[int] = []
        self._next_row = 0
        # One lock over index mutation, pending-update drain, device
        # refresh AND the step launch: subscribes arrive on the server's
        # event-loop thread while the pipeline flushes on a worker
        # thread — an unsynchronized drain could scatter a half-applied
        # insert (torn trie) into HBM, and a refresh mid-launch would
        # donate away buffers the step still reads.  The serialization
        # mirrors the reference's per-topic router_pool discipline
        # (emqx_router.erl:200-204) at model granularity.
        self._mlock = threading.RLock()
        self._trie_dev: Optional[tm.DeviceTrie] = None
        self._rowmap_dev: Optional[jax.Array] = None
        self._pool_dev: Optional[jax.Array] = None
        self._rowmap_host: Optional[np.ndarray] = None  # [F_cap] int32
        self._pool_host: Optional[np.ndarray] = None    # [P_cap, W] uint32
        self._rowmap_dirty: set[int] = set()
        self._pool_dirty: set[tuple[int, int]] = set()  # (row, word)
        self._dirty = True
        self.upload_count = 0      # full device uploads (test/obs hook)
        self.patch_count = 0       # incremental scatter flushes
        self.launch_count = 0      # publish_batch kernel launches
        self.host_match_count = 0  # batches served by the host matcher
        # kernel-plane observability (ISSUE 18): with_counters bakes the
        # [*, C] counters pack into the step so it rides the SAME
        # collect-time device_get; EMQX_TPU_KERNEL_TELEMETRY=0 is the
        # escape hatch (compiles the counters out entirely)
        if kernel_telemetry is None:
            kernel_telemetry = os.environ.get(
                "EMQX_TPU_KERNEL_TELEMETRY", "1"
            ).lower() not in ("0", "off", "false")
        self.kernel_telemetry = bool(kernel_telemetry)
        # DeviceMetricsFold attach point (observe/device_metrics.py);
        # the model never imports the observe plane — the app wires it
        self.telemetry = None
        self.patch_upload_bytes = 0   # unpadded dirty bytes scattered
        if self._sharded:
            step_fn = functools.partial(
                router_step_sharded, n_shards=self.n_shards)
        else:
            step_fn = router_step
        self._step = jax.jit(
            functools.partial(
                step_fn,
                K=K,
                M=M,
                ret_cap=self.ret_cap,
                max_probes=self.index.max_probes,
                shardings=self.shardings,
                with_counters=self.kernel_telemetry,
            )
        )
        # platform-aware dispatch: on a cpu backend the XLA kernel is a
        # ~0.1x regression vs the host matcher (BENCH_r05), so serve
        # from the host mirror unless the escape hatch says otherwise
        self._host_matcher = (_HostMatcher()
                              if self._resolve_host_dispatch() else None)

    def _resolve_host_dispatch(self) -> bool:
        """Should publish_batch serve from the host matcher?

        ``EMQX_TPU_CPU_KERNEL``: ``host`` forces the host matcher,
        ``xla`` forces the device kernel (the bench's validation-mode
        escape hatch — measuring the XLA program ON cpu is the point
        there), anything else is auto: host matcher iff the resolved
        platform is cpu and no mesh was requested.
        """
        mode = os.environ.get("EMQX_TPU_CPU_KERNEL", "auto").lower()
        if mode == "host":
            return True
        if mode == "xla":
            return False
        return self.mesh is None and jax.default_backend() == "cpu"

    # -- subscription surface (driven by the broker layer) -----------------

    def _mask_of(self, name: str, n: int) -> np.ndarray:
        """The named fid mask, grown to cover at least ``n`` fids."""
        mask = getattr(self, name)
        if mask.shape[0] < n:
            mask = np.pad(mask, (0, n - mask.shape[0]))
            setattr(self, name, mask)
        return mask

    def _mark(self, mask_name: str, fid: int, val: bool) -> None:
        mask = getattr(self, mask_name)
        if fid >= mask.shape[0]:
            grown = np.zeros(max(fid + 1, mask.shape[0] * 2), bool)
            grown[: mask.shape[0]] = mask
            mask = grown
            setattr(self, mask_name, mask)
        mask[fid] = val

    def subscribe(self, filt: str, slot: int) -> int:
        if not 0 <= slot < self.n_sub_slots:
            raise ValueError(
                f"subscriber slot {slot} out of range [0, {self.n_sub_slots})"
            )
        with self._mlock:
            fid = self.index.insert(filt)
            if self._host_matcher is not None:
                self._host_matcher.add(fid, filt)
            self._mark("_sub_mask", fid, True)
            slots = self._subs.setdefault(fid, {})
            n = slots.get(slot, 0)
            slots[slot] = n + 1
            if n == 0:                     # first subscriber in the shard
                self._slot_added(fid, slot)
                self._dirty = True
            return fid

    def unsubscribe(self, filt: str, slot: int) -> None:
        with self._mlock:
            fid = self.index.fid_of(filt)
            if fid is None:
                return
            slots = self._subs.get(fid)
            if not slots or slot not in slots:
                return
            slots[slot] -= 1
            if slots[slot] == 0:
                del slots[slot]
                self._slot_removed(fid, slot)
                if not slots:
                    self._subs.pop(fid, None)
                    self._mark("_sub_mask", fid, False)
                    # an aux registration (rule FROM filter) keeps the
                    # trie entry alive past the last subscriber
                    if fid not in self._aux_refs:
                        self.index.delete(filt)
                        if self._host_matcher is not None:
                            self._host_matcher.remove(fid)
                self._dirty = True

    # -- auxiliary (rule-engine) filters ------------------------------------

    def aux_register(self, filt: str) -> int:
        """Co-batch a non-subscriber filter (rule FROM clause) into the
        device trie; refcounted across rules sharing a filter."""
        with self._mlock:
            fid = self.index.insert(filt)
            if self._host_matcher is not None:
                self._host_matcher.add(fid, filt)
            self._aux_refs[fid] = self._aux_refs.get(fid, 0) + 1
            self._mark("_aux_mask", fid, True)
            self._dirty = True
            return fid

    def aux_release(self, filt: str) -> None:
        with self._mlock:
            fid = self.index.fid_of(filt)
            if fid is None or fid not in self._aux_refs:
                return
            self._aux_refs[fid] -= 1
            if self._aux_refs[fid] > 0:
                return
            del self._aux_refs[fid]
            self._mark("_aux_mask", fid, False)
            if fid not in self._subs:      # no subscribers either
                self.index.delete(filt)
                if self._host_matcher is not None:
                    self._host_matcher.remove(fid)
            self._dirty = True

    # -- dense-pool promotion / demotion -----------------------------------

    def _slot_added(self, fid: int, slot: int) -> None:
        row = self._dense_row.get(fid)
        if row is not None:
            self._pool_bit(row, slot, on=True)
        elif len(self._subs[fid]) > self.dense_threshold:
            self._promote(fid)

    def _slot_removed(self, fid: int, slot: int) -> None:
        row = self._dense_row.get(fid)
        if row is not None:
            self._pool_bit(row, slot, on=False)
            # hysteresis: demote well below the promote threshold so a
            # filter oscillating around it doesn't thrash the pool
            if len(self._subs[fid]) < self.dense_threshold // 2:
                self._demote(fid)

    def _promote(self, fid: int) -> None:
        if self._row_free:
            row = self._row_free.pop()
        else:
            row = self._next_row
            self._next_row += 1
        self._dense_row[fid] = row
        if (self._pool_host is None or row >= self._pool_host.shape[0]):
            self._pool_host = None        # pool growth → full rebuild
        else:
            for slot in self._subs[fid]:
                self._pool_bit(row, slot, on=True)
        self._set_rowmap(fid, row)

    def _demote(self, fid: int) -> None:
        row = self._dense_row.pop(fid)
        if self._pool_host is not None and row < self._pool_host.shape[0]:
            for slot in self._subs.get(fid, ()):   # leave the row zeroed
                self._pool_bit(row, slot, on=False)
        self._row_free.append(row)
        self._set_rowmap(fid, -1)

    def _pool_bit(self, row: int, slot: int, *, on: bool) -> None:
        pool = self._pool_host
        if pool is None or row >= pool.shape[0] or slot // 32 >= pool.shape[1]:
            self._pool_host = None
            return
        if on:
            pool[row, slot // 32] |= np.uint32(1) << np.uint32(slot % 32)
        else:
            pool[row, slot // 32] &= ~(np.uint32(1) << np.uint32(slot % 32))
        self._pool_dirty.add((row, slot // 32))

    def _set_rowmap(self, fid: int, row: int) -> None:
        rm = self._rowmap_host
        if rm is None or fid >= rm.shape[0]:
            self._rowmap_host = None      # fid capacity growth → rebuild
            return
        rm[fid] = row
        self._rowmap_dirty.add(fid)

    # -- device refresh ----------------------------------------------------

    @property
    def bitmap_words(self) -> int:
        return max(1, (self.n_sub_slots + 31) // 32)

    def build_pool(self) -> tuple[np.ndarray, np.ndarray]:
        """Full (rowmap, pool) rebuild: compact rows, fresh headroom."""
        W = self.bitmap_words
        live = max(1, len(self.index.filters))
        F = 64
        while F < live + live // 2:
            F *= 2
        rowmap = np.full(F, -1, np.int32)
        # compact row ids (frees fragmentation from demotes)
        self._dense_row = {
            fid: i for i, fid in enumerate(sorted(self._dense_row))
        }
        self._row_free = []
        self._next_row = len(self._dense_row)
        P = 64
        while P < max(1, self._next_row * 2):
            P *= 2
        pool = np.zeros((P, W), np.uint32)
        for fid, row in self._dense_row.items():
            rowmap[fid] = row
            for slot in self._subs.get(fid, ()):
                pool[row, slot // 32] |= np.uint32(1) << np.uint32(slot % 32)
        return rowmap, pool

    def refresh(self) -> None:
        """Bring the device arrays up to date: one fused scatter dispatch
        when possible, full upload on structural growth."""
        with self._mlock:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        full_trie = (self.index.needs_rebuild or self._trie_dev is None
                     or (not self._sharded and self.index.arrays is None))
        if full_trie:
            if self._sharded:
                # ensure() also equalizes the per-shard edge-table sizes
                # so the [S, H] stack shares one probe mask
                shard_arrays = self.index.ensure()
                trie_dev = tm.stacked_device_trie(shard_arrays)
                if self.shardings is not None:
                    trie_dev = jax.device_put(
                        trie_dev, self.shardings["trie_sub"])
                else:
                    trie_dev = tm.DeviceTrie(
                        *(jnp.asarray(x) for x in trie_dev))
            else:
                arrays = self.index.ensure()
                trie_dev = tm.device_trie(arrays)
                if self.shardings is not None:
                    trie_dev = jax.device_put(
                        trie_dev, self.shardings["replicated"])
            self._trie_dev = trie_dev
            self.index.drain_updates()    # superseded by the upload
            self.upload_count += 1

        # fid capacity must cover every live fid (rowmap gathers by fid)
        if (self._rowmap_host is not None
                and len(self.index.filters) > self._rowmap_host.shape[0]):
            self._rowmap_host = None
        full_pool = (self._pool_host is None or self._rowmap_host is None
                     or self._pool_dev is None
                     or self._pool_host.shape[1] != self.bitmap_words)
        if full_pool:
            self._rowmap_host, self._pool_host = self.build_pool()
            rowmap, pool = self._rowmap_host, self._pool_host
            if self.shardings is not None:
                rowmap = jax.device_put(rowmap, self.shardings["replicated"])
                pool = jax.device_put(pool, self.shardings["bitmaps"])
            else:
                rowmap, pool = jnp.asarray(rowmap), jnp.asarray(pool)
            self._rowmap_dev, self._pool_dev = rowmap, pool
            self._rowmap_dirty.clear()
            self._pool_dirty.clear()

        updates = {} if full_trie else self.index.drain_updates()
        rm_dirty = [] if full_pool else sorted(self._rowmap_dirty)
        pool_dirty = [] if full_pool else sorted(self._pool_dirty)
        if updates or rm_dirty or pool_dirty:
            # patch-upload accounting (UNPADDED dirty counts — the pad
            # repeats a no-op write): each trie element scatters an
            # (index, value) int32 pair, +4 B for the shard index on the
            # stacked layout; pool writes carry (row, col, val)
            n_elems = sum(len(v) for v in updates.values())
            self.patch_upload_bytes += (
                n_elems * (12 if self._sharded else 8)
                + len(rm_dirty) * 8 + len(pool_dirty) * 12)
            cap = _patch_bucket(max(
                max((len(v) for v in updates.values()), default=0),
                len(rm_dirty), len(pool_dirty)))
            tupd = {}
            for name in tm.DeviceTrie._fields:
                idxs = updates.get(name)
                if self._sharded:
                    # (shard, idx) pairs → a 2-D scatter into [S, ...]:
                    # a steady-state subscribe patches just the owning
                    # shard's slice, never the whole stack
                    if idxs:
                        sidx = np.asarray([s for s, _ in idxs], np.int32)
                        eidx = np.asarray([i for _, i in idxs], np.int32)
                    else:
                        sidx = np.zeros(1, np.int32)   # no-op self-write
                        eidx = np.zeros(1, np.int32)
                    shards = self.index.shards
                    vals = np.asarray(
                        [getattr(shards[s].arrays, name)[i]
                         for s, i in zip(sidx, eidx)], np.int32)
                    sidx, vals = _pad_to(cap, sidx, vals)
                    eidx, _ = _pad_to(cap, eidx, eidx)
                    tupd[name] = ((jnp.asarray(sidx), jnp.asarray(eidx)),
                                  jnp.asarray(vals))
                    continue
                host = getattr(self.index.arrays, name)
                if idxs:
                    idx = np.asarray(idxs, np.int32)
                else:
                    idx = np.zeros(1, np.int32)    # no-op self-write
                vals = host[idx]
                idx, vals = _pad_to(cap, idx, vals)
                tupd[name] = (jnp.asarray(idx), jnp.asarray(vals))
            ridx = (np.asarray(rm_dirty, np.int32) if rm_dirty
                    else np.zeros(1, np.int32))
            rvals = self._rowmap_host[ridx]
            ridx, rvals = _pad_to(cap, ridx, rvals)
            if pool_dirty:
                rows = np.asarray([r for r, _ in pool_dirty], np.int32)
                cols = np.asarray([c for _, c in pool_dirty], np.int32)
            else:
                rows = np.zeros(1, np.int32)
                cols = np.zeros(1, np.int32)
            vals = self._pool_host[rows, cols]
            # pad rows/cols/vals with the SAME (row0, col0, val0) triple:
            # a duplicate write of the identical value is a no-op
            rows, vals = _pad_to(cap, rows, vals)
            cols, _ = _pad_to(cap, cols, cols)
            self._trie_dev, self._rowmap_dev, self._pool_dev = \
                _apply_patches(
                    self._trie_dev, self._rowmap_dev, self._pool_dev, tupd,
                    (jnp.asarray(ridx), jnp.asarray(rvals)),
                    (jnp.asarray(rows), jnp.asarray(cols),
                     jnp.asarray(vals)))
            self._rowmap_dirty.clear()
            self._pool_dirty.clear()
            self.patch_count += 1
        self._dirty = False

    # -- the hot path ------------------------------------------------------

    def publish_batch(self, topics: Sequence[str]):
        """Route a batch of publish topics.

        Returns ``(matched, aux, slots, fallback)``:
        - matched: per-topic subscriber filter strings
        - aux: per-topic auxiliary (rule FROM) filter strings matched by
          the same kernel launch — config-5 co-batching
        - slots: per-topic subscriber shard slots
        - fallback: batch positions (overflow/too-long) that must take
          the host-oracle path upstream (router.match_filters)
        """
        return self.publish_batch_collect(self.publish_batch_submit(topics))

    def publish_batch_submit(self, topics: Sequence[str]):
        """Stage 1: tokenize + dispatch the kernel; returns an opaque
        pending handle WITHOUT waiting for the device. The serving
        pipeline overlaps this launch's device round trip (~70 ms on a
        tunneled TPU, fixed per synchronous fetch) with the NEXT batch's
        hook fold and tokenization — the SURVEY §2.5-6 double-buffering."""
        if self._host_matcher is not None:
            # cpu platform: serve synchronously from the host matcher —
            # the "pending" handle is the finished result, so the
            # pipeline's submit/collect overlap degenerates harmlessly
            return ("host", self._publish_batch_host(topics))
        t0 = time.monotonic_ns()
        with self._mlock:
            if self._dirty or self._trie_dev is None:
                self._refresh_locked()
            self.launch_count += 1
            n = len(topics)
            # pad the batch to a pow2 bucket (≥64) — keeps the set of
            # compiled program shapes small, the {active,N}-style
            # batching discipline
            B = 64
            while B < n:
                B *= 2
            padded = list(topics) + [""] * (B - n)
            tokens, lengths, sys_flags, too_long = self.index.tokenize(
                padded)
            too_long = [b for b in too_long if b < n]
            # padding rows: length 0 + sys flag so even the root '#'/'+'
            # filters (which match an empty prefix) cannot emit for them
            lengths[n:] = 0
            sys_flags[n:] = True
            args = (tokens, lengths, sys_flags)
            if self.shardings is not None:
                # sharded trie: topics go dp-only (tp-REPLICATED — every
                # trie shard matches every topic); replicated trie keeps
                # the full dp×tp batch split
                key = "batch_dp" if self._sharded else "batch_full"
                args = jax.device_put(args, self.shardings[key])
            fids, fanout, overflow, fan_any, counters = self._step(
                self._trie_dev, self._rowmap_dev, self._pool_dev, *args
            )
            # freed fids stay quarantined until this batch is decoded —
            # a reused fid would decode as the WRONG (new) filter
            self.index.begin_inflight()
            # (t0, t1) stamps the submit stage (tokenize + dispatch) for
            # the telemetry fold; the dispatch is async, so t1 is NOT a
            # device sync point
            return (list(topics), too_long, fids, fanout, overflow,
                    fan_any, counters, (t0, time.monotonic_ns()))

    def publish_batch_collect(self, pending):
        """Stage 2: fetch + decode a submitted batch's results."""
        if isinstance(pending, tuple) and len(pending) == 2 \
                and pending[0] == "host":
            return pending[1]
        (topics, too_long, fids, fanout, overflow, fan_any, counters,
         (t0, t1)) = pending
        try:
            # ONE device_get for all needed outputs: it issues
            # copy_to_host_async for every array before materializing,
            # so the transfers overlap into ~one device round trip.
            # Serial np.asarray calls cost a full round trip EACH —
            # measured 3×89 ms per flush on a tunneled TPU, which
            # dominated the e2e broker latency. The [B, W] fanout block
            # starts its copy speculatively so the fan_any=True case
            # (dense rows matched) costs no SECOND dependent round trip;
            # it only materializes when needed. The kernel counters
            # (when enabled) join the SAME device_get — telemetry costs
            # no extra sync.
            try:
                fanout.copy_to_host_async()
            except AttributeError:     # non-jax array (tests/mocks)
                pass
            t2 = time.monotonic_ns()
            if counters is not None:
                fids, overflow, fan_any, counters = jax.device_get(
                    (fids, overflow, fan_any, counters))
            else:
                fids, overflow, fan_any = jax.device_get(
                    (fids, overflow, fan_any))
            t3 = time.monotonic_ns()
            if fan_any:
                fan = np.asarray(fanout)
            else:
                fan = np.zeros(fanout.shape, np.uint32)
            with self._mlock:
                res = self._decode_locked(topics, too_long, fids, fan,
                                          overflow)
            tel = self.telemetry
            if tel is not None:
                try:   # telemetry must never break the serving path
                    tel.on_batch(
                        counters, n_topics=len(topics),
                        submit_ns=t1 - t0, step_ns=t3 - t2,
                        decode_ns=time.monotonic_ns() - t3,
                        t_submit_ns=t0, t_collect_ns=t3)
                except Exception:  # noqa: BLE001 — observe-plane bug
                    pass
            return res
        finally:
            with self._mlock:
                self.index.end_inflight()

    def _publish_batch_host(self, topics):
        """Serve one batch from the host matcher (cpu-platform path).

        Same ``(matched, aux, slots, fallback)`` contract as the device
        decode.  The host walk is exact and depth-unbounded, so there is
        no overflow/too-long leg: fallback is always empty.  Slots come
        straight from the subscription dict for every matched filter —
        dense-pool promotion is a device-bandwidth optimization with no
        meaning here.
        """
        with self._mlock:
            self.host_match_count += 1
            tel = self.telemetry
            if tel is not None:
                try:
                    tel.on_host_batch(len(topics))
                except Exception:  # noqa: BLE001 — observe-plane bug
                    pass
            filters = self.index.filters
            any_aux = bool(self._aux_refs)
            matched: list[list[str]] = []
            aux: list[list[str]] = []
            slots_out: list[list[int]] = []
            for topic in topics:
                m: list[str] = []
                a: list[str] = []
                sl: set[int] = set()
                for fid in self._host_matcher.match(topic):
                    filt = filters[fid]
                    if filt is None:
                        continue
                    if fid in self._subs:
                        m.append(filt)
                        sl.update(self._subs[fid])
                    if any_aux and fid in self._aux_refs:
                        a.append(filt)
                matched.append(m)
                aux.append(a)
                slots_out.append(sorted(sl))
            return matched, aux, slots_out, []

    def _decode_locked(self, topics, too_long, fids, fan, overflow):
        # -- vectorized batch decode (the r2 host hot-spot): classify the
        # whole [B, M] fid block with two mask gathers, and expand ALL
        # delivering bitmap words with one shift table instead of a
        # per-topic Python popcount loop — decode cost is O(nonzero
        # words + actual matches), not O(B · per-topic python)
        B_out = len(topics)
        F = max(1, len(self.index.filters))
        fb = fids[:B_out]
        valid = fb >= 0
        safe = np.where(valid, fb, 0)
        sub_hit = valid & self._mask_of("_sub_mask", F)[safe]
        any_aux = bool(self._aux_refs)
        if any_aux:
            aux_hit = valid & self._mask_of("_aux_mask", F)[safe]
        filters = self.index.filters
        matched: list[list[str]] = []
        aux: list[list[str]] = []
        slots_out: list[list[int]] = []

        # bitmap words → slot ids, all topics at once
        fan_b = fan[:B_out]
        rb, wb = np.nonzero(fan_b)
        if len(rb):
            vals = fan_b[rb, wb].astype(np.uint32)
            bits = (vals[:, None] >> np.arange(32, dtype=np.uint32)) & 1
            nz_r, nz_bit = np.nonzero(bits)
            rows_flat = rb[nz_r]                      # non-decreasing
            slots_flat = wb[nz_r] * 32 + nz_bit
            bounds = np.searchsorted(rows_flat, np.arange(B_out + 1))
        else:
            slots_flat = np.zeros(0, np.int64)
            bounds = np.zeros(B_out + 1, np.int64)

        for b in range(B_out):
            row = fb[b]
            sub_fids = row[sub_hit[b]]
            # a fid deleted while the batch was in flight decodes to
            # None — that unsubscribe raced the publish; drop the leg
            # (reuse is prevented by the index's in-flight quarantine)
            matched.append([filters[f] for f in sub_fids
                            if filters[f] is not None])
            aux.append([filters[f] for f in row[aux_hit[b]]
                        if filters[f] is not None]
                       if any_aux else [])
            # hybrid decode: dense (high-degree) filters' shard slots
            # come from the device OR (bitmap words above); low-degree
            # filters' slots from the host dict — O(deliveries) total
            out_slots = set(slots_flat[bounds[b]:bounds[b + 1]].tolist())
            for f in sub_fids:
                fi = int(f)
                if fi not in self._dense_row:
                    out_slots.update(self._subs.get(fi, ()))
            slots_out.append(sorted(out_slots))
        fallback = sorted(set(too_long) | set(np.nonzero(overflow)[0].tolist()))
        return matched, aux, slots_out, fallback
