"""Resource behaviour + manager FSM — parity with
``apps/emqx_resource/src/emqx_resource_manager.erl``.

A *resource* is a managed client to an external system (HTTP service,
remote broker, database). The manager owns its lifecycle FSM:

    connecting ⇄ connected → disconnected → (retry) connecting
                    ↓
                 stopped

- ``start()`` runs ``on_start``; failure leaves the resource
  ``connecting`` and retried with backoff (the reference's
  auto_restart_interval).
- ``health_check()`` (driven by the app tick, like the reference's
  health_check_interval timer) probes ``on_health_check``; a failure
  flips connected → disconnected and schedules reconnect.
- queries route through a BufferWorker (worker.py), which asks the
  manager for the live resource and backs off while it is down.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional

log = logging.getLogger(__name__)


class Resource:
    """The behaviour (-callback on_start/on_stop/on_query/... of
    emqx_resource.erl). Subclasses raise on failure."""

    def on_start(self, conf: dict) -> None:
        raise NotImplementedError

    def on_stop(self) -> None:
        pass

    def on_query(self, req: Any) -> Any:
        raise NotImplementedError

    def on_batch_query(self, reqs: list) -> list:
        return [self.on_query(r) for r in reqs]

    def on_health_check(self) -> bool:
        return True


class ResourceManager:
    def __init__(self, id: str, resource: Resource, conf: Optional[dict] = None,
                 *, auto_restart_s: float = 2.0,
                 health_check_s: float = 15.0) -> None:
        self.id = id
        self.resource = resource
        self.conf = conf or {}
        self.auto_restart_s = auto_restart_s
        self.health_check_s = health_check_s
        self.state = "stopped"
        self.error: Optional[str] = None
        self._next_retry_at = 0.0
        self._next_health_at = 0.0

    # -- lifecycle -----------------------------------------------------------

    def start(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        try:
            self.resource.on_start(self.conf)
        except Exception as e:
            self.state = "connecting"
            self.error = str(e)
            self._next_retry_at = now + self.auto_restart_s
            log.warning("resource %s failed to start: %s", self.id, e)
            return False
        self.state = "connected"
        self.error = None
        self._next_health_at = now + self.health_check_s
        return True

    def stop(self) -> None:
        if self.state != "stopped":
            try:
                self.resource.on_stop()
            except Exception:
                log.exception("resource %s on_stop failed", self.id)
            self.state = "stopped"

    def restart(self) -> bool:
        self.stop()
        return self.start()

    # -- periodic (app tick) -------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self.state == "connecting" and now >= self._next_retry_at:
            self.start(now)
        elif self.state == "connected" and now >= self._next_health_at:
            self.health_check(now)
        elif self.state == "disconnected" and now >= self._next_retry_at:
            self.start(now)

    def health_check(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        self._next_health_at = now + self.health_check_s
        try:
            ok = self.resource.on_health_check()
        except Exception as e:
            ok, self.error = False, str(e)
        if not ok and self.state == "connected":
            self.state = "disconnected"
            self._next_retry_at = now + self.auto_restart_s
            log.warning("resource %s went down: %s", self.id, self.error)
        return ok

    # -- query surface (used by BufferWorker) --------------------------------

    @property
    def connected(self) -> bool:
        return self.state == "connected"

    def query(self, req: Any) -> Any:
        if not self.connected:
            raise ConnectionError(f"resource {self.id} is {self.state}")
        return self.resource.on_query(req)

    def batch_query(self, reqs: list) -> list:
        if not self.connected:
            raise ConnectionError(f"resource {self.id} is {self.state}")
        return self.resource.on_batch_query(reqs)

    def status(self) -> dict:
        return {"id": self.id, "status": self.state, "error": self.error}
