"""Buffer worker — parity with
``apps/emqx_resource/src/emqx_resource_worker.erl``.

Sits between rule-engine actions and a ResourceManager: requests are
queued (RAM or disk via replayq — emqx_resource_worker.erl:17-18,164),
flushed in batches, retried with backoff while the resource is down,
and dropped past ``max_retries`` / on queue overflow. Counters mirror
the reference's buffer metrics (matched/success/failed/dropped/queuing).

Flush is explicit (``flush``/``tick``), driven by the app housekeeping
timer — the same role the reference's batch_time timer plays.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, Optional

from emqx_tpu.resource.resource import ResourceManager
from emqx_tpu.utils.replayq import ReplayQ

log = logging.getLogger(__name__)


def _default_encode(req: Any) -> bytes:
    return json.dumps(req).encode()


def _default_decode(b: bytes) -> Any:
    return json.loads(b)


class BufferWorker:
    def __init__(
        self, manager: ResourceManager, *,
        batch_size: int = 16,
        batch_time_s: float = 0.02,
        max_retries: int = 3,
        retry_backoff_s: float = 1.0,
        queue_dir: Optional[str] = None,       # None → RAM queue
        max_queue_bytes: int = 64 * 1024 * 1024,
        encode: Callable[[Any], bytes] = _default_encode,
        decode: Callable[[bytes], Any] = _default_decode,
        on_result: Optional[Callable[[Any, Any], None]] = None,
        auto_flush: bool = False,
    ) -> None:
        self.manager = manager
        self.batch_size = batch_size
        self.batch_time_s = batch_time_s
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.encode, self.decode = encode, decode
        self.on_result = on_result             # fn(req, result) async replies
        self.q = ReplayQ(queue_dir, mem_only=queue_dir is None,
                         max_total_bytes=max_queue_bytes)
        self.metrics = {
            "matched": 0, "success": 0, "failed": 0,
            "dropped": 0, "retried": 0,
        }
        self._retries = 0
        self._next_flush_at = 0.0
        self._next_retry_at = 0.0
        # a flush can race between the event-loop thread (enqueue hits
        # batch_size inside a publish hook) and the housekeeping thread
        # (app.tick runs in to_thread): without this, both pop/ack the
        # same batch — duplicated sends + silently discarded requests
        self._lock = threading.RLock()
        # auto_flush: a dedicated flusher honours batch_time_s/batch_size
        # instead of waiting for the (much slower) app housekeeping tick.
        # Off by default so tests with simulated clocks stay deterministic.
        self._stop = threading.Event()
        self._wake = threading.Event()
        # paused: queue accepts but nothing flushes (disabled bridge keeps
        # its buffered data instead of burning retries into drops)
        self.paused = False
        self._flusher: Optional[threading.Thread] = None
        if auto_flush:
            self._flusher = threading.Thread(
                target=self._run_flusher, daemon=True,
                name=f"buffer-{manager.id}")
            self._flusher.start()

    # -- enqueue -------------------------------------------------------------

    def enqueue(self, req: Any, now: Optional[float] = None) -> bool:
        with self._lock:
            now = time.monotonic() if now is None else now
            self.metrics["matched"] += 1
            before = self.q.dropped
            self.q.append([self.encode(req)])
            if self.q.dropped > before:
                self.metrics["dropped"] += 1
                return False
            if self._next_flush_at == 0.0:
                self._next_flush_at = now + self.batch_time_s
            # NOTE: no inline flush here even at batch_size — enqueue is
            # called from publish hooks on the event-loop thread, and a
            # flush does blocking network I/O. The flusher thread (or the
            # housekeeping tick) does the I/O; a full batch just wakes it.
            if self._flusher is not None and self.q.count() >= self.batch_size:
                self._wake.set()
            return True

    def queuing(self) -> int:
        return self.q.count()

    # -- flush ---------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        if self.paused:
            return
        with self._lock:
            now = time.monotonic() if now is None else now
            if self.q.count() and (
                    self.q.count() >= self.batch_size
                    or now >= self._next_flush_at
            ) and now >= self._next_retry_at:
                self.flush(now)

    def _run_flusher(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.batch_time_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception:
                log.exception("buffer %s flusher", self.manager.id)

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._flusher is not None:
            self._flusher.join(timeout=2)
            self._flusher = None

    def flush(self, now: Optional[float] = None) -> int:
        """Drain as many full/partial batches as the resource accepts;
        returns the number of requests completed."""
        with self._lock:
            now = time.monotonic() if now is None else now
            if now < self._next_retry_at:
                return 0
            done = 0
            while self.q.count():
                ref, raw = self.q.pop(self.batch_size)
                reqs = [self.decode(b) for b in raw]
                try:
                    results = self.manager.batch_query(reqs)
                except Exception as e:
                    self._retries += 1
                    self.metrics["retried"] += 1
                    if self._retries > self.max_retries:
                        # drop the poisoned batch, move on (reference's
                        # max_retries → reply {error, ...} and dequeue)
                        self.q.ack(ref)
                        self.metrics["failed"] += len(reqs)
                        self._retries = 0
                        log.warning(
                            "buffer %s dropped batch after retries: %s",
                            self.manager.id, e)
                        continue
                    self._next_retry_at = now + self.retry_backoff_s
                    return done
                self.q.ack(ref)
                self._retries = 0
                self.metrics["success"] += len(reqs)
                done += len(reqs)
                if self.on_result is not None:
                    for req, res in zip(reqs, results or [None] * len(reqs)):
                        self.on_result(req, res)
            self._next_flush_at = 0.0
            return done
