"""Managed external-resource runtime — the ``emqx_resource`` app."""

from emqx_tpu.resource.resource import Resource, ResourceManager   # noqa: F401
from emqx_tpu.resource.worker import BufferWorker                  # noqa: F401
