"""Device mesh + sharding layout for the routing data plane.

The reference scales out with mria replication + gen_rpc forwarding
(SURVEY.md §2.5); the TPU-native equivalents are XLA collectives over an
ICI mesh. Axis mapping (broker → mesh):

- ``dp``  (data/batch): the publish-topic batch dimension B. Matching is
  embarrassingly parallel across topics — the analogue of EMQX's
  connection/worker-pool parallelism (§2.5-1/2).
- ``tp``  (fan-out/tensor): the subscriber-bitmap word dimension W.
  Fan-out over 10M+ subscribers is a bitmap-OR whose bandwidth scales
  linearly with tp — the analogue of subscriber sharding at >1024 subs
  (emqx_broker_helper.erl:55,82-92).
- ``sp``  (sequence): topic depth L is walked sequentially inside the
  kernel (lax.scan) — intentionally NOT sharded: L ≤ 16 while B is
  thousands, so the parallel win lives on dp/tp (this is the design
  answer to ring/Ulysses-style sequence parallelism for this workload).
- ``sub`` (subscription space): the trie supports TWO layouts.
  *Replicated* (the v1 decision, still the TrieIndex default — the
  reference's full route-table replication per node,
  emqx_router.erl:148-153): matching is local, only fan-out shards.
  *Sharded* (ShardedTrieIndex): the fid space partitions into S
  per-shard tries stacked into [S, ...] buffers whose shard axis rides
  ``tp`` (``trie_sub`` below) — each device holds only its subscription
  slice, so 10M-filter HBM residency and match bandwidth both scale
  with tp instead of being a single chip's problem.

During a replicated-trie step, match runs with B sharded over BOTH axes
(dp×tp — full data parallelism), then matched fids reshard to dp-only
(an all-gather along tp that XLA inserts from the sharding constraints)
so the bitmap-OR can run with W sharded over tp.  During a SHARDED-trie
step the batch is dp-only (tp-replicated — every shard sees every
topic); each shard matches + compacts its slice in place, and the tp
collective moves the [B, S·M] merged compacted-fid tensor before the
same tp-sharded bitmap-OR.  Either way the collective rides ICI and
moves only compacted fids, never candidate blocks or bitmaps.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP = "dp"
TP = "tp"


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A (dp, tp) mesh. Default split: tp = min(4, largest pow2 divisor)."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    devices = devices[:n]
    if shape is None:
        tp = math.gcd(n, 4)
        shape = (n // tp, tp)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    import numpy as np

    return Mesh(np.asarray(devices).reshape(shape), (DP, TP))


def router_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Named shardings for the routing step's operands."""

    def s(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "replicated": s(),
        "batch_full": s((DP, TP)),       # tokens/lengths/sys: B over dp×tp
        "batch_dp": s(DP),               # fids after reshard: B over dp
        "bitmaps": s(None, TP),          # [F, W]: W over tp, F replicated
        "fanout_out": s(DP, TP),         # [B, W] result tiles
        "trie_sub": s(TP),               # stacked trie [S, ...]: S over tp
    }
