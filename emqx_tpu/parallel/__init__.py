from emqx_tpu.parallel.mesh import (
    DP,
    TP,
    make_mesh,
    router_shardings,
)

__all__ = ["DP", "TP", "make_mesh", "router_shardings"]
