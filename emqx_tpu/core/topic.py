"""Topic algebra: split/join/validate/wildcard/match.

Functional parity with the reference's ``apps/emqx/src/emqx_topic.erl``
(words/1, join/1, validate/1, wildcard/1, match/2, parse/1) — re-expressed
as pure Python over word lists so it can feed both the host oracle trie and
the tokenizer for the device index.

MQTT matching semantics implemented here:

- ``+`` matches exactly one level (which may be the empty word);
- ``#`` matches the remaining levels *including zero* (``a/#`` matches ``a``)
  and must be the last level of a filter;
- topics whose first level begins with ``$`` (``$SYS/...``) are NOT matched
  by filters whose first level is a wildcard (reference:
  ``emqx_topic.erl`` match clauses for ``<<$$, _>>``).
"""

from __future__ import annotations

from typing import Iterable, Optional

MAX_TOPIC_LEN = 65535

PLUS = "+"
HASH = "#"


def words(topic: str) -> list[str]:
    """Split a topic/filter into levels. ``"a//b"`` → ``["a", "", "b"]``."""
    return topic.split("/")


def join(ws: Iterable[str]) -> str:
    return "/".join(ws)


def levels(topic: str) -> int:
    return len(words(topic))


def wildcard(topic_or_words: str | list[str]) -> bool:
    """True if the filter contains ``+`` or ``#`` (emqx_topic:wildcard/1)."""
    ws = words(topic_or_words) if isinstance(topic_or_words, str) else topic_or_words
    return any(w in (PLUS, HASH) for w in ws)


def validate_name(topic: str) -> bool:
    """A publish topic: non-empty, bounded, no wildcards, no NUL."""
    return (
        0 < len(topic) <= MAX_TOPIC_LEN
        and "\x00" not in topic
        and not wildcard(topic)
    )


def validate_filter(topic: str) -> bool:
    """A subscription filter: wildcards allowed; ``#`` only at the last level."""
    if not 0 < len(topic) <= MAX_TOPIC_LEN or "\x00" in topic:
        return False
    ws = words(topic)
    for i, w in enumerate(ws):
        if w == HASH and i != len(ws) - 1:
            return False
        if w not in (PLUS, HASH) and (PLUS in w or HASH in w):
            # '+'/'#' must occupy the whole level
            return False
    return True


def validate(topic: str, kind: str = "filter") -> bool:
    return validate_name(topic) if kind == "name" else validate_filter(topic)


def is_sys(topic_or_words: str | list[str]) -> bool:
    """First level starts with '$' (``$SYS``, ``$share``, ``$queue``, ...)."""
    ws = words(topic_or_words) if isinstance(topic_or_words, str) else topic_or_words
    return bool(ws) and ws[0].startswith("$")


def match_words(name: list[str], filt: list[str]) -> bool:
    """Single filter match over word lists (emqx_topic:match/2)."""
    if is_sys(name) and filt and filt[0] in (PLUS, HASH):
        return False
    return _match(name, filt)


def _match(name: list[str], filt: list[str]) -> bool:
    for i, f in enumerate(filt):
        if f == HASH:
            # '#' swallows the rest, including zero levels ("a/#" matches "a")
            return True
        if i >= len(name):
            return False
        if f != PLUS and f != name[i]:
            return False
    return len(name) == len(filt)


def match(name: str, filt: str) -> bool:
    """Does publish-topic ``name`` match subscription-filter ``filt``?"""
    return match_words(words(name), words(filt))


# --- $share / $queue parsing (emqx_topic:parse/1) -------------------------

SHARE_PREFIX = "$share"
QUEUE_PREFIX = "$queue"


def parse_share(topic: str) -> tuple[Optional[str], str]:
    """Return ``(group, real_topic)``; group is None for non-shared topics.

    ``$share/g1/t/1`` → ``("g1", "t/1")``; ``$queue/t`` → ``("$queue", "t")``.
    """
    ws = words(topic)
    if ws[0] == SHARE_PREFIX and len(ws) >= 3:
        return ws[1], join(ws[2:])
    if ws[0] == QUEUE_PREFIX and len(ws) >= 2:
        return QUEUE_PREFIX, join(ws[1:])
    return None, topic


EXCLUSIVE_PREFIX = "$exclusive"


def parse_exclusive(topic: str) -> tuple[bool, str]:
    """``$exclusive/t/1`` → ``(True, "t/1")`` — the reference strips the
    prefix and flags the subopts (emqx_topic.erl:225-230); the
    subscription itself lands on the real topic."""
    ws = words(topic)
    if ws[0] == EXCLUSIVE_PREFIX and len(ws) >= 2:
        return True, join(ws[1:])
    return False, topic


def feed_var(template: str, bindings: dict[str, str]) -> str:
    """Substitute ``%c``/``%u``-style or ``${var}`` placeholders in a topic.

    Covers both emqx_topic:feed_var/3 and the mountpoint/auto-subscribe
    placeholder conventions. Single-pass per level: substituted values are
    never re-scanned, so a clientid that literally contains ``%u`` cannot
    inject the username expansion (the reference substitutes on parsed
    words for the same reason).
    """

    def sub_word(w: str) -> str:
        if w in bindings:
            val = bindings[w]
            return val if val is not None else ""
        # single-pass left-to-right scan for embedded placeholders
        out, i = [], 0
        while i < len(w):
            for key, val in bindings.items():
                if w.startswith(key, i):
                    out.append(val if val is not None else "")
                    i += len(key)
                    break
            else:
                out.append(w[i])
                i += 1
        return "".join(out)

    return join(sub_word(w) for w in words(template))
