"""Message / route / subscription data model.

Parity with the reference records in ``apps/emqx/include/emqx.hrl:63-101``
(#message{}, #route{}, #delivery{}, #subscription{}) and helpers from
``apps/emqx/src/emqx_message.erl`` — as plain dataclasses (host side; the
device side sees only tokenized topic ids and subscriber bitmaps).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field, replace
from typing import Any, Optional

_guid_counter = itertools.count()


def guid() -> int:
    """Monotonic snowflake-ish message id (emqx_guid.erl analogue):
    48-bit µs timestamp | 16-bit sequence."""
    return (time.time_ns() // 1000 << 16) | (next(_guid_counter) & 0xFFFF)


def now_ms() -> int:
    return time.time_ns() // 1_000_000


@dataclass
class Message:
    """#message{} — emqx.hrl:63-82."""

    topic: str
    payload: bytes = b""
    qos: int = 0
    from_: str = ""                      # clientid of the publisher
    id: int = field(default_factory=guid)
    flags: dict[str, bool] = field(default_factory=dict)   # retain/dup/sys
    headers: dict[str, Any] = field(default_factory=dict)  # props/peer/username
    timestamp: int = field(default_factory=now_ms)
    extra: dict[str, Any] = field(default_factory=dict)

    @property
    def retain(self) -> bool:
        return bool(self.flags.get("retain"))

    @property
    def dup(self) -> bool:
        return bool(self.flags.get("dup"))

    @property
    def sys(self) -> bool:
        return bool(self.flags.get("sys"))

    def set_flag(self, flag: str, val: bool = True) -> "Message":
        return replace(self, flags={**self.flags, flag: val})

    def set_header(self, key: str, val: Any) -> "Message":
        return replace(self, headers={**self.headers, key: val})

    def is_expired(self, now: Optional[int] = None) -> bool:
        """Message-expiry-interval (MQTT5 property, seconds)."""
        interval = (self.headers.get("properties") or {}).get(
            "Message-Expiry-Interval"
        )
        if interval is None:
            return False
        now = now_ms() if now is None else now
        return now - self.timestamp >= interval * 1000

    def update_expiry(self) -> "Message":
        """Shrink the expiry interval by elapsed time on forward (MQTT5)."""
        props = dict(self.headers.get("properties") or {})
        interval = props.get("Message-Expiry-Interval")
        if interval is None:
            return self
        remaining = max(1, interval - (now_ms() - self.timestamp) // 1000)
        props["Message-Expiry-Interval"] = remaining
        return self.set_header("properties", props)


@dataclass(frozen=True)
class Route:
    """#route{} — a topic filter routed to a destination.

    dest is a node name, ``(group, node)`` for shared subs, or a session id
    for persistent session routes (emqx_router.erl dest forms).
    """

    topic: str
    dest: Any


@dataclass(frozen=True)
class Subscription:
    """#subscription{} — subscriber (session) × topic filter."""

    topic: str
    subid: str
    subopts: "SubOpts"


@dataclass(frozen=True)
class SubOpts:
    """Subscription options (MQTT5 + emqx extensions).

    Defaults mirror ?DEFAULT_SUBOPTS (emqx.hrl / emqx_types).
    """

    qos: int = 0
    rh: int = 0      # retain-handling: 0 send, 1 send-if-new, 2 don't send
    rap: int = 0     # retain-as-published
    nl: int = 0      # no-local
    share: Optional[str] = None   # $share group name
    subid: Optional[int] = None   # MQTT5 subscription identifier
    exclusive: bool = False       # came in as $exclusive/... (is_exclusive)

    def effective_qos(self, msg_qos: int) -> int:
        """Granted delivery QoS = min(subscription max QoS, message QoS)."""
        return min(self.qos, msg_qos)


@dataclass
class Delivery:
    """#delivery{} — sender + message travelling through the broker."""

    sender: str
    message: Message
