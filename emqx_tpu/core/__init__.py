from emqx_tpu.core import topic
from emqx_tpu.core.message import Message

__all__ = ["topic", "Message"]
