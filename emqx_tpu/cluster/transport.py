"""Cluster transports.

``Transport`` carries method calls between named nodes:

- ``call(node, method, kwargs)``  → result (sync RPC; the Erlang-dist /
  gen_rpc sync slot)
- ``cast(node, method, kwargs)``  → fire-and-forget, per-peer ordered
  (gen_rpc async with per-topic-key ordering: one ordered lane per peer;
  TCP framing preserves order, LocalBus is synchronous)

Implementations:

- ``LocalBus`` — in-process registry; the multi-node-on-one-host test
  harness (the reference's ct_slave peer-node pattern, SURVEY.md §4.3,
  without separate processes).
- ``TcpTransport`` — asyncio TCP, 4-byte-length-prefixed codec frames,
  lazy per-peer connections, request/response correlation ids. The DCN
  path; one connection per peer keeps the forwarding lane ordered.
"""

from __future__ import annotations

import asyncio
import struct
import threading
from collections import deque
from typing import Any, Callable, Optional

from emqx_tpu.cluster import codec

Handler = Callable[..., Any]   # handler(**kwargs) -> result


class TransportError(ConnectionError):
    pass


class Transport:
    def __init__(self, node: str) -> None:
        self.node = node
        self._handlers: dict[str, Handler] = {}

    def register(self, method: str, fn: Handler) -> None:
        self._handlers[method] = fn

    def _dispatch(self, method: str, kwargs: dict) -> Any:
        fn = self._handlers.get(method)
        if fn is None:
            raise TransportError(f"{self.node}: no handler for {method!r}")
        return fn(**kwargs)

    def call(self, to: str, method: str, **kwargs: Any) -> Any:
        raise NotImplementedError

    def cast(self, to: str, method: str, _key: Any = None,
             **kwargs: Any) -> None:
        """Fire-and-forget. ``_key`` (gen_rpc's per-{Key,Node} client
        pools, emqx_rpc.erl:79-84) pins all casts sharing a key to ONE
        ordered lane to the peer; different keys may ride parallel
        lanes. None = the default lane."""
        raise NotImplementedError

    def peers(self) -> list[str]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalBus(Transport):
    """All nodes in one process; calls are direct function invocations
    (still passed through the codec so anything that would not survive a
    real wire fails loudly in tests)."""

    class Fabric:
        def __init__(self) -> None:
            self.nodes: dict[str, "LocalBus"] = {}
            self.partitions: set[frozenset] = set()

        def partition(self, a: str, b: str) -> None:
            """Cut the link a↔b (net-split injection)."""
            self.partitions.add(frozenset((a, b)))

        def heal(self, a: str, b: str) -> None:
            self.partitions.discard(frozenset((a, b)))

    def __init__(self, node: str, fabric: "LocalBus.Fabric") -> None:
        super().__init__(node)
        self.fabric = fabric
        fabric.nodes[node] = self

    def _peer(self, node: str) -> "LocalBus":
        if frozenset((self.node, node)) in self.fabric.partitions:
            raise TransportError(f"partitioned from {node}")
        peer = self.fabric.nodes.get(node)
        if peer is None:
            raise TransportError(f"unknown node {node}")
        return peer

    def call(self, to: str, method: str, **kwargs: Any) -> Any:
        peer = self._peer(to)
        wire = codec.decode(codec.encode(kwargs))
        return codec.decode(codec.encode(peer._dispatch(method, wire)))

    def cast(self, to: str, method: str, _key: Any = None,
             **kwargs: Any) -> None:
        self.call(to, method, **kwargs)     # in-process: always ordered

    def peers(self) -> list[str]:
        return [n for n in self.fabric.nodes if n != self.node]

    def close(self) -> None:
        self.fabric.nodes.pop(self.node, None)


class TcpTransport(Transport):
    """Length-prefixed frames over N_LANES TCP connections per peer.

    Runs its own event loop in a daemon thread so the synchronous
    call/cast surface works from broker code. Frame = 4-byte BE length +
    codec.encode({id, kind: req|resp|cast, method, kwargs | result |
    error}).

    Lanes are the gen_rpc client-pool analogue (emqx_rpc.erl:74-84,
    ?DefaultClientNum): casts carrying the same ``_key`` (the topic, at
    the forwarding call sites) always take the same connection — TCP
    framing plus the server's sequential per-connection dispatch keep
    per-key order — while different keys spread across lanes and are
    processed in parallel on the peer. Lane 0 carries calls and keyless
    casts.
    """

    N_LANES = 4

    def __init__(self, node: str, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        super().__init__(node)
        self.host, self.port = host, port
        self._peer_addrs: dict[str, tuple[str, int]] = {}
        self._writers: dict[tuple[str, int], asyncio.StreamWriter] = {}
        self._conn_futs: dict[tuple[str, int], asyncio.Future] = {}
        self._futures: dict[int, asyncio.Future] = {}
        self._req_id = 0
        # per-lane cast FIFOs + their pump tasks: casts are written to
        # the socket strictly in enqueue order (see cast() for why a
        # bare write-after-await cannot keep that promise)
        self._cast_bufs: dict[tuple[str, int], deque] = {}
        self._cast_pumps: dict[tuple[str, int], asyncio.Task] = {}
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True,
            name=f"cluster-{node}")
        self._thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._start(), self._loop)
        fut.result(timeout=10)

    async def _start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    def add_peer(self, node: str, host: str, port: int) -> None:
        self._peer_addrs[node] = (host, port)

    # -- framing ------------------------------------------------------------

    @staticmethod
    async def _read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
        try:
            head = await reader.readexactly(4)
            (ln,) = struct.unpack(">I", head)
            return codec.decode(await reader.readexactly(ln))
        except (asyncio.IncompleteReadError, ConnectionError):
            return None

    @staticmethod
    def _frame(obj: dict) -> bytes:
        body = codec.encode(obj)
        return struct.pack(">I", len(body)) + body

    # -- server side --------------------------------------------------------

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        while True:
            msg = await self._read_frame(reader)
            if msg is None:
                break
            kind = msg.get("kind")
            if kind in ("req", "cast"):
                # handlers run on executor threads, NOT the loop thread:
                # a handler may itself issue blocking transport.call()s
                # (bootstrap-from-handler paths) which schedule onto this
                # loop — running them inline would deadlock it. Awaiting
                # the executor future keeps per-connection frame order.
                try:
                    result = await self._loop.run_in_executor(
                        None, lambda m=msg: self._dispatch(
                            m["method"], m.get("kwargs") or {}))
                    err = None
                except Exception as e:          # noqa: BLE001 — relay error
                    result, err = None, f"{type(e).__name__}: {e}"
                if kind == "req":
                    writer.write(self._frame({
                        "id": msg["id"], "kind": "resp",
                        "result": result, "error": err}))
                    await writer.drain()
            elif kind == "resp":
                fut = self._futures.pop(msg["id"], None)
                if fut is not None and not fut.done():
                    if msg.get("error"):
                        fut.set_exception(TransportError(msg["error"]))
                    else:
                        fut.set_result(msg.get("result"))
        writer.close()

    # -- client side --------------------------------------------------------

    async def _open_lane(self, node: str, lane: int) -> asyncio.StreamWriter:
        addr = self._peer_addrs.get(node)
        if addr is None:
            raise TransportError(f"unknown node {node}")
        reader, writer = await asyncio.open_connection(*addr)
        self._writers[(node, lane)] = writer
        # responses to our requests come back on this same connection
        asyncio.ensure_future(self._on_conn(reader, writer))
        return writer

    async def _get_writer(self, node: str,
                          lane: int = 0) -> asyncio.StreamWriter:
        # single connect future per (node, lane): a burst of same-key
        # casts before the lane exists must all await ONE connection —
        # racing opens would split the lane across sockets and break the
        # per-key ordering the lane exists to provide
        key = (node, lane)
        fut = self._conn_futs.get(key)
        if fut is None or (fut.done() and (
                fut.exception() is not None
                or fut.result().is_closing())):
            fut = self._conn_futs[key] = self._loop.create_task(
                self._open_lane(node, lane))
        return await asyncio.shield(fut)

    @classmethod
    def _lane_for(cls, key: Any) -> int:
        if key is None:
            return 0
        import zlib
        return 1 + zlib.crc32(str(key).encode()) % max(1, cls.N_LANES - 1)

    async def _send(self, node: str, obj: dict, lane: int = 0) -> None:
        writer = await self._get_writer(node, lane)
        writer.write(self._frame(obj))
        await writer.drain()

    async def _call_async(self, node: str, method: str,
                          kwargs: dict, timeout: float) -> Any:
        self._req_id += 1
        rid = self._req_id
        fut: asyncio.Future = self._loop.create_future()
        self._futures[rid] = fut
        await self._send(node, {"id": rid, "kind": "req",
                                "method": method, "kwargs": kwargs})
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._futures.pop(rid, None)

    def call(self, to: str, method: str, *, _timeout: float = 10.0,
             **kwargs: Any) -> Any:
        fut = asyncio.run_coroutine_threadsafe(
            self._call_async(to, method, kwargs, _timeout), self._loop)
        try:
            return fut.result(timeout=_timeout + 1)
        except (ConnectionError, OSError, asyncio.TimeoutError,
                TimeoutError) as e:
            raise TransportError(f"call {method} to {to}: {e}") from e

    def cast(self, to: str, method: str, _key: Any = None,
             **kwargs: Any) -> None:
        # Enqueue-then-pump, NOT write-after-await: a coroutine that
        # awaits the lane's connect future resumes via the event-loop
        # callback queue (two hops), while a cast issued just AFTER the
        # connect completed awaits an already-done future and writes
        # immediately (zero hops) — overtaking every cast still parked
        # on its wakeup. The deflaked per-key ordering contract (the
        # gen_rpc client-pool guarantee) therefore pins the ORDER at
        # enqueue time: the frame is appended to the lane's FIFO as the
        # pump task's first synchronous step, and one pump per lane
        # drains it in order.
        lane = self._lane_for(_key)
        frame = self._frame({"id": 0, "kind": "cast",
                             "method": method, "kwargs": kwargs})
        key = (to, lane)

        def _enq():
            self._cast_bufs.setdefault(key, deque()).append(frame)
            t = self._cast_pumps.get(key)
            if t is None or t.done():
                self._cast_pumps[key] = self._loop.create_task(
                    self._pump_casts(key))
        # call_soon_threadsafe preserves submission order per caller
        # thread, so enqueue order == cast order
        self._loop.call_soon_threadsafe(_enq)

    async def _pump_casts(self, key: tuple) -> None:
        node, lane = key
        q = self._cast_bufs[key]
        while q:
            frame = q.popleft()
            try:
                writer = await self._get_writer(node, lane)
                writer.write(frame)
                await writer.drain()
            except (ConnectionError, OSError):
                # only THIS frame drops (async-mode semantics, same as
                # the old per-cast _send): the next frame re-dials via
                # _get_writer — clearing the whole queue here would
                # silently discard every queued broadcast on a one-frame
                # transient (e.g. a shared-membership delta after a
                # peer restart)
                continue
        # a cast appended after the final `while q` check sees the task
        # done() and spawns a fresh pump — both run on the loop thread,
        # so the check/append interleaving cannot lose a frame

    def flush_casts(self, timeout: float = 10.0) -> None:
        """Barrier: block until every queued cast has been written AND
        drained to its socket (the deterministic settle the lane tests
        need — the bytes are on the wire; the peer's per-connection
        sequential dispatch does the rest in order)."""
        async def _wait():
            while (any(self._cast_bufs.values())
                   or any(not t.done()
                          for t in self._cast_pumps.values())):
                await asyncio.sleep(0.001)
        fut = asyncio.run_coroutine_threadsafe(_wait(), self._loop)
        try:
            fut.result(timeout)
        except BaseException:
            # a timed-out (or interrupted) barrier must not leave the
            # 1ms poll coroutine spinning on the loop forever
            fut.cancel()
            raise

    def peers(self) -> list[str]:
        return list(self._peer_addrs)

    def close(self) -> None:
        async def shutdown():
            for t in self._cast_pumps.values():
                t.cancel()
            for w in self._writers.values():
                w.close()
            self._server.close()
        asyncio.run_coroutine_threadsafe(shutdown(), self._loop).result(5)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
