"""Multi-node-on-one-host test harness — the
``emqx_common_test_helpers:emqx_cluster/2`` analogue (SURVEY.md §4.3):
N real broker nodes with the real replication/RPC stack, no real
network (LocalBus) or loopback TCP (TcpTransport), one process.
"""

from __future__ import annotations

from typing import Optional

from emqx_tpu.cluster.node import ClusterNode
from emqx_tpu.cluster.transport import LocalBus, TcpTransport


def make_cluster(n: int, transport: str = "local",
                 names: Optional[list[str]] = None,
                 **app_kw) -> list[ClusterNode]:
    """Boot an n-node cluster, fully joined. ``transport``: "local"
    (in-process bus) or "tcp" (loopback sockets)."""
    names = names or [f"node{i + 1}" for i in range(n)]
    nodes: list[ClusterNode] = []
    if transport == "local":
        fabric = LocalBus.Fabric()
        for name in names:
            nodes.append(ClusterNode(name, LocalBus(name, fabric),
                                     **app_kw))
        for node in nodes:
            node.fabric = fabric
    else:
        transports = [TcpTransport(name) for name in names]
        for t in transports:
            for u in transports:
                if t is not u:
                    t.add_peer(u.node, u.host, u.port)
        for name, t in zip(names, transports):
            nodes.append(ClusterNode(name, t, **app_kw))
    # join everyone to the first seed (static discovery)
    for node in nodes[1:]:
        node.join([names[0]])
    sync(nodes)
    return nodes


def sync(nodes: list[ClusterNode]) -> None:
    """Flush every node's replication stream (deterministic settle)."""
    for node in nodes:
        node.flush()


def stop(nodes: list[ClusterNode]) -> None:
    for node in nodes:
        node.transport.close()
