"""Wire codec for the cluster planes.

JSON envelope with tagged binaries (``{"$b": base64}``) and tagged
tuples (``{"$t": [...]}``, needed because route destinations use tuples
as ``(group, node)``) — the gen_rpc/ETF serialization slot. Message and
SubOpts get explicit to/from-dict forms so forwarding and takeover are
cross-process safe, not just cross-object.
"""

from __future__ import annotations

import base64
import json
from typing import Any

from emqx_tpu.core.message import Message, SubOpts


def _enc(obj: Any) -> Any:
    if isinstance(obj, bytes):
        return {"$b": base64.b64encode(obj).decode()}
    if isinstance(obj, tuple):
        return {"$t": [_enc(x) for x in obj]}
    if isinstance(obj, dict):
        return {k: _enc(v) for k, v in obj.items()}
    if isinstance(obj, (list, set)):
        return [_enc(x) for x in obj]
    return obj


def _dec(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "$b" in obj and len(obj) == 1:
            return base64.b64decode(obj["$b"])
        if "$t" in obj and len(obj) == 1:
            return tuple(_dec(x) for x in obj["$t"])
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(x) for x in obj]
    return obj


def encode(obj: Any) -> bytes:
    return json.dumps(_enc(obj), separators=(",", ":")).encode()


def decode(data: bytes) -> Any:
    return _dec(json.loads(data.decode()))


# -- domain objects --------------------------------------------------------


def msg_to_dict(m: Message) -> dict:
    return {
        "topic": m.topic, "payload": m.payload, "qos": m.qos,
        "from": m.from_, "id": m.id, "flags": dict(m.flags),
        "headers": dict(m.headers), "timestamp": m.timestamp,
    }


def msg_from_dict(d: dict) -> Message:
    return Message(
        topic=d["topic"], payload=d["payload"], qos=d["qos"],
        from_=d.get("from", ""), id=d.get("id", 0),
        flags=d.get("flags") or {}, headers=d.get("headers") or {},
        timestamp=d.get("timestamp", 0),
    )


def subopts_to_dict(o: SubOpts) -> dict:
    return {"qos": o.qos, "rh": o.rh, "rap": o.rap, "nl": o.nl,
            "share": o.share, "subid": o.subid}


def subopts_from_dict(d: dict) -> SubOpts:
    return SubOpts(qos=d.get("qos", 0), rh=d.get("rh", 0),
                   rap=d.get("rap", 0), nl=d.get("nl", 0),
                   share=d.get("share"), subid=d.get("subid"))
