"""Cluster-replicated config transactions — the ``emqx_cluster_rpc``
analogue (apps/emqx_conf/src/emqx_cluster_rpc.erl:26-44,71-140).

The reference keeps an mnesia table of config transactions (MFAs) plus a
per-node commit-cursor table; every node applies the log in order, a
lagging/failed node stalls its cursor and catches up later, with
``skip_failed_commit`` / ``fast_forward_to_commit`` escape hatches.

Here the same shape without mnesia:

- **ordered log**: entries ``{tnx_id, kind, path, value, initiator}``.
  Global order comes from a deterministic **coordinator** — the
  lowest-named alive *core* node (mria core/replicant split: replicants
  never coordinate, they forward appends — ``emqx_machine.erl:86-87``).
  The coordinator assigns ``tnx_id``, validates the op by applying it
  locally (the reference aborts a multicall whose MFA fails on the
  initiating node), then broadcasts the commit.
- **per-node cursors**: each node applies strictly in order; an entry
  that fails to apply stalls the cursor (later commits queue), the
  stall is retried every housekeeping tick, and the operator can
  ``skip_failed_commit`` past a poison entry or
  ``fast_forward_to_commit`` to a chosen id.
- **catch-up**: a commit arriving with a gap pulls ``conf.catchup``
  from its sender; joiners replay the log carried in the bootstrap
  snapshot (emqx_cluster_rpc.erl:92-105 catch-up on join).

Coordinator fail-over: commits replicate the log everywhere, so the
next-lowest core continues from ``max(tnx_id)`` it has seen (after
draining its own queue — a catching-up coordinator refuses writes
rather than committing unvalidated entries).

Partitions: like the reference (mnesia is not partition-tolerant;
ekka **autoheal** restarts the minority island, discarding its
divergent writes), both sides of a split may commit conflicting
tnx_ids. On heal, the bootstrap exchange detects the conflict and the
side that lost the coordinator tie-break (higher-named core) ADOPTS
the winner's log and cluster override wholesale — its
partition-era writes are discarded, exactly the autoheal outcome.
A 2-node cluster therefore keeps accepting config changes when one
node dies (availability parity with the reference) at the documented
cost of last-writer-wins-by-node-order across a true split-brain.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from emqx_tpu.cluster.transport import TransportError


class ClusterConfError(RuntimeError):
    """Transient cluster condition (no core, coordinator catching up,
    local apply stalled) — retryable."""


class ClusterConfRejected(ClusterConfError):
    """The txn failed validation on the coordinator — permanent for this
    value; NOT retryable (mgmt maps it to 400, not 503)."""


class ClusterConf:
    # applied entries kept behind the cursor for lagging peers' catch-up;
    # older entries compact away (the reference prunes applied cluster_rpc
    # rows the same way) — a peer further behind adopts a snapshot instead
    KEEP = 500

    def __init__(self, node) -> None:
        self.node = node                     # ClusterNode
        self.log: dict[int, dict] = {}       # tnx_id → entry
        self.max_seen = 0                    # highest tnx_id in self.log
        self.cursor = 0                      # last APPLIED tnx_id
        self.compacted_to = 0                # entries ≤ this are pruned
        self.failed: Optional[dict] = None   # {"tnx_id", "error"}
        self._was_coordinator = False        # tail-sync latch (failover)
        self._lock = threading.RLock()

    # -- coordinator election ------------------------------------------------

    def coordinator(self) -> Optional[str]:
        """Lowest-named alive core node (self included). None when no
        core is reachable — replicants cannot commit alone."""
        n = self.node
        alive = [n.name] if n.role == "core" else []
        with n._lock:
            alive += [peer for peer, m in n.members.items()
                      if m.get("alive")
                      and m.get("role", "core") == "core"]
        return min(alive) if alive else None

    # -- write path (emqx_cluster_rpc:multicall) -----------------------------

    def multicall(self, kind: str, path: tuple, value: Any = None) -> Any:
        """Cluster-wide config op. Returns the locally applied value."""
        leader = self.coordinator()
        if leader is None:
            raise ClusterConfError(
                "no core node reachable — config txns need a core "
                "(mria core/replicant: replicants cannot commit)")
        if leader == self.node.name:
            entry = self._append(kind, list(path), value)
        else:
            self._was_coordinator = False
            try:
                resp = self.node.transport.call(
                    leader, "conf.append", from_node=self.node.name,
                    kind=kind, path=list(path), value=value)
            except TransportError as e:
                raise ClusterConfError(
                    f"coordinator {leader} unreachable: {e}") from e
            if resp.get("error"):
                cls = (ClusterConfRejected if resp.get("rejected")
                       else ClusterConfError)
                raise cls(resp["error"])
            entry = resp["entry"]
            # apply here-and-now; the broadcast cast that also carries
            # this entry is a no-op once the cursor has passed it
            self._ingest(entry, from_node=leader)
            with self._lock:
                if self.cursor < entry["tnx_id"]:
                    # committed cluster-wide but failed to apply HERE —
                    # surface the partial state instead of returning the
                    # stale pre-txn value as success
                    err = (self.failed or {}).get("error", "apply lagging")
                    raise ClusterConfError(
                        f"txn {entry['tnx_id']} committed cluster-wide "
                        f"but failed to apply on {self.node.name}: {err} "
                        f"(node stalled; see /cluster_rpc, "
                        f"skip_failed_commit to recover)")
        conf = getattr(self.node.app, "config", None)
        if conf is not None and kind == "put":
            return conf.get(tuple(entry["path"]))
        return None

    def _sync_tail(self) -> None:
        """On promotion, learn the true log tail from every reachable
        peer before assigning ids: the previous coordinator's final
        commit may have reached a subset of nodes we haven't heard from
        (a lost cast), and re-using its tnx_id would silently diverge
        that subset."""
        for peer in self.node.alive_peers():
            try:
                st = self.node.transport.call(
                    peer, "conf.status", from_node=self.node.name)
            except TransportError:
                continue
            if st.get("max_seen", 0) > self.max_seen:
                self.catchup(peer)

    def _append(self, kind: str, path: list, value: Any) -> dict:
        """Coordinator side: assign id, validate by local apply,
        replicate."""
        if not self._was_coordinator:
            self._sync_tail()            # failover read-repair
            self._was_coordinator = True
        self._drain()      # a just-promoted coordinator finishes catching
        #                    up before accepting new txns
        with self._lock:
            tnx_id = self.max_seen + 1
            if self.cursor != tnx_id - 1:
                raise ClusterConfError(
                    f"coordinator still catching up "
                    f"(applied {self.cursor}/{self.max_seen}) — retry")
            entry = {"tnx_id": tnx_id, "kind": kind, "path": path,
                     "value": value, "initiator": self.node.name,
                     # committing coordinator: the split-brain tie-break
                     # compares the CONFLICTING ENTRIES' coordinators so
                     # every node on both sides reaches the same verdict
                     "coord": self.node.name}
            # validate: the txn must apply cleanly on the coordinator
            # (reference: multicall aborts if the MFA fails on the
            # initiating node — nothing is committed)
            try:
                self._apply(entry)
            except Exception as e:
                raise ClusterConfRejected(
                    f"config txn rejected: {e}") from e
            self.cursor = tnx_id
            self.log[tnx_id] = entry
            self.max_seen = tnx_id
        self.node._broadcast("conf.commit", entry=entry)
        return entry

    # -- apply machinery -----------------------------------------------------

    def _apply(self, entry: dict) -> None:
        conf = getattr(self.node.app, "config", None)
        if conf is None:
            return                        # log-only node (no Config bound)
        path = tuple(entry["path"])
        if entry["kind"] == "put":
            conf.put(path, entry["value"], layer="cluster", local=True)
        elif entry["kind"] == "remove":
            conf.remove(path, layer="cluster", local=True)

    def _drain(self) -> None:
        """Apply every queued entry in order until a gap or a failure."""
        while True:
            with self._lock:
                nxt = self.log.get(self.cursor + 1)
                if nxt is None:
                    return
                try:
                    self._apply(nxt)
                except Exception as e:   # stall; retried on tick
                    self.failed = {"tnx_id": nxt["tnx_id"],
                                   "error": str(e)}
                    return
                self.cursor = nxt["tnx_id"]
                if self.failed and self.failed["tnx_id"] <= self.cursor:
                    self.failed = None

    def _ingest(self, entry: dict, from_node: str) -> None:
        with self._lock:
            self.log[entry["tnx_id"]] = entry
            self.max_seen = max(self.max_seen, entry["tnx_id"])
            gap = entry["tnx_id"] > self.cursor + 1 and \
                self.log.get(self.cursor + 1) is None
        if gap:
            self.catchup(from_node)
        self._drain()

    def catchup(self, peer: str) -> None:
        with self._lock:
            since = self.cursor
        try:
            resp = self.node.transport.call(
                peer, "conf.catchup", from_node=self.node.name,
                since=since)
        except TransportError:
            return
        if resp.get("snapshot") is not None:
            # the peer compacted past our cursor: individual replay is
            # impossible, adopt its state wholesale
            self._adopt(resp["snapshot"])
            return
        with self._lock:
            for e in resp.get("entries", ()):
                self.log[e["tnx_id"]] = e
                self.max_seen = max(self.max_seen, e["tnx_id"])
        self._drain()

    def tick(self) -> None:
        """Housekeeping: retry a stalled apply, pull missing entries,
        prune the applied tail."""
        with self._lock:
            if not self._was_coordinator or \
                    self.coordinator() != self.node.name:
                self._was_coordinator = False
            stalled = self.failed is not None
            behind = self.cursor < self.max_seen
            gap = behind and self.log.get(self.cursor + 1) is None
        if stalled:
            with self._lock:
                self.failed = None       # retry from the stalled entry
            self._drain()
        elif gap:
            # a lost commit cast left a hole; re-pull from the
            # coordinator (or whoever has the tail)
            leader = self.coordinator()
            if leader is not None and leader != self.node.name:
                self.catchup(leader)
        elif behind:
            self._drain()
        self.prune()

    def prune(self) -> None:
        """Compact applied entries beyond the KEEP window (bounded
        memory + bounded bootstrap size; peers further behind than the
        window adopt a snapshot instead of replaying)."""
        with self._lock:
            floor = self.cursor - self.KEEP
            if floor > self.compacted_to:
                for i in range(self.compacted_to + 1, floor + 1):
                    self.log.pop(i, None)
                self.compacted_to = floor

    # -- operator escape hatches (emqx_cluster_rpc.erl:26-44) ---------------

    def skip_failed_commit(self) -> int:
        """Advance past a poison entry WITHOUT applying it; returns the
        new cursor."""
        with self._lock:
            if self.failed is not None:
                self.cursor = max(self.cursor, self.failed["tnx_id"])
                self.failed = None
        self._drain()
        with self._lock:
            return self.cursor

    def fast_forward_to_commit(self, tnx_id: int) -> int:
        """Jump the cursor to ``tnx_id`` (entries in between are NOT
        applied — operator asserts the node state already matches)."""
        with self._lock:
            self.cursor = max(self.cursor, min(tnx_id, self.max_seen))
            self.failed = None
        self._drain()
        with self._lock:
            return self.cursor

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {"node": self.node.name, "role": self.node.role,
                    "tnx_id": self.cursor, "max_seen": self.max_seen,
                    "coordinator": self.coordinator(),
                    "failed": dict(self.failed) if self.failed else None}

    def cluster_status(self) -> list[dict]:
        """This node's view + every live peer's (mgmt/CLI surface)."""
        out = [self.status()]
        for peer in self.node.alive_peers():
            try:
                out.append(self.node.transport.call(
                    peer, "conf.status", from_node=self.node.name))
            except TransportError:
                pass
        return out

    # -- transport handlers --------------------------------------------------

    def h_append(self, from_node: str, kind: str, path: list,
                 value: Any) -> dict:
        if self.coordinator() != self.node.name:
            return {"error": f"not the coordinator "
                             f"(coordinator={self.coordinator()})"}
        try:
            entry = self._append(kind, path, value)
        except ClusterConfRejected as e:
            return {"error": str(e), "rejected": True}
        except ClusterConfError as e:
            return {"error": str(e)}
        return {"entry": entry}

    def h_commit(self, from_node: str, entry: dict) -> None:
        self._ingest(entry, from_node)

    def h_catchup(self, from_node: str, since: int) -> dict:
        with self._lock:
            if since < self.compacted_to:
                pass                     # snapshot path (outside lock)
            else:
                return {"entries": [self.log[i] for i in sorted(self.log)
                                    if i > since]}
        return {"snapshot": self.snapshot()}

    def h_status(self, from_node: str) -> dict:
        return self.status()

    # -- snapshot integration (catch-up on join, autoheal on re-merge) ------

    def snapshot(self) -> dict:
        conf = getattr(self.node.app, "config", None)
        with self._lock:
            return {"log": [self.log[i] for i in sorted(self.log)],
                    "compacted_to": self.compacted_to,
                    "cursor": self.cursor,
                    "override": (conf.overrides()[0]
                                 if conf is not None else {})}

    def apply_snapshot(self, snap: dict, from_node: str = "") -> None:
        entries = list(snap.get("log", ()))
        with self._lock:
            conflicting = [
                e for e in entries
                if self.log.get(e["tnx_id"]) is not None
                and self.log[e["tnx_id"]] != e]
            mine = (self.log[conflicting[0]["tnx_id"]]
                    if conflicting else None)
            behind_compaction = snap.get("compacted_to", 0) > self.cursor
        if conflicting:
            # split-brain re-merge: same tnx_id, different content on the
            # two sides. The tie-break compares the CONFLICTING ENTRIES'
            # committing coordinators (not the snapshot sender — a node
            # can receive the winning log from any peer of the other
            # side): lower coordinator name wins, so every node on both
            # sides reaches the same verdict. The losing side adopts log
            # + override wholesale and its partition-era writes are
            # discarded (ekka autoheal restarts the minority — same
            # outcome)
            theirs_coord = conflicting[0].get("coord", from_node)
            mine_coord = mine.get("coord", self.node.name)
            if theirs_coord < mine_coord:
                self._adopt(snap)
            return                       # else: the peer adopts ours
        if behind_compaction:
            # the peer pruned past our cursor — entry-by-entry replay is
            # impossible; adopt its state (fresh joiner far behind)
            self._adopt(snap)
            return
        with self._lock:
            for e in entries:
                self.log[e["tnx_id"]] = e
                self.max_seen = max(self.max_seen, e["tnx_id"])
        self._drain()

    def _adopt(self, snap: dict) -> None:
        conf = getattr(self.node.app, "config", None)
        with self._lock:
            self.log = {e["tnx_id"]: e for e in snap.get("log", ())}
            self.max_seen = max(self.log) if self.log else \
                snap.get("compacted_to", 0)
            # the adopted override reflects the sender's APPLIED prefix
            # (its cursor), not its whole log — a stalled sender may
            # carry queued entries its override doesn't include yet; set
            # our cursor to the sender's and drain the tail normally
            self.cursor = snap.get("cursor", self.max_seen)
            self.compacted_to = snap.get("compacted_to", 0)
            self.failed = None
            if conf is not None:
                conf.adopt_cluster_override(snap.get("override", {}))
        self._drain()
