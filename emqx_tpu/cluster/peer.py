"""Standalone cluster peer process — one real broker node in its own OS
process, the piece ``ct_slave`` provides the reference (real peer BEAM
nodes on one host, emqx_common_test_helpers.erl:553-620). The test
harness spawns N of these, wires their loopback cluster ports together,
and drives them with real MQTT clients; killing one exercises the
failure-detection path for real.

Usage:
    python -m emqx_tpu.cluster.peer --name n1 \
        --cluster-port 7001 --mqtt-port 1884 \
        --peer n2:127.0.0.1:7002 --seed n2

Prints ``READY <mqtt_port> <mgmt_port> rlog=<v>`` on stdout once both
listeners serve; ``rlog=<v>`` is the rlog BPAPI version negotiated with
the join seed (or this node's own max when it boots alone) — the
mixed-version interop test asserts the downshift on it.
"""

from __future__ import annotations

import argparse
import asyncio
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--cluster-port", type=int, default=0)
    ap.add_argument("--mqtt-port", type=int, default=0)
    ap.add_argument("--peer", action="append", default=[],
                    help="name:host:port, repeatable")
    ap.add_argument("--seed", default=None,
                    help="node name to join (first peer by default)")
    ap.add_argument("--role", default="core",
                    choices=["core", "replicant"])
    ap.add_argument("--mgmt", action="store_true",
                    help="also serve the REST API (port printed on READY)")
    args = ap.parse_args()

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.cluster.node import ClusterNode
    from emqx_tpu.cluster.transport import TcpTransport
    from emqx_tpu.config.config import Config

    conf = Config()
    conf.init_load("")
    app = BrokerApp.from_config(conf, node=args.name)
    transport = TcpTransport(args.name, port=args.cluster_port)
    for spec in args.peer:
        name, host, port = spec.rsplit(":", 2)
        transport.add_peer(name, host, int(port))
    node = ClusterNode(args.name, transport, app=app, role=args.role)
    if args.peer:
        seed = args.seed or args.peer[0].split(":", 1)[0]
        node.join([seed])

    mgmt_port = 0
    if args.mgmt:
        from emqx_tpu.mgmt.api import ManagementApi
        mgmt = ManagementApi(app, cluster_node=node)
        mgmt_port = mgmt.start()

    async def serve() -> None:
        from emqx_tpu.cluster import bpapi

        server = BrokerServer(port=args.mqtt_port, app=node.app)
        await server.start()
        rlog_v = (min(node.proto_rlog.values()) if node.proto_rlog
                  else max(bpapi.supported_versions()["rlog"]))
        print(f"READY {server.port} {mgmt_port} rlog={rlog_v}",
              flush=True)
        await asyncio.Event().wait()          # run until killed

    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
