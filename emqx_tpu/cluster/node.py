"""Cluster node — binds a BrokerApp to the cluster planes.

Responsibilities and their reference counterparts:

- **route replication** (mria rlog, ``emqx_router.erl:78-92``): every
  local route mutation appends to the Router's delta log; ``flush``
  pushes per-peer delta streams (``rlog.apply_deltas``); a trimmed log or
  fresh joiner triggers full ``rlog.bootstrap``. Each node thus holds a
  full route-table replica and match stays node-local
  (emqx_router.erl:148-153's design decision).
- **message forwarding** (gen_rpc, ``emqx_broker.erl:302-324``): routes
  whose dest is a peer node cast ``broker.dispatch`` on the peer's
  ordered lane.
- **shared subscriptions** (``emqx_shared_sub.erl``): membership
  replicates via ``rlog.shared_delta`` into the node-aware member table;
  the publishing node's strategy picks ONE member cluster-wide, remote
  members get ``shared_sub.deliver``.
- **clientid registry + takeover** (``emqx_cm_registry`` /
  ``emqx_cm_proto_v1``): connects broadcast ``rlog.registry_delta``; a
  resume finding the session on a peer calls ``cm.takeover``, which
  serializes the session (subscriptions + pending queue) and tears down
  the old owner — the 2-phase takeover of emqx_cm.erl:377-429.
- **failure detection** (``emqx_router_helper``): missed heartbeats mark
  a peer down; its routes, shared members and registry entries purge; a
  succeeding ping re-bootstraps both sides (ekka autoheal analogue).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from emqx_tpu.app import BrokerApp
from emqx_tpu.cluster import bpapi, codec
from emqx_tpu.cluster.transport import Transport, TransportError
from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message
from emqx_tpu.session.session import Session


class ClusterNode:
    def __init__(self, name: str, transport: Transport,
                 app: Optional[BrokerApp] = None,
                 heartbeat_misses: int = 2,
                 role: str = "core", **app_kw: Any) -> None:
        self.name = name
        # mria core/replicant split (emqx_machine.erl:86-87): cores
        # coordinate config txns and serve bootstrap; replicants
        # forward writes and replicate
        self.role = role
        self.transport = transport
        self.app = app or BrokerApp(node=name, forward_fn=self._forward,
                                    **app_kw)
        if self.app.broker.forward_fn is None:
            self.app.broker.forward_fn = self._forward
        self.app.broker.shared_dispatch = self._shared_dispatch
        self.registry: dict[str, str] = {}        # clientid → node
        # topic → (sid, node) for $exclusive holders on OTHER nodes; local
        # holders live in broker.exclusive (emqx_exclusive_subscription's
        # cluster-wide mnesia table, split per node here)
        self.exclusive_remote: dict[str, tuple[str, str]] = {}
        # topic → sid for claims WE are acquiring or hold: registered
        # BEFORE the peer RPC so a concurrent claim from another node
        # sees it in _h_excl_try (mutual-reject, never double-grant)
        self.exclusive_local: dict[str, str] = {}
        self._excl_sync_was_nonempty = False
        self.members: dict[str, dict] = {}        # peer → {alive, missed}
        self._peer_cursor: dict[str, int] = {}    # peer → flushed seq
        self.heartbeat_misses = heartbeat_misses
        self._lock = threading.RLock()

        # per-peer negotiated rlog version (bpapi.negotiate at hello,
        # both directions); absent peer = assume v1, the frozen floor
        self.proto_rlog: dict[str, int] = {}
        self._rlog_v2_ok = 2 in bpapi.supported_versions().get("rlog", [])

        # native cluster trunk (round 9): when this node's listener is
        # a NativeBrokerServer with a trunk port, hello/ping advertise
        # it and peers' advertisements wire trunk links — cross-node
        # QoS0/1 publishes then ride the C++ plane; everything else
        # (and every non-native peer) stays on the Python lanes below
        self.native_server = None
        self._trunk_advertise_host = "127.0.0.1"

        t = self.transport
        t.register("broker.dispatch", self._h_dispatch)
        t.register("shared_sub.deliver", self._h_shared_deliver)
        t.register("cm.takeover", self._h_takeover)
        t.register("cm.kick", self._h_kick)
        t.register("cm.lookup", self._h_lookup)
        t.register("rlog.apply_deltas", self._h_apply_deltas)
        if self._rlog_v2_ok:
            # the v2 compact delta wire exists only where v2 is
            # registered (EMQX_BPAPI_RLOG_V2) — a v1 peer can never be
            # sent to it because flush() gates on the negotiated version
            t.register("rlog.apply_deltas2", self._h_apply_deltas2)
        t.register("rlog.bootstrap", self._h_bootstrap)
        t.register("rlog.shared_delta", self._h_shared_delta)
        t.register("rlog.registry_delta", self._h_registry_delta)
        t.register("excl.try", self._h_excl_try)
        t.register("excl.release", self._h_excl_release)
        t.register("excl.sync", self._h_excl_sync)
        t.register("node.hello", self._h_hello)
        t.register("node.ping", self._h_ping)
        t.register("node.bye", self._h_bye)

        # cluster-replicated config transactions (emqx_cluster_rpc)
        from emqx_tpu.cluster.conf import ClusterConf
        self.conf = ClusterConf(self)
        t.register("conf.append", self.conf.h_append)
        t.register("conf.commit", self.conf.h_commit)
        t.register("conf.catchup", self.conf.h_catchup)
        t.register("conf.status", self.conf.h_status)
        config = getattr(self.app, "config", None)
        if config is not None:
            # PUT /configs (and every cluster-layer Config.put) becomes
            # a cluster-wide transaction
            config.cluster_fn = self.conf.multicall

        hooks = self.app.hooks
        hooks.add("session.subscribed", self._on_subscribed, priority=-500)
        hooks.add("session.unsubscribed", self._on_unsubscribed,
                  priority=-500)
        hooks.add("client.connected", self._on_client_connected,
                  priority=-500)
        hooks.add("session.terminated", self._on_session_gone,
                  priority=-500)
        hooks.add("session.discarded", self._on_session_gone,
                  priority=-500)
        # cluster-wide $exclusive locking seam (broker/broker.py)
        self.app.broker.exclusive_try_fn = self._exclusive_try
        self.app.broker.exclusive_release_fn = self._exclusive_release
        # cross-node session lookup/takeover seam
        self._orig_open_session = self.app.cm.open_session
        self.app.cm.open_session = self._open_session
        self.app.add_ticker(self.tick)   # heartbeat on app housekeeping

    # -- native trunk wiring ------------------------------------------------

    def attach_native(self, server, advertise_host: str = "127.0.0.1"
                      ) -> None:
        """Bind a NativeBrokerServer with a trunk listener to this
        node: hello/ping now advertise the trunk address, and peers'
        advertisements dial it. Call before join() (a later attach
        converges on the next heartbeat round)."""
        self.native_server = server
        self._trunk_advertise_host = advertise_host

    def _trunk_advert(self):
        srv = self.native_server
        if srv is None or getattr(srv, "trunk_port", None) is None:
            return None
        return [self._trunk_advertise_host, srv.trunk_port]

    def _learn_trunk(self, node: str, trunk) -> None:
        """Record a peer's advertised trunk address (idempotent for an
        unchanged address — trunk_register re-dials only on change)."""
        if self.native_server is None or not trunk:
            return
        try:
            self.native_server.trunk_register(node, trunk[0],
                                              int(trunk[1]))
        except Exception:                     # noqa: BLE001 — advisory
            # a bad advert must not poison membership: the Python
            # forward lane keeps carrying this peer's traffic
            pass

    # -- membership ---------------------------------------------------------

    def join(self, seeds: list[str]) -> None:
        """Static-seed discovery (ekka join): hello each seed, learn the
        full membership, bootstrap state from the first live seed."""
        for seed in seeds:
            if seed == self.name:
                continue
            try:
                resp = self.transport.call(
                    seed, "node.hello", node=self.name,
                    versions=bpapi.supported_versions(), role=self.role,
                    trunk=self._trunk_advert())
            except TransportError:
                continue
            # compat gate + downshift: a v2 node joining a v1 cluster
            # records 1 here and speaks the v1 dict wire to this peer
            self.proto_rlog[seed] = bpapi.negotiate(resp["versions"],
                                                    "rlog")
            self._learn_trunk(seed, resp.get("trunk"))
            self._mark_alive(seed, role=resp.get("role", "core"))
            # learned members start UNVERIFIED (alive only on direct
            # contact — a dead peer in the seed's list must not receive
            # deltas that vanish silently)
            others = [m for m in resp.get("members", [])
                      if m not in (self.name, seed)]
            with self._lock:
                for other in others:
                    self.members.setdefault(
                        other, {"alive": False, "missed": 0})
            # announce ourselves; a successful hello IS the verification
            for other in others:
                try:
                    r2 = self.transport.call(
                        other, "node.hello", node=self.name,
                        versions=bpapi.supported_versions(),
                        role=self.role, trunk=self._trunk_advert())
                    self.proto_rlog[other] = bpapi.negotiate(
                        r2["versions"], "rlog")
                    self._learn_trunk(other, r2.get("trunk"))
                    self._mark_alive(other, role=r2.get("role", "core"))
                except TransportError:
                    pass
            self._bootstrap_from(seed)
            return
        # no live seed: boot as a single-node cluster (first core)

    def leave(self) -> None:
        for peer in self.alive_peers():
            try:
                self.transport.cast(peer, "node.bye", node=self.name)
            except TransportError:
                pass

    def alive_peers(self) -> list[str]:
        with self._lock:
            return [n for n, m in self.members.items() if m.get("alive")]

    def _mark_alive(self, node: str, role: Optional[str] = None) -> None:
        with self._lock:
            was_down = (node in self.members
                        and not self.members[node]["alive"])
            kept_role = role or self.members.get(node, {}).get(
                "role", "core")
            self.members[node] = {"alive": True, "missed": 0,
                                  "role": kept_role}
            if was_down:
                self._peer_cursor[node] = 0      # full re-flush of ours
        if was_down:
            # healed partition: pull the peer's state; the peer pulls
            # ours when its own ping sees us (ekka autoheal, both sides
            # resync). RPC happens OUTSIDE the lock: the peer's handler
            # takes its own lock and may call back into us.
            try:
                self._bootstrap_from(node)
                self.flush()
            except TransportError:
                with self._lock:
                    self.members[node] = {"alive": False, "missed": 99,
                                          "role": kept_role}

    def _nodedown(self, node: str) -> None:
        """Purge everything owned by a dead peer
        (emqx_router_helper:cleanup_routes + shared/registry sweeps)."""
        with self._lock:
            self.members[node] = {
                "alive": False, "missed": 99,
                "role": self.members.get(node, {}).get("role", "core")}
            dead_cids = [c for c, n in self.registry.items() if n == node]
            for cid in dead_cids:
                del self.registry[cid]
            for t in [t for t, (_, n) in self.exclusive_remote.items()
                      if n == node]:
                del self.exclusive_remote[t]
        self._drop_peer_routes(node)
        self.app.shared.node_down(node)

    def tick(self) -> None:
        """Heartbeat + route flush (housekeeping timer)."""
        self.flush()
        self.conf.tick()          # retry stalled / pull missing config txns
        with self._lock:
            holders = [{"topic": t, "sid": s}
                       for t, s in self.exclusive_local.items()]
        # claim reconciliation: skip the broadcast while the feature is
        # idle (one final empty sync after the last claim disappears is
        # all the GC needs — steady-state O(nodes²) chatter otherwise)
        if holders or self._excl_sync_was_nonempty:
            self._broadcast("excl.sync", holders=holders)
        self._excl_sync_was_nonempty = bool(holders)
        with self._lock:
            peers = list(self.members)
        for peer in peers:
            try:
                resp = self.transport.call(peer, "node.ping",
                                           node=self.name, role=self.role,
                                           trunk=self._trunk_advert())
                if isinstance(resp, dict):
                    self._learn_trunk(peer, resp.get("trunk"))
                self._mark_alive(
                    peer, role=(resp.get("role")
                                if isinstance(resp, dict) else None))
            except TransportError:
                with self._lock:
                    m = self.members.get(peer)
                    if m is None:
                        continue
                    m["missed"] = m.get("missed", 0) + 1
                    down_now = (m["alive"]
                                and m["missed"] >= self.heartbeat_misses)
                if down_now:
                    self._nodedown(peer)

    # -- route replication --------------------------------------------------

    def _own_deltas(self, deltas) -> list[dict]:
        mine = []
        for d in deltas:
            dest = d.dest
            if dest == self.name or (
                    isinstance(dest, tuple) and dest[1] == self.name):
                mine.append({"op": d.op, "topic": d.topic, "dest": dest})
        return mine

    def flush(self) -> None:
        """Push pending route deltas to every live peer. Replication is
        a confirmed ``call`` (mria transactions are acked) — the cursor
        only advances on success, so a dropped frame is retransmitted
        next flush; the message-forwarding lane stays fire-and-forget."""
        router = self.app.broker.router
        head = router.seq
        for peer in self.alive_peers():
            with self._lock:
                cursor = self._peer_cursor.get(peer, 0)
            if cursor >= head:
                continue
            deltas = router.deltas_since(cursor)
            try:
                if deltas is None:
                    # our log no longer reaches the peer's cursor: the
                    # peer re-pulls a full snapshot (replicant bootstrap)
                    self.transport.call(peer, "rlog.apply_deltas",
                                        from_node=self.name, deltas=None)
                else:
                    mine = self._own_deltas(deltas)
                    if mine:
                        if (self._rlog_v2_ok
                                and self.proto_rlog.get(peer, 1) >= 2):
                            # negotiated v2 both ways: compact tuple wire
                            self.transport.call(
                                peer, "rlog.apply_deltas2",
                                from_node=self.name,
                                deltas=[(d["op"], d["topic"], d["dest"])
                                        for d in mine])
                        else:
                            self.transport.call(peer, "rlog.apply_deltas",
                                                from_node=self.name,
                                                deltas=mine)
                with self._lock:
                    self._peer_cursor[peer] = max(
                        self._peer_cursor.get(peer, 0), head)
            except TransportError:
                pass                              # retried next flush

    def _h_apply_deltas(self, from_node: str,
                        deltas: Optional[list]) -> None:
        router = self.app.broker.router
        if deltas is None:                        # sender asks us to re-pull
            self._drop_peer_routes(from_node)
            self._bootstrap_from(from_node)
            return
        for d in deltas:
            if d["op"] == "add":
                router.add_route(d["topic"], d["dest"])
            else:
                router.delete_route(d["topic"], d["dest"])

    def _h_apply_deltas2(self, from_node: str, deltas: list) -> None:
        """rlog v2 wire: (op, topic, dest) tuples (bpapi.RLOG_V2)."""
        router = self.app.broker.router
        for op, topic, dest in deltas:
            if op == "add":
                router.add_route(topic, dest)
            else:
                router.delete_route(topic, dest)

    def _drop_peer_routes(self, node: str) -> None:
        router = self.app.broker.router
        router.cleanup_dest(node)
        for t in list(router.topics()):
            for r in router.lookup_routes(t):
                if isinstance(r.dest, tuple) and r.dest[1] == node:
                    router.delete_route(t, r.dest)

    def _snapshot(self) -> dict:
        """Everything a joiner needs: all routes we know (ours + third
        party), shared membership, clientid registry."""
        router = self.app.broker.router
        routes = []
        for t in router.topics():
            for r in router.lookup_routes(t):
                routes.append({"topic": t, "dest": r.dest})
        shared = [
            {"group": g, "topic": tp, "sid": sid, "node": node}
            for (g, tp), ms in self.app.shared.members().items()
            for sid, node in ms
        ]
        with self._lock:
            registry = dict(self.registry)
            exclusive = [{"topic": t, "sid": s, "node": n}
                         for t, (s, n) in self.exclusive_remote.items()]
            exclusive += [{"topic": t, "sid": s, "node": self.name}
                          for t, s in self.exclusive_local.items()]
        return {"routes": routes, "shared": shared,
                "registry": registry, "exclusive": exclusive,
                "conf": self.conf.snapshot(), "node": self.name}

    def _apply_snapshot(self, snap: dict) -> None:
        router = self.app.broker.router
        for r in snap["routes"]:
            dest = r["dest"]
            if dest != self.name and not (
                    isinstance(dest, tuple) and dest[1] == self.name):
                router.add_route(r["topic"], dest)
        for s in snap["shared"]:
            if s["node"] != self.name:
                self.app.shared.join(s["group"], s["topic"], s["sid"],
                                     node=s["node"])
        with self._lock:
            for cid, node in snap["registry"].items():
                if node != self.name:
                    self.registry[cid] = node
            for e in snap.get("exclusive", ()):
                if e["node"] != self.name:
                    self.exclusive_remote.setdefault(
                        e["topic"], (e["sid"], e["node"]))
        # config-txn catch-up on join (emqx_cluster_rpc.erl:92-105)
        self.conf.apply_snapshot(snap.get("conf", {}),
                                 from_node=snap.get("node", ""))

    def _bootstrap_from(self, peer: str) -> None:
        snap = self.transport.call(peer, "rlog.bootstrap",
                                   from_node=self.name)
        self._apply_snapshot(snap)
        self._peer_cursor.setdefault(peer, 0)

    def _h_bootstrap(self, from_node: str) -> dict:
        if from_node not in self.members:
            self._mark_alive(from_node)
        return self._snapshot()

    # -- forwarding (gen_rpc lane) ------------------------------------------

    def _forward(self, dest: str, filt: str, msg: Message) -> None:
        with self._lock:
            alive = self.members.get(dest, {}).get("alive", False)
        if not alive:
            return                    # stale route; purge is in flight
        try:
            # the broker's _route counts messages.forward for this leg
            # per-topic lane keeps one topic's messages ordered while
            # different topics parallelize (gen_rpc key, emqx_rpc.erl:79)
            self.transport.cast(dest, "broker.dispatch", _key=filt,
                                filter=filt, msg=codec.msg_to_dict(msg))
        except TransportError:
            pass

    def _h_dispatch(self, filter: str, msg: dict) -> int:
        """Remote leg of emqx_broker:dispatch/2 (emqx_broker.erl:326-337)."""
        m = codec.msg_from_dict(msg)
        deliveries: dict[str, list] = {}
        self.app.broker._dispatch_local(filter, m, deliveries)
        self.app.cm.dispatch(deliveries)
        return len(deliveries)

    # -- shared subscriptions -----------------------------------------------

    def _shared_dispatch(self, group: str, topic: str, msg: Message):
        def deliver_fn(sid: str, node: str) -> bool:
            if node == self.name:
                ch = self.app.cm.lookup_channel(sid)
                return ch is not None and ch.conn_state == "connected"
            return self.members.get(node, {}).get("alive", False)

        local = []
        for sid, node, sub_topic in self.app.shared.dispatch(
                group, topic, msg, deliver_fn=deliver_fn):
            if node == self.name:
                local.append((sid, sub_topic))
            else:
                try:
                    self.transport.cast(
                        node, "shared_sub.deliver", _key=sub_topic,
                        sid=sid, sub_topic=sub_topic,
                        msg=codec.msg_to_dict(msg))
                except TransportError:
                    pass
        return local

    def _h_shared_deliver(self, sid: str, sub_topic: str, msg: dict) -> None:
        self.app.cm.dispatch(
            {sid: [(sub_topic, codec.msg_from_dict(msg))]})

    def _on_subscribed(self, sid: str, topic: str, opts,
                       is_new: bool = True) -> None:
        group, real = T.parse_share(topic)
        if group and is_new:
            self._broadcast("rlog.shared_delta", op="join", group=group,
                            topic=real, sid=sid)
        self.flush()

    def _on_unsubscribed(self, sid: str, topic: str) -> None:
        group, real = T.parse_share(topic)
        if group:
            self._broadcast("rlog.shared_delta", op="leave", group=group,
                            topic=real, sid=sid)
        self.flush()

    def _h_shared_delta(self, from_node: str, op: str, group: str,
                        topic: str, sid: str) -> None:
        if op == "join":
            self.app.shared.join(group, topic, sid, node=from_node)
        elif op == "leave":
            self.app.shared.leave(group, topic, sid, node=from_node)
        else:                                     # "down": all groups
            self.app.shared.member_down(sid)

    # -- $exclusive cluster lock --------------------------------------------
    #
    # The reference makes $exclusive cluster-wide with one mnesia
    # transaction (emqx_exclusive_subscription.erl try_subscribe).  Here
    # the acquire is peer-confirmed: every live peer must accept the
    # claim before the local subscribe proceeds.  Two nodes claiming the
    # same topic concurrently can both be rejected (each sees the
    # other's in-flight claim) — safe, never double-granted; the client
    # simply retries.  Claims are purged on release, session teardown
    # (via unsubscribe) and nodedown.

    def _exclusive_try(self, topic: str, sid: str):
        """Cluster acquire; returns the holding sid on conflict, else
        None.  Runs OUTSIDE the broker lock (broker/broker.py)."""
        with self._lock:
            mine = self.exclusive_local.get(topic)
            if mine is not None and mine != sid:
                return mine
            rh = self.exclusive_remote.get(topic)
            if rh is not None and rh[0] != sid:
                return rh[0]
            # Register the in-flight claim BEFORE any RPC: a concurrent
            # excl.try from another node must see it and reject (both
            # claimants may mutually reject — safe; never double-grant).
            self.exclusive_local[topic] = sid
        accepted: list[str] = []
        for peer in self.alive_peers():
            try:
                conflict = self.transport.call(
                    peer, "excl.try", from_node=self.name,
                    topic=topic, sid=sid)
            except TransportError:
                continue   # dead/flaky peer: its stale view of this
                #            claim reconciles via the periodic excl.sync
            if conflict is not None:
                with self._lock:
                    if self.exclusive_local.get(topic) == sid:
                        del self.exclusive_local[topic]
                for p in accepted:
                    try:
                        self.transport.cast(p, "excl.release",
                                            from_node=self.name,
                                            topic=topic, sid=sid)
                    except TransportError:
                        pass   # dangling claim on p GC'd by excl.sync
                return conflict
            accepted.append(peer)
        return None

    def _exclusive_release(self, topic: str, sid: str) -> None:
        with self._lock:
            if self.exclusive_local.get(topic) == sid:
                del self.exclusive_local[topic]
        self._broadcast("excl.release", topic=topic, sid=sid)

    def _h_excl_try(self, from_node: str, topic: str, sid: str):
        """Peer's side of the acquire: record the claim unless we know a
        different holder.  Touches only our own state — never calls back
        into the claimant (deadlock-free by construction)."""
        with self._lock:
            mine = self.exclusive_local.get(topic)
            if mine is not None and mine != sid:
                return mine
            rh = self.exclusive_remote.get(topic)
            if rh is not None and rh[0] != sid:
                return rh[0]
            self.exclusive_remote[topic] = (sid, from_node)
        return None

    def _h_excl_release(self, from_node: str, topic: str, sid: str) -> None:
        with self._lock:
            rh = self.exclusive_remote.get(topic)
            if rh is not None and rh[0] == sid:
                del self.exclusive_remote[topic]

    def _h_excl_sync(self, from_node: str, holders: list) -> None:
        """Authoritative claim set from one node: drop every claim we
        attribute to that node that it no longer asserts (GC for claims
        orphaned by lost release casts / timed-out acquires)."""
        asserted = {(h["topic"], h["sid"]) for h in holders}
        with self._lock:
            stale = [t for t, (s, n) in self.exclusive_remote.items()
                     if n == from_node and (t, s) not in asserted]
            for t in stale:
                del self.exclusive_remote[t]
            for h in holders:
                self.exclusive_remote.setdefault(
                    h["topic"], (h["sid"], from_node))

    # -- clientid registry + takeover ---------------------------------------

    def _on_client_connected(self, ci) -> None:
        cid = getattr(ci, "clientid", None)
        if cid:
            with self._lock:
                self.registry[cid] = self.name
            self._broadcast("rlog.registry_delta", op="register",
                            clientid=cid)

    def _on_session_gone(self, sid: str, *a) -> None:
        with self._lock:
            owned = self.registry.get(sid) == self.name
            if owned:
                del self.registry[sid]
        if owned:
            self._broadcast("rlog.registry_delta", op="unregister",
                            clientid=sid)
        # shared membership cleanup replicates as leaves via unsubscribe
        # hooks; a crashed channel's members go with member_down locally
        # and with registry_delta on peers
        self._broadcast("rlog.shared_delta", op="down", group="",
                        topic="", sid=sid)

    def _h_registry_delta(self, from_node: str, op: str,
                          clientid: str) -> None:
        with self._lock:
            if op == "register":
                self.registry[clientid] = from_node
            elif self.registry.get(clientid) == from_node:
                del self.registry[clientid]

    def _open_session(self, clean_start: bool, clientid: str,
                      new_channel, session_opts: Optional[dict] = None):
        """Cross-node open_session: consult the replicated registry; if
        the session lives on a peer, kick (clean start) or take it over
        (emqx_cm.erl:268-341 + cm_proto_v1)."""
        local = self.app.cm.lookup_channel(clientid)
        with self._lock:
            owner = self.registry.get(clientid)
            owner_alive = self.members.get(owner, {}).get("alive", False)
        if (local is None and owner is not None and owner != self.name
                and owner_alive):
            if clean_start:
                try:
                    self.transport.call(owner, "cm.kick",
                                        clientid=clientid)
                except TransportError:
                    pass
                return self._orig_open_session(
                    True, clientid, new_channel, session_opts)
            try:
                state = self.transport.call(owner, "cm.takeover",
                                            clientid=clientid)
            except TransportError:
                state = None
            if state is not None:
                session = Session(clientid=clientid, clean_start=False,
                                  **(session_opts or {}))
                # consume-on-ack (round 18): sessions minted OUTSIDE
                # CM.open_session must wire the settle seam too, or a
                # durable-enabled node's acks would never spend their
                # store replay markers (review finding)
                self.app.cm._wire_settle(clientid, session)
                for t, o in state["subscriptions"].items():
                    opts = codec.subopts_from_dict(o)
                    session.subscribe(t, opts)
                    self.app.broker.subscribe(clientid, t, opts)
                pending = [codec.msg_from_dict(d)
                           for d in state["pending"]]
                self.app.cm.register_channel(clientid, new_channel)
                return session, True, pending
        return self._orig_open_session(clean_start, clientid, new_channel,
                                       session_opts)

    def _h_takeover(self, clientid: str) -> Optional[dict]:
        ch = self.app.cm.lookup_channel(clientid)
        if ch is None or ch.session is None:
            return None
        session, pending = ch.takeover()
        subs = {t: codec.subopts_to_dict(o)
                for t, o in session.subscriptions.items()}
        # the old owner's broker footprint migrates with the session
        self.app.broker.subscriber_down(clientid)
        self.app.cm.unregister_channel(clientid)
        with self._lock:
            if self.registry.get(clientid) == self.name:
                del self.registry[clientid]
        self.flush()
        return {"subscriptions": subs,
                "pending": [codec.msg_to_dict(m) for m in pending]}

    def _h_kick(self, clientid: str) -> bool:
        return self.app.cm.kick(clientid)

    def _h_lookup(self, clientid: str) -> bool:
        return self.app.cm.lookup_channel(clientid) is not None

    # -- hello/ping/bye -----------------------------------------------------

    def _h_hello(self, node: str, versions: dict,
                 role: str = "core", trunk=None) -> dict:
        # record the negotiated rlog version for the REVERSE direction
        # too: our flushes to a v1 joiner must use the v1 dict wire
        self.proto_rlog[node] = bpapi.negotiate(versions, "rlog")
        self._learn_trunk(node, trunk)
        with self._lock:
            members = list(self.members) + [self.name]
        self._mark_alive(node, role=role)
        return {"versions": bpapi.supported_versions(),
                "members": members, "role": self.role,
                "trunk": self._trunk_advert()}

    def _h_ping(self, node: str, role: Optional[str] = None,
                trunk=None) -> dict:
        with self._lock:
            known_down = (node in self.members
                          and not self.members[node]["alive"])
            if node not in self.members:
                self.members[node] = {"alive": True, "missed": 0,
                                      "role": role or "core"}
            elif role is not None:
                self.members[node]["role"] = role
        if known_down:
            self._mark_alive(node, role=role)
        self._learn_trunk(node, trunk)
        # role rides the pong so a peer that learned us indirectly (seed
        # member list, no hello) still classifies us correctly — a
        # replicant misread as core could be elected coordinator; the
        # trunk advert rides it too so a late attach_native converges
        # on the next heartbeat round
        return {"pong": True, "role": self.role,
                "trunk": self._trunk_advert()}

    def _h_bye(self, node: str) -> None:
        with self._lock:
            known = node in self.members
        if known:
            if self.native_server is not None:
                # the node LEFT (not a partition): drop its trunk link
                # and replay ring for good; routes purge below
                self.native_server.trunk_unregister(node, forget=True)
            self._nodedown(node)
            with self._lock:
                self.members.pop(node, None)

    def _broadcast(self, method: str, **kwargs: Any) -> None:
        for peer in self.alive_peers():
            try:
                self.transport.cast(peer, method,
                                    from_node=self.name, **kwargs)
            except TransportError:
                pass
