"""BPAPI — versioned cross-node protos, parity with
``apps/emqx/src/bpapi/`` + the static snapshot check
(``apps/emqx/test/emqx_bpapi_static_checks.erl``).

Every cross-node call goes through a registered proto: a named,
versioned bundle of method signatures. Signatures are FROZEN once
released — ``snapshot()`` renders the registry to a canonical dict that
a test pins verbatim; any drift fails the suite, which is exactly the
mechanism that makes rolling upgrades safe in the reference. A node
announces ``supported_versions()`` at join; callers pick
``negotiate(peer_versions, proto)`` = highest common version.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Proto:
    name: str
    version: int
    # method name → argument names (the wire signature)
    methods: dict = field(default_factory=dict)

    @property
    def id(self) -> str:
        return f"{self.name}_v{self.version}"


_REGISTRY: dict[str, Proto] = {}


def register(proto: Proto) -> Proto:
    if proto.id in _REGISTRY and _REGISTRY[proto.id] != proto:
        raise ValueError(f"BPAPI {proto.id} redefined with new signature")
    _REGISTRY[proto.id] = proto
    return proto


def get(name: str, version: int) -> Proto:
    return _REGISTRY[f"{name}_v{version}"]


def snapshot() -> dict[str, dict]:
    """Canonical registry dump — pinned by tests/test_cluster.py."""
    return {
        p.id: {m: list(args) for m, args in sorted(p.methods.items())}
        for p in sorted(_REGISTRY.values(), key=lambda p: p.id)
    }


def supported_versions() -> dict[str, list[int]]:
    out: dict[str, list[int]] = {}
    for p in _REGISTRY.values():
        out.setdefault(p.name, []).append(p.version)
    return {k: sorted(v) for k, v in out.items()}


def negotiate(peer_versions: dict[str, list[int]], name: str) -> int:
    """Highest proto version both sides speak (emqx_bpapi:supported_version)."""
    mine = set(supported_versions().get(name, ()))
    theirs = set(peer_versions.get(name, ()))
    common = mine & theirs
    if not common:
        raise ValueError(f"no common version for BPAPI {name!r}")
    return max(common)


# -- the v1 protos (mirroring apps/emqx/src/proto/*_proto_v1.erl) ---------

BROKER_V1 = register(Proto("broker", 1, {
    # emqx_broker_proto_v1:forward_async/3 — dispatch on the remote node
    "dispatch": ["filter", "msg"],
}))

CM_V1 = register(Proto("cm", 1, {
    # emqx_cm_proto_v1: takeover_session / kick / lookup
    "takeover": ["clientid"],
    "kick": ["clientid"],
    "lookup": ["clientid"],
}))

SHARED_SUB_V1 = register(Proto("shared_sub", 1, {
    # emqx_shared_sub_proto_v1:dispatch — deliver to a group member
    "deliver": ["sid", "sub_topic", "msg"],
}))

RLOG_V1 = register(Proto("rlog", 1, {
    # mria-rlog analogue: delta stream + bootstrap
    "apply_deltas": ["from_node", "deltas"],
    "bootstrap": ["from_node"],
    "shared_delta": ["from_node", "op", "group", "topic", "sid"],
    "registry_delta": ["from_node", "op", "clientid"],
}))

EXCL_V1 = register(Proto("excl", 1, {
    # $exclusive cluster lock (emqx_exclusive_subscription try_subscribe):
    # peer-confirmed acquire + release broadcast + periodic claim sync
    # (the GC for claims orphaned by lost casts)
    "try": ["from_node", "topic", "sid"],
    "release": ["from_node", "topic", "sid"],
    "sync": ["from_node", "holders"],
}))

NODE_V1 = register(Proto("node", 1, {
    "hello": ["node", "versions"],
    "ping": ["node"],
    "bye": ["node"],
}))


# -- v2 protos (opt-in rollouts) -------------------------------------------
#
# RLOG v2 compacts the delta stream: ``apply_deltas2`` carries
# (op, topic, dest) tuples instead of keyed dicts. Registration is
# OPT-IN via EMQX_BPAPI_RLOG_V2=1 — exactly the reference's
# rolling-upgrade shape (a cluster mixes releases mid-upgrade): a node
# without the flag announces rlog [1], ``negotiate`` downshifts the v2
# node to the v1 dict wire, and route replication keeps flowing either
# way (tests/test_cluster_procs.py drives both mixes with real
# processes). The v1 signature stays frozen per the snapshot pin.
RLOG_V2 = None
if os.environ.get("EMQX_BPAPI_RLOG_V2"):
    RLOG_V2 = register(Proto("rlog", 2, {
        "apply_deltas": ["from_node", "deltas"],
        "apply_deltas2": ["from_node", "deltas"],
        "bootstrap": ["from_node"],
        "shared_delta": ["from_node", "op", "group", "topic", "sid"],
        "registry_delta": ["from_node", "op", "clientid"],
    }))
