"""Cluster plane (SURVEY.md §5 'distributed communication backend'):

1. control RPC + replication transport (Erlang-dist / mria-rlog slot)
2. per-topic-ordered message forwarding (gen_rpc slot)
3. BPAPI-style versioned protos with frozen-signature snapshots
4. membership + failure detection with route purge on nodedown

Transports: in-process ``LocalBus`` (the ct_slave-style multi-node-
on-one-host test harness) and length-prefixed TCP (the DCN path).
"""
