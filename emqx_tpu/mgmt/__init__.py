"""Management plane (SURVEY.md §1 L11): REST API (minirest analogue),
API-key/JWT auth, CLI verbs (emqx_ctl analogue)."""
