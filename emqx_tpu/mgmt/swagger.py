"""OpenAPI generator — the ``emqx_dashboard_swagger.erl`` analogue.

The reference derives its swagger document from the HOCON schemas that
also validate the config; here the same ``Struct``/``Field`` tree
(emqx_tpu/config/schema.py) becomes OpenAPI component schemas, and the
ManagementApi route table becomes the path list — one source of truth
for validation, docs, and the REST surface.
"""

from __future__ import annotations

from typing import Any

from emqx_tpu.config.schema import Field, Struct

_TYPE_MAP = {
    "bool": {"type": "boolean"},
    "int": {"type": "integer"},
    "float": {"type": "number"},
    "string": {"type": "string"},
    "duration": {"type": "string",
                 "description": "duration (e.g. 30s, 5m, 1h)"},
    "bytesize": {"type": "string",
                 "description": "byte size (e.g. 16MB, 1024KB)"},
    "map": {"type": "object", "additionalProperties": True},
}


def field_to_openapi(f: "Field | Struct") -> dict[str, Any]:
    if isinstance(f, Struct):
        return struct_to_openapi(f)
    spec = dict(_TYPE_MAP.get(f.type, {"type": "string"}))
    if f.type == "enum":
        spec = {"type": "string", "enum": list(f.enum or [])}
    if f.type == "array":
        spec = {"type": "array",
                "items": field_to_openapi(f.item) if f.item is not None
                else {"type": "string"}}
    if f.default is not None:
        spec["default"] = (f.default if not isinstance(f.default, bytes)
                           else f.default.decode("utf-8", "replace"))
    if f.desc:
        spec["description"] = f.desc
    return spec


def struct_to_openapi(s: Struct) -> dict[str, Any]:
    required = [k for k, f in s.fields.items()
                if isinstance(f, Field) and f.required]
    spec: dict[str, Any] = {
        "type": "object",
        "properties": {k: field_to_openapi(f) for k, f in s.fields.items()},
    }
    if required:
        spec["required"] = required
    if s.open:
        spec["additionalProperties"] = True
    if s.desc:
        spec["description"] = s.desc
    return spec


def generate(api, title: str = "EMQX-TPU Management API",
             version: str = "5.0.14-tpu") -> dict[str, Any]:
    """Build the OpenAPI 3.0 document from a ManagementApi instance."""
    from emqx_tpu.config.schema import root_schema

    paths: dict[str, dict] = {}
    for method, _pat, names, fn, desc in api._routes:
        # desc carries the original path template (route() default)
        template = desc if desc.startswith("/") else None
        if template is None:
            continue
        op = {
            "summary": (fn.__doc__ or fn.__name__).strip().split("\n")[0],
            "security": [{"bearerAuth": []}],
            "responses": {"200": {"description": "success"}},
        }
        if names:
            op["parameters"] = [
                {"name": n, "in": "path", "required": True,
                 "schema": {"type": "string"}} for n in names
            ]
        if method in ("POST", "PUT"):
            op["requestBody"] = {"content": {"application/json": {
                "schema": {"type": "object"}}}}
        paths.setdefault(template, {})[method.lower()] = op
    return {
        "openapi": "3.0.3",
        "info": {"title": title, "version": version},
        "paths": dict(sorted(paths.items())),
        "components": {
            "securitySchemes": {
                "bearerAuth": {"type": "http", "scheme": "bearer",
                               "bearerFormat": "JWT"},
            },
            "schemas": {
                "Config": struct_to_openapi(root_schema()),
            },
        },
    }
