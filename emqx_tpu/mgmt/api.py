"""REST management API — parity with ``apps/emqx_management`` +
``apps/emqx_dashboard`` (minirest/cowboy + swagger).

Endpoints (subset mirroring emqx_mgmt_api_*.erl, /api/v5 prefix):

    POST /login                     → bearer token (dashboard JWT slot)
    GET  /status /nodes /metrics /stats /prometheus /alarms
    GET  /clients [?page,limit,like_clientid]   GET/DELETE /clients/{id}
    GET  /subscriptions             GET /topics (the route table)
    POST /publish                   {topic, payload, qos, retain}
    GET/POST /banned                DELETE /banned/{kind}/{value}
    GET  /configs?path=a.b          PUT /configs {path, value}
    GET/POST /rules   GET/PUT/DELETE /rules/{id}   POST /rule_test
    GET  /retainer/messages         DELETE /retainer/message/{topic}
    GET  /api-docs.json             (swagger-ish doc from the registry)

Auth: ``Authorization: Bearer <token>`` from /login, or API-key basic
auth (emqx_mgmt_auth analogue). Runs a stdlib ThreadingHTTPServer on a
daemon thread beside the asyncio broker.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

from emqx_tpu.core.message import Message


class HtmlPage(str):
    """Marker: a handler EXPLICITLY returning HTML. The reply path
    keys content-type on this type, never on body sniffing — a string
    handler echoing user data must stay text/plain."""


class ApiError(Exception):
    def __init__(self, status: int, code: str, message: str = "") -> None:
        super().__init__(message or code)
        self.status = status
        self.code = code


class ApiKeys:
    """API key/secret pairs (emqx_mgmt_auth.erl)."""

    def __init__(self) -> None:
        self._keys: dict[str, str] = {}       # key → sha256(secret)

    def create(self, key: Optional[str] = None,
               secret: Optional[str] = None) -> tuple[str, str]:
        key = key or base64.urlsafe_b64encode(os.urandom(9)).decode()
        secret = secret or base64.urlsafe_b64encode(os.urandom(18)).decode()
        self._keys[key] = hashlib.sha256(secret.encode()).hexdigest()
        return key, secret

    def check(self, key: str, secret: str) -> bool:
        want = self._keys.get(key)
        return want is not None and hmac.compare_digest(
            want, hashlib.sha256(secret.encode()).hexdigest())

    def delete(self, key: str) -> bool:
        return self._keys.pop(key, None) is not None

    def list(self) -> list[str]:
        return list(self._keys)


class Dashboard:
    """Admin users + bearer tokens (emqx_dashboard_admin/_token)."""

    TOKEN_TTL_S = 3600.0

    def __init__(self) -> None:
        self._users: dict[str, str] = {}
        self._tokens: dict[str, tuple[str, float]] = {}
        self.add_user("admin", "public")      # the reference's default

    def add_user(self, username: str, password: str) -> None:
        self._users[username] = hashlib.sha256(password.encode()).hexdigest()

    def login(self, username: str, password: str) -> Optional[str]:
        want = self._users.get(username)
        if want is None or not hmac.compare_digest(
                want, hashlib.sha256(password.encode()).hexdigest()):
            return None
        token = base64.urlsafe_b64encode(os.urandom(24)).decode()
        self._tokens[token] = (username, time.time() + self.TOKEN_TTL_S)
        return token

    def verify(self, token: str) -> bool:
        hit = self._tokens.get(token)
        if hit is None:
            return False
        if time.time() > hit[1]:
            del self._tokens[token]
            return False
        return True


class ManagementApi:
    """Route registry + handlers over a BrokerApp (and optional cluster
    node for /nodes)."""

    def __init__(self, app, cluster_node=None) -> None:
        self.app = app
        self.cluster = cluster_node
        self.api_keys = ApiKeys()
        self.dashboard = Dashboard()
        self._routes: list[tuple[str, re.Pattern, list[str], Callable,
                                 str]] = []
        self._register_all()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self.port: Optional[int] = None

    # -- routing ------------------------------------------------------------

    def route(self, method: str, path: str, fn: Callable,
              desc: str = "") -> None:
        names = re.findall(r"\{(\w+)\}", path)
        pat = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", path) + "$")
        self._routes.append((method, pat, names, fn, desc or path))

    def handle(self, method: str, path: str, query: dict,
               body: Any, authed: bool) -> tuple[int, Any]:
        if path == "/api/v5/login" and method == "POST":
            # body may be raw bytes when the client skipped the JSON
            # content-type — a malformed login is a 400, not a crash
            if not isinstance(body, dict):
                return 400, {"code": "BAD_REQUEST",
                             "message": "JSON body required"}
            return self._login(body)
        if path == "/api-docs.json" and method == "GET":
            return 200, self._docs()
        if path in ("/", "/dashboard") and method == "GET":
            # minimal built-in status page (the reference ships a full
            # Vue app from a separate repo; this keeps the dashboard
            # surface self-contained: login + live monitor over the
            # same REST API)
            return 200, HtmlPage(_DASHBOARD_HTML)
        if not authed:
            return 401, {"code": "UNAUTHORIZED",
                         "message": "missing or bad credentials"}
        for m, pat, names, fn, _desc in self._routes:
            if m != method:
                continue
            match = pat.match(path)
            if match is None:
                continue
            try:
                kwargs = {n: urllib.parse.unquote(match.group(n))
                          for n in names}
                result = fn(query=query, body=body, **kwargs)
                if isinstance(result, tuple):
                    return result
                return (204, None) if result is None else (200, result)
            except ApiError as e:
                return e.status, {"code": e.code, "message": str(e)}
            except Exception as e:        # noqa: BLE001 — surface as 500
                return 500, {"code": "INTERNAL_ERROR", "message": str(e)}
        return 404, {"code": "NOT_FOUND", "message": path}

    def _login(self, body: dict) -> tuple[int, Any]:
        token = self.dashboard.login(body.get("username", ""),
                                     body.get("password", ""))
        if token is None:
            return 401, {"code": "BAD_USERNAME_OR_PWD"}
        return 200, {"token": token, "version": "5"}

    def check_auth(self, headers) -> bool:
        auth = headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            return self.dashboard.verify(auth[7:].strip())
        if auth.startswith("Basic "):
            try:
                user, _, pw = base64.b64decode(
                    auth[6:].strip()).decode().partition(":")
            except Exception:
                return False
            return self.api_keys.check(user, pw)
        return False

    def _docs(self) -> dict:
        from emqx_tpu.mgmt import swagger

        return swagger.generate(self)

    # -- handlers -----------------------------------------------------------

    def _register_all(self) -> None:
        r = self.route
        r("GET", "/api/v5/status", self.h_status)
        r("GET", "/api/v5/nodes", self.h_nodes)
        r("GET", "/api/v5/metrics", self.h_metrics)
        r("GET", "/api/v5/stats", self.h_stats)
        r("GET", "/api/v5/prometheus", self.h_prometheus)
        r("GET", "/api/v5/alarms", self.h_alarms)
        r("GET", "/api/v5/clients", self.h_clients)
        r("GET", "/api/v5/clients/{clientid}", self.h_client)
        r("DELETE", "/api/v5/clients/{clientid}", self.h_kick)
        r("GET", "/api/v5/subscriptions", self.h_subscriptions)
        r("GET", "/api/v5/topics", self.h_topics)
        r("POST", "/api/v5/publish", self.h_publish)
        r("GET", "/api/v5/banned", self.h_banned_list)
        r("POST", "/api/v5/banned", self.h_banned_create)
        r("DELETE", "/api/v5/banned/{kind}/{value}", self.h_banned_delete)
        r("GET", "/api/v5/configs", self.h_config_get)
        r("PUT", "/api/v5/configs", self.h_config_put)
        r("GET", "/api/v5/cluster_rpc", self.h_cluster_rpc_status)
        r("POST", "/api/v5/cluster_rpc/skip", self.h_cluster_rpc_skip)
        r("POST", "/api/v5/cluster_rpc/fast_forward",
          self.h_cluster_rpc_ff)
        r("GET", "/api/v5/rules", self.h_rules_list)
        r("POST", "/api/v5/rules", self.h_rules_create)
        r("GET", "/api/v5/rules/{id}", self.h_rule_get)
        r("PUT", "/api/v5/rules/{id}", self.h_rule_put)
        r("DELETE", "/api/v5/rules/{id}", self.h_rule_delete)
        r("POST", "/api/v5/rule_test", self.h_rule_test)
        r("GET", "/api/v5/retainer/messages", self.h_retained)
        r("DELETE", "/api/v5/retainer/message/{topic}",
          self.h_retained_delete)
        r("GET", "/api/v5/api_key", self.h_api_keys)
        r("POST", "/api/v5/api_key", self.h_api_key_create)
        r("GET", "/api/v5/trace", self.h_trace_list)
        r("POST", "/api/v5/trace", self.h_trace_create)
        r("DELETE", "/api/v5/trace/{name}", self.h_trace_delete)
        r("PUT", "/api/v5/trace/{name}/stop", self.h_trace_stop)
        r("GET", "/api/v5/trace/{name}/log", self.h_trace_log)
        # native distributed tracing (round 13): the queryable last-N
        # span ring + the degradation ledger's event ring/totals
        r("GET", "/api/v5/tracing/spans", self.h_tracing_spans)
        r("GET", "/api/v5/tracing/ledger", self.h_tracing_ledger)
        # kernel-plane observability (round 19): trie-health snapshot
        # from the device-metrics fold (counters + gauges + stages)
        r("GET", "/api/v5/kernel/stats", self.h_kernel_stats)
        r("GET", "/api/v5/slow_subscriptions", self.h_slow_subs)
        r("DELETE", "/api/v5/slow_subscriptions", self.h_slow_subs_clear)
        r("GET", "/api/v5/mqtt/topic_metrics", self.h_topic_metrics)
        r("POST", "/api/v5/mqtt/topic_metrics", self.h_topic_metrics_add)
        r("DELETE", "/api/v5/mqtt/topic_metrics/{topic}",
          self.h_topic_metrics_del)
        r("GET", "/api/v5/mqtt/topic_rewrite", self.h_rewrite_get)
        r("PUT", "/api/v5/mqtt/topic_rewrite", self.h_rewrite_put)
        r("GET", "/api/v5/mqtt/auto_subscribe", self.h_auto_sub_get)
        r("PUT", "/api/v5/mqtt/auto_subscribe", self.h_auto_sub_put)
        r("GET", "/api/v5/plugins", self.h_plugins)
        r("PUT", "/api/v5/plugins/{name}/{action}", self.h_plugin_action)
        r("DELETE", "/api/v5/plugins/{name}", self.h_plugin_delete)
        r("GET", "/api/v5/monitor", self.h_monitor)
        r("GET", "/api/v5/monitor_current", self.h_monitor_current)
        # listeners (emqx_mgmt_api_listeners): list + stop by id
        r("GET", "/api/v5/listeners", self.h_listeners)
        r("DELETE", "/api/v5/listeners/{lid}", self.h_listener_stop)
        # gateways (emqx_gateway_api / _api_clients): list, detail,
        # per-gateway clients + kick, unload
        r("GET", "/api/v5/gateways", self.h_gateways)
        r("GET", "/api/v5/gateways/{name}", self.h_gateway)
        r("DELETE", "/api/v5/gateways/{name}", self.h_gateway_unload)
        r("GET", "/api/v5/gateways/{name}/clients",
          self.h_gateway_clients)
        r("DELETE", "/api/v5/gateways/{name}/clients/{clientid}",
          self.h_gateway_kick)

    @staticmethod
    def _page(items: list, query: dict) -> dict:
        page = int(query.get("page", 1))
        limit = int(query.get("limit", 100))
        return {
            "data": items[(page - 1) * limit: page * limit],
            "meta": {"page": page, "limit": limit, "count": len(items)},
        }

    def h_status(self, query, body):
        return {"node": self.app.broker.node, "status": "running",
                "uptime": int(self.app.sys.uptime_s()),
                "version": __import__(
                    "emqx_tpu.observe.sys", fromlist=["VERSION"]).VERSION}

    def h_nodes(self, query, body):
        me = {"node": self.app.broker.node, "status": "running",
              "role": getattr(self.cluster, "role", "core")}
        if self.cluster is None:
            return [me]
        return [me] + [
            {"node": n, "status": "running" if m.get("alive")
             else "stopped", "role": m.get("role", "core")}
            for n, m in self.cluster.members.items()
        ]

    def _cluster_conf(self):
        if self.cluster is None:
            raise ApiError(503, "NO_CLUSTER",
                           "node is not part of a cluster")
        return self.cluster.conf

    def h_cluster_rpc_status(self, query, body):
        return {"data": self._cluster_conf().cluster_status()}

    def h_cluster_rpc_skip(self, query, body):
        return {"tnx_id": self._cluster_conf().skip_failed_commit()}

    def h_cluster_rpc_ff(self, query, body):
        body = body or {}
        try:
            tnx_id = int(body["tnx_id"])
        except (KeyError, ValueError, TypeError) as e:
            raise ApiError(400, "BAD_REQUEST", "tnx_id required") from e
        return {"tnx_id":
                self._cluster_conf().fast_forward_to_commit(tnx_id)}

    def h_metrics(self, query, body):
        return self.app.metrics.all()

    def h_stats(self, query, body):
        self.app.stats.tick()
        return self.app.stats.all()

    def h_prometheus(self, query, body):
        # ?format=openmetrics opts into trace-id exemplars (illegal in
        # the default text 0.0.4 exposition — a classic parser would
        # fail the whole scrape on them)
        om = query.get("format") == "openmetrics"
        return 200, self.app.prometheus(openmetrics=om)

    def h_alarms(self, query, body):
        which = ("activated" if query.get("activated") in ("true", "1")
                 else "all")
        return [
            {"name": a.name, "message": a.message, "details": a.details,
             "activate_at": a.activate_at, "deactivate_at": a.deactivate_at}
            for a in self.app.alarms.get_alarms(which)
        ]

    def _client_info(self, cid: str, ch) -> dict:
        ci = ch.conninfo
        return {
            "clientid": cid, "username": ci.username,
            "peername": ci.peername, "proto_ver": ci.proto_ver,
            "keepalive": ci.keepalive, "clean_start": ci.clean_start,
            "connected": ch.conn_state == "connected",
            "connected_at": ci.connected_at,
            "subscriptions_cnt": len(ch.session.subscriptions)
            if ch.session else 0,
        }

    def h_clients(self, query, body):
        like = query.get("like_clientid")
        items = [
            self._client_info(cid, ch)
            for cid, ch in sorted(self.app.cm.all_channels())
            if like is None or like in cid
        ]
        return self._page(items, query)

    def h_client(self, query, body, clientid):
        ch = self.app.cm.lookup_channel(clientid)
        if ch is None:
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        return self._client_info(clientid, ch)

    def h_kick(self, query, body, clientid):
        if not self.app.cm.kick(clientid):
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        return None

    def h_subscriptions(self, query, body):
        items = [
            {"clientid": sid, "topic": t, "qos": opts.qos, "nl": opts.nl,
             "rap": opts.rap, "rh": opts.rh}
            for (sid, t), opts in sorted(self.app.broker.suboption.items())
        ]
        return self._page(items, query)

    def h_topics(self, query, body):
        router = self.app.broker.router
        items = [
            {"topic": t, "node": str(r.dest)}
            for t in sorted(router.topics())
            for r in router.lookup_routes(t)
        ]
        return self._page(items, query)

    def h_publish(self, query, body):
        body = body or {}
        topic = body.get("topic")
        if not topic:
            raise ApiError(400, "BAD_REQUEST", "topic required")
        payload = body.get("payload", "")
        if body.get("payload_encoding") == "base64":
            payload = base64.b64decode(payload)
        elif isinstance(payload, str):
            payload = payload.encode()
        msg = Message(
            topic=topic, payload=payload, qos=int(body.get("qos", 0)),
            from_="mgmt_api",
            flags={"retain": bool(body.get("retain", False))},
            headers={"properties": body.get("properties") or {}},
        )
        self.app.cm.dispatch(self.app.broker.publish(msg))
        return {"id": msg.id}

    def h_banned_list(self, query, body):
        return self._page([
            {"as": e.kind, "who": e.value, "by": e.by, "reason": e.reason,
             "at": e.at, "until": e.until}
            for e in self.app.access.banned.all()
        ], query)

    def h_banned_create(self, query, body):
        body = body or {}
        try:
            entry = self.app.access.banned.create(
                body.get("as", "clientid"), body["who"],
                by=body.get("by", "mgmt_api"),
                reason=body.get("reason", ""),
                duration_s=body.get("seconds"))
        except (KeyError, ValueError) as e:
            raise ApiError(400, "BAD_REQUEST", str(e)) from e
        return 201, {"as": entry.kind, "who": entry.value}

    def h_banned_delete(self, query, body, kind, value):
        if not self.app.access.banned.delete(kind, value):
            raise ApiError(404, "NOT_FOUND")
        return None

    def _conf(self):
        conf = getattr(self.app, "config", None)
        if conf is None:
            raise ApiError(503, "NO_CONFIG",
                           "app not booted from a Config")
        return conf

    def h_config_get(self, query, body):
        return {"value": self._conf().get(query.get("path", ""))}

    def h_config_put(self, query, body):
        from emqx_tpu.cluster.conf import (ClusterConfError,
                                           ClusterConfRejected)

        body = body or {}
        try:
            value = self._conf().put(body["path"], body["value"])
        except KeyError as e:
            raise ApiError(400, "BAD_REQUEST", "path/value required") from e
        except ClusterConfRejected as e:
            # validation failure on the coordinator — permanently bad
            # value, same 400 a non-clustered node would return
            raise ApiError(400, "BAD_VALUE", str(e)) from e
        except ClusterConfError as e:
            # transient cluster condition (no core reachable, coordinator
            # catching up, local apply stalled) — retryable, not a bad
            # request
            raise ApiError(503, "CLUSTER_UNAVAILABLE", str(e)) from e
        except Exception as e:
            raise ApiError(400, "BAD_VALUE", str(e)) from e
        return {"value": value}

    def _rule_info(self, rule) -> dict:
        return {"id": rule.id, "sql": rule.sql, "enable": rule.enabled,
                "description": rule.description, "actions": rule.actions,
                "metrics": self.app.rules.metrics.get_counters(rule.id)}

    def h_rules_list(self, query, body):
        return self._page([self._rule_info(r)
                           for r in self.app.rules.list_rules()], query)

    def h_rules_create(self, query, body):
        body = body or {}
        try:
            rule = self.app.rules.create_rule(
                body.get("id") or f"rule_{int(time.time() * 1000):x}",
                body["sql"], body.get("actions", []),
                enabled=body.get("enable", True),
                description=body.get("description", ""))
        except KeyError as e:
            raise ApiError(400, "BAD_REQUEST", "sql required") from e
        except ValueError as e:
            raise ApiError(400, "BAD_SQL", str(e)) from e
        return 201, self._rule_info(rule)

    def h_rule_get(self, query, body, id):
        rule = self.app.rules.get_rule(id)
        if rule is None:
            raise ApiError(404, "RULE_NOT_FOUND")
        return self._rule_info(rule)

    def h_rule_put(self, query, body, id):
        if self.app.rules.get_rule(id) is None:
            raise ApiError(404, "RULE_NOT_FOUND")
        body = body or {}
        self.app.rules.delete_rule(id)
        try:
            rule = self.app.rules.create_rule(
                id, body["sql"], body.get("actions", []),
                enabled=body.get("enable", True),
                description=body.get("description", ""))
        except ValueError as e:
            raise ApiError(400, "BAD_SQL", str(e)) from e
        return self._rule_info(rule)

    def h_rule_delete(self, query, body, id):
        if not self.app.rules.delete_rule(id):
            raise ApiError(404, "RULE_NOT_FOUND")
        return None

    def h_rule_test(self, query, body):
        body = body or {}
        try:
            res = self.app.rules.test_sql(body["sql"],
                                          body.get("context", {}))
        except KeyError as e:
            raise ApiError(400, "BAD_REQUEST", "sql required") from e
        except ValueError as e:
            raise ApiError(400, "BAD_SQL", str(e)) from e
        if res is None:
            raise ApiError(412, "SQL_NO_MATCH", "WHERE filtered out")
        return res

    def h_retained(self, query, body):
        items = []
        for t in sorted(self.app.retainer.topics()):
            for m in self.app.retainer.match(t):
                items.append({
                    "topic": m.topic, "qos": m.qos,
                    "payload": base64.b64encode(m.payload).decode(),
                    "from_clientid": m.from_, "publish_at": m.timestamp})
        return self._page(items, query)

    def h_retained_delete(self, query, body, topic):
        if not self.app.retainer.delete(topic):
            raise ApiError(404, "NOT_FOUND")
        return None

    def h_api_keys(self, query, body):
        return [{"api_key": k} for k in self.api_keys.list()]

    def h_api_key_create(self, query, body):
        body = body or {}
        key, secret = self.api_keys.create(body.get("api_key"),
                                           body.get("api_secret"))
        return 201, {"api_key": key, "api_secret": secret}

    # -- trace / slow subs (emqx_mgmt_api_trace, emqx_slow_subs_api) ---------

    def h_trace_list(self, query, body):
        return self.app.trace.list()

    def h_trace_create(self, query, body):
        body = body or {}
        try:
            self.app.trace.start(
                body["name"], body.get("type", "clientid"),
                body.get(body.get("type", "clientid"), body.get("value", "")),
                duration_s=body.get("duration"),
                # "punt" (default) = full-fidelity slow-path capture;
                # "native" = stay on the fast path, log sampled span
                # timelines instead (the production-safe mode)
                mode=body.get("mode", "punt"))
        except (KeyError, ValueError) as e:
            raise ApiError(400, "BAD_REQUEST", str(e)) from None
        return 201, {"name": body["name"]}

    def h_tracing_spans(self, query, body):
        """Recent assembled span timelines from the native tracing
        plane (empty when no native server is attached)."""
        fn = getattr(self.app, "native_spans_fn", None)
        if fn is None:
            return []
        try:
            limit = int(query.get("limit", 32))
        except (TypeError, ValueError):
            limit = 32
        return fn(max(1, limit))   # a negative slice would invert
        #                            the newest-N semantics

    def h_kernel_stats(self, query, body):
        """Trie-health + device-counter snapshot from the kernel-plane
        fold; 404 when the app runs without a device router (or with
        EMQX_TPU_KERNEL_TELEMETRY=0)."""
        dm = getattr(self.app, "device_metrics", None)
        if dm is None:
            raise ApiError(404, "NOT_FOUND",
                           "kernel telemetry not attached")
        return dm.snapshot()

    def h_tracing_ledger(self, query, body):
        """Degradation-ledger totals + the bounded structured event
        ring (ring-full punts, trunk punts, sheds, device failovers,
        store degradations)."""
        led = getattr(self.app, "ledger", None)
        if led is None:
            return {"totals": {}, "events": []}
        try:
            limit = int(query.get("limit", 64))
        except (TypeError, ValueError):
            limit = 64
        return {"totals": led.totals(),
                "events": led.recent(max(1, limit))}

    def h_trace_delete(self, query, body, name):
        if not self.app.trace.delete(name):
            raise ApiError(404, "NOT_FOUND")
        return 204, None

    def h_trace_stop(self, query, body, name):
        if not self.app.trace.stop(name):
            raise ApiError(404, "NOT_FOUND")
        return {"name": name, "status": "stopped"}

    def h_trace_log(self, query, body, name):
        if name not in self.app.trace.traces:
            raise ApiError(404, "NOT_FOUND")
        return 200, "\n".join(self.app.trace.log_lines(name))

    def h_slow_subs(self, query, body):
        return self._page([
            {"clientid": e.clientid, "topic": e.topic,
             "timespan": e.latency_ms, "last_update_time": e.last_update}
            for e in self.app.slow_subs.top()
        ], query)

    def h_slow_subs_clear(self, query, body):
        self.app.slow_subs.clear()
        return 204, None

    # -- mqtt modules (emqx_mgmt_api_topic_metrics / _rewrite / _auto_sub) ---

    def h_topic_metrics(self, query, body):
        return self.app.topic_metrics.all()

    def h_topic_metrics_add(self, query, body):
        try:
            if not self.app.topic_metrics.register((body or {})["topic"]):
                raise ApiError(400, "BAD_REQUEST", "already registered")
        except (KeyError, ValueError) as e:
            raise ApiError(400, "BAD_REQUEST", str(e)) from None
        return 201, {"topic": body["topic"]}

    def h_topic_metrics_del(self, query, body, topic):
        if not self.app.topic_metrics.deregister(topic):
            raise ApiError(404, "NOT_FOUND")
        return 204, None

    def h_rewrite_get(self, query, body):
        return self.app.rewrite.list()

    def h_rewrite_put(self, query, body):
        # validate the full replacement set first — a bad body must leave
        # the existing rules untouched
        from emqx_tpu.services.rewrite import TopicRewrite

        staged = TopicRewrite()
        import re as _re
        try:
            for spec in body or []:
                staged.add_rule(
                    action=spec.get("action", "all"),
                    source_topic=spec["source_topic"],
                    re=spec["re"], dest_topic=spec["dest_topic"])
        except (KeyError, ValueError, TypeError, _re.error) as e:
            raise ApiError(400, "BAD_REQUEST", str(e)) from None
        self.app.rewrite.replace(staged.pub_rules, staged.sub_rules)
        return self.app.rewrite.list()

    def h_auto_sub_get(self, query, body):
        return self.app.auto_subscribe.topics

    def h_auto_sub_put(self, query, body):
        from emqx_tpu.services.auto_subscribe import AutoSubscribe

        staged = AutoSubscribe(self.app)     # validate before swapping in
        try:
            for spec in body or []:
                staged.add(
                    topic=spec["topic"], qos=int(spec.get("qos", 0)),
                    nl=int(spec.get("nl", 0)), rh=int(spec.get("rh", 0)),
                    rap=int(spec.get("rap", 0)))
        except (KeyError, ValueError, TypeError) as e:
            raise ApiError(400, "BAD_REQUEST", str(e)) from None
        self.app.auto_subscribe.topics = staged.topics
        return self.app.auto_subscribe.topics

    # -- plugins / monitor (emqx_mgmt_api_plugins, emqx_dashboard_monitor) --

    def h_plugins(self, query, body):
        self.app.plugins.scan()
        return self.app.plugins.list()

    def h_plugin_action(self, query, body, name, action):
        pm = self.app.plugins
        pm.scan()
        if name not in pm.plugins:
            raise ApiError(404, "NOT_FOUND", f"plugin {name} not installed")
        try:
            if action == "start":
                pm.ensure_enabled(name)
                pm.ensure_started(name)
                if pm.plugins[name].error:
                    raise ApiError(400, "BAD_PLUGIN",
                                   pm.plugins[name].error)
            elif action == "stop":
                pm.ensure_stopped(name)
                pm.ensure_disabled(name)
            elif action == "restart":
                pm.restart(name)
                if pm.plugins[name].error:
                    raise ApiError(400, "BAD_PLUGIN",
                                   pm.plugins[name].error)
            else:
                raise ApiError(400, "BAD_REQUEST",
                               f"unknown action {action}")
            return pm.describe(name)
        except (ValueError, KeyError) as e:
            raise ApiError(404, "NOT_FOUND", str(e)) from None

    def h_plugin_delete(self, query, body, name):
        if not self.app.plugins.ensure_uninstalled(name):
            raise ApiError(404, "NOT_FOUND")
        return 204, None

    def h_monitor(self, query, body):
        latest = query.get("latest")
        try:
            window = float(latest) if latest else None
        except ValueError:
            raise ApiError(400, "BAD_REQUEST",
                           f"latest must be numeric: {latest!r}") from None
        return self.app.monitor.history(window)

    def h_monitor_current(self, query, body):
        return self.app.monitor.current()

    # -- listeners (emqx_mgmt_api_listeners) --------------------------------

    def h_listeners(self, query, body):
        sup = getattr(self.app, "listeners", None)
        return sup.info() if sup is not None else []

    def h_listener_stop(self, query, body, lid):
        import asyncio

        sup = getattr(self.app, "listeners", None)
        server = sup.find(lid) if sup is not None else None
        if server is None:
            raise ApiError(404, "LISTENER_NOT_FOUND")
        # the listener's sockets live on the broker loop; this handler
        # runs on the REST thread — stop must execute over there
        srv = getattr(server, "_server", None)
        loop = srv.get_loop() if srv is not None else None
        if loop is not None and loop.is_running():
            asyncio.run_coroutine_threadsafe(
                sup.stop(lid), loop).result(timeout=10)
        else:
            raise ApiError(409, "LISTENER_NOT_RUNNING")
        return None

    # -- gateways (emqx_gateway_api / emqx_gateway_api_clients) -------------

    def h_gateways(self, query, body):
        return self._page(self.app.gateway.list(), query)

    def h_gateway(self, query, body, name):
        for g in self.app.gateway.list():
            if g["name"] == name:
                return g
        raise ApiError(404, "GATEWAY_NOT_FOUND")

    def h_gateway_unload(self, query, body, name):
        if not self.app.gateway.unload(name):
            raise ApiError(404, "GATEWAY_NOT_FOUND")
        return None

    def h_gateway_clients(self, query, body, name):
        clients = self.app.gateway.clients(name)
        if clients is None:
            raise ApiError(404, "GATEWAY_NOT_FOUND")
        return self._page(clients, query)

    def h_gateway_kick(self, query, body, name, clientid):
        ctx = self.app.gateway.contexts.get(name)
        if ctx is None:
            raise ApiError(404, "GATEWAY_NOT_FOUND")
        if clientid not in ctx.sessions:
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        if not self.app.cm.kick(clientid):
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        return None

    # -- http server --------------------------------------------------------

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _run(self, method: str) -> None:
                parsed = urllib.parse.urlsplit(self.path)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                body = None
                ln = int(self.headers.get("Content-Length") or 0)
                if ln:
                    raw = self.rfile.read(ln)
                    ctype = self.headers.get("Content-Type", "")
                    if "json" in ctype or not ctype:
                        try:
                            body = json.loads(raw)
                        except ValueError:
                            self._reply(400, {"code": "BAD_JSON"})
                            return
                    else:
                        body = raw
                status, result = api.handle(
                    method, parsed.path, query, body,
                    authed=api.check_auth(self.headers))
                self._reply(status, result)

            def _reply(self, status: int, result: Any) -> None:
                if isinstance(result, str):
                    data = result.encode()
                    ctype = ("text/html; charset=utf-8"
                             if isinstance(result, HtmlPage)
                             else "text/plain; version=0.0.4")
                elif result is None:
                    data = b""
                    ctype = "application/json"
                else:
                    # rule_test / trace results can carry bytes (gzip,
                    # payloads); never let a reply crash the handler
                    data = json.dumps(
                        result,
                        default=lambda o: (
                            o.decode("utf-8", "replace")
                            if isinstance(o, (bytes, bytearray))
                            else str(o)),
                    ).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._run("GET")

            def do_POST(self):
                self._run("POST")

            def do_PUT(self):
                self._run("PUT")

            def do_DELETE(self):
                self._run("DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="mgmt-api").start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


# ---------------------------------------------------------------------------
# built-in status page (served at / — the reference's dashboard is a
# separate Vue application; this is the self-contained equivalent
# surface: login + live broker stats over the same REST API)

_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>emqx_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#fafafa}
 h1{font-size:1.2rem} .err{color:#b00}
 .grid{display:grid;grid-template-columns:repeat(auto-fill,minmax(220px,1fr));
       gap:12px;margin-top:1rem}
 .card{background:#fff;border:1px solid #ddd;border-radius:8px;
       padding:12px 16px}
 .card b{display:block;font-size:1.6rem;margin-top:4px}
 .muted{color:#777;font-size:.85rem}
 table{border-collapse:collapse;margin-top:1rem;background:#fff;width:100%}
 td,th{border:1px solid #ddd;padding:6px 10px;font-size:.9rem;
       text-align:left}
 input,button{padding:6px 10px;font-size:1rem}
</style></head><body>
<h1>emqx_tpu &mdash; broker status</h1>
<div id="login">
 <input id="u" placeholder="username" value="admin">
 <input id="p" placeholder="password" type="password" value="public">
 <button onclick="login()">Login</button> <span id="msg" class="err"></span>
</div>
<div id="main" style="display:none">
 <div class="grid" id="cards"></div>
 <table id="clients"><tr><th>client</th><th>connected</th></tr></table>
 <p class="muted">auto-refreshes every 2s &middot;
    <a href="/api-docs.json">API docs</a></p>
</div>
<script>
let tok=null;
// every interpolated value passes through esc(): clientids are
// ATTACKER-CONTROLLED (any connecting client picks one) and raw
// innerHTML interpolation would be stored XSS in the admin session
function esc(v){const d=document.createElement('div');
  d.textContent=String(v??'');return d.innerHTML}
async function login(){
  const r=await fetch('/api/v5/login',{method:'POST',
    headers:{'Content-Type':'application/json'},
    body:JSON.stringify({username:u.value,password:p.value})});
  if(!r.ok){msg.textContent='login failed';return}
  tok=(await r.json()).token;
  document.getElementById('login').style.display='none';
  document.getElementById('main').style.display='';
  tick();setInterval(tick,2000);
}
async function get(p){const r=await fetch(p,
  {headers:{Authorization:'Bearer '+tok}});return r.json()}
function card(k,v){return `<div class=card><span class=muted>${esc(k)}</span>`+
  `<b>${esc(v)}</b></div>`}
async function tick(){
  const [st,stats,mon]=await Promise.all([
    get('/api/v5/status'),get('/api/v5/stats'),
    get('/api/v5/monitor_current')]);
  const cards=document.getElementById('cards');
  cards.innerHTML=
    card('node',st.node??'-')+
    card('uptime s',Math.round(st.uptime??0))+
    card('connections',stats['connections.count']??0)+
    card('subscriptions',stats['subscriptions.count']??0)+
    card('topics',stats['topics.count']??0)+
    card('msgs received',mon['messages.received']??0)+
    card('msgs sent',mon['messages.sent']??0);
  const cl=await get('/api/v5/clients');
  const rows=(cl.data||[]).slice(0,50).map(c=>
    `<tr><td>${esc(c.clientid)}</td><td>${esc(c.connected_at)}</td></tr>`);
  document.getElementById('clients').innerHTML=
    '<tr><th>client</th><th>connected</th></tr>'+rows.join('');
}
</script></body></html>
"""
