"""Operator CLI — parity with ``emqx_ctl`` / ``emqx_mgmt_cli.erl``.

Verbs drive the running broker through the management REST API (the
reference's ctl RPCs into the live node map to HTTP here):

    emqx_ctl status | broker | cluster
    emqx_ctl clients list | show <id> | kick <id>
    emqx_ctl subscriptions list | topics list
    emqx_ctl metrics | stats
    emqx_ctl publish <topic> <payload> [--qos N] [--retain]
    emqx_ctl banned list | add <kind> <who> | del <kind> <who>
    emqx_ctl rules list | show <id> | delete <id>
    emqx_ctl retainer topics | clean <topic>
    emqx_ctl gateway list | show <name> | clients <name> |
             kick <name> <clientid> | unload <name>

Auth via --user/--pass (dashboard login) or EMQX_API_KEY/EMQX_API_SECRET
(basic auth).
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Any, Optional


class CtlClient:
    def __init__(self, base: str = "http://127.0.0.1:18083",
                 username: str = "admin", password: str = "public",
                 api_key: Optional[str] = None,
                 api_secret: Optional[str] = None) -> None:
        self.base = base.rstrip("/")
        self.api_key = api_key or os.environ.get("EMQX_API_KEY")
        self.api_secret = api_secret or os.environ.get("EMQX_API_SECRET")
        self.username, self.password = username, password
        self._token: Optional[str] = None

    def _auth_header(self) -> str:
        if self.api_key:
            raw = f"{self.api_key}:{self.api_secret or ''}".encode()
            return "Basic " + base64.b64encode(raw).decode()
        if self._token is None:
            resp = self._raw("POST", "/api/v5/login",
                             {"username": self.username,
                              "password": self.password}, auth=False)
            self._token = resp["token"]
        return f"Bearer {self._token}"

    def _raw(self, method: str, path: str, body: Any = None,
             auth: bool = True) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base + path, data=data,
                                     method=method)
        req.add_header("Content-Type", "application/json")
        if auth:
            req.add_header("Authorization", self._auth_header())
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                err = json.loads(raw)
            except ValueError:
                err = {"code": str(e.code)}
            raise SystemExit(
                f"error {e.code}: {err.get('code')} "
                f"{err.get('message', '')}".strip()) from e
        if not raw:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return raw.decode()

    def request(self, method: str, path: str, body: Any = None) -> Any:
        return self._raw(method, path, body)


def _print(obj: Any) -> None:
    if isinstance(obj, str):
        print(obj, end="" if obj.endswith("\n") else "\n")
    else:
        print(json.dumps(obj, indent=2, default=str))


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="emqx_ctl",
                                 description="emqx_tpu control CLI")
    ap.add_argument("--url", default=os.environ.get(
        "EMQX_API_URL", "http://127.0.0.1:18083"))
    ap.add_argument("--user", default="admin")
    ap.add_argument("--password", default="public")
    sub = ap.add_subparsers(dest="verb", required=True)

    for simple in ("status", "metrics", "stats", "broker"):
        sub.add_parser(simple)
    sub.add_parser("cluster")

    p = sub.add_parser("clients")
    p.add_argument("action", choices=["list", "show", "kick"])
    p.add_argument("clientid", nargs="?")

    p = sub.add_parser("subscriptions")
    p.add_argument("action", choices=["list"])
    p = sub.add_parser("topics")
    p.add_argument("action", choices=["list"])

    p = sub.add_parser("publish")
    p.add_argument("topic")
    p.add_argument("payload")
    p.add_argument("--qos", type=int, default=0)
    p.add_argument("--retain", action="store_true")

    p = sub.add_parser("banned")
    p.add_argument("action", choices=["list", "add", "del"])
    p.add_argument("kind", nargs="?",
                   choices=["clientid", "username", "peerhost"])
    p.add_argument("who", nargs="?")
    p.add_argument("--seconds", type=float, default=None)

    p = sub.add_parser("rules")
    p.add_argument("action", choices=["list", "show", "delete"])
    p.add_argument("id", nargs="?")

    p = sub.add_parser("retainer")
    p.add_argument("action", choices=["topics", "clean"])
    p.add_argument("topic", nargs="?")

    # emqx_gateway_cli: gateway list | show <name> | clients <name> |
    # kick <name> <clientid> | unload <name>
    p = sub.add_parser("gateway")
    p.add_argument("action",
                   choices=["list", "show", "clients", "kick", "unload"])
    p.add_argument("name", nargs="?")
    p.add_argument("clientid", nargs="?")

    args = ap.parse_args(argv)
    ctl = CtlClient(args.url, args.user, args.password)

    if args.verb in ("status", "broker"):
        _print(ctl.request("GET", "/api/v5/status"))
    elif args.verb == "cluster":
        _print(ctl.request("GET", "/api/v5/nodes"))
    elif args.verb == "metrics":
        _print(ctl.request("GET", "/api/v5/metrics"))
    elif args.verb == "stats":
        _print(ctl.request("GET", "/api/v5/stats"))
    elif args.verb == "clients":
        if args.action == "list":
            _print(ctl.request("GET", "/api/v5/clients"))
        elif args.action == "show":
            _print(ctl.request("GET", f"/api/v5/clients/{args.clientid}"))
        else:
            ctl.request("DELETE", f"/api/v5/clients/{args.clientid}")
            print(f"kicked {args.clientid}")
    elif args.verb == "subscriptions":
        _print(ctl.request("GET", "/api/v5/subscriptions"))
    elif args.verb == "topics":
        _print(ctl.request("GET", "/api/v5/topics"))
    elif args.verb == "publish":
        _print(ctl.request("POST", "/api/v5/publish", {
            "topic": args.topic, "payload": args.payload,
            "qos": args.qos, "retain": args.retain}))
    elif args.verb == "banned":
        if args.action == "list":
            _print(ctl.request("GET", "/api/v5/banned"))
        elif args.action == "add":
            _print(ctl.request("POST", "/api/v5/banned", {
                "as": args.kind, "who": args.who,
                "seconds": args.seconds}))
        else:
            ctl.request("DELETE",
                        f"/api/v5/banned/{args.kind}/{args.who}")
            print(f"unbanned {args.kind}={args.who}")
    elif args.verb == "rules":
        if args.action == "list":
            _print(ctl.request("GET", "/api/v5/rules"))
        elif args.action == "show":
            _print(ctl.request("GET", f"/api/v5/rules/{args.id}"))
        else:
            ctl.request("DELETE", f"/api/v5/rules/{args.id}")
            print(f"deleted rule {args.id}")
    elif args.verb == "retainer":
        if args.action == "topics":
            _print(ctl.request("GET", "/api/v5/retainer/messages"))
        else:
            ctl.request("DELETE",
                        f"/api/v5/retainer/message/{args.topic}")
            print(f"cleaned {args.topic}")
    elif args.verb == "gateway":
        if args.action == "list":
            _print(ctl.request("GET", "/api/v5/gateways"))
        elif args.action == "show":
            _print(ctl.request("GET", f"/api/v5/gateways/{args.name}"))
        elif args.action == "clients":
            _print(ctl.request(
                "GET", f"/api/v5/gateways/{args.name}/clients"))
        elif args.action == "kick":
            ctl.request("DELETE", f"/api/v5/gateways/{args.name}"
                                  f"/clients/{args.clientid}")
            print(f"kicked {args.clientid} from {args.name}")
        else:
            ctl.request("DELETE", f"/api/v5/gateways/{args.name}")
            print(f"unloaded {args.name}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
