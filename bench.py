"""Route-match throughput benchmark (the BASELINE.json north-star metric).

Measures the flagship device step — batched wildcard match + compact +
subscriber-shard fan-out — against a connected-vehicle-style filter set
(BASELINE configs 2/3: ~1M subscriptions, ~10% single-level '+' wildcards,
7-level topic tree). The reference equivalent is `emqx_router:match_routes/1`
(per-message Erlang trie walk over ETS, apps/emqx/src/emqx_router.erl:141-153,
driven in-VM by apps/emqx/src/emqx_broker_bench.erl).

Prints ONE JSON line:
  {"metric": "route-matches/sec", "value": N, "unit": "topics/sec",
   "vs_baseline": X}

vs_baseline: ratio against the reference's own headline sustained cluster
throughput of 1M msg/s (reference README.md:16) — every routed message
needs exactly one match_routes call, so topics-matched/sec is directly
comparable. No per-config BEAM numbers are published (BASELINE.md).

Latency is measured with synchronous dispatch (block every step);
throughput with the production discipline — a bounded in-flight window of
batches (SURVEY.md §2.5-6 pipeline parallelism: batch assembly overlaps
device execution, as the reference overlaps socket reads with dispatch via
{active,N}) — every output is still blocked on before it leaves the window.

Env knobs: BENCH_FILTERS (default 1_000_000), BENCH_BATCH (16384),
BENCH_ITERS (100), BENCH_SHARDS (8192 subscriber fan-out shards),
BENCH_WINDOW (8 in-flight batches), BENCH_LAT_ITERS (30 sync latency samples).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_filters(n: int, rng: np.random.Generator) -> list[str]:
    """Vehicle-fleet topic tree, 7 levels deep, ~10% '+' wildcards,
    a few percent '#' — the BASELINE config 2/3 shape."""
    n_vehicles = max(1000, n // 2)
    filters = []
    kinds = rng.random(n)
    vids = rng.integers(0, n_vehicles, n)
    fleets = rng.integers(0, 512, n)
    metrics = rng.integers(0, 16, n)
    parts = rng.integers(0, 8, n)
    for i in range(n):
        v, fl, m, p = vids[i], fleets[i], metrics[i], parts[i]
        k = kinds[i]
        if k < 0.80:      # exact 7-level
            f = f"fleet/f{fl}/vehicle/v{v}/part/p{p}/m{m}"
        elif k < 0.90:    # single-level '+'
            f = f"fleet/f{fl}/vehicle/+/part/p{p}/m{m}"
        elif k < 0.95:
            f = f"fleet/f{fl}/vehicle/v{v}/part/+/m{m}"
        elif k < 0.98:    # multi-level '#'
            f = f"fleet/f{fl}/vehicle/v{v}/#"
        else:
            f = f"fleet/+/vehicle/v{v}/part/p{p}/#"
        filters.append(f)
    return filters


def main() -> None:
    n_filters = int(os.environ.get("BENCH_FILTERS", 1_000_000))
    B = int(os.environ.get("BENCH_BATCH", 16384))
    iters = int(os.environ.get("BENCH_ITERS", 100))
    n_shards = int(os.environ.get("BENCH_SHARDS", 8192))
    window_n = int(os.environ.get("BENCH_WINDOW", 8))

    import jax

    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.router.index import TrieIndex

    rng = np.random.default_rng(42)
    t0 = time.time()
    filters = build_filters(n_filters, rng)
    log(f"built {len(filters)} filters in {time.time()-t0:.1f}s")

    t0 = time.time()
    index = TrieIndex(max_levels=8)
    model = RouterModel(index, n_sub_slots=n_shards, K=32, M=128)
    index.load(filters)
    # one subscriber shard per subscription (slot = hash of i)
    slot_of = rng.integers(0, n_shards, len(index.filters))
    for fid in range(len(index.filters)):
        if index.filters[fid] is not None:
            model._subs.setdefault(fid, set()).add(int(slot_of[fid]))
    log(f"loaded index in {time.time()-t0:.1f}s "
        f"({len(index.filters)} distinct filters)")

    t0 = time.time()
    model.refresh()
    arrays = index.arrays
    log(f"rebuilt device arrays in {time.time()-t0:.1f}s: "
        f"nodes={arrays.n_nodes} ht={arrays.ht_parent.shape[0]} "
        f"bitmap={int(model._bitmaps_dev.nbytes) >> 20}MiB "
        f"device={jax.devices()[0]}")

    # pre-tokenized topic batches (the C++ ingest host's job in production).
    # Publishers publish into the subscribed tree (emqx_broker_bench shape):
    # instantiate a random subscribed filter's wildcards with concrete words.
    n_vehicles = max(1000, n_filters // 2)
    n_batches = 8
    t0 = time.time()
    live = [f for f in index.filters if f is not None]
    batches = []
    for _ in range(n_batches):
        picks = rng.integers(0, len(live), B)
        v = rng.integers(0, n_vehicles, B)
        p = rng.integers(0, 8, B)
        m = rng.integers(0, 16, B)
        fl = rng.integers(0, 512, B)
        topics = []
        for i in range(B):
            ws = live[picks[i]].split("/")
            out = []
            for j, w in enumerate(ws):
                if w == "+":
                    out.append(
                        f"v{v[i]}" if j == 3 else f"p{p[i]}" if j == 5 else f"f{fl[i]}"
                    )
                elif w == "#":
                    out.extend([f"part/p{p[i]}", f"m{m[i]}"][: 7 - j])
                    break
                else:
                    out.append(w)
            topics.append("/".join(out))
        tok, lens, sysf, too_long = index.tokenize(topics)
        assert not too_long
        batches.append(
            tuple(jax.device_put(x) for x in (tok, lens, sysf))
        )
    log(f"tokenized {n_batches}x{B} topics in {time.time()-t0:.1f}s")

    step = model._step
    trie_dev, bm_dev = model._trie_dev, model._bitmaps_dev

    # warmup / compile
    t0 = time.time()
    out = step(trie_dev, bm_dev, *batches[0])
    jax.block_until_ready(out)
    log(f"compile+first step {time.time()-t0:.1f}s")

    # synchronous per-step latency (the p99 a single publish batch sees);
    # sample count capped (each sync step round-trips the tunnel)
    lat_iters = min(iters, int(os.environ.get("BENCH_LAT_ITERS", 30)))
    lat = []
    for i in range(lat_iters):
        t0 = time.time()
        out = step(trie_dev, bm_dev, *batches[i % n_batches])
        jax.block_until_ready(out)
        lat.append(time.time() - t0)

    # steady-state throughput: bounded in-flight window; every output is
    # blocked on before leaving the window (nothing unverified in flight)
    t_start = time.time()
    window = []
    last = None
    for i in range(iters):
        window.append(step(trie_dev, bm_dev, *batches[i % n_batches]))
        if len(window) >= window_n:
            last = window.pop(0)
            jax.block_until_ready(last)
    for o in window:
        last = o
        jax.block_until_ready(o)
    wall = time.time() - t_start
    topics_per_sec = iters * B / wall

    counts = np.asarray(last[2])
    lat_ms = np.array(lat) * 1e3
    log(f"matched-subscriber shards/topic: mean={counts.mean():.2f}")
    log(f"sync step latency ms: p50={np.percentile(lat_ms,50):.2f} "
        f"p99={np.percentile(lat_ms,99):.2f} (batch={B})")
    log(f"throughput (window={window_n}): {topics_per_sec:,.0f} topics/sec "
        f"@ {n_filters} subs")

    print(json.dumps({
        "metric": "route-matches/sec",
        "value": round(topics_per_sec),
        "unit": "topics/sec",
        "vs_baseline": round(topics_per_sec / 1_000_000, 3),
    }))


if __name__ == "__main__":
    main()
