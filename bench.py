"""Route-match throughput benchmark (the BASELINE.json north-star metric).

Measures the flagship device step — batched wildcard match + compact +
subscriber-shard fan-out — against a connected-vehicle-style filter set
(BASELINE configs 2/3: ~1M subscriptions, ~10% single-level '+' wildcards,
7-level topic tree). The reference equivalent is `emqx_router:match_routes/1`
(per-message Erlang trie walk over ETS, apps/emqx/src/emqx_router.erl:141-153,
driven in-VM by apps/emqx/src/emqx_broker_bench.erl).

Prints ONE JSON line:
  {"metric": "route-matches/sec", "value": N, "unit": "topics/sec",
   "vs_baseline": X}

vs_baseline: ratio against the reference's own headline sustained cluster
throughput of 1M msg/s (reference README.md:16) — every routed message
needs exactly one match_routes call, so topics-matched/sec is directly
comparable. No per-config BEAM numbers are published (BASELINE.md).

Latency is measured with synchronous dispatch (block every step);
throughput with the production discipline — a bounded in-flight window of
batches (SURVEY.md §2.5-6 pipeline parallelism: batch assembly overlaps
device execution, as the reference overlaps socket reads with dispatch via
{active,N}) — every output is still blocked on before it leaves the window.

Env knobs: BENCH_FILTERS (default 1_000_000), BENCH_BATCH (16384),
BENCH_ITERS (100), BENCH_SHARDS (8192 subscriber fan-out shards),
BENCH_WINDOW (8 in-flight batches), BENCH_LAT_ITERS (30 sync latency samples).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

if os.environ.get("JAX_PLATFORMS"):
    # the axon sitecustomize force-registers the TPU platform via
    # jax.config.update, which beats the env var — honour an explicit
    # JAX_PLATFORMS so the bench can be verified off-TPU
    import jax as _jax
    _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
def _supervise() -> None:
    """A flaky device tunnel can pass any pre-probe and still hang the
    bench mid-upload — which would leave the round without an artifact
    (the r2 failure mode: rc!=0, zero numbers). Re-invoke this script as
    a supervised child with a hard deadline; if the device run hangs or
    dies, run ONCE more pinned to CPU so a measured (slower, clearly
    labelled) artifact always exists."""
    import subprocess as _sp

    # a healthy-tunnel run at defaults takes ~5 min + ~8 min for the
    # 10M config-3 section; 35 min of headroom still leaves room for
    # the CPU retry (which skips the 10M section) inside a 1h budget
    deadline = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", 2100))
    base_env = {**os.environ, "BENCH_SUPERVISED": "1"}
    # cheap tunnel probe FIRST: a wedged tunnel hangs backend init for
    # many minutes (observed: >1h after a killed in-flight process) —
    # without this, the device attempt eats its whole deadline before
    # the CPU fallback even starts
    def cpu_fallback(reason: str) -> None:
        log(f"{reason}; falling back to CPU — numbers below are NOT "
            "TPU numbers")

    device_ok = False
    try:
        # platform must be a real accelerator: bare jax.devices()
        # SILENTLY falls back to CPU where no device is registered,
        # which would pass CPU numbers off as device numbers
        probe = _sp.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "assert d and d[0].platform != 'cpu', d"],
            env=base_env, timeout=float(
                os.environ.get("BENCH_PROBE_TIMEOUT_S", 180)),
            capture_output=True, text=True)
        device_ok = probe.returncode == 0
        if not device_ok:
            tail = (probe.stderr or "").strip().splitlines()[-1:]
            cpu_fallback("device probe failed"
                         + (f" ({tail[0][:200]})" if tail else ""))
    except _sp.TimeoutExpired:
        cpu_fallback("device probe hung (tunnel wedged)")
    if device_ok:
        try:
            rc = _sp.run(
                [sys.executable, "-u", os.path.abspath(__file__)],
                env=base_env, timeout=deadline).returncode
            if rc == 0:
                sys.exit(0)
            cpu_fallback(f"device bench exited rc={rc}")
        except _sp.TimeoutExpired:
            cpu_fallback(f"device bench exceeded {deadline:.0f}s "
                         "(tunnel hang?)")
    cpu_env = {**base_env, "JAX_PLATFORMS": "cpu"}
    # the CPU retry skips the 10M section and needs far less than the
    # device deadline; its own cap keeps the worst case (probe 180s +
    # device 2100s + cpu 900s ≈ 53 min) inside a 1h driver budget
    cpu_deadline = float(os.environ.get("BENCH_CPU_TIMEOUT_S", 900))
    sys.exit(_sp.run([sys.executable, "-u", os.path.abspath(__file__)],
                     env=cpu_env, timeout=cpu_deadline).returncode)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_filters(n: int, rng: np.random.Generator) -> list[str]:
    """Vehicle-fleet topic tree, 7 levels deep, ~10% '+' wildcards,
    a few percent '#' — the BASELINE config 2/3 shape."""
    n_vehicles = max(1000, n // 2)
    filters = []
    kinds = rng.random(n)
    vids = rng.integers(0, n_vehicles, n)
    fleets = rng.integers(0, 512, n)
    metrics = rng.integers(0, 16, n)
    parts = rng.integers(0, 8, n)
    for i in range(n):
        v, fl, m, p = vids[i], fleets[i], metrics[i], parts[i]
        k = kinds[i]
        if k < 0.80:      # exact 7-level
            f = f"fleet/f{fl}/vehicle/v{v}/part/p{p}/m{m}"
        elif k < 0.90:    # single-level '+'
            f = f"fleet/f{fl}/vehicle/+/part/p{p}/m{m}"
        elif k < 0.95:
            f = f"fleet/f{fl}/vehicle/v{v}/part/+/m{m}"
        elif k < 0.98:    # multi-level '#'
            f = f"fleet/f{fl}/vehicle/v{v}/#"
        else:
            f = f"fleet/+/vehicle/v{v}/part/p{p}/#"
        filters.append(f)
    return filters


def main() -> None:
    n_filters = int(os.environ.get("BENCH_FILTERS", 1_000_000))
    B = int(os.environ.get("BENCH_BATCH", 16384))
    iters = int(os.environ.get("BENCH_ITERS", 100))
    n_shards = int(os.environ.get("BENCH_SHARDS", 8192))
    window_n = int(os.environ.get("BENCH_WINDOW", 8))

    import jax

    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.router.index import TrieIndex

    rng = np.random.default_rng(42)
    t0 = time.time()
    filters = build_filters(n_filters, rng)
    log(f"built {len(filters)} filters in {time.time()-t0:.1f}s")

    t0 = time.time()
    index = TrieIndex(max_levels=8)
    model = RouterModel(index, n_sub_slots=n_shards, K=32, M=128)
    index.load(filters)
    # one subscriber shard per subscription (slot = hash of i)
    slot_of = rng.integers(0, n_shards, len(index.filters))
    for fid in range(len(index.filters)):
        if index.filters[fid] is not None:
            model._subs.setdefault(fid, {})[int(slot_of[fid])] = 1
    log(f"loaded index in {time.time()-t0:.1f}s "
        f"({len(index.filters)} distinct filters)")

    t0 = time.time()
    model.refresh()
    arrays = index.arrays
    log(f"rebuilt device arrays in {time.time()-t0:.1f}s: "
        f"nodes={arrays.n_nodes} ht={arrays.ht_parent.shape[0]} "
        f"pool={int(model._pool_dev.nbytes) >> 10}KiB "
        f"rowmap={int(model._rowmap_dev.nbytes) >> 20}MiB "
        f"device={jax.devices()[0]}")

    # pre-tokenized topic batches (the C++ ingest host's job in production).
    # Publishers publish into the subscribed tree (emqx_broker_bench shape):
    # instantiate a random subscribed filter's wildcards with concrete words.
    n_vehicles = max(1000, n_filters // 2)
    n_batches = 8
    t0 = time.time()
    live = [f for f in index.filters if f is not None]
    batches = []
    for _ in range(n_batches):
        picks = rng.integers(0, len(live), B)
        v = rng.integers(0, n_vehicles, B)
        p = rng.integers(0, 8, B)
        m = rng.integers(0, 16, B)
        fl = rng.integers(0, 512, B)
        topics = []
        for i in range(B):
            ws = live[picks[i]].split("/")
            out = []
            for j, w in enumerate(ws):
                if w == "+":
                    out.append(
                        f"v{v[i]}" if j == 3 else f"p{p[i]}" if j == 5 else f"f{fl[i]}"
                    )
                elif w == "#":
                    out.extend([f"part/p{p[i]}", f"m{m[i]}"][: 7 - j])
                    break
                else:
                    out.append(w)
            topics.append("/".join(out))
        tok, lens, sysf, too_long = index.tokenize(topics)
        assert not too_long
        batches.append(
            tuple(jax.device_put(x) for x in (tok, lens, sysf))
        )
    log(f"tokenized {n_batches}x{B} topics in {time.time()-t0:.1f}s")

    step = model._step
    trie_dev = model._trie_dev
    bm_dev = (model._rowmap_dev, model._pool_dev)

    # warmup / compile
    t0 = time.time()
    out = step(trie_dev, *bm_dev, *batches[0])
    jax.block_until_ready(out)
    log(f"compile+first step {time.time()-t0:.1f}s")

    # synchronous per-step latency (the p99 a single publish batch sees);
    # sample count capped (each sync step round-trips the tunnel)
    lat_iters = min(iters, int(os.environ.get("BENCH_LAT_ITERS", 30)))
    lat = []
    for i in range(lat_iters):
        t0 = time.time()
        out = step(trie_dev, *bm_dev, *batches[i % n_batches])
        jax.block_until_ready(out)
        lat.append(time.time() - t0)

    # steady-state throughput: bounded in-flight window; every output is
    # blocked on before leaving the window (nothing unverified in flight)
    t_start = time.time()
    window = []
    last = None
    for i in range(iters):
        window.append(step(trie_dev, *bm_dev, *batches[i % n_batches]))
        if len(window) >= window_n:
            last = window.pop(0)
            jax.block_until_ready(last)
    for o in window:
        last = o
        jax.block_until_ready(o)
    wall = time.time() - t_start
    topics_per_sec = iters * B / wall

    matched_per_topic = np.sum(np.asarray(last[0]) >= 0, axis=1)
    lat_ms = np.array(lat) * 1e3
    log(f"matched filters/topic: mean={matched_per_topic.mean():.2f} "
        f"(dense-pool rows: {len(model._dense_row)})")
    log(f"sync step latency ms: p50={np.percentile(lat_ms,50):.2f} "
        f"p99={np.percentile(lat_ms,99):.2f} (batch={B})")
    log(f"throughput (window={window_n}): {topics_per_sec:,.0f} topics/sec "
        f"@ {n_filters} subs")

    # measured in-repo anchor (VERDICT r2 weak #3): the host-oracle trie
    # (router/trie.py — the emqx_trie.erl semantics the kernel is
    # differentially tested against) walking the SAME topic
    # distribution. Match cost is O(topic depth), not O(filters), so a
    # subset-built trie gives the same per-topic walk cost as 1M.
    from emqx_tpu.router.trie import Trie

    n_oracle = min(len(live),
                   int(os.environ.get("BENCH_ORACLE_FILTERS", 200_000)))
    oracle = Trie()
    for f in live[:n_oracle]:
        oracle.insert(f)
    o_topics = topics[: min(len(topics), 4096)]
    t0 = time.time()
    o_hits = sum(len(oracle.match(t)) for t in o_topics)
    oracle_tps = len(o_topics) / (time.time() - t0)
    vs_oracle = topics_per_sec / oracle_tps
    log(f"host-oracle anchor: {oracle_tps:,.0f} topics/sec "
        f"(python trie walk, {n_oracle} filters, {o_hits} matches) "
        f"→ device = {vs_oracle:,.1f}x the measured host oracle")

    # -- incremental subscribe→routable latency -----------------------------
    # North star: emqx_trie.erl:113-144-style O(topic-depth) insert, NOT a
    # full rebuild (round 1: 106 s at 1M filters). Each sample: subscribe a
    # brand-new filter → scatter-patch HBM → publish a matching topic and
    # block on its fan-out.
    B2 = 64
    def routable(topic: str):
        tok, lens, sysf, _ = index.tokenize([topic] + [""] * (B2 - 1))
        lens[1:] = 0
        sysf[1:] = True
        # numpy args transfer inside the ONE dispatch; separate
        # device_put calls are each a full tunnel round trip
        return step(model._trie_dev, model._rowmap_dev, model._pool_dev, tok, lens, sysf)

    # warm the B2-shaped program + the scatter shapes off the clock
    model.subscribe("fleet/warm/vehicle/w/part/p0/m0", 0)
    model.refresh()
    jax.block_until_ready(routable("fleet/warm/vehicle/w/part/p0/m0"))

    inc = []
    for i in range(30):
        f = f"fleet/fnew/vehicle/z{i}/part/p{i % 8}/m{i % 16}"
        t0 = time.time()
        model.subscribe(f, int(rng.integers(0, n_shards)))
        model.refresh()
        out = routable(f)
        jax.block_until_ready(out)
        inc.append(time.time() - t0)
        assert int(np.sum(np.asarray(out[0])[0] >= 0)) >= 1, \
            "new filter not routable"
    inc_ms = np.array(inc) * 1e3
    rebuilds = model.upload_count
    log(f"incremental subscribe→routable ms: p50={np.percentile(inc_ms,50):.2f} "
        f"p99={np.percentile(inc_ms,99):.2f} (full uploads since load: "
        f"{rebuilds - 1}, patches: {model.patch_count})")
    # the sync number above is dominated by a fixed ~70ms tunnel
    # synchronization cost (measured: block_until_ready on x+1 over 64
    # ints pays the same) — the amortized chain below shows the actual
    # device-side update cost: N dependent subscribe→patch→match chains,
    # one block at the end
    n_chain = 50
    t0 = time.time()
    out = None
    for i in range(n_chain):
        f = f"fleet/fchain/vehicle/c{i}/part/p{i % 8}/m{i % 16}"
        model.subscribe(f, int(rng.integers(0, n_shards)))
        model.refresh()
        out = routable(f)
    jax.block_until_ready(out)
    chain_ms = (time.time() - t0) * 1e3 / n_chain
    log(f"incremental update amortized (pipelined chain of {n_chain}): "
        f"{chain_ms:.2f} ms/update")

    if os.environ.get("BENCH_TENM", "1") != "0":
        bench_ten_million(time.time() - T_START)

    if os.environ.get("BENCH_SHARED", "1") != "0":
        bench_shared_retained()

    if os.environ.get("BENCH_E2E", "1") != "0":
        bench_e2e()

    if os.environ.get("BENCH_NATIVE", "1") != "0":
        bench_host_plane()

    print(json.dumps({
        "metric": "route-matches/sec",
        "value": round(topics_per_sec),
        "unit": "topics/sec",
        # the MEASURED in-repo anchor leads (VERDICT r3 weak #8): the
        # host-oracle python trie walk on the same topic distribution
        "vs_host_oracle": round(vs_oracle, 1),
        # the reference's published headline (1M msg/s sustained,
        # reference README.md:16) — kept as the BASELINE.md-defined
        # denominator for cross-round comparability
        "vs_baseline": round(topics_per_sec / 1_000_000, 3),
        # the host-plane e2e + shared/retained/10M sections (real
        # sockets through the C++ data plane, VERDICT r3 #1/#2)
        **HOST_PLANE_RESULTS,
    }))


HOST_PLANE_RESULTS: dict = {}
T_START = time.time()


def bench_ten_million(elapsed_s: float) -> None:
    """BASELINE config 3 / the north star's 10M-subscription point
    (VERDICT r3 #2: the 10M run must live in a driver artifact, not a
    commit message). Cold build + device upload + windowed kernel
    throughput + sync p99 at 10M mixed-wildcard filters.

    Skipped on the CPU fallback (a 10M CPU kernel run would blow the
    supervisor deadline and prove nothing about the device) and when
    the earlier sections already consumed too much of the budget —
    partial artifacts beat a deadline kill that loses everything."""
    import jax

    if jax.devices()[0].platform == "cpu":
        log("10M section: skipped on CPU fallback")
        return
    cutoff = float(os.environ.get("BENCH_TENM_CUTOFF_S", 700))
    if elapsed_s > cutoff:
        log(f"10M section: skipped, {elapsed_s:.0f}s already elapsed "
            f"(cutoff {cutoff:.0f}s)")
        return

    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.router.index import TrieIndex

    n = int(os.environ.get("BENCH_TENM_FILTERS", 10_000_000))
    B = int(os.environ.get("BENCH_BATCH", 16384))
    iters = int(os.environ.get("BENCH_TENM_ITERS", 30))
    n_shards = int(os.environ.get("BENCH_SHARDS", 8192))
    rng = np.random.default_rng(3)

    t0 = time.time()
    filters = build_filters(n, rng)
    index = TrieIndex(max_levels=8)
    model = RouterModel(index, n_sub_slots=n_shards, K=32, M=128)
    index.load(filters)
    slot_of = rng.integers(0, n_shards, len(index.filters))
    for fid in range(len(index.filters)):
        if index.filters[fid] is not None:
            model._subs.setdefault(fid, {})[int(slot_of[fid])] = 1
    model.refresh()
    build_s = time.time() - t0
    import jax.tree_util as jtu
    hbm_bytes = (int(model._pool_dev.nbytes) + int(model._rowmap_dev.nbytes)
                 + sum(int(x.nbytes)
                       for x in jtu.tree_leaves(model._trie_dev)))
    log(f"10M: built+loaded+uploaded {len(index.filters)} filters in "
        f"{build_s:.0f}s, device bytes={hbm_bytes / (1 << 30):.2f} GiB")

    live = [f for f in index.filters if f is not None]
    picks = rng.integers(0, len(live), B)
    topics = []
    for i in range(B):
        ws = live[int(picks[i])].split("/")
        out = []
        for j, w in enumerate(ws):
            if w == "+":
                out.append("w")
            elif w == "#":
                out.extend(["part/p0", "m0"][: 7 - j])
                break
            else:
                out.append(w)
        topics.append("/".join(out))
    tok, lens, sysf, too_long = index.tokenize(topics)
    batch = tuple(jax.device_put(x) for x in (tok, lens, sysf))

    step = model._step
    t0 = time.time()
    out = step(model._trie_dev, model._rowmap_dev, model._pool_dev, *batch)
    jax.block_until_ready(out)
    log(f"10M: compile+first step {time.time() - t0:.1f}s")

    lat = []
    for _ in range(5):
        t0 = time.time()
        jax.block_until_ready(
            step(model._trie_dev, model._rowmap_dev, model._pool_dev,
                 *batch))
        lat.append(time.time() - t0)
    window_n = int(os.environ.get("BENCH_WINDOW", 8))
    t0 = time.time()
    window = []
    for i in range(iters):
        window.append(
            step(model._trie_dev, model._rowmap_dev, model._pool_dev,
                 *batch))
        if len(window) >= window_n:
            jax.block_until_ready(window.pop(0))
    for o in window:
        jax.block_until_ready(o)
    wall = time.time() - t0
    tps = iters * B / wall
    p99 = float(np.percentile(np.array(lat) * 1e3, 99))
    log(f"10M: {tps:,.0f} topics/sec (window={window_n}), sync p99 "
        f"{p99:.1f}ms @ {n} subs")
    HOST_PLANE_RESULTS.update({
        "tenm_build_s": round(build_s, 1),
        "tenm_device_gib": round(hbm_bytes / (1 << 30), 2),
        "tenm_topics_per_sec": round(tps),
        "tenm_sync_p99_ms": round(p99, 1),
    })


def bench_host_plane() -> None:
    """VERDICT r3 #1 before/after: the round-3 configuration (asyncio
    server, Python clients — measured 14k msg/s host path, 5.5k e2e)
    against the round-4 C++ data plane (epoll host with the native
    PUBLISH fast path, driven by the C++ loadgen — the emqtt-bench
    analogue; a Python client fleet would measure itself, not the
    broker). Reference anchor: 1M msg/s sustained (README.md:16),
    sub-ms latency."""
    import asyncio

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    n_msg_before = int(os.environ.get("BENCH_HOST_BEFORE_MSGS", 1500))
    n_msg_blast = int(os.environ.get("BENCH_HOST_BLAST_MSGS", 40000))

    # -- before: asyncio server + python clients (the r3 shape) -------------
    async def drive_python_clients(port) -> float:
        subs = [MqttClient(port=port, clientid=f"ns{i}") for i in range(8)]
        for i, s in enumerate(subs):
            await s.connect()
            await s.subscribe(f"lg/{i}/+", qos=0)
        pubs = [MqttClient(port=port, clientid=f"np{i}") for i in range(8)]
        for p in pubs:
            await p.connect()
        expected = 8 * n_msg_before
        got = 0
        done = asyncio.Event()

        async def drain(s):
            nonlocal got
            while got < expected:
                try:
                    await s.recv(timeout=10)
                except asyncio.TimeoutError:
                    break
                got += 1
                if got >= expected:
                    done.set()
        drains = [asyncio.create_task(drain(s)) for s in subs]

        async def blast(i, p):
            for j in range(n_msg_before):
                await p.publish(f"lg/{(i + j) % 8}/m", b"x" * 16, qos=0)
        t0 = time.time()
        await asyncio.gather(*(blast(i, p) for i, p in enumerate(pubs)))
        try:
            await asyncio.wait_for(done.wait(), timeout=60)
        except asyncio.TimeoutError:
            pass
        wall = time.time() - t0
        for d in drains:
            d.cancel()
        for c in subs + pubs:
            try:
                await c.disconnect()
            except Exception:
                pass
        return got / wall

    async def run_before() -> float:
        server = BrokerServer(port=0, app=BrokerApp())
        await server.start()
        try:
            return await drive_python_clients(server.port)
        finally:
            await server.stop()

    before = asyncio.run(run_before())
    log(f"host plane BEFORE (asyncio + python clients, qos0): "
        f"{before:,.0f} msg/s")

    # -- after: C++ epoll host + native fast path + C++ loadgen -------------
    # NOTE for readers of CPU-fallback artifacts: every host-plane
    # number in this section measures the C++ data plane on the host
    # CPU BY DESIGN — a device fallback upstream does not change what
    # these sections measure (unlike the kernel/10M sections above)
    log("host plane sections measure the CPU data plane by design "
        "(device fallback does not affect them)")
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()
    try:
        blast = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast, qos=0, payload_len=16)
        wall = blast["wall_ns"] / 1e9
        blast_rate = blast["received"] / max(wall, 1e-9)
        log(f"host plane AFTER (C++ fast path, blast qos0): "
            f"{blast['received']}/{blast['sent']} in {wall:.2f}s = "
            f"{blast_rate:,.0f} msg/s  ({blast_rate / max(before, 1):,.0f}x "
            f"before, {blast_rate / 1e6:.2f}x the reference's 1M/s headline)")

        lat = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=3000, qos=0, payload_len=16, window=64)
        lat_wall = lat["wall_ns"] / 1e9
        log(f"host plane latency (windowed 64, qos0): "
            f"{lat['received'] / max(lat_wall, 1e-9):,.0f} msg/s  "
            f"p50={lat['p50_ns'] / 1e6:.3f}ms p99={lat['p99_ns'] / 1e6:.3f}ms")

        q1 = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast // 2, qos=1, payload_len=16,
            window=4096)
        q1_wall = q1["wall_ns"] / 1e9
        q1_rate = q1["received"] / max(q1_wall, 1e-9)
        log(f"host plane qos1 (windowed 4096): {q1_rate:,.0f} msg/s "
            f"acks={q1['acks']} p99={q1['p99_ns'] / 1e6:.2f}ms  "
            f"fast stats: {server.fast_stats()}")
        HOST_PLANE_RESULTS.update({
            "e2e_host_msgs_per_sec": round(blast_rate),
            "e2e_host_before_msgs_per_sec": round(before),
            "e2e_host_p50_ms": round(lat["p50_ns"] / 1e6, 3),
            "e2e_host_p99_ms": round(lat["p99_ns"] / 1e6, 3),
            "e2e_host_qos1_msgs_per_sec": round(q1_rate),
        })
    finally:
        server.stop()


def bench_shared_retained() -> None:
    """BASELINE config 4: shared subscriptions + retained messages at
    100K groups. Measures strategy-pick dispatch throughput across the
    group table (emqx_shared_sub.erl:138-157) and wildcard retained
    lookup against a populated store (emqx_retainer_index semantics)."""
    import time as _time

    from emqx_tpu.broker.shared_sub import SharedSub
    from emqx_tpu.core.message import Message
    from emqx_tpu.services.retainer import Retainer

    n_groups = int(os.environ.get("BENCH_GROUPS", 100_000))
    members_per = int(os.environ.get("BENCH_GROUP_MEMBERS", 4))
    rng = np.random.default_rng(7)

    shared = SharedSub(node="bench", strategy="round_robin")
    t0 = _time.time()
    for g in range(n_groups):
        topic = f"fleet/f{g % 512}/group{g}/+"
        for m in range(members_per):
            shared.join(f"g{g}", topic, f"sess-{g}-{m}", node="bench")
    log(f"shared: {n_groups} groups x {members_per} members joined "
        f"in {_time.time()-t0:.1f}s")

    picks = [int(x) for x in rng.integers(0, n_groups, 50_000)]
    msg = Message(topic="x", payload=b"p")
    t0 = _time.time()
    n_dispatched = 0
    for g in picks:
        # dispatch is keyed by the subscribed FILTER (the route topic),
        # exactly as broker._route hands it over
        got = shared.dispatch(f"g{g}", f"fleet/f{g % 512}/group{g}/+",
                              msg, deliver_fn=lambda s, n: True)
        n_dispatched += len(got)
    dt = _time.time() - t0
    log(f"shared dispatch (python, per-message): "
        f"{len(picks)/dt:,.0f} dispatches/sec @ {n_groups} groups "
        f"({n_dispatched} deliveries)")
    legs = [(f"g{g}", f"fleet/f{g % 512}/group{g}/+", msg) for g in picks]
    t0 = _time.time()
    out = shared.dispatch_batch(legs)
    dt = _time.time() - t0
    log(f"shared dispatch (python, batched): "
        f"{len(legs)/dt:,.0f} dispatches/sec "
        f"({sum(o is not None for o in out)} picks)")
    # the native C++ dispatcher — the path that actually serves fully
    # native groups in the broker (host.cc SharedGroup; VERDICT r3 #7)
    from emqx_tpu import native as _native
    if _native.available():
        tab = _native.NativeSubTable()
        for g in range(n_groups):
            filt = f"fleet/f{g % 512}/group{g}/+"
            for m in range(members_per):
                tab.shared_add(g + 1, (g << 3) | m, filt)
        topics = [f"fleet/f{g % 512}/group{g}/x"
                  for g in rng.integers(0, n_groups, 500_000)]
        t0 = _time.time()
        n_t, n_picks = tab.shared_pick_many(topics)
        dt = _time.time() - t0
        log(f"shared dispatch (native C++, incl. full topic match): "
            f"{n_picks/dt:,.0f} picks/sec @ {n_groups} groups")
        HOST_PLANE_RESULTS["shared_native_picks_per_sec"] = round(
            n_picks / dt)
        tab.close()

    retainer = Retainer(max_retained=n_groups + 10)
    t0 = _time.time()
    for g in range(n_groups):
        retainer.store(Message(
            topic=f"fleet/f{g % 512}/group{g}/state", payload=b"s",
            flags={"retain": True}))
    log(f"retainer: {n_groups} retained in {_time.time()-t0:.1f}s")
    t0 = _time.time()
    n_cold = sum(len(retainer.match(f"fleet/f{f}/+/state"))
                 for f in range(512))
    cold_dt = _time.time() - t0
    # steady state: the per-bucket submatrix caches are warm (retained
    # dispatch on subscribe hits the same buckets continuously)
    reps = 10
    t0 = _time.time()
    n_hits = 0
    for _ in range(reps):
        for f in range(512):
            n_hits += len(retainer.match(f"fleet/f{f}/+/state"))
    dt = _time.time() - t0
    log(f"retained wildcard lookup: {reps*512/dt:,.0f} lookups/sec warm "
        f"({512/cold_dt:,.0f} cold) = {n_hits/dt:,.0f} matched msgs/sec "
        f"(~{n_hits//(512*reps)} matches per lookup @ {n_groups} "
        f"retained; vectorized store, VERDICT r3 #5)")


def bench_e2e() -> None:
    """End-to-end broker number (VERDICT r1 weak #1): real MQTT clients
    over TCP against the asyncio host with the device router on the
    serving path — msg/s and delivery p99 through the full stack
    (parse → channel FSM → pipeline → kernel → CM → socket).  This is
    the broker-level figure comparable to the reference's 1M msg/s
    cluster claim; the kernel number above is the routing-core ceiling."""
    import asyncio

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.config.config import Config
    from emqx_tpu.mqtt.client import MqttClient

    n_pub = int(os.environ.get("BENCH_E2E_PUBS", 16))
    n_sub = int(os.environ.get("BENCH_E2E_SUBS", 16))
    n_msg = int(os.environ.get("BENCH_E2E_MSGS", 250))  # per publisher
    n_rules = int(os.environ.get("BENCH_RULES", 1000))  # config 5

    conf = Config()
    conf.put("router.device.enable", True)
    conf.put("router.device.max_levels", 8)
    # throughput section: pin the knee to 0 so every batch rides the
    # kernel (round-comparable device numbers); the low-load probe
    # below switches to the adaptive policy it is measuring
    conf.put("router.device.min_batch", 0)
    app = BrokerApp.from_config(conf)

    # BASELINE config 5: rule-engine SQL topic filters co-batched with the
    # router match — every FROM filter rides the SAME kernel launch as
    # fan-out; per-publish rule lookup is O(matched), not O(rules)
    # (emqx_rule_engine.erl:198-205)
    rule_hits = [0]
    if n_rules:
        app.rules.register_action(
            "bench_sink", lambda cols, args: rule_hits.__setitem__(
                0, rule_hits[0] + 1))
        for r in range(n_rules):
            # a few rules match live bench traffic; the rest are realistic
            # dead weight over the same topic space
            filt = (f"bench/{r % max(1, n_sub)}/+" if r < 8
                    else f"rules/fleet{r}/+/telemetry")
            app.rules.create_rule(
                f"bench_rule_{r}", f'SELECT topic FROM "{filt}"',
                [{"function": "bench_sink", "args": {}}])

    async def run():
        server = BrokerServer(port=0, app=app)
        await server.start()
        subs = [MqttClient(port=server.port, clientid=f"s{i}")
                for i in range(n_sub)]
        pubs = [MqttClient(port=server.port, clientid=f"p{i}")
                for i in range(n_pub)]
        for i, s in enumerate(subs):
            await s.connect()
            await s.subscribe(f"bench/{i}/+", qos=0)
        for p in pubs:
            await p.connect()
        # warm every pow2 batch shape the pipeline can hit (64..batch_max)
        # off the clock — each fresh shape costs an XLA compile
        def warm_shapes():
            model = app.broker.model
            b = 64
            while b <= app.pipeline.max_batch:
                model.publish_batch(["bench/warmup/x"] * b)
                b *= 2
        await asyncio.to_thread(warm_shapes)
        await pubs[0].publish("bench/0/warm", b"w", qos=0)
        await subs[0].recv(timeout=30)

        recv_done = asyncio.Event()
        lat_ns: list[int] = []
        expected = n_pub * n_msg            # each lands on exactly 1 sub
        got = 0

        async def drain(s):
            nonlocal got
            while got < expected:
                try:
                    m = await s.recv(timeout=10)
                except asyncio.TimeoutError:
                    break
                lat_ns.append(time.perf_counter_ns()
                              - int(m.payload.decode()))
                got += 1
                if got >= expected:
                    recv_done.set()

        drains = [asyncio.create_task(drain(s)) for s in subs]

        async def blast(i, p):
            for j in range(n_msg):
                stamp = str(time.perf_counter_ns()).encode()
                await p.publish(f"bench/{(i + j) % n_sub}/m", stamp, qos=0)

        t0 = time.time()
        await asyncio.gather(*(blast(i, p) for i, p in enumerate(pubs)))
        try:
            await asyncio.wait_for(recv_done.wait(), timeout=60)
        except asyncio.TimeoutError:
            pass
        wall = time.time() - t0
        for d in drains:
            d.cancel()

        # low-load latency (VERDICT r3 #3 done-criterion): sequential
        # publishes trickle in as 1-message batches, which the pipeline's
        # knee policy answers from the host oracle — no device RTT
        app.pipeline.min_device_batch = -1   # the policy under test
        probe = MqttClient(port=server.port, clientid="lat-probe")
        await probe.connect()
        await probe.subscribe("bench/lat/x", qos=0)
        low = []
        for i in range(40):
            t0 = time.perf_counter_ns()
            await pubs[0].publish("bench/lat/x", b"x", qos=0)
            try:
                await probe.recv(timeout=10)
            except asyncio.TimeoutError:
                # one dropped probe must not discard the whole e2e
                # section's already-measured results
                log(f"low-load probe: recv timeout at sample {i}")
                break
            low.append((time.perf_counter_ns() - t0) / 1e6)
            await asyncio.sleep(0.01)
        low_a = np.array(low) if low else np.array([float("nan")])
        await probe.close()

        for c in subs + pubs:
            try:
                await c.disconnect()
            except Exception:
                pass
        await server.stop()
        lat_ms = np.array(lat_ns, float) / 1e6
        log(f"e2e broker: {got}/{expected} msgs in {wall:.2f}s = "
            f"{got / wall:,.0f} msg/s end-to-end "
            f"(pubs={n_pub} subs={n_sub} qos=0, device path, "
            f"kernel launches={app.broker.model.launch_count}, "
            f"rules={n_rules} co-batched, rule fires={rule_hits[0]})")
        if len(lat_ms):
            log(f"e2e delivery latency ms: p50={np.percentile(lat_ms, 50):.2f} "
                f"p99={np.percentile(lat_ms, 99):.2f}")
        log(f"e2e LOW-LOAD latency ms (device on, knee="
            f"{app.pipeline.device_knee()}, host-bypassed batches="
            f"{app.pipeline.host_batches}): "
            f"p50={np.percentile(low_a, 50):.2f} "
            f"p99={np.percentile(low_a, 99):.2f}")
        HOST_PLANE_RESULTS.update({
            "e2e_lowload_p50_ms": round(float(np.percentile(low_a, 50)), 2),
            "e2e_lowload_p99_ms": round(float(np.percentile(low_a, 99)), 2),
        })

    asyncio.run(run())

    # -- device-path ceiling under native load ------------------------------
    # The same app (warmed model/pipeline) behind the C++ host with the
    # fast path OFF: every publish runs Channel.handle_in → pipeline →
    # kernel. This is the honest "Python FSM + device router" e2e bound
    # (the r3 famine was Python clients measuring themselves; the C++
    # loadgen removes that), and the gap to the fast-path number above
    # is the remaining host-plane work for future rounds.
    from emqx_tpu import native as _native

    if _native.available() and os.environ.get("BENCH_DEVICE_E2E", "1") != "0":
        from emqx_tpu.broker.native_server import NativeBrokerServer

        app.pipeline.min_device_batch = 0   # measure the KERNEL path,
        server = NativeBrokerServer(port=0, app=app, fast_path=False)
        server.start()                      # not the knee's host bypass
        try:
            res = _native.loadgen_run(
                "127.0.0.1", server.port, n_subs=8, n_pubs=8,
                msgs_per_pub=int(os.environ.get("BENCH_DEVICE_E2E_MSGS",
                                                1500)),
                qos=0, payload_len=16, window=2048, warmup=False)
            wall = res["wall_ns"] / 1e9
            rate = res["received"] / max(wall, 1e-9)
            log(f"device-path e2e (native load, fast path OFF, window "
                f"2048): {res['received']}/{res['sent']} = {rate:,.0f} "
                f"msg/s through channel FSM + pipeline + kernel "
                f"(launches={app.broker.model.launch_count})")
            HOST_PLANE_RESULTS["e2e_device_path_msgs_per_sec"] = round(rate)
        except Exception as e:  # noqa: BLE001
            # a loadgen flake must not cost the whole artifact (every
            # earlier section's numbers print in main()'s final JSON)
            log(f"device-path e2e section failed, skipping: {e}")
        finally:
            server.stop()


if __name__ == "__main__":
    if os.environ.get("BENCH_SUPERVISED") != "1":
        _supervise()
    main()
