"""Route-match throughput benchmark (the BASELINE.json north-star metric).

Measures the flagship device step — batched wildcard match + compact +
subscriber-shard fan-out — against a connected-vehicle-style filter set
(BASELINE configs 2/3: ~1M subscriptions, ~10% single-level '+' wildcards,
7-level topic tree). The reference equivalent is `emqx_router:match_routes/1`
(per-message Erlang trie walk over ETS, apps/emqx/src/emqx_router.erl:141-153,
driven in-VM by apps/emqx/src/emqx_broker_bench.erl).

Prints a cumulative JSON line after EVERY completed section (the last line
is the full artifact):
  {"metric": "route-matches/sec", "value": N, "unit": "topics/sec",
   "vs_baseline": X, ...}

Supervision model (VERDICT r4 #1 — the artifact must be un-missable):
  * each section runs as its OWN child process with its OWN deadline, so a
    tunnel wedge in section k cannot take sections 1..k-1 (or the host-CPU
    sections) down with it;
  * sections write partial results to $BENCH_PARTIAL_DIR/section_<name>.json
    as they go, and the supervisor re-emits the cumulative stdout line after
    every section — a SIGKILL at any point leaves the newest cumulative
    line in the tail;
  * the device probe retries with backoff (~10 min worst case) instead of
    one 180s shot, and its attempt log lands in the artifact;
  * on a wedged tunnel mid-run, remaining device sections are skipped (with
    reasons in the artifact), host sections still run, and a CPU kernel
    fallback runs ONLY if no device kernel number was captured — captured
    device sections are never overwritten.

vs_baseline: ratio against the reference's own headline sustained cluster
throughput of 1M msg/s (reference README.md:16) — every routed message
needs exactly one match_routes call, so topics-matched/sec is directly
comparable. No per-config BEAM numbers are published (BASELINE.md).

Latency is measured with synchronous dispatch (block every step);
throughput with the production discipline — a bounded in-flight window of
batches (SURVEY.md §2.5-6 pipeline parallelism: batch assembly overlaps
device execution, as the reference overlaps socket reads with dispatch via
{active,N}) — every output is still blocked on before it leaves the window.

Env knobs: BENCH_FILTERS (default 1_000_000), BENCH_BATCH (16384),
BENCH_ITERS (100), BENCH_SHARDS (8192 subscriber fan-out shards),
BENCH_WINDOW (8 in-flight batches), BENCH_LAT_ITERS (30 sync latency
samples), BENCH_TOTAL_BUDGET_S (3300), BENCH_SECTION (internal: run one
section inline), BENCH_PARTIAL_DIR (internal: partial-results directory).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np

if os.environ.get("JAX_PLATFORMS"):
    # the axon sitecustomize force-registers the TPU platform via
    # jax.config.update, which beats the env var — honour an explicit
    # JAX_PLATFORMS so the bench can be verified off-TPU
    import jax as _jax
    _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# partial-results plumbing
# ---------------------------------------------------------------------------

RESULTS: dict = {}


def flush_results(section: str) -> None:
    """Atomically persist this section's results-so-far. Called after every
    subsection so a mid-section wedge still lands the completed numbers."""
    d = os.environ.get("BENCH_PARTIAL_DIR")
    if not d:
        return
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{section}.tmp")
    with open(tmp, "w") as f:
        json.dump(RESULTS, f)
    os.replace(tmp, os.path.join(d, f"section_{section}.json"))


def put_broker_hists(section: str, server, prefix: str) -> dict:
    """Persist broker-SIDE stage latency percentiles (the native
    telemetry plane's histograms, native_server.latency_summary) next
    to the loadgen-side numbers — p50/p99/p999 per stage in µs. The
    loadgen measures publish→deliver across the wire; these split that
    budget into the in-broker stages (ingress→route, route→flush, ack
    RTTs, lane dwell, GIL stints), so ROADMAP's 'p99 <= 2ms' gate can
    be audited from the broker's own clocks, not just the client's."""
    # hist deltas ship on a ~100ms cadence (host.cc): give the poll
    # loop a few idle cycles so the run's FINAL window (incl. the tail
    # ack-RTT samples) reaches the Python accumulators before we read
    time.sleep(0.5)
    try:
        summ = server.latency_summary()
    except Exception:  # noqa: BLE001 — telemetry off / old server
        return {}
    kv = {}
    for stage, s in summ.items():
        kv[f"{prefix}_{stage}_p50_us"] = s["p50_us"]
        kv[f"{prefix}_{stage}_p99_us"] = s["p99_us"]
        kv[f"{prefix}_{stage}_p999_us"] = s["p999_us"]
        kv[f"{prefix}_{stage}_count"] = s["count"]
    if kv:
        put(section, **kv)
    return summ


def put(section: str, **kv) -> None:
    RESULTS.update(kv)
    flush_results(section)


# ---------------------------------------------------------------------------
# shared builders (BASELINE config 2/3 shape)
# ---------------------------------------------------------------------------

def build_filters(n: int, rng: np.random.Generator) -> list[str]:
    """Vehicle-fleet topic tree, 7 levels deep, ~10% '+' wildcards,
    a few percent '#' — the BASELINE config 2/3 shape."""
    n_vehicles = max(1000, n // 2)
    filters = []
    kinds = rng.random(n)
    vids = rng.integers(0, n_vehicles, n)
    fleets = rng.integers(0, 512, n)
    metrics = rng.integers(0, 16, n)
    parts = rng.integers(0, 8, n)
    for i in range(n):
        v, fl, m, p = vids[i], fleets[i], metrics[i], parts[i]
        k = kinds[i]
        if k < 0.80:      # exact 7-level
            f = f"fleet/f{fl}/vehicle/v{v}/part/p{p}/m{m}"
        elif k < 0.90:    # single-level '+'
            f = f"fleet/f{fl}/vehicle/+/part/p{p}/m{m}"
        elif k < 0.95:
            f = f"fleet/f{fl}/vehicle/v{v}/part/+/m{m}"
        elif k < 0.98:    # multi-level '#'
            f = f"fleet/f{fl}/vehicle/v{v}/#"
        else:
            f = f"fleet/+/vehicle/v{v}/part/p{p}/#"
        filters.append(f)
    return filters


def build_model(n_filters: int, rng: np.random.Generator, n_shards: int,
                mesh=None, trie_shards: Optional[int] = None):
    """Index + RouterModel with one subscriber shard per subscription,
    uploaded to the device. Returns (index, model, live_filters).

    ``trie_shards`` builds the subscription-sharded layout
    (ShardedTrieIndex, shard axis over tp when ``mesh`` is given)
    instead of the replicated trie."""
    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.router.index import ShardedTrieIndex, TrieIndex

    filters = build_filters(n_filters, rng)
    index = (ShardedTrieIndex(trie_shards, max_levels=8) if trie_shards
             else TrieIndex(max_levels=8))
    model = RouterModel(index, n_sub_slots=n_shards, K=32, M=128,
                        mesh=mesh)
    index.load(filters)
    slot_of = rng.integers(0, n_shards, len(index.filters))
    for fid in range(len(index.filters)):
        if index.filters[fid] is not None:
            model._subs.setdefault(fid, {})[int(slot_of[fid])] = 1
    model.refresh()
    live = [f for f in index.filters if f is not None]
    return index, model, live


def make_topics(live: list[str], rng: np.random.Generator, count: int,
                n_vehicles: int) -> list[str]:
    """Publish into the subscribed tree (emqx_broker_bench shape):
    instantiate a random subscribed filter's wildcards with concrete
    words."""
    picks = rng.integers(0, len(live), count)
    v = rng.integers(0, n_vehicles, count)
    p = rng.integers(0, 8, count)
    m = rng.integers(0, 16, count)
    fl = rng.integers(0, 512, count)
    topics = []
    for i in range(count):
        ws = live[picks[i]].split("/")
        out = []
        for j, w in enumerate(ws):
            if w == "+":
                out.append(
                    f"v{v[i]}" if j == 3 else f"p{p[i]}" if j == 5 else f"f{fl[i]}"
                )
            elif w == "#":
                out.extend([f"part/p{p[i]}", f"m{m[i]}"][: 7 - j])
                break
            else:
                out.append(w)
        topics.append("/".join(out))
    return topics


def make_routable(index, model, warm_topic: str):
    """Single-topic subscribe→routable probe shared by the kernel and
    churn sections: a 64-row padded batch whose rows 1.. are masked out
    (length 0 + sys flag) so only the probe topic can match. Numpy args
    transfer inside the ONE dispatch — separate device_put calls each
    cost a full tunnel round trip. Warms the 64-shape program and the
    scatter shapes off the clock via ``warm_topic``."""
    import jax

    B2 = 64
    step = model._step

    def routable(topic: str):
        tok, lens, sysf, _ = index.tokenize([topic] + [""] * (B2 - 1))
        lens[1:] = 0
        sysf[1:] = True
        return step(model._trie_dev, model._rowmap_dev, model._pool_dev,
                    tok, lens, sysf)

    model.subscribe(warm_topic, 0)
    model.refresh()
    jax.block_until_ready(routable(warm_topic))
    return routable


def windowed_tps(step, args_fn, iters: int, window_n: int, B: int):
    """Steady-state throughput with a bounded in-flight window; every
    output is blocked on before leaving the window (nothing unverified
    in flight). Returns (topics/sec, last_output)."""
    import jax

    t_start = time.time()
    window = []
    last = None
    for i in range(iters):
        window.append(step(*args_fn(i)))
        if len(window) >= window_n:
            last = window.pop(0)
            jax.block_until_ready(last)
    for o in window:
        last = o
        jax.block_until_ready(o)
    return iters * B / (time.time() - t_start), last


# ---------------------------------------------------------------------------
# section: kernel (the headline — 1M-filter device match)
# ---------------------------------------------------------------------------

def sec_kernel() -> None:
    n_filters = int(os.environ.get("BENCH_FILTERS", 1_000_000))
    B = int(os.environ.get("BENCH_BATCH", 16384))
    iters = int(os.environ.get("BENCH_ITERS", 100))
    n_shards = int(os.environ.get("BENCH_SHARDS", 8192))
    window_n = int(os.environ.get("BENCH_WINDOW", 8))

    import jax

    platform = jax.devices()[0].platform
    put("kernel", kernel_platform=platform, kernel_filters=n_filters)

    rng = np.random.default_rng(42)
    t0 = time.time()
    index, model, live = build_model(n_filters, rng, n_shards)
    arrays = index.arrays
    log(f"built+loaded+uploaded {len(index.filters)} filters in "
        f"{time.time()-t0:.1f}s: nodes={arrays.n_nodes} "
        f"ht={arrays.ht_parent.shape[0]} "
        f"pool={int(model._pool_dev.nbytes) >> 10}KiB "
        f"rowmap={int(model._rowmap_dev.nbytes) >> 20}MiB "
        f"device={jax.devices()[0]}")

    # pre-tokenized topic batches (the C++ ingest host's job in production)
    n_vehicles = max(1000, n_filters // 2)
    n_batches = 8
    t0 = time.time()
    batches = []
    topics = None
    for _ in range(n_batches):
        topics = make_topics(live, rng, B, n_vehicles)
        tok, lens, sysf, too_long = index.tokenize(topics)
        assert not too_long
        batches.append(tuple(jax.device_put(x) for x in (tok, lens, sysf)))
    log(f"tokenized {n_batches}x{B} topics in {time.time()-t0:.1f}s")

    step = model._step
    trie_dev = model._trie_dev
    bm_dev = (model._rowmap_dev, model._pool_dev)

    t0 = time.time()
    out = step(trie_dev, *bm_dev, *batches[0])
    jax.block_until_ready(out)
    log(f"compile+first step {time.time()-t0:.1f}s")

    # synchronous per-step latency (the p99 a single publish batch sees);
    # sample count capped (each sync step round-trips the tunnel)
    lat_iters = min(iters, int(os.environ.get("BENCH_LAT_ITERS", 30)))
    lat = []
    for i in range(lat_iters):
        t0 = time.time()
        out = step(trie_dev, *bm_dev, *batches[i % n_batches])
        jax.block_until_ready(out)
        lat.append(time.time() - t0)

    tps, last = windowed_tps(
        step, lambda i: (trie_dev, *bm_dev, *batches[i % n_batches]),
        iters, window_n, B)

    matched_per_topic = np.sum(np.asarray(last[0]) >= 0, axis=1)
    lat_ms = np.array(lat) * 1e3
    log(f"matched filters/topic: mean={matched_per_topic.mean():.2f} "
        f"(dense-pool rows: {len(model._dense_row)})")
    log(f"sync step latency ms: p50={np.percentile(lat_ms,50):.2f} "
        f"p99={np.percentile(lat_ms,99):.2f} (batch={B})")
    log(f"throughput (window={window_n}): {tps:,.0f} topics/sec "
        f"@ {n_filters} subs")
    put("kernel",
        kernel_topics_per_sec=round(tps),
        kernel_sync_p50_ms=round(float(np.percentile(lat_ms, 50)), 2),
        kernel_sync_p99_ms=round(float(np.percentile(lat_ms, 99)), 2))

    # measured in-repo anchor (VERDICT r2 weak #3): the host-oracle trie
    # (router/trie.py — the emqx_trie.erl semantics the kernel is
    # differentially tested against) walking the SAME topic
    # distribution. Match cost is O(topic depth), not O(filters), so a
    # subset-built trie gives the same per-topic walk cost as 1M.
    from emqx_tpu.router.trie import Trie

    n_oracle = min(len(live),
                   int(os.environ.get("BENCH_ORACLE_FILTERS", 200_000)))
    oracle = Trie()
    for f in live[:n_oracle]:
        oracle.insert(f)
    o_topics = topics[: min(len(topics), 4096)]
    t0 = time.time()
    o_hits = sum(len(oracle.match(t)) for t in o_topics)
    oracle_tps = len(o_topics) / (time.time() - t0)
    vs_oracle = tps / oracle_tps
    log(f"host-oracle anchor: {oracle_tps:,.0f} topics/sec "
        f"(python trie walk, {n_oracle} filters, {o_hits} matches) "
        f"→ device = {vs_oracle:,.1f}x the measured host oracle")
    put("kernel", vs_host_oracle=round(vs_oracle, 1))

    # -- incremental subscribe→routable latency -----------------------------
    # North star: emqx_trie.erl:113-144-style O(topic-depth) insert, NOT a
    # full rebuild (round 1: 106 s at 1M filters). Each sample: subscribe a
    # brand-new filter → scatter-patch HBM → publish a matching topic and
    # block on its fan-out.
    routable = make_routable(index, model,
                             "fleet/warm/vehicle/w/part/p0/m0")

    inc = []
    for i in range(30):
        f = f"fleet/fnew/vehicle/z{i}/part/p{i % 8}/m{i % 16}"
        t0 = time.time()
        model.subscribe(f, int(rng.integers(0, n_shards)))
        model.refresh()
        out = routable(f)
        jax.block_until_ready(out)
        inc.append(time.time() - t0)
        assert int(np.sum(np.asarray(out[0])[0] >= 0)) >= 1, \
            "new filter not routable"
    inc_ms = np.array(inc) * 1e3
    log(f"incremental subscribe→routable ms: "
        f"p50={np.percentile(inc_ms,50):.2f} "
        f"p99={np.percentile(inc_ms,99):.2f} (full uploads since load: "
        f"{model.upload_count - 1}, patches: {model.patch_count})")
    put("kernel",
        inc_sub_routable_p50_ms=round(float(np.percentile(inc_ms, 50)), 2),
        inc_sub_routable_p99_ms=round(float(np.percentile(inc_ms, 99)), 2))

    # the sync number above is dominated by a fixed ~70ms tunnel
    # synchronization cost (measured: block_until_ready on x+1 over 64
    # ints pays the same) — the amortized chain below shows the actual
    # device-side update cost: N dependent subscribe→patch→match chains,
    # one block at the end
    n_chain = 50
    t0 = time.time()
    out = None
    for i in range(n_chain):
        f = f"fleet/fchain/vehicle/c{i}/part/p{i % 8}/m{i % 16}"
        model.subscribe(f, int(rng.integers(0, n_shards)))
        model.refresh()
        out = routable(f)
    jax.block_until_ready(out)
    chain_ms = (time.time() - t0) * 1e3 / n_chain
    log(f"incremental update amortized (pipelined chain of {n_chain}): "
        f"{chain_ms:.2f} ms/update")
    put("kernel", inc_chain_ms=round(chain_ms, 2))

    # -- kernel-plane telemetry percentiles (round 19) ----------------------
    # drive the full submit→collect path with a DeviceMetricsFold
    # attached so the artifact records the device-clock stage split
    # (kernel_summary(), the same surface server.kernel_summary()
    # serves) next to the loadgen-free step numbers above
    from emqx_tpu.observe.device_metrics import DeviceMetricsFold
    from emqx_tpu.observe.metrics import Metrics as _Metrics

    fold = DeviceMetricsFold(_Metrics(), model=model)
    hm, model._host_matcher = model._host_matcher, None
    model.telemetry = fold
    try:
        tel_topics = make_topics(live, rng, 1024, n_vehicles)
        for _ in range(10):
            model.publish_batch_collect(
                model.publish_batch_submit(tel_topics))
    finally:
        model.telemetry = None
        model._host_matcher = hm
    ks = fold.kernel_summary()
    log(f"kernel telemetry stages us: "
        + " ".join(f"{s}=p50:{v['p50_us']}/p99:{v['p99_us']}"
                   for s, v in ks["stages"].items())
        + f" counters={ks['counters']}")
    put("kernel",
        kernel_submit_p50_us=ks["stages"]["submit"]["p50_us"],
        kernel_submit_p99_us=ks["stages"]["submit"]["p99_us"],
        kernel_step_p50_us=ks["stages"]["step"]["p50_us"],
        kernel_step_p99_us=ks["stages"]["step"]["p99_us"],
        kernel_decode_p50_us=ks["stages"]["decode"]["p50_us"],
        kernel_decode_p99_us=ks["stages"]["decode"]["p99_us"],
        kernel_telemetry_batches=ks["batches"])


# ---------------------------------------------------------------------------
# section: tenm (BASELINE config 3 — 10M subscriptions)
# ---------------------------------------------------------------------------

def _tenm_cache_dir(n: int, n_shards: int, B: int,
                    variant: str = "") -> str:
    import tempfile

    root = os.environ.get("BENCH_TENM_CACHE_DIR",
                          os.path.join(tempfile.gettempdir(),
                                       "emqx_bench_tenm"))
    # the sharded layout gets its OWN cache (variant="shN"): its vocab
    # intern order, fid namespace, rowmap/pool and tokenization all
    # differ from the replicated build's
    return os.path.join(root, f"n{n}_s{n_shards}_b{B}{variant}_v1")


_TENM_ARRAYS = ("ht_parent", "ht_word", "ht_child", "plus_child",
                "hash_fid", "node_fid", "rowmap", "pool",
                "tok", "lens", "sysf")


def _tenm_save_cache(cache: str, index, model, tok, lens, sysf) -> None:
    """Persist the host-built trie/pool arrays + the tokenized probe
    batch as individual .npy files (np.savez would defeat mmap). The
    meta file lands LAST so a killed writer never fakes a valid cache."""
    tmp = cache + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    arrays = index.ensure()
    vals = dict(
        ht_parent=arrays.ht_parent, ht_word=arrays.ht_word,
        ht_child=arrays.ht_child, plus_child=arrays.plus_child,
        hash_fid=arrays.hash_fid, node_fid=arrays.node_fid,
        rowmap=model._rowmap_host, pool=model._pool_host,
        tok=tok, lens=lens, sysf=sysf)
    for name in _TENM_ARRAYS:
        np.save(os.path.join(tmp, f"{name}.npy"), vals[name])
    live = sum(f is not None for f in index.filters)
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump({"n_nodes": arrays.n_nodes,
                   "n_filters": arrays.n_filters,
                   "max_probes": arrays.max_probes,
                   "live": live}, f)
    if os.path.isdir(cache):
        import shutil
        shutil.rmtree(cache, ignore_errors=True)
    os.replace(tmp, cache)


def _tenm_load_cache(cache: str):
    """mmap-load a previously built 10M index: the device upload streams
    straight out of the page cache instead of re-running the ~6-minute
    host build (VERDICT r5 next #1: the 800s section deadline must buy
    measurement, not rebuild)."""
    with open(os.path.join(cache, "meta.json")) as f:
        meta = json.load(f)
    arrs = {name: np.load(os.path.join(cache, f"{name}.npy"),
                          mmap_mode="r")
            for name in _TENM_ARRAYS}
    from emqx_tpu.router.index import TrieIndexArrays

    arrays = TrieIndexArrays(
        ht_parent=arrs["ht_parent"], ht_word=arrs["ht_word"],
        ht_child=arrs["ht_child"], plus_child=arrs["plus_child"],
        hash_fid=arrs["hash_fid"], node_fid=arrs["node_fid"],
        n_nodes=meta["n_nodes"], n_filters=meta["n_filters"],
        max_probes=meta["max_probes"])
    return meta, arrays, arrs


_TENM_TRIE_ARRAYS = _TENM_ARRAYS[:6]
_TENM_AUX_ARRAYS = _TENM_ARRAYS[6:]


def _tenm_save_cache_sharded(cache: str, index, model,
                             tok, lens, sysf) -> None:
    """Sharded-layout twin of _tenm_save_cache: per-shard trie arrays
    under shard<k>/ plus the shared rowmap/pool/batch at the root."""
    tmp = cache + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    shard_arrays = index.ensure()      # equalized edge tables
    per_meta = []
    for k, arrays in enumerate(shard_arrays):
        d = os.path.join(tmp, f"shard{k}")
        os.makedirs(d, exist_ok=True)
        for name in _TENM_TRIE_ARRAYS:
            np.save(os.path.join(d, f"{name}.npy"), getattr(arrays, name))
        per_meta.append({"n_nodes": arrays.n_nodes,
                         "n_filters": arrays.n_filters,
                         "max_probes": arrays.max_probes})
    aux = dict(rowmap=model._rowmap_host, pool=model._pool_host,
               tok=tok, lens=lens, sysf=sysf)
    for name in _TENM_AUX_ARRAYS:
        np.save(os.path.join(tmp, f"{name}.npy"), aux[name])
    live = sum(f is not None for f in index.filters)
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump({"n_shards": index.n_shards, "shards": per_meta,
                   "live": live}, f)
    if os.path.isdir(cache):
        import shutil
        shutil.rmtree(cache, ignore_errors=True)
    os.replace(tmp, cache)


def _tenm_load_cache_sharded(cache: str):
    """mmap-load a cached sharded build: (meta, shard_arrays, aux)."""
    from emqx_tpu.router.index import TrieIndexArrays

    with open(os.path.join(cache, "meta.json")) as f:
        meta = json.load(f)
    shard_arrays = []
    for k, sm in enumerate(meta["shards"]):
        d = os.path.join(cache, f"shard{k}")
        arrs = {name: np.load(os.path.join(d, f"{name}.npy"),
                              mmap_mode="r")
                for name in _TENM_TRIE_ARRAYS}
        shard_arrays.append(TrieIndexArrays(
            n_nodes=sm["n_nodes"], n_filters=sm["n_filters"],
            max_probes=sm["max_probes"], **arrs))
    aux = {name: np.load(os.path.join(cache, f"{name}.npy"),
                         mmap_mode="r")
           for name in _TENM_AUX_ARRAYS}
    return meta, shard_arrays, aux


def sec_tenm() -> None:
    """BASELINE config 3 / the north star's 10M-subscription point
    (VERDICT r3 #2: the 10M run must live in a driver artifact, not a
    commit message). Cold build + device upload + windowed kernel
    throughput + sync p99 at 10M mixed-wildcard filters.

    The host-side build serializes to disk on first success and
    mmap-loads on every later attempt (~378s → seconds), so a flaky
    tunnel window that only opens mid-run still yields the TPU number.

    Skipped on the CPU fallback (a 10M CPU kernel run would blow its
    deadline and prove nothing about the device)."""
    import jax

    if (jax.devices()[0].platform == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU") != "1"):
        # BENCH_ALLOW_CPU is a validation-only override (tiny sizes):
        # it lets the device-only sections' LOGIC run off-TPU so a bug
        # cannot burn the driver's device budget undetected
        log("10M section: skipped on CPU fallback")
        return

    n = int(os.environ.get("BENCH_TENM_FILTERS", 10_000_000))
    B = int(os.environ.get("BENCH_BATCH", 16384))
    iters = int(os.environ.get("BENCH_TENM_ITERS", 30))
    n_shards = int(os.environ.get("BENCH_SHARDS", 8192))
    rng = np.random.default_rng(3)

    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.ops import trie_match as tm
    from emqx_tpu.router.index import TrieIndex

    cache = _tenm_cache_dir(n, n_shards, B)
    cached = os.path.exists(os.path.join(cache, "meta.json"))
    t0 = time.time()
    if cached:
        meta, arrays, arrs = _tenm_load_cache(cache)
        trie_dev = tm.device_trie(arrays)
        import jax.numpy as jnp
        rowmap_dev = jnp.asarray(arrs["rowmap"])
        pool_dev = jnp.asarray(arrs["pool"])
        batch = tuple(jax.device_put(np.asarray(arrs[k]))
                      for k in ("tok", "lens", "sysf"))
        # a bare model supplies the jitted step (same K/M/ret_cap/
        # max_probes statics as build_model's)
        step = RouterModel(TrieIndex(max_levels=8),
                           n_sub_slots=n_shards, K=32, M=128)._step
        n_live = meta["live"]
        build_s = time.time() - t0
        log(f"10M: mmap-loaded {n_live} cached filters in {build_s:.0f}s "
            f"({cache})")
    else:
        index, model, live = build_model(n, rng, n_shards)
        topics = make_topics(live, rng, B, max(1000, n // 2))
        tok, lens, sysf, _ = index.tokenize(topics)
        batch = tuple(jax.device_put(x) for x in (tok, lens, sysf))
        trie_dev = model._trie_dev
        rowmap_dev, pool_dev = model._rowmap_dev, model._pool_dev
        step = model._step
        n_live = len(index.filters)
        build_s = time.time() - t0
        try:
            t1 = time.time()
            _tenm_save_cache(cache, index, model, tok, lens, sysf)
            log(f"10M: cached host build to {cache} "
                f"({time.time()-t1:.0f}s)")
        except OSError as e:       # disk-full etc: cache is optional
            log(f"10M: cache write failed ({e}); continuing uncached")
    import jax.tree_util as jtu
    hbm_bytes = (int(pool_dev.nbytes) + int(rowmap_dev.nbytes)
                 + sum(int(x.nbytes) for x in jtu.tree_leaves(trie_dev)))
    log(f"10M: built+loaded+uploaded {n_live} filters in "
        f"{build_s:.0f}s, device bytes={hbm_bytes / (1 << 30):.2f} GiB")
    put("tenm", tenm_build_s=round(build_s, 1),
        tenm_index_cached=cached,
        tenm_platform=jax.devices()[0].platform,
        tenm_device_gib=round(hbm_bytes / (1 << 30), 2))
    t0 = time.time()
    out = step(trie_dev, rowmap_dev, pool_dev, *batch)
    jax.block_until_ready(out)
    log(f"10M: compile+first step {time.time() - t0:.1f}s")

    lat = []
    for _ in range(5):
        t0 = time.time()
        jax.block_until_ready(
            step(trie_dev, rowmap_dev, pool_dev, *batch))
        lat.append(time.time() - t0)
    window_n = int(os.environ.get("BENCH_WINDOW", 8))
    tps, _ = windowed_tps(
        step,
        lambda i: (trie_dev, rowmap_dev, pool_dev, *batch),
        iters, window_n, B)
    p99 = float(np.percentile(np.array(lat) * 1e3, 99))
    log(f"10M: {tps:,.0f} topics/sec (window={window_n}), sync p99 "
        f"{p99:.1f}ms @ {n} subs")
    put("tenm", tenm_topics_per_sec=round(tps),
        tenm_sync_p99_ms=round(p99, 1))
    del trie_dev, rowmap_dev, pool_dev, batch, out  # free HBM for the arm
    _tenm_sharded_arm(n, B, iters, n_shards, window_n)


def _tenm_sharded_arm(n: int, B: int, iters: int, n_shards: int,
                      window_n: int) -> None:
    """The ISSUE-17 comparison arm: the SAME 10M filter set on the
    subscription-sharded trie (ShardedTrieIndex stacked [S, ...], shard
    axis over tp at the largest available mesh), measured next to the
    replicated baseline above.  Its own disk cache — the sharded
    build's fid namespace, vocab order, rowmap/pool and tokenization
    all differ from the replicated one's."""
    import jax
    import jax.numpy as jnp

    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.ops import trie_match as tm
    from emqx_tpu.parallel import mesh as pmesh
    from emqx_tpu.router.index import ShardedTrieIndex

    rng = np.random.default_rng(3)
    n_dev = len(jax.devices())
    mesh = pmesh.make_mesh(n_dev) if n_dev >= 2 else None
    tp_ext = mesh.shape[pmesh.TP] if mesh is not None else 1
    S = int(os.environ.get("BENCH_TRIE_SHARDS", 0)) or max(4, tp_ext)
    S = max(tp_ext, S - S % tp_ext)    # shard axis must split evenly
    mesh_label = (f"{mesh.shape[pmesh.DP]}x{tp_ext}" if mesh is not None
                  else "1x1")
    shardings = pmesh.router_shardings(mesh) if mesh is not None else None

    cache = _tenm_cache_dir(n, n_shards, B, variant=f"_sh{S}")
    cached = os.path.exists(os.path.join(cache, "meta.json"))
    t0 = time.time()
    # the bare model supplies the jitted sharded step (n_shards static)
    step_model = RouterModel(
        ShardedTrieIndex(S, max_levels=8), n_sub_slots=n_shards,
        K=32, M=128, mesh=mesh)
    if cached:
        meta, shard_arrays, aux = _tenm_load_cache_sharded(cache)
        trie_dev = tm.stacked_device_trie(shard_arrays)
        rowmap_host, pool_host = aux["rowmap"], aux["pool"]
        batch_host = tuple(np.asarray(aux[k])
                           for k in ("tok", "lens", "sysf"))
        n_live = meta["live"]
    else:
        index, model, live = build_model(n, rng, n_shards, mesh=mesh,
                                         trie_shards=S)
        topics = make_topics(live, rng, B, max(1000, n // 2))
        tok, lens, sysf, _ = index.tokenize(topics)
        trie_dev = tm.stacked_device_trie(index.ensure())
        rowmap_host, pool_host = model._rowmap_host, model._pool_host
        batch_host = (tok, lens, sysf)
        n_live = sum(f is not None for f in index.filters)
        try:
            t1 = time.time()
            _tenm_save_cache_sharded(cache, index, model, tok, lens, sysf)
            log(f"10M sharded: cached host build to {cache} "
                f"({time.time()-t1:.0f}s)")
        except OSError as e:
            log(f"10M sharded: cache write failed ({e}); uncached")
    if shardings is not None:
        trie_dev = jax.device_put(trie_dev, shardings["trie_sub"])
        rowmap_dev = jax.device_put(np.asarray(rowmap_host),
                                    shardings["replicated"])
        pool_dev = jax.device_put(np.asarray(pool_host),
                                  shardings["bitmaps"])
        batch = jax.device_put(batch_host, shardings["batch_dp"])
    else:
        trie_dev = tm.DeviceTrie(*(jnp.asarray(x) for x in trie_dev))
        rowmap_dev = jnp.asarray(np.asarray(rowmap_host))
        pool_dev = jnp.asarray(np.asarray(pool_host))
        batch = tuple(jax.device_put(np.asarray(x)) for x in batch_host)
    build_s = time.time() - t0
    import jax.tree_util as jtu
    hbm_bytes = (int(pool_dev.nbytes) + int(rowmap_dev.nbytes)
                 + sum(int(x.nbytes) for x in jtu.tree_leaves(trie_dev)))
    log(f"10M sharded: S={S} mesh={mesh_label} {n_live} filters ready in "
        f"{build_s:.0f}s, device bytes={hbm_bytes / (1 << 30):.2f} GiB")
    put("tenm", tenm_sharded_shards=S, tenm_sharded_mesh=mesh_label,
        tenm_sharded_build_s=round(build_s, 1),
        tenm_sharded_index_cached=cached,
        tenm_sharded_device_gib=round(hbm_bytes / (1 << 30), 2))

    step = step_model._step
    t0 = time.time()
    jax.block_until_ready(step(trie_dev, rowmap_dev, pool_dev, *batch))
    log(f"10M sharded: compile+first step {time.time() - t0:.1f}s")
    lat = []
    for _ in range(5):
        t0 = time.time()
        jax.block_until_ready(
            step(trie_dev, rowmap_dev, pool_dev, *batch))
        lat.append(time.time() - t0)
    tps, _ = windowed_tps(
        step, lambda i: (trie_dev, rowmap_dev, pool_dev, *batch),
        iters, window_n, B)
    p99 = float(np.percentile(np.array(lat) * 1e3, 99))
    log(f"10M sharded: {tps:,.0f} topics/sec (S={S}, mesh={mesh_label}),"
        f" sync p99 {p99:.1f}ms @ {n} subs")
    put("tenm", tenm_sharded_topics_per_sec=round(tps),
        tenm_sharded_sync_p99_ms=round(p99, 1))


# ---------------------------------------------------------------------------
# section: churn (route updates under load — emqx_trie.erl:113-144 analogue)
# ---------------------------------------------------------------------------

def sec_churn() -> None:
    """On-device route churn (VERDICT r4 #6 / SURVEY §7 hard-part (a)):
    sustained subscribe/unsubscribe ops concurrent with windowed match
    launches at 1M filters. Reports ops/s, match-throughput degradation
    vs the quiescent rate from the SAME run, and subscribe→routable p99
    sampled under load. The reference's anchor is emqx_trie.erl's
    incremental insert/delete inside a live mnesia transaction stream."""
    import jax

    if (jax.devices()[0].platform == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU") != "1"):
        log("churn section: skipped on CPU fallback")
        return

    n = int(os.environ.get("BENCH_CHURN_FILTERS", 1_000_000))
    B = int(os.environ.get("BENCH_BATCH", 16384))
    window_n = int(os.environ.get("BENCH_WINDOW", 8))
    n_shards = int(os.environ.get("BENCH_SHARDS", 8192))
    ops_per_round = int(os.environ.get("BENCH_CHURN_OPS_PER_ROUND", 512))
    rounds = int(os.environ.get("BENCH_CHURN_ROUNDS", 60))
    rng = np.random.default_rng(11)

    t0 = time.time()
    index, model, live = build_model(n, rng, n_shards)
    log(f"churn: built+uploaded {len(index.filters)} filters in "
        f"{time.time()-t0:.0f}s")
    put("churn", churn_filters=n)

    topics = make_topics(live, rng, B, max(1000, n // 2))
    tok, lens, sysf, _ = index.tokenize(topics)
    batch = tuple(jax.device_put(x) for x in (tok, lens, sysf))
    step = model._step

    def launch():
        return step(model._trie_dev, model._rowmap_dev, model._pool_dev,
                    *batch)

    jax.block_until_ready(launch())

    # quiescent baseline from the same run/shape
    base_iters = 30
    base_tps, _ = windowed_tps(step, lambda i: (
        model._trie_dev, model._rowmap_dev, model._pool_dev, *batch),
        base_iters, window_n, B)
    log(f"churn: quiescent baseline {base_tps:,.0f} topics/sec")

    routable = make_routable(index, model,
                             "fleet/cwarm/vehicle/w/part/p0/m0")

    # churn loop: every round does ops_per_round/2 subscribes +
    # ops_per_round/2 unsubscribes (of filters added ~8 rounds ago, so
    # the table size stays ~n), one refresh (flushes the patch batch),
    # then keeps the match window full. Every 10th round also samples a
    # full subscribe→routable latency under the running window.
    added: list[tuple[str, int]] = []
    ridx = 0
    window = []
    n_ops = 0
    sub_lat = []
    t_start = time.time()
    for r in range(rounds):
        half = ops_per_round // 2
        for i in range(half):
            f = f"fleet/churn{r}/vehicle/c{i}/part/p{i % 8}/m{i % 16}"
            slot = int((r * half + i) % n_shards)
            model.subscribe(f, slot)
            added.append((f, slot))
        while len(added) > 8 * half:
            f, slot = added.pop(0)
            model.unsubscribe(f, slot)
            n_ops += 1
        model.refresh()
        n_ops += half
        if r % 10 == 5:
            # a tracked subscribe→routable sample riding the live window
            f = f"fleet/probe/vehicle/pr{r}/part/p0/m0"
            t0 = time.time()
            model.subscribe(f, 0)
            model.refresh()
            out = routable(f)
            jax.block_until_ready(out)
            sub_lat.append(time.time() - t0)
            assert int(np.sum(np.asarray(out[0])[0] >= 0)) >= 1
            added.append((f, 0))
        window.append(launch())
        if len(window) >= window_n:
            jax.block_until_ready(window.pop(0))
    for o in window:
        jax.block_until_ready(o)
    wall = time.time() - t_start
    churn_tps = rounds * B / wall
    ops_per_sec = n_ops / wall
    ratio = churn_tps / max(base_tps, 1e-9)
    sub_ms = np.array(sub_lat) * 1e3 if sub_lat else np.array([float("nan")])
    log(f"churn: {ops_per_sec:,.0f} route ops/s sustained, match "
        f"throughput {churn_tps:,.0f} topics/sec ({ratio:.2f}x quiescent), "
        f"subscribe→routable under load p50="
        f"{np.percentile(sub_ms,50):.1f}ms p99={np.percentile(sub_ms,99):.1f}ms "
        f"(patches: {model.patch_count}, uploads: {model.upload_count})")
    put("churn",
        churn_ops_per_sec=round(ops_per_sec),
        churn_match_topics_per_sec=round(churn_tps),
        churn_match_vs_quiescent=round(ratio, 2),
        churn_sub_routable_p50_ms=round(float(np.percentile(sub_ms, 50)), 2),
        churn_sub_routable_p99_ms=round(float(np.percentile(sub_ms, 99)), 2))


# ---------------------------------------------------------------------------
# sections: crossover study (C++ per-message walk vs device kernel)
# ---------------------------------------------------------------------------

CROSS_SIZES = tuple(
    int(x) for x in os.environ.get(
        "BENCH_CROSS_SIZES", "1000,100000,1000000").split(","))


def sec_xdev() -> None:
    """Device half of the crossover study (VERDICT r4 #3): the kernel's
    windowed throughput at the sub-1M table sizes (the 1M point comes
    from the kernel section itself; composed by the supervisor)."""
    import jax

    if (jax.devices()[0].platform == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU") != "1"):
        log("xdev section: skipped on CPU fallback")
        return

    B = int(os.environ.get("BENCH_BATCH", 16384))
    window_n = int(os.environ.get("BENCH_WINDOW", 8))
    iters = int(os.environ.get("BENCH_XDEV_ITERS", 40))
    for n in CROSS_SIZES[:-1]:
        rng = np.random.default_rng(100 + n % 97)
        index, model, live = build_model(n, rng, 8192)
        topics = make_topics(live, rng, B, max(1000, n // 2))
        tok, lens, sysf, _ = index.tokenize(topics)
        batch = tuple(jax.device_put(x) for x in (tok, lens, sysf))
        step = model._step
        jax.block_until_ready(step(
            model._trie_dev, model._rowmap_dev, model._pool_dev, *batch))
        tps, _ = windowed_tps(step, lambda i: (
            model._trie_dev, model._rowmap_dev, model._pool_dev, *batch),
            iters, window_n, B)
        log(f"xdev: {tps:,.0f} topics/sec @ {n} filters")
        put("xdev", **{f"dev_match_tps_{n}": round(tps)})


def sec_xcpp() -> None:
    """C++ half of the crossover study: the per-message trie walk
    (native/src/router.h SubTable::Match — the same code the epoll fast
    path runs per PUBLISH) against the same filter distribution at
    1k/100k/1M, in the emqx_broker_bench.erl:run1/4 shape (topics
    published into a wildcard-dense subscribed tree). Single core, bulk
    C call so ctypes overhead stays off the measurement."""
    from emqx_tpu import native

    if not native.available():
        log(f"xcpp: native lib unavailable: {native.build_error()}")
        return

    n_topics = int(os.environ.get("BENCH_XCPP_TOPICS", 65_536))
    for n in CROSS_SIZES:
        rng = np.random.default_rng(100 + n % 97)
        filters = build_filters(n, rng)
        tab = native.NativeSubTable()
        t0 = time.time()
        for i, f in enumerate(filters):
            tab.add(i, f)
        build_s = time.time() - t0
        live = sorted(set(filters))
        topics = make_topics(live, rng, n_topics, max(1000, n // 2))
        tab.match_many(topics[:1024])  # warm caches
        t0 = time.time()
        reps = 0
        matches = 0
        while time.time() - t0 < 2.0:
            _, m = tab.match_many(topics)
            matches += m
            reps += 1
        dt = time.time() - t0
        tps = reps * len(topics) / dt
        log(f"xcpp: {tps:,.0f} topics/sec @ {n} filters "
            f"({matches / (reps * len(topics)):.2f} matches/topic, "
            f"table build {build_s:.1f}s, single core)")
        put("xcpp", **{f"cpp_match_tps_{n}": round(tps)})
        tab.close()


# ---------------------------------------------------------------------------
# section: shared subscriptions + retained (BASELINE config 4)
# ---------------------------------------------------------------------------

def sec_shared() -> None:
    """BASELINE config 4: shared subscriptions + retained messages at
    100K groups. Measures strategy-pick dispatch throughput across the
    group table (emqx_shared_sub.erl:138-157) and wildcard retained
    lookup against a populated store (emqx_retainer_index semantics)."""
    import time as _time

    from emqx_tpu.broker.shared_sub import SharedSub
    from emqx_tpu.core.message import Message
    from emqx_tpu.services.retainer import Retainer

    n_groups = int(os.environ.get("BENCH_GROUPS", 100_000))
    members_per = int(os.environ.get("BENCH_GROUP_MEMBERS", 4))
    rng = np.random.default_rng(7)

    shared = SharedSub(node="bench", strategy="round_robin")
    t0 = _time.time()
    for g in range(n_groups):
        topic = f"fleet/f{g % 512}/group{g}/+"
        for m in range(members_per):
            shared.join(f"g{g}", topic, f"sess-{g}-{m}", node="bench")
    log(f"shared: {n_groups} groups x {members_per} members joined "
        f"in {_time.time()-t0:.1f}s")

    picks = [int(x) for x in rng.integers(0, n_groups, 50_000)]
    msg = Message(topic="x", payload=b"p")
    t0 = _time.time()
    n_dispatched = 0
    for g in picks:
        # dispatch is keyed by the subscribed FILTER (the route topic),
        # exactly as broker._route hands it over
        got = shared.dispatch(f"g{g}", f"fleet/f{g % 512}/group{g}/+",
                              msg, deliver_fn=lambda s, n: True)
        n_dispatched += len(got)
    dt = _time.time() - t0
    log(f"shared dispatch (python, per-message): "
        f"{len(picks)/dt:,.0f} dispatches/sec @ {n_groups} groups "
        f"({n_dispatched} deliveries)")
    legs = [(f"g{g}", f"fleet/f{g % 512}/group{g}/+", msg) for g in picks]
    t0 = _time.time()
    out = shared.dispatch_batch(legs)
    dt = _time.time() - t0
    log(f"shared dispatch (python, batched): "
        f"{len(legs)/dt:,.0f} dispatches/sec "
        f"({sum(o is not None for o in out)} picks)")
    # the native C++ dispatcher — the path that actually serves fully
    # native groups in the broker (host.cc SharedGroup; VERDICT r3 #7)
    from emqx_tpu import native as _native
    if _native.available():
        tab = _native.NativeSubTable()
        for g in range(n_groups):
            filt = f"fleet/f{g % 512}/group{g}/+"
            for m in range(members_per):
                tab.shared_add(g + 1, (g << 3) | m, filt)
        topics = [f"fleet/f{g % 512}/group{g}/x"
                  for g in rng.integers(0, n_groups, 500_000)]
        t0 = _time.time()
        n_t, n_picks = tab.shared_pick_many(topics)
        dt = _time.time() - t0
        log(f"shared dispatch (native C++, incl. full topic match): "
            f"{n_picks/dt:,.0f} picks/sec @ {n_groups} groups")
        put("shared", shared_native_picks_per_sec=round(n_picks / dt))
        tab.close()

    retainer = Retainer(max_retained=n_groups + 10)
    t0 = _time.time()
    for g in range(n_groups):
        retainer.store(Message(
            topic=f"fleet/f{g % 512}/group{g}/state", payload=b"s",
            flags={"retain": True}))
    log(f"retainer: {n_groups} retained in {_time.time()-t0:.1f}s")
    t0 = _time.time()
    n_cold = sum(len(retainer.match(f"fleet/f{f}/+/state"))
                 for f in range(512))
    cold_dt = _time.time() - t0
    # steady state: the per-bucket submatrix caches are warm (retained
    # dispatch on subscribe hits the same buckets continuously)
    reps = 10
    t0 = _time.time()
    n_hits = 0
    for _ in range(reps):
        for f in range(512):
            n_hits += len(retainer.match(f"fleet/f{f}/+/state"))
    dt = _time.time() - t0
    log(f"retained wildcard lookup: {reps*512/dt:,.0f} lookups/sec warm "
        f"({512/cold_dt:,.0f} cold) = {n_hits/dt:,.0f} matched msgs/sec "
        f"(~{n_hits//(512*reps)} matches per lookup @ {n_groups} "
        f"retained; vectorized store, VERDICT r3 #5)")
    put("shared",
        retained_lookups_per_sec=round(reps * 512 / dt),
        retained_lookups_per_sec_cold=round(512 / cold_dt))


# ---------------------------------------------------------------------------
# section: host plane (C++ epoll data plane; CPU by design)
# ---------------------------------------------------------------------------

def sec_host() -> None:
    """VERDICT r3 #1 before/after: the round-3 configuration (asyncio
    server, Python clients — measured 14k msg/s host path, 5.5k e2e)
    against the round-4 C++ data plane (epoll host with the native
    PUBLISH fast path, driven by the C++ loadgen — the emqtt-bench
    analogue; a Python client fleet would measure itself, not the
    broker). Reference anchor: 1M msg/s sustained (README.md:16),
    sub-ms latency.

    NOTE for readers of CPU-fallback artifacts: every number in this
    section measures the C++ data plane on the host CPU BY DESIGN — a
    device fallback upstream does not change what it measures."""
    import asyncio

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    n_msg_before = int(os.environ.get("BENCH_HOST_BEFORE_MSGS", 1500))
    n_msg_blast = int(os.environ.get("BENCH_HOST_BLAST_MSGS", 40000))

    # -- before: asyncio server + python clients (the r3 shape) -------------
    async def drive_python_clients(port) -> float:
        subs = [MqttClient(port=port, clientid=f"ns{i}") for i in range(8)]
        for i, s in enumerate(subs):
            await s.connect()
            await s.subscribe(f"lg/{i}/+", qos=0)
        pubs = [MqttClient(port=port, clientid=f"np{i}") for i in range(8)]
        for p in pubs:
            await p.connect()
        expected = 8 * n_msg_before
        got = 0
        done = asyncio.Event()

        async def drain(s):
            nonlocal got
            while got < expected:
                try:
                    await s.recv(timeout=10)
                except asyncio.TimeoutError:
                    break
                got += 1
                if got >= expected:
                    done.set()
        drains = [asyncio.create_task(drain(s)) for s in subs]

        async def blast(i, p):
            for j in range(n_msg_before):
                await p.publish(f"lg/{(i + j) % 8}/m", b"x" * 16, qos=0)
        t0 = time.time()
        await asyncio.gather(*(blast(i, p) for i, p in enumerate(pubs)))
        try:
            await asyncio.wait_for(done.wait(), timeout=60)
        except asyncio.TimeoutError:
            pass
        wall = time.time() - t0
        for d in drains:
            d.cancel()
        for c in subs + pubs:
            try:
                await c.disconnect()
            except Exception:
                pass
        return got / wall

    async def run_before() -> float:
        server = BrokerServer(port=0, app=BrokerApp())
        await server.start()
        try:
            return await drive_python_clients(server.port)
        finally:
            await server.stop()

    before = asyncio.run(run_before())
    log(f"host plane BEFORE (asyncio + python clients, qos0): "
        f"{before:,.0f} msg/s")
    put("host", e2e_host_before_msgs_per_sec=round(before))

    # -- after: C++ epoll host + native fast path + C++ loadgen -------------
    # mqtt.max_inflight is a zone knob (emqx_schema default 32): the
    # reference's 1M msg/s runs tune it up, and the windowed qos1/2
    # sweep measures the broker, not a 16-slot default window — so the
    # bench app raises it (the native/python planes split this budget
    # dynamically per ack cycle, see native_server._on_ack_batch)
    server = NativeBrokerServer(port=0, app=BrokerApp(),
                                session_opts={"max_inflight": 1024})
    server.start()
    try:
        blast = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast, qos=0, payload_len=16)
        wall = blast["wall_ns"] / 1e9
        blast_rate = blast["received"] / max(wall, 1e-9)
        log(f"host plane AFTER (C++ fast path, blast qos0): "
            f"{blast['received']}/{blast['sent']} in {wall:.2f}s = "
            f"{blast_rate:,.0f} msg/s  ({blast_rate / max(before, 1):,.0f}x "
            f"before, {blast_rate / 1e6:.2f}x the reference's 1M/s headline)")
        put("host", e2e_host_msgs_per_sec=round(blast_rate))

        lat = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=3000, qos=0, payload_len=16, window=64)
        lat_wall = lat["wall_ns"] / 1e9
        log(f"host plane latency (windowed 64, qos0): "
            f"{lat['received'] / max(lat_wall, 1e-9):,.0f} msg/s  "
            f"p50={lat['p50_ns'] / 1e6:.3f}ms p99={lat['p99_ns'] / 1e6:.3f}ms")
        put("host",
            e2e_host_p50_ms=round(lat["p50_ns"] / 1e6, 3),
            e2e_host_p99_ms=round(lat["p99_ns"] / 1e6, 3))

        # qos1 window sweep (VERDICT r4 #8 / r5 next #10): at a fixed
        # service rate the p99 is dominated by Little's-law queueing
        # (window / rate). Every point lands suffixed; the UNSUFFIXED
        # headline is the best rate among points meeting the 2ms p99
        # budget (the VERDICT #10 acceptance shape) — or, when no point
        # qualifies (e.g. a starved CI box), the max-rate point with
        # its honest p99.
        best = None          # (rate, p99_ms) best under the 2ms budget
        peak = None          # max-rate fallback
        for win in (256, 512, 1024, 2048, 4096):
            q1 = native.loadgen_run(
                "127.0.0.1", server.port, n_subs=8, n_pubs=8,
                msgs_per_pub=n_msg_blast // 2, qos=1, payload_len=16,
                window=win)
            q1_wall = q1["wall_ns"] / 1e9
            q1_rate = q1["received"] / max(q1_wall, 1e-9)
            q1_p99 = q1["p99_ns"] / 1e6
            log(f"host plane qos1 (windowed {win}): {q1_rate:,.0f} msg/s "
                f"acks={q1['acks']} p99={q1_p99:.2f}ms")
            if q1_p99 <= 2.0 and (best is None or q1_rate > best[0]):
                best = (q1_rate, q1_p99)
            if peak is None or q1_rate > peak[0]:
                peak = (q1_rate, q1_p99)
            # headline keys ride EVERY flush (running best-so-far): a
            # deadline kill mid-sweep must still leave a headline in
            # the artifact, not just suffixed points
            head = best or peak
            put("host", **{
                f"e2e_host_qos1_msgs_per_sec_w{win}": round(q1_rate),
                f"e2e_host_qos1_p99_ms_w{win}": round(q1_p99, 3),
                "e2e_host_qos1_msgs_per_sec": round(head[0]),
                "e2e_host_qos1_p99_ms": round(head[1], 3),
                "e2e_host_qos1_within_p99_budget": bool(best)})
        head = best or peak
        log(f"host plane qos1 headline: {head[0]:,.0f} msg/s "
            f"p99={head[1]:.2f}ms"
            + ("" if best else "  (NO point met the 2ms budget)"))

        # qos2 e2e (round 6): the native exactly-once plane. Prior
        # rounds ran qos2 entirely in Python (~5k msg/s, VERDICT r5
        # missing #2); the four-packet exchange now lives in C++
        # (host.cc awaiting-rel bitmap + PUBREC/PUBREL/PUBCOMP), so
        # qos2_fast_in must move and the rate must sit well above the
        # Python plane's ceiling.
        q2 = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast // 4, qos=2, payload_len=16,
            window=1024)
        q2_wall = q2["wall_ns"] / 1e9
        q2_rate = q2["received"] / max(q2_wall, 1e-9)
        st = server.fast_stats()
        log(f"host plane qos2 (windowed 1024): {q2_rate:,.0f} msg/s "
            f"p99={q2['p99_ns'] / 1e6:.2f}ms "
            f"qos2_fast_in={st['qos2_in']} qos2_rel={st['qos2_rel']} "
            f"({q2_rate / 5311:.0f}x the r05 python-only qos2 rate)")
        put("host",
            e2e_host_qos2_msgs_per_sec=round(q2_rate),
            e2e_host_qos2_p99_ms=round(q2["p99_ns"] / 1e6, 3),
            qos2_fast_in=st["qos2_in"],
            qos2_rel_native=st["qos2_rel"])
        # broker-side stage percentiles, cumulative across this
        # server's blast/latency/qos1-sweep/qos2 runs (ingress→route,
        # route→flush, qos1/qos2 ack RTT, GIL stint)
        summ = put_broker_hists("host", server, "broker")
        for stage in ("ingress_route", "qos1_rtt", "qos2_rtt"):
            if stage in summ:
                s = summ[stage]
                log(f"broker-side {stage}: p50={s['p50_us']:.1f}us "
                    f"p99={s['p99_us']:.1f}us p999={s['p999_us']:.1f}us "
                    f"(n={s['count']})")
        log(f"fast stats: {st}")
    finally:
        server.stop()

    # -- broad-rule cliff (VERDICT r4 #5) -----------------------------------
    # One FROM '#' console rule used to de-permit the entire fast path
    # (→ ~13k msg/s, a 130x cliff). With rule taps the ruled plane must
    # retain the bulk of the fast-path rate while the rule's copies
    # flow to the runtime (bounded queue; overload counts tap_dropped).
    app2 = BrokerApp()
    app2.rules.create_rule("bench_all", 'SELECT topic FROM "#"',
                           [{"function": "console", "args": {}}])
    server = NativeBrokerServer(port=0, app=app2)
    server.start()
    try:
        rb = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast, qos=0, payload_len=16)
        rb_wall = rb["wall_ns"] / 1e9
        rb_rate = rb["received"] / max(rb_wall, 1e-9)
        st = server.fast_stats()
        rule_m = app2.rules.metrics.get("bench_all", "matched")
        log(f"host plane qos0 with ONE 'FROM \"#\"' rule (taps): "
            f"{rb_rate:,.0f} msg/s ({rb_rate / max(blast_rate, 1):.2f}x "
            f"the rule-free rate) taps={st['taps']} "
            f"rule_matched={rule_m} tap_dropped={server.tap_dropped}")
        put("host",
            rule_tap_msgs_per_sec=round(rb_rate),
            rule_tap_vs_free=round(rb_rate / max(blast_rate, 1), 2),
            rule_tap_dropped=server.tap_dropped)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# section: ws (MQTT-over-WebSocket on the native plane; CPU by design)
# ---------------------------------------------------------------------------

def sec_ws() -> None:
    """Round-7 tentpole before/after: the asyncio WS plane (ws.py —
    every WS client inherited the ~14k msg/s GIL ceiling while native
    TCP did 1.7M) against RFC6455 in the C++ host (ws.h + host.cc),
    driven by the loadgen's ws mode (masked frames, nonzero keys, so
    the broker pays the real unmask cost). Acceptance (ISSUE 2):
    native-WS >= 0.5x the native-TCP blast on the same box and >= 10x
    the asyncio WS plane."""
    import asyncio
    import base64

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.broker.ws import (OP_BINARY, FrameDecoder,
                                    WsBrokerServer, encode_frame)
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.mqtt.frame import Parser, serialize

    n_msg_before = int(os.environ.get("BENCH_WS_BEFORE_MSGS", 1200))
    n_msg_blast = int(os.environ.get("BENCH_WS_BLAST_MSGS", 40000))

    # -- before: asyncio WS listener + python ws clients --------------------
    class _WsClient:
        def __init__(self, port):
            self.port = port
            self.dec = FrameDecoder(require_mask=False)
            self.parser = Parser()
            self.inbox: list = []

        async def connect(self, cid):
            self.r, self.w = await asyncio.open_connection(
                "127.0.0.1", self.port)
            key = base64.b64encode(os.urandom(16)).decode()
            self.w.write((
                "GET /mqtt HTTP/1.1\r\nHost: x\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Protocol: mqtt\r\n\r\n").encode())
            await self.r.readuntil(b"\r\n\r\n")
            await self.send(P.Connect(clientid=cid))
            await self.recv()
            return self

        async def send(self, pkt):
            self.w.write(encode_frame(
                OP_BINARY, serialize(pkt, P.MQTT_V4), mask=True))
            await self.w.drain()

        async def recv(self, timeout=10):
            while not self.inbox:
                data = await asyncio.wait_for(self.r.read(65536), timeout)
                assert data
                for op, payload in self.dec.feed(data):
                    if op == OP_BINARY:
                        self.inbox.extend(self.parser.feed(payload))
            return self.inbox.pop(0)

    async def run_before() -> float:
        server = WsBrokerServer(port=0, app=BrokerApp())
        await server.start()
        try:
            subs = [await _WsClient(server.port).connect(f"ws{i}")
                    for i in range(8)]
            for i, s in enumerate(subs):
                await s.send(P.Subscribe(packet_id=1,
                                         topic_filters=[(f"lg/{i}/+",
                                                         {"qos": 0})]))
                await s.recv()
            pubs = [await _WsClient(server.port).connect(f"wp{i}")
                    for i in range(8)]
            expected = 8 * n_msg_before
            got = 0
            done = asyncio.Event()

            async def drain(s):
                nonlocal got
                while got < expected:
                    try:
                        await s.recv(timeout=10)
                    except asyncio.TimeoutError:
                        break
                    got += 1
                    if got >= expected:
                        done.set()
            drains = [asyncio.create_task(drain(s)) for s in subs]

            async def blast(i, p):
                for j in range(n_msg_before):
                    await p.send(P.Publish(topic=f"lg/{(i + j) % 8}/m",
                                           payload=b"x" * 16, qos=0))
            t0 = time.time()
            await asyncio.gather(*(blast(i, p) for i, p in enumerate(pubs)))
            try:
                await asyncio.wait_for(done.wait(), timeout=60)
            except asyncio.TimeoutError:
                pass
            wall = time.time() - t0
            for d in drains:
                d.cancel()
            for c in subs + pubs:
                c.w.close()
            return got / wall
        finally:
            await server.stop()

    before = asyncio.run(run_before())
    log(f"ws plane BEFORE (asyncio + python ws clients, qos0): "
        f"{before:,.0f} msg/s")
    put("ws", ws_asyncio_msgs_per_sec=round(before))

    # -- after: C++ RFC6455 listener + ws loadgen ---------------------------
    server = NativeBrokerServer(port=0, app=BrokerApp(), ws_port=0,
                                session_opts={"max_inflight": 1024})
    server.start()
    try:
        # same-box native-TCP anchor (the ws_vs_native_tcp denominator
        # must come from THIS box/run, not a stale artifact)
        tcp = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast, qos=0, payload_len=16)
        tcp_rate = tcp["received"] / max(tcp["wall_ns"] / 1e9, 1e-9)

        ws = native.loadgen_run(
            "127.0.0.1", server.ws_port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast, qos=0, payload_len=16, ws=True)
        ws_wall = ws["wall_ns"] / 1e9
        ws_rate = ws["received"] / max(ws_wall, 1e-9)
        log(f"ws plane AFTER (C++ RFC6455 + fast path, blast qos0): "
            f"{ws['received']}/{ws['sent']} in {ws_wall:.2f}s = "
            f"{ws_rate:,.0f} msg/s  ({ws_rate / max(before, 1):,.0f}x "
            f"asyncio-ws, {ws_rate / max(tcp_rate, 1):.2f}x native-tcp "
            f"same box)")
        put("ws",
            ws_native_msgs_per_sec=round(ws_rate),
            ws_vs_native_tcp=round(ws_rate / max(tcp_rate, 1), 2),
            ws_vs_asyncio=round(ws_rate / max(before, 1), 1))

        lat = native.loadgen_run(
            "127.0.0.1", server.ws_port, n_subs=8, n_pubs=8,
            msgs_per_pub=3000, qos=0, payload_len=16, window=64, ws=True)
        log(f"ws plane latency (windowed 64, qos0): "
            f"p50={lat['p50_ns'] / 1e6:.3f}ms "
            f"p99={lat['p99_ns'] / 1e6:.3f}ms")
        put("ws",
            ws_native_p50_ms=round(lat["p50_ns"] / 1e6, 3),
            ws_native_p99_ms=round(lat["p99_ns"] / 1e6, 3))

        q1 = native.loadgen_run(
            "127.0.0.1", server.ws_port, n_subs=8, n_pubs=8,
            msgs_per_pub=n_msg_blast // 4, qos=1, payload_len=16,
            window=1024, ws=True)
        q1_rate = q1["received"] / max(q1["wall_ns"] / 1e9, 1e-9)
        st = server.fast_stats()
        log(f"ws plane qos1 (windowed 1024): {q1_rate:,.0f} msg/s "
            f"acks={q1['acks']} p99={q1['p99_ns'] / 1e6:.2f}ms  "
            f"ws_handshakes={st['ws_handshakes']}")
        put("ws",
            ws_native_qos1_msgs_per_sec=round(q1_rate),
            ws_native_qos1_p99_ms=round(q1["p99_ns"] / 1e6, 3),
            ws_handshakes=st["ws_handshakes"])
        # broker-side stages incl. ws_ingest (what RFC6455 adds per
        # read chunk on top of the shared TCP fast path)
        put_broker_hists("ws", server, "ws_broker")
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# section: observe_overhead (telemetry plane cost; CPU by design)
# ---------------------------------------------------------------------------

def _observe_overhead_kernel() -> None:
    """Kernel-counters overhead pair (round 19): publish_batch
    submit→collect throughput with in-kernel counters + the host fold
    ON vs OFF. Same interleaved alternating-order best-of-N convention
    as the native pairs — the two models differ ONLY by the
    kernel_telemetry flag (the EMQX_TPU_KERNEL_TELEMETRY switch)."""
    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.observe.device_metrics import DeviceMetricsFold
    from emqx_tpu.observe.metrics import Metrics as _Metrics
    from emqx_tpu.router.index import TrieIndex

    n_filters = int(os.environ.get("BENCH_OBS_KERNEL_FILTERS", 20000))
    B = int(os.environ.get("BENCH_OBS_KERNEL_BATCH", 2048))
    n_batches = int(os.environ.get("BENCH_OBS_KERNEL_BATCHES", 20))
    reps = int(os.environ.get("BENCH_OBS_REPS", 3))
    rng = np.random.default_rng(7)
    filters = build_filters(n_filters, rng)
    n_vehicles = max(1000, n_filters // 2)

    models = {}
    for arm, flag in (("on", True), ("off", False)):
        index = TrieIndex(max_levels=8)
        model = RouterModel(index, n_sub_slots=64, K=32, M=128,
                            kernel_telemetry=flag)
        index.load(filters)
        for fid in range(len(index.filters)):
            if index.filters[fid] is not None:
                model._subs.setdefault(fid, {})[fid % 64] = 1
        model.refresh()
        model._host_matcher = None    # force the device path on cpu
        if flag:
            model.telemetry = DeviceMetricsFold(_Metrics(), model=model)
        models[arm] = model

    live = [f for f in filters]
    topic_sets = [make_topics(live, rng, B, n_vehicles)
                  for _ in range(4)]
    for model in models.values():      # compile off the clock
        model.publish_batch_collect(
            model.publish_batch_submit(topic_sets[0]))

    best = {"on": 0.0, "off": 0.0}
    for rep in range(reps):
        arms = ("on", "off") if rep % 2 == 0 else ("off", "on")
        for arm in arms:
            model = models[arm]
            t0 = time.time()
            for i in range(n_batches):
                model.publish_batch_collect(
                    model.publish_batch_submit(
                        topic_sets[i % len(topic_sets)]))
            rate = n_batches * B / (time.time() - t0)
            best[arm] = max(best[arm], rate)
            log(f"observe_overhead rep{rep} kernel_counters={arm}: "
                f"{rate:,.0f} topics/s")
    overhead = 1.0 - best["on"] / max(best["off"], 1e-9)
    log(f"observe_overhead kernel counters: on={best['on']:,.0f} "
        f"off={best['off']:,.0f} topics/s  "
        f"overhead={overhead * 100:.2f}% "
        f"({'within' if overhead < 0.02 else 'OVER'} the 2% budget)")
    put("observe_overhead",
        kernel_counters_on_topics_per_sec=round(best["on"]),
        kernel_counters_off_topics_per_sec=round(best["off"]),
        kernel_counters_overhead_frac=round(overhead, 4),
        kernel_counters_within_2pct_budget=bool(overhead < 0.02))


def sec_observe_overhead() -> None:
    """ISSUE 3 acceptance: the native telemetry plane (histograms +
    flight recorders + kind-8 export) must cost < 2% QoS0 native-TCP
    throughput against the EMQX_NATIVE_TELEMETRY=0 escape hatch.
    Best-of-3 per arm, interleaved, same box — the arms differ ONLY by
    the telemetry toggle (NativeBrokerServer(telemetry=...), the same
    switch the env var drives).

    ISSUE 8 acceptance: a second interleaved pair on the 2-SHARD qos0
    fan-out measures the distributed-tracing sampler — sampled tracing
    ON (1-in-64, the production default) vs OFF must also land within
    the 2% budget.

    ISSUE 19 acceptance: a third interleaved pair on the DEVICE router
    path measures the in-kernel counters + host fold
    (kernel_telemetry=True with a DeviceMetricsFold attached vs False)
    — the counters ride the existing collect device_get, so they must
    also land within the 2% budget. Model-plane only: runs even when
    the native host is unavailable."""
    _observe_overhead_kernel()

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer

    n_msg = int(os.environ.get("BENCH_OBS_MSGS", 40000))
    reps = int(os.environ.get("BENCH_OBS_REPS", 3))
    best = {"on": 0.0, "off": 0.0}
    for rep in range(reps):
        # alternate the pair order per rep (round 13): on a warming box
        # the SECOND arm of every pair wins systematically, and that
        # drift measured bigger than the effect under test
        arms = ("on", "off") if rep % 2 == 0 else ("off", "on")
        for arm in arms:                 # interleaved: drift hits both
            server = NativeBrokerServer(
                port=0, app=BrokerApp(), telemetry=(arm == "on"),
                session_opts={"max_inflight": 1024})
            server.start()
            try:
                r = native.loadgen_run(
                    "127.0.0.1", server.port, n_subs=8, n_pubs=8,
                    msgs_per_pub=n_msg, qos=0, payload_len=16)
                rate = r["received"] / max(r["wall_ns"] / 1e9, 1e-9)
                best[arm] = max(best[arm], rate)
                log(f"observe_overhead rep{rep} telemetry={arm}: "
                    f"{rate:,.0f} msg/s")
            finally:
                server.stop()
    overhead = 1.0 - best["on"] / max(best["off"], 1e-9)
    log(f"observe_overhead: on={best['on']:,.0f} off={best['off']:,.0f} "
        f"msg/s  overhead={overhead * 100:.2f}% "
        f"({'within' if overhead < 0.02 else 'OVER'} the 2% budget)")

    # -- tracing arm (ISSUE 8): 1-in-64 sampler on the 2-shard fan-out.
    # Two poll threads + the loadgen fleet oversubscribe the 2-core
    # container far harder than the single-host pair above, so this
    # pair runs a smaller fleet (4x4) and more interleaved reps — the
    # best-of convention needs both arms to find their scheduling peak.
    tbest = {"on": 0.0, "off": 0.0}
    tspans = 0
    treps = max(reps, int(os.environ.get("BENCH_OBS_TRACE_REPS", 5)))
    for rep in range(treps):
        # alternate the pair order per rep: on a warming box the SECOND
        # arm of every pair otherwise wins systematically (measured —
        # the drift was bigger than the effect under test)
        arms = ("on", "off") if rep % 2 == 0 else ("off", "on")
        for arm in arms:
            server = NativeBrokerServer(
                port=0, app=BrokerApp(), shards=2,
                tracing=(arm == "on"), trace_sample_shift=6,
                session_opts={"max_inflight": 1024})
            server.start()
            try:
                r = native.loadgen_run(
                    "127.0.0.1", server.port, n_subs=4, n_pubs=4,
                    msgs_per_pub=n_msg, qos=0, payload_len=16)
                rate = r["received"] / max(r["wall_ns"] / 1e9, 1e-9)
                tbest[arm] = max(tbest[arm], rate)
                if arm == "on":
                    tspans = max(tspans,
                                 server.fast_stats()["traced_pubs"])
                log(f"observe_overhead rep{rep} tracing={arm} "
                    f"(2 shards): {rate:,.0f} msg/s")
            finally:
                server.stop()
    t_overhead = 1.0 - tbest["on"] / max(tbest["off"], 1e-9)
    log(f"observe_overhead tracing (2-shard qos0 fan-out): "
        f"on={tbest['on']:,.0f} off={tbest['off']:,.0f} msg/s  "
        f"overhead={t_overhead * 100:.2f}% sampled={tspans} "
        f"({'within' if t_overhead < 0.02 else 'OVER'} the 2% budget)")
    put("observe_overhead",
        qos0_msgs_per_sec_telemetry_on=round(best["on"]),
        qos0_msgs_per_sec_telemetry_off=round(best["off"]),
        overhead_frac=round(overhead, 4),
        within_2pct_budget=bool(overhead < 0.02),
        shard2_qos0_msgs_per_sec_tracing_on=round(tbest["on"]),
        shard2_qos0_msgs_per_sec_tracing_off=round(tbest["off"]),
        tracing_overhead_frac=round(t_overhead, 4),
        tracing_sampled_pubs=int(tspans),
        tracing_within_2pct_budget=bool(t_overhead < 0.02))


# ---------------------------------------------------------------------------
# section: conn_scale (C10M axis: the million-connection broker; CPU by
# design — the plane under test is the C++ epoll host)
# ---------------------------------------------------------------------------

def _rss_bytes() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) * 1024
    return 0


def _malloc_trim() -> None:
    import ctypes
    try:
        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except OSError:
        pass


def sec_conn_scale() -> None:
    """ISSUE 12 acceptance: the conn-scale plane (wheel.h + park.h).

    Arm A (real sockets, full broker): a connect storm of mostly-idle
    clients against a NativeBrokerServer, held with staggered
    keepalives while a small loadgen fleet measures fan-out throughput
    — the gate is fan-out within 10% of the unloaded number while the
    herd idles, keepalive p99 honored (ping RTT p99 + zero broker
    closes), and measured RSS/conn. The herd size is fd-capped: this
    container pins RLIMIT_NOFILE at 20k (hard), so the in-process
    ceiling is ~9k conn PAIRS — recorded in the artifact.

    Arm B (raw host, synthetic sockets): the conn-scale structures at
    the ROADMAP's 1M scale. emqx_host_synth_conns drives 10^6 conns
    through the REAL admission + park machinery (fd-less conns whose
    egress is discarded), measuring resident vs parked RSS/conn, the
    parked-record gauge, and the housekeep cost with 1M armed timers —
    against a projection of the old O(N) per-housekeep sweep."""
    import resource
    import threading
    import ctypes as ct

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    put("conn_scale", conn_scale_fd_limit=soft)
    n_real = int(os.environ.get("BENCH_CONN_REAL_N",
                                max(1000, min(8000, (soft - 2000) // 2))))
    n_synth = int(os.environ.get("BENCH_CONN_SYNTH_N", 1_000_000))

    # -- arm A: real sockets through the full broker --------------------
    server = NativeBrokerServer(port=0, app=BrokerApp(),
                                park_after_ms=3000, accept_burst=512)
    server.start()
    try:
        fan_args = dict(n_subs=4, n_pubs=4, msgs_per_pub=int(
            os.environ.get("BENCH_CONN_FAN_MSGS", 8000)),
            qos=0, payload_len=16, window=0, warmup=True, salt=700000)
        reps = int(os.environ.get("BENCH_CONN_FAN_REPS", 3))

        def fan_best() -> float:
            # best-of-N: this box's identical-config throughput swings
            # more than the 10% under test (the round-13 lesson), so
            # each arm reports its PEAK capacity
            best = 0.0
            for _ in range(reps):
                r = native.loadgen_run("127.0.0.1", server.port,
                                       **fan_args)
                best = max(best,
                           r["received"] / max(r["wall_ns"], 1) * 1e9)
            return best

        base_rate = fan_best()
        put("conn_scale",
            conn_scale_fanout_unloaded_msgs_per_sec=round(base_rate))

        rss0 = _rss_bytes()
        stop = ct.c_int32(0)
        live = (ct.c_uint64 * 4)()
        herd_out = {}

        def herd():
            herd_out.update(native.loadgen_conn_scale(
                "127.0.0.1", server.port, n_real, burst=256,
                keepalive_s=20, sub_every=10, hold_ms=600_000,
                stop=stop, live=live))

        t_conn0 = time.time()
        ht = threading.Thread(target=herd, daemon=True)
        ht.start()
        deadline = time.time() + 240
        while time.time() < deadline and live[0] < n_real * 0.99:
            time.sleep(0.25)
        connected = int(live[0])
        storm_s = time.time() - t_conn0
        put("conn_scale", conn_scale_real_n=connected,
            conn_scale_connect_per_sec=round(connected /
                                             max(storm_s, 1e-9)))
        rss_resident = _rss_bytes()
        put("conn_scale",
            conn_scale_real_resident_bytes_per_conn=round(
                (rss_resident - rss0) / max(connected, 1)))
        # let the herd hibernate (park horizon 3s; pings ride the
        # parked fast path so the herd STAYS parked)
        t0 = time.time()
        while time.time() - t0 < 60:
            if server.fast_stats()["conns_parked"] >= connected * 0.9:
                break
            time.sleep(0.5)
        parked_events = server.fast_stats()["conns_parked"]
        _malloc_trim()
        rss_parked = _rss_bytes()
        put("conn_scale", conn_scale_real_parked_events=parked_events,
            conn_scale_real_parked_rss_delta_bytes_per_conn=round(
                (rss_parked - rss0) / max(connected, 1)))
        # fan-out with >= 99% of conns idle-parked (same best-of-N)
        loaded_rate = fan_best()
        ratio = loaded_rate / max(base_rate, 1e-9)
        stop.value = 1
        ht.join(timeout=60)
        p99_ms = herd_out.get("ping_p99_ns", 0) / 1e6
        put("conn_scale",
            conn_scale_fanout_with_herd_msgs_per_sec=round(loaded_rate),
            conn_scale_fanout_ratio_real_sockets=round(ratio, 3),
            conn_scale_ping_p50_ms=round(
                herd_out.get("ping_p50_ns", 0) / 1e6, 2),
            conn_scale_ping_p99_ms=round(p99_ms, 2),
            conn_scale_pings=int(herd_out.get("pings", 0)),
            conn_scale_herd_errors=int(herd_out.get("errors", 0)),
            conn_scale_broker_closes=int(
                herd_out.get("broker_closes", 0)),
            conn_scale_keepalive_honored=bool(
                p99_ms < 1000.0
                and herd_out.get("broker_closes", 1) == 0),
            conn_scale_parked_pings=server.fast_stats()["parked_pings"])
        # the PLANE's own fan-out tax, isolated: a 100k synthetic herd
        # parks on the SAME broker (no kernel sockets, no Python conn
        # objects — exactly the structures this PR added) and the
        # fan-out reruns. The real-socket ratio above additionally
        # carries the herd client sharing this 1-core box and the
        # kernel-socket + Python-object footprint (the documented
        # carried edge); the gate isolates the new subsystem.
        t0 = time.time()
        while time.time() - t0 < 20 and len(server.conns) > 16:
            time.sleep(0.25)   # real herd teardown drains
        base2 = fan_best()
        server.hosts[0].synth_conns(100_000, keepalive_ms=0,
                                    sub_every=10,
                                    topic_prefix="synthherd")
        t0 = time.time()
        want = server.fast_stats()["conns_parked"] + 99_000
        while time.time() - t0 < 60:
            if server.fast_stats()["conns_parked"] >= want:
                break
            time.sleep(0.25)
        loaded2 = fan_best()
        ratio2 = loaded2 / max(base2, 1e-9)
        put("conn_scale",
            conn_scale_synth_herd_on_broker=100_000,
            conn_scale_fanout_unloaded2_msgs_per_sec=round(base2),
            conn_scale_fanout_with_synth_herd_msgs_per_sec=round(
                loaded2),
            conn_scale_fanout_ratio=round(ratio2, 3),
            conn_scale_fanout_within_10pct=bool(ratio2 >= 0.9))
    finally:
        server.stop()

    # -- arm B: the 1M herd on a raw host -------------------------------
    host = native.NativeHost(port=0, max_size=4096)
    try:
        _malloc_trim()
        rss0 = _rss_bytes()
        chunk = 100_000
        t0 = time.time()
        done = 0
        while done < n_synth:
            host.synth_conns(min(chunk, n_synth - done),
                             keepalive_ms=3_600_000, sub_every=20,
                             topic_prefix="herd1m")
            done += chunk
            list(host.poll(0))
        cc = host.conn_counts()
        rss_resident = _rss_bytes()
        put("conn_scale", conn_scale_synth_n=int(cc["resident"]),
            conn_scale_synth_create_s=round(time.time() - t0, 1),
            conn_scale_synth_resident_bytes_per_conn=round(
                (rss_resident - rss0) / max(cc["resident"], 1)))
        # the old housekeep shape: one conn_idle_ms probe per conn per
        # tick — measure a 100k slice and project to the full herd
        t0 = time.time()
        probe_n = 100_000
        for cid in range(1, probe_n + 1):
            host.conn_idle_ms(cid)
        sweep_ms = (time.time() - t0) * 1000 * (n_synth / probe_n)
        # hibernate the herd through the real park machinery
        host.set_park(True, park_after_ms=100)
        t0 = time.time()
        while time.time() - t0 < 300:
            list(host.poll(0))
            cc = host.conn_counts()
            if cc["parked"] >= n_synth * 0.999:
                break
        park_s = time.time() - t0
        _malloc_trim()
        rss_parked = _rss_bytes()
        cc = host.conn_counts()
        # idle housekeep cost with the full herd parked + 1M armed
        # keepalive timers: the wheel pays O(expired)
        t0 = time.time()
        cycles = 200
        for _ in range(cycles):
            list(host.poll(0))
        cycle_us = (time.time() - t0) * 1e6 / cycles
        put("conn_scale",
            conn_scale_parked_n=int(cc["parked"]),
            conn_scale_park_drain_s=round(park_s, 1),
            conn_scale_parked_record_bytes_per_conn=round(
                cc["parked_bytes"] / max(cc["parked"], 1)),
            conn_scale_parked_rss_bytes_per_conn=round(
                (rss_parked - rss0) / max(cc["parked"], 1)),
            conn_scale_timers_armed=int(cc["timers_armed"]),
            conn_scale_idle_cycle_us_at_1m_parked=round(cycle_us, 1),
            conn_scale_old_sweep_projection_ms=round(sweep_ms, 1),
            # the acceptance claim: housekeep no longer scales O(N)
            # with parked conns — an idle cycle over the parked
            # million costs ~3 orders less than one old-style sweep
            conn_scale_housekeep_o_expired=bool(
                cycle_us / 1000.0 < sweep_ms / 100.0))
    finally:
        host.destroy()


# ---------------------------------------------------------------------------
# section: fault_overhead (faultline disarmed cost; CPU by design)
# ---------------------------------------------------------------------------

_FAULT_ARM_SRC = r"""
import sys
sys.path.insert(0, %(repo)r)
from emqx_tpu import native
from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.native_server import NativeBrokerServer

server = NativeBrokerServer(port=0, app=BrokerApp(),
                            session_opts={"max_inflight": 1024})
server.start()
r = native.loadgen_run("127.0.0.1", server.port, n_subs=8, n_pubs=8,
                       msgs_per_pub=%(n_msg)d, qos=0, payload_len=16)
print("RATE", r["received"] / max(r["wall_ns"] / 1e9, 1e-9), flush=True)
server.stop()
"""


def sec_fault_overhead() -> None:
    """ISSUE 11 acceptance: disarmed fault sites are FREE — the qos0
    fan-out with the faultline-compiled binary lands within the 2%
    noise budget of a -DEMQX_NO_FAULTLINE build (every site compiled
    out; EMQX_NATIVE_NOFAULT=1 selects it). Each arm runs the broker +
    loadgen in a SUBPROCESS so the two .so variants never share a
    process; interleaved best-of-N with alternating pair order (the
    round-13 warm-box discipline)."""
    import subprocess as sp

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return
    repo = os.path.dirname(os.path.abspath(__file__))
    n_msg = int(os.environ.get("BENCH_FAULT_MSGS", 40000))
    reps = int(os.environ.get("BENCH_FAULT_REPS", 3))
    src = _FAULT_ARM_SRC % {"repo": repo, "n_msg": n_msg}
    best = {"faultline": 0.0, "nofault": 0.0}
    for rep in range(reps):
        arms = (("faultline", "nofault") if rep % 2 == 0
                else ("nofault", "faultline"))
        for arm in arms:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            if arm == "nofault":
                env["EMQX_NATIVE_NOFAULT"] = "1"
            else:
                env.pop("EMQX_NATIVE_NOFAULT", None)
            p = sp.run([sys.executable, "-c", src], env=env,
                       capture_output=True, text=True, timeout=300)
            rate = 0.0
            for line in p.stdout.splitlines():
                if line.startswith("RATE "):
                    rate = float(line.split()[1])
            if rate <= 0:
                log(f"fault_overhead rep{rep} {arm}: FAILED "
                    f"{p.stderr[-500:]}")
                continue
            best[arm] = max(best[arm], rate)
            log(f"fault_overhead rep{rep} {arm}: {rate:,.0f} msg/s")
    if best["faultline"] <= 0 or best["nofault"] <= 0:
        # a dead arm must never read as a budget pass: with the
        # baseline at 0 the overhead goes hugely negative and
        # "< 2%" would be a false green on a run that measured nothing
        log(f"fault_overhead: arm(s) produced no rate "
            f"(faultline={best['faultline']:,.0f} "
            f"compiled-out={best['nofault']:,.0f}) — no verdict")
        put("fault_overhead",
            qos0_msgs_per_sec_faultline=round(best["faultline"]),
            qos0_msgs_per_sec_compiled_out=round(best["nofault"]),
            within_2pct_budget=False, failed_arm=True)
        return
    overhead = 1.0 - best["faultline"] / best["nofault"]
    log(f"fault_overhead: faultline={best['faultline']:,.0f} "
        f"compiled-out={best['nofault']:,.0f} msg/s  "
        f"overhead={overhead * 100:.2f}% "
        f"({'within' if overhead < 0.02 else 'OVER'} the 2% budget)")
    put("fault_overhead",
        qos0_msgs_per_sec_faultline=round(best["faultline"]),
        qos0_msgs_per_sec_compiled_out=round(best["nofault"]),
        overhead_frac=round(overhead, 4),
        within_2pct_budget=bool(overhead < 0.02))


# ---------------------------------------------------------------------------
# raw-socket MQTT codec shared by the trunk/durable sections (one copy:
# a framing fix must not have to land twice)
# ---------------------------------------------------------------------------

def mqtt_connect(cid, clean=True):
    import struct
    flags = 0x02 if clean else 0x00
    vh = (b"\x00\x04MQTT\x04" + bytes([flags]) + b"\x00\x3c"
          + struct.pack(">H", len(cid)) + cid)
    return bytes([0x10, len(vh)]) + vh


def mqtt_subscribe(pid, topic, qos=0):
    import struct
    body = struct.pack(">H", pid) + struct.pack(">H", len(topic)) \
        + topic + bytes([qos])
    return bytes([0x82, len(body)]) + body


def mqtt_publish(topic, payload, qos=0, pid=0):
    import struct
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    head = bytes([0x30 | (qos << 1)])
    remaining = len(body)
    var = b""
    while True:
        b7 = remaining & 0x7F
        remaining >>= 7
        var += bytes([b7 | (0x80 if remaining else 0)])
        if not remaining:
            break
    return head + var + body


def count_publishes(buf, counts):
    """Consume whole frames from buf, counting PUBLISHes; returns the
    unconsumed tail."""
    pos = 0
    while True:
        if len(buf) - pos < 2:
            break
        rl = 0
        shift = 0
        i = pos + 1
        ok = True
        while True:
            if i >= len(buf):
                ok = False
                break
            byte = buf[i]
            rl |= (byte & 0x7F) << shift
            shift += 7
            i += 1
            if not byte & 0x80:
                break
        if not ok or len(buf) - i < rl:
            break
        if buf[pos] >> 4 == 3:
            counts[0] += 1
        pos = i + rl
    return buf[pos:]


def publish_drainer(sock, counts, stop):
    """Count inbound PUBLISHes until stop. select-based on purpose: the
    durable replay leg shares the PUBLISHER's socket with the main
    thread's sendall loop, and a socket-level settimeout would apply to
    send too — a >200ms fsync stall mid-blast would then raise
    TimeoutError out of sendall and kill the whole section."""
    import select
    buf = b""
    while not stop.is_set():
        try:
            r, _, _ = select.select([sock], [], [], 0.2)
            if not r:
                continue
            chunk = sock.recv(1 << 16)
        except (OSError, ValueError):
            return
        if not chunk:
            return
        buf = count_publishes(buf + chunk, counts)


# ---------------------------------------------------------------------------
# section: trunk (cross-node forwarding on the native plane; CPU by design)
# ---------------------------------------------------------------------------

def sec_trunk() -> None:
    """ISSUE 4 acceptance: a two-node loopback pair forwarding QoS0
    cross-node over the NATIVE trunk must run >= 10x the Python gen_rpc
    lane (TcpTransport casts through both nodes' Python planes — the
    lane every cross-node leg rode before this round). Same driver both
    arms: raw-socket publisher on node A, raw-socket subscriber on node
    B, the cluster plane replicating the route; the arms differ only by
    attach_native (trunk adverts on hello/ping)."""
    import socket
    import threading

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.cluster.node import ClusterNode
    from emqx_tpu.cluster.transport import TcpTransport

    def build_pair(trunk: bool, suffix: str):
        ta = TcpTransport(f"bA{suffix}")
        tb = TcpTransport(f"bB{suffix}")
        ta.add_peer(tb.node, tb.host, tb.port)
        tb.add_peer(ta.node, ta.host, ta.port)
        na = ClusterNode(ta.node, ta)
        nb = ClusterNode(tb.node, tb)
        sa = NativeBrokerServer(port=0, app=na.app,
                                trunk_port=0 if trunk else None)
        sb = NativeBrokerServer(port=0, app=nb.app,
                                trunk_port=0 if trunk else None)
        if trunk:
            na.attach_native(sa)
            nb.attach_native(sb)
        sa.start()
        sb.start()
        nb.join([na.name])
        return na, nb, sa, sb

    def drive(trunk: bool, suffix: str, n_msg: int, deadline_s: float):
        na, nb, sa, sb = build_pair(trunk, suffix)
        try:
            sub = socket.create_connection(("127.0.0.1", sb.port))
            sub.sendall(mqtt_connect(b"bsub") + mqtt_subscribe(1, b"bt/x"))
            pub = socket.create_connection(("127.0.0.1", sa.port))
            pub.sendall(mqtt_connect(b"bpub"))
            time.sleep(0.3)
            na.flush()
            nb.flush()
            if trunk:
                t0 = time.time()
                while (not sa.trunk_peer_status().get(nb.name)
                       and time.time() - t0 < 10):
                    time.sleep(0.05)
                assert sa.trunk_peer_status().get(nb.name), "trunk not up"
            counts = [0]
            stop = threading.Event()
            dt = threading.Thread(target=publish_drainer,
                                  args=(sub, counts, stop), daemon=True)
            dt.start()
            # warm leg earns the permit through the Python lane
            pub.sendall(mqtt_publish(b"bt/x", b"warm-up-00000"))
            t0 = time.time()
            while counts[0] < 1 and time.time() - t0 < 15:
                time.sleep(0.05)
            time.sleep(0.6)     # permit grants on an idle poll step
            frame = mqtt_publish(b"bt/x", b"x" * 16)
            blob = frame * 256
            sent = 0
            t0 = time.time()
            while sent < n_msg and time.time() - t0 < deadline_s:
                pub.sendall(blob)
                sent += 256
            t_sent = time.time()
            deadline = t_sent + max(15.0, deadline_s / 2)
            last = -1
            while counts[0] < sent + 1 and time.time() < deadline:
                if counts[0] != last:
                    last = counts[0]
                time.sleep(0.05)
            wall = time.time() - t0
            received = counts[0] - 1      # minus the warm leg
            rate = received / max(wall, 1e-9)
            # windowed cross-node latency: W outstanding, p99 of the
            # per-window round trip (send last byte -> all W received)
            lats = []
            W = 64
            for _ in range(40):
                base = counts[0]
                lt0 = time.time()
                pub.sendall(frame * W)
                while counts[0] < base + W and time.time() - lt0 < 5:
                    time.sleep(0)
                lats.append((time.time() - lt0) * 1000 / W)
            lats.sort()
            p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
            stop.set()
            dt.join(timeout=2)
            stats = sa.fast_stats()
            summ = sa.latency_summary() if trunk else {}
            for s in (pub, sub):
                try:
                    s.close()
                except OSError:
                    pass
            return rate, received, sent, p99, stats, summ
        finally:
            sa.stop()
            sb.stop()
            na.transport.close()
            nb.transport.close()

    n_py = int(os.environ.get("BENCH_TRUNK_PY_MSGS", 4096))
    n_tk = int(os.environ.get("BENCH_TRUNK_MSGS", 120000))

    py_rate, py_recv, py_sent, py_p99, py_stats, _ = drive(
        False, "p", n_py, 60.0)
    log(f"trunk BEFORE (python gen_rpc lane, qos0 cross-node): "
        f"{py_recv}/{py_sent} = {py_rate:,.0f} msg/s "
        f"p99/msg={py_p99:.3f}ms (trunk_out={py_stats['trunk_out']})")
    put("trunk", trunk_python_fwd_msgs_per_sec=round(py_rate),
        trunk_python_fwd_p99_ms=round(py_p99, 3))

    tk_rate, tk_recv, tk_sent, tk_p99, tk_stats, summ = drive(
        True, "t", n_tk, 90.0)
    ratio = tk_rate / max(py_rate, 1e-9)
    log(f"trunk AFTER (native trunk, qos0 cross-node): "
        f"{tk_recv}/{tk_sent} = {tk_rate:,.0f} msg/s "
        f"p99/msg={tk_p99:.3f}ms  ({ratio:,.1f}x the python lane"
        f"{'' if ratio >= 10 else ' — UNDER the 10x acceptance'}; "
        f"trunk_out={tk_stats['trunk_out']} "
        f"batches={tk_stats['trunk_batches_out']})")
    put("trunk",
        trunk_native_msgs_per_sec=round(tk_rate),
        trunk_native_p99_ms=round(tk_p99, 3),
        trunk_vs_python=round(ratio, 2),
        trunk_10x_acceptance=bool(ratio >= 10))
    # broker-side trunk-stage percentiles (enqueue->peer-ack RTT in us;
    # batch occupancy's "us" axis is really an entry count / 1000 — the
    # one count-valued stage, host.cc kHistTrunkBatchN)
    for stage in ("trunk_rtt", "trunk_batch_n"):
        if stage in summ:
            s = summ[stage]
            log(f"broker-side {stage}: p50={s['p50_us']:.1f} "
                f"p99={s['p99_us']:.1f} (n={s['count']})")
            put("trunk", **{
                f"trunk_broker_{stage}_p50_us": round(s["p50_us"], 1),
                f"trunk_broker_{stage}_p99_us": round(s["p99_us"], 1)})


# ---------------------------------------------------------------------------
# section: durable (ISSUE 5 acceptance)
# ---------------------------------------------------------------------------

def sec_durable() -> None:
    """ISSUE 5 acceptance: with ONE persistent subscriber in a fan-out
    audience, fast-path throughput must be >= 10x the punt-everything
    behavior (pre-round-10, a single durable subscriber collapsed every
    matching publish onto the Python plane). Same driver both arms —
    raw-socket publisher + N fast subscribers + 1 persistent subscriber
    — differing only by the durable plane being attached. Plus the
    resume-replay drain rate (store -> native delivery machinery)."""
    import socket
    import tempfile
    import threading

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.session.persistent import MemStore

    def build(durable: bool):
        app = BrokerApp(persistent_store=MemStore())
        server = NativeBrokerServer(
            port=0, app=app, durable=durable,
            durable_dir=tempfile.mkdtemp(prefix="emqx_dur_")
            if durable else None)
        server.start()
        return server

    N_FAST = int(os.environ.get("BENCH_DURABLE_FANOUT", 4))

    def drive(durable: bool, n_msg: int, deadline_s: float):
        server = build(durable)
        socks, threads, stop = [], [], threading.Event()
        counts = [[0] for _ in range(N_FAST)]
        try:
            for i in range(N_FAST):
                s = socket.create_connection(("127.0.0.1", server.port))
                s.sendall(mqtt_connect(b"df%d" % i)
                          + mqtt_subscribe(1, b"du/t"))
                socks.append(s)
                t = threading.Thread(target=publish_drainer,
                                     args=(s, counts[i], stop),
                                     daemon=True)
                t.start()
                threads.append(t)
            ps = socket.create_connection(("127.0.0.1", server.port))
            ps.sendall(mqtt_connect(b"dps", clean=False)
                       + mqtt_subscribe(1, b"du/t", qos=1))
            pcount = [0]
            pt = threading.Thread(target=publish_drainer,
                                  args=(ps, pcount, stop), daemon=True)
            pt.start()
            pub = socket.create_connection(("127.0.0.1", server.port))
            pub.sendall(mqtt_connect(b"dpub"))
            time.sleep(0.3)
            # warm leg earns the permit through the Python plane
            pub.sendall(mqtt_publish(b"du/t", b"warm-000"))
            t0 = time.time()
            while counts[0][0] < 1 and time.time() - t0 < 15:
                time.sleep(0.05)
            time.sleep(0.8)     # permit grants on an idle poll step
            blob = mqtt_publish(b"du/t", b"x" * 16) * 256
            sent = 0
            t0 = time.time()
            while sent < n_msg and time.time() - t0 < deadline_s:
                pub.sendall(blob)
                sent += 256
            deadline = time.time() + max(15.0, deadline_s / 2)
            while counts[0][0] < sent + 1 and time.time() < deadline:
                time.sleep(0.05)
            wall = time.time() - t0
            received = counts[0][0] - 1          # minus the warm leg
            rate = received / max(wall, 1e-9)
            st = server.fast_stats()
            return rate, received, sent, st, server, socks + [ps, pub], \
                stop, threads + [pt]
        except Exception:
            stop.set()
            server.stop()
            raise

    def teardown(server, socks, stop, threads):
        stop.set()
        for t in threads:
            t.join(timeout=2)
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        server.stop()

    n_before = int(os.environ.get("BENCH_DURABLE_PY_MSGS", 4096))
    n_after = int(os.environ.get("BENCH_DURABLE_MSGS", 120000))

    rate0, recv0, sent0, st0, srv0, socks0, stop0, th0 = drive(
        False, n_before, 45.0)
    log(f"durable BEFORE (punt-everything: 1 persistent sub among "
        f"{N_FAST} fast subs, qos0): {recv0}/{sent0} = {rate0:,.0f} "
        f"msg/s (punts={st0['punts']}, durable_in={st0['durable_in']})")
    teardown(srv0, socks0, stop0, th0)
    put("durable", durable_fanout_before_msgs_per_sec=round(rate0),
        durable_fanout_n_fast=N_FAST)

    rate1, recv1, sent1, st1, srv1, socks1, stop1, th1 = drive(
        True, n_after, 60.0)
    ratio = rate1 / max(rate0, 1e-9)
    log(f"durable AFTER (native durable plane): {recv1}/{sent1} = "
        f"{rate1:,.0f} msg/s ({ratio:,.1f}x the punt path"
        f"{'' if ratio >= 10 else ' — UNDER the 10x acceptance'}; "
        f"durable_in={st1['durable_in']} punts={st1['punts']} "
        f"store_appends={st1['store_appends']})")
    put("durable",
        durable_fanout_after_msgs_per_sec=round(rate1),
        durable_vs_punt=round(ratio, 2),
        durable_10x_acceptance=bool(ratio >= 10))
    put_broker_hists("durable", srv1, "durable")
    teardown(srv1, socks1, stop1, th1)

    # -- resume-replay drain rate -------------------------------------------
    server = build(True)
    try:
        ps = socket.create_connection(("127.0.0.1", server.port))
        ps.sendall(mqtt_connect(b"drp", clean=False)
                   + mqtt_subscribe(1, b"dr/t", qos=1))
        time.sleep(0.4)
        ps.sendall(b"\xe0\x00")          # DISCONNECT: offline, session kept
        ps.close()
        pub = socket.create_connection(("127.0.0.1", server.port))
        pub.sendall(mqtt_connect(b"drpub"))
        stop = threading.Event()
        acks = [0]
        at = threading.Thread(target=publish_drainer, args=(pub, acks, stop),
                              daemon=True)
        at.start()
        time.sleep(0.3)
        pub.sendall(mqtt_publish(b"dr/t", b"warm", qos=1, pid=1))
        time.sleep(0.8)                  # permit grant window
        n_replay = int(os.environ.get("BENCH_DURABLE_REPLAY_MSGS", 20000))
        sent = 0
        blob = b"".join(mqtt_publish(b"dr/t", b"y" * 16, qos=1,
                                     pid=1 + (k % 60000))
                        for k in range(256))
        while sent < n_replay:
            pub.sendall(blob)
            sent += 256
        tok = server._durable_tokens.get("drp")
        t0 = time.time()
        while (tok is None or server._durable_store.pending(tok)
               < sent) and time.time() - t0 < 30:
            time.sleep(0.1)
            tok = server._durable_tokens.get("drp")
        stored = server._durable_store.pending(tok) if tok else 0
        # resume: the replay rides session.deliver -> host.send
        ps2 = socket.create_connection(("127.0.0.1", server.port))
        rcount = [0]
        rt = threading.Thread(target=publish_drainer, args=(ps2, rcount, stop),
                              daemon=True)
        t0 = time.time()
        ps2.sendall(mqtt_connect(b"drp", clean=False))
        rt.start()
        deadline = t0 + 60
        # qos1 replay throttles on the session window without acks; the
        # drain counts deliveries, acking is out of scope — measure the
        # first-window burst plus stored drain via the store gauge
        while (tok and server._durable_store.pending(tok) > 0
               and time.time() < deadline):
            time.sleep(0.05)
        drain_wall = time.time() - t0
        drained = stored - (server._durable_store.pending(tok)
                            if tok else 0)
        drate = drained / max(drain_wall, 1e-9)
        time.sleep(1.0)   # let the first-window deliveries hit the wire
        log(f"durable replay: {stored} stored, {drained} drained in "
            f"{drain_wall:.2f}s = {drate:,.0f} msg/s "
            f"(first-window deliveries on the wire: {rcount[0]}; the "
            f"rest ride the session mqueue/window as the client acks)")
        put("durable",
            durable_replay_stored=stored,
            durable_replay_drain_msgs_per_sec=round(drate))
        put_broker_hists("durable", server, "durable_replay")
        stop.set()
        for s in (pub, ps2):
            try:
                s.close()
            except OSError:
                pass
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# section: mixed (edge-gateway plane: MQTT-SN + retained; CPU by design)
# ---------------------------------------------------------------------------

def sec_mixed() -> None:
    """ISSUE 6 acceptance: (a) native-SN publish throughput >= 10x the
    asyncio gateway/mqttsn.py path on the same box, (b) retained COLD
    delivery on the native snapshot >= 10x the Python retain-lookup
    path, with per-stage broker histograms (sn_ingest, retain_deliver)
    recorded; plus the mixed-protocol blast (TCP+WS+SN publishers on
    ONE broker, topic/cid spaces salted apart so the planes share the
    match table without cross-plane fan-out)."""
    import asyncio
    import select
    import socket
    import threading

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.core.message import Message
    from emqx_tpu.gateway import mqttsn as SN

    n_before = int(os.environ.get("BENCH_SN_BEFORE_MSGS", 1000))
    n_blast = int(os.environ.get("BENCH_SN_BLAST_MSGS", 20000))
    n_mixed = int(os.environ.get("BENCH_MIXED_MSGS", 12000))
    n_ret = int(os.environ.get("BENCH_RETAIN_TOPICS", 2000))

    # -- before: asyncio SN gateway (gateway/mqttsn.py), SAME loadgen -------
    # the SN loadgen speaks the shared sn.h codec against either plane,
    # so both arms see identical wire traffic
    gw_state: dict = {}
    gw_stop = threading.Event()
    gw_ready = threading.Event()

    def gw_main():
        async def run_gw():
            app = BrokerApp()
            gw = app.gateway.load(SN.MqttsnGateway(port=0))
            await gw.start_listeners()
            gw_state["port"] = gw.port
            gw_ready.set()
            while not gw_stop.is_set():
                await asyncio.sleep(0.05)
            await gw.stop_listeners()
        asyncio.run(run_gw())

    th = threading.Thread(target=gw_main)
    th.start()
    assert gw_ready.wait(10), "asyncio SN gateway did not come up"
    try:
        before = native.loadgen_sn_run(
            "127.0.0.1", gw_state["port"], n_subs=4, n_pubs=4,
            msgs_per_pub=n_before, qos=0, payload_len=16,
            idle_timeout_ms=8000, window=256)
    finally:
        gw_stop.set()
        th.join()
    before_rate = before["received"] / max(before["wall_ns"] / 1e9, 1e-9)
    log(f"sn plane BEFORE (asyncio gateway/mqttsn.py, qos0 windowed): "
        f"{before['received']}/{before['sent']} = {before_rate:,.0f} msg/s")
    put("mixed", sn_asyncio_msgs_per_sec=round(before_rate))

    # -- after: native SN gateway (sn.h in the C++ host) --------------------
    server = NativeBrokerServer(port=0, app=BrokerApp(), ws_port=0,
                                sn_port=0,
                                session_opts={"max_inflight": 1024})
    server.start()
    try:
        # identical pacing to the BEFORE arm (window + idle timeout):
        # the ratio must measure the plane, not the window depth
        sn = native.loadgen_sn_run(
            "127.0.0.1", server.sn_port, n_subs=4, n_pubs=4,
            msgs_per_pub=n_blast, qos=0, payload_len=16,
            idle_timeout_ms=8000, window=256)
        sn_rate = sn["received"] / max(sn["wall_ns"] / 1e9, 1e-9)
        log(f"sn plane AFTER (native sn.h + fast path, qos0 windowed): "
            f"{sn['received']}/{sn['sent']} = {sn_rate:,.0f} msg/s  "
            f"({sn_rate / max(before_rate, 1):,.0f}x asyncio-sn)  "
            f"p99={sn['p99_ns'] / 1e6:.3f}ms")
        put("mixed",
            sn_native_msgs_per_sec=round(sn_rate),
            sn_native_p99_ms=round(sn["p99_ns"] / 1e6, 3),
            sn_vs_asyncio=round(sn_rate / max(before_rate, 1), 1))

        # qos1 rides the native ack plane (inflight bitmaps + SN PUBACK)
        q1 = native.loadgen_sn_run(
            "127.0.0.1", server.sn_port, n_subs=4, n_pubs=4,
            msgs_per_pub=n_blast // 4, qos=1, payload_len=16, window=512)
        q1_rate = q1["received"] / max(q1["wall_ns"] / 1e9, 1e-9)
        log(f"sn plane qos1 (windowed 512): {q1_rate:,.0f} msg/s "
            f"acks={q1['acks']} p99={q1['p99_ns'] / 1e6:.3f}ms")
        put("mixed",
            sn_native_qos1_msgs_per_sec=round(q1_rate),
            sn_native_qos1_p99_ms=round(q1["p99_ns"] / 1e6, 3))

        # -- mixed-protocol blast: TCP + WS + SN fleets on ONE broker -------
        res: dict = {}

        def tcp_arm():
            res["tcp"] = native.loadgen_run(
                "127.0.0.1", server.port, n_subs=4, n_pubs=4,
                msgs_per_pub=n_mixed, qos=0, payload_len=16)

        def ws_arm():
            res["ws"] = native.loadgen_run(
                "127.0.0.1", server.ws_port, n_subs=4, n_pubs=4,
                msgs_per_pub=n_mixed, qos=0, payload_len=16, ws=True,
                salt=100)

        def sn_arm():
            res["sn"] = native.loadgen_sn_run(
                "127.0.0.1", server.sn_port, n_subs=4, n_pubs=4,
                msgs_per_pub=n_mixed, qos=0, payload_len=16)

        arms = [threading.Thread(target=f)
                for f in (tcp_arm, ws_arm, sn_arm)]
        t0 = time.time()
        for a in arms:
            a.start()
        for a in arms:
            a.join()
        wall = time.time() - t0
        total = sum(r["received"] for r in res.values())
        per = {k: round(r["received"] / max(r["wall_ns"] / 1e9, 1e-9))
               for k, r in res.items()}
        log(f"mixed blast (TCP+WS+SN concurrent, qos0): "
            f"{total} delivered in {wall:.2f}s = {total / wall:,.0f} msg/s "
            f"aggregate  (tcp={per['tcp']:,} ws={per['ws']:,} "
            f"sn={per['sn']:,} msg/s)")
        put("mixed",
            mixed_total_msgs_per_sec=round(total / wall),
            mixed_tcp_msgs_per_sec=per["tcp"],
            mixed_ws_msgs_per_sec=per["ws"],
            mixed_sn_msgs_per_sec=per["sn"])
        # broker-side stages incl. sn_ingest (sampled SN decode+dispatch)
        put_broker_hists("mixed", server, "mixed_broker")
    finally:
        server.stop()

    # -- retained delivery: Python retain-lookup vs native snapshot ---------
    # identical measurement sink on both arms: a raw-socket subscriber
    # (the shared module codec) timing SUBSCRIBE -> n_ret-th retained
    # PUBLISH; cold = first wildcard subscribe on a fresh conn, warm =
    # repeat on another fresh conn
    def seed_retainer(app):
        for i in range(n_ret):
            app.retainer.store(Message(topic=f"bret/{i:05d}",
                                       payload=b"r" * 16, qos=0,
                                       flags={"retain": True}))

    def measure_retained(port, tag):
        s = socket.create_connection(("127.0.0.1", port))
        s.sendall(mqtt_connect(b"ret-" + tag))
        got = b""
        while len(got) < 4:                    # CONNACK
            got += s.recv(4096)
        t0 = time.time()
        s.sendall(mqtt_subscribe(1, b"bret/#"))
        counts = [0]
        buf = got[4:]
        deadline = time.time() + 60
        while counts[0] < n_ret and time.time() < deadline:
            r, _, _ = select.select([s], [], [], 0.5)
            if not r:
                continue
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            buf = count_publishes(buf + chunk, counts)
        wall = time.time() - t0
        s.close()
        return counts[0], wall

    # Python arm: asyncio BrokerServer, retainer.match + per-msg deliver
    py_state: dict = {}
    py_stop = threading.Event()
    py_ready = threading.Event()
    app_py = BrokerApp()
    seed_retainer(app_py)

    def py_main():
        async def run_py():
            srv = BrokerServer(port=0, app=app_py)
            await srv.start()
            py_state["port"] = srv.port
            py_ready.set()
            while not py_stop.is_set():
                await asyncio.sleep(0.05)
            await srv.stop()
        asyncio.run(run_py())

    th = threading.Thread(target=py_main)
    th.start()
    assert py_ready.wait(10), "asyncio broker did not come up"
    try:
        py_cold_n, py_cold_wall = measure_retained(py_state["port"], b"c1")
        py_warm_n, py_warm_wall = measure_retained(py_state["port"], b"c2")
    finally:
        py_stop.set()
        th.join()
    py_cold = py_cold_n / max(py_cold_wall, 1e-9)
    py_warm = py_warm_n / max(py_warm_wall, 1e-9)
    log(f"retained BEFORE (python retain-lookup, {n_ret} topics): "
        f"cold {py_cold_n} in {py_cold_wall:.3f}s = {py_cold:,.0f} msg/s, "
        f"warm {py_warm:,.0f} msg/s")
    put("mixed",
        retain_py_cold_msgs_per_sec=round(py_cold),
        retain_py_warm_msgs_per_sec=round(py_warm))

    # native arm: the retainer mirror installs the host-side snapshot
    # at boot; SUBSCRIBE-triggered delivery resolves below the GIL
    app_nat = BrokerApp()
    seed_retainer(app_nat)
    srv_ret = NativeBrokerServer(port=0, app=app_nat,
                                 session_opts={"max_inflight": 1024})
    srv_ret.start()
    try:
        nat_cold_n, nat_cold_wall = measure_retained(srv_ret.port, b"n1")
        nat_warm_n, nat_warm_wall = measure_retained(srv_ret.port, b"n2")
        nat_cold = nat_cold_n / max(nat_cold_wall, 1e-9)
        nat_warm = nat_warm_n / max(nat_warm_wall, 1e-9)
        st = srv_ret.fast_stats()
        log(f"retained AFTER (native snapshot, {n_ret} topics): "
            f"cold {nat_cold_n} in {nat_cold_wall:.3f}s = "
            f"{nat_cold:,.0f} msg/s ({nat_cold / max(py_cold, 1):,.0f}x "
            f"python cold), warm {nat_warm:,.0f} msg/s  "
            f"retain_msgs_out={st['retain_msgs_out']}")
        put("mixed",
            retain_native_cold_msgs_per_sec=round(nat_cold),
            retain_native_warm_msgs_per_sec=round(nat_warm),
            retain_native_vs_py_cold=round(nat_cold / max(py_cold, 1), 1))
        # broker-side retain_deliver stage (one SUBSCRIBE's snapshot
        # match + encode + write batch)
        put_broker_hists("mixed", srv_ret, "retain_broker")
    finally:
        srv_ret.stop()


# ---------------------------------------------------------------------------
# section: e2e (full broker stack with the device router on path)
# ---------------------------------------------------------------------------

def sec_e2e() -> None:
    """End-to-end broker number (VERDICT r1 weak #1): real MQTT clients
    over TCP against the asyncio host with the device router on the
    serving path — msg/s and delivery p99 through the full stack
    (parse → channel FSM → pipeline → kernel → CM → socket).  This is
    the broker-level figure comparable to the reference's 1M msg/s
    cluster claim; the kernel number above is the routing-core ceiling."""
    import asyncio

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.config import Config
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    n_pub = int(os.environ.get("BENCH_E2E_PUBS", 16))
    n_sub = int(os.environ.get("BENCH_E2E_SUBS", 16))
    n_msg = int(os.environ.get("BENCH_E2E_MSGS", 250))  # per publisher
    n_rules = int(os.environ.get("BENCH_RULES", 1000))  # config 5

    conf = Config()
    conf.put("router.device.enable", True)
    conf.put("router.device.max_levels", 8)
    # throughput section: pin the knee to 0 so every batch rides the
    # kernel (round-comparable device numbers); the low-load probe
    # below switches to the adaptive policy it is measuring
    conf.put("router.device.min_batch", 0)
    app = BrokerApp.from_config(conf)

    # BASELINE config 5: rule-engine SQL topic filters co-batched with the
    # router match — every FROM filter rides the SAME kernel launch as
    # fan-out; per-publish rule lookup is O(matched), not O(rules)
    # (emqx_rule_engine.erl:198-205)
    rule_hits = [0]
    if n_rules:
        app.rules.register_action(
            "bench_sink", lambda cols, args: rule_hits.__setitem__(
                0, rule_hits[0] + 1))
        for r in range(n_rules):
            # a few rules match live bench traffic; the rest are realistic
            # dead weight over the same topic space
            filt = (f"bench/{r % max(1, n_sub)}/+" if r < 8
                    else f"rules/fleet{r}/+/telemetry")
            app.rules.create_rule(
                f"bench_rule_{r}", f'SELECT topic FROM "{filt}"',
                [{"function": "bench_sink", "args": {}}])

    async def run():
        server = BrokerServer(port=0, app=app)
        await server.start()
        subs = [MqttClient(port=server.port, clientid=f"s{i}")
                for i in range(n_sub)]
        pubs = [MqttClient(port=server.port, clientid=f"p{i}")
                for i in range(n_pub)]
        for i, s in enumerate(subs):
            await s.connect()
            await s.subscribe(f"bench/{i}/+", qos=0)
        for p in pubs:
            await p.connect()
        # warm every pow2 batch shape the pipeline can hit (64..batch_max)
        # off the clock — each fresh shape costs an XLA compile
        def warm_shapes():
            model = app.broker.model
            b = 64
            while b <= app.pipeline.max_batch:
                model.publish_batch(["bench/warmup/x"] * b)
                b *= 2
        await asyncio.to_thread(warm_shapes)
        await pubs[0].publish("bench/0/warm", b"w", qos=0)
        await subs[0].recv(timeout=30)

        recv_done = asyncio.Event()
        lat_ns: list[int] = []
        expected = n_pub * n_msg            # each lands on exactly 1 sub
        got = 0

        async def drain(s):
            nonlocal got
            while got < expected:
                try:
                    m = await s.recv(timeout=10)
                except asyncio.TimeoutError:
                    break
                lat_ns.append(time.perf_counter_ns()
                              - int(m.payload.decode()))
                got += 1
                if got >= expected:
                    recv_done.set()

        drains = [asyncio.create_task(drain(s)) for s in subs]

        async def blast(i, p):
            for j in range(n_msg):
                stamp = str(time.perf_counter_ns()).encode()
                await p.publish(f"bench/{(i + j) % n_sub}/m", stamp, qos=0)

        t0 = time.time()
        await asyncio.gather(*(blast(i, p) for i, p in enumerate(pubs)))
        try:
            await asyncio.wait_for(recv_done.wait(), timeout=60)
        except asyncio.TimeoutError:
            pass
        wall = time.time() - t0
        for d in drains:
            d.cancel()

        # low-load latency (VERDICT r3 #3 done-criterion): sequential
        # publishes trickle in as 1-message batches, which the pipeline's
        # knee policy answers from the host oracle — no device RTT
        app.pipeline.min_device_batch = -1   # the policy under test
        probe = MqttClient(port=server.port, clientid="lat-probe")
        await probe.connect()
        await probe.subscribe("bench/lat/x", qos=0)
        low = []
        for i in range(40):
            t0 = time.perf_counter_ns()
            await pubs[0].publish("bench/lat/x", b"x", qos=0)
            try:
                await probe.recv(timeout=10)
            except asyncio.TimeoutError:
                # one dropped probe must not discard the whole e2e
                # section's already-measured results
                log(f"low-load probe: recv timeout at sample {i}")
                break
            low.append((time.perf_counter_ns() - t0) / 1e6)
            await asyncio.sleep(0.01)
        low_a = np.array(low) if low else np.array([float("nan")])
        await probe.close()

        for c in subs + pubs:
            try:
                await c.disconnect()
            except Exception:
                pass
        await server.stop()
        lat_ms = np.array(lat_ns, float) / 1e6
        log(f"e2e broker: {got}/{expected} msgs in {wall:.2f}s = "
            f"{got / wall:,.0f} msg/s end-to-end "
            f"(pubs={n_pub} subs={n_sub} qos=0, device path, "
            f"kernel launches={app.broker.model.launch_count}, "
            f"rules={n_rules} co-batched, rule fires={rule_hits[0]})")
        put("e2e", e2e_msgs_per_sec=round(got / max(wall, 1e-9)))
        if len(lat_ms):
            log(f"e2e delivery latency ms: p50={np.percentile(lat_ms, 50):.2f} "
                f"p99={np.percentile(lat_ms, 99):.2f}")
            put("e2e",
                e2e_p50_ms=round(float(np.percentile(lat_ms, 50)), 2),
                e2e_p99_ms=round(float(np.percentile(lat_ms, 99)), 2))
        log(f"e2e LOW-LOAD latency ms (device on, knee="
            f"{app.pipeline.device_knee()}, host-bypassed batches="
            f"{app.pipeline.host_batches}): "
            f"p50={np.percentile(low_a, 50):.2f} "
            f"p99={np.percentile(low_a, 99):.2f}")
        put("e2e",
            e2e_lowload_p50_ms=round(float(np.percentile(low_a, 50)), 2),
            e2e_lowload_p99_ms=round(float(np.percentile(low_a, 99)), 2))

    asyncio.run(run())

    # -- device-path ceiling under native load ------------------------------
    # The same app (warmed model/pipeline) behind the C++ host with the
    # fast path OFF: every publish runs Channel.handle_in → pipeline →
    # kernel. This is the honest "Python FSM + device router" e2e bound
    # (the r3 famine was Python clients measuring themselves; the C++
    # loadgen removes that), and the gap to the fast-path number above
    # is the remaining host-plane work for future rounds.
    from emqx_tpu import native as _native

    if _native.available() and os.environ.get("BENCH_DEVICE_E2E", "1") != "0":
        from emqx_tpu.broker.native_server import NativeBrokerServer

        app.pipeline.min_device_batch = 0   # measure the KERNEL path,
        server = NativeBrokerServer(port=0, app=app, fast_path=False)
        server.start()                      # not the knee's host bypass
        try:
            res = _native.loadgen_run(
                "127.0.0.1", server.port, n_subs=8, n_pubs=8,
                msgs_per_pub=int(os.environ.get("BENCH_DEVICE_E2E_MSGS",
                                                1500)),
                qos=0, payload_len=16, window=2048, warmup=False)
            wall = res["wall_ns"] / 1e9
            rate = res["received"] / max(wall, 1e-9)
            log(f"device-path e2e (native load, fast path OFF, window "
                f"2048): {res['received']}/{res['sent']} = {rate:,.0f} "
                f"msg/s through channel FSM + pipeline + kernel "
                f"(launches={app.broker.model.launch_count})")
            put("e2e", e2e_device_path_msgs_per_sec=round(rate))
        except Exception as e:  # noqa: BLE001
            # a loadgen flake must not cost the whole artifact (every
            # earlier section's numbers stay in the partial file)
            log(f"device-path e2e section failed, skipping: {e}")
        finally:
            server.stop()

    if _native.available() and os.environ.get("BENCH_LANE", "1") != "0":
        bench_device_lane(app)


def bench_device_lane(app) -> None:
    """The one-path hot loop (VERDICT r4 #2 done-criterion): the C++
    data plane with the DEVICE doing the wildcard match — permitted
    publishes park in C++, topics batch through the RouterModel kernel,
    and the response fans out natively by exact filter lookup. The
    device table is padded to BENCH_LANE_FILTERS wildcard filters
    (synthetic dead weight that does not match the published topics —
    the emqx_broker_bench wildcard-dense-table shape) so the number
    demonstrates device matching at scale, not an 8-entry walk."""
    import jax

    from emqx_tpu import native as _native
    from emqx_tpu.broker.native_server import NativeBrokerServer

    on_cpu = jax.devices()[0].platform == "cpu"
    n_filters = int(os.environ.get(
        "BENCH_LANE_FILTERS", 20_000 if on_cpu else 100_000))
    msgs_per_pub = int(os.environ.get(
        "BENCH_LANE_MSGS", 1_500 if on_cpu else 20_000))
    model = app.broker.model
    rng = np.random.default_rng(23)
    t0 = time.time()
    filters = build_filters(n_filters, rng)
    n_slots = model.n_sub_slots
    for i, f in enumerate(filters):
        model.subscribe(f, int(i % n_slots))
    model.refresh()
    log(f"lane: padded device table with {n_filters} filters in "
        f"{time.time()-t0:.1f}s (platform={'cpu' if on_cpu else 'device'})")

    app.pipeline.min_device_batch = 0
    server = NativeBrokerServer(port=0, app=app, device_lane="on")
    server.start()
    try:
        res = _native.loadgen_run(
            "127.0.0.1", server.port, n_subs=8, n_pubs=8,
            msgs_per_pub=msgs_per_pub, qos=0, payload_len=16,
            window=int(os.environ.get("BENCH_LANE_WINDOW", 8192)))
        wall = res["wall_ns"] / 1e9
        rate = res["received"] / max(wall, 1e-9)
        st = server.fast_stats()
        log(f"lane e2e (C++ plane + device match @ {n_filters} filters, "
            f"windowed): {res['received']}/{res['sent']} = {rate:,.0f} "
            f"msg/s  lane_in={st['lane_in']} lane_out={st['lane_out']} "
            f"punts={st['lane_punts']} fallback={st['lane_fallback']} "
            f"p99={res['p99_ns'] / 1e6:.2f}ms")
        put("e2e",
            lane_msgs_per_sec=round(rate),
            lane_filters=n_filters,
            lane_out=st["lane_out"],
            lane_p99_ms=round(res["p99_ns"] / 1e6, 2))
        # broker-side stages: lane_dwell is THE number here (enqueue →
        # device verdict applied — the kernel round trip as the data
        # plane experiences it)
        summ = put_broker_hists("e2e", server, "lane_broker")
        if "lane_dwell" in summ:
            s = summ["lane_dwell"]
            log(f"broker-side lane_dwell: p50={s['p50_us']:.0f}us "
                f"p99={s['p99_us']:.0f}us (n={s['count']})")
    except Exception as e:  # noqa: BLE001
        log(f"lane e2e subsection failed, skipping: {e}")
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def sec_shards() -> None:
    """ISSUE 7 acceptance: 2-shard qos0 fan-out >= 1.6x the 1-shard
    throughput on this box (4-shard recorded when >= 4 cores). Two
    shapes, both burst-into-buffers (publishers pre-serialize the whole
    burst and the broker's outbufs absorb delivery, so the measurement
    window contains ONLY broker-plane work — the thing shards scale —
    instead of driver recv() competing for the same cores):

    - ``fanout`` (the headline): per-publisher topics with the audience
      on the publisher's shard — the accept-sharding scale-out story,
      near-linear by construction;
    - ``cross`` (the ring): one shared topic, audience split across
      shards, ~50%% of deliveries ride the SPSC rings — records the
      crossing tax, the ring occupancy histogram (shard_ring_n) and the
      shard_ring_out/in/full counters.
    """
    import socket
    import threading

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer

    FAN = int(os.environ.get("BENCH_SHARD_FANOUT", 8))
    N_PUBS = 2
    K = int(os.environ.get("BENCH_SHARD_BURST", 120_000))
    FRAME_PAYLOAD = b"x" * 16

    def connect_on_shard(server, cid, want, bufs=8 << 20):
        """Raw conn placed on shard `want` (None = anywhere): each
        retry re-rolls the kernel's SO_REUSEPORT hash via a fresh
        ephemeral source port."""
        for _ in range(96):
            before = set(server.conns)
            s = socket.create_connection(("127.0.0.1", server.port))
            s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, bufs)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, bufs)
            s.sendall(mqtt_connect(cid))
            new = set()
            t0 = time.time()
            while not new and time.time() - t0 < 5:
                new = set(server.conns) - before
                if not new:
                    time.sleep(0.005)
            conn_id = new.pop()
            if want is None or native.shard_of(conn_id) == want:
                return s
            s.close()
            time.sleep(0.02)
        raise RuntimeError(f"cannot place {cid} on shard {want}")

    def drain_all(socks):
        for s in socks:
            s.setblocking(False)
            while True:
                try:
                    if not s.recv(1 << 18):
                        break
                except BlockingIOError:
                    break
                except OSError:
                    break
            s.setblocking(True)

    def drive(shards: int, cross: bool, reps: int = 3):
        server = NativeBrokerServer(port=0, app=BrokerApp(),
                                    shards=shards)
        server.start()
        time.sleep(0.3)
        subs, pubs, frames = [], [], []
        try:
            for p in range(N_PUBS):
                sh = (p % shards) if shards > 1 else None
                topic = b"fan/all" if cross else b"fan/%d" % p
                if not cross or p == 0:
                    for i in range(FAN):
                        ssh = (i % shards) if (cross and shards > 1) \
                            else sh
                        s = connect_on_shard(server, b"bs%d_%d" % (p, i),
                                             ssh)
                        s.sendall(mqtt_subscribe(1, topic))
                        subs.append(s)
                s = connect_on_shard(server, b"bp%d" % p, sh)
                frames.append(mqtt_publish(topic, FRAME_PAYLOAD))
                pubs.append(s)
            for s, f in zip(pubs, frames):
                s.sendall(f)           # slow leg earns the permit
            time.sleep(0.8)
            drain_all(subs)
            fan_per_pub = FAN          # both shapes: FAN subs per topic
            # burst-into-buffers bound: every sub's burst share must fit
            # rcvbuf + the host outbuf (kHighWater 4MB), or the arm
            # stalls on backpressure instead of measuring capacity. The
            # cross shape lands BOTH publishers' bursts on every sub.
            k = K if not cross else min(K, (3 << 20) // 19 // N_PUBS)
            best = 0.0
            for _ in range(reps):
                expect = fan_per_pub * k * N_PUBS
                st0 = server.fast_stats()
                t0 = time.time()
                bts = [threading.Thread(
                    target=lambda s=s, f=f: s.sendall(f * k),
                    daemon=True) for s, f in zip(pubs, frames)]
                for t in bts:
                    t.start()
                last, stall = -1, 0
                while True:
                    done = (server.fast_stats()["fast_out"]
                            - st0["fast_out"])
                    if done >= expect:
                        break
                    if done == last:
                        stall += 1
                        if stall > 800:
                            break
                    else:
                        stall, last = 0, done
                    time.sleep(0.005)
                wall = time.time() - t0
                st1 = server.fast_stats()
                best = max(best,
                           (st1["fast_out"] - st0["fast_out"]) / wall)
                for t in bts:
                    t.join(timeout=5)
                drain_all(subs)
                time.sleep(0.3)
            st = server.fast_stats()
            hists = server.latency_summary()
            shard_hists = server.shard_latency_summary()
            return best, st, hists, shard_hists
        finally:
            for s in subs + pubs:
                try:
                    s.close()
                except OSError:
                    pass
            server.stop()

    shard_counts = [1, 2]
    if (os.cpu_count() or 2) >= 4:
        shard_counts.append(4)
    rates = {}
    for shape in ("fanout", "cross"):
        cross = shape == "cross"
        for s in shard_counts:
            rate, st, hists, shard_hists = drive(s, cross)
            rates[(shape, s)] = rate
            log(f"shards/{shape} s={s}: {rate/1e6:.2f}M msg/s "
                f"ring_out={st['shard_ring_out']} "
                f"ring_full={st['shard_ring_full']}")
            kv = {f"shards_{shape}_{s}shard_msgs_per_sec": round(rate)}
            if cross and s > 1:
                kv.update({
                    f"shards_cross_{s}shard_ring_out":
                        st["shard_ring_out"],
                    f"shards_cross_{s}shard_ring_in":
                        st["shard_ring_in"],
                    f"shards_cross_{s}shard_ring_full":
                        st["shard_ring_full"],
                    f"shards_cross_{s}shard_punts": st["punts"],
                })
                occ = hists.get("shard_ring_n")
                if occ:
                    # count-valued stage (the trunk_batch_n
                    # convention): "p50_us" slots carry ENTRIES/batch
                    kv[f"shards_cross_{s}shard_ring_occupancy_p50"] = \
                        occ["p50_us"]
                    kv[f"shards_cross_{s}shard_ring_occupancy_p99"] = \
                        occ["p99_us"]
            # per-shard stage breakdown (ingress + flush per shard)
            for shard, stages in shard_hists.items():
                for stage in ("ingress_route", "route_flush"):
                    sm = stages.get(stage)
                    if sm:
                        kv[f"shards_{shape}_{s}shard_s{shard}_"
                           f"{stage}_p50_us"] = sm["p50_us"]
            put("shards", **kv)
    for shape in ("fanout", "cross"):
        base = rates.get((shape, 1), 0)
        for s in shard_counts[1:]:
            if base:
                put("shards", **{
                    f"shards_{shape}_speedup_{s}x":
                        round(rates[(shape, s)] / base, 2)})
    ok = (rates.get(("fanout", 2), 0)
          >= 1.6 * rates.get(("fanout", 1), float("inf")))
    put("shards", shards_accept_2x_fanout_ge_1_6x=bool(ok))


def sec_coap() -> None:
    """ISSUE 15 acceptance: native-CoAP publish throughput AND observe
    fan-out >= 10x the asyncio gateway/coap.py path on IDENTICAL wire
    traffic with IDENTICAL pacing (the SN gate shape: the same coap.h
    loadgen fleet drives both planes, windowed the same), with
    broker-side stage hists (coap_ingest, observe_notify) recorded."""
    import asyncio
    import threading

    from emqx_tpu import native

    if not native.available():
        log(f"native host unavailable, skipping: {native.build_error()}")
        return

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.gateway import coap as COAP

    n_before = int(os.environ.get("BENCH_COAP_BEFORE_MSGS", 1000))
    n_blast = int(os.environ.get("BENCH_COAP_BLAST_MSGS", 20000))
    n_fan = int(os.environ.get("BENCH_COAP_FANOUT_MSGS", 16000))

    def run_asyncio_arm(fn):
        """One measurement against a fresh asyncio CoapGateway."""
        state: dict = {}
        stop = threading.Event()
        ready = threading.Event()

        def gw_main():
            async def run_gw():
                app = BrokerApp()
                gw = app.gateway.load(COAP.CoapGateway(port=0))
                await gw.start_listeners()
                state["port"] = gw.port
                ready.set()
                while not stop.is_set():
                    await asyncio.sleep(0.05)
                await gw.stop_listeners()
            asyncio.run(run_gw())

        th = threading.Thread(target=gw_main)
        th.start()
        assert ready.wait(10), "asyncio CoAP gateway did not come up"
        try:
            return fn(state["port"])
        finally:
            stop.set()
            th.join()

    # -- before: asyncio gateway/coap.py, the SAME loadgen fleet ------------
    before = run_asyncio_arm(lambda port: native.loadgen_coap_run(
        "127.0.0.1", port, n_subs=4, n_pubs=4, msgs_per_pub=n_before,
        qos=0, payload_len=16, idle_timeout_ms=8000, window=256))
    before_rate = before["received"] / max(before["wall_ns"] / 1e9, 1e-9)
    log(f"coap plane BEFORE (asyncio gateway/coap.py, NON windowed): "
        f"{before['received']}/{before['sent']} = "
        f"{before_rate:,.0f} msg/s")
    put("coap", coap_asyncio_msgs_per_sec=round(before_rate))


    # -- after: the native CoAP plane (coap.h in the C++ host) --------------
    server = NativeBrokerServer(port=0, app=BrokerApp(), coap_port=0,
                                session_opts={"max_inflight": 1024})
    server.start()
    try:
        # identical pacing to the BEFORE arm (window + idle timeout):
        # the ratio must measure the plane, not the window depth
        after = native.loadgen_coap_run(
            "127.0.0.1", server.coap_port, n_subs=4, n_pubs=4,
            msgs_per_pub=n_blast, qos=0, payload_len=16,
            idle_timeout_ms=8000, window=256)
        after_rate = after["received"] / max(after["wall_ns"] / 1e9, 1e-9)
        log(f"coap plane AFTER (native coap.h + fast path, NON "
            f"windowed): {after['received']}/{after['sent']} = "
            f"{after_rate:,.0f} msg/s  "
            f"({after_rate / max(before_rate, 1):,.0f}x asyncio-coap)  "
            f"p99={after['p99_ns'] / 1e6:.3f}ms")
        put("coap",
            coap_native_msgs_per_sec=round(after_rate),
            coap_native_p99_ms=round(after["p99_ns"] / 1e6, 3),
            coap_vs_asyncio=round(after_rate / max(before_rate, 1), 1),
            coap_pub_10x_gate=bool(
                after_rate >= 10 * max(before_rate, 1)))

        # qos1: CON publishes gated on the native ack plane
        q1 = native.loadgen_coap_run(
            "127.0.0.1", server.coap_port, n_subs=4, n_pubs=4,
            msgs_per_pub=n_blast // 4, qos=1, payload_len=16,
            window=256)
        q1_rate = q1["received"] / max(q1["wall_ns"] / 1e9, 1e-9)
        log(f"coap plane qos1 (CON windowed 256): {q1_rate:,.0f} msg/s "
            f"acks={q1['acks']} p99={q1['p99_ns'] / 1e6:.3f}ms")
        put("coap",
            coap_native_qos1_msgs_per_sec=round(q1_rate),
            coap_native_qos1_p99_ms=round(q1["p99_ns"] / 1e6, 3))

        # observe fan-out: 8 observers on ONE topic, identical shape
        # on both planes. Interleaved best-of-3 with the pair order
        # ALTERNATED per rep (the observe_overhead discipline): this
        # 1-core box's run-to-run drift swamps a single-shot ratio.
        def native_fan_arm():
            return native.loadgen_coap_run(
                "127.0.0.1", server.coap_port, n_subs=8, n_pubs=1,
                msgs_per_pub=max(n_fan // 8, 200), qos=0,
                payload_len=16, idle_timeout_ms=8000, window=512,
                fanout=True)

        def asyncio_fan_arm():
            return run_asyncio_arm(lambda port: native.loadgen_coap_run(
                "127.0.0.1", port, n_subs=8, n_pubs=1,
                msgs_per_pub=max(n_fan // 8, 200), qos=0,
                payload_len=16, idle_timeout_ms=8000, window=512,
                fanout=True))

        def rate_of(r):
            return r["received"] / max(r["wall_ns"] / 1e9, 1e-9)

        fan_rate = bf_rate = 0.0
        for rep in range(3):
            arms = ([asyncio_fan_arm, native_fan_arm] if rep % 2 == 0
                    else [native_fan_arm, asyncio_fan_arm])
            for arm in arms:
                r = rate_of(arm())
                if arm is native_fan_arm:
                    fan_rate = max(fan_rate, r)
                else:
                    bf_rate = max(bf_rate, r)
        log(f"coap observe fan-out (8 observers/1 topic, best-of-3 "
            f"interleaved): native {fan_rate:,.0f} notify/s vs asyncio "
            f"{bf_rate:,.0f} notify/s "
            f"({fan_rate / max(bf_rate, 1):,.0f}x)")
        put("coap",
            coap_asyncio_fanout_notifies_per_sec=round(bf_rate),
            coap_native_fanout_notifies_per_sec=round(fan_rate),
            coap_fanout_vs_asyncio=round(fan_rate / max(bf_rate, 1), 1),
            coap_fanout_10x_gate=bool(fan_rate >= 10 * max(bf_rate, 1)))
        # broker-side stages incl. coap_ingest + observe_notify
        put_broker_hists("coap", server, "coap_broker")
        st = server.host.stats()
        put("coap", coap_in=st["coap_in"], coap_punts=st["coap_punts"],
            coap_notifies=st["coap_notifies"])
    finally:
        server.stop()


SECTIONS = {
    "kernel": sec_kernel,
    "tenm": sec_tenm,
    "churn": sec_churn,
    "xdev": sec_xdev,
    "xcpp": sec_xcpp,
    "shared": sec_shared,
    "host": sec_host,
    "ws": sec_ws,
    "trunk": sec_trunk,
    "durable": sec_durable,
    "mixed": sec_mixed,
    "coap": sec_coap,
    "shards": sec_shards,
    "e2e": sec_e2e,
    "observe_overhead": sec_observe_overhead,
    "fault_overhead": sec_fault_overhead,
    "conn_scale": sec_conn_scale,
}

# (name, needs_device, pin_cpu, deadline_s). Device sections run first —
# they are the artifact's reason to exist (VERDICT r2/r3/r4) — and in
# decreasing value order so a budget squeeze drops the cheapest claims.
DEVICE_PLAN = [
    ("kernel", True, False, 800),
    ("tenm", True, False, 800),
    ("churn", True, False, 500),
    ("xdev", True, False, 500),
    ("e2e", True, False, 600),
    ("xcpp", False, True, 400),
    ("host", False, True, 500),
    ("ws", False, True, 400),
    ("trunk", False, True, 400),
    ("durable", False, True, 400),
    ("mixed", False, True, 500),
    ("coap", False, True, 400),
    ("shards", False, True, 500),
    ("shared", False, True, 400),
    ("observe_overhead", False, True, 300),
    ("fault_overhead", False, True, 400),
    ("conn_scale", False, True, 800),
]
CPU_PLAN = [
    ("kernel", False, True, 700),
    # validation-mode 10M section: sec_tenm itself skips unless
    # BENCH_ALLOW_CPU=1 (with small BENCH_TENM_FILTERS), so a degraded
    # plan can still land the tenm_*/sharded-arm keys the r06+ artifact
    # schema requires
    ("tenm", False, True, 700),
    ("xcpp", False, True, 400),
    ("host", False, True, 500),
    ("ws", False, True, 400),
    ("trunk", False, True, 400),
    ("durable", False, True, 400),
    ("mixed", False, True, 500),
    ("coap", False, True, 400),
    ("shards", False, True, 500),
    ("shared", False, True, 400),
    ("e2e", False, True, 600),
    ("observe_overhead", False, True, 300),
    ("fault_overhead", False, True, 400),
    ("conn_scale", False, True, 800),
]

_SECTION_ORDER = ["kernel", "tenm", "churn", "xdev", "xcpp",
                  "shared", "host", "ws", "trunk", "durable", "mixed",
                  "coap", "shards", "e2e", "observe_overhead",
                  "fault_overhead", "conn_scale", "kernel_cpu"]


def _probe_device(attempts: int, timeout_s: float, backoff_s: float,
                  total_budget_s: Optional[float] = None) -> dict:
    """Retrying tunnel probe (VERDICT r4 #1b): a wedged tunnel can
    recover in minutes; one 180s shot never sees it. The platform must
    be a real accelerator — bare jax.devices() SILENTLY falls back to
    CPU where no device is registered, which would pass CPU numbers off
    as device numbers.

    Each attempt is a killable child (sp.run's timeout SIGKILLs a hung
    ``jax.devices()``, the r05 failure mode), the backoff doubles per
    attempt (capped at 60s), and ``total_budget_s`` is a hard wall: a
    wedged tunnel costs at most that long before the plan degrades to
    CPU validation — r05 spent 4×120s probes + 3×60s fixed backoffs
    (~11 min) learning the same thing.  When the probe gives up, the
    returned ``reason`` string lands in the artifact
    (``probe_degraded_reason``) so the capture says WHY it is CPU-only.
    """
    import subprocess as sp

    t_all = time.time()
    attempts_log = []
    delay = backoff_s
    for i in range(attempts):
        shot = timeout_s
        if total_budget_s is not None:
            left = total_budget_s - (time.time() - t_all)
            if left <= 1:
                reason = (f"probe budget {total_budget_s:.0f}s exhausted "
                          f"after {i} attempt(s)")
                attempts_log.append(reason)
                log(f"device probe: {reason}")
                return {"ok": False, "attempts": i, "log": attempts_log,
                        "reason": reason}
            shot = min(timeout_s, left)
        t0 = time.time()
        try:
            p = sp.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); "
                 "assert d and d[0].platform != 'cpu', d; "
                 "print(d[0])"],
                env=dict(os.environ), timeout=shot,
                capture_output=True, text=True)
            if p.returncode == 0:
                dev = (p.stdout or "").strip()
                attempts_log.append(f"ok in {time.time()-t0:.0f}s: {dev}")
                log(f"device probe attempt {i+1}/{attempts}: {attempts_log[-1]}")
                return {"ok": True, "attempts": i + 1,
                        "log": attempts_log, "device": dev}
            tail = (p.stderr or "").strip().splitlines()[-1:]
            attempts_log.append(
                f"rc={p.returncode}" + (f" {tail[0][:160]}" if tail else ""))
        except sp.TimeoutExpired:
            attempts_log.append(f"hung >{shot:.0f}s (tunnel wedged?)")
        log(f"device probe attempt {i+1}/{attempts}: {attempts_log[-1]}")
        if i + 1 < attempts:
            sleep = delay
            if total_budget_s is not None:
                sleep = min(sleep,
                            max(0.0, total_budget_s - (time.time() - t_all)))
            time.sleep(sleep)
            delay = min(delay * 2, 60.0)
    reason = (f"no usable accelerator after {attempts} attempt(s) in "
              f"{time.time() - t_all:.0f}s"
              + (f"; last: {attempts_log[-1]}" if attempts_log else ""))
    return {"ok": False, "attempts": attempts, "log": attempts_log,
            "reason": reason}


def _kernel_captured(partial_dir: str) -> bool:
    """A device kernel counts as captured only when its THROUGHPUT
    landed — a section file holding just the platform/filters keys
    (child wedged right after its first flush) does not."""
    path = os.path.join(partial_dir, "section_kernel.json")
    try:
        with open(path) as f:
            return "kernel_topics_per_sec" in json.load(f)
    except Exception:
        return False


def _compose(partial_dir: str, meta: dict) -> dict:
    """Merge every captured section file (canonical order) + supervisor
    metadata into the one cumulative artifact line."""
    merged: dict = {}
    kernel_ok = _kernel_captured(partial_dir)
    for name in _SECTION_ORDER:
        path = os.path.join(partial_dir, f"section_{name}.json")
        if os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
            except Exception:
                continue
            if name == "kernel_cpu":
                if kernel_ok:
                    # a captured device kernel must never be shadowed by
                    # the CPU fallback rerun (VERDICT r4 #1d)
                    data = {k: v for k, v in data.items()
                            if k not in merged}
                # else: the device kernel section holds at most partial
                # metadata (platform=tpu without numbers) — the CPU
                # rerun overrides it wholesale so the artifact can't
                # pair a 'tpu' label with CPU-measured values
            merged.update(data)

    platform = merged.get("kernel_platform", "none")
    value = merged.get("kernel_topics_per_sec", 0)
    final = {
        "metric": "route-matches/sec",
        "value": value,
        "unit": "topics/sec",
        # the MEASURED in-repo anchor (VERDICT r3 weak #8): the
        # host-oracle python trie walk on the same topic distribution
        "vs_host_oracle": merged.get("vs_host_oracle", 0),
        # the reference's published headline (1M msg/s sustained,
        # reference README.md:16) — the BASELINE.md-defined denominator
        "vs_baseline": round(value / 1_000_000, 3),
        "platform": platform,
    }
    final.update(merged)
    # both names stay: `platform` is the headline label, and the
    # artifact-schema lint (tests/test_bench_schema.py) pins the raw
    # `kernel_platform` capture so future runs can't silently drop it
    final["kernel_platform"] = platform
    # crossover point: smallest table size where the device kernel beats
    # the C++ per-message walk (the number that justifies the project)
    cross = None
    for n in CROSS_SIZES:
        dev = merged.get(f"dev_match_tps_{n}",
                         value if n == CROSS_SIZES[-1]
                         and platform not in ("cpu", "none") else None)
        cpp = merged.get(f"cpp_match_tps_{n}")
        if dev and cpp:
            final[f"dev_match_tps_{n}"] = dev
            if cross is None and dev > cpp:
                cross = n
    if cross is not None:
        final["crossover_filters"] = cross
    final.update(meta)
    return final


def _emit(final: dict) -> None:
    print(json.dumps(final), flush=True)


def supervise() -> None:
    import subprocess as sp
    import tempfile

    partial_dir = os.environ.get("BENCH_PARTIAL_DIR")
    if not partial_dir:
        partial_dir = tempfile.mkdtemp(prefix="emqx_bench_")
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET_S", 3300))
    t_start = time.time()

    probe = _probe_device(
        attempts=int(os.environ.get("BENCH_PROBE_RETRIES", 4)),
        timeout_s=float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 45)),
        backoff_s=float(os.environ.get("BENCH_PROBE_BACKOFF_S", 5)),
        total_budget_s=float(os.environ.get("BENCH_PROBE_BUDGET_S", 180)))
    device_ok = probe["ok"]
    if not device_ok:
        log("no usable device after retries; CPU plan — numbers below "
            "are NOT TPU numbers")
    plan = list(DEVICE_PLAN if device_ok else CPU_PLAN)

    section_status: dict = {}
    meta = {
        "probe_ok": device_ok,
        "probe_attempts": probe["attempts"],
        "probe_log": probe["log"][-4:],
        "sections": section_status,
    }
    if not device_ok:
        # the bounded-degradation contract (ISSUE 17): the artifact says
        # WHY this capture is CPU-only, and the probe can never burn
        # more than BENCH_PROBE_BUDGET_S finding out
        meta["probe_degraded_reason"] = probe.get(
            "reason", "device probe failed")
    # Per-section re-probe (VERDICT r5 next #1): the r05 run proved a
    # tunnel can wedge and recover within one bench — a single up-front
    # probe (or a permanent wedged flag) turns one bad minute into zero
    # TPU numbers. A device section re-probes right before launch ONLY
    # when the previous device section failed or timed out (a wedge
    # always manifests as one of those); a healthy run pays zero probe
    # overhead, a wedge skips sections one at a time, and a recovered
    # window still captures the later ones.
    prev_device_bad = False

    i = 0
    while i < len(plan):
        name, needs_device, pin_cpu, deadline = plan[i]
        i += 1
        elapsed = time.time() - t_start
        remaining = budget - elapsed
        if remaining < 90:
            section_status[name] = "skipped: budget exhausted"
            log(f"section {name}: skipped, {remaining:.0f}s of budget left")
            continue
        if needs_device and prev_device_bad:
            re = _probe_device(attempts=1, timeout_s=60, backoff_s=0)
            if not re["ok"]:
                section_status[name] = "skipped: device probe failed"
                log(f"section {name}: skipped, device probe failed "
                    f"(next device section will re-probe)")
                continue
            prev_device_bad = False
        timeout = min(deadline, remaining - 60)
        env = {**os.environ, "BENCH_SECTION": name,
               "BENCH_PARTIAL_DIR": partial_dir}
        child_name = name
        if pin_cpu:
            env["JAX_PLATFORMS"] = "cpu"
            if name == "kernel":
                # CPU fallback rerun: its partial file must not clobber
                # a captured device kernel section
                child_name = "kernel_cpu"
                env["BENCH_SECTION_AS"] = child_name
        log(f"=== section {child_name} (timeout {timeout:.0f}s, "
            f"{remaining:.0f}s budget left) ===")
        t0 = time.time()
        try:
            rc = sp.run([sys.executable, "-u", os.path.abspath(__file__)],
                        env=env, timeout=timeout).returncode
            if rc == 0:
                section_status[name] = f"ok ({time.time()-t0:.0f}s)"
            else:
                section_status[name] = f"failed rc={rc}"
                if needs_device:
                    prev_device_bad = True
        except sp.TimeoutExpired:
            section_status[name] = f"timeout after {timeout:.0f}s"
            log(f"section {child_name}: killed at {timeout:.0f}s deadline")
            if needs_device:
                # the pre-launch probe of the NEXT device section will
                # decide whether this was a slow section or a wedge —
                # no permanent skip flag (a recovered tunnel window
                # must still capture the remaining sections)
                prev_device_bad = True
                meta.setdefault("device_timeouts", []).append(name)
        # cumulative line lands on stdout after EVERY section — a later
        # wedge or driver kill still leaves this tail (VERDICT r4 #1a)
        _emit(_compose(partial_dir, meta))

    # CPU plan (initial probe failed) + budget left → one late re-probe:
    # a tunnel that wedged at minute 0 and recovered at minute 30 must
    # still yield TPU numbers (the tenm section's disk cache makes the
    # second attempt cheap even off a cold child)
    if not device_ok and budget - (time.time() - t_start) > 300:
        re = _probe_device(attempts=1, timeout_s=60, backoff_s=0)
        if re["ok"]:
            log("device recovered after CPU plan; capturing device "
                "kernel/tenm in the remaining budget")
            meta["late_probe_ok"] = True
            for name, deadline in (("kernel", 800), ("tenm", 800)):
                remaining = budget - (time.time() - t_start)
                if remaining < 150:
                    break
                env = {**os.environ, "BENCH_SECTION": name,
                       "BENCH_PARTIAL_DIR": partial_dir}
                env.pop("JAX_PLATFORMS", None)
                try:
                    rc = sp.run([sys.executable, "-u",
                                 os.path.abspath(__file__)], env=env,
                                timeout=min(deadline,
                                            remaining - 60)).returncode
                    section_status[name] = (
                        "ok (late window)" if rc == 0
                        else f"failed rc={rc}")
                except sp.TimeoutExpired:
                    section_status[name] = "timeout (late window)"
                _emit(_compose(partial_dir, meta))

    # device plan without a captured device kernel NUMBER → one labeled
    # CPU kernel rerun so the headline slot is never empty. The gate is
    # the throughput key, not file existence: a kernel child that wedged
    # after its very first put() leaves a section file with only
    # platform/filters keys, and that must still trigger the fallback
    if device_ok and not _kernel_captured(partial_dir):
        remaining = budget - (time.time() - t_start)
        if remaining > 120:
            log("no device kernel captured; running labeled CPU fallback")
            env = {**os.environ, "BENCH_SECTION": "kernel",
                   "BENCH_SECTION_AS": "kernel_cpu",
                   "BENCH_PARTIAL_DIR": partial_dir,
                   "JAX_PLATFORMS": "cpu"}
            try:
                rc = sp.run([sys.executable, "-u",
                             os.path.abspath(__file__)],
                            env=env,
                            timeout=min(700, remaining - 30)).returncode
                section_status["kernel_cpu"] = (
                    "ok" if rc == 0 else f"failed rc={rc}")
            except sp.TimeoutExpired:
                section_status["kernel_cpu"] = "timeout"
            _emit(_compose(partial_dir, meta))

    final = _compose(partial_dir, meta)
    _emit(final)
    sys.exit(0 if final.get("value") else 1)


def run_section(name: str) -> None:
    """Child entry: run one section inline, persisting partials as the
    section's own flush cadence dictates."""
    global flush_results
    alias = os.environ.get("BENCH_SECTION_AS")
    if alias:
        orig = flush_results

        def flush_results(section, _orig=orig, _alias=alias):  # noqa: F811
            _orig(_alias)
    SECTIONS[name]()
    flush_results(name)


if __name__ == "__main__":
    if "--observe-overhead" in sys.argv:
        # standalone micro-run of the telemetry-cost proof (ISSUE 3):
        # same section the supervisor schedules, runnable in seconds
        run_section("observe_overhead")
        sys.exit(0)
    section = os.environ.get("BENCH_SECTION")
    if section:
        run_section(section)
    else:
        supervise()
