"""Counters-layout lint (ISSUE 19 satellite): the kernel packs its
per-batch counters as a positional int32 vector, and the host decoder
reads it back positionally — there is no schema on the wire. The field
order is declared ONCE in ops/trie_match.py; observe/device_metrics.py
carries a literal copy. This lint is the only thing holding the two in
parity, so a field added to one module without the other fails HERE,
not as silently-swapped telemetry."""

import numpy as np
import pytest

from emqx_tpu.observe import device_metrics as dm
from emqx_tpu.ops import trie_match as tm


def test_counter_fields_parity():
    # the load-bearing assert: packer and decoder share one layout
    assert tm.KERNEL_COUNTER_FIELDS == dm.KERNEL_COUNTER_FIELDS
    assert len(set(tm.KERNEL_COUNTER_FIELDS)) == \
        len(tm.KERNEL_COUNTER_FIELDS)


def test_pack_decode_round_trip():
    # distinct sentinels per field: a swapped position cannot cancel
    vals = {n: 100 + i for i, n in enumerate(tm.KERNEL_COUNTER_FIELDS)}
    raw = tm.pack_counters(**vals)
    assert raw.shape == (len(tm.KERNEL_COUNTER_FIELDS),)
    kc = dm.KernelCounters(raw)
    assert kc.n_shards == 1
    for n, v in vals.items():
        assert kc.value(n) == v


def test_pack_decode_round_trip_sharded():
    S = 4
    vals = {n: np.arange(S, dtype=np.int32) * (i + 1)
            for i, n in enumerate(tm.KERNEL_COUNTER_FIELDS)}
    raw = tm.pack_counters(**vals)
    assert raw.shape == (S, len(tm.KERNEL_COUNTER_FIELDS))
    kc = dm.KernelCounters(raw)
    assert kc.n_shards == S
    for i, n in enumerate(tm.KERNEL_COUNTER_FIELDS):
        assert kc.field(n).tolist() == (np.arange(S) * (i + 1)).tolist()
    # fold rule: peaks max over shards, the rest sum
    assert kc.value("frontier_peak") == int(vals["frontier_peak"].max())
    assert kc.value("probe_iters") == int(vals["probe_iters"].sum())


def test_pack_counters_rejects_drifted_field_set():
    vals = {n: 1 for n in tm.KERNEL_COUNTER_FIELDS}
    with pytest.raises(TypeError):
        tm.pack_counters(**{**vals, "bogus_field": 1})
    missing = dict(vals)
    missing.pop(tm.KERNEL_COUNTER_FIELDS[0])
    with pytest.raises(TypeError):
        tm.pack_counters(**missing)


def test_decoder_rejects_wrong_width():
    with pytest.raises(ValueError):
        dm.KernelCounters(np.zeros(len(dm.KERNEL_COUNTER_FIELDS) + 1,
                                   np.int32))


# -- real-kernel spot checks: the counters mean what their names say ------

def _match_stats(filters, topics, K=32, max_levels=8):
    from emqx_tpu.router.index import TrieIndex

    idx = TrieIndex(max_levels=max_levels)
    idx.load(filters)
    dev = tm.device_trie(idx.ensure())
    tok, lens, sysf, too_long = idx.tokenize(topics)
    assert not too_long
    cand, overflow, mstats = tm.match_batch(
        dev, np.asarray(tok), np.asarray(lens), np.asarray(sysf), K=K)
    return (np.asarray(cand), np.asarray(overflow),
            {k: int(v) for k, v in mstats.items()})


def test_kernel_counters_sane_batch():
    filters = ["a/+/c", "a/b/#", "d/e", "a/b/c"]
    cand, overflow, st = _match_stats(filters, ["a/b/c", "d/e", "x/y"])
    n_matched = int(np.sum(cand >= 0))
    assert st["cand_pre"] == n_matched == 4
    assert st["overflow_rows"] == 0
    # 3 matches on row 0 → the frontier held at least 2 live walkers
    assert st["frontier_peak"] >= 2
    # every resolved exact edge costs at least one probe iteration
    assert st["probe_iters"] >= 1


def test_kernel_counters_overflow_rows():
    # a full binary exact/plus fan doubles the frontier every level;
    # K=2 cannot hold the 4-walker front at depth 3 → overflow
    filters = ["a/b/c/d", "a/b/c/+", "a/b/+/d", "a/b/+/+",
               "a/+/c/d", "a/+/c/+", "a/+/+/d", "a/+/+/+"]
    cand, overflow, st = _match_stats(filters, ["a/b/c/d"], K=2)
    assert bool(overflow[0])
    assert st["overflow_rows"] == int(np.sum(overflow)) == 1
    assert st["frontier_peak"] == 2     # clamped at K
