"""Stats-slot drift guard (ISSUE 3 satellite).

``host.cc`` exports its fast-path counters as a flat slot array whose
order MUST match ``native/__init__.py STAT_NAMES`` — the "keep in sync"
comment at the enum was previously enforced by nothing, so a slot added
on one side silently shifted every later counter's meaning. These tests
parse the C++ source directly (no compiler needed):

- every ``kSt*`` slot appears in ``STAT_NAMES`` at the same index under
  the mechanical CamelCase -> snake_case mapping;
- every slot is actually incremented somewhere in ``host.cc`` (a dead
  slot is a lie in the export);
- every exported stat renders in the prometheus text exposition
  (``emqx_native_<name>``), and the histogram stage list matches the
  C++ ``HistStage`` enum the same way.

Round 14: the ad-hoc C++ parsing moved into the shared nativecheck
source model (tools/nativecheck/model.py — comment-aware enum
extraction, the mechanical CamelCase mapping); the assertions below
are unchanged.
"""

import os
import re
import sys

from emqx_tpu import native

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools.nativecheck.model import (  # noqa: E402
    enum_body as _model_enum_body, enumerators, snake as _snake)

HOST_CC = os.path.join(os.path.dirname(__file__), "..", "emqx_tpu",
                       "native", "src", "host.cc")


def _src() -> str:
    with open(HOST_CC) as f:
        return f.read()


def _enum_body(src: str, name: str) -> str:
    # shared model: // comments stripped, so slot docs that NAME other
    # slots ("subset of kStFastIn") never count as enumerators
    return _model_enum_body(src, name)


def _stat_slots() -> list:
    # kStatCount is the sentinel ('a' after kSt breaks the [A-Z] match,
    # so the model's enumerator regex skips it by construction)
    return enumerators(_src(), "StatSlot", "kSt")


def test_stat_slots_match_python_names_and_order():
    got = [_snake(s) for s in _stat_slots()]
    assert got == list(native.STAT_NAMES), (
        "host.cc StatSlot order/name drifted from native.STAT_NAMES:\n"
        f"  C++   : {got}\n  Python: {list(native.STAT_NAMES)}")


def test_every_stat_slot_is_incremented_in_host_cc():
    src = _src()
    for slot in _stat_slots():
        # direct (stats_[kStX].fetch_add) or selected (ternary inside
        # the subscript, e.g. stats_[ok ? kStA : kStB].fetch_add)
        assert re.search(
            rf"stats_\[[^\]]*\bkSt{slot}\b[^\]]*\]\s*\.?\s*fetch_add",
            src), (
            f"kSt{slot} is exported but never incremented in host.cc")


def test_hist_stages_match_cpp_enum():
    stages = enumerators(_src(), "HistStage", "kHist")
    stages = [s for s in stages if s != "Count"]
    assert [_snake(s) for s in stages] == list(native.HIST_STAGES)


def test_prometheus_renders_every_native_stat():
    from emqx_tpu.observe import prometheus

    out = prometheus.render(native={k: 7 for k in native.STAT_NAMES})
    for name in native.STAT_NAMES:
        assert f"emqx_native_{name}" in out, (
            f"stat {name} exported by the host but absent from the "
            f"prometheus exposition")


def test_app_prometheus_carries_native_stats_when_wired():
    """app.prometheus() must pass the native server's stats through —
    the scrape endpoint, not just the render function, sees them."""
    from emqx_tpu.app import BrokerApp

    app = BrokerApp()
    assert app.native_stats_fn is None
    app.native_stats_fn = lambda: {k: 3 for k in native.STAT_NAMES}
    out = app.prometheus()
    for name in native.STAT_NAMES:
        assert f"emqx_native_{name}" in out


# -- cluster trunk (ISSUE 4) -------------------------------------------------


def test_trunk_slots_and_stages_exported():
    """The trunk plane's StatSlots/HistStages must stay exported — the
    mechanical enum lint above would pass if BOTH sides dropped them,
    so their presence is pinned here by name."""
    for name in ("trunk_out", "trunk_in", "trunk_batches_out",
                 "trunk_batches_in", "trunk_punts", "trunk_replays"):
        assert name in native.STAT_NAMES, name
    assert "trunk_rtt" in native.HIST_STAGES
    assert "trunk_batch_n" in native.HIST_STAGES
    # and the C++ side actually defines them (not just the Python list)
    src = _src()
    assert "kStTrunkOut" in src and "kHistTrunkRtt" in src


def test_forward_split_fixed_slots_render_at_zero():
    """messages.forward.native / .slow are FIXED metric slots: they
    render (at zero) in prometheus and ride the $SYS metrics heartbeat
    before the first cross-node leg ever happens."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    assert m.val("messages.forward.native") == 0
    assert m.val("messages.forward.slow") == 0
    out = prometheus.render(metrics=m)
    assert "emqx_messages_forward_native" in out
    assert "emqx_messages_forward_slow" in out

    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m)
    hb.publish_metrics()
    assert seen["$SYS/brokers/n1/metrics/messages.forward.native"] == b"0"
    assert seen["$SYS/brokers/n1/metrics/messages.forward.slow"] == b"0"


# -- durable-session plane (ISSUE 5) -----------------------------------------


def test_durable_slots_and_stages_exported():
    """The durable plane's StatSlots / HistStages stay exported — the
    mechanical enum lint passes if BOTH sides dropped them, so their
    presence is pinned here by name (the trunk-pin pattern)."""
    for name in ("durable_in", "durable_batches", "store_appends",
                 "handoffs"):
        assert name in native.STAT_NAMES, name
    assert "store_append" in native.HIST_STAGES
    assert "replay_drain" in native.HIST_STAGES
    src = _src()
    assert "kStDurableIn" in src and "kHistStoreAppend" in src
    assert "kStHandoffs" in src and "kHistReplayDrain" in src


def test_store_stat_names_match_store_h_enum():
    """STORE_STAT_NAMES mirrors store.h's StoreStat enum the same way
    STAT_NAMES mirrors host.cc's StatSlot (kSsFooBar <-> foo_bar)."""
    store_h = os.path.join(os.path.dirname(HOST_CC), "store.h")
    with open(store_h) as f:
        src = f.read()
    slots = enumerators(src, "StoreStat", "kSs")
    slots = [s for s in slots if s != "StatCount"]
    assert [_snake(s) for s in slots] == list(native.STORE_STAT_NAMES), (
        "store.h StoreStat drifted from native.STORE_STAT_NAMES")


def test_durable_fixed_metric_slots_render_at_zero():
    """messages.durable.stored / .replayed are FIXED metric slots: they
    render (at zero) in prometheus and ride the $SYS metrics heartbeat
    before the first durable publish ever happens."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    assert m.val("messages.durable.stored") == 0
    assert m.val("messages.durable.replayed") == 0
    out = prometheus.render(metrics=m)
    assert "emqx_messages_durable_stored" in out
    assert "emqx_messages_durable_replayed" in out

    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m)
    hb.publish_metrics()
    assert seen["$SYS/brokers/n1/metrics/messages.durable.stored"] == b"0"
    assert seen["$SYS/brokers/n1/metrics/messages.durable.replayed"] == b"0"


# -- one-recovery-path plane (ISSUE 14) ---------------------------------------


def test_one_recovery_path_slots_exported():
    """The store-backed trunk ring's StatSlots and the store's new
    slots stay exported — presence pinned by name (the trunk-pin
    pattern; the mechanical enum lints pass if BOTH sides dropped
    them)."""
    for name in ("trunk_ring_persisted", "trunk_ring_recovered"):
        assert name in native.STAT_NAMES, name
    for name in ("replay_bytes", "sessions", "trunk_pending",
                 "meta_rewrites"):
        assert name in native.STORE_STAT_NAMES, name
    src = _src()
    assert "kStTrunkRingPersisted" in src
    assert "kStTrunkRingRecovered" in src


def test_store_stats_render_in_prometheus():
    """Every STORE_STAT_NAMES slot scrapes as an emqx_native_store_*
    gauge (render-at-zero: a fresh store exports the whole surface)."""
    from emqx_tpu.observe import prometheus

    store = dict.fromkeys(native.STORE_STAT_NAMES, 0)
    out = prometheus.render(native_store=store)
    for name in native.STORE_STAT_NAMES:
        assert f"emqx_native_store_{name}" in out, name


def test_durable_settled_fixed_slot_renders_at_zero():
    """messages.durable.settled (consume-on-ack marker spends) is a
    FIXED metric slot: renders at zero in prometheus and rides the
    $SYS metrics heartbeat before the first settle."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    assert m.val("messages.durable.settled") == 0
    out = prometheus.render(metrics=m)
    assert "emqx_messages_durable_settled" in out

    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m)
    hb.publish_metrics()
    assert seen[
        "$SYS/brokers/n1/metrics/messages.durable.settled"] == b"0"


# -- edge-gateway plane (ISSUE 6) ---------------------------------------------


def test_sn_retain_slots_and_stages_exported():
    """The SN gateway + retained-snapshot planes' StatSlots/HistStages
    stay exported — the mechanical enum lint above passes if BOTH sides
    dropped them, so their presence is pinned here by name (the
    trunk-pin pattern). fetch_add sites and prometheus render-at-zero
    ride the mechanical tests at the top of this file."""
    for name in ("sn_in", "sn_out", "sn_qos_m1", "sn_pings",
                 "sn_registers", "sn_sleep_parked",
                 "retain_set", "retain_del", "retain_deliver",
                 "retain_msgs_out"):
        assert name in native.STAT_NAMES, name
    assert "sn_ingest" in native.HIST_STAGES
    assert "retain_deliver" in native.HIST_STAGES
    src = _src()
    assert "kStSnIn" in src and "kStRetainMsgsOut" in src
    assert "kHistSnIngest" in src and "kHistRetainDeliver" in src


# -- native coap gateway plane (ISSUE 15) -------------------------------------


def test_coap_slots_and_stages_exported():
    """The CoAP gateway plane's StatSlots / HistStages / ledger reason
    stay exported — the mechanical enum lint above passes if BOTH sides
    dropped them, so their presence is pinned here by name (the
    trunk-pin pattern). fetch_add sites and prometheus render-at-zero
    ride the mechanical tests at the top of this file."""
    for name in ("coap_in", "coap_notifies", "coap_pings",
                 "coap_dedup_hits", "coap_rexmits", "coap_giveups",
                 "coap_punts", "coap_drops_oversize"):
        assert name in native.STAT_NAMES, name
    assert "coap_ingest" in native.HIST_STAGES
    assert "observe_notify" in native.HIST_STAGES
    assert "coap_giveup" in native.LEDGER_REASONS
    src = _src()
    assert "kStCoapIn" in src and "kStCoapDropsOversize" in src
    assert "kHistCoapIngest" in src and "kHistObserveNotify" in src
    assert "kLrCoapGiveup" in src


# -- multi-core shard plane (ISSUE 7) -----------------------------------------


def test_shard_slots_and_stage_exported():
    """The shard plane's StatSlots / HistStage stay exported — the
    mechanical enum lint above passes if BOTH sides dropped them, so
    their presence is pinned here by name (the trunk-pin pattern).
    fetch_add sites and prometheus render-at-zero ride the mechanical
    tests at the top of this file."""
    for name in ("shard_ring_out", "shard_ring_in", "shard_ring_full"):
        assert name in native.STAT_NAMES, name
    assert "shard_ring_n" in native.HIST_STAGES
    src = _src()
    assert "kStShardRingOut" in src and "kStShardRingFull" in src
    assert "kHistShardRingN" in src


# -- native distributed tracing + degradation ledger (ISSUE 8) ----------------


def test_span_stages_match_cpp_enum():
    """native.SPAN_STAGES mirrors host.cc's SpanStage enum the same
    mechanical way HIST_STAGES mirrors HistStage."""
    stages = enumerators(_src(), "SpanStage", "kSpan")
    stages = [s for s in stages if s != "Count"]
    assert [_snake(s) for s in stages] == list(native.SPAN_STAGES), (
        "host.cc SpanStage drifted from native.SPAN_STAGES")


def test_ledger_reasons_prefix_and_parity():
    """host.cc's LedgerReason enum is a PREFIX of native.LEDGER_REASONS
    (device_failover / store_degraded are Python-plane reasons), and
    the observe-side canonical tuple matches the native one exactly."""
    from emqx_tpu.observe import metrics as om

    reasons = enumerators(_src(), "LedgerReason", "kLr")
    reasons = [s for s in reasons if s != "Count"]
    got = [_snake(s) for s in reasons]
    assert got == list(native.LEDGER_REASONS[:len(got)]), (
        f"C++ LedgerReason {got} is not a prefix of "
        f"{native.LEDGER_REASONS}")
    assert tuple(om.LEDGER_REASONS) == tuple(native.LEDGER_REASONS)
    # every reason has a fixed messages.ledger.* metric slot
    for r in native.LEDGER_REASONS:
        assert f"messages.ledger.{r}" in om.ALL_NAMES, r


def test_tracing_slots_exported():
    """The tracing plane's StatSlots stay exported (trunk-pin
    pattern)."""
    for name in ("traced_pubs", "span_batches"):
        assert name in native.STAT_NAMES, name
    src = _src()
    assert "kStTracedPubs" in src and "kStSpanBatches" in src


def test_ledger_fixed_metric_slots_render_at_zero():
    """messages.ledger.* are FIXED metric slots: they render (at zero)
    in prometheus and ride the $SYS metrics heartbeat before the first
    degradation ever happens; the ledger totals ride the dedicated
    $SYS ledger heartbeat too."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import DegradationLedger, Metrics
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    for r in native.LEDGER_REASONS:
        assert m.val(f"messages.ledger.{r}") == 0
    out = prometheus.render(metrics=m)
    for r in native.LEDGER_REASONS:
        assert f"emqx_messages_ledger_{r}" in out, r

    led = DegradationLedger(m)
    led.record("shed", 3, shard=1, aux=42)
    assert m.val("messages.ledger.shed") == 3
    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m, ledger=led)
    hb.publish_metrics()
    assert seen["$SYS/brokers/n1/metrics/messages.ledger.shed"] == b"3"
    hb.publish_ledger()
    assert seen["$SYS/brokers/n1/ledger/shed"] == b"3"
    assert seen["$SYS/brokers/n1/ledger/ring_full"] == b"0"
    assert b'"reason": "shed"' in seen["$SYS/brokers/n1/ledger/last"]


def test_connscale_slots_ledger_and_render_at_zero():
    """Conn-scale plane (ISSUE 12): the hibernation/shed stat slots
    stay exported by name, accept_shed is a ledger reason on BOTH
    planes in the C++-prefix position, and the conns.* fixed metric
    slots render at zero in prometheus and ride the $SYS metrics
    heartbeat before the first park ever happens."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import (
        LEDGER_REASONS as M_REASONS, DegradationLedger, Metrics)
    from emqx_tpu.observe.sys import SysHeartbeat

    for name in ("conns_parked", "conns_inflated", "conns_shed",
                 "parked_pings"):
        assert name in native.STAT_NAMES, name
    src = _src()
    assert "kStConnsParked" in src and "kStConnsShed" in src
    # accept_shed sits inside the C++ LedgerReason prefix (the enum
    # parity test above checks order; presence is pinned by name here)
    assert "accept_shed" in native.LEDGER_REASONS
    assert "kLrAcceptShed" in src
    assert tuple(M_REASONS) == tuple(native.LEDGER_REASONS)

    m = Metrics()
    for slot in ("conns.parked", "conns.inflated", "conns.shed",
                 "messages.ledger.accept_shed"):
        assert m.val(slot) == 0
    out = prometheus.render(metrics=m)
    for tok in ("emqx_conns_parked", "emqx_conns_inflated",
                "emqx_conns_shed", "emqx_messages_ledger_accept_shed"):
        assert tok in out, tok
    led = DegradationLedger(m)
    led.record("accept_shed", 2, aux=7)
    assert m.val("messages.ledger.accept_shed") == 2
    m.inc("conns.parked", 5)
    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m, ledger=led)
    hb.publish_metrics()
    assert seen["$SYS/brokers/n1/metrics/conns.parked"] == b"5"
    assert seen["$SYS/brokers/n1/metrics/conns.inflated"] == b"0"
    assert seen[
        "$SYS/brokers/n1/metrics/messages.ledger.accept_shed"] == b"2"
    hb.publish_ledger()
    assert seen["$SYS/brokers/n1/ledger/accept_shed"] == b"2"


def test_prometheus_per_shard_label_set():
    """ISSUE 8 satellite: emqx_native_* gauges AND the stage histograms
    gain a ``shard`` label. The label set is pinned here: every
    exported stat renders per shard as emqx_native_<name>{...,
    shard="<i>"} next to the unlabelled aggregate, and a per-shard
    stage histogram (latency.native.shard<i>.<stage>) renders under
    the AGGREGATE metric name with the shard label."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics

    agg = {k: 7 for k in native.STAT_NAMES}
    shards = [{k: 3 for k in native.STAT_NAMES},
              {k: 4 for k in native.STAT_NAMES}]
    out = prometheus.render(native=agg, native_shards=shards)
    for name in native.STAT_NAMES:
        assert f'emqx_native_{name}{{node="emqx_tpu"}} 7' in out, name
        for i in (0, 1):
            assert (f'emqx_native_{name}'
                    f'{{node="emqx_tpu",shard="{i}"}}') in out, (name, i)
    # exactly ONE TYPE line per metric name despite three series
    assert out.count("# TYPE emqx_native_fast_in gauge") == 1

    m = Metrics()
    m.register_hist("latency.native.ingress_route").observe(1000)
    m.register_hist("latency.native.shard0.ingress_route").observe(1000)
    m.register_hist("latency.native.shard1.ingress_route").observe(2000)
    out = prometheus.render(metrics=m)
    base = "emqx_latency_native_ingress_route_seconds"
    assert f'{base}_count{{node="emqx_tpu"}} 1' in out
    assert f'{base}_count{{node="emqx_tpu",shard="0"}} 1' in out
    assert f'{base}_count{{node="emqx_tpu",shard="1"}} 1' in out
    assert "shard0" not in out          # the name never leaks the shard
    assert out.count(f"# TYPE {base} histogram") == 1


def test_prometheus_bucket_exemplars():
    """Histogram _bucket lines carry OpenMetrics-style exemplars once a
    trace id is hung off them (round 13) — but ONLY under the
    openmetrics flag: exemplar syntax is illegal in the default text
    0.0.4 exposition, where a classic Prometheus parser would fail the
    WHOLE scrape on the '#' after the sample value."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics

    m = Metrics()
    h = m.register_hist("latency.native.ingress_route")
    h.observe(5_000)
    h.put_exemplar(0xABC123, 5_000)
    out = prometheus.render(metrics=m, openmetrics=True)
    assert '# {trace_id="0000000000abc123"}' in out
    assert "trace_id=" not in prometheus.render(metrics=m)  # 0.0.4-clean


# -- faultline (ISSUE 11) -----------------------------------------------------


def test_fault_sites_match_cpp_enum_everywhere():
    """fault.h's Site enum, native.FAULT_SITES, and the observe-side
    canonical tuple all agree (order + the mechanical name mapping) —
    the STAT_NAMES discipline applied to the fault-site catalog."""
    from emqx_tpu.observe import metrics as om

    fault_h = os.path.join(os.path.dirname(HOST_CC), "fault.h")
    with open(fault_h) as f:
        src = f.read()
    sites = [s for s in enumerators(src, "Site", "kSite")
             if s != "Count"]
    assert [_snake(s) for s in sites] == list(native.FAULT_SITES), (
        "fault.h Site enum drifted from native.FAULT_SITES")
    assert tuple(om.FAULT_SITES) == tuple(native.FAULT_SITES)
    # modes too: the Python dict must cover the C++ Mode enum exactly
    modes = [m for m in enumerators(src, "Mode", "kMode")]
    assert sorted(native.FAULT_MODES.values()) == list(
        range(len(modes))), (modes, native.FAULT_MODES)


def test_faults_injected_slot_exported_and_ledger_reason_present():
    """The faultline plane's StatSlot stays exported (trunk-pin
    pattern), and "fault" is a C++-prefix ledger reason with a fixed
    messages.ledger.fault metric slot."""
    from emqx_tpu.observe import metrics as om

    assert "faults_injected" in native.STAT_NAMES
    src = _src()
    assert "kStFaultsInjected" in src and "kLrFault" in src
    assert "fault" in native.LEDGER_REASONS
    # kLrFault sits inside the C++ prefix (ledger entries fold below
    # the GIL for host-plane fires)
    reasons = [_snake(s) for s in enumerators(src, "LedgerReason", "kLr")
               if s != "Count"]
    assert "fault" in reasons
    assert "messages.ledger.fault" in om.ALL_NAMES


def test_faults_fixed_metric_slots_render_at_zero():
    """faults.<site> are FIXED metric slots: they render (at zero) in
    prometheus before the first injection ever fires — chaos
    observability is not opt-in."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics

    m = Metrics()
    for s in native.FAULT_SITES:
        assert m.val(f"faults.{s}") == 0
    out = prometheus.render(metrics=m)
    for s in native.FAULT_SITES:
        assert f"emqx_faults_{s}" in out, s


# -- kernel plane (ISSUE 19) --------------------------------------------------


def test_kernel_fixed_metric_slots_render_at_zero():
    """The kernel plane's promoted slots (messages.kernel.hostmatch,
    kernel.uploads, kernel.upload_patches) and the two appended ledger
    reasons' slots are FIXED: they render (at zero) in prometheus and
    ride the $SYS metrics heartbeat before the first device batch."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics
    from emqx_tpu.observe.sys import SysHeartbeat

    slots = ("messages.kernel.hostmatch", "kernel.uploads",
             "kernel.upload_patches", "messages.ledger.kernel_overflow",
             "messages.ledger.kernel_hostmatch")
    m = Metrics()
    for s in slots:
        assert m.val(s) == 0, s
    out = prometheus.render(metrics=m)
    for s in slots:
        assert "emqx_" + s.replace(".", "_") in out, s

    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m)
    hb.publish_metrics()
    for s in slots:
        assert seen[f"$SYS/brokers/n1/metrics/{s}"] == b"0", s


def test_kernel_ledger_reasons_appended_not_inserted():
    """kernel_overflow / kernel_hostmatch are Python-plane ledger
    reasons: they live AFTER the C++ prefix in both canonical tuples
    (native and observe agree by the existing parity lint; this pins
    that nobody reorders them INTO the prefix, which would shift the
    kind-13 wire encoding)."""
    from emqx_tpu.observe import metrics as om

    reasons = [_snake(s) for s in enumerators(_src(), "LedgerReason",
                                              "kLr") if s != "Count"]
    for r in ("kernel_overflow", "kernel_hostmatch"):
        assert r in om.LEDGER_REASONS and r in native.LEDGER_REASONS
        assert r not in reasons, f"{r} must not enter the C++ enum"
        assert list(om.LEDGER_REASONS).index(r) >= len(reasons)


def test_kernel_stage_hists_render_at_zero_and_shard_labelled():
    """latency.kernel.<stage> histograms render their +Inf/_sum/_count
    series at zero the moment a DeviceMetricsFold exists, and a
    per-shard latency.kernel.shard<i>.<stage> name renders under the
    aggregate metric name with a shard label (the native-plane
    convention, generalized)."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.device_metrics import (KERNEL_STAGES,
                                                 DeviceMetricsFold)
    from emqx_tpu.observe.metrics import Metrics

    m = Metrics()
    DeviceMetricsFold(m)
    out = prometheus.render(metrics=m, node="n1")
    for stage in KERNEL_STAGES:
        base = f"emqx_latency_kernel_{stage}_seconds"
        assert f'{base}_bucket{{node="n1",le="+Inf"}} 0' in out, stage
        assert f'{base}_count{{node="n1"}} 0' in out, stage

    m2 = Metrics()
    m2.register_hist("latency.kernel.shard0.step").observe(1_000_000)
    out2 = prometheus.render(metrics=m2, node="n1")
    assert ('emqx_latency_kernel_step_seconds_count'
            '{node="n1",shard="0"} 1') in out2
