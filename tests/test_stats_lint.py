"""Stats-slot drift guard (ISSUE 3 satellite).

``host.cc`` exports its fast-path counters as a flat slot array whose
order MUST match ``native/__init__.py STAT_NAMES`` — the "keep in sync"
comment at the enum was previously enforced by nothing, so a slot added
on one side silently shifted every later counter's meaning. These tests
parse the C++ source directly (no compiler needed):

- every ``kSt*`` slot appears in ``STAT_NAMES`` at the same index under
  the mechanical CamelCase -> snake_case mapping;
- every slot is actually incremented somewhere in ``host.cc`` (a dead
  slot is a lie in the export);
- every exported stat renders in the prometheus text exposition
  (``emqx_native_<name>``), and the histogram stage list matches the
  C++ ``HistStage`` enum the same way.
"""

import os
import re

from emqx_tpu import native

HOST_CC = os.path.join(os.path.dirname(__file__), "..", "emqx_tpu",
                       "native", "src", "host.cc")


def _src() -> str:
    with open(HOST_CC) as f:
        return f.read()


def _enum_body(src: str, name: str) -> str:
    m = re.search(rf"enum {name}\b[^{{]*\{{(.*?)\}};", src, re.S)
    assert m, f"enum {name} not found in host.cc"
    # strip // comments: slot docs routinely NAME other slots ("subset
    # of kStFastIn"), which must not count as enumerators
    return re.sub(r"//[^\n]*", "", m.group(1))


def _snake(camel: str) -> str:
    return "_".join(p.lower() for p in re.findall(r"[A-Z][a-z0-9]*", camel))


def _stat_slots() -> list:
    # kStatCount is the sentinel ('a' after kSt breaks the [A-Z] match,
    # so the regex skips it by construction)
    return re.findall(r"\bkSt([A-Z]\w*)\b", _enum_body(_src(), "StatSlot"))


def test_stat_slots_match_python_names_and_order():
    got = [_snake(s) for s in _stat_slots()]
    assert got == list(native.STAT_NAMES), (
        "host.cc StatSlot order/name drifted from native.STAT_NAMES:\n"
        f"  C++   : {got}\n  Python: {list(native.STAT_NAMES)}")


def test_every_stat_slot_is_incremented_in_host_cc():
    src = _src()
    for slot in _stat_slots():
        # direct (stats_[kStX].fetch_add) or selected (ternary inside
        # the subscript, e.g. stats_[ok ? kStA : kStB].fetch_add)
        assert re.search(
            rf"stats_\[[^\]]*\bkSt{slot}\b[^\]]*\]\s*\.?\s*fetch_add",
            src), (
            f"kSt{slot} is exported but never incremented in host.cc")


def test_hist_stages_match_cpp_enum():
    stages = re.findall(r"\bkHist([A-Z]\w*)\b",
                        _enum_body(_src(), "HistStage"))
    stages = [s for s in stages if s != "Count"]
    assert [_snake(s) for s in stages] == list(native.HIST_STAGES)


def test_prometheus_renders_every_native_stat():
    from emqx_tpu.observe import prometheus

    out = prometheus.render(native={k: 7 for k in native.STAT_NAMES})
    for name in native.STAT_NAMES:
        assert f"emqx_native_{name}" in out, (
            f"stat {name} exported by the host but absent from the "
            f"prometheus exposition")


def test_app_prometheus_carries_native_stats_when_wired():
    """app.prometheus() must pass the native server's stats through —
    the scrape endpoint, not just the render function, sees them."""
    from emqx_tpu.app import BrokerApp

    app = BrokerApp()
    assert app.native_stats_fn is None
    app.native_stats_fn = lambda: {k: 3 for k in native.STAT_NAMES}
    out = app.prometheus()
    for name in native.STAT_NAMES:
        assert f"emqx_native_{name}" in out


# -- cluster trunk (ISSUE 4) -------------------------------------------------


def test_trunk_slots_and_stages_exported():
    """The trunk plane's StatSlots/HistStages must stay exported — the
    mechanical enum lint above would pass if BOTH sides dropped them,
    so their presence is pinned here by name."""
    for name in ("trunk_out", "trunk_in", "trunk_batches_out",
                 "trunk_batches_in", "trunk_punts", "trunk_replays"):
        assert name in native.STAT_NAMES, name
    assert "trunk_rtt" in native.HIST_STAGES
    assert "trunk_batch_n" in native.HIST_STAGES
    # and the C++ side actually defines them (not just the Python list)
    src = _src()
    assert "kStTrunkOut" in src and "kHistTrunkRtt" in src


def test_forward_split_fixed_slots_render_at_zero():
    """messages.forward.native / .slow are FIXED metric slots: they
    render (at zero) in prometheus and ride the $SYS metrics heartbeat
    before the first cross-node leg ever happens."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    assert m.val("messages.forward.native") == 0
    assert m.val("messages.forward.slow") == 0
    out = prometheus.render(metrics=m)
    assert "emqx_messages_forward_native" in out
    assert "emqx_messages_forward_slow" in out

    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m)
    hb.publish_metrics()
    assert seen["$SYS/brokers/n1/metrics/messages.forward.native"] == b"0"
    assert seen["$SYS/brokers/n1/metrics/messages.forward.slow"] == b"0"


# -- durable-session plane (ISSUE 5) -----------------------------------------


def test_durable_slots_and_stages_exported():
    """The durable plane's StatSlots / HistStages stay exported — the
    mechanical enum lint passes if BOTH sides dropped them, so their
    presence is pinned here by name (the trunk-pin pattern)."""
    for name in ("durable_in", "durable_batches", "store_appends",
                 "handoffs"):
        assert name in native.STAT_NAMES, name
    assert "store_append" in native.HIST_STAGES
    assert "replay_drain" in native.HIST_STAGES
    src = _src()
    assert "kStDurableIn" in src and "kHistStoreAppend" in src
    assert "kStHandoffs" in src and "kHistReplayDrain" in src


def test_store_stat_names_match_store_h_enum():
    """STORE_STAT_NAMES mirrors store.h's StoreStat enum the same way
    STAT_NAMES mirrors host.cc's StatSlot (kSsFooBar <-> foo_bar)."""
    store_h = os.path.join(os.path.dirname(HOST_CC), "store.h")
    with open(store_h) as f:
        src = f.read()
    slots = re.findall(r"\bkSs([A-Z]\w*)\b", _enum_body(src, "StoreStat"))
    slots = [s for s in slots if s != "StatCount"]
    assert [_snake(s) for s in slots] == list(native.STORE_STAT_NAMES), (
        "store.h StoreStat drifted from native.STORE_STAT_NAMES")


def test_durable_fixed_metric_slots_render_at_zero():
    """messages.durable.stored / .replayed are FIXED metric slots: they
    render (at zero) in prometheus and ride the $SYS metrics heartbeat
    before the first durable publish ever happens."""
    from emqx_tpu.observe import prometheus
    from emqx_tpu.observe.metrics import Metrics
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    assert m.val("messages.durable.stored") == 0
    assert m.val("messages.durable.replayed") == 0
    out = prometheus.render(metrics=m)
    assert "emqx_messages_durable_stored" in out
    assert "emqx_messages_durable_replayed" in out

    seen = {}
    hb = SysHeartbeat("n1", lambda msg: seen.__setitem__(
        msg.topic, msg.payload), metrics=m)
    hb.publish_metrics()
    assert seen["$SYS/brokers/n1/metrics/messages.durable.stored"] == b"0"
    assert seen["$SYS/brokers/n1/metrics/messages.durable.replayed"] == b"0"


# -- edge-gateway plane (ISSUE 6) ---------------------------------------------


def test_sn_retain_slots_and_stages_exported():
    """The SN gateway + retained-snapshot planes' StatSlots/HistStages
    stay exported — the mechanical enum lint above passes if BOTH sides
    dropped them, so their presence is pinned here by name (the
    trunk-pin pattern). fetch_add sites and prometheus render-at-zero
    ride the mechanical tests at the top of this file."""
    for name in ("sn_in", "sn_out", "sn_qos_m1", "sn_pings",
                 "sn_registers", "sn_sleep_parked",
                 "retain_set", "retain_del", "retain_deliver",
                 "retain_msgs_out"):
        assert name in native.STAT_NAMES, name
    assert "sn_ingest" in native.HIST_STAGES
    assert "retain_deliver" in native.HIST_STAGES
    src = _src()
    assert "kStSnIn" in src and "kStRetainMsgsOut" in src
    assert "kHistSnIngest" in src and "kHistRetainDeliver" in src


# -- multi-core shard plane (ISSUE 7) -----------------------------------------


def test_shard_slots_and_stage_exported():
    """The shard plane's StatSlots / HistStage stay exported — the
    mechanical enum lint above passes if BOTH sides dropped them, so
    their presence is pinned here by name (the trunk-pin pattern).
    fetch_add sites and prometheus render-at-zero ride the mechanical
    tests at the top of this file."""
    for name in ("shard_ring_out", "shard_ring_in", "shard_ring_full"):
        assert name in native.STAT_NAMES, name
    assert "shard_ring_n" in native.HIST_STAGES
    src = _src()
    assert "kStShardRingOut" in src and "kStShardRingFull" in src
    assert "kHistShardRingN" in src
