"""Platform-aware kernel dispatch (ISSUE 17 satellite): on a cpu
backend RouterModel.publish_batch serves from the host matcher (the C++
SubTable, or the oracle Trie when the native plane didn't build)
instead of the XLA program — BENCH_r05 measured the XLA kernel at 0.1x
the host matcher on CPU, a regression we used to serve.

``EMQX_TPU_CPU_KERNEL`` is the escape hatch: ``xla`` (what conftest
pins for the rest of the suite) forces the device kernel so CPU CI
still validates it; ``host`` forces the matcher; auto picks the matcher
iff the platform is cpu and no mesh was requested.
"""

import pytest

from emqx_tpu.models.router_model import RouterModel
from emqx_tpu.router.index import ShardedTrieIndex, TrieIndex

FILTERS = [
    ("a/b", 1), ("a/+", 2), ("c/#", 3), ("+/b", 4),
    ("deep/x/y/z/w", 5), ("deep/x/+/z/#", 6), ("$SYS/#", 7), ("#", 8),
]
TOPICS = ["a/b", "c/d/e", "deep/x/y/z/w", "$SYS/broker/uptime",
          "no/match/here", "a"]


def _mk(monkeypatch, mode, index=None):
    monkeypatch.setenv("EMQX_TPU_CPU_KERNEL", mode)
    model = RouterModel(index or TrieIndex(max_levels=8), n_sub_slots=256)
    for f, s in FILTERS:
        model.subscribe(f, s)
    model.aux_register("a/#")
    return model


def test_mode_gates(monkeypatch):
    monkeypatch.setenv("EMQX_TPU_CPU_KERNEL", "host")
    assert RouterModel(TrieIndex())._host_matcher is not None
    monkeypatch.setenv("EMQX_TPU_CPU_KERNEL", "xla")
    assert RouterModel(TrieIndex())._host_matcher is None
    # auto: cpu backend + no mesh → host matcher (conftest pins the
    # whole suite to the cpu platform)
    monkeypatch.delenv("EMQX_TPU_CPU_KERNEL")
    assert RouterModel(TrieIndex())._host_matcher is not None


@pytest.mark.parametrize("index_kind", ["flat", "sharded"])
def test_host_dispatch_parity_with_xla(monkeypatch, index_kind):
    def mk_index():
        return (ShardedTrieIndex(4, max_levels=8)
                if index_kind == "sharded" else TrieIndex(max_levels=8))

    host = _mk(monkeypatch, "host", mk_index())
    xla = _mk(monkeypatch, "xla", mk_index())
    rh = host.publish_batch(TOPICS)
    rx = xla.publish_batch(TOPICS)
    assert [sorted(x) for x in rh[0]] == [sorted(x) for x in rx[0]]
    assert [sorted(x) for x in rh[1]] == [sorted(x) for x in rx[1]]
    assert rh[2] == rx[2]
    assert rh[3] == rx[3] == []
    assert host.launch_count == 0 and host.host_match_count == 1
    assert xla.launch_count == 1 and xla.host_match_count == 0


def test_host_dispatch_tracks_unsubscribe_and_aux(monkeypatch):
    model = _mk(monkeypatch, "host")
    assert sorted(model.publish_batch(["a/b"])[0][0]) == \
        ["#", "+/b", "a/+", "a/b"]
    model.unsubscribe("a/+", 2)
    model.unsubscribe("#", 8)
    assert sorted(model.publish_batch(["a/b"])[0][0]) == ["+/b", "a/b"]
    assert model.publish_batch(["a/b"])[1][0] == ["a/#"]
    model.aux_release("a/#")
    assert model.publish_batch(["a/b"])[1][0] == []


def test_host_dispatch_rides_submit_collect(monkeypatch):
    """The pipeline calls submit/collect, not publish_batch — the host
    path must flow through the same two-stage surface."""
    model = _mk(monkeypatch, "host")
    pending = model.publish_batch_submit(["a/b"])
    matched, aux, slots, fallback = model.publish_batch_collect(pending)
    assert "a/b" in matched[0] and slots[0] and fallback == []


def test_host_dispatch_sys_topics(monkeypatch):
    """MQTT-3.7.2-1: root-level wildcards must not match $-topics on
    the host path either (the C++ SubTable doesn't enforce it; the
    dispatch layer does)."""
    model = _mk(monkeypatch, "host")
    r = model.publish_batch(["$SYS/broker/uptime"])
    assert r[0][0] == ["$SYS/#"]
