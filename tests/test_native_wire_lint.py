"""Cross-plane wire-format drift guard (ISSUE 8 satellite).

host.cc documents its event-record wire formats (kinds 6-12) as
``[uNN name]`` field tokens in the header comment — the comment IS the
writer's contract, maintained next to the emission code. The Python
decoders in ``native/__init__.py`` declare what they consume in
``WIRE_FIELDS``. These tests parse the C++ source directly (no
compiler needed) and assert the two sides agree per kind on the exact
(width, name) token set — the cross-plane analogue of the StatSlot
lint: a field added, renamed, or widened on ONE side fails the build
instead of silently mis-decoding every later field.

Round 14: the wire-comment parser moved into the shared nativecheck
source model (tools/nativecheck/model.py wire_kind_sections /
wire_tokens — [u8 1]-style sub-kind markers are still excluded by the
identifier-start requirement); the assertions are unchanged.
"""

import os
import sys

from emqx_tpu import native

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools.nativecheck.model import (  # noqa: E402
    wire_kind_sections, wire_tokens)

HOST_CC = os.path.join(os.path.dirname(__file__), "..", "emqx_tpu",
                       "native", "src", "host.cc")


def _kind_sections() -> dict[int, str]:
    """kind number -> its slice of the wire-format header comment."""
    with open(HOST_CC) as f:
        return wire_kind_sections(f.read())


def test_every_documented_kind_has_a_python_constant():
    """Every event kind host.cc documents is named on the Python side
    (EV_*), and the batched kinds 6-12 all have a WIRE_FIELDS entry."""
    kinds = set(_kind_sections())
    ev_consts = {
        v for k, v in vars(native).items()
        if k.startswith("EV_") and isinstance(v, int)}
    missing = kinds - ev_consts
    assert not missing, (
        f"host.cc documents event kinds {sorted(missing)} with no EV_* "
        f"constant in native/__init__.py")
    for kind in range(6, 14):
        assert kind in kinds, f"kind {kind} undocumented in host.cc"
        assert kind in native.WIRE_FIELDS, (
            f"kind {kind} has no WIRE_FIELDS declaration")


def test_wire_fields_match_host_cc_comment_per_kind():
    """Per kind 6-12: the set of (width, name) tokens in the C++
    wire-format comment equals the Python decoder declaration exactly.
    Width drift (u32 -> u64) changes the token and fails; a new field
    on either side fails until both are updated."""
    sections = _kind_sections()
    for kind, want in sorted(native.WIRE_FIELDS.items()):
        got = wire_tokens(sections[kind])
        assert got == want, (
            f"kind {kind} wire drift:\n"
            f"  host.cc comment : {sorted(got)}\n"
            f"  WIRE_FIELDS     : {sorted(want)}\n"
            f"  only in C++     : {sorted(got - want)}\n"
            f"  only in Python  : {sorted(want - got)}")


def test_declared_widths_are_real_widths():
    """Spot-check that WIRE_FIELDS agrees with what the decoders
    actually slice — the table must describe the code, not just the
    comment. Exercises one synthetic record per decoder."""
    # kind 12 spans: 25-byte body per span sub-record
    span = (bytes([1]) + (0xBEEF).to_bytes(8, "little") + bytes([7])
            + (123456).to_bytes(8, "little") + (42).to_bytes(8, "little"))
    ledger = (bytes([2, 3]) + (9).to_bytes(8, "little")
              + (0xBEEF).to_bytes(8, "little") + (5).to_bytes(8, "little")
              + (777).to_bytes(8, "little"))
    recs = native.parse_spans(span + ledger)
    assert recs == [("span", 0xBEEF, 7, 123456, 42),
                    ("ledger", 3, 9, 0xBEEF, 5, 777)]

    # kind 10 durable entry with the bit4 trace extension
    entry = ((11).to_bytes(8, "little") + bytes([0b10011])  # inline+qos1+trace
             + (1).to_bytes(2, "little") + (77).to_bytes(8, "little")
             + (3).to_bytes(2, "little") + b"t/x"
             + (0xCAFE).to_bytes(8, "little")
             + (2).to_bytes(4, "little") + b"hi")
    payload = ((100).to_bytes(8, "little") + (5).to_bytes(8, "little")
               + (1).to_bytes(4, "little") + entry)
    base, ts, entries = native.parse_durable(payload)
    assert (base, ts) == (100, 5)
    assert entries == [(11, 0b10011, [77], "t/x", b"hi", 0xCAFE, "")]

    # kind 9 sub-3 punt entry with a trace id skipped losslessly
    punt = (bytes([3]) + (11).to_bytes(8, "little") + bytes([0b10011])
            + (3).to_bytes(2, "little") + b"t/y"
            + (0xCAFE).to_bytes(8, "little")
            + (2).to_bytes(4, "little") + b"yo")
    assert native.parse_trunk_punts(punt) == [(11, 1, False, "t/y", b"yo")]


def test_store_record_types_match_store_h_constants():
    """The store's on-disk record catalog (ISSUE 14): every kRec*
    constant in store.h matches native.STORE_RECORD_TYPES by name AND
    value — a record type added or renumbered on one side fails here
    instead of silently mis-walking the recovery scan."""
    import re

    store_h = os.path.join(os.path.dirname(HOST_CC), "store.h")
    with open(store_h) as f:
        src = f.read()
    got = {}
    for m in re.finditer(
            r"constexpr\s+uint8_t\s+kRec([A-Za-z0-9]+)\s*=\s*(\d+)\s*;",
            src):
        name = re.sub(r"(?<!^)(?=[A-Z])", "_", m.group(1)).lower()
        got[name] = int(m.group(2))
    assert got == native.STORE_RECORD_TYPES, (
        f"store.h kRec* drifted from native.STORE_RECORD_TYPES:\n"
        f"  C++   : {got}\n  Python: {native.STORE_RECORD_TYPES}")
