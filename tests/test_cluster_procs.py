"""Distributed tests with REAL peer processes — the ct_slave pattern
(SURVEY.md §4.3): every node is its own OS process running the full
broker + cluster stack on loopback; clients are real sockets; failure
injection = killing a process."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Peer:
    def __init__(self, name: str, cluster_port: int,
                 peers: list[str], seed: str | None,
                 mgmt: bool = False,
                 env: dict | None = None) -> None:
        cmd = [sys.executable, "-m", "emqx_tpu.cluster.peer",
               "--name", name, "--cluster-port", str(cluster_port),
               "--mqtt-port", "0"]
        for p in peers:
            cmd += ["--peer", p]
        if seed:
            cmd += ["--seed", seed]
        if mgmt:
            cmd += ["--mgmt"]
        env = {**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})}
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, cwd=os.path.dirname(os.path.dirname(__file__)),
            env=env)
        line = self.proc.stdout.readline().strip()
        assert line.startswith("READY"), f"peer {name} failed: {line!r}"
        parts = line.split()
        self.mqtt_port = int(parts[1])
        self.mgmt_port = int(parts[2]) if len(parts) > 2 else 0
        # trailing key=value fields (e.g. the negotiated rlog version)
        self.info = dict(p.split("=", 1) for p in parts[3:] if "=" in p)

    def kill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


@pytest.fixture()
def two_peers():
    p1_port, p2_port = _free_port(), _free_port()
    n1 = Peer("n1", p1_port, [f"n2:127.0.0.1:{p2_port}"], seed=None)
    n2 = Peer("n2", p2_port, [f"n1:127.0.0.1:{p1_port}"], seed="n1")
    yield n1, n2
    n1.stop()
    n2.stop()


def test_cross_process_pubsub(two_peers):
    """Subscribe on n2, publish on n1 → route replication + forwarding
    across a REAL process/socket boundary."""
    import asyncio

    from emqx_tpu.mqtt.client import MqttClient

    n1, n2 = two_peers

    async def main():
        sub = MqttClient(port=n2.mqtt_port, clientid="sub-proc")
        await sub.connect()
        await sub.subscribe("fleet/+/speed", qos=1)
        await asyncio.sleep(0.6)       # route replication settles
        pub = MqttClient(port=n1.mqtt_port, clientid="pub-proc")
        await pub.connect()
        await pub.publish("fleet/v1/speed", b"88", qos=1)
        got = await sub.recv(timeout=10)
        assert got.topic == "fleet/v1/speed" and got.payload == b"88"
        await pub.disconnect()
        await sub.disconnect()
    asyncio.run(main())


def test_peer_kill_purges_routes_and_keeps_serving(two_peers):
    """SIGKILL one peer: the survivor must detect the death, purge its
    routes, and keep serving local traffic (emqx_router_helper nodedown,
    SURVEY.md §5 failure detection)."""
    import asyncio

    from emqx_tpu.mqtt.client import MqttClient

    n1, n2 = two_peers

    async def main():
        sub2 = MqttClient(port=n2.mqtt_port, clientid="doomed")
        await sub2.connect()
        await sub2.subscribe("will-vanish/#", qos=0)
        await asyncio.sleep(0.6)
        n2.kill()
        # survivor keeps serving; publish to the dead route must not wedge
        c = MqttClient(port=n1.mqtt_port, clientid="survivor")
        await c.connect()
        await c.publish("will-vanish/x", b"into-the-void")
        await c.subscribe("local/#", qos=0)
        await c.publish("local/ok", b"alive")
        got = await c.recv(timeout=10)
        assert got.payload == b"alive"
        await c.disconnect()
    asyncio.run(main())


def test_cross_process_session_takeover(two_peers):
    """clean_start=False reconnect on the OTHER node takes the session
    over across the process boundary (emqx_cm takeover, SURVEY §3.4)."""
    import asyncio

    from emqx_tpu.mqtt.client import MqttClient

    n1, n2 = two_peers

    async def main():
        c1 = MqttClient(port=n1.mqtt_port, clientid="roamer",
                        clean_start=False)
        await c1.connect()
        await c1.subscribe("sticky/#", qos=1)
        await asyncio.sleep(0.6)
        # reconnect on the other node with the same clientid
        c2 = MqttClient(port=n2.mqtt_port, clientid="roamer",
                        clean_start=False)
        ack = await c2.connect()
        assert ack.session_present          # session migrated
        await asyncio.sleep(0.6)
        pub = MqttClient(port=n1.mqtt_port, clientid="tk-pub")
        await pub.connect()
        await pub.publish("sticky/1", b"followed-you", qos=1)
        got = await c2.recv(timeout=10)
        assert got.payload == b"followed-you"
        await pub.disconnect()
        await c2.disconnect()
    asyncio.run(main())


# -- mixed-version rolling-upgrade interop -------------------------------------

def _pubsub_roundtrip(sub_port: int, pub_port: int, topic: str,
                      payload: bytes) -> None:
    """Subscribe on one node, publish on the other, assert delivery —
    the functional proof that route deltas crossed the wire."""
    import asyncio

    from emqx_tpu.mqtt.client import MqttClient

    async def main():
        sub = MqttClient(port=sub_port, clientid="mv-sub")
        await sub.connect()
        await sub.subscribe(topic, qos=1)
        await asyncio.sleep(0.6)       # route replication settles
        pub = MqttClient(port=pub_port, clientid="mv-pub")
        await pub.connect()
        await pub.publish(topic, payload, qos=1)
        got = await sub.recv(timeout=10)
        assert got.payload == payload
        await pub.disconnect()
        await sub.disconnect()
    asyncio.run(main())


def test_mixed_version_rlog_negotiation_downshifts():
    """VERDICT next #7: one node pins rlog v1 (default registry), the
    other registers v2 (EMQX_BPAPI_RLOG_V2). bpapi.negotiate must land
    the v2 node on v1 at join, and route deltas must still apply across
    the process boundary on the v1 dict wire — the reference's
    mid-rolling-upgrade cluster shape."""
    p1_port, p2_port = _free_port(), _free_port()
    n1 = Peer("n1", p1_port, [f"n2:127.0.0.1:{p2_port}"], seed=None)
    n2 = Peer("n2", p2_port, [f"n1:127.0.0.1:{p1_port}"], seed="n1",
              env={"EMQX_BPAPI_RLOG_V2": "1"})
    try:
        assert n1.info.get("rlog") == "1", n1.info   # v1-only node
        # the joiner supports [1, 2] but its peer announced [1]:
        # negotiate downshifted to 1
        assert n2.info.get("rlog") == "1", n2.info
        _pubsub_roundtrip(n2.mqtt_port, n1.mqtt_port,
                          "mixed/ver/speed", b"downshifted")
    finally:
        n1.stop()
        n2.stop()


def test_v2_cluster_negotiates_up_and_replicates():
    """Both sides register rlog v2: negotiate lands on 2 and the
    compact tuple delta wire (apply_deltas2) carries the routes."""
    v2 = {"EMQX_BPAPI_RLOG_V2": "1"}
    p1_port, p2_port = _free_port(), _free_port()
    n1 = Peer("n1", p1_port, [f"n2:127.0.0.1:{p2_port}"], seed=None,
              env=v2)
    n2 = Peer("n2", p2_port, [f"n1:127.0.0.1:{p1_port}"], seed="n1",
              env=v2)
    try:
        assert n2.info.get("rlog") == "2", n2.info
        _pubsub_roundtrip(n2.mqtt_port, n1.mqtt_port,
                          "v2/wire/topic", b"tuple-wire")
        # and the reverse direction (n1 flushes to n2 on the v2 wire
        # it learned from n2's hello)
        _pubsub_roundtrip(n1.mqtt_port, n2.mqtt_port,
                          "v2/rev/topic", b"reverse")
    finally:
        n1.stop()
        n2.stop()


# -- cluster config transactions across real processes -------------------------

def _http(port, method, path, body=None, token=None):
    import json
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method)
    req.add_header("Content-Type", "application/json")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read() or b"null")


def _login(port):
    return _http(port, "POST", "/api/v5/login",
                 {"username": "admin", "password": "public"})["token"]


def test_config_txn_replication_and_lagging_peer_catchup():
    """emqx_cluster_rpc across REAL processes: a PUT /configs on one node
    is visible on the other; a node that was DEAD during several txns
    catches the whole log up when it rejoins (emqx_conf_app_SUITE's
    cluster_rpc catch-up scenario)."""
    import time as _t

    p1_port, p2_port = _free_port(), _free_port()
    n1 = Peer("n1", p1_port, [f"n2:127.0.0.1:{p2_port}"], seed=None,
              mgmt=True)
    n2 = Peer("n2", p2_port, [f"n1:127.0.0.1:{p1_port}"], seed="n1",
              mgmt=True)
    n2b = None
    try:
        t1 = _login(n1.mgmt_port)
        t2 = _login(n2.mgmt_port)
        # cluster-wide PUT via n2 (non-coordinator: forwards to n1)
        _http(n2.mgmt_port, "PUT", "/api/v5/configs",
              {"path": "mqtt.max_packet_size", "value": 4096}, t2)
        v1 = _http(n1.mgmt_port, "GET",
                   "/api/v5/configs?path=mqtt.max_packet_size",
                   token=t1)["value"]
        assert v1 == 4096

        status = _http(n1.mgmt_port, "GET", "/api/v5/cluster_rpc",
                       token=t1)["data"]
        assert {s["node"]: s["tnx_id"] for s in status} == \
            {"n1": 1, "n2": 1}

        # n2 dies; txns continue on n1
        n2.kill()
        for v in (8192, 16384):
            _http(n1.mgmt_port, "PUT", "/api/v5/configs",
                  {"path": "mqtt.max_packet_size", "value": v}, t1)

        # n2 rejoins on the same ports → bootstrap replays the conf log
        n2b = Peer("n2", p2_port, [f"n1:127.0.0.1:{p1_port}"], seed="n1",
                   mgmt=True)
        t2b = _login(n2b.mgmt_port)
        deadline = _t.time() + 15
        val = None
        while _t.time() < deadline:
            val = _http(n2b.mgmt_port, "GET",
                        "/api/v5/configs?path=mqtt.max_packet_size",
                        token=t2b)["value"]
            if val == 16384:
                break
            _t.sleep(0.5)
        assert val == 16384, f"lagging peer never caught up (saw {val})"
        st2 = _http(n2b.mgmt_port, "GET", "/api/v5/cluster_rpc",
                    token=t2b)["data"]
        assert any(s["node"] == "n2" and s["tnx_id"] == 3 for s in st2)
    finally:
        n1.stop()
        n2.stop()
        if n2b is not None:
            n2b.stop()
