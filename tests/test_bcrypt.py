"""In-repo bcrypt (native/src/bcrypt.cc) — the reference's bcrypt NIF
analogue (mix.exs:635, emqx_authn_password_hashing.erl). The Blowfish
tables are COMPUTED from pi at init (Machin fixed-point), so these
vector tests double as a proof the table derivation is exact."""

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.access.hashing import (HashSpec, check_password,  # noqa: E402
                                     gen_salt, hash_password)

# published OpenBSD / John-the-Ripper bcrypt test vectors
VECTORS = [
    (b"U*U", "$2a$05$CCCCCCCCCCCCCCCCCCCCC.E5YPO9kmyuRGyh0XouQYb4YMJKvyOeW"),
    (b"U*U*", "$2a$05$CCCCCCCCCCCCCCCCCCCCC.VGOzA784oUp/Z0DY336zx7pLYAy0lwK"),
    (b"U*U*U", "$2a$05$XXXXXXXXXXXXXXXXXXXXXOAcXxm9kjPGEMsLznoKqmqw7tc8WCx4a"),
]


@pytest.mark.parametrize("password,expected", VECTORS)
def test_known_vectors(password, expected):
    spec = HashSpec(name="bcrypt")
    got = hash_password(spec, expected[:29].encode(), password)
    assert got.decode() == expected


def test_hash_roundtrip_and_reject():
    spec = HashSpec(name="bcrypt", salt_rounds=4)   # fast cost for tests
    salt = gen_salt(spec)
    assert salt.startswith(b"$2b$04$") and len(salt) == 29
    stored = hash_password(spec, salt, b"s3cret")
    assert len(stored) == 60
    assert check_password(spec, salt, stored, b"s3cret")
    assert not check_password(spec, salt, stored, b"wrong")
    assert not check_password(spec, salt, b"$2b$04$garbage", b"s3cret")


def test_long_passwords_truncate_at_72():
    spec = HashSpec(name="bcrypt", salt_rounds=4)
    salt = gen_salt(spec)
    a = hash_password(spec, salt, b"x" * 72)
    b = hash_password(spec, salt, b"x" * 100)   # $2b truncation
    assert a == b


def test_authn_chain_with_bcrypt_credentials():
    """bcrypt through the real authn surface: builtin database with
    bcrypt-hashed credentials accepts the right password."""
    from emqx_tpu.access.authn import AuthnChain, BuiltinDbProvider

    chain = AuthnChain()
    p = BuiltinDbProvider(
        hash_spec=HashSpec(name="bcrypt", salt_rounds=4))
    p.add_user("alice", "pw-alice")
    chain.add(p)
    ok = chain.authenticate(dict(clientid="c1", username="alice",
                                 password=b"pw-alice"))
    assert ok[0] == "ok", ok
    bad = chain.authenticate(dict(clientid="c1", username="alice",
                                  password=b"nope"))
    assert bad[0] == "error", bad
