"""Broker core tests — mirrors emqx_broker_SUITE / emqx_hooks_SUITE."""

import pytest

from emqx_tpu.broker.broker import Broker, SlotRegistry
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.core.message import Message, SubOpts


def msg(topic="t/1", **kw):
    return Message(topic=topic, **kw)


# -- hooks ------------------------------------------------------------------

def test_hooks_priority_and_stop():
    h = Hooks()
    calls = []
    h.add("p", lambda: calls.append("lo"), priority=1)
    h.add("p", lambda: calls.append("hi"), priority=10)
    h.run("p")
    assert calls == ["hi", "lo"]

    h2 = Hooks()
    h2.add("p", lambda: Hooks.STOP, priority=5)
    h2.add("p", lambda: calls.append("never"), priority=1)
    h2.run("p")
    assert "never" not in calls


def test_hooks_run_fold():
    h = Hooks()
    h.add("f", lambda acc: acc + 1, priority=3)
    h.add("f", lambda acc: (Hooks.OK, acc * 10), priority=2)
    h.add("f", lambda acc: (Hooks.STOP, acc + 5), priority=1)
    h.add("f", lambda acc: acc + 100, priority=0)   # never reached
    assert h.run_fold("f", (), 1) == 25             # ((1+1)*10)+5


def test_hooks_put_replaces_and_delete():
    h = Hooks()
    def a(acc): return acc + 1
    h.add("f", a, priority=1)
    h.add("f", a, priority=9)      # idempotent add: keeps first
    assert h.run_fold("f", (), 0) == 1
    h.put("f", a, priority=2)
    assert h.run_fold("f", (), 0) == 1
    h.delete("f", a)
    assert h.run_fold("f", (), 0) == 0


# -- slot registry ----------------------------------------------------------

def test_slot_registry_recycling():
    r = SlotRegistry(capacity=4)
    s1, s2 = r.get_or_assign("a"), r.get_or_assign("b")
    assert {s1, s2} == {0, 1}
    assert r.get_or_assign("a") == s1
    r.release("a")
    assert list(r.lookup_sids(s1)) == []
    assert r.get_or_assign("c") == s1   # recycled
    r.get_or_assign("d")
    assert r.capacity == 4              # FIXED — never grows


def test_slot_registry_shards_past_capacity():
    """Past capacity, sids hash into the fixed shard space and a slot
    holds several candidates (emqx_broker_helper sharding analogue)."""
    r = SlotRegistry(capacity=4)
    sids = [f"client-{i}" for i in range(20)]
    slots = [r.get_or_assign(s) for s in sids]
    assert all(0 <= s < 4 for s in slots)
    assert r.capacity == 4
    # every sid is findable through its slot
    for sid, slot in zip(sids, slots):
        assert sid in r.lookup_sids(slot)
    # release keeps co-tenants intact
    r.release(sids[10])
    assert sids[10] not in r.lookup_sids(slots[10])
    for sid, slot in zip(sids, slots):
        if sid != sids[10]:
            assert sid in r.lookup_sids(slot)


# -- pub/sub ----------------------------------------------------------------

def test_subscribe_publish_deliver():
    b = Broker()
    b.subscribe("s1", "t/+")
    b.subscribe("s2", "t/1", SubOpts(qos=1))
    b.subscribe("s3", "other")
    d = b.publish(msg("t/1"))
    assert set(d) == {"s1", "s2"}
    assert d["s1"] == [("t/+", d["s1"][0][1])]
    assert b.metrics.val("messages.delivered") == 2


def test_unsubscribe_and_subscriber_down():
    b = Broker()
    b.subscribe("s1", "a/#")
    b.subscribe("s1", "b")
    b.subscribe("s2", "b")
    assert b.unsubscribe("s1", "a/#") is True
    assert b.unsubscribe("s1", "a/#") is False
    assert set(b.publish(msg("b"))) == {"s1", "s2"}
    assert b.subscriber_down("s1") == 1
    assert set(b.publish(msg("b"))) == {"s2"}
    assert b.router.stats()["filters.count"] == 0


def test_publish_hook_can_rewrite_and_drop():
    b = Broker()
    b.subscribe("s1", "t")
    b.hooks.add("message.publish", lambda m: m.set_header("tag", 1))
    d = b.publish(msg("t"))
    assert d["s1"][0][1].headers["tag"] == 1
    # drop via allow_publish=False (the emqx header convention)
    b.hooks.put(
        "message.publish",
        lambda m: m.set_header("allow_publish", False) and None or
        m.set_header("allow_publish", False),
        priority=99,
    )
    assert b.publish(msg("t")) == {}
    assert b.metrics.val("messages.dropped") == 1


def test_remote_route_forwarding():
    fwd = []
    b = Broker(node="n1", forward_fn=lambda node, t, m: fwd.append((node, t)))
    b.subscribe("s1", "t")
    b.router.add_route("t", "n2")     # simulated remote subscriber
    d = b.publish(msg("t"))
    assert set(d) == {"s1"}
    assert fwd == [("n2", "t")]


def test_shared_group_routes_to_dispatcher():
    picked = []
    def dispatch(group, topic, m):
        picked.append(group)
        return [("member1", f"$share/{group}/{topic}")]
    b = Broker(shared_dispatch=dispatch)
    b.subscribe("member1", "$share/g1/t")
    d = b.publish(msg("t"))
    assert picked == ["g1"]
    assert set(d) == {"member1"}


def test_no_subscribers_drop_metric():
    b = Broker()
    dropped = []
    b.hooks.add("message.dropped", lambda m, why: dropped.append(why))
    assert b.publish(msg("nobody")) == {}
    assert dropped == ["no_subscribers"]


# -- device-path batch ------------------------------------------------------

def test_publish_batch_device_path_equals_host():
    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.router.index import TrieIndex

    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    b = Broker(router_model=model)
    b.subscribe("s1", "t/+")
    b.subscribe("s2", "t/1")
    b.subscribe("s3", "zzz/#")
    msgs = [msg("t/1"), msg("t/2"), msg("nope"), msg("zzz/a/b")]
    got_dev = b.publish_batch(msgs)

    b2 = Broker()
    for s, t in [("s1", "t/+"), ("s2", "t/1"), ("s3", "zzz/#")]:
        b2.subscribe(s, t)
    got_host = [b2.publish(m) for m in msgs]
    for dd, hh in zip(got_dev, got_host):
        assert {k: [t for t, _ in v] for k, v in dd.items()} == \
               {k: [t for t, _ in v] for k, v in hh.items()}


def test_publish_batch_with_hook_drop():
    b = Broker()
    b.subscribe("s1", "a")
    b.hooks.add(
        "message.publish",
        lambda m: m.set_header("allow_publish", False) if m.topic == "a" else m,
    )
    out = b.publish_batch([msg("a"), msg("a")])
    assert out == [{}, {}]
