"""MQTT 5 conformance breadth over real sockets — the
emqx_mqtt_protocol_v5_SUITE areas not covered elsewhere: subscription
options Retain-As-Published / Retain-Handling, request/response +
user-property pass-through, client Receive-Maximum governing the
SERVER's send window, and Message-Expiry-Interval countdown."""

import asyncio

import pytest

from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.client import MqttClient


@pytest.fixture
def run():
    def _run(scenario):
        async def main():
            server = BrokerServer(port=0)
            await server.start()
            try:
                await scenario(server)
            finally:
                await server.stop()
        asyncio.run(main())
    return _run


def _c(server, cid, **kw):
    return MqttClient(port=server.port, clientid=cid, proto_ver=5, **kw)


def test_retain_as_published(run):
    """[MQTT-3.8.3.1] rap=1 keeps the retain flag on forwarded
    messages; rap=0 clears it."""
    async def scenario(server):
        raw = _c(server, "raw")
        plain = _c(server, "plain")
        pub = _c(server, "pub")
        for c in (raw, plain, pub):
            await c.connect()
        await raw.subscribe("r/t", qos=0, rap=1)
        await plain.subscribe("r/t", qos=0)
        await pub.publish("r/t", b"x", retain=True)
        assert (await raw.recv()).retain is True
        assert (await plain.recv()).retain is False
        for c in (raw, plain, pub):
            await c.disconnect()
    run(scenario)


def test_retain_handling(run):
    """[MQTT-3.8.3.1] rh=0 always sends retained on subscribe; rh=1
    only when the subscription is NEW; rh=2 never."""
    async def scenario(server):
        pub = _c(server, "pub")
        await pub.connect()
        await pub.publish("rh/t", b"kept", retain=True)

        sub = _c(server, "sub")
        await sub.connect()
        await sub.subscribe("rh/t", qos=0, rh=2)       # never
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.3)

        await sub.subscribe("rh/t", qos=0, rh=1)       # existing sub: no
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.3)

        await sub.subscribe("rh/t", qos=0, rh=0)       # always
        assert (await sub.recv()).payload == b"kept"

        fresh = _c(server, "fresh")
        await fresh.connect()
        await fresh.subscribe("rh/t", qos=0, rh=1)     # new sub: yes
        assert (await fresh.recv()).payload == b"kept"
        for c in (pub, sub, fresh):
            await c.disconnect()
    run(scenario)


def test_request_response_properties_pass_through(run):
    """[MQTT-3.3.2] Response-Topic, Correlation-Data and User-Property
    must reach the subscriber unchanged (the broker never interprets
    them)."""
    async def scenario(server):
        responder = _c(server, "responder")
        requester = _c(server, "requester")
        await responder.connect()
        await requester.connect()
        await responder.subscribe("svc/req", qos=1)
        await requester.subscribe("svc/resp/42", qos=1)

        await requester.publish("svc/req", b"do-it", qos=1, properties={
            "Response-Topic": "svc/resp/42",
            "Correlation-Data": b"corr-7",
            "User-Property": [("trace", "abc"), ("hop", "1")],
        })
        req = await responder.recv()
        props = req.properties or {}
        assert props.get("Response-Topic") == "svc/resp/42"
        assert props.get("Correlation-Data") == b"corr-7"
        assert ("trace", "abc") in (props.get("User-Property") or [])

        # the response flows back over the carried Response-Topic
        await responder.publish(props["Response-Topic"], b"done", qos=1,
                                properties={
                                    "Correlation-Data":
                                        props["Correlation-Data"]})
        resp = await requester.recv()
        assert resp.payload == b"done"
        assert (resp.properties or {}).get("Correlation-Data") == b"corr-7"
        await responder.disconnect()
        await requester.disconnect()
    run(scenario)


def test_client_receive_maximum_caps_server_window(run):
    """[MQTT-3.1.2-11] CONNECT Receive-Maximum=1: the server may keep
    only ONE un-acked QoS1 PUBLISH toward us; the next arrives only
    after our PUBACK."""
    async def scenario(server):
        sub = _c(server, "sub", auto_ack=False,
                 properties={"Receive-Maximum": 1})
        pub = _c(server, "pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("fc/t", qos=1)
        for i in range(3):
            await pub.publish("fc/t", b"%d" % i, qos=1)

        first = await sub.recv()
        assert first.payload == b"0"
        with pytest.raises(asyncio.TimeoutError):   # window is full
            await sub.recv(timeout=0.4)

        await sub.puback(first.packet_id)           # frees the window
        second = await sub.recv()
        assert second.payload == b"1"
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.4)
        await sub.puback(second.packet_id)
        assert (await sub.recv()).payload == b"2"
        await sub.disconnect()
        await pub.disconnect()
    run(scenario)


def test_message_expiry_interval_counts_down(run):
    """[MQTT-3.3.2-6] a queued message's Message-Expiry-Interval is
    forwarded MINUS the time spent waiting; fully expired messages are
    not delivered."""
    async def scenario(server):
        sub = _c(server, "sub", clean_start=False,
                 properties={"Session-Expiry-Interval": 300})
        pub = _c(server, "pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("exp/t", qos=1)
        await sub.close()                     # offline, session kept

        await pub.publish("exp/t", b"keeps", qos=1,
                          properties={"Message-Expiry-Interval": 100})
        await asyncio.sleep(1.1)

        back = _c(server, "sub", clean_start=False,
                  properties={"Session-Expiry-Interval": 300})
        ack = await back.connect()
        assert ack.session_present
        got = await back.recv()
        assert got.payload == b"keeps"
        remaining = (got.properties or {}).get("Message-Expiry-Interval")
        assert remaining is not None and remaining <= 99
        await back.disconnect()
        await pub.disconnect()
    run(scenario)


def test_expired_message_not_delivered_on_resume(run):
    async def scenario(server):
        sub = _c(server, "sub2", clean_start=False,
                 properties={"Session-Expiry-Interval": 300})
        pub = _c(server, "pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("exp2/t", qos=1)
        await sub.close()

        await pub.publish("exp2/t", b"dies", qos=1,
                          properties={"Message-Expiry-Interval": 1})
        await pub.publish("exp2/t", b"lives", qos=1)
        await asyncio.sleep(1.3)

        back = _c(server, "sub2", clean_start=False,
                  properties={"Session-Expiry-Interval": 300})
        await back.connect()
        got = await back.recv()
        assert got.payload == b"lives"       # the expired one is gone
        assert back.messages.empty()
        await back.disconnect()
        await pub.disconnect()
    run(scenario)


def test_no_local_over_socket(run):
    """[MQTT-3.8.3.1] nl=1: a client's own publishes do not loop back."""
    async def scenario(server):
        c = _c(server, "looper")
        other = _c(server, "other")
        await c.connect()
        await other.connect()
        await c.subscribe("nl/t", qos=0, nl=1)
        await c.publish("nl/t", b"self")
        await other.publish("nl/t", b"peer")
        got = await c.recv()
        assert got.payload == b"peer"
        assert c.messages.empty()
        await c.disconnect()
        await other.disconnect()
    run(scenario)
