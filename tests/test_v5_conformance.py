"""MQTT 5 conformance breadth over real sockets — the
emqx_mqtt_protocol_v5_SUITE areas not covered elsewhere: subscription
options Retain-As-Published / Retain-Handling, request/response +
user-property pass-through, client Receive-Maximum governing the
SERVER's send window, Message-Expiry-Interval countdown, topic-alias
lifecycle in both directions, CONNACK capability caps, overlapping
subscriptions, and shared-group member death at QoS2.

Traceability vs the reference suite (every t_ case in
apps/emqx/test/emqx_mqtt_protocol_v5_SUITE.erl):

| reference case | covered by |
|---|---|
| t_basic_test | test_channel.test_subscribe_publish_qos1_end_to_end, test_publish_qos2_exactly_once, test_server socket suite |
| t_connect_clean_start | test_connack_session_present (here), test_channel.test_clean_start_discards_old_session |
| t_connect_will_message | test_channel.test_will_message_on_abnormal_disconnect |
| t_connect_will_retain | test_channel will cases + test_retain_as_published (retain forwarding) |
| t_batch_subscribe | test_channel.test_unsubscribe (multi-filter SUBSCRIBE/UNSUBACK codes) |
| t_connect_idle_timeout | test_channel.test_keepalive_expiry (idle close) |
| t_connect_emit_stats_timeout | N/A — BEAM process-stats emission cadence; stats surface is tests/test_observe.py |
| t_connect_keepalive_timeout | test_channel.test_keepalive_expiry |
| t_connect_duplicate_clientid | test_channel.test_takeover_preserves_pending, test_cm_kick |
| t_connack_session_present | test_connack_session_present (here) |
| t_connack_max_qos_allowed | test_connack_max_qos_allowed (here) |
| t_connack_assigned_clienid | test_connack_assigned_clientid (here) |
| t_publish_rap | test_retain_as_published (here) |
| t_publish_wildtopic | test_publish_wildtopic_disconnects (here) |
| t_publish_payload_format_indicator | test_publish_payload_format_indicator (here) |
| t_publish_topic_alias | test_publish_topic_alias_lifecycle (here) + test_channel.test_topic_alias_v5 |
| t_publish_response_topic | test_request_response_properties_pass_through (here) |
| t_publish_properties | test_request_response_properties_pass_through (User-Property leg) |
| t_publish_overlapping_subscriptions | test_publish_overlapping_subscriptions (here) |
| t_subscribe_topic_alias | test_subscribe_topic_alias_outbound (here) |
| t_subscribe_no_local | test_no_local_over_socket (here) |
| t_subscribe_actions | test_channel.test_subscription_identifiers_on_delivery + subscribe qos grant in test_connack_max_qos_allowed |
| t_unscbsctibe | test_channel.test_unsubscribe |
| t_pingreq | exercised by every keepalive test + MqttClient.ping in gateway suites |
| t_shared_subscriptions_client_terminates_when_qos_eq_2 | test_shared_subscription_qos2_member_death (here; mid-flight ack redispatch at unit level: test_services.test_redispatch_on_nack) |
"""

import asyncio

import pytest

from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.client import MqttClient


@pytest.fixture
def run():
    def _run(scenario):
        async def main():
            server = BrokerServer(port=0)
            await server.start()
            try:
                await scenario(server)
            finally:
                await server.stop()
        asyncio.run(main())
    return _run


def _c(server, cid, **kw):
    return MqttClient(port=server.port, clientid=cid, proto_ver=5, **kw)


def test_retain_as_published(run):
    """[MQTT-3.8.3.1] rap=1 keeps the retain flag on forwarded
    messages; rap=0 clears it."""
    async def scenario(server):
        raw = _c(server, "raw")
        plain = _c(server, "plain")
        pub = _c(server, "pub")
        for c in (raw, plain, pub):
            await c.connect()
        await raw.subscribe("r/t", qos=0, rap=1)
        await plain.subscribe("r/t", qos=0)
        await pub.publish("r/t", b"x", retain=True)
        assert (await raw.recv()).retain is True
        assert (await plain.recv()).retain is False
        for c in (raw, plain, pub):
            await c.disconnect()
    run(scenario)


def test_retain_handling(run):
    """[MQTT-3.8.3.1] rh=0 always sends retained on subscribe; rh=1
    only when the subscription is NEW; rh=2 never."""
    async def scenario(server):
        pub = _c(server, "pub")
        await pub.connect()
        await pub.publish("rh/t", b"kept", retain=True)

        sub = _c(server, "sub")
        await sub.connect()
        await sub.subscribe("rh/t", qos=0, rh=2)       # never
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.3)

        await sub.subscribe("rh/t", qos=0, rh=1)       # existing sub: no
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.3)

        await sub.subscribe("rh/t", qos=0, rh=0)       # always
        assert (await sub.recv()).payload == b"kept"

        fresh = _c(server, "fresh")
        await fresh.connect()
        await fresh.subscribe("rh/t", qos=0, rh=1)     # new sub: yes
        assert (await fresh.recv()).payload == b"kept"
        for c in (pub, sub, fresh):
            await c.disconnect()
    run(scenario)


def test_request_response_properties_pass_through(run):
    """[MQTT-3.3.2] Response-Topic, Correlation-Data and User-Property
    must reach the subscriber unchanged (the broker never interprets
    them)."""
    async def scenario(server):
        responder = _c(server, "responder")
        requester = _c(server, "requester")
        await responder.connect()
        await requester.connect()
        await responder.subscribe("svc/req", qos=1)
        await requester.subscribe("svc/resp/42", qos=1)

        await requester.publish("svc/req", b"do-it", qos=1, properties={
            "Response-Topic": "svc/resp/42",
            "Correlation-Data": b"corr-7",
            "User-Property": [("trace", "abc"), ("hop", "1")],
        })
        req = await responder.recv()
        props = req.properties or {}
        assert props.get("Response-Topic") == "svc/resp/42"
        assert props.get("Correlation-Data") == b"corr-7"
        assert ("trace", "abc") in (props.get("User-Property") or [])

        # the response flows back over the carried Response-Topic
        await responder.publish(props["Response-Topic"], b"done", qos=1,
                                properties={
                                    "Correlation-Data":
                                        props["Correlation-Data"]})
        resp = await requester.recv()
        assert resp.payload == b"done"
        assert (resp.properties or {}).get("Correlation-Data") == b"corr-7"
        await responder.disconnect()
        await requester.disconnect()
    run(scenario)


def test_client_receive_maximum_caps_server_window(run):
    """[MQTT-3.1.2-11] CONNECT Receive-Maximum=1: the server may keep
    only ONE un-acked QoS1 PUBLISH toward us; the next arrives only
    after our PUBACK."""
    async def scenario(server):
        sub = _c(server, "sub", auto_ack=False,
                 properties={"Receive-Maximum": 1})
        pub = _c(server, "pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("fc/t", qos=1)
        for i in range(3):
            await pub.publish("fc/t", b"%d" % i, qos=1)

        first = await sub.recv()
        assert first.payload == b"0"
        with pytest.raises(asyncio.TimeoutError):   # window is full
            await sub.recv(timeout=0.4)

        await sub.puback(first.packet_id)           # frees the window
        second = await sub.recv()
        assert second.payload == b"1"
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.4)
        await sub.puback(second.packet_id)
        assert (await sub.recv()).payload == b"2"
        await sub.disconnect()
        await pub.disconnect()
    run(scenario)


def test_message_expiry_interval_counts_down(run):
    """[MQTT-3.3.2-6] a queued message's Message-Expiry-Interval is
    forwarded MINUS the time spent waiting; fully expired messages are
    not delivered."""
    async def scenario(server):
        sub = _c(server, "sub", clean_start=False,
                 properties={"Session-Expiry-Interval": 300})
        pub = _c(server, "pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("exp/t", qos=1)
        await sub.close()                     # offline, session kept

        await pub.publish("exp/t", b"keeps", qos=1,
                          properties={"Message-Expiry-Interval": 100})
        await asyncio.sleep(1.1)

        back = _c(server, "sub", clean_start=False,
                  properties={"Session-Expiry-Interval": 300})
        ack = await back.connect()
        assert ack.session_present
        got = await back.recv()
        assert got.payload == b"keeps"
        remaining = (got.properties or {}).get("Message-Expiry-Interval")
        assert remaining is not None and remaining <= 99
        await back.disconnect()
        await pub.disconnect()
    run(scenario)


def test_expired_message_not_delivered_on_resume(run):
    async def scenario(server):
        sub = _c(server, "sub2", clean_start=False,
                 properties={"Session-Expiry-Interval": 300})
        pub = _c(server, "pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("exp2/t", qos=1)
        await sub.close()

        await pub.publish("exp2/t", b"dies", qos=1,
                          properties={"Message-Expiry-Interval": 1})
        await pub.publish("exp2/t", b"lives", qos=1)
        await asyncio.sleep(1.3)

        back = _c(server, "sub2", clean_start=False,
                  properties={"Session-Expiry-Interval": 300})
        await back.connect()
        got = await back.recv()
        assert got.payload == b"lives"       # the expired one is gone
        assert back.messages.empty()
        await back.disconnect()
        await pub.disconnect()
    run(scenario)


def test_no_local_over_socket(run):
    """[MQTT-3.8.3.1] nl=1: a client's own publishes do not loop back."""
    async def scenario(server):
        c = _c(server, "looper")
        other = _c(server, "other")
        await c.connect()
        await other.connect()
        await c.subscribe("nl/t", qos=0, nl=1)
        await c.publish("nl/t", b"self")
        await other.publish("nl/t", b"peer")
        got = await c.recv()
        assert got.payload == b"peer"
        assert c.messages.empty()
        await c.disconnect()
        await other.disconnect()
    run(scenario)


# -- round-5 breadth: the remaining emqx_mqtt_protocol_v5_SUITE cases --------

async def _expect_disconnect(client, rc, timeout=5.0):
    pkt = await client._expect(P.DISCONNECT, timeout)
    assert pkt.reason_code == rc, hex(pkt.reason_code)


def test_publish_payload_format_indicator(run):
    """[MQTT-3.3.2-6] (t_publish_payload_format_indicator): publish
    properties — PFI included — are forwarded verbatim."""
    async def scenario(server):
        c = _c(server, "pfi")
        await c.connect()
        await c.subscribe("pfi/t", qos=2)
        await c.publish("pfi/t", b"Payload Format Indicator",
                        properties={"Payload-Format-Indicator": 1})
        m = await c.recv()
        assert m.properties.get("Payload-Format-Indicator") == 1
        await c.disconnect()
    run(scenario)


def test_publish_topic_alias_lifecycle(run):
    """t_publish_topic_alias: alias 0 is a protocol error (DISCONNECT
    0x94 [MQTT-3.3.2-8]); a registered alias then resolves an
    empty-topic publish [MQTT-3.3.2-12]."""
    async def scenario(server):
        bad = _c(server, "alias-bad")
        await bad.connect()
        await bad.publish("al/t", b"x",
                          properties={"Topic-Alias": 0})
        await _expect_disconnect(bad, P.RC_TOPIC_ALIAS_INVALID)
        await bad.close()

        c = _c(server, "alias-ok")
        await c.connect()
        await c.subscribe("al/t", qos=2)
        await c.publish("al/t", b"one",
                        properties={"Topic-Alias": 233})
        await c.publish("", b"two",
                        properties={"Topic-Alias": 233})
        msgs = [await c.recv(), await c.recv()]
        assert sorted(m.payload for m in msgs) == [b"one", b"two"]
        for m in msgs:
            # [MQTT-3.3.2-7]: the publisher's alias is connection-scoped
            # — this subscriber announced no Topic-Alias-Maximum, so no
            # alias may reach it
            assert "Topic-Alias" not in (m.properties or {}), m.properties
            assert m.topic == "al/t"
        await c.disconnect()
    run(scenario)


def test_subscribe_topic_alias_outbound(run):
    """t_subscribe_topic_alias: the client's Topic-Alias-Maximum lets
    the SERVER alias deliveries — first use carries alias + full name,
    repeats carry alias + empty name, and topics beyond the budget go
    un-aliased."""
    async def scenario(server):
        c = _c(server, "out-alias",
               properties={"Topic-Alias-Maximum": 1})
        await c.connect()
        await c.subscribe("oa/t1", qos=2)
        await c.subscribe("oa/t2", qos=2)
        await c.publish("oa/t1", b"a")
        m1 = await c.recv()
        assert m1.topic == "oa/t1"
        assert m1.properties.get("Topic-Alias") == 1
        await c.publish("oa/t1", b"b")
        m2 = await c.recv()
        assert m2.topic == ""
        assert m2.properties.get("Topic-Alias") == 1
        await c.publish("oa/t2", b"c")
        m3 = await c.recv()
        assert m3.topic == "oa/t2"
        assert "Topic-Alias" not in (m3.properties or {})
        await c.disconnect()
    run(scenario)


def test_publish_overlapping_subscriptions(run):
    """t_publish_overlapping_subscriptions: two overlapping wildcard
    subscriptions each deliver ([MQTT-3.3.4-2]: forwarded qos below the
    publish qos 2; [MQTT-3.3.4-3]: the Subscription-Identifier rides
    each delivery)."""
    async def scenario(server):
        c = _c(server, "overlap")
        await c.connect()
        await c.subscribe("ov/+", qos=1,
                          properties={"Subscription-Identifier": 2333})
        await c.subscribe("ov/#", qos=0,
                          properties={"Subscription-Identifier": 2333})
        await c.publish("ov/t", b"overlap", qos=2)
        msgs = [await c.recv(), await c.recv()]
        for m in msgs:
            assert m.qos < 2
            assert m.properties.get("Subscription-Identifier") == [2333]
        await c.disconnect()
    run(scenario)


def test_publish_wildtopic_disconnects(run):
    """t_publish_wildtopic: publishing to a topic NAME containing
    wildcards is a protocol violation → DISCONNECT 0x90."""
    async def scenario(server):
        c = _c(server, "wildpub")
        await c.connect()
        await c.publish("wild/#", b"error topic")
        await _expect_disconnect(c, P.RC_TOPIC_NAME_INVALID)
        await c.close()
    run(scenario)


def test_connack_session_present(run):
    """t_connack_session_present: clean_start=1 → session_present=0
    [MQTT-3.2.2-2]; reconnect with clean_start=0 and a live expiry →
    session_present=1 [MQTT-3.2.2-3]."""
    async def scenario(server):
        c1 = _c(server, "sp-cid", clean_start=True,
                properties={"Session-Expiry-Interval": 7200})
        ack1 = await c1.connect()
        assert ack1.session_present is False
        await c1.disconnect()
        c2 = _c(server, "sp-cid", clean_start=False,
                properties={"Session-Expiry-Interval": 7200})
        ack2 = await c2.connect()
        assert ack2.session_present is True
        await c2.disconnect()
    run(scenario)


def test_connack_assigned_clientid(run):
    """t_connack_assigned_clienid [MQTT-3.2.2-16]: an empty v5
    clientid gets a server-assigned identifier in CONNACK."""
    async def scenario(server):
        c = MqttClient(port=server.port, clientid="", proto_ver=5)
        ack = await c.connect()
        assigned = (ack.properties or {}).get("Assigned-Client-Identifier")
        assert assigned, ack.properties
        await c.disconnect()
    run(scenario)


def test_connack_max_qos_allowed():
    """t_connack_max_qos_allowed: with mqtt.max_qos_allowed=1 the cap
    is advertised [MQTT-3.2.2-9], any-qos SUBSCRIBE is still granted
    [MQTT-3.2.2-10], a qos2 PUBLISH disconnects with 0x9B
    [MQTT-3.2.2-11], and a qos2 will is refused at CONNECT with 0x9B
    [MQTT-3.2.2-12]."""
    import asyncio as aio

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.config import Config

    conf = Config()
    conf.put("mqtt.max_qos_allowed", 1)
    app = BrokerApp.from_config(conf)

    async def main():
        server = BrokerServer(port=0, app=app)
        await server.start()
        try:
            c = _c(server, "mq1")
            ack = await c.connect()
            assert (ack.properties or {}).get("Maximum-QoS") == 1
            for q in (0, 1, 2):
                sa = await c.subscribe("mq/t", qos=q)
                assert sa.reason_codes[0] == q, sa.reason_codes
            # raw send: the helper would block awaiting a PUBREC that
            # the refusal replaces with DISCONNECT
            await c._send(P.Publish(topic="mq/t", payload=b"too high",
                                    qos=2, packet_id=c._pid(),
                                    properties={}))
            await _expect_disconnect(c, P.RC_QOS_NOT_SUPPORTED)
            await c.close()

            w = _c(server, "mq-will")
            with pytest.raises(ConnectionRefusedError, match="0x9b"):
                await w.connect(will_topic="mq/will", will_qos=2,
                                will_payload=b"Unsupported Qos")
            await w.close()
        finally:
            await server.stop()

    aio.run(main())


def test_shared_subscription_qos2_member_death(run):
    """t_shared_subscriptions_client_terminates_when_qos_eq_2 essence:
    a qos2 shared-group message is never lost to a dead member — after
    one member's socket dies abruptly, the group's traffic lands on the
    surviving member exactly once. (Mid-flight ack-timeout redispatch
    is covered at the SharedSub unit level: redispatch-on-nack.)"""
    async def scenario(server):
        doomed = _c(server, "sub_client_1")
        await doomed.connect()
        await doomed.subscribe("$share/sharename/sq/t", qos=2)
        survivor = _c(server, "sub_client_2")
        await survivor.connect()
        await survivor.subscribe("$share/sharename/sq/t", qos=2)
        pub = _c(server, "pub_client")
        await pub.connect()
        # abrupt death (no DISCONNECT): transport close → terminate →
        # member_down reaps the membership
        doomed._writer.close()
        await asyncio.sleep(0.3)
        for i in range(4):
            await pub.publish("sq/t", f"m{i}".encode(), qos=2)
        got = sorted([(await survivor.recv()).payload for _ in range(4)])
        assert got == [b"m0", b"m1", b"m2", b"m3"], got
        with pytest.raises(asyncio.TimeoutError):
            await survivor.recv(timeout=0.4)   # exactly once, no dup
        await survivor.disconnect(); await pub.disconnect()
    run(scenario)
