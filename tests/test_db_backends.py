"""MySQL / PostgreSQL / MongoDB stacks: wire clients against the in-repo
protocol-faithful mini servers, authn providers + authz sources through a
real broker CONNECT/PUBLISH, and data bridges fed by rules (the
reference's authn/authz/bridge suites run against real containers —
SURVEY §4.5; these miniatures speak the real protocols)."""

import asyncio

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.config.config import Config
from emqx_tpu.connector.mongodb import (MiniMongo, MongoClient,
                                        MongoConnector, bson_decode,
                                        bson_encode)
from emqx_tpu.connector.mysql import MiniMySQL, MySqlClient, MySqlConnector
from emqx_tpu.connector.pgsql import (MiniPg, PgClient, PgConnector,
                                      quote_literal, render_sql)
from emqx_tpu.mqtt.client import MqttClient


USERS = [{"username": "alice", "password_hash": "pw-alice", "salt": "",
          "is_superuser": "0"}]
ACL = [
    {"username": "alice", "permission": "allow", "action": "publish",
     "topic": "up/${username}/#"},
    {"username": "alice", "permission": "allow", "action": "subscribe",
     "topic": "up/#"},
    {"username": "alice", "permission": "deny", "action": "publish",
     "topic": "#"},
]


# -- wire clients --------------------------------------------------------------

def test_pg_wire_roundtrip():
    srv = MiniPg(password="pgpass").start()
    try:
        srv.tables["t"] = [{"a": "1", "b": None}, {"a": "o'brien", "b": "x"}]
        c = PgClient(port=srv.port, user="emqx", password="pgpass")
        assert c.query("SELECT 1")[1] == [["1"]]
        cols, rows = c.query("SELECT a, b FROM t WHERE a = 'o''brien'")
        assert cols == ["a", "b"] and rows == [["o'brien", "x"]]
        # NULL round-trips as None
        assert c.query("SELECT b FROM t WHERE a = '1'")[1] == [[None]]
        c.query("INSERT INTO logs (m) VALUES ('hi')")
        assert srv.tables["logs"] == [{"m": "hi"}]
        with pytest.raises(Exception):
            PgClient(port=srv.port, password="bad").query("SELECT 1")
        c.close()
    finally:
        srv.stop()


def test_mysql_wire_roundtrip():
    srv = MiniMySQL(user="emqx", password="mypass").start()
    try:
        srv.tables["t"] = [{"a": "v1", "n": None}]
        c = MySqlClient(port=srv.port, user="emqx", password="mypass")
        assert c.query("SELECT 1")[1] == [["1"]]
        cols, rows = c.query("SELECT a, n FROM t WHERE a = 'v1'")
        assert cols == ["a", "n"] and rows == [["v1", None]]
        c.query("INSERT INTO logs (m) VALUES ('hey')")
        assert srv.tables["logs"] == [{"m": "hey"}]
        from emqx_tpu.connector.mysql import MySqlError
        with pytest.raises(MySqlError):
            MySqlClient(port=srv.port, user="emqx",
                        password="bad").query("SELECT 1")
        c.close()
    finally:
        srv.stop()


def test_bson_roundtrip_and_mongo_wire():
    doc = {"s": "x", "i": 3, "big": 1 << 40, "f": 1.5, "t": True,
           "n": None, "sub": {"a": 1}, "arr": ["p", 2], "bin": b"\x00\x01"}
    assert bson_decode(bson_encode(doc))[0] == doc
    srv = MiniMongo().start()
    try:
        srv.collections["c"] = [{"k": "v", "n": 7}]
        c = MongoClient(port=srv.port)
        assert c.command({"ping": 1})["ok"] == 1.0
        assert c.find("c", {"k": "v"}) == [{"k": "v", "n": 7}]
        assert c.find("c", {"k": "zz"}) == []
        assert c.insert("c2", [{"a": 1}, {"a": 2}]) == 2
        assert len(srv.collections["c2"]) == 2
        from emqx_tpu.connector.mongodb import MongoError
        with pytest.raises(MongoError):
            c.command({"nonsense": 1})
        c.close()
    finally:
        srv.stop()


def test_sql_literal_quoting():
    assert quote_literal("a'b") == "'a''b'"
    assert quote_literal(None) == "NULL"
    assert quote_literal(5) == "5"
    assert render_sql("SELECT x WHERE u = ${u}", {"u": "a'; DROP --"}) \
        == "SELECT x WHERE u = 'a''; DROP --'"


# -- connector resources -------------------------------------------------------

@pytest.mark.parametrize("kind", ["pgsql", "mysql", "mongodb"])
def test_connector_health_and_query(kind):
    if kind == "pgsql":
        srv = MiniPg().start()
        conn = PgConnector(port=srv.port)
    elif kind == "mysql":
        srv = MiniMySQL().start()
        conn = MySqlConnector(port=srv.port, user="root", password="")
    else:
        srv = MiniMongo().start()
        conn = MongoConnector(port=srv.port)
    try:
        conn.on_start({})
        assert conn.on_health_check()
        if kind == "mongodb":
            assert conn.on_query(
                {"insert": "x", "documents": [{"a": 1}]}) == 1
            assert conn.on_query({"find": "x", "filter": {"a": 1}}) \
                == [{"a": 1}]
        else:
            conn.on_query({"sql": "INSERT INTO x (a) VALUES (${a})",
                           "binds": {"a": "1"}})
            cols, rows = conn.on_query("SELECT a FROM x")
            assert rows == [["1"]]
        conn.on_stop()
        # clients reconnect lazily — a health check after stop re-opens
        # (same as the reference's pooled clients)
        assert conn.on_health_check()
    finally:
        srv.stop()


# -- authn / authz through a live broker ---------------------------------------

def _db_spec(kind, srv):
    if kind == "mysql":
        return {"mechanism": "password_based", "backend": "mysql",
                "server": f"127.0.0.1:{srv.port}", "username": "root",
                "password": "", "database": "mqtt"}
    if kind == "postgresql":
        return {"mechanism": "password_based", "backend": "postgresql",
                "server": f"127.0.0.1:{srv.port}", "username": "postgres",
                "password": "", "database": "mqtt"}
    return {"mechanism": "password_based", "backend": "mongodb",
            "server": f"127.0.0.1:{srv.port}", "database": "mqtt"}


def _seed(kind, srv):
    if kind == "mongodb":
        srv.collections["mqtt_user"] = [
            {"username": "alice", "password_hash": "pw-alice",
             "salt": "", "is_superuser": False}]
        srv.collections["mqtt_acl"] = [
            {"username": "alice", "permission": "allow",
             "action": "publish", "topics": ["up/${username}/#"]},
            {"username": "alice", "permission": "allow",
             "action": "subscribe", "topics": ["up/#"]},
            {"username": "alice", "permission": "deny",
             "action": "publish", "topics": ["#"]}]
    else:
        srv.tables["mqtt_user"] = [dict(u) for u in USERS]
        srv.tables["mqtt_acl"] = [dict(r) for r in ACL]


@pytest.mark.parametrize("kind", ["mysql", "postgresql", "mongodb"])
def test_authn_authz_via_live_broker(kind):
    srv = {"mysql": MiniMySQL(user="root", password=""),
           "postgresql": MiniPg(),
           "mongodb": MiniMongo()}[kind].start()
    _seed(kind, srv)

    async def main():
        conf = Config()
        conf.init_load("authorization { no_match = deny }")
        conf.put("authentication", [_db_spec(kind, srv)], layer="local")
        spec = dict(_db_spec(kind, srv))
        spec["type"] = kind
        conf.put("authorization.sources", [spec], layer="local")
        app = BrokerApp.from_config(conf)
        server = BrokerServer(port=0, app=app)
        await server.start()

        bad = MqttClient(port=server.port, clientid="b1", proto_ver=5,
                         username="alice", password=b"wrong")
        with pytest.raises(ConnectionRefusedError):
            await bad.connect()

        good = MqttClient(port=server.port, clientid="g1", proto_ver=5,
                          username="alice", password=b"pw-alice")
        ack = await good.connect()
        assert ack.reason_code == 0, f"{kind}: good password rejected"

        # authz: allow up/alice/#, deny everything else (deny row + fold)
        sub = MqttClient(port=server.port, clientid="s1", proto_ver=5,
                         username="alice", password=b"pw-alice")
        await sub.connect()
        await sub.subscribe("up/#", qos=0)   # no_match deny? subscribe...
        await good.publish("up/alice/data", b"ok", qos=0)
        await good.publish("other/topic", b"denied", qos=0)
        try:
            msg = await asyncio.wait_for(sub.messages.get(), 5)
            assert msg.topic == "up/alice/data"
        finally:
            await good.disconnect()
            await sub.disconnect()
            await server.stop()

    try:
        asyncio.run(main())
    finally:
        srv.stop()


# -- bridges -------------------------------------------------------------------

def test_sql_bridge_inserts_per_message():
    srv = MiniPg().start()
    try:
        app = BrokerApp()
        app.bridges.create(
            "pgsql", "audit", PgConnector(port=srv.port),
            {"sql": "INSERT INTO mqtt_msg (topic, payload) VALUES "
                    "(${topic}, ${payload})"},
            batch_size=1, batch_time_s=0.0)
        app.rules.create_rule(
            "to-pg", 'SELECT topic, payload FROM "audit/#"',
            [{"function": "pgsql:audit", "args": {}}])
        from emqx_tpu.core.message import Message
        app.broker.publish(Message(topic="audit/x", payload=b"evt-1"))
        app.bridges.tick()
        deadline = 50
        while not srv.tables.get("mqtt_msg") and deadline:
            import time
            time.sleep(0.1)
            app.bridges.tick()
            deadline -= 1
        assert srv.tables.get("mqtt_msg") == [
            {"topic": "audit/x", "payload": "evt-1"}]
    finally:
        srv.stop()


def test_mongo_bridge_inserts_documents():
    srv = MiniMongo().start()
    try:
        app = BrokerApp()
        app.bridges.create(
            "mongodb", "sink", MongoConnector(port=srv.port),
            {"collection": "mqtt_msg"}, batch_size=1, batch_time_s=0.0)
        app.rules.create_rule(
            "to-mongo", 'SELECT topic, payload FROM "m/#"',
            [{"function": "mongodb:sink", "args": {}}])
        from emqx_tpu.core.message import Message
        app.broker.publish(Message(topic="m/1", payload=b"doc-1"))
        deadline = 50
        while not srv.collections.get("mqtt_msg") and deadline:
            import time
            time.sleep(0.1)
            app.bridges.tick()
            deadline -= 1
        docs = srv.collections.get("mqtt_msg")
        assert docs and docs[0]["topic"] == "m/1" \
            and docs[0]["payload"] == "doc-1"
    finally:
        srv.stop()
