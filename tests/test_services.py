"""Shared subs, retainer, delayed — mirrors emqx_shared_sub_SUITE,
emqx_retainer_SUITE, emqx_delayed_SUITE."""

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.shared_sub import SharedSub
from emqx_tpu.core.message import Message
from emqx_tpu.services.delayed import Delayed, parse_delayed
from emqx_tpu.services.retainer import Retainer


def msg(topic="t", payload=b"x", qos=0, retain=False, **kw):
    return Message(topic=topic, payload=payload, qos=qos,
                   flags={"retain": retain}, **kw)


# -- shared sub strategies --------------------------------------------------

def members(n):
    return [f"m{i}" for i in range(n)]


def test_round_robin():
    s = SharedSub(strategy="round_robin")
    for m in members(3):
        s.join("g", "t", m)
    picks = [s.pick("g", "t", msg())[0] for _ in range(6)]
    assert picks == ["m0", "m1", "m2", "m0", "m1", "m2"]


def test_round_robin_per_group_shares_cursor_across_topics():
    s = SharedSub(strategy="round_robin_per_group")
    for m in members(2):
        s.join("g", "t1", m)
        s.join("g", "t2", m)
    p1 = s.pick("g", "t1", msg())[0]
    p2 = s.pick("g", "t2", msg())[0]
    assert {p1, p2} == {"m0", "m1"}


def test_sticky_until_leave():
    s = SharedSub(strategy="sticky", seed=1)
    for m in members(3):
        s.join("g", "t", m)
    first = s.pick("g", "t", msg())[0]
    assert all(s.pick("g", "t", msg())[0] == first for _ in range(5))
    s.leave("g", "t", first)
    second = s.pick("g", "t", msg())[0]
    assert second != first
    assert all(s.pick("g", "t", msg())[0] == second for _ in range(5))


def test_hash_strategies_are_deterministic():
    for strat, key in [("hash_clientid", "from_"), ("hash_topic", "topic")]:
        s = SharedSub(strategy=strat)
        for m in members(4):
            s.join("g", "t", m)
        m1 = msg(topic="t", from_="alice")
        assert len({s.pick("g", "t", m1)[0] for _ in range(8)}) == 1


def test_local_prefers_local_node():
    s = SharedSub(node="n1", strategy="local")
    s.join("g", "t", "remote_m", node="n2")
    s.join("g", "t", "local_m", node="n1")
    assert all(s.pick("g", "t", msg())[0] == "local_m" for _ in range(5))
    s.leave("g", "t", "local_m")
    assert s.pick("g", "t", msg())[0] == "remote_m"


def test_redispatch_on_nack():
    s = SharedSub(strategy="round_robin")
    for m in members(3):
        s.join("g", "t", m)
    alive = {"m2"}
    got = s.dispatch("g", "t", msg(qos=1),
                     deliver_fn=lambda sid, node: sid in alive)
    assert got == [("m2", "node1", "$share/g/t")]
    # nobody alive → no delivery (and no infinite loop)
    assert s.dispatch("g", "t", msg(qos=1), deliver_fn=lambda s_, n_: False) == []


def test_member_down_cleans_all_groups():
    s = SharedSub()
    s.join("g1", "t", "m")
    s.join("g2", "u", "m")
    s.member_down("m")
    assert s.pick("g1", "t", msg()) is None
    assert s.pick("g2", "u", msg()) is None


# -- retainer ---------------------------------------------------------------

def test_retain_store_match_delete():
    r = Retainer()
    r.on_publish(msg("a/b", b"1", retain=True))
    r.on_publish(msg("a/c", b"2", retain=True))
    r.on_publish(msg("x", b"3", retain=True))
    assert {m.payload for m in r.match("a/+")} == {b"1", b"2"}
    assert [m.payload for m in r.match("#")] == [b"3", b"1", b"2"] or \
           {m.payload for m in r.match("#")} == {b"1", b"2", b"3"}
    assert r.match("a/b")[0].headers["retained"] is True
    r.on_publish(msg("a/b", b"", retain=True))    # empty payload clears
    assert r.match("a/b") == []
    assert len(r) == 2


def test_retained_overwrite_and_sys_hidden():
    r = Retainer()
    r.on_publish(msg("t", b"old", retain=True))
    r.on_publish(msg("t", b"new", retain=True))
    assert [m.payload for m in r.match("t")] == [b"new"]
    assert len(r) == 1
    r.on_publish(msg("$SYS/x", b"s", retain=True))
    assert r.match("#") and all(m.topic != "$SYS/x" for m in r.match("#"))
    assert [m.topic for m in r.match("$SYS/#")] == ["$SYS/x"]


def test_retained_expiry():
    r = Retainer(default_expiry_ms=1000)
    r.store(msg("t", b"1", retain=True), now=0)
    assert r.match("t", now=500)
    assert r.match("t", now=1500) == []
    assert len(r) == 0


def test_retained_compaction_keeps_buckets_consistent():
    """Round-7 regression: _compact rebuilds the per-bucket submatrices;
    a stale loop variable used to leave every bucket's topics list
    holding ONE topic, so a post-compaction expiry deleted the wrong
    retained message. Force a compaction (tombstones dominate), then
    expire one bucketed topic and assert the victim — and only the
    victim — is gone."""
    from emqx_tpu.core.message import now_ms

    r = Retainer()
    # one shared (l0, l1) bucket + churn victims to trip the compactor
    for i in range(1400):
        r.store(msg(f"churn/z/t{i}", b"c", retain=True))
    for i in range(40):
        r.store(msg(f"fleet/f1/g{i}", b"keep%d" % i, retain=True))
    for i in range(1400):
        r.delete(f"churn/z/t{i}")          # >1024 dead, dead*2 > n
    assert r._n < 1440 - 1024              # compaction ran mid-churn
    # bucket path still matches every survivor with the right payloads
    got = {m.topic: m.payload for m in r.match("fleet/f1/+")}
    assert got == {f"fleet/f1/g{i}": b"keep%d" % i for i in range(40)}
    # re-store ONE topic with a 1s Message-Expiry-Interval, look past
    # its deadline: exactly that topic must vanish — not a neighbour
    # (the pre-fix bucket topics list would have named a wrong victim)
    r.store(msg("fleet/f1/g7", b"dying", retain=True,
                headers={"properties": {"Message-Expiry-Interval": 1}}))
    alive = {m.topic for m in r.match("fleet/f1/+", now=now_ms() + 5000)}
    assert "fleet/f1/g7" not in alive
    assert alive == {f"fleet/f1/g{i}" for i in range(40) if i != 7}
    # the lazy expiry really deleted g7 (bucket + row state consistent)
    assert len(r.match("fleet/f1/g7")) == 0


def test_retained_max_limit():
    r = Retainer(max_retained=1)
    assert r.store(msg("a", retain=True))
    assert not r.store(msg("b", retain=True))
    assert r.store(msg("a", b"upd", retain=True))   # overwrite always ok
    assert r.dropped == 1


# -- delayed ----------------------------------------------------------------

def test_parse_delayed():
    assert parse_delayed("$delayed/5/a/b") == (5, "a/b")
    assert parse_delayed("a/b") is None
    with pytest.raises(ValueError):
        parse_delayed("$delayed/xx/a")
    with pytest.raises(ValueError):
        parse_delayed("$delayed/99999999999/a")


def test_delayed_scheduler_order():
    fired = []
    d = Delayed(publish_fn=lambda m: fired.append(m.topic))
    d.store(msg("$delayed/2/later"), 2, "later", now=0)
    d.store(msg("$delayed/1/sooner"), 1, "sooner", now=0)
    assert d.tick(now=500) == 0
    assert d.tick(now=1500) == 1 and fired == ["sooner"]
    assert d.tick(now=2500) == 1 and fired == ["sooner", "later"]


# -- app wiring -------------------------------------------------------------

def test_app_delayed_intercepts_publish():
    app = BrokerApp()
    app.broker.subscribe("s1", "real/t")
    deliveries = app.broker.publish(msg("$delayed/1/real/t", b"soon"))
    assert deliveries == {}                 # intercepted, not routed
    assert len(app.delayed) == 1
    fired = []
    app.cm.dispatch = lambda d: fired.append(d)
    app.delayed.tick(now=app.delayed.next_due() + 1)
    assert fired and "s1" in fired[0]


def test_app_retained_on_subscribe():
    app = BrokerApp()
    app.broker.publish(msg("news/today", b"headline", retain=True))
    got = []
    app.cm.dispatch = lambda d: got.append(d)
    app.broker.subscribe("reader", "news/+")
    assert got and got[0]["reader"][0][1].payload == b"headline"
    # rh=2 suppresses retained dispatch
    got.clear()
    from emqx_tpu.core.message import SubOpts
    app.broker.subscribe("reader2", "news/+", SubOpts(rh=2))
    assert got == []


def test_app_shared_group_end_to_end():
    app = BrokerApp(shared_strategy="round_robin")
    app.broker.subscribe("w1", "$share/g/jobs")
    app.broker.subscribe("w2", "$share/g/jobs")
    sids = []
    for _ in range(4):
        d = app.broker.publish(msg("jobs", b"j"))
        assert len(d) == 1
        sids.append(next(iter(d)))
    assert set(sids) == {"w1", "w2"}
    # member down → remaining member gets everything
    app.broker.subscriber_down("w1")
    app.hooks.run("session.terminated", ("w1", "down"))
    d = app.broker.publish(msg("jobs", b"j"))
    assert set(d) == {"w2"}


def test_malformed_delayed_topic_dropped_not_crash():
    app = BrokerApp()
    app.broker.subscribe("s1", "#")
    assert app.broker.publish(msg("$delayed/xx/t")) == {}
    assert app.broker.publish(msg("$delayed/99999999999/t")) == {}
    assert app.delayed.dropped == 2
    assert len(app.delayed) == 0


def test_rh1_no_retained_on_resubscribe():
    from emqx_tpu.core.message import SubOpts
    app = BrokerApp()
    app.broker.publish(msg("n/t", b"r", retain=True))
    got = []
    app.cm.dispatch = lambda d: got.append(d)
    app.broker.subscribe("c", "n/+", SubOpts(rh=1))
    assert len(got) == 1                 # new subscription → retained sent
    app.broker.subscribe("c", "n/+", SubOpts(rh=1))
    assert len(got) == 1                 # resubscribe → suppressed
    app.broker.subscribe("c", "n/+", SubOpts(rh=0))
    assert len(got) == 2                 # rh=0 always sends


def test_shared_group_two_filters_both_dispatch():
    app = BrokerApp()
    app.broker.subscribe("w1", "$share/g/a/+")
    app.broker.subscribe("w2", "$share/g/a/b")
    d = app.broker.publish(msg("a/b"))
    # both (group, filter) routes dispatch: w1 via 'a/+', w2 via 'a/b'
    assert set(d) == {"w1", "w2"}


def test_hash_strategy_deterministic_across_instances():
    import zlib
    s1 = SharedSub(strategy="hash_clientid")
    s2 = SharedSub(strategy="hash_clientid")
    for s in (s1, s2):
        for m in members(5):
            s.join("g", "t", m)
    m1 = msg(from_="publisher-x")
    assert s1.pick("g", "t", m1) == s2.pick("g", "t", m1)


def test_retainer_lazy_expiry_prunes_store():
    r = Retainer(default_expiry_ms=10)
    r.store(msg("deep/a/b/c", b"1", retain=True), now=0)
    assert r.match("deep/#", now=100) == []
    assert len(r) == 0
    # the lazy expiry released the entry, not just hid it: the topic is
    # re-storable and absent from the dump (the vectorized store
    # tombstones rows; compaction reclaims them in bulk)
    assert r.topics() == []
    assert r._row_of == {}
    assert r.store(msg("deep/a/b/c", b"2", retain=True), now=200)
    assert [m.payload for m in r.match("deep/#", now=201)] == [b"2"]


def test_retainer_vectorized_store_edges():
    """Round-4 vectorized retainer: deep-topic fallback, bucket
    invalidation across delete/re-store, tombstone compaction, and the
    wildcard-prefix full scan all agree with T.match semantics."""
    from emqx_tpu.core import topic as T

    r = Retainer()
    deep = "a/" * 20 + "leaf"            # > MAX_LEVELS: fallback dict
    r.store(msg(deep, b"deep", retain=True))
    for i in range(50):
        r.store(msg(f"v/d{i}/s", bytes(str(i), "ascii"), retain=True))
    assert [m.payload for m in r.match("a/#")] == [b"deep"]
    assert len(r.match("v/+/s")) == 50       # full scan (wildcard lvl 1)
    assert len(r.match("v/d7/s")) == 1       # bucketed
    # delete + re-store invalidate the warm bucket cache
    assert len(r.match("v/d7/+")) == 1       # warm the (v, d7) bucket
    r.delete("v/d7/s")
    assert r.match("v/d7/+") == []
    r.store(msg("v/d7/s", b"back", retain=True))
    assert [m.payload for m in r.match("v/d7/+")] == [b"back"]
    # mass delete triggers compaction; survivors still match
    for i in range(50):
        if i != 7:
            r.delete(f"v/d{i}/s")
    for _ in range(1500):                # push past the tombstone gate
        r.store(msg("w/x/y", b"t", retain=True))
        r.delete("w/x/y")
    assert [m.payload for m in r.match("v/#")] == [b"back"]
    assert sorted(r.topics()) == sorted([deep, "v/d7/s"])
    # differential spot-check vs T.match over a random mix
    import random
    rng = random.Random(3)
    r2 = Retainer()
    topics = [f"{rng.choice(['x','y'])}/{rng.choice(['a','b','c'])}/"
              f"n{i % 7}" for i in range(60)] + ["$sys/u/v"]
    for i, t in enumerate(set(topics)):
        r2.store(msg(t, b"m", retain=True))
    for filt in ["x/+/n1", "+/a/#", "#", "x/#", "+/+/+", "$sys/#",
                 "x/a/n1", "zz/+/+"]:
        want = sorted(t for t in r2.topics()
                      if T.match(t, filt)
                      and not (filt[0] in "+#" and t.startswith("$")))
        got = sorted(m.topic for m in r2.match(filt))
        assert got == want, (filt, got, want)


def test_retainer_deep_filters_and_topics():
    """Filters and topics beyond MAX_LEVELS must neither crash nor miss
    (round-4 review finding: the literal-word loop indexed past the
    token matrix for 17+-level filters)."""
    r = Retainer()
    r.store(msg("a/b", b"shallow", retain=True))
    deep_t = "/".join(["d"] * 20)
    r.store(msg(deep_t, b"deep", retain=True))
    deep_filt = "/".join(["x"] * 17)         # deeper than MAX_LEVELS
    assert r.match(deep_filt) == []           # no crash, no hits
    assert [m.payload for m in r.match("/".join(["d"] * 20))] == [b"deep"]
    assert [m.payload for m in r.match("d/#")] == [b"deep"]
    assert [m.payload for m in r.match("a/+")] == [b"shallow"]
    wild_deep = "/".join(["+"] * 17)
    assert r.match(wild_deep) == []           # full-scan path, no crash


def test_dispatch_batch_deliver_fn_runs_outside_lock():
    """dispatch_batch must not hold the table lock across deliver_fn
    (round-4 advisor finding): a re-entrant or slow callback — the real
    member_down-on-dead-session shape here — must neither trip on the
    held lock nor extend the hold across the whole batch. The nack path
    must also still redispatch to a live member, matching dispatch()'s
    semantics."""
    s = SharedSub(strategy="round_robin")
    for m in members(3):
        s.join("g", "t", m)
    alive = {"m2"}

    def deliver(sid, node):
        if sid not in alive:
            # re-enters SharedSub.member_down → self._lock; held lock
            # here means deadlock (test would hang, caught by timeout)
            s.member_down(sid)
            return False
        return True

    legs = [("g", "t", msg(qos=1)) for _ in range(6)]
    out = s.dispatch_batch(legs, deliver_fn=deliver)
    assert all(o is not None and o[0] == "m2" for o in out), out
    # dead members were reaped by the callback's member_down
    assert s.pick("g", "t", msg()) == ("m2", "node1")


def test_dispatch_batch_all_nacked_gives_none():
    s = SharedSub(strategy="round_robin")
    for m in members(2):
        s.join("g", "t", m)
    out = s.dispatch_batch([("g", "t", msg(qos=1))] * 3,
                           deliver_fn=lambda sid, node: False)
    assert out == [None, None, None]
