"""jq-subset interpreter (utils/jq.py) — cases from the jq manual plus
the rule-engine seam (jq/2, emqx_rule_funcs.erl:806-828)."""

import pytest

from emqx_tpu.utils.jq import JqError, jq


@pytest.mark.parametrize("prog,input_,want", [
    # identity / paths
    (".", {"a": 1}, [{"a": 1}]),
    (".a", {"a": 1}, [1]),
    (".a.b", {"a": {"b": 2}}, [2]),
    (".a.b.c", {"a": {"b": {"c": 5}}}, [5]),   # 3+ segments: per-segment
    (".w.x.y.z", {"w": {"x": {"y": {"z": 9}}}}, [9]),   # name binding
    ('.["a b"]', {"a b": 3}, [3]),
    (".a", {"b": 1}, [None]),                  # missing key -> null
    (".a?", 7, []),                            # optional suppresses error
    (".[0]", [10, 20], [10]),
    (".[-1]", [10, 20], [20]),
    (".[5]", [10], [None]),
    (".[1:3]", [0, 1, 2, 3], [[1, 2]]),
    (".[:2]", "abcd", ["ab"]),
    # iteration, pipe, comma
    (".[]", [1, 2, 3], [1, 2, 3]),
    (".[]", {"a": 1, "b": 2}, [1, 2]),
    (".a[]", {"a": [4, 5]}, [4, 5]),
    (".[] | .x", [{"x": 1}, {"x": 2}], [1, 2]),
    (".a, .b", {"a": 1, "b": 2}, [1, 2]),
    # literals, construction
    ("[.[] | . * 2]", [1, 2], [[2, 4]]),
    ('{t: .topic, "q": .qos}', {"topic": "x", "qos": 1},
     [{"t": "x", "q": 1}]),
    ("{a}", {"a": 9, "b": 1}, [{"a": 9}]),
    ("[]", None, [[]]),
    # arithmetic
    (".a + .b", {"a": 1, "b": 2}, [3]),
    ('.a + "s"', {"a": "x"}, ["xs"]),
    (".a + .b", {"a": [1], "b": [2]}, [[1, 2]]),
    (".a + .b", {"a": {"x": 1}, "b": {"y": 2}}, [{"x": 1, "y": 2}]),
    ("null + 5", None, [5]),
    ("10 - 3", None, [7]),
    ("[1,2,3] - [2]", None, [[1, 3]]),
    ("6 / 2", None, [3]),                      # exact quotient stays int
    ("7 / 2", None, [3.5]),
    ('"a,b" / ","', None, [["a", "b"]]),
    ("7 % 3", None, [1]),
    ("-(.a)", {"a": 4}, [-4]),
    # comparisons / booleans / select
    (".a == 1", {"a": 1}, [True]),
    (".[] | select(. > 2)", [1, 2, 3, 4], [3, 4]),
    ('.[] | select(.t == "on")',
     [{"t": "on", "i": 1}, {"t": "off", "i": 2}], [{"t": "on", "i": 1}]),
    ("1 < 2 and 2 < 1", None, [False]),
    ("1 < 2 or 2 < 1", None, [True]),
    (".a | not", {"a": False}, [True]),
    ("null < 1", None, [True]),                # jq total order
    # alternative, if
    (".a // 42", {}, [42]),
    (".a // 42", {"a": 7}, [7]),
    ("if . > 0 then \"pos\" elif . == 0 then \"zero\" else \"neg\" end",
     -3, ["neg"]),
    ("if . then 1 end", False, [False]),       # default else = identity
    # builtins
    ("length", [1, 2, 3], [3]),
    ("length", "abcd", [4]),
    ("length", None, [0]),
    ("keys", {"b": 1, "a": 2}, [["a", "b"]]),
    ("has(\"a\")", {"a": 1}, [True]),
    ("type", [1], ["array"]),
    ("empty", 1, []),
    ("add", [1, 2, 3], [6]),
    ("add", [[1], [2]], [[1, 2]]),
    ("min, max", [3, 1, 2], [1, 3]),
    ("sort", [3, 1, 2], [[1, 2, 3]]),
    ("sort_by(.x)", [{"x": 2}, {"x": 1}], [[{"x": 1}, {"x": 2}]]),
    ("unique", [2, 1, 2], [[1, 2]]),
    ("reverse", [1, 2], [[2, 1]]),
    ('join("-")', ["a", "b"], ["a-b"]),
    ('split(",")', "a,b", [["a", "b"]]),
    ("map(. + 1)", [1, 2], [[2, 3]]),
    ("any(. > 2)", [1, 3], [True]),
    ("all(. > 2)", [1, 3], [False]),
    ("range(3)", None, [0, 1, 2]),
    ("first, last", [5, 6, 7], [5, 7]),
    ("first, last", [], [None, None]),         # first = .[0] on empty
    ('{("a","b"): 1}', None, [{"a": 1}, {"b": 1}]),   # key backtracking
    ("floor, ceil", 1.5, [1, 2]),
    ("tostring", 5, ["5"]),
    ("tonumber", "5", [5]),
    ("tojson", {"a": 1}, ['{"a": 1}']),
    ('fromjson | .a', '{"a": 3}', [3]),
    ("ascii_upcase", "ab", ["AB"]),
    ('startswith("ab")', "abc", [True]),
    ('ltrimstr("ab")', "abc", ["c"]),
    ('contains("bc")', "abcd", [True]),
    ("to_entries", {"a": 1}, [[{"key": "a", "value": 1}]]),
    ("from_entries", [{"key": "a", "value": 1}], [{"a": 1}]),
    ("values", None, []),
    ("values", 0, [0]),
    # stream distribution: operators over cartesian products
    ("(1,2) + (10,20)", None, [11, 12, 21, 22]),
    # and/or short-circuit: rhs must not evaluate when lhs decides
    (".enabled and (1 / .total > 0.5)", {"enabled": False, "total": 0},
     [False]),
    (".done or error(\"x\")", {"done": True}, [True]),
    # error containment: builtin failures are JqError, so ? suppresses
    (".p | fromjson? // \"fallback\"", {"p": "not json"}, ["fallback"]),
    ("(-1 | sqrt)? // null", None, [None]),
    ("(\"x\" | floor)? // 0", None, [0]),
    (".maybe[0:2]", {}, [None]),               # slicing null → null
])
def test_jq_manual_cases(prog, input_, want):
    assert jq(prog, input_) == want


def test_json_string_input():
    # bytes are a JSON document (the reference passes binaries);
    # a str is ALWAYS a plain term — never sniffed as JSON text
    assert jq(".a", b'{"a": 1}') == [1]
    with pytest.raises(JqError):
        jq(".", b"{not json")                 # bytes must be valid JSON
    assert jq("length", "not json") == [8]    # str is a term
    assert jq(".", "0") == ["0"]              # NOT [0] — no sniffing


def test_rule_seam_str_is_json_text():
    # the rule-engine seam applies reference semantics: SQL values are
    # binaries holding JSON text, whether our runtime hands them over
    # as str or bytes (emqx_rule_funcs.erl:806-828)
    from emqx_tpu.rules.funcs import FUNCS
    assert FUNCS["jq"](".sensor.temp", '{"sensor": {"temp": 21.5}}') == [21.5]
    assert FUNCS["jq"](".", "0") == [0]       # JSON text at the seam
    with pytest.raises(JqError):
        FUNCS["jq"](".", "not json")          # invalid JSON fails the rule


@pytest.mark.parametrize("prog", [
    "def f: .; f",          # defs
    ". as $x | $x",         # variables
    "reduce .[] as $i (0; . + $i)",
    "..",                   # recursive descent
    '"\\(.a)"',             # interpolation
    "nosuchfn(3)",
    "(",                    # malformed
    ". |",
])
def test_unsupported_and_malformed_raise(prog):
    with pytest.raises(JqError):
        jq(prog, {"a": 1})


def test_runtime_errors():
    with pytest.raises(JqError):
        jq(".a + .b", {"a": 1, "b": "s"})
    with pytest.raises(JqError):
        jq("1 / 0", None)
    with pytest.raises(JqError):
        jq('error("boom")', None)


def test_rule_func_seam():
    from emqx_tpu.rules.funcs import FUNCS
    assert FUNCS["jq"](b".[] | .x", '[{"x": 1}, {"x": 2}]') == [1, 2]
    assert FUNCS["jq"](".a", {"a": 5}, 1000) == [5]   # jq/3 timeout arg


def test_rule_sql_with_jq():
    """jq inside a full SQL rule — the reference's headline use."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.core.message import Message

    app = BrokerApp()
    got = []
    app.rules.register_action("sink", lambda cols, args: got.append(cols))
    app.rules.create_rule(
        "r1",
        "SELECT jq('.readings[] | select(.v > 10) | .v', payload) AS hot "
        "FROM \"jq/t\"",
        [{"function": "sink", "args": {}}])
    app.broker.publish(Message(
        topic="jq/t",
        payload=b'{"readings": [{"v": 5}, {"v": 11}, {"v": 30}]}'))
    assert got and got[0]["hot"] == [11, 30]
