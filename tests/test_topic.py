"""Topic algebra tests — mirrors apps/emqx/test/emqx_topic_SUITE.erl."""

import random

from emqx_tpu.core import topic as T


def test_words():
    assert T.words("a/b/c") == ["a", "b", "c"]
    assert T.words("a//c") == ["a", "", "c"]
    assert T.words("/a") == ["", "a"]
    assert T.words("a/") == ["a", ""]
    assert T.join(["a", "b", "c"]) == "a/b/c"


def test_wildcard():
    assert T.wildcard("a/+/c")
    assert T.wildcard("a/b/#")
    assert not T.wildcard("a/b/c")
    assert not T.wildcard("a/b+/c#")  # embedded chars are not wildcards


def test_validate():
    assert T.validate_name("a/b/c")
    assert not T.validate_name("a/+/c")
    assert not T.validate_name("")
    assert not T.validate_name("a/\x00/c")
    assert T.validate_filter("a/+/c")
    assert T.validate_filter("a/b/#")
    assert T.validate_filter("#")
    assert T.validate_filter("+")
    assert not T.validate_filter("a/#/c")     # '#' must be last
    assert not T.validate_filter("a/b+/c")    # '+' must fill the level
    assert not T.validate_filter("a/b#")
    assert T.validate_filter("a//c")          # empty level is legal


# (name, filter, matches?) — cases from emqx_topic_SUITE + MQTT-5 spec 4.7
MATCH_CASES = [
    ("a/b/c", "a/b/c", True),
    ("a/b/c", "a/+/c", True),
    ("a/b/c", "a/#", True),
    ("a/b/c", "#", True),
    ("a/b/c", "+/+/+", True),
    ("a/b/c", "a/b", False),
    ("a/b", "a/b/c", False),
    ("a/b/c", "a/+", False),
    ("a", "a/#", True),            # '#' matches the parent level
    ("a/b", "a/#", True),
    ("a", "a/+", False),
    ("a/", "a/+", True),           # '+' matches the empty level
    ("/b", "+/b", True),
    ("/b", "#", True),
    ("sport/tennis/player1", "sport/tennis/player1/#", True),
    ("sport/tennis/player1/ranking", "sport/tennis/player1/#", True),
    ("sport", "sport/#", True),
    ("$SYS/broker", "#", False),   # '$' topics hidden from root wildcards
    ("$SYS/broker", "+/broker", False),
    ("$SYS/broker", "$SYS/broker", True),
    ("$SYS/broker", "$SYS/#", True),
    ("$SYS/broker", "$SYS/+", True),
    ("a/$SYS/b", "a/+/b", True),   # '$' rule only applies at level 0
    ("a/b/c/d/e", "a/b/#", True),
    ("abc", "+", True),
    ("a/b", "+", False),
]


def test_match_table():
    for name, filt, expect in MATCH_CASES:
        assert T.match(name, filt) is expect, (name, filt, expect)


def test_match_randomized_vs_bruteforce():
    """Random topics/filters vs an independent recursive matcher."""

    def brute(n, f):
        if n and f and n[0].startswith("$") and f[0] in ("+", "#"):
            return False

        def rec(n, f):
            if not f:
                return not n
            if f[0] == "#":
                return True
            if not n:
                return False
            if f[0] == "+" or f[0] == n[0]:
                return rec(n[1:], f[1:])
            return False

        return rec(n, f)

    rng = random.Random(7)
    alphabet = ["a", "b", "c", "$x", ""]
    for _ in range(3000):
        name = [rng.choice(alphabet[:4]) for _ in range(rng.randint(1, 5))]
        filt = [
            rng.choice(alphabet + ["+", "+", "#"])
            for _ in range(rng.randint(1, 5))
        ]
        # keep filter valid: truncate at first '#'
        if "#" in filt:
            filt = filt[: filt.index("#") + 1]
        got = T.match_words(name, filt)
        assert got == brute(name, filt), (name, filt)


def test_parse_share():
    assert T.parse_share("$share/g1/t/1") == ("g1", "t/1")
    assert T.parse_share("$queue/t") == ("$queue", "t")
    assert T.parse_share("t/1") == (None, "t/1")
    assert T.parse_share("$share/g/+/x") == ("g", "+/x")


def test_is_sys():
    assert T.is_sys("$SYS/a")
    assert T.is_sys("$share/g/t")
    assert not T.is_sys("a/$SYS")


def test_feed_var_no_cascade():
    assert T.feed_var("x/%c/%u", {"%c": "has%u", "%u": "U"}) == "x/has%u/U"
    assert T.feed_var("m/${clientid}/t", {"${clientid}": "c1"}) == "m/c1/t"
    assert T.feed_var("a/%c", {"%c": None}) == "a/"
