"""Device router ON the live serving path (VERDICT r1 item 1): real MQTT
clients over TCP, deliveries coming off batched kernel launches, with
host-oracle fallback covered.  The reference equivalent is the whole of
emqx_broker.erl:218-232 driven from emqx_connection.erl:132."""

import asyncio

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.config.config import Config
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.client import MqttClient


def make_device_app(**kw):
    conf = Config()
    conf.put("router.device.enable", True)
    conf.put("router.device.max_levels", 8)
    # this suite tests the KERNEL serving path: pin the latency knee to
    # 0 so even single-message batches launch the device (the adaptive
    # default would host-bypass them — covered by the policy tests)
    conf.put("router.device.min_batch", 0)
    return BrokerApp.from_config(conf, **kw)


@pytest.fixture
def run():
    def _run(scenario, app=None):
        async def main():
            server = BrokerServer(port=0, app=app or make_device_app())
            await server.start()
            try:
                await scenario(server)
            finally:
                await server.stop()
        asyncio.run(main())
    return _run


def test_from_config_builds_router_model():
    app = make_device_app()
    assert app.broker.model is not None
    assert app.pipeline is not None
    assert app.pipeline.max_batch == 512


def test_e2e_delivery_via_kernel(run):
    """Publishes from a live client must route through the device model
    (kernel-launch counter moves), not the host walk."""
    async def scenario(server):
        model = server.app.broker.model
        sub = MqttClient(port=server.port, clientid="sub")
        pub = MqttClient(port=server.port, clientid="pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("room/+/temp", qos=1)
        launches0 = model.launch_count
        await pub.publish("room/7/temp", b"21.5", qos=1)
        # generous: the first publish pays the kernel's XLA compile
        got = await sub.recv(timeout=60)
        assert got.topic == "room/7/temp" and got.payload == b"21.5"
        assert model.launch_count > launches0
        assert server.app.pipeline.published >= 1
        await sub.disconnect()
        await pub.disconnect()
    run(scenario)


def test_e2e_concurrent_publishers_batched(run):
    """N clients publishing concurrently: every message delivered exactly
    once, and the pipeline coalesces (launches ≤ messages)."""
    async def scenario(server):
        model = server.app.broker.model
        sub = MqttClient(port=server.port, clientid="sub")
        await sub.connect()
        await sub.subscribe("fleet/#", qos=0)
        n_pubs, n_msgs = 8, 10
        pubs = [MqttClient(port=server.port, clientid=f"p{i}")
                for i in range(n_pubs)]
        for p in pubs:
            await p.connect()
        launches0 = model.launch_count

        async def blast(i, p):
            for j in range(n_msgs):
                await p.publish(f"fleet/v{i}/m{j}", b"x", qos=0)

        await asyncio.gather(*(blast(i, p) for i, p in enumerate(pubs)))
        want = {f"fleet/v{i}/m{j}"
                for i in range(n_pubs) for j in range(n_msgs)}
        got = set()
        while len(got) < len(want):
            m = await sub.recv(timeout=30)
            assert m.topic not in got, "duplicate delivery"
            got.add(m.topic)
        assert got == want
        launches = model.launch_count - launches0
        assert launches >= 1
        assert server.app.pipeline.published >= n_pubs * n_msgs
        for p in pubs:
            await p.disconnect()
        await sub.disconnect()
    run(scenario)


def test_e2e_ordering_per_publisher(run):
    """A publisher's messages arrive in submission order through the
    batched path (the per-connection ordering guarantee)."""
    async def scenario(server):
        sub = MqttClient(port=server.port, clientid="sub")
        pub = MqttClient(port=server.port, clientid="pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("seq/t", qos=1)
        for i in range(20):
            await pub.publish("seq/t", b"%d" % i, qos=1)
        seen = [int((await sub.recv(timeout=30)).payload) for _ in range(20)]
        assert seen == list(range(20))
        await sub.disconnect()
        await pub.disconnect()
    run(scenario)


def test_e2e_host_oracle_fallback_deep_topic(run):
    """A topic deeper than router.device.max_levels overflows the kernel
    row and must take the host-oracle fallback — still delivered."""
    async def scenario(server):
        sub = MqttClient(port=server.port, clientid="sub")
        pub = MqttClient(port=server.port, clientid="pub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("deep/#", qos=0)
        deep = "deep/" + "/".join(str(i) for i in range(12))   # 13 levels
        await pub.publish(deep, b"fb", qos=0)
        got = await sub.recv(timeout=30)
        assert got.topic == deep and got.payload == b"fb"
        await sub.disconnect()
        await pub.disconnect()
    run(scenario)


def test_e2e_shared_and_retained_still_work(run):
    """Device path covers direct local subscribers; shared groups and
    retained messages ride their own seams — all must coexist."""
    async def scenario(server):
        a = MqttClient(port=server.port, clientid="a")
        b = MqttClient(port=server.port, clientid="b")
        pub = MqttClient(port=server.port, clientid="pub")
        await a.connect(); await b.connect(); await pub.connect()
        await a.subscribe("$share/g/t", qos=0)
        await b.subscribe("t", qos=0)
        await pub.publish("t", b"ret", qos=0, retain=True)
        got_b = await b.recv(timeout=30)
        assert got_b.payload == b"ret"
        got_a = await a.recv(timeout=30)
        assert got_a.payload == b"ret"
        # late subscriber gets the retained copy
        c = MqttClient(port=server.port, clientid="c")
        await c.connect()
        await c.subscribe("t", qos=0)
        got_c = await c.recv(timeout=30)
        assert got_c.payload == b"ret" and got_c.retain
        for cl in (a, b, pub, c):
            await cl.disconnect()
    run(scenario)


def test_small_batch_host_bypass_policy(run):
    """Latency policy (VERDICT r3 #3): batches below the knee answer
    from the host oracle (no device launch); a saturated batch still
    takes the kernel. Deliveries are correct on both legs.

    Deflaked (PR 4's documented timing flake) on BOTH wall-clock seams:
    the burst used to ride 16 separate writes, so under full-suite load
    the server could read them trickled into sub-knee batches; and the
    ADAPTIVE spill deadline (>= 30ms queue sojourn) could divert even a
    full batch to the host oracle on a loaded box. The burst is now ONE
    socket write (one read batch, one >= knee submission) and spill_ms
    is pinned far above any scheduler hiccup — the device launch is a
    policy decision again, not a race."""
    from emqx_tpu.mqtt.frame import serialize

    app = make_device_app()
    app.pipeline.min_device_batch = 4      # fixed knee for the test
    app.pipeline.spill_ms = 60_000.0       # no sojourn spill in-test

    async def scenario(server):
        model = app.broker.model
        sub = MqttClient(port=server.port, clientid="bp-s")
        await sub.connect()
        await sub.subscribe("kb/+", qos=0)
        pub = MqttClient(port=server.port, clientid="bp-p")
        await pub.connect()
        launches0 = model.launch_count
        # trickle: single-message batches stay on the host oracle (the
        # await-recv between publishes makes each its own batch)
        for i in range(3):
            await pub.publish("kb/t", f"lo{i}".encode(), qos=0)
            m = await sub.recv(timeout=10)
            assert m.payload == f"lo{i}".encode()
        assert app.pipeline.host_batches >= 3
        assert model.launch_count == launches0, "bypass launched kernel"
        # burst: one coalesced write of 16 frames lands as one read
        # batch well above the knee — the device path must run
        burst = b"".join(
            serialize(P.Publish(topic="kb/t", payload=f"hi{i}".encode(),
                                qos=0, properties={}),
                      pub.proto_ver)
            for i in range(16))
        pub._writer.write(burst)
        await pub._writer.drain()
        got = sorted([(await sub.recv(timeout=10)).payload
                      for _ in range(16)])
        assert got == sorted(f"hi{i}".encode() for i in range(16))
        assert model.launch_count > launches0, "burst did not use device"
        await sub.close(); await pub.close()

    run(scenario, app=app)


def test_host_bypass_rules_still_fire(run):
    """force_host batches must run rules through the normal hook fold
    (the co-batch gate stays off)."""
    app = make_device_app()
    app.pipeline.min_device_batch = 8
    hits = []
    app.rules.register_action("sink", lambda cols, a: hits.append(cols))
    app.rules.create_rule("r", 'SELECT topic FROM "rb/#"',
                          [{"function": "sink", "args": {}}])

    async def scenario(server):
        sub = MqttClient(port=server.port, clientid="rb-s")
        await sub.connect()
        await sub.subscribe("rb/t", qos=0)
        pub = MqttClient(port=server.port, clientid="rb-p")
        await pub.connect()
        for i in range(3):
            await pub.publish("rb/t", b"x", qos=0)
            await sub.recv(timeout=10)
        assert len(hits) == 3, hits
        await sub.close(); await pub.close()

    run(scenario, app=app)


def test_adaptive_knee_tracks_measured_costs():
    from emqx_tpu.broker.pipeline import PublishPipeline

    class FakeBroker:
        model = object()
    p = PublishPipeline(FakeBroker(), cm=None)
    p._rtt_ema = 0.070          # tunneled chip
    p._host_cost_ema = 5e-6     # measured oracle walk
    assert p.device_knee() == p.max_batch      # saturates at max_batch
    p._rtt_ema = 0.001          # local chip
    assert p.device_knee() == 200
    p.min_device_batch = 32     # explicit config wins
    assert p.device_knee() == 32
    p.broker.model = None
    assert p.device_knee() == 0


def test_pipeline_depth_preserves_order_and_raises_throughput(run):
    """VERDICT r4 #4: >2 in-flight launches. At depth 4 the per-
    publisher order still holds across a burst that spans many batches
    (collection is strictly in submission order)."""
    app = make_device_app()
    app.pipeline.depth = 4
    app.pipeline.max_batch = 8       # force many small batches

    async def scenario(server):
        sub = MqttClient(port=server.port, clientid="dsub")
        pub = MqttClient(port=server.port, clientid="dpub")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("dp/t", qos=0)
        for i in range(120):
            await pub.publish("dp/t", b"%d" % i, qos=0)
        seen = [int((await sub.recv(timeout=30)).payload)
                for _ in range(120)]
        assert seen == list(range(120))
        assert app.pipeline.batches >= 120 // 8
        await sub.disconnect()
        await pub.disconnect()
    run(scenario, app=app)


def test_sojourn_spill_bounds_loaded_latency():
    """VERDICT r4 #4 spill: once a batch's head message has out-waited
    the deadline, the batch answers from the host oracle instead of
    joining the device queue — spilled_batches advances and delivery
    still happens."""
    import time as _t

    from emqx_tpu.core.message import Message

    app = make_device_app()
    app.broker.subscribe("s1", "sp/t")
    pipe = app.pipeline
    pipe.depth = 2
    pipe.spill_ms = 5            # tiny deadline: everything spills
    class _SpyCM:
        def __init__(self):
            self.got = []

        def dispatch(self, merged):
            self.got.append(merged)

    pipe.cm = _SpyCM()
    old = Message(topic="sp/t", payload=b"x")
    old.timestamp -= 1000        # aged 1s in the queue
    pipe.submit(old)
    pipe.flush()
    assert pipe.spilled_batches == 1, pipe.spilled_batches
    assert pipe.cm.got and "s1" in pipe.cm.got[0], pipe.cm.got
