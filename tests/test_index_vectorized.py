"""Scalar vs vectorized TrieIndex builder equivalence.

The vectorized level-synchronous builder (router/index.py
``_rebuild_vectorized``) only engages above ``VECTOR_BUILD_MIN`` live
filters — above every other test's scale — so it gets its own direct
coverage here: both builders must produce semantically identical tries
(same match results for every topic) on randomized filter sets with
collisions, across edge-table growth/probe-overflow, and at the real
``VECTOR_BUILD_MIN`` engagement scale that the live serving path hits
(mirrors emqx_trie.erl:113-144 insert/match semantics).
"""

import random

import numpy as np
import pytest

from emqx_tpu.core import topic as T
from emqx_tpu.ops import trie_match as tm
from emqx_tpu.router.index import TrieIndex
from emqx_tpu.router.trie import Trie


def random_filters(rng, n, alphabet, max_depth=7):
    filters = set()
    while len(filters) < n:
        ws = [rng.choice(alphabet + ["+", "#"])
              for _ in range(rng.randint(1, max_depth))]
        if "#" in ws:
            ws = ws[: ws.index("#") + 1]
        f = T.join(ws)
        if T.validate_filter(f):
            filters.add(f)
    return sorted(filters)


def build_pair(filters, max_levels=10, max_probes=8):
    """Same filter set through both builders."""
    scalar = TrieIndex(max_levels=max_levels, max_probes=max_probes)
    scalar.load(filters)
    scalar._rebuild_scalar()
    vec = TrieIndex(max_levels=max_levels, max_probes=max_probes)
    vec.load(filters)
    vec._rebuild_vectorized()
    return scalar, vec


def match_all(idx, topics, K=64):
    dev = tm.device_trie(idx.arrays)
    tokens, lengths, sys_flags, too_long = idx.tokenize(topics)
    assert not too_long
    cand, overflow, _ = tm.match_batch(
        dev, np.asarray(tokens), np.asarray(lengths),
        np.asarray(sys_flags), K=K)
    cand = np.asarray(cand)
    out = []
    for b in range(len(topics)):
        fids = cand[b][cand[b] >= 0]
        out.append(sorted(idx.filters[f] for f in fids))
    return out, np.asarray(overflow)


@pytest.mark.parametrize("seed,n_filters", [(11, 2_000), (12, 20_000)])
def test_vectorized_equals_scalar_randomized(seed, n_filters):
    """The r2 regression repro: 20k filters crashed ``_rebuild_vectorized``
    with a numpy broadcast error the moment any probe slot was occupied
    (router/index.py:526).  Equivalence is checked semantically — node
    numbering differs between builders by design."""
    rng = random.Random(seed)
    alphabet = [f"w{i}" for i in range(40)] + ["", "a", "b"]
    filters = random_filters(rng, n_filters, alphabet)
    scalar, vec = build_pair(filters)

    assert vec.n_nodes == scalar.n_nodes
    assert vec.n_edges == scalar.n_edges

    topics = []
    for _ in range(512):
        nw = [rng.choice(alphabet[:24] + ["zz"])
              for _ in range(rng.randint(1, 8))]
        topics.append(T.join(nw))
    got_s, ov_s = match_all(scalar, topics, K=128)
    got_v, ov_v = match_all(vec, topics, K=128)
    for b, topic in enumerate(topics):
        if ov_s[b] or ov_v[b]:
            continue
        assert got_s[b] == got_v[b], (topic, got_s[b], got_v[b])
    assert (ov_s == ov_v).all()


def test_vectorized_probe_overflow_grows_table():
    """Tight probe bound forces collision handling through multiple probe
    rounds and (usually) at least one table-growth retry — the loop the
    broken `placed` bookkeeping corrupted."""
    rng = random.Random(7)
    alphabet = [f"n{i}" for i in range(300)]
    filters = random_filters(rng, 5_000, alphabet, max_depth=5)
    scalar, vec = build_pair(filters, max_probes=2)
    topics = [T.join([rng.choice(alphabet)
                      for _ in range(rng.randint(1, 5))])
              for _ in range(256)]
    got_s, _ = match_all(scalar, topics)
    got_v, _ = match_all(vec, topics)
    assert got_s == got_v


def test_vectorized_engages_on_live_path():
    """Above VECTOR_BUILD_MIN, rebuild() must take the vectorized path and
    produce a usable trie (this is the ≥50k-live-filter state in which the
    r2 device broker dropped every publish)."""
    n = TrieIndex.VECTOR_BUILD_MIN
    idx = TrieIndex(max_levels=10)
    idx.load([f"fleet/{i}/+/telemetry" for i in range(n)])
    arrays = idx.ensure()          # would raise before the fix
    assert arrays.n_filters == n
    got, overflow = match_all(idx, ["fleet/17/axle3/telemetry", "fleet/x/y"])
    assert not overflow.any()
    assert got[0] == ["fleet/17/+/telemetry"]
    assert got[1] == []


def test_vectorized_vs_oracle_with_deletes_and_overdepth():
    """Vectorized build over a filter set containing over-depth filters
    (deeper than max_levels — previously an IndexError) and post-build
    incremental mutations must stay equivalent to the host oracle."""
    rng = random.Random(3)
    alphabet = ["a", "b", "c", "d", ""]
    filters = random_filters(rng, 800, alphabet, max_depth=6)
    deep = ["a/b/c/d/a/b/c/d/+", "a/b/c/d/a/b/c/d/e/#"]  # > max_levels=6
    idx = TrieIndex(max_levels=6)
    idx.load(filters + deep)
    idx._rebuild_vectorized()

    dropped = set(rng.sample(filters, 200))
    for f in dropped:
        idx.delete(f)
    oracle = Trie()
    for f in filters:
        if f not in dropped:
            oracle.insert(f)

    topics = [T.join([rng.choice(alphabet[:4] + ["q"])
                      for _ in range(rng.randint(1, 6))])
              for _ in range(300)]
    got, overflow = match_all(idx, topics, K=128)
    for b, topic in enumerate(topics):
        if overflow[b]:
            continue
        assert got[b] == sorted(oracle.match(topic)), topic
