"""Cluster-replicated config transactions (emqx_cluster_rpc.erl:26-44,
71-140): ordered commit log via the core coordinator, per-node cursors,
catch-up on join, stall + skip_failed_commit / fast_forward escape
hatches, core/replicant roles."""

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.cluster.conf import ClusterConfError
from emqx_tpu.cluster.harness import stop as stop_nodes
from emqx_tpu.cluster.node import ClusterNode
from emqx_tpu.cluster.transport import LocalBus
from emqx_tpu.config.config import Config


def make_conf_cluster(names, roles=None):
    fabric = LocalBus.Fabric()
    nodes = []
    for i, name in enumerate(names):
        conf = Config()
        conf.init_load("")
        app = BrokerApp.from_config(conf, node=name)
        node = ClusterNode(
            name, LocalBus(name, fabric), app=app,
            role=(roles or {}).get(name, "core"))
        node.fabric = fabric
        nodes.append(node)
    for node in nodes[1:]:
        node.join([names[0]])
    return nodes


def test_put_replicates_to_all_nodes():
    nodes = make_conf_cluster(["n1", "n2", "n3"])
    try:
        # write on the coordinator (lowest core = n1)
        nodes[0].app.config.put("mqtt.max_packet_size", 2048)
        for n in nodes:
            assert n.app.config.get("mqtt.max_packet_size") == 2048
        # write on a NON-coordinator routes through the coordinator
        nodes[2].app.config.put("mqtt.max_qos_allowed", 1)
        for n in nodes:
            assert n.app.config.get("mqtt.max_qos_allowed") == 1
        ids = {n.conf.cursor for n in nodes}
        assert ids == {2}, "all cursors advance through the same log"
    finally:
        stop_nodes(nodes)


def test_replicant_forwards_and_requires_a_core():
    nodes = make_conf_cluster(
        ["n1", "n2"], roles={"n1": "core", "n2": "replicant"})
    n1, n2 = nodes
    try:
        assert n2.conf.coordinator() == "n1"
        n2.app.config.put("mqtt.retain_available", False)
        assert n1.app.config.get("mqtt.retain_available") is False
        # core gone → replicant cannot commit (mria: replicants don't own
        # the table)
        n2._nodedown("n1")
        with pytest.raises(ClusterConfError, match="core"):
            n2.app.config.put("mqtt.retain_available", True)
    finally:
        stop_nodes(nodes)


def test_joiner_catches_up_from_snapshot():
    nodes = make_conf_cluster(["n1", "n2"])
    try:
        for i, v in enumerate((1024, 2048, 4096)):
            nodes[0].app.config.put("mqtt.max_packet_size", v)
        # a fresh node joins AFTER the txns — bootstrap replays the log
        conf = Config()
        conf.init_load("")
        app = BrokerApp.from_config(conf, node="n9")
        late = ClusterNode("n9", LocalBus("n9", nodes[0].fabric), app=app)
        late.join(["n1"])
        assert late.app.config.get("mqtt.max_packet_size") == 4096
        assert late.conf.cursor == 3
        late.transport.close()
    finally:
        stop_nodes(nodes)


def test_failed_commit_stalls_then_skip_advances():
    nodes = make_conf_cluster(["n1", "n2", "n3"])
    n1, n2, n3 = nodes
    try:
        # poison handler ONLY on n2: the txn applies on n1/n3, n2 stalls
        def poison(path, value, old):
            if value == 666:
                raise ValueError("n2 rejects 666")
            return value

        n2.app.config.add_handler("mqtt.max_inflight", poison)
        n1.app.config.put("mqtt.max_inflight", 666)
        assert n1.app.config.get("mqtt.max_inflight") == 666
        assert n3.app.config.get("mqtt.max_inflight") == 666
        assert n2.app.config.get("mqtt.max_inflight") != 666
        st = n2.conf.status()
        assert st["failed"] and st["failed"]["tnx_id"] == 1
        assert st["tnx_id"] == 0

        # later txns queue behind the stall (strict order)
        n1.app.config.put("mqtt.max_awaiting_rel", 50)
        assert n2.app.config.get("mqtt.max_awaiting_rel") != 50
        assert n2.conf.max_seen == 2

        # operator skips the poison entry; queued entries then apply
        assert n2.conf.skip_failed_commit() == 2
        assert n2.app.config.get("mqtt.max_awaiting_rel") == 50
        assert n2.conf.status()["failed"] is None

        # cluster_status sees every node's cursor
        view = {s["node"]: s["tnx_id"] for s in n1.conf.cluster_status()}
        assert view == {"n1": 2, "n2": 2, "n3": 2}
    finally:
        stop_nodes(nodes)


def test_coordinator_rejects_locally_failing_txn():
    """The reference aborts a multicall whose MFA fails on the initiating
    node — nothing commits anywhere."""
    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        def poison(path, value, old):
            raise ValueError("bad value")

        n1.app.config.add_handler("mqtt.server_keepalive", poison)
        with pytest.raises(Exception):
            n1.app.config.put("mqtt.server_keepalive", 30)
        assert n1.conf.max_seen == 0
        assert n2.conf.max_seen == 0
        # a non-coordinator initiator gets the rejection surfaced too
        with pytest.raises(ClusterConfError, match="rejected"):
            n2.app.config.put("mqtt.server_keepalive", 30)
        assert n2.conf.max_seen == 0
    finally:
        stop_nodes(nodes)


def test_fast_forward_to_commit():
    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        def poison(path, value, old):
            raise ValueError("nope")

        n2.app.config.add_handler("mqtt.max_topic_levels", poison)
        n1.app.config.put("mqtt.max_topic_levels", 9)
        n1.app.config.put("mqtt.max_subscriptions", 77)
        assert n2.conf.status()["failed"]
        # operator asserts n2's state is fine as-is and jumps the cursor
        assert n2.conf.fast_forward_to_commit(2) == 2
        assert n2.app.config.get("mqtt.max_subscriptions") != 77  # skipped
        assert n2.conf.status()["failed"] is None
        # new txns apply normally again
        n1.app.config.put("mqtt.max_subscriptions", 88)
        assert n2.app.config.get("mqtt.max_subscriptions") == 88
    finally:
        stop_nodes(nodes)


def test_remove_replicates():
    nodes = make_conf_cluster(["n1", "n2"])
    try:
        nodes[0].app.config.put("mqtt.max_packet_size", 555)
        assert nodes[1].app.config.get("mqtt.max_packet_size") == 555
        nodes[1].app.config.remove("mqtt.max_packet_size")
        default = Config().get("mqtt.max_packet_size")
        for n in nodes:
            assert n.app.config.get("mqtt.max_packet_size") == default
    finally:
        stop_nodes(nodes)


def test_split_brain_heal_adopts_winner():
    """Both sides of a partition commit conflicting tnx_ids; on heal the
    higher-named core adopts the lower's log + override wholesale (the
    ekka-autoheal outcome: the minority island's writes are discarded)."""
    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        n1.app.config.put("mqtt.max_packet_size", 1111)   # tnx 1 everywhere
        # partition: both sides mark the other down
        n1._nodedown("n2")
        n2._nodedown("n1")
        # both sides keep accepting writes (availability like the
        # reference); each assigns tnx 2 with different content
        n1.app.config.put("mqtt.max_packet_size", 2222)
        n2.app.config.put("mqtt.max_packet_size", 3333)
        assert n1.conf.max_seen == n2.conf.max_seen == 2
        assert n1.app.config.get("mqtt.max_packet_size") == 2222
        assert n2.app.config.get("mqtt.max_packet_size") == 3333
        # heal: both re-bootstrap from each other
        n1._mark_alive("n2")
        n2._mark_alive("n1")
        # n1 < n2 → n1 wins the tie-break; n2 adopts n1's state
        assert n1.app.config.get("mqtt.max_packet_size") == 2222
        assert n2.app.config.get("mqtt.max_packet_size") == 2222
        assert n2.conf.cursor == n1.conf.cursor == 2
        # post-heal txns replicate normally again
        n2.app.config.put("mqtt.max_packet_size", 4444)
        assert n1.app.config.get("mqtt.max_packet_size") == 4444
        assert n2.app.config.get("mqtt.max_packet_size") == 4444
    finally:
        stop_nodes(nodes)


def test_two_node_cluster_survives_nodedown():
    """The surviving core keeps committing config txns after the other
    node dies (availability parity: the reference's cluster_rpc does not
    halt on nodedown — the dead node catches up on rejoin)."""
    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        n1.app.config.put("mqtt.max_packet_size", 1000)
        n1._nodedown("n2")
        n1.app.config.put("mqtt.max_packet_size", 2000)   # must not raise
        assert n1.app.config.get("mqtt.max_packet_size") == 2000
        # n2 also keeps serving (it becomes its own coordinator)
        n2._nodedown("n1")
        n2.app.config.put("mqtt.max_qos_allowed", 1)
        assert n2.app.config.get("mqtt.max_qos_allowed") == 1
    finally:
        stop_nodes(nodes)


def test_failover_tail_sync_no_duplicate_tnx_id():
    """The old coordinator's last commit reached n3 but not n2; when n2
    takes over it must learn the tail from n3 before assigning ids —
    otherwise it re-issues the same tnx_id and n3 silently diverges."""
    nodes = make_conf_cluster(["n1", "n2", "n3"])
    n1, n2, n3 = nodes
    try:
        n1.app.config.put("mqtt.max_packet_size", 1111)   # tnx 1
        # simulate the lost cast: hand-deliver tnx 2 to n3 only
        entry = {"tnx_id": 2, "kind": "put",
                 "path": ["mqtt", "max_packet_size"], "value": 2222,
                 "initiator": "n1"}
        with n1.conf._lock:
            n1.conf.log[2] = entry
            n1.conf.max_seen = 2
            n1.conf.cursor = 2
        n3.conf.h_commit("n1", entry)
        assert n3.conf.cursor == 2 and n2.conf.cursor == 1
        # n1 dies; n2 becomes coordinator and must NOT reuse tnx 2
        n2._nodedown("n1")
        n3._nodedown("n1")
        n2.app.config.put("mqtt.max_inflight", 64)
        assert n2.conf.log[3]["path"] == ["mqtt", "max_inflight"]
        assert n2.conf.log[2] == entry          # learned from n3
        assert n2.app.config.get("mqtt.max_packet_size") == 2222
        assert n3.app.config.get("mqtt.max_inflight") == 64
    finally:
        stop_nodes(nodes)


def test_stalled_initiator_surfaces_error_not_stale_success():
    """A txn that commits cluster-wide but fails to apply on the
    INITIATING node must raise, not return the stale value as success."""
    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        def poison(path, value, old):
            raise ValueError("n2 cannot apply this")

        n2.app.config.add_handler("mqtt.max_mqueue_len", poison)
        with pytest.raises(ClusterConfError, match="committed cluster-wide"):
            n2.app.config.put("mqtt.max_mqueue_len", 42)
        # ...but the cluster did commit it (n1 applied)
        assert n1.app.config.get("mqtt.max_mqueue_len") == 42
        assert n2.conf.status()["failed"]["tnx_id"] == 1
    finally:
        stop_nodes(nodes)


def test_log_pruning_and_snapshot_adoption():
    """Applied entries compact beyond the KEEP window; a joiner that is
    behind the compaction horizon adopts the snapshot wholesale."""
    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        old_keep = type(n1.conf).KEEP
        type(n1.conf).KEEP = 5
        for i in range(12):
            n1.app.config.put("mqtt.max_packet_size", 1000 + i)
        n1.conf.prune()
        assert n1.conf.compacted_to == 12 - 5
        assert len(n1.conf.log) == 5
        # fresh joiner behind the horizon → snapshot adoption
        conf = Config()
        conf.init_load("")
        app = BrokerApp.from_config(conf, node="n8")
        late = ClusterNode("n8", LocalBus("n8", nodes[0].fabric), app=app)
        late.join(["n1"])
        assert late.app.config.get("mqtt.max_packet_size") == 1011
        assert late.conf.cursor == 12
        # and catchup() against a compacted peer also adopts
        resp = n1.conf.h_catchup("nX", since=2)
        assert "snapshot" in resp
        late.transport.close()
    finally:
        type(nodes[0].conf).KEEP = old_keep
        stop_nodes(nodes)


def test_rejected_vs_unavailable_error_classes():
    """Validation failure on the coordinator is ClusterConfRejected
    (permanent → HTTP 400); infra conditions stay ClusterConfError
    (transient → 503)."""
    from emqx_tpu.cluster.conf import ClusterConfRejected

    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        # schema rejection travels back to the non-coordinator initiator
        # as the Rejected subclass
        with pytest.raises(ClusterConfRejected):
            n2.app.config.put("mqtt.max_packet_size", "not-an-int")
        # transient: no core reachable is plain ClusterConfError
        n2._nodedown("n1")
        n2.role = "replicant"
        try:
            n2.app.config.put("mqtt.max_packet_size", 1)
            raise AssertionError("should have raised")
        except ClusterConfRejected:
            raise AssertionError("transient error misclassified")
        except Exception as e:
            assert "core" in str(e)
    finally:
        stop_nodes(nodes)


def test_adoption_fires_section_listeners():
    """Split-brain adoption must notify per top-level config section so
    runtime state (e.g. shared-sub strategy) follows the adopted tree."""
    nodes = make_conf_cluster(["n1", "n2"])
    n1, n2 = nodes
    try:
        n1._nodedown("n2")
        n2._nodedown("n1")
        n1.app.config.put("shared_subscription_strategy", "local")
        n2.app.config.put("shared_subscription_strategy", "sticky")
        # heal: n2 adopts n1's override and must re-wire runtime state
        n2._mark_alive("n1")
        n1._mark_alive("n2")
        assert n2.app.config.get("shared_subscription_strategy") == "local"
        assert n2.app.shared.strategy == "local"
    finally:
        stop_nodes(nodes)


def test_split_brain_tiebreak_is_by_entry_coordinator_not_sender():
    """A node must reach the same adoption verdict no matter WHICH peer
    delivers the winning log — the tie-break compares the conflicting
    entries' committing coordinators."""
    nodes = make_conf_cluster(["a", "b", "c"])
    a, b, c = nodes
    try:
        a.app.config.put("mqtt.max_packet_size", 1)     # tnx 1 everywhere
        # partition: {a} vs {b, c}
        a._nodedown("b"); a._nodedown("c")
        b._nodedown("a"); c._nodedown("a")
        a.app.config.put("mqtt.max_packet_size", 100)   # coord a, tnx 2
        b.app.config.put("mqtt.max_packet_size", 200)   # coord b, tnx 2
        assert c.app.config.get("mqtt.max_packet_size") == 200
        # heal: a receives the OTHER side's log from c (sender 'c' > 'a',
        # but the conflicting entry's coord is 'b'... and a's own is 'a':
        # 'a' < 'b' → side {b,c} must adopt side {a}; a keeps its log
        # regardless of who the sender is)
        a._mark_alive("c"); c._mark_alive("a")
        b._mark_alive("a"); a._mark_alive("b")
        for n in nodes:
            assert n.app.config.get("mqtt.max_packet_size") == 100, n.name
        assert {n.conf.cursor for n in nodes} == {2}
    finally:
        stop_nodes(nodes)
