"""Test harness: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is
validated on host-platform virtual devices (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the session's axon/tpu default
# the suite validates the XLA kernel ON the cpu backend — keep the
# platform-aware host-matcher dispatch out of the way except in the
# tests that opt back in (test_host_dispatch)
os.environ.setdefault("EMQX_TPU_CPU_KERNEL", "xla")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# the axon sitecustomize force-registers the TPU platform via
# jax.config.update("jax_platforms", ...), which beats the env var —
# override it back so tests run on the virtual 8-device CPU mesh
jax.config.update("jax_platforms", "cpu")

import random

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soaks excluded from tier-1 (-m 'not slow')")


@pytest.fixture
def rng():
    return random.Random(0xE19)
