"""Rule-engine tests: SQL parse, runtime eval, builtin funcs, events,
actions, metrics, end-to-end via the broker (reference ground:
emqx_rule_engine_SUITE, emqx_rule_funcs_SUITE)."""

import json

import pytest

from emqx_tpu.core.message import Message
from emqx_tpu.rules.engine import RuleEngine, render_template
from emqx_tpu.rules.funcs import FUNCS
from emqx_tpu.rules.runtime import apply_select, eval_expr
from emqx_tpu.rules.sqlparser import SqlError, parse


def run_sql(sql, **columns):
    out = apply_select(parse(sql), columns)
    return out if out is None else out[0] if len(out) == 1 else out


# -- parser ----------------------------------------------------------------

def test_parse_basic_select():
    s = parse("SELECT * FROM 't/#'")
    assert s.fields == [("*",)] and s.topics == ["t/#"] and s.where is None


def test_parse_fields_aliases_where():
    s = parse("SELECT payload.x as x, qos + 1 AS q FROM 't/1', 't/2' "
              "WHERE qos > 0 and clientid != 'admin'")
    assert len(s.fields) == 2 and s.topics == ["t/1", "t/2"]
    assert s.where[0] == "and"


def test_parse_errors():
    with pytest.raises(SqlError):
        parse("SELECT FROM 't'")
    with pytest.raises(SqlError):
        parse("SELECT * FROM")
    with pytest.raises(SqlError):
        parse("SELECT * FROM 't' WHERE x = ")
    with pytest.raises(SqlError):
        parse("SELECT * FROM 't' garbage")


# -- runtime ---------------------------------------------------------------

def test_select_projection_and_where():
    out = run_sql("SELECT payload.temp AS t, clientid FROM 't/#' "
                  "WHERE payload.temp > 20",
                  payload=b'{"temp": 25}', clientid="c1", topic="t/1")
    assert out == {"t": 25, "clientid": "c1"}
    assert run_sql("SELECT * FROM 't/#' WHERE payload.temp > 20",
                   payload=b'{"temp": 15}', clientid="c1") is None


def test_select_star_and_nested_alias():
    out = run_sql("SELECT *, qos + 1 AS meta.next_qos FROM 't'",
                  qos=1, topic="t", clientid="c")
    assert out["qos"] == 1 and out["meta"]["next_qos"] == 2


def test_arithmetic_and_precedence():
    assert run_sql("SELECT 2 + 3 * 4 AS v FROM 't'")["v"] == 14
    assert run_sql("SELECT (2 + 3) * 4 AS v FROM 't'")["v"] == 20
    assert run_sql("SELECT 7 div 2 AS v FROM 't'")["v"] == 3
    assert run_sql("SELECT 7 mod 2 AS v FROM 't'")["v"] == 1
    assert run_sql("SELECT -payload.x AS v FROM 't'",
                   payload=b'{"x": 5}')["v"] == -5


def test_string_concat_and_compare():
    out = run_sql("SELECT 'a' + clientid AS s FROM 't'", clientid="b")
    assert out["s"] == "ab"
    assert run_sql("SELECT * FROM 't' WHERE clientid = 'c1'",
                   clientid="c1") is not None
    # payload bytes compare equal to strings
    assert run_sql("SELECT * FROM 't' WHERE payload = 'on'",
                   payload=b"on") is not None


def test_in_case_and_index():
    assert run_sql("SELECT * FROM 't' WHERE qos IN (1, 2)",
                   qos=2) is not None
    assert run_sql("SELECT * FROM 't' WHERE qos IN (1, 2)", qos=0) is None
    out = run_sql("SELECT CASE WHEN qos > 1 THEN 'hi' ELSE 'lo' END AS l "
                  "FROM 't'", qos=2)
    assert out["l"] == "hi"
    out = run_sql("SELECT payload.xs[2] AS second FROM 't'",
                  payload=b'{"xs": [10, 20, 30]}')
    assert out["second"] == 20


def test_foreach_do_incase():
    sql = ("FOREACH payload.sensors AS s DO s.name AS name, s.v AS v "
           "INCASE s.v > 10 FROM 't'")
    payload = json.dumps({"sensors": [
        {"name": "a", "v": 5}, {"name": "b", "v": 15},
        {"name": "c", "v": 25}]}).encode()
    out = apply_select(parse(sql), {"payload": payload})
    assert out == [{"name": "b", "v": 15}, {"name": "c", "v": 25}]


def test_like_operator():
    assert run_sql("SELECT * FROM 't' WHERE clientid LIKE 'dev-%'",
                   clientid="dev-42") is not None
    assert run_sql("SELECT * FROM 't' WHERE clientid LIKE 'dev-%'",
                   clientid="sensor-1") is None


# -- funcs -----------------------------------------------------------------

def test_builtin_funcs_sampler():
    assert FUNCS["upper"]("abc") == "ABC"
    assert FUNCS["substr"]("hello", 1, 3) == "ell"
    assert FUNCS["split"]("a,b,c") == ["a", "b", "c"]
    assert FUNCS["concat"]("a", 1, "b") == "a1b"
    assert FUNCS["nth"](2, [1, 2, 3]) == 2
    assert FUNCS["map_get"]("k", {"k": "v"}) == "v"
    assert FUNCS["json_decode"]('{"a":1}') == {"a": 1}
    assert FUNCS["base64_decode"](FUNCS["base64_encode"](b"xy")) == b"xy"
    assert FUNCS["md5"]("abc") == "900150983cd24fb0d6963f7d28e17f72"
    assert FUNCS["regex_match"]("v1.2", r"^v\d")
    assert FUNCS["nth_topic_level"](2, "a/b/c") == "b"
    assert FUNCS["topic"]("a", "b", 1) == "a/b/1"
    assert FUNCS["now_timestamp"]() > 1_700_000_000
    assert FUNCS["is_num"](3) and not FUNCS["is_num"](True)


def test_funcs_in_sql():
    out = run_sql("SELECT upper(clientid) AS u, "
                  "nth_topic_level(2, topic) AS lvl FROM 't/#'",
                  clientid="dev1", topic="t/abc")
    assert out == {"u": "DEV1", "lvl": "abc"}


def test_template_render():
    cols = {"topic": "t/1", "payload": b'{"v": 7}', "clientid": "c",
            "nested": {"a": [1, 2]}}
    assert render_template("up/${clientid}/${topic}", cols) == "up/c/t/1"
    assert render_template("${payload.v}", cols) == "7"
    assert render_template("${nested}", cols) == '{"a":[1,2]}'


# -- engine ----------------------------------------------------------------

def _engine():
    out = []
    eng = RuleEngine(publish_fn=out.append)
    return eng, out


def test_rule_republish_action():
    eng, out = _engine()
    eng.create_rule(
        "r1", "SELECT payload.v AS v, topic FROM 'sensor/#' WHERE "
        "payload.v > 10",
        [{"function": "republish",
          "args": {"topic": "alert/${topic}", "payload": "v=${v}",
                   "qos": 1}}])
    eng._on_publish(Message(topic="sensor/1", payload=b'{"v": 99}'))
    assert len(out) == 1
    assert out[0].topic == "alert/sensor/1"
    assert out[0].payload == b"v=99" and out[0].qos == 1
    eng._on_publish(Message(topic="sensor/1", payload=b'{"v": 3}'))
    assert len(out) == 1                          # filtered by WHERE
    m = eng.metrics.get_counters("r1")
    assert m["matched"] == 2 and m["passed"] == 1
    assert m["failed.no_result"] == 1 and m["actions.success"] == 1


def test_rule_no_self_loop():
    eng, out = _engine()
    eng.create_rule("loop", "SELECT * FROM 't/#'",
                    [{"function": "republish",
                      "args": {"topic": "t/again", "payload": "x"}}])
    eng._on_publish(Message(topic="t/1", payload=b"go"))
    assert len(out) == 1
    # feed the republished message back: the same rule must not re-fire
    eng._on_publish(out[0])
    assert len(out) == 1


def test_event_rules():
    eng, out = _engine()
    eng.create_rule(
        "ev", "SELECT clientid, reason FROM '$events/client_disconnected'",
        [{"function": "console"}])
    from emqx_tpu.broker.hooks import Hooks
    hooks = Hooks()
    eng.attach(hooks)

    class CI:
        clientid = "c7"
        username = None
    hooks.run("client.disconnected", (CI(), "keepalive_timeout"))
    assert eng._console_out[-1]["clientid"] == "c7"
    assert eng._console_out[-1]["reason"] == "keepalive_timeout"
    assert eng.metrics.get("ev", "passed") == 1


def test_unknown_event_topic_rejected():
    eng, _ = _engine()
    with pytest.raises(ValueError):
        eng.create_rule("bad", "SELECT * FROM '$events/nope'", [])


def test_custom_action_and_disable():
    eng, _ = _engine()
    got = []
    eng.register_action("collect", lambda cols, args: got.append(
        (cols["topic"], args.get("tag"))))
    r = eng.create_rule("c1", "SELECT * FROM 'x/#'",
                        [{"function": "collect", "args": {"tag": "T"}}])
    eng._on_publish(Message(topic="x/1", payload=b""))
    assert got == [("x/1", "T")]
    r.enabled = False
    eng._on_publish(Message(topic="x/1", payload=b""))
    assert len(got) == 1


def test_sql_test_api():
    eng, _ = _engine()
    res = eng.test_sql("SELECT upper(clientid) AS u FROM 't'",
                       {"clientid": "ab"})
    assert res == [{"u": "AB"}]


def test_rules_via_live_broker():
    """End-to-end: rule transforms device telemetry into an alert topic
    another subscriber receives."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.mqtt import packet as P

    app = BrokerApp()
    app.rules.create_rule(
        "alert", "SELECT payload.temp AS t, clientid FROM 'dev/+/temp' "
        "WHERE payload.temp > 30",
        [{"function": "republish",
          "args": {"topic": "alerts/${clientid}",
                   "payload": "overheat ${t}"}}])
    watcher = Channel(app.broker, app.cm)
    watcher.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="watch"))
    watcher.handle_in(P.Subscribe(packet_id=1,
                                  topic_filters=[("alerts/#", {"qos": 0})]))
    dev = Channel(app.broker, app.cm)
    dev.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="dev42"))
    dev.handle_in(P.Publish(topic="dev/42/temp", qos=0,
                            payload=b'{"temp": 41}'))
    pubs = [p for p in watcher.outbox if isinstance(p, P.Publish)]
    assert len(pubs) == 1
    assert pubs[0].topic == "alerts/dev42"
    assert pubs[0].payload == b"overheat 41"
    # below threshold → no alert
    dev.handle_in(P.Publish(topic="dev/42/temp", qos=0,
                            payload=b'{"temp": 20}'))
    assert len([p for p in watcher.outbox
                if isinstance(p, P.Publish)]) == 1


def test_builtin_funcs_long_tail_via_sql():
    """The bit/compression/topic/map/date func families added for parity
    with emqx_rule_funcs.erl, exercised through real SQL."""
    from emqx_tpu.rules.engine import RuleEngine
    from emqx_tpu.core.message import Message

    eng = RuleEngine(node="n1")
    got = []
    eng.register_action("probe", lambda cols, args: got.append(cols))
    eng.create_rule(
        id="tail",
        sql=("SELECT bitand(12, 10) as band, mod(7, 3) as m, "
             "contains_topic_match(['t/+'], topic) as hit, "
             "map_path('a.b', json_decode(payload)) as nested, "
             "hash('sha256', 'x') as h "
             'FROM "t/#"'),
        actions=[{"function": "probe"}])
    eng.ingest(Message(topic="t/1", payload=b'{"a": {"b": 42}}'))
    assert got and got[0]["band"] == 8 and got[0]["m"] == 1
    assert got[0]["hit"] is True and got[0]["nested"] == 42
    assert len(got[0]["h"]) == 64


def test_kv_store_scoped_per_rule(engine_and_broker=None):
    """kv_store_*/proc_dict_* are namespaced per rule (the reference
    scopes them to the rule worker's process dictionary) — two rules
    using the same key must not collide."""
    from emqx_tpu.rules import funcs as F

    t1 = F.set_rule_context("rule_a")
    try:
        F.FUNCS["kv_store_put"]("k", 1)
        assert F.FUNCS["kv_store_get"]("k") == 1
    finally:
        F.reset_rule_context(t1)
    t2 = F.set_rule_context("rule_b")
    try:
        assert F.FUNCS["kv_store_get"]("k") is None
        F.FUNCS["kv_store_put"]("k", 2)
        assert F.FUNCS["kv_store_get"]("k") == 2
    finally:
        F.reset_rule_context(t2)
    F.drop_rule_store("rule_a")
    F.drop_rule_store("rule_b")


def test_kv_store_bounded():
    from emqx_tpu.rules import funcs as F

    tok = F.set_rule_context("rule_bound")
    try:
        for i in range(F._KV_MAX_KEYS + 50):
            F.FUNCS["kv_store_put"](f"k{i}", i)
        assert len(F._KV_STORE["rule_bound"]) == F._KV_MAX_KEYS
        assert F.FUNCS["kv_store_get"]("k0") is None      # evicted oldest
    finally:
        F.reset_rule_context(tok)
        F.drop_rule_store("rule_bound")


# -- topic index + device co-batching (BASELINE config 5) ----------------------

def _mk_engine():
    from emqx_tpu.rules.engine import RuleEngine

    return RuleEngine(node="n1")


def test_rules_for_topic_is_trie_indexed():
    e = _mk_engine()
    e.create_rule("r1", 'SELECT * FROM "fleet/+/speed"', [])
    e.create_rule("r2", 'SELECT * FROM "fleet/#"', [])
    e.create_rule("r3", 'SELECT * FROM "other/x"', [])
    e.create_rule("r4", 'SELECT * FROM "fleet/+/speed"', [])  # shared filter
    got = sorted(r.id for r in e.rules_for_topic("fleet/v1/speed"))
    assert got == ["r1", "r2", "r4"]
    assert [r.id for r in e.rules_for_topic("other/x")] == ["r3"]
    assert e.rules_for_topic("unrelated") == []
    # disabled rules stay indexed but don't fire
    e.rules["r2"].enabled = False
    got = sorted(r.id for r in e.rules_for_topic("fleet/v1/speed"))
    assert got == ["r1", "r4"]
    # deleting one sharer keeps the filter; deleting both removes it
    e.delete_rule("r1")
    assert [r.id for r in e.rules_for_topic("fleet/v9/speed")] == ["r4"]
    e.delete_rule("r4")
    e.rules["r2"].enabled = True
    assert [r.id for r in e.rules_for_topic("fleet/v9/speed")] == ["r2"]
    assert "fleet/+/speed" not in e._filter_rules


def test_device_cobatch_fires_rules_once():
    """With a RouterModel attached, publish_batch matches rule filters in
    the SAME kernel launch; the hook path must not double-fire."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.core.message import Message
    from emqx_tpu.models.router_model import RouterModel

    model = RouterModel(n_sub_slots=64)
    app = BrokerApp(router_model=model)
    fired = []
    app.rules.register_action("record", lambda cols, args: fired.append(
        cols["topic"]))
    app.rules.create_rule(
        "rb", 'SELECT topic FROM "fleet/+/speed"',
        [{"function": "record", "args": {}}])
    # rule filter must be co-batched into the device index
    assert model.index.fid_of("fleet/+/speed") is not None
    app.broker.subscribe("subA", "fleet/#")
    out = app.broker.publish_batch([
        Message(topic="fleet/v1/speed", payload=b"1"),
        Message(topic="fleet/v1/other", payload=b"2"),
    ])
    assert fired == ["fleet/v1/speed"]          # exactly once, first msg only
    assert "subA" in out[0] and "subA" in out[1]  # fan-out unaffected
    # host-path publish still fires rules (hook path, host trie)
    app.broker.publish(Message(topic="fleet/v2/speed", payload=b"3"))
    assert fired == ["fleet/v1/speed", "fleet/v2/speed"]


def test_cobatch_fallback_topic_still_fires_rules():
    """A topic deeper than max_levels takes the host-oracle fallback —
    rules must still fire for it (host trie via on_matched(None))."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.core.message import Message
    from emqx_tpu.models.router_model import RouterModel
    from emqx_tpu.router.index import TrieIndex

    model = RouterModel(TrieIndex(max_levels=4), n_sub_slots=64)
    app = BrokerApp(router_model=model)
    fired = []
    app.rules.register_action("record", lambda cols, args: fired.append(
        cols["topic"]))
    app.rules.create_rule(
        "rf", 'SELECT topic FROM "deep/#"', [{"function": "record", "args": {}}])
    deep = "deep/a/b/c/d/e/f"
    app.broker.subscribe("subD", "deep/#")
    out = app.broker.publish_batch([Message(topic=deep, payload=b"x")])
    assert fired == [deep]
    assert "subD" in out[0]


def test_rule_filter_shared_with_subscription_survives_unsubscribe():
    """A rule FROM filter that equals a live subscription's filter must
    stay in the device index after the subscriber leaves (and vice
    versa)."""
    from emqx_tpu.models.router_model import RouterModel

    model = RouterModel(n_sub_slots=64)
    fid = model.aux_register("shared/+")
    model.subscribe("shared/+", slot=3)
    model.unsubscribe("shared/+", slot=3)
    assert model.index.fid_of("shared/+") == fid      # aux ref keeps it
    model.aux_release("shared/+")
    assert model.index.fid_of("shared/+") is None     # now gone
    # other direction: subscriber keeps it after rule release
    model.subscribe("keep/+", slot=1)
    model.aux_register("keep/+")
    model.aux_release("keep/+")
    assert model.index.fid_of("keep/+") is not None


def test_delayed_message_from_device_batch_still_fires_rules():
    """r3 review regression guard: the co-batch gate must not leak into
    messages hooks store (the delayed queue) — their later republish on
    the host path must still rule-match."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.core.message import Message
    from emqx_tpu.models.router_model import RouterModel

    model = RouterModel(n_sub_slots=64)
    app = BrokerApp(router_model=model)
    fired = []
    app.rules.register_action("record", lambda cols, args: fired.append(
        cols["topic"]))
    app.rules.create_rule(
        "rd", 'SELECT topic FROM "sensor/t"',
        [{"function": "record", "args": {}}])
    # $delayed publish enters through the DEVICE batch path
    app.broker.publish_batch(
        [Message(topic="$delayed/1/sensor/t", payload=b"x")])
    assert fired == []                       # intercepted, queued
    assert len(app.delayed) == 1
    # force the due-time and tick the delayed service (host republish)
    due, seq, msg = app.delayed._heap[0]
    app.delayed._heap[0] = (0, seq, msg)
    app.delayed.tick(now=1)
    assert fired == ["sensor/t"], "rule suppressed after delayed republish"


def test_denied_publish_still_fires_rules_on_device_path():
    """Host hook order runs rules before a deny (retainer-style
    allow_publish=False); the device path must match that."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.core.message import Message
    from emqx_tpu.models.router_model import RouterModel

    model = RouterModel(n_sub_slots=64)
    app = BrokerApp(router_model=model)
    fired = []
    app.rules.register_action("record", lambda cols, args: fired.append(
        cols["topic"]))
    app.rules.create_rule(
        "rx", 'SELECT topic FROM "audit/#"',
        [{"function": "record", "args": {}}])

    def deny(msg):
        msg.headers["allow_publish"] = False
        return msg

    app.hooks.add("message.publish", deny, priority=-200)  # after rules
    out = app.broker.publish_batch(
        [Message(topic="audit/evt", payload=b"x")])
    assert out == [{}]                        # routing denied
    assert fired == ["audit/evt"], "rules must fire before the deny"


def test_round3_rule_funcs_and_context_accessors():
    from emqx_tpu.rules.funcs import FUNCS
    from emqx_tpu.rules.runtime import eval_expr

    assert FUNCS["null"]() is None
    assert FUNCS["find_s"]("a-b-c", "-", "leading") == "-b-c"
    assert FUNCS["find_s"]("a-b-c", "-", "trailing") == "-c"
    assert FUNCS["find_s"]("abc", "x") == ""
    assert FUNCS["sprintf_s"] is FUNCS["sprintf"]
    # jq/2 runs on the in-repo interpreter (utils/jq.py) — no libjq
    # gate anymore; full coverage in tests/test_jq.py
    assert FUNCS["jq"](".", "{}") == [{}]

    cols = {"clientid": "c1", "username": "u1", "payload": b"pp",
            "qos": 1, "topic": "t/x", "peerhost": "1.2.3.4",
            "id": "m-9", "flags": {"retain": True}}
    assert eval_expr(("call", "clientid", []), cols) == "c1"
    assert eval_expr(("call", "msgid", []), cols) == "m-9"
    assert eval_expr(("call", "clientip", []), cols) == "1.2.3.4"
    assert eval_expr(("call", "flag", [("const", "retain")]), cols) is True
    assert eval_expr(("call", "flags", []), cols) == {"retain": True}


def test_context_accessor_via_sql():
    from emqx_tpu.rules.engine import RuleEngine
    from emqx_tpu.core.message import Message

    e = RuleEngine(node="n1")
    got = []
    e.register_action("rec", lambda cols, args: got.append(cols))
    e.create_rule("r", 'SELECT clientid() as who, flag(\'retain\') as r '
                       'FROM "t/#"', [{"function": "rec", "args": {}}])
    m = Message(topic="t/1", payload=b"x", from_="dev-7",
                flags={"retain": True})
    e._on_publish(m)
    assert got and got[0]["who"] == "dev-7"
    assert got[0]["r"] is True


def test_topic_builtin_not_shadowed_by_context_accessor():
    from emqx_tpu.rules.runtime import eval_expr

    cols = {"topic": "real/topic", "clientid": "c"}
    # zero-arg: the column accessor
    assert eval_expr(("call", "topic", []), cols) == "real/topic"
    # with args: the join builtin
    assert eval_expr(("call", "topic",
                      [("const", "a"), ("const", "b")]), cols) == "a/b"
