"""Faultline (ISSUE 11 tentpole): deterministic fault injection at the
native plane's syscall seams (native/src/fault.h).

Covered here, site by site (the nativecheck ``fault`` rule requires
every declared site to be named by at least one test):

- replay determinism: same seed => the bit-identical firing sequence
  (the acceptance-criteria pin), different seed => a different one;
- conn_read / conn_write / conn_accept: errno (ECONNRESET), short
  writes (the partial-write backlog machinery makes real progress),
  and blackhole (bytes vanish, the socket stays up);
- trunk_connect / trunk_accept: injected dial/accept failures drive
  the real DOWN -> redial machinery;
- ring_seal / ring_doorbell: forced ring_full degrades through the
  REAL ladder (punt -> Python, nothing lost); a suppressed doorbell
  delays delivery but never loses it;
- housekeep_clock: ConnIdleMs reads a skewed clock;
- store_msync / store_seg_open: EIO/ENOSPC drive the store's real
  degradation machinery (degraded stat, anonymous-segment fallback);
- observability: every fired fault counts faults.<site> and lands in
  the degradation ledger as reason "fault" — chaos through the same
  seams as organic degradation;
- disarmed sites are inert: zero fires under traffic with nothing
  armed.
"""

import asyncio
import socket
import struct
import threading
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp                              # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer    # noqa: E402
from emqx_tpu.mqtt.client import MqttClient                     # noqa: E402


def run(coro):
    asyncio.run(coro)


def _wait(pred, timeout=8.0, step=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return False


def _mqtt_connect(cid: bytes) -> bytes:
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    return bytes([0x10, len(vh)]) + vh


def _mqtt_publish(topic: bytes, payload: bytes, qos=0, pid=0) -> bytes:
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    return bytes([0x30 | (qos << 1), len(body)]) + body


def _raw_conn(host, cid: bytes):
    """Connect a raw socket to a poll-driven-by-the-test host; returns
    (sock, conn_id) once the CONNECT frame surfaced (answered with a
    CONNACK). The TEST thread drives host.poll(), so it IS the poll
    thread for poll-thread-only surfaces like conn_idle_ms."""
    s = socket.create_connection(("127.0.0.1", host.port))
    s.sendall(_mqtt_connect(cid))
    conn_id = None
    framed = False
    deadline = time.time() + 10
    while (conn_id is None or not framed) and time.time() < deadline:
        for kind, conn, _payload in host.poll(50):
            if kind == native.EV_OPEN:
                conn_id = conn
            elif kind == native.EV_FRAME:
                framed = True
                host.send(conn, b"\x20\x02\x00\x00")
    assert conn_id is not None and framed, (conn_id, framed)
    return s, conn_id


# -- API hygiene --------------------------------------------------------------


def test_unknown_site_or_mode_fails_loudly():
    """A typo'd site must never arm nothing (the sanitizer-lint
    discipline, enforced at runtime here and statically by the
    nativecheck fault rule)."""
    host = native.NativeHost(port=0)
    try:
        with pytest.raises(ValueError):
            host.fault_arm("conn_raed")
        with pytest.raises(KeyError):
            host.fault_arm("conn_read", mode="explode")
        # store sites with no attached store refuse instead of no-op
        with pytest.raises(ValueError):
            host.fault_arm("store_msync")
    finally:
        host.destroy()


def test_disarmed_sites_are_inert_under_traffic():
    """Nothing armed => zero fires, zero faults_injected, ledger clean
    — the disarmed branch is a single relaxed atomic load."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        s, conn = _raw_conn(host, b"inert")
        host.enable_fast(conn, 4)
        s.sendall(_mqtt_publish(b"f/x", b"p"))
        for _ in range(10):
            list(host.poll(10))
        assert host.stats()["faults_injected"] == 0
        for site in native.FAULT_SITES:
            assert host.fault_fired(site) == 0, site
        s.close()
    finally:
        host.destroy()


# -- replay determinism (acceptance criterion) --------------------------------


def test_same_seed_same_firing_sequence():
    """Probabilistic arming replays bit-identically: the per-hit
    fire/no-fire sequence over 200 store appends (each append is one
    store_msync hit under fsync=batch) is equal for equal seeds and
    different for a different seed."""

    def sequence(tmpdir, seed):
        st = native.NativeStore(tmpdir, 1 << 20, "batch")
        st.fault_arm("store_msync", "errno", n_or_prob=0.5, seed=seed)
        tok = st.register("det-sid")
        seq, last = [], 0
        for i in range(200):
            st.append(1, 1, [tok], "d/t", b"x%d" % i)
            fired = st.fault_fired("store_msync")
            seq.append(fired - last)
            last = fired
        st.close()
        return seq

    import tempfile
    with tempfile.TemporaryDirectory() as d1, \
            tempfile.TemporaryDirectory() as d2, \
            tempfile.TemporaryDirectory() as d3:
        a = sequence(d1, seed=42)
        b = sequence(d2, seed=42)
        c = sequence(d3, seed=43)
    assert a == b                     # same seed => identical replay
    assert 20 < sum(a) < 180          # p=0.5 actually fires
    assert a != c                     # a different seed diverges


def test_counted_arm_fires_exactly_n_then_disarms(tmp_path):
    """n_or_prob >= 1 fires on exactly the next n hits, then the site
    auto-disarms (deterministic with no PRNG at all). (An anonymous
    store never msyncs — fd < 0 — so this runs on a real dir.)"""
    st = native.NativeStore(str(tmp_path), 1 << 20, "batch")
    try:
        tok = st.register("cnt-sid")
        st.fault_arm("store_msync", "errno", n_or_prob=3)
        for i in range(10):
            st.append(1, 1, [tok], "c/t", b"y%d" % i)
        assert st.fault_fired("store_msync") == 3
    finally:
        st.close()


# -- conn sites ---------------------------------------------------------------


def test_conn_read_errno_drops_conn_and_counts():
    """Injected ECONNRESET on the conn recv seam tears the conn down
    through the REAL sock_error path, counted in faults_injected."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        s, conn = _raw_conn(host, b"crd")
        host.fault_arm("conn_read", "errno", n_or_prob=1, key=conn)
        s.sendall(b"\xc0\x00")   # PINGREQ: any inbound bytes trigger
        closed = []
        deadline = time.time() + 8
        while not closed and time.time() < deadline:
            for kind, cid, payload in host.poll(50):
                if kind == native.EV_CLOSED and cid == conn:
                    closed.append(payload)
        assert closed and closed[0] == b"sock_error", closed
        assert host.fault_fired("conn_read") == 1
        assert host.stats()["faults_injected"] == 1
        s.close()
    finally:
        host.destroy()


def test_conn_write_short_writes_still_deliver_everything():
    """Short writes exercise the partial-write backlog (outbuf/outpos +
    EPOLLOUT re-arm) for real: every delivery arrives intact, just in
    more pieces."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        pub_s, pub = _raw_conn(host, b"swp")
        sub_s, sub = _raw_conn(host, b"sws")
        host.enable_fast(pub, 4)
        host.enable_fast(sub, 4)
        host.sub_add(sub, "sw/+")
        host.permit(pub, "sw/x")
        host.fault_arm("conn_write", "short", key=sub)  # every send
        want = [b"m%04d" % i for i in range(50)]
        for p in want:
            pub_s.sendall(_mqtt_publish(b"sw/x", p))
        sub_s.settimeout(0.2)
        got = b""
        deadline = time.time() + 10
        while time.time() < deadline and got.count(b"sw/x") < len(want):
            list(host.poll(20))
            try:
                got += sub_s.recv(65536)
            except TimeoutError:
                continue
        for p in want:
            assert p in got, p
        # the backlog halves per armed send: a handful of short writes
        # carried the whole burst (deliveries coalesce per poll cycle)
        assert host.fault_fired("conn_write") >= 5
        pub_s.close()
        sub_s.close()
    finally:
        host.destroy()


def test_conn_write_blackhole_bytes_vanish_conn_survives():
    """A blackholed conn write claims success while nothing reaches the
    wire — the conn stays open (no FIN/RST), exactly a partitioned
    subscriber. Healing resumes delivery."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        pub_s, pub = _raw_conn(host, b"bhp")
        sub_s, sub = _raw_conn(host, b"bhs")
        host.enable_fast(pub, 4)
        host.enable_fast(sub, 4)
        host.sub_add(sub, "bh/+")
        host.permit(pub, "bh/x")
        host.fault_arm("conn_write", "blackhole", key=sub)
        pub_s.sendall(_mqtt_publish(b"bh/x", b"void"))
        for _ in range(10):
            list(host.poll(10))
        sub_s.settimeout(0.3)
        with pytest.raises((TimeoutError, socket.timeout)):
            sub_s.recv(4096)
        assert host.fault_fired("conn_write") >= 1
        host.fault_disarm("conn_write")
        pub_s.sendall(_mqtt_publish(b"bh/x", b"healed"))
        got = b""
        deadline = time.time() + 8
        while b"healed" not in got and time.time() < deadline:
            list(host.poll(20))
            try:
                got += sub_s.recv(4096)
            except TimeoutError:
                continue
        assert b"healed" in got
        pub_s.close()
        sub_s.close()
    finally:
        host.destroy()


def test_conn_accept_shed_then_recovers():
    """An injected accept fault sheds exactly the armed count of
    connections (the client sees a close); the next connect lands."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        host.fault_arm("conn_accept", "errno", n_or_prob=1)
        s1 = socket.create_connection(("127.0.0.1", host.port))
        s1.sendall(_mqtt_connect(b"shed1"))
        # the shed conn never surfaces as OPEN; the socket dies
        t0 = time.time()
        opened = []
        while time.time() - t0 < 1.0:
            for kind, conn, _p in host.poll(20):
                if kind == native.EV_OPEN:
                    opened.append(conn)
        assert opened == [], opened
        assert host.fault_fired("conn_accept") == 1
        s2, _conn = _raw_conn(host, b"shed2")   # site auto-disarmed
        s1.close()
        s2.close()
    finally:
        host.destroy()


# -- housekeep clock ----------------------------------------------------------


def test_housekeep_clock_skew_ages_idle_conns():
    """With housekeep_clock armed (skew mode), ConnIdleMs reads a
    future clock: an idle conn ages by the skew instantly — the
    keepalive-teardown machinery's input under test."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        s, conn = _raw_conn(host, b"skw")
        list(host.poll(10))
        base = host.conn_idle_ms(conn)
        assert 0 <= base < 5000, base
        host.fault_arm("housekeep_clock", "skew", n_or_prob=70000)
        aged = host.conn_idle_ms(conn)
        assert aged >= 70000, aged
        assert host.fault_fired("housekeep_clock") >= 1
        host.fault_disarm("housekeep_clock")
        assert host.conn_idle_ms(conn) < 5000
        s.close()
    finally:
        host.destroy()


# -- conn-scale plane seams (round 16) ----------------------------------------


def test_conn_accept_fault_during_park_storm_ledger_visible():
    """park-during-storm: with a hibernating herd resident, an armed
    conn_accept fault sheds exactly the counted storm connects while
    the PARKED conns stay untouched — and every fire is ledger-visible
    (kind-12 reason "fault") next to the faults.conn_accept counter."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        host.set_park(True, park_after_ms=100)
        host.synth_conns(500, keepalive_ms=600_000)
        deadline = time.time() + 10
        while time.time() < deadline:
            list(host.poll(20))
            if host.conn_counts()["parked"] >= 500:
                break
        assert host.conn_counts()["parked"] >= 500
        host.fault_arm("conn_accept", "errno", n_or_prob=3)
        storm = [socket.create_connection(("127.0.0.1", host.port))
                 for _ in range(6)]
        opened, ledger = [], []
        t0 = time.time()
        while time.time() - t0 < 5 and (
                len(opened) < 3 or host.fault_fired("conn_accept") < 3
                or not ledger):
            for kind, conn, payload in host.poll(20):
                if kind == native.EV_OPEN:
                    opened.append(conn)
                elif kind == native.EV_SPANS:
                    ledger += [r for r in native.parse_spans(payload)
                               if r[0] == "ledger"]
        assert host.fault_fired("conn_accept") == 3
        assert len(opened) == 3, opened      # the other 3 were shed
        fault_reason = native.LEDGER_REASONS.index("fault") + 1
        assert any(r[1] == fault_reason for r in ledger), ledger
        # the hibernating herd rode out the storm untouched
        assert host.conn_counts()["parked"] >= 500
        for sk in storm:
            sk.close()
    finally:
        host.destroy()


def test_clock_skew_reaps_parked_conns_wake_still_inflates():
    """wake-during-skew: housekeep_clock skew feeds the WHEEL's
    keepalive fires too — a hibernating conn is judged against the
    future clock and reaped from its parked record (no inflation on
    the way to the grave), while a first byte arriving under skew
    still re-inflates normally; every fire ledger-visible."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        host.set_park(True, park_after_ms=120)
        s1, c1 = _raw_conn(host, b"skp1")
        s2, c2 = _raw_conn(host, b"skp2")
        host.set_keepalive(c1, 900)
        host.set_keepalive(c2, 900)
        deadline = time.time() + 5
        while time.time() < deadline:
            list(host.poll(20))
            if host.conn_counts()["parked"] == 2:
                break
        assert host.conn_counts()["parked"] == 2
        host.fault_arm("housekeep_clock", "skew", n_or_prob=70_000)
        # the wake: a first byte under skew re-inflates c2 before the
        # skewed keepalive reaps it
        s2.sendall(b"\xc0\x00\xc0")   # pings + a torn byte => inflate
        closed, ledger = {}, []
        t0 = time.time()
        while time.time() - t0 < 6 and len(closed) < 2:
            for kind, conn, payload in host.poll(20):
                if kind == native.EV_CLOSED:
                    closed[conn] = payload
                elif kind == native.EV_SPANS:
                    ledger += [r for r in native.parse_spans(payload)
                               if r[0] == "ledger"]
        assert closed.get(c1) == b"keepalive_timeout", closed
        assert closed.get(c2) == b"keepalive_timeout", closed
        assert host.stats()["conns_inflated"] >= 1   # the wake worked
        assert host.fault_fired("housekeep_clock") >= 1
        fault_reason = native.LEDGER_REASONS.index("fault") + 1
        assert any(r[1] == fault_reason for r in ledger), ledger
        s1.close()
        s2.close()
    finally:
        host.destroy()


# -- trunk link sites ---------------------------------------------------------


def test_trunk_connect_and_trunk_accept_faults_drive_down_up():
    """Injected dial/accept failures surface as kind-9 DOWN events and
    the link still comes up once the sites disarm — the redial
    machinery under injected (not just organic) failure."""
    A = native.NativeHost(port=0, max_size=1 << 16)
    B = native.NativeHost(port=0, max_size=1 << 16)
    try:
        tp = B.trunk_listen()

        events = {"up": 0, "down": []}

        def pump(timeout=0.05):
            for h in (A, B):
                for kind, _cid, payload in h.poll(int(timeout * 1000)):
                    if kind == native.EV_TRUNK and payload and h is A:
                        if payload[0] == native.TRUNK_UP:
                            events["up"] += 1
                        elif payload[0] == native.TRUNK_DOWN:
                            events["down"].append(payload[1:])

        # dial fault: DOWN with the injected reason, no socket made
        A.fault_arm("trunk_connect", "errno", n_or_prob=1, key=7)
        A.trunk_connect(7, "127.0.0.1", tp)
        deadline = time.time() + 5
        while not events["down"] and time.time() < deadline:
            pump()
        assert events["down"] and events["down"][0] == b"fault_connect"
        assert A.fault_fired("trunk_connect") == 1

        # accept fault on B: A's dial lands on an RST; A reports DOWN
        B.fault_arm("trunk_accept", "errno", n_or_prob=1)
        A.trunk_connect(7, "127.0.0.1", tp)
        deadline = time.time() + 5
        while len(events["down"]) < 2 and time.time() < deadline:
            pump()
        assert B.fault_fired("trunk_accept") == 1

        # healed: the next dial completes UP
        A.trunk_connect(7, "127.0.0.1", tp)
        deadline = time.time() + 8
        while events["up"] == 0 and time.time() < deadline:
            pump()
        assert events["up"] >= 1, events
    finally:
        A.destroy()
        B.destroy()


# -- store sites --------------------------------------------------------------


def test_store_seg_open_enospc_degrades_to_anonymous():
    """Injected ENOSPC on the segment-open seam drives the REAL
    disk-full machinery: the store degrades to an anonymous segment,
    counts it, and keeps serving (PUBACKs keep flowing; restart
    survival is what is lost)."""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        st = native.NativeStore(d, 1 << 16, "never")
        try:
            tok = st.register("eno-sid")
            st.fault_arm("store_seg_open", "errno", n_or_prob=1)
            # roll past the tiny segment so Roll() runs the armed site
            big = b"z" * 8192
            for i in range(20):
                st.append(1, 1, [tok], "e/t", big)
            assert st.fault_fired("store_seg_open") == 1
            assert st.stats()["degraded"] >= 1
            assert st.pending(tok) == 20   # the plane kept running
        finally:
            st.close()


def test_store_msync_eio_counts_degraded_and_heals():
    """Injected EIO on the fsync seam: each failed sync counts degraded
    (the PUBACK-after-fsync contract is void for that stretch); a
    clean sync afterwards keeps the store serving."""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        st = native.NativeStore(d, 1 << 20, "batch")
        try:
            tok = st.register("eio-sid")
            st.append(1, 1, [tok], "m/t", b"pre")
            assert st.stats()["degraded"] == 0
            st.fault_arm("store_msync", "errno", n_or_prob=2)
            st.append(1, 1, [tok], "m/t", b"d1")
            st.append(1, 1, [tok], "m/t", b"d2")
            assert st.fault_fired("store_msync") == 2
            assert st.stats()["degraded"] == 2
            st.append(1, 1, [tok], "m/t", b"post")   # auto-disarmed
            assert st.stats()["degraded"] == 2
            assert st.pending(tok) == 4
        finally:
            st.close()


# -- ring sites (sharded) -----------------------------------------------------


def _group_pair():
    """Two raw hosts in one shard group (the test_native_shards raw
    pattern): the TEST drives both polls, so placement is explicit."""
    group = native.NativeShardGroup(2)
    h0 = native.NativeHost(port=0, max_size=1 << 16)
    h1 = native.NativeHost(port=0, max_size=1 << 16)
    h0.join_group(group, 0)
    h1.join_group(group, 1)
    return group, h0, h1


def test_ring_seal_forced_full_degrades_to_punt():
    """An armed ring_seal site makes the admission check report no
    room: the publish degrades ring-full -> punt -> Python BEFORE any
    side effect (the frame surfaces to Python verbatim), and both the
    organic shard_ring_full stat and the faults counter tick."""
    group, h0, h1 = _group_pair()
    try:
        pub_s, pub = _raw_conn(h0, b"rsp")
        sub_s, sub = _raw_conn(h1, b"rss")
        h0.enable_fast(pub, 4)
        h1.enable_fast(sub, 4)
        for h in (h0, h1):                 # replicated table
            h.sub_add(sub, "rs/+")
        h0.permit(pub, "rs/x")
        h0.fault_arm("ring_seal", "full", n_or_prob=1, key=2)  # dst 1
        pub_s.sendall(_mqtt_publish(b"rs/x", b"punted"))
        punted = []
        deadline = time.time() + 8
        while not punted and time.time() < deadline:
            for kind, cid, payload in h0.poll(20):
                if kind == native.EV_FRAME and cid == pub:
                    punted.append(payload)
            list(h1.poll(0))
        assert punted and b"punted" in punted[0]
        st = h0.stats()
        assert st["shard_ring_full"] >= 1
        assert st["faults_injected"] >= 1
        assert h0.fault_fired("ring_seal") == 1
        # healed (count exhausted): the next publish crosses natively
        pub_s.sendall(_mqtt_publish(b"rs/x", b"across"))
        sub_s.settimeout(0.2)
        got = b""
        deadline = time.time() + 8
        while b"across" not in got and time.time() < deadline:
            list(h0.poll(20))
            list(h1.poll(20))
            try:
                got += sub_s.recv(4096)
            except TimeoutError:
                continue
        assert b"across" in got
        pub_s.close()
        sub_s.close()
    finally:
        h0.destroy()
        h1.destroy()
        group.destroy()


def test_ring_doorbell_suppressed_delivery_late_never_lost():
    """A suppressed doorbell delays the consumer shard to its next
    natural poll timeout — delivery still happens (late, never lost)
    and the suppression is counted."""
    group, h0, h1 = _group_pair()
    try:
        pub_s, pub = _raw_conn(h0, b"dbp")
        sub_s, sub = _raw_conn(h1, b"dbs")
        h0.enable_fast(pub, 4)
        h1.enable_fast(sub, 4)
        for h in (h0, h1):
            h.sub_add(sub, "db/+")
        h0.permit(pub, "db/x")
        h0.fault_arm("ring_doorbell", "blackhole")   # every wakeup
        pub_s.sendall(_mqtt_publish(b"db/x", b"late"))
        sub_s.settimeout(0.2)
        got = b""
        deadline = time.time() + 10
        while b"late" not in got and time.time() < deadline:
            list(h0.poll(20))
            list(h1.poll(20))   # natural poll drains the ring anyway
            try:
                got += sub_s.recv(4096)
            except TimeoutError:
                continue
        assert b"late" in got
        assert h0.fault_fired("ring_doorbell") >= 1
        pub_s.close()
        sub_s.close()
    finally:
        h0.destroy()
        h1.destroy()
        group.destroy()


# -- observability through the product seams ----------------------------------


def test_fired_faults_land_in_ledger_and_faults_metrics():
    """Server-level: a fired host-plane fault surfaces as (a) the
    faults.<site> fixed metric slot, (b) a degradation-ledger event
    with reason "fault" and aux = the site index, (c) the
    faults_injected host stat — the same observability seams organic
    degradation uses."""
    app = BrokerApp()
    srv = NativeBrokerServer(port=0, app=app)
    srv.start()
    try:
        async def main():
            c = MqttClient(port=srv.port, clientid="lf")
            await c.connect()
            assert _wait(lambda: "lf" in srv._fast_conn_of)
            conn_id = srv._fast_conn_of["lf"]
            srv.fault_arm("conn_read", "errno", n_or_prob=1,
                          key=conn_id)
            try:
                await c.publish("lf/x", b"boom")   # inbound bytes fire
            except (ConnectionError, OSError):
                pass
            assert _wait(lambda: srv.fault_fired("conn_read") >= 1), (
                srv.fast_stats())
            try:
                await c.close()
            except (ConnectionError, OSError):
                pass

        run(main())
        srv._merge_fast_metrics()
        m = srv.broker.metrics
        assert m.val("faults.conn_read") >= 1
        assert srv.fast_stats()["faults_injected"] >= 1
        # the C++ kind-12 ledger fold carries reason "fault"
        assert _wait(lambda: srv.ledger.totals().get("fault", 0) >= 1), (
            srv.ledger.totals())
        idx = native.FAULT_SITES.index("conn_read")
        assert any(e["reason"] == "fault" and e["aux"] == idx
                   for e in srv.ledger.recent()), srv.ledger.recent()
        assert m.val("messages.ledger.fault") >= 1
    finally:
        srv.stop()


def test_store_faults_fold_into_ledger_via_housekeep(tmp_path):
    """Store-site fires happen under the store mutex on arbitrary
    threads: their ledger entries fold in _merge_fast_metrics (detail
    = the site name), next to the faults.store_* metric slots. (A real
    durable_dir: an anonymous store never msyncs.)"""
    from emqx_tpu.session.persistent import MemStore

    app = BrokerApp(persistent_store=MemStore())
    srv = NativeBrokerServer(port=0, app=app,
                             durable_dir=str(tmp_path),
                             durable_fsync="batch")
    if srv._durable_store is None:
        srv.stop()
        pytest.skip("durable store unavailable")
    srv.start()
    try:
        srv.fault_arm("store_msync", "errno", n_or_prob=1)
        # one direct append drives the armed msync under fsync=batch
        tok = srv._durable_store.register("lf-sid")
        srv._durable_store.append(1, 1, [tok], "lf/t", b"x")
        assert srv.fault_fired("store_msync") == 1
        srv._merge_fast_metrics()
        m = srv.broker.metrics
        assert m.val("faults.store_msync") == 1
        assert any(e["reason"] == "fault" and e["detail"] == "store_msync"
                   for e in srv.ledger.recent()), srv.ledger.recent()
    finally:
        srv.stop()


# -- one-recovery-path seams (round 18) ---------------------------------------

def test_store_eio_during_trunk_ring_append_ledger_visible(tmp_path):
    """Satellite (round 18): store_msync EIO armed while the TRUNK
    RING journals (FlushTrunkPeer → TrunkPut → policy fsync) drives
    the real degradation ladder — the store flips degraded (sticky),
    the fire counts in faults.store_msync, and the ledger carries the
    fault. The ring itself keeps working: qos1 forwarding is
    at-least-once via replay, never blocked on a dying disk."""
    from emqx_tpu.session.persistent import NativeDurableStore

    base = str(tmp_path / "nodeA")
    app = BrokerApp(persistent_store=NativeDurableStore(base))
    app.broker.node = "ftA"
    srv = NativeBrokerServer(port=0, app=app, trunk_port=0)
    srv.start()

    # a never-acking sink: the ring provably holds (and journals) the
    # batches while the fault is armed
    sink = socket.socket()
    sink.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sink.bind(("127.0.0.1", 0))
    sink.listen(1)

    def sink_loop():
        try:
            c, _ = sink.accept()
            c.settimeout(0.2)
            while True:
                try:
                    if not c.recv(65536):
                        return
                except socket.timeout:
                    continue
                except OSError:
                    return
        except OSError:
            return
    threading.Thread(target=sink_loop, daemon=True).start()

    try:
        async def main():
            pub = MqttClient(port=srv.port, clientid="ft-pub")
            await pub.connect()
            app.broker.router.add_route("ft/x", "ftB")
            srv.trunk_register("ftB", "127.0.0.1",
                               sink.getsockname()[1])
            assert _wait(lambda: srv.trunk_peer_status().get("ftB"))
            await pub.publish("ft/x", b"warm", qos=1)
            await asyncio.sleep(0.6)
            srv.fault_arm("store_msync", "errno", n_or_prob=2)
            for i in range(4):
                await pub.publish("ft/x", b"f%d" % i, qos=1)
            assert _wait(
                lambda: srv.fault_fired("store_msync") >= 2), (
                srv.fault_fired("store_msync"))
            await pub.close()

        run(main())
        # the ring journaled through the erroring disk (counted)...
        st = srv.fast_stats()
        assert st["trunk_ring_persisted"] >= 1, st
        # ...the store flipped degraded (sticky)...
        assert srv._durable_store.stats()["degraded"] >= 1
        # ...and the chaos is ledger-visible + counted
        srv._merge_fast_metrics()
        assert srv.broker.metrics.val("faults.store_msync") >= 2
        assert any(e["reason"] == "fault"
                   and e["detail"] == "store_msync"
                   for e in srv.ledger.recent()), srv.ledger.recent()
    finally:
        srv.stop()
        app.persistent.store.close()
        try:
            sink.close()
        except OSError:
            pass


def test_store_enospc_during_delivery_retention_append(tmp_path):
    """Satellite (round 18): store_seg_open ENOSPC armed while the
    durable plane appends retained-delivery bytes (the consume-on-ack
    records a resume replay draws from) degrades to anonymous segments
    — PUBACKs keep flowing, restart survival is loudly gone (degraded
    counted, ledger store_degraded via housekeep)."""
    from emqx_tpu.session.persistent import NativeDurableStore

    base = str(tmp_path / "nodeB")
    app = BrokerApp(persistent_store=NativeDurableStore(
        base, segment_bytes=64 * 1024))
    srv = NativeBrokerServer(port=0, app=app)
    srv.start()
    try:
        async def main():
            ps = MqttClient(port=srv.port, clientid="en-ps",
                            clean_start=False, proto_ver=5,
                            properties={"Session-Expiry-Interval": 600})
            await ps.connect()
            await ps.subscribe("en/t", qos=1)
            await ps.close()                 # offline: appends retained
            await asyncio.sleep(0.3)
            pub = MqttClient(port=srv.port, clientid="en-pub")
            await pub.connect()
            srv.fault_arm("store_seg_open", "errno", n_or_prob=1)
            # enough payload to force a segment Roll through the
            # armed open → ENOSPC → anonymous-segment fallback
            blob = b"x" * 24_000
            for i in range(6):
                await pub.publish("en/t", blob + b"%d" % i, qos=1)
            assert _wait(
                lambda: srv.fault_fired("store_seg_open") >= 1), (
                srv.fault_fired("store_seg_open"))
            await pub.close()

        run(main())
        assert srv._durable_store.stats()["degraded"] >= 1
        srv._merge_fast_metrics()
        assert srv.broker.metrics.val("faults.store_seg_open") >= 1
        led = srv.ledger.totals()
        assert led.get("store_degraded", 0) >= 1 or any(
            e["reason"] == "fault" and e["detail"] == "store_seg_open"
            for e in srv.ledger.recent()), (led, srv.ledger.recent())
    finally:
        srv.stop()
        app.persistent.store.close()
