"""Real-socket integration tests: BrokerServer + MqttClient over TCP —
the emqx_client_SUITE analogue (broker driven by a real client)."""

import asyncio

import pytest

from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.client import MqttClient


@pytest.fixture
def run():
    """Run an async scenario against a fresh broker on an ephemeral port."""
    def _run(scenario):
        async def main():
            server = BrokerServer(port=0)
            await server.start()
            try:
                await scenario(server)
            finally:
                await server.stop()
        asyncio.run(main())
    return _run


def test_connect_sub_pub_over_tcp(run):
    async def scenario(server):
        sub = MqttClient(port=server.port, clientid="sub")
        pub = MqttClient(port=server.port, clientid="pub")
        assert (await sub.connect()).reason_code == 0
        await pub.connect()
        suback = await sub.subscribe("room/+/temp", qos=1)
        assert suback.reason_codes == [1]
        await pub.publish("room/12/temp", b"21.5", qos=1)
        got = await sub.recv()
        assert got.topic == "room/12/temp" and got.payload == b"21.5"
        assert got.qos == 1
        await sub.disconnect()
        await pub.disconnect()
    run(scenario)


def test_qos2_over_tcp(run):
    async def scenario(server):
        sub = MqttClient(port=server.port, clientid="s2")
        pub = MqttClient(port=server.port, clientid="p2")
        await sub.connect()
        await pub.connect()
        await sub.subscribe("exact/once", qos=2)
        await pub.publish("exact/once", b"x", qos=2)
        got = await sub.recv()
        assert got.qos == 2 and got.payload == b"x"
        await sub.disconnect()
        await pub.disconnect()
    run(scenario)


def test_retained_flag_passthrough_and_wildcards(run):
    async def scenario(server):
        c = MqttClient(port=server.port, clientid="c", proto_ver=P.MQTT_V5)
        await c.connect()
        await c.subscribe("#", qos=0)
        p = MqttClient(port=server.port, clientid="p")
        await p.connect()
        await p.publish("deep/a/b/c", b"1")
        got = await c.recv()
        assert got.topic == "deep/a/b/c"
        await c.disconnect()
        await p.disconnect()
    run(scenario)


def test_takeover_over_tcp(run):
    async def scenario(server):
        c1 = MqttClient(port=server.port, clientid="dev",
                        proto_ver=P.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 600})
        await c1.connect()
        await c1.subscribe("t", qos=1)
        c2 = MqttClient(port=server.port, clientid="dev",
                        proto_ver=P.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 600})
        ack = await c2.connect()
        assert ack.session_present
        # old socket gets closed by the server side eventually; new one works
        p = MqttClient(port=server.port, clientid="p")
        await p.connect()
        await p.publish("t", b"after", qos=1)
        got = await c2.recv()
        assert got.payload == b"after"
        await c2.disconnect()
        await p.disconnect()
        await c1.close()
    run(scenario)


def test_will_message_over_tcp(run):
    async def scenario(server):
        w = MqttClient(port=server.port, clientid="watcher")
        await w.connect()
        await w.subscribe("will/+", qos=0)
        dying = MqttClient(port=server.port, clientid="dying")
        await dying.connect(will_topic="will/dying", will_payload=b"RIP")
        # abrupt socket close (no DISCONNECT) → will fires
        await dying.close()
        got = await w.recv()
        assert got.topic == "will/dying" and got.payload == b"RIP"
        await w.disconnect()
    run(scenario)


def test_malformed_bytes_close_connection(run):
    async def scenario(server):
        reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
        writer.write(bytes([0x00, 0x01, 0x00]))   # reserved type 0
        await writer.drain()
        data = await asyncio.wait_for(reader.read(64), 5)
        assert data == b""                         # closed on us
        writer.close()
    run(scenario)


def test_1k_fanout_over_tcp(run):
    """BASELINE config 1 shape: 1K subscribers, 1 publisher, one message."""
    async def scenario(server):
        n = 200   # keep CI fast; the shape is what matters
        subs = []
        for i in range(n):
            c = MqttClient(port=server.port, clientid=f"s{i}")
            await c.connect()
            await c.subscribe("fan/out", qos=0)
            subs.append(c)
        p = MqttClient(port=server.port, clientid="p")
        await p.connect()
        await p.publish("fan/out", b"boom")
        for c in subs:
            got = await c.recv()
            assert got.payload == b"boom"
        await p.disconnect()
        for c in subs:
            await c.disconnect()
    run(scenario)


def test_retained_and_shared_over_tcp(run):
    async def scenario(server):
        p = MqttClient(port=server.port, clientid="p")
        await p.connect()
        await p.publish("cfg/one", b"v1", retain=True)
        # late subscriber still gets the retained value
        late = MqttClient(port=server.port, clientid="late")
        await late.connect()
        await late.subscribe("cfg/+", qos=0)
        got = await late.recv()
        assert got.topic == "cfg/one" and got.payload == b"v1" and got.retain
        # shared group: exactly one member receives each publish
        w1 = MqttClient(port=server.port, clientid="w1")
        w2 = MqttClient(port=server.port, clientid="w2")
        await w1.connect(); await w2.connect()
        await w1.subscribe("$share/g/jobs", qos=0)
        await w2.subscribe("$share/g/jobs", qos=0)
        for i in range(4):
            await p.publish("jobs", b"%d" % i)
        await asyncio.sleep(0.2)
        total = w1.messages.qsize() + w2.messages.qsize()
        assert total == 4
        assert w1.messages.qsize() in (1, 2, 3)
        for c in (p, late, w1, w2):
            await c.disconnect()
    run(scenario)


def test_outbound_maximum_packet_size_enforced():
    """MQTT5 3.1.2-25: a PUBLISH exceeding the client's announced
    Maximum-Packet-Size is dropped for that client (and counted), while
    small packets and other clients flow normally."""
    import asyncio

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    async def main():
        app = BrokerApp()
        server = BrokerServer(port=0, app=app)
        await server.start()
        tiny = MqttClient(port=server.port, clientid="tiny", proto_ver=5,
                          properties={"Maximum-Packet-Size": 64})
        await tiny.connect()
        await tiny.subscribe("mps/t", qos=0)
        big = MqttClient(port=server.port, clientid="bigc", proto_ver=5)
        await big.connect()
        await big.subscribe("mps/t", qos=0)
        pub = MqttClient(port=server.port, clientid="p", proto_ver=5)
        await pub.connect()
        await pub.publish("mps/t", b"x" * 500, qos=0)   # > 64 bytes framed
        await pub.publish("mps/t", b"ok", qos=0)
        # big client gets both; tiny client only the small one
        m1 = await asyncio.wait_for(big.messages.get(), 5)
        m2 = await asyncio.wait_for(big.messages.get(), 5)
        assert {m1.payload, m2.payload} == {b"x" * 500, b"ok"}
        mt = await asyncio.wait_for(tiny.messages.get(), 5)
        assert mt.payload == b"ok"
        assert tiny.messages.empty()
        assert app.metrics.val("delivery.dropped.too_large") == 1
        await tiny.disconnect(); await big.disconnect(); await pub.disconnect()
        await server.stop()
    asyncio.run(main())


def test_size_dropped_qos1_releases_inflight_window():
    """MQTT5 3.1.2-25 follow-through: an oversized QoS1 publish releases
    its window slot, so later (small) messages still flow."""
    import asyncio

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    async def main():
        app = BrokerApp()
        server = BrokerServer(port=0, app=app)
        await server.start()
        tiny = MqttClient(port=server.port, clientid="tq", proto_ver=5,
                          properties={"Maximum-Packet-Size": 64,
                                      "Receive-Maximum": 2})
        await tiny.connect()
        await tiny.subscribe("mq/t", qos=1)
        pub = MqttClient(port=server.port, clientid="pq", proto_ver=5)
        await pub.connect()
        # fill the 2-slot window with oversized messages, then small ones
        for _ in range(3):
            await pub.publish("mq/t", b"z" * 300, qos=1)
        for i in range(3):
            await pub.publish("mq/t", f"s{i}".encode(), qos=1)
        got = []
        for _ in range(3):
            m = await asyncio.wait_for(tiny.messages.get(), 5)
            got.append(m.payload)
        assert got == [b"s0", b"s1", b"s2"], got
        ch = app.cm.lookup_channel("tq")
        assert len(ch.session.inflight) <= 2
        await tiny.disconnect(); await pub.disconnect(); await server.stop()
    asyncio.run(main())
