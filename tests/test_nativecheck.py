"""nativecheck: the compiler-free concurrency & contract analyzer for
the C++ native plane (ISSUE 10 tentpole + the ISSUE 13 v2 rules,
tools/nativecheck).

Nine checked rules over ~12k LoC of hand-rolled C++ + the Python fold
layer, in the spirit of Clang's annotate-then-propagate thread-safety
analysis, Eraser-style lockset checking, and RacerD's compositional
source-level discipline, built on the repo's proven
parse-the-source-directly lint pattern:

1. plane     — nothing reachable from a @plane(poll) root may be
               @blocking or @plane(control) (the msync-on-the-poll-
               thread class);
2. lockset   — @guards(mu_) fields are only touched inside the
               mutex's lexical scope or in @locked functions;
3. ladder    — @admit-gated side effects lexically FOLLOW an
               @admit-check (decided-before-side-effects, PRs 4/7);
4. pyfold    — _on_* kind-folds (round 17: plus the TRANSITIVE
               closure of their self.X() callees) touch
               @guards-annotated server state only under its lock;
5. fault     — faultline coverage (every fire site annotated, every
               site tested, Python parity);
6. atomics   — every std::atomic field declares @atomic(<disc>: why)
               and every load/store/RMW passes an explicit
               memory_order within it; @published SPSC data precedes
               its index publish; the wheel/park generation-handle
               protocol (@gen-check/-bump/-checked/-handle);
7. lock-order— the global lock-acquisition graph (both languages,
               call-graph propagated) matches the declared LOCK_ORDER
               edges; undeclared nesting, stale edges, cycles, and
               Lock self-acquisition fail;
8. tap-bound — appends into @bounded poll-cycle event buffers happen
               only in @bounded(<buf>) writers behind a chunk-or-flush
               margin check;
9. waivers   — waiver hygiene: every waiver is well-formed and
               matches a live finding (stale waivers fail).

Covered here:
- the real tree is CLEAN (zero unwaived findings, zero stale waivers)
  and the CLI enforces that in tier-1 (< 15s, pure stdlib), with a
  stable --json schema for CI/editor consumers;
- the mutation self-test: one seeded known-bad edit per rule, each
  rule fires on exactly the seeded site;
- every annotation in the sources is LOAD-BEARING: stripping it flips
  a rule result (on the real tree or on a per-annotation probe);
- regression pins for the real violations this analyzer surfaced
  (store.h ok() data race, the tap_dropped fold race);
- the round-17 call-graph upgrade: same-named methods resolve by
  enclosing-class scope when the call is unqualified;
- the sanitizer-coverage lint (satellite): every DRIVER_* in
  test_native_sanitizers.py is registered and parametrized, and every
  native/src/*.h subsystem is exercised by at least one ASan+TSan
  driver (future gateway headers waived by name).
"""

import os
import queue
import re
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tools.nativecheck import rules                       # noqa: E402
from tools.nativecheck.pymodel import PySource            # noqa: E402
from tools.nativecheck.waivers import WAIVERS             # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SRC = os.path.join(REPO, "emqx_tpu", "native", "src")
SERVER_PY = os.path.join(REPO, "emqx_tpu", "broker", "native_server.py")


def _read(path: str) -> str:
    with open(path) as f:
        return f.read()


def _host() -> str:
    return _read(os.path.join(SRC, "host.cc"))


def _insert_in_body(text: str, fname: str, func: str, stmt: str) -> str:
    """Insert ``stmt`` right after ``func``'s opening brace WITHOUT a
    newline, so line numbers (and later annotation lines) are
    preserved."""
    model = rules.build_cpp_model(REPO, overrides={fname: text})
    fns = [f for f in model.sources[fname].functions if f.name == func]
    assert fns, f"{func} not found in {fname}"
    at = fns[0].body_start + 1
    return text[:at] + " " + stmt + " " + text[at:]


# -- the tree is clean + the CLI enforces it ----------------------------------


def test_tree_is_clean_and_waivers_are_live():
    res = rules.run(REPO)
    assert res.unwaived == [], [f.message for f in res.unwaived]
    assert res.stale_waivers == []
    # the deliberately-waived contracts stay visible (not suppressed):
    # the fsync/segment-roll plane findings + the two already-admitted
    # ladder receivers
    waived = sorted(f.site for f in res.findings if f.waived_by)
    assert waived == ["host.cc:ApplyShardBatch->TrunkEnqueue",
                      "host.cc:TrunkFanOut->FanOut",
                      "store.h:Roll", "store.h:SyncSeg"], waived


def test_cli_exits_zero_fast_pure_stdlib():
    """`python -m tools.nativecheck` is the tier-1 entry point: green
    tree -> exit 0, well under the 15s budget, no compiler, stdlib
    only."""
    t0 = time.monotonic()
    p = subprocess.run([sys.executable, "-m", "tools.nativecheck", REPO],
                      capture_output=True, text=True, cwd=REPO,
                      timeout=60)
    dt = time.monotonic() - t0
    assert p.returncode == 0, p.stdout + p.stderr
    assert "0 unwaived finding(s)" in p.stdout, p.stdout
    assert "0 stale waiver(s)" in p.stdout, p.stdout
    assert dt < 15.0, dt


def test_cli_exits_nonzero_on_unwaived_finding(tmp_path):
    """The enforcement half: a tree with a violation fails the CLI.
    Exercised against a scratch copy of the repo layout with one
    seeded lockset violation."""
    import shutil
    scratch = tmp_path / "repo"
    (scratch / "emqx_tpu" / "native" / "src").mkdir(parents=True)
    (scratch / "emqx_tpu" / "broker").mkdir(parents=True)
    (scratch / "tests").mkdir(parents=True)
    for f in rules.CPP_FILES:
        shutil.copy(os.path.join(SRC, f),
                    scratch / "emqx_tpu" / "native" / "src" / f)
    shutil.copy(SERVER_PY, scratch / "emqx_tpu" / "broker"
                / "native_server.py")
    # the fault rule reads FAULT_SITES parity + tests/ coverage too
    shutil.copy(os.path.join(REPO, "emqx_tpu", "native", "__init__.py"),
                scratch / "emqx_tpu" / "native" / "__init__.py")
    for tf in ("test_native_fault.py", "test_native_trunk.py"):
        shutil.copy(os.path.join(REPO, "tests", tf),
                    scratch / "tests" / tf)
    bad = scratch / "emqx_tpu" / "native" / "src" / "store.h"
    bad.write_text(bad.read_text()
                   + "\nvoid NcMutant__(long* o) { (void)o; }\n")
    # first confirm the copy is green, then seed the violation
    p = subprocess.run(
        [sys.executable, "-m", "tools.nativecheck", str(scratch)],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert p.returncode == 0, p.stdout
    bad.write_text(bad.read_text().replace(
        "void NcMutant__(long* o) { (void)o; }",
        "long NcMutant__() { return (long)msgs_.size(); }"))
    p = subprocess.run(
        [sys.executable, "-m", "tools.nativecheck", str(scratch)],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert p.returncode == 1, p.stdout
    assert "NcMutant__" in p.stdout, p.stdout


# -- mutation self-test: one seeded known-bad edit per rule -------------------


def test_mutation_plane_rule_fires():
    """Seed a control-plane call (a listener open) into a poll-plane
    function: rule 1 must flag it through the call-graph propagation."""
    mut = _insert_in_body(_host(), "host.cc", "HandleEvent",
                          "ListenTrunk(0, 0);")
    res = rules.run(REPO, overrides={"host.cc": mut})
    assert "plane:host.cc:ListenTrunk" in {f.key for f in res.unwaived}, (
        [f.key for f in res.unwaived])


def test_mutation_lockset_rule_fires():
    """Seed an unguarded access to a @guards(mu_) field: rule 2 must
    flag the function that touches it outside the mutex's scope."""
    mut = (_read(os.path.join(SRC, "store.h"))
           + "\nlong NcMutant__(void* s) { return 0; }\n")
    res = rules.run(REPO, overrides={"store.h": mut})
    assert res.unwaived == []   # a guarded-field-free function is fine
    mut = (_read(os.path.join(SRC, "store.h"))
           + "\nlong NcMutant__() { return (long)pending_.size(); }\n")
    res = rules.run(REPO, overrides={"store.h": mut})
    assert "lockset:store.h:NcMutant__:pending_" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_mutation_ladder_rule_fires():
    """Seed an @admit-gated side effect BEFORE TryFast's ShardAdmit:
    rule 3 must flag the call site with no preceding admit check."""
    mut = _insert_in_body(_host(), "host.cc", "TryFast", "EmitTap(0);")
    res = rules.run(REPO, overrides={"host.cc": mut})
    assert "ladder:host.cc:TryFast->EmitTap" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_mutation_pyfold_rule_fires():
    """Seed an _on_* fold that touches guarded server state without
    its lock: rule 4 must flag it."""
    text = _read(SERVER_PY)
    marker = "    def _on_tap(self"
    assert marker in text
    mut = text.replace(
        marker,
        "    def _on_nc_mutant__(self, payload):\n"
        "        self.ack_plane[\"acked\"] += 1\n\n" + marker, 1)
    res = rules.run(REPO, overrides={"native_server.py": mut})
    assert "pyfold:native_server.py:_on_nc_mutant__:ack_plane" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_mutation_fault_rule_fires():
    """Seed an UNANNOTATED fault fire site: the fault rule must flag
    the line (every kSite use with firing vocabulary needs a matching
    // @fault(<site>) nearby — the faultline coverage contract)."""
    mut = _insert_in_body(_host(), "host.cc", "HandleEvent",
                          "FaultHit(fault::kSiteConnRead, 0);")
    res = rules.run(REPO, overrides={"host.cc": mut})
    bad = [f for f in res.unwaived
           if f.rule == "fault" and f.site.endswith(":conn_read")]
    assert bad, [f.key for f in res.unwaived]
    # ...and an annotation naming a NONEXISTENT site fires too
    mut2 = _host() + "\n// @fault(conn_raed)\n"
    res2 = rules.run(REPO, overrides={"host.cc": mut2})
    assert any(f.rule == "fault" and "conn_raed" in f.site
               for f in res2.unwaived), [f.key for f in res2.unwaived]


def test_fault_rule_python_parity_and_test_coverage():
    """The fault rule's other two legs: a FAULT_SITES drift on the
    Python side fails, and a site no test names fails (the
    sanitizer-lint pattern — a chaos lever nothing pulls is dead)."""
    # drop one site from a scratch copy of the Python tuple
    nat = _read(os.path.join(REPO, "emqx_tpu", "native", "__init__.py"))
    assert '"housekeep_clock"' in nat
    # parity is currently green on the real tree
    res = rules.run(REPO)
    assert not any(f.rule == "fault" for f in res.unwaived), (
        [f.key for f in res.unwaived if f.rule == "fault"])
    # a site declared in fault.h but absent from tests' text would fail:
    # prove the detector by scanning for an impossible site name
    blob = rules._tests_blob(REPO)
    for site in ("conn_read", "conn_write", "conn_accept", "trunk_read",
                 "trunk_write", "trunk_accept", "trunk_connect",
                 "store_msync", "store_seg_open", "ring_seal",
                 "ring_doorbell", "housekeep_clock"):
        assert re.search(rf"\b{site}\b", blob), (
            f"fault site {site} lost its test coverage")


def test_every_fault_annotation_is_load_bearing():
    """Stripping ANY single // @fault(<site>) annotation flips the
    fault rule (its fire site loses coverage) — the load-bearing sweep
    extended to the faultline grammar (the @fault tokens live outside
    the shared model's function-attachment machinery, so the main
    sweep cannot see them)."""
    base_keys = rules.run(REPO).keys()
    stripped = 0
    for fname in ("host.cc", "store.h"):
        text = _read(os.path.join(SRC, fname))
        lines = text.split("\n")
        for i, line in enumerate(lines):
            m = re.search(r"@fault\([a-z0-9_]+\)", line)
            if not m:
                continue
            mut_lines = list(lines)
            mut_lines[i] = line.replace(m.group(0), "", 1)
            res = rules.run(REPO,
                            overrides={fname: "\n".join(mut_lines)})
            assert res.keys() != base_keys, (
                f"stripping {m.group(0)} at {fname}:{i + 1} flips "
                f"nothing — dead annotation")
            stripped += 1
    assert stripped >= 12, stripped   # every site has >= 1 annotation


def test_mutation_atomics_rule_fires():
    """Rule 6, leg by leg: a bare (seq_cst-defaulted) access fires; an
    out-of-discipline memory_order fires; an unannotated std::atomic
    declaration fires."""
    # bare access on a declared-relaxed counter
    mut = _insert_in_body(_host(), "host.cc", "HandleEvent",
                          "(void)stats_[0].load();")
    res = rules.run(REPO, overrides={"host.cc": mut})
    bad = [f for f in res.unwaived
           if f.rule == "atomics" and f.site.endswith(":stats_")]
    assert bad and "bare" in bad[0].message, (
        [f.key for f in res.unwaived])
    # out-of-discipline order: an acq_rel index stored seq_cst
    ring = _read(os.path.join(SRC, "ring.h"))
    mut = ring + ("\nvoid NcMutant__(emqx_native::ring::SpscRing* r)"
                  " { (void)r; }\n")
    res = rules.run(REPO, overrides={"ring.h": mut})
    assert not any(f.rule == "atomics" for f in res.unwaived)
    mut = ring + ("\nvoid NcMutant__() "
                  "{ head_.store(1, std::memory_order_seq_cst); }\n")
    res = rules.run(REPO, overrides={"ring.h": mut})
    bad = [f for f in res.unwaived
           if f.rule == "atomics" and f.site.endswith(":head_")]
    assert bad and "acq_rel" in bad[0].message, (
        [f.key for f in res.unwaived])
    # unannotated atomic declaration
    mut = _host() + "\nstd::atomic<int> nc_mutant_{0};\n"
    res = rules.run(REPO, overrides={"host.cc": mut})
    assert "atomics:host.cc:nc_mutant_" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_mutation_spsc_publish_order_fires():
    """The SPSC structural leg: slot data touched lexically AFTER the
    index's release store (publish-before-write — the classic lock-free
    bug) fires on exactly that function."""
    ring = _read(os.path.join(SRC, "ring.h"))
    mut = ring + ("\nvoid NcMutant__() {"
                  " head_.store(1, std::memory_order_release);"
                  " slots_[0].clear(); }\n")
    res = rules.run(REPO, overrides={"ring.h": mut})
    assert "atomics:ring.h:NcMutant__:slots_" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_mutation_gen_handle_protocol_fires():
    """The generation-handle leg: a @gen-checked consumer that touches
    the slot before validating fires; a @gen-handle passed to an
    unchecked function fires."""
    wheel = _read(os.path.join(SRC, "wheel.h"))
    mut = wheel + ("\n// @gen-checked\n"
                   "void NcMutant__(uint64_t h) {"
                   " Unlink(static_cast<int32_t>(h));"
                   " (void)NodeOf(h); }\n")
    res = rules.run(REPO, overrides={"wheel.h": mut})
    assert "atomics:wheel.h:NcMutant__" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]
    mut = _host() + ("\nvoid NcSink__(uint64_t v) { (void)v; }\n"
                     "void NcMutant__() { NcSink__(tm_park); }\n")
    res = rules.run(REPO, overrides={"host.cc": mut})
    assert "atomics:host.cc:NcMutant__:tm_park" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_atomics_rule_flags_cross_file_name_collision():
    """Review pin (round 17): access sites resolve by NAME across
    files (host.cc's group_->alive hits ring.h's field), so a second
    file declaring the same atomic name under a DIFFERENT discipline
    must flag loudly instead of letting the last-scanned file win."""
    mut = (_read(os.path.join(SRC, "store.h"))
           + "\n// @atomic(relaxed: collides with ring.h head_)\n"
           + "std::atomic<size_t> head_{0};\n")
    res = rules.run(REPO, overrides={"store.h": mut})
    assert any(f.rule == "atomics" and f.site.endswith(":ambiguous")
               and "head_" in f.site for f in res.unwaived), (
        [f.key for f in res.unwaived])
    # same name + SAME discipline is fine (one contract, two decls)
    mut = (_read(os.path.join(SRC, "store.h"))
           + "\n// @atomic(relaxed: a second relaxed gauge)\n"
           + "std::atomic<uint64_t> lane_backlog_{0};\n")
    res = rules.run(REPO, overrides={"store.h": mut})
    assert not any(f.site.endswith(":ambiguous")
                   for f in res.unwaived), (
        [f.key for f in res.unwaived])


def test_lock_order_memo_not_poisoned_by_call_cycles():
    """Review pin (round 17): a call cycle used to memoize
    cycle-truncated partial acquire-sets — the first query walking
    D1->Cchain->A->B->(Cchain) stored B as {} and A as {m1}, so a
    later holder of m3 calling A never observed the real m3 < m2
    nesting. Partial results are no longer memoized."""
    mut = _host() + (
        "\nstruct NcCyc__ {"
        "\n  std::mutex nc_m1_, nc_m2_, nc_m3_, nc_m4_;"
        "\n  void NcD1__() { std::lock_guard<std::mutex> lk(nc_m4_);"
        " NcCchain__(); }"
        "\n  void NcCchain__() { std::lock_guard<std::mutex> lk(nc_m2_);"
        " NcA__(); }"
        "\n  void NcA__() { std::lock_guard<std::mutex> lk(nc_m1_);"
        " NcB__(); }"
        "\n  void NcB__() { NcCchain__(); }"
        "\n  void NcD2__() { std::lock_guard<std::mutex> lk(nc_m3_);"
        " NcA__(); }"
        "\n};\n")
    res = rules.run(REPO, overrides={"host.cc": mut})
    keys = {f.key for f in res.unwaived}
    # the edge only reachable THROUGH the cycle's truncated member
    assert "lock-order:host.cc:nc_m3_<host.cc:nc_m2_" in keys, keys
    # and the direct one still observed
    assert "lock-order:host.cc:nc_m3_<host.cc:nc_m1_" in keys, keys


def test_mutation_lock_order_rule_fires():
    """Rule 7: an inverted nesting (durable under closed... here:
    mirror acquired while holding durable) is BOTH an undeclared edge
    and a cycle against the declared _mirror_lock < _durable_lock."""
    text = _read(SERVER_PY)
    marker = "    def _on_tap(self"
    mut = text.replace(
        marker,
        "    def _nc_mutant__(self):\n"
        "        with self._durable_lock:\n"
        "            with self._mirror_lock:\n"
        "                pass\n\n" + marker, 1)
    res = rules.run(REPO, overrides={"native_server.py": mut})
    keys = {f.key for f in res.unwaived}
    assert "lock-order:_durable_lock<_mirror_lock" in keys, keys
    assert any(k.startswith("lock-order:cycle:") for k in keys), keys
    # a plain-Lock self-acquisition is flagged as a self-deadlock
    mut = text.replace(
        marker,
        "    def _nc_mutant__(self):\n"
        "        with self._tap_lock:\n"
        "            with self._tap_lock:\n"
        "                pass\n\n" + marker, 1)
    res = rules.run(REPO, overrides={"native_server.py": mut})
    assert "lock-order:_tap_lock<_tap_lock" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_lock_order_config_is_load_bearing():
    """Removing a declared LOCK_ORDER edge makes the observed nesting
    an undeclared-edge finding; declaring a never-observed edge goes
    stale — the config cannot rot in either direction."""
    from tools.nativecheck.waivers import LOCK_ORDER
    keep = [e for e in LOCK_ORDER
            if not e["order"].startswith("_mirror_lock")]
    assert len(keep) == len(LOCK_ORDER) - 1
    res = rules.run(REPO, lock_order=keep)
    assert "lock-order:_mirror_lock<_durable_lock" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]
    res = rules.run(REPO, lock_order=LOCK_ORDER + [
        {"order": "_tap_lock < _ack_lock", "why": "never happens"}])
    assert any("stale:_tap_lock<_ack_lock" in f.site
               for f in res.unwaived), [f.key for f in res.unwaived]
    # malformed entry (no '<' / empty why) fires
    res = rules.run(REPO, lock_order=LOCK_ORDER + [
        {"order": "_tap_lock", "why": "x"}])
    assert any(f.rule == "lock-order" and "malformed" in f.message
               for f in res.unwaived), [f.key for f in res.unwaived]


def test_mutation_tap_bound_rule_fires():
    """Rule 8: an append to a @bounded buffer outside its writer
    fires; a writer whose append has no margin check fires."""
    mut = _insert_in_body(_host(), "host.cc", "HandleEvent",
                          'tap_buf_.append("x", 1);')
    res = rules.run(REPO, overrides={"host.cc": mut})
    assert "tap-bound:host.cc:HandleEvent:tap_buf_" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]
    mut = _host() + ('\n// @bounded(tap_buf_)\n'
                     'void NcMutant__() { tap_buf_.append("x", 1); }\n')
    res = rules.run(REPO, overrides={"host.cc": mut})
    assert "tap-bound:host.cc:NcMutant__:tap_buf_" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]
    # ...and a writer annotation naming a nonexistent buffer fires
    mut = _host() + ('\n// @bounded(nc_buf_)\n'
                     'void NcMutant2__() { }\n')
    res = rules.run(REPO, overrides={"host.cc": mut})
    assert "tap-bound:host.cc:NcMutant2__:@bounded" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_pyfold_scope_is_transitive():
    """Round-17 satellite: a guarded-state touch TWO callee hops below
    an _on_* fold fires (the old scope was one hop)."""
    text = _read(SERVER_PY)
    marker = "    def _on_tap(self"
    mut = text.replace(
        marker,
        "    def _on_nc_mutant__(self, payload):\n"
        "        self._nc_hop1__()\n\n"
        "    def _nc_hop1__(self):\n"
        "        self._nc_hop2__()\n\n"
        "    def _nc_hop2__(self):\n"
        "        self.ack_plane[\"acked\"] += 1\n\n" + marker, 1)
    res = rules.run(REPO, overrides={"native_server.py": mut})
    assert "pyfold:native_server.py:_nc_hop2__:ack_plane" in {
        f.key for f in res.unwaived}, [f.key for f in res.unwaived]


def test_cpp_callgraph_resolves_by_class_scope():
    """Round-17 satellite: an UNQUALIFIED call to a same-named method
    resolves to the caller's class only (no cross-class edge), while a
    qualified call keeps the over-approximation."""
    mut = _host() + (
        "\nstruct NcScopeA__ {"
        "\n  void NcEntry__() { NcHelper__(); }"
        "\n  void NcHelper__() {}"
        "\n};"
        "\nstruct NcScopeB__ {"
        "\n  void NcHelper__() {}"
        "\n  void NcOther__(NcScopeA__* a) { a->NcHelper__(); }"
        "\n};\n")
    model = rules.build_cpp_model(REPO, overrides={"host.cc": mut})
    entry = next(f for f in model.sources["host.cc"].functions
                 if f.name == "NcEntry__")
    callees = {(c.cls, c.name) for c, _ in model.call_edges(entry)}
    assert callees == {("NcScopeA__", "NcHelper__")}, callees
    other = next(f for f in model.sources["host.cc"].functions
                 if f.name == "NcOther__")
    callees = {(c.cls, c.name) for c, _ in model.call_edges(other)}
    assert callees == {("NcScopeA__", "NcHelper__"),
                       ("NcScopeB__", "NcHelper__")}, callees
    # the real tree still resolves the waived plane paths (the fsync
    # contract stays visible, not accidentally unreachable)
    res = rules.run(REPO)
    waived = {f.site for f in res.findings if f.waived_by}
    assert "store.h:SyncSeg" in waived and "store.h:Roll" in waived


def test_cli_json_schema():
    """--json: the stable machine surface (schema 1) CI and editors
    consume instead of scraping text. Keys and finding shape pinned."""
    import json
    p = subprocess.run(
        [sys.executable, "-m", "tools.nativecheck", "--json", REPO],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert p.returncode == 0, p.stdout + p.stderr
    doc = json.loads(p.stdout)
    assert set(doc) == {"schema", "ok", "elapsed_s", "unwaived",
                        "waived", "stale", "findings",
                        "stale_waivers"}, sorted(doc)
    assert doc["schema"] == 1 and doc["ok"] is True
    assert doc["unwaived"] == 0 and doc["stale"] == 0
    assert doc["waived"] == len(doc["findings"]) == 4
    for f in doc["findings"]:
        assert set(f) == {"rule", "file", "line", "site", "message",
                          "waived_by"}, sorted(f)
        assert isinstance(f["line"], int) and f["waived_by"]


def test_mutation_waiver_hygiene_fires():
    """Seed a stale waiver and a malformed one: rule 5 must flag
    both — the waiver file can never rot into a blanket allowlist."""
    res = rules.run(REPO, waivers=WAIVERS + [
        {"rule": "plane", "site": "host.cc:NoSuchFn",
         "why": "left over after a refactor"}])
    assert [w["site"] for w in res.stale_waivers] == ["host.cc:NoSuchFn"]
    res = rules.run(REPO, waivers=WAIVERS + [
        {"rule": "plane", "site": "store.h:SyncSeg", "why": "   "}])
    assert any(f.rule == "waivers" and f.waived_by is None
               for f in res.findings), res.findings


# -- every annotation is load-bearing -----------------------------------------


def _strip_token(text: str, line: int, token: str) -> str:
    lines = text.split("\n")
    assert token in lines[line - 1], (line, token, lines[line - 1])
    lines[line - 1] = lines[line - 1].replace(token, "", 1)
    return "\n".join(lines)


def _collect_annotations():
    """Every annotation in the analyzed sources with the probe that
    demonstrates its load-bearing-ness: (label, file, line, token,
    probe) where probe(texts) mutates the override dict in place (or
    is None when stripping on the real tree already flips a result)."""
    model = rules.build_cpp_model(REPO)
    out = []

    def cpp_probe(kind, arg, owner, fname):
        if kind == "plane" and arg == "poll":
            return ("host.cc", lambda t: _insert_in_body(
                t, "host.cc", owner, "ListenTrunk(0, 0);"))
        if kind == "plane" and arg == "control":
            return ("host.cc", lambda t: _insert_in_body(
                t, "host.cc", "Poll", f"{owner}(0);"))
        if kind == "blocking":
            return ("host.cc", lambda t: _insert_in_body(
                t, "host.cc", "Poll", f"{owner}(0);"))
        if kind == "admit-gated":
            return ("host.cc",
                    lambda t: t + f"\nvoid NcProbe__() {{ {owner}(0); }}\n")
        if kind == "admit-check":
            return ("host.cc", lambda t: t + (
                f"\nvoid NcProbe__() {{ if (!{owner}(0)) return; "
                f"FanOut(0); }}\n"))
        if kind == "guards":
            return (fname,
                    lambda t: t + f"\nvoid NcProbe__() {{ (void){owner}; }}\n")
        if kind == "published":
            idx = arg.split(",")[0].strip()
            return (fname, lambda t: t + (
                f"\nvoid NcProbe__() {{"
                f" {idx}.store(1, std::memory_order_release);"
                f" {owner}[0].clear(); }}\n"))
        if kind == "gen-handle":
            # pass the handle to an unchecked sink: only the
            # annotation makes that a finding
            return ("host.cc", lambda t: t + (
                f"\nvoid NcSinkP__(uint64_t v) {{ (void)v; }}\n"
                f"void NcProbe__() {{ NcSinkP__({owner}); }}\n"))
        # @locked / @atomic / @bounded / @gen-check / @gen-bump /
        # @gen-checked: stripping flips results on the real tree
        return None

    for fn in model.functions():
        for kind, ann in fn.annotations.items():
            token = f"@{kind}({ann.arg})" if ann.arg else f"@{kind}"
            out.append((f"{fn.file}:{fn.name}:{kind}", fn.file, ann.line,
                        token, cpp_probe(kind, ann.arg, fn.name, fn.file)))
    for src in model.sources.values():
        for fld in src.fields:
            for kind, ann in fld.annotations.items():
                token = f"@{kind}({ann.arg})" if ann.arg else f"@{kind}"
                out.append((f"{src.name}:{fld.name}:{kind}", src.name,
                            ann.line, token,
                            cpp_probe(kind, ann.arg, fld.name, src.name)))

    py = PySource(SERVER_PY)
    for attr, lock in py.model.guarded.items():
        line = py.model.guarded_lines[attr]
        marker = "    def _on_tap(self"

        def probe(t, attr=attr):
            return t.replace(
                marker,
                f"    def _on_nc_probe__(self):\n"
                f"        return self.{attr}\n\n" + marker, 1)
        out.append((f"native_server.py:{attr}:guards", "native_server.py",
                    line, f"@guards({lock})", ("native_server.py", probe)))
    for m in py.model.methods.values():
        if m.locked:
            out.append((f"native_server.py:{m.name}:locked",
                        "native_server.py", m.locked_line,
                        f"@locked({m.locked})", None))
    return out


def test_every_annotation_is_load_bearing():
    """Stripping ANY single annotation flips a rule result — either on
    the real tree (waivers go stale / findings appear) or on the
    annotation's probe (a seeded bad edit its rule can only catch with
    the annotation present). An annotation failing this is dead weight
    and must be removed."""
    anns = _collect_annotations()
    # every annotation kind in the grammar is represented in the tree
    kinds = {a[0].rsplit(":", 1)[1] for a in anns}
    assert kinds == {"plane", "guards", "blocking", "locked",
                     "admit-gated", "admit-check", "atomic",
                     "published", "bounded", "gen-check", "gen-bump",
                     "gen-checked", "gen-handle"}, kinds
    assert len(anns) >= 60, len(anns)

    def text_of(fname):
        if fname == "native_server.py":
            return _read(SERVER_PY)
        return _read(os.path.join(SRC, fname))

    base_keys = rules.run(REPO).keys()   # probe-less runs reuse this
    failures = []
    for label, fname, line, token, probe in anns:
        overrides = {}
        if probe is not None:
            pfile, pfn = probe
            overrides[pfile] = pfn(text_of(pfile))
            with_keys = rules.run(REPO, overrides=overrides).keys()
        else:
            with_keys = base_keys
        base = overrides.get(fname, text_of(fname))
        overrides[fname] = _strip_token(base, line, token)
        without_ann = rules.run(REPO, overrides=overrides)
        if with_keys == without_ann.keys():
            failures.append(label)
    assert failures == [], (
        f"annotations whose removal flips nothing: {failures}")


# -- regression pins for the real violations nativecheck surfaced -------------


def test_store_ok_acquires_the_store_mutex():
    """Real violation #1 (lockset): DurableStore::ok() returned ok_
    with no lock while Roll() flips it on the poll thread mid-run — a
    C++ data race (benign-looking bool, undefined behavior). Pinned:
    ok() now holds mu_ like every other guarded read."""
    model = rules.build_cpp_model(REPO)
    store = model.sources["store.h"]
    ok = [f for f in store.functions if f.name == "ok"]
    assert ok, "DurableStore::ok() not found"
    assert [m for m, _, _ in store.lock_sites(ok[0])] == ["mu_"], (
        "ok() no longer acquires mu_")
    # and it still behaves: a healthy store constructs through
    # emqx_store_open (which asserts ok() through the locked accessor)
    # and serves its surface
    from emqx_tpu import native
    if native.available():
        s = native.NativeStore("", 1 << 16, "never")
        try:
            tok = s.register("nc-sid")
            assert tok > 0 and s.pending(tok) == 0
        finally:
            s.close()


def test_tap_dropped_fold_is_locked_and_counts():
    """Real violation #2 (pyfold): _on_tap folded tap_dropped with a
    bare += from N shard poll threads (read-modify-write: concurrent
    queue.Full hits lost drop counts). Pinned: the fold runs under
    _tap_lock and still counts exactly."""
    from emqx_tpu.broker.native_server import NativeBrokerServer

    srv = NativeBrokerServer.__new__(NativeBrokerServer)
    srv._tap_q = queue.Queue(maxsize=1)
    srv._tap_q.put_nowait(b"occupied")
    srv._tap_lock = threading.Lock()
    srv.tap_dropped = 0
    # one batch holding two pre-parsed entries (inline payloads)
    entry = ((7).to_bytes(8, "little") + bytes([1])
             + (3).to_bytes(2, "little") + b"t/x"
             + (2).to_bytes(4, "little") + b"hi")
    srv._on_tap(0, entry + entry)
    assert srv.tap_dropped == 2
    # the rule itself guards the lock: tap_dropped is annotated
    py = PySource(SERVER_PY)
    assert py.model.guarded.get("tap_dropped") == "_tap_lock"


def test_durable_sids_single_guardian():
    """Real violation #3 (pyfold): _durable_token wrote _durable_sids/
    _durable_dead under _mirror_lock while the kind-10 fold read them
    under _durable_lock — two different locks is no mutual exclusion.
    Pinned: the annotations name ONE guardian and the tree is clean
    (test_tree_is_clean), so every touch now holds _durable_lock."""
    py = PySource(SERVER_PY)
    for attr in ("_durable_sids", "_durable_dead", "_durable_drain_mark"):
        assert py.model.guarded.get(attr) == "_durable_lock", attr


# -- sanitizer-coverage lint (satellite) --------------------------------------

SAN_TEST = os.path.join(REPO, "tests", "test_native_sanitizers.py")

# every native/src/*.h subsystem -> (driver name, a token that driver
# must contain proving it exercises the subsystem). A header with no
# ASan+TSan driver yet must be waived BY NAME below (the CoAP rule:
# new gateway headers land with their driver or an explicit IOU).
SANCOV_HEADERS = {
    "coap.h": ("coap", "listen_coap"),       # observe churn + storms
    "fault.h": ("fault", "fault_arm"),       # arm/disarm vs poll races
    "frame.h": ("host", "NativeHost"),       # byte-dribbled framing
    "park.h": ("park", "set_park"),          # park/inflate + shed churn
    "router.h": ("fastpath", "sub_add"),     # match-table churn
    "ring.h": ("shards", "NativeShardGroup"),
    "sn.h": ("sn", "listen_sn"),
    "store.h": ("durable", "NativeStore"),
    "trunk.h": ("trunk", "trunk_connect"),
    "wheel.h": ("park", "set_keepalive"),    # keepalive/park timer churn
    "ws.h": ("ws", "listen_ws"),
}
SANCOV_WAIVED: set = set()   # e.g. {"coap.h"} until its driver lands


def _san_text() -> str:
    return _read(SAN_TEST)


def _san_drivers() -> dict:
    """module-level DRIVER_* blocks: suffix-derived name -> body."""
    text = _san_text()
    out = {}
    for m in re.finditer(
            r'^DRIVER(?:_([A-Z0-9]+))? = r?"""(.*?)"""', text,
            re.M | re.S):
        name = (m.group(1) or "HOST").lower()
        out[name] = m.group(2)
    return out


def test_every_driver_is_registered_and_parametrized():
    """A DRIVER_* blob that exists but never runs is silent coverage
    loss: every module-level driver must appear in the src map AND the
    parametrize list (and vice versa)."""
    text = _san_text()
    drivers = set(_san_drivers())
    map_m = re.search(r"src = \{(.*?)\}\[driver\]", text, re.S)
    assert map_m, "driver map not found"
    mapped = dict(re.findall(r'"(\w+)":\s*(DRIVER\w*)', map_m.group(1)))
    param_m = re.search(
        r'@pytest\.mark\.parametrize\("driver",\s*\[(.*?)\]\)', text, re.S)
    assert param_m, "driver parametrize not found"
    params = set(re.findall(r'"(\w+)"', param_m.group(1)))
    assert set(mapped) == drivers, (
        f"driver map keys {sorted(mapped)} != DRIVER_* blobs "
        f"{sorted(drivers)}")
    assert params == drivers, (
        f"parametrize list {sorted(params)} != DRIVER_* blobs "
        f"{sorted(drivers)}")
    # the mapped value really is that blob (no crossed wires)
    for key, val in mapped.items():
        want = "DRIVER" if key == "host" else f"DRIVER_{key.upper()}"
        assert val == want, (key, val)


def test_every_native_header_has_a_sanitizer_driver():
    """Every native/src/*.h subsystem is exercised by at least one
    ASan+TSan driver — the declared mapping is checked against both
    the filesystem and the driver bodies, so a NEW header fails until
    it gets a driver or a by-name waiver."""
    headers = {f for f in os.listdir(SRC) if f.endswith(".h")}
    declared = set(SANCOV_HEADERS) | SANCOV_WAIVED
    assert headers == declared, (
        f"native/src headers {sorted(headers)} drifted from the "
        f"sanitizer-coverage map {sorted(declared)} — add a driver "
        f"mapping (or a by-name waiver with an IOU)")
    drivers = _san_drivers()
    for header, (driver, token) in SANCOV_HEADERS.items():
        assert driver in drivers, (header, driver)
        assert token in drivers[driver], (
            f"{header}: driver '{driver}' no longer exercises it "
            f"(token {token!r} missing)")


# -- the shared source model stays the legacy lints' substrate ----------------


def test_legacy_lints_ride_the_shared_model():
    """The two migrated lints import their parsing from
    tools.nativecheck.model — the duplicated ad-hoc C++ parsers are
    gone (one source model, three consumers)."""
    for rel in ("tests/test_stats_lint.py", "tests/test_native_wire_lint.py"):
        text = _read(os.path.join(REPO, rel))
        assert "tools.nativecheck.model" in text, rel
        assert "re.search(rf\"enum" not in text, rel
    from tools.nativecheck.model import enum_body, enumerators, snake
    host = _host()
    assert snake("FastBytesOut") == "fast_bytes_out"
    assert enumerators(host, "StatSlot", "kSt")[0] == "FastIn"
    assert "kStFastIn" in enum_body(host, "StatSlot")
