"""Limiter tests: bucket math, hierarchy, container, server admission,
and live-broker message_in backpressure (reference ground:
emqx_htb_limiter tests + emqx_ratelimiter_SUITE)."""

import asyncio

import pytest

from emqx_tpu.broker.limiter import (
    Bucket, LimiterConfig, LimiterContainer, LimiterServer,
)


def test_bucket_basic_consume_and_refill():
    b = Bucket(rate=10.0, burst=5.0)
    now = 100.0
    b._last = now
    b.tokens = 5.0
    ok, _ = b.try_consume(5, now)
    assert ok
    ok, retry = b.try_consume(1, now)
    assert not ok and retry == pytest.approx(0.1)
    ok, _ = b.try_consume(1, now + 0.1)      # refilled 1 token
    assert ok


def test_infinity_bucket():
    b = Bucket(rate=None)
    for _ in range(1000):
        ok, _ = b.try_consume(1e9)
        assert ok


def test_hierarchy_parent_caps_children():
    now = 100.0
    root = Bucket(rate=10.0, burst=10.0)
    a = root.child(rate=None)
    bb = root.child(rate=None)
    for b in (root, a, bb):
        b._last = now
    # children individually unlimited, but root holds 10 tokens total
    assert a.try_consume(6, now)[0]
    assert bb.try_consume(4, now)[0]
    ok, retry = a.try_consume(1, now)
    assert not ok and retry > 0
    # after refill both can draw again
    assert bb.try_consume(1, now + 0.5)[0]


def test_child_tighter_than_parent():
    now = 50.0
    root = Bucket(rate=1000.0, burst=1000.0)
    leaf = root.child(rate=2.0, burst=2.0)
    root._last = leaf._last = now
    assert leaf.try_consume(2, now)[0]
    ok, retry = leaf.try_consume(2, now)
    assert not ok and retry == pytest.approx(1.0)


def test_all_or_nothing_no_partial_drain():
    now = 10.0
    root = Bucket(rate=10.0, burst=10.0)
    leaf = root.child(rate=100.0, burst=3.0)
    root._last = leaf._last = now
    ok, _ = leaf.try_consume(5, now)          # leaf has only 3
    assert not ok
    assert root.tokens == pytest.approx(10.0)  # nothing taken from root


def test_container_missing_type_is_infinite():
    c = LimiterContainer()
    assert c.check("bytes_in", 1e12) == (True, 0.0)


def test_limiter_server_scopes():
    srv = LimiterServer(LimiterConfig(bytes_in=1000.0))
    srv.add_listener(
        "tcp:1",
        LimiterConfig(connection=2.0, connection_burst=2.0,
                      bytes_in=500.0),
        client_config=LimiterConfig(bytes_in=100.0, bytes_in_burst=100.0),
    )
    # conn admission: burst of 2, then refused
    assert srv.connect("tcp:1")[0]
    assert srv.connect("tcp:1")[0]
    assert not srv.connect("tcp:1")[0]
    # container chains client(100) → listener(500) → node(1000)
    cont = srv.make_container("tcp:1")
    b = cont.buckets["bytes_in"]
    assert b.rate == 100.0
    assert b.parent.rate == 500.0
    assert b.parent.parent.rate == 1000.0
    ok, _ = cont.check("bytes_in", 100)
    assert ok
    ok, _ = cont.check("bytes_in", 50)
    assert not ok
    # unknown listener → unlimited container
    assert srv.make_container("nope").check("bytes_in", 1e9)[0]


def test_live_broker_message_in_backpressure():
    """2 msg/s per client: 10 QoS1 publishes take ≥~1.5s wall clock but
    all get through (backpressure pauses the socket, drops nothing)."""
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    async def main():
        limiter = LimiterServer()
        limiter.add_listener(
            "tcp:default", LimiterConfig(),
            client_config=LimiterConfig(message_in=8.0, message_in_burst=4.0),
        )
        srv = BrokerServer(port=0, limiter=limiter)
        await srv.start()
        try:
            c = MqttClient(port=srv.port, clientid="lim1")
            await c.connect()
            await c.subscribe("t/#", qos=0)
            loop = asyncio.get_event_loop()
            t0 = loop.time()
            for i in range(10):
                await c.publish("t/x", b"m%d" % i, qos=1)
            elapsed = loop.time() - t0
            # burst 4 free, remaining 6 at 8/s → ≳0.6s
            assert elapsed > 0.4, f"no backpressure applied ({elapsed:.2f}s)"
            got = [await c.recv() for _ in range(10)]
            assert len(got) == 10
            await c.disconnect()
            await c.close()
        finally:
            await srv.stop()

    asyncio.run(main())
