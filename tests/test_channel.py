"""Channel FSM + CM tests — mirrors emqx_channel_SUITE / emqx_cm_SUITE:
whole client flows driven at the parsed-packet level."""

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel, ConnInfo
from emqx_tpu.broker.cm import CM
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.core.message import SubOpts
from emqx_tpu.mqtt import packet as P


class Harness:
    """A tiny single-node broker with packet-level clients."""

    def __init__(self):
        self.broker = Broker()
        self.cm = CM()
        self.channels: dict[str, Channel] = {}

    def connect(self, clientid, clean_start=True, proto=P.MQTT_V4, **kw):
        ch = Channel(self.broker, self.cm)
        out = ch.handle_in(P.Connect(
            clientid=clientid, clean_start=clean_start, proto_ver=proto, **kw
        ))
        self.channels[clientid] = ch
        return ch, out

    def publish(self, ch, topic, payload=b"", qos=0, pid=None, **kw):
        """Publish from a client and fan deliveries out to all channels."""
        acks = ch.handle_in(P.Publish(
            topic=topic, payload=payload, qos=qos, packet_id=pid, **kw
        ))
        # route once more to capture deliveries (publish already happened
        # inside handle_in; we emulate the conn layer fan-out by publishing
        # via broker? no — handle_in called broker.publish which returned
        # deliveries we dropped. For tests, deliver explicitly:
        return acks


def connect_flow():
    h = Harness()
    ch, out = h.connect("c1")
    return h, ch, out


def test_connect_connack():
    h, ch, out = connect_flow()
    assert out == [P.Connack(session_present=False)]
    assert ch.conn_state == "connected"
    assert h.cm.lookup_channel("c1") is ch


def test_first_packet_must_be_connect():
    h = Harness()
    ch = Channel(h.broker, h.cm)
    with pytest.raises(P.FrameError):
        ch.handle_in(P.PingReq())


def test_duplicate_connect_is_protocol_error():
    h, ch, _ = connect_flow()
    with pytest.raises(P.FrameError):
        ch.handle_in(P.Connect(clientid="c1"))


def test_empty_clientid_v5_assigned():
    h = Harness()
    ch, out = h.connect("", proto=P.MQTT_V5)
    assert out[0].reason_code == P.RC_SUCCESS
    assert "Assigned-Client-Identifier" in out[0].properties
    assert ch.clientid


def test_empty_clientid_v4_persistent_rejected():
    h = Harness()
    ch, out = h.connect("", clean_start=False, proto=P.MQTT_V4)
    assert out[0].reason_code == 2     # v3 "identifier rejected"


def test_subscribe_publish_qos1_end_to_end():
    h = Harness()
    sub_ch, _ = h.connect("sub")
    suback = sub_ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[
        ("t/+", {"qos": 1}), ("bad/#/x", {"qos": 0}),
    ]))
    assert suback[0].reason_codes == [1, P.RC_TOPIC_FILTER_INVALID]

    pub_ch, _ = h.connect("pub")
    deliveries_seen = []
    # emulate the connection host: deliver broker output to the sub channel
    acks = pub_ch.handle_in(P.Publish(topic="t/1", payload=b"hi", qos=1,
                                      packet_id=10))
    assert acks == [P.PubAck(packet_id=10)]
    # deliveries from broker.publish happen inside handle_in; drive them:
    out = sub_ch.handle_deliver([("t/+",
                                  __import__("emqx_tpu.core.message",
                                             fromlist=["Message"]).Message(
                                      topic="t/1", payload=b"hi", qos=1))])
    assert len(out) == 1 and out[0].qos == 1 and out[0].payload == b"hi"
    # client acks
    assert sub_ch.handle_in(P.PubAck(packet_id=out[0].packet_id)) == []


def test_publish_qos2_exactly_once():
    h = Harness()
    ch, _ = h.connect("c")
    got = []
    h.broker.hooks.add("message.publish", lambda m: got.append(m.topic) or m)
    rec = ch.handle_in(P.Publish(topic="q2", qos=2, packet_id=5))
    assert rec == [P.PubRec(packet_id=5)]
    # duplicate PUBLISH with same pid before PUBREL → not re-published
    rec2 = ch.handle_in(P.Publish(topic="q2", qos=2, packet_id=5))
    assert rec2[0].reason_code == P.RC_PACKET_IDENTIFIER_IN_USE
    assert got.count("q2") == 1
    comp = ch.handle_in(P.PubRel(packet_id=5))
    assert comp == [P.PubComp(packet_id=5)]
    # unknown PUBREL
    comp2 = ch.handle_in(P.PubRel(packet_id=99))
    assert comp2[0].reason_code == P.RC_PACKET_IDENTIFIER_NOT_FOUND


def test_authz_deny_publish():
    h = Harness()
    ch, _ = h.connect("c")
    h.broker.hooks.add(
        "client.authorize",
        lambda who, action, topic, acc: "deny" if topic == "secret" else acc,
    )
    assert ch.handle_in(P.Publish(topic="secret", qos=1, packet_id=1)) == \
        [P.PubAck(packet_id=1, reason_code=P.RC_NOT_AUTHORIZED)]
    suback = ch.handle_in(P.Subscribe(packet_id=2, topic_filters=[
        ("secret", {"qos": 0})]))
    assert suback[0].reason_codes == [P.RC_NOT_AUTHORIZED]


def test_authn_reject():
    h = Harness()
    h.broker.hooks.add(
        "client.authenticate",
        lambda info, acc: {"result": "error", "rc": P.RC_BAD_USER_NAME_OR_PASSWORD}
        if info["username"] != "root" else acc,
    )
    ch, out = h.connect("c", proto=P.MQTT_V5, username="eve", password=b"x")
    assert out[0].reason_code == P.RC_BAD_USER_NAME_OR_PASSWORD
    ch2, out2 = h.connect("c2", proto=P.MQTT_V5, username="root", password=b"x")
    assert out2[0].reason_code == P.RC_SUCCESS


def test_takeover_preserves_pending():
    h = Harness()
    ch1, _ = h.connect("dev1", clean_start=False, proto=P.MQTT_V5,
                       properties={"Session-Expiry-Interval": 3600})
    ch1.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 1})]))
    # backlog: deliver more than the inflight window while "slow"
    from emqx_tpu.core.message import Message
    ch1.session.max_inflight = 1
    ch1.session.inflight.max_size = 1
    ch1.handle_deliver([("t", Message(topic="t", qos=1, payload=b"a"))])
    ch1.handle_deliver([("t", Message(topic="t", qos=1, payload=b"b"))])
    assert len(ch1.session.mqueue) == 1
    # second client resumes the session
    ch2, out = h.connect("dev1", clean_start=False, proto=P.MQTT_V5,
                         properties={"Session-Expiry-Interval": 3600})
    assert out[0].session_present is True
    assert ch1.conn_state == "disconnected"
    # the carried-over window is 1, so one replay flies, one re-queues
    replays = [p for p in out if isinstance(p, P.Publish)]
    assert [p.payload for p in replays] == [b"a"]
    assert len(ch2.session.mqueue) == 1
    assert h.cm.lookup_channel("dev1") is ch2
    # acking the first frees the window for the second
    nxt = ch2.handle_in(P.PubAck(packet_id=replays[0].packet_id))
    assert [p.payload for p in nxt] == [b"b"]


def test_clean_start_discards_old_session():
    h = Harness()
    ch1, _ = h.connect("dev", clean_start=False, proto=P.MQTT_V5,
                       properties={"Session-Expiry-Interval": 3600})
    ch1.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 1})]))
    ch2, out = h.connect("dev", clean_start=True)
    assert out[0].session_present is False
    assert ch1.conn_state == "disconnected"


def test_will_message_on_abnormal_disconnect():
    h = Harness()
    watcher, _ = h.connect("w")
    watcher.handle_in(P.Subscribe(packet_id=1, topic_filters=[("will/t", {"qos": 0})]))
    seen = []
    h.broker.hooks.add("message.publish", lambda m: seen.append(m.topic) or m)
    ch, _ = h.connect("dying", will_flag=True, will_qos=0,
                      will_topic="will/t", will_payload=b"gone")
    ch.terminate("socket_error")
    assert "will/t" in seen
    # normal DISCONNECT discards the will
    ch2, _ = h.connect("polite", will_flag=True, will_qos=0,
                       will_topic="will/t", will_payload=b"oops")
    seen.clear()
    ch2.handle_in(P.Disconnect())
    assert seen == []


def test_keepalive_expiry():
    h = Harness()
    ch, _ = h.connect("k")
    ch.conninfo.keepalive = 10
    ch.last_packet_at = 0
    assert ch.keepalive_expired(now=15_001)
    assert not ch.keepalive_expired(now=14_999)


def test_unsubscribe():
    h = Harness()
    ch, _ = h.connect("c")
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 0})]))
    out = ch.handle_in(P.Unsubscribe(packet_id=2, topic_filters=["t", "never"]))
    assert out[0].reason_codes == [P.RC_SUCCESS, P.RC_NO_SUBSCRIPTION_EXISTED]
    assert h.broker.publish(
        __import__("emqx_tpu.core.message", fromlist=["Message"]).Message(topic="t")
    ) == {}


def test_topic_alias_v5():
    h = Harness()
    ch, _ = h.connect("a", proto=P.MQTT_V5)
    got = []
    h.broker.hooks.add("message.publish", lambda m: got.append(m.topic) or m)
    ch.handle_in(P.Publish(topic="long/topic", qos=0,
                           properties={"Topic-Alias": 1}))
    ch.handle_in(P.Publish(topic="", qos=0, properties={"Topic-Alias": 1}))
    assert got == ["long/topic", "long/topic"]
    with pytest.raises(P.FrameError):
        ch.handle_in(P.Publish(topic="", qos=0, properties={"Topic-Alias": 9}))


def test_mountpoint_namespacing():
    h = Harness()
    ch = Channel(h.broker, h.cm, mountpoint="tenant/%c/")
    ch.handle_in(P.Connect(clientid="c9"))
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 0})]))
    assert h.broker.router.has_route("tenant/c9/t", h.broker.node)
    from emqx_tpu.core.message import Message
    out = ch.handle_deliver([("tenant/c9/t",
                              Message(topic="tenant/c9/t", payload=b"x"))])
    assert out[0].topic == "t"    # unmounted on the way out


def test_cm_kick():
    h = Harness()
    ch, _ = h.connect("k1")
    assert h.cm.kick("k1") is True
    assert h.cm.kick("k1") is False
    assert ch.conn_state == "disconnected"


def test_publish_actually_reaches_subscriber_socket():
    """End-to-end: publisher handle_in drives bytes into the subscriber's
    outbox without any test-side glue (the review-found missing link)."""
    h = Harness()
    sub_ch, _ = h.connect("sub2")
    sub_ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("e2e/+", {"qos": 1})]))
    pub_ch, _ = h.connect("pub2")
    pub_ch.handle_in(P.Publish(topic="e2e/x", payload=b"live", qos=1, packet_id=3))
    got = [p for p in sub_ch.outbox if isinstance(p, P.Publish)]
    assert len(got) == 1 and got[0].payload == b"live" and got[0].topic == "e2e/x"


def test_discard_cleans_broker_state():
    h = Harness()
    ch1, _ = h.connect("dev", clean_start=False, proto=P.MQTT_V5,
                       properties={"Session-Expiry-Interval": 3600})
    ch1.handle_in(P.Subscribe(packet_id=1, topic_filters=[("leak/t", {"qos": 0})]))
    h.connect("dev", clean_start=True)       # clean start discards old
    assert "leak/t" not in h.broker.subscriber
    assert h.broker.router.match_routes("leak/t") == []


def test_mountpoint_shared_sub():
    h = Harness()
    ch = Channel(h.broker, h.cm, mountpoint="ns/")
    ch.handle_in(P.Connect(clientid="sc"))
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("$share/g/t", {"qos": 0})]))
    # route must be a shared-group route for the mounted real topic
    assert h.broker.router.has_route("ns/t", ("g", h.broker.node))
    out = ch.handle_in(P.Unsubscribe(packet_id=2, topic_filters=["$share/g/t"]))
    assert out[0].reason_codes == [P.RC_SUCCESS]
    assert not h.broker.router.has_route("ns/t", ("g", h.broker.node))


def test_dequeued_packet_unmounted():
    h = Harness()
    ch = Channel(h.broker, h.cm, mountpoint="m/")
    ch.handle_in(P.Connect(clientid="dq"))
    ch.handle_in(P.Subscribe(packet_id=1, topic_filters=[("t", {"qos": 1})]))
    ch.session.inflight.max_size = 1
    from emqx_tpu.core.message import Message
    first = ch.handle_deliver([("m/t", Message(topic="m/t", qos=1, payload=b"1"))])
    ch.handle_deliver([("m/t", Message(topic="m/t", qos=1, payload=b"2"))])
    nxt = ch.handle_in(P.PubAck(packet_id=first[0].packet_id))
    assert nxt[0].topic == "t"               # unmounted on dequeue too


def test_will_delay_cancelled_by_resume():
    from emqx_tpu.core.message import now_ms
    h = Harness()
    watcher, _ = h.connect("w9")
    watcher.handle_in(P.Subscribe(packet_id=1, topic_filters=[("will/d", {"qos": 0})]))
    ch, _ = h.connect("dev9", clean_start=False, proto=P.MQTT_V5,
                      properties={"Session-Expiry-Interval": 600},
                      will_flag=True, will_topic="will/d", will_payload=b"late",
                      will_props={"Will-Delay-Interval": 30})
    ch.terminate("socket_error")
    assert ch.pending_will_at is not None
    assert watcher.outbox == []                  # withheld
    # resume before the delay fires → will cancelled
    ch2, _ = h.connect("dev9", clean_start=False, proto=P.MQTT_V5,
                       properties={"Session-Expiry-Interval": 600})
    assert ch.pending_will_at is None and ch.will is None
    ch.will_tick(now=now_ms() + 60_000)
    assert all(not isinstance(p, P.Publish) for p in watcher.outbox)


def test_will_delay_fires_when_due():
    from emqx_tpu.core.message import now_ms
    h = Harness()
    watcher, _ = h.connect("w8")
    watcher.handle_in(P.Subscribe(packet_id=1, topic_filters=[("will/f", {"qos": 0})]))
    ch, _ = h.connect("dev8", clean_start=False, proto=P.MQTT_V5,
                      properties={"Session-Expiry-Interval": 600},
                      will_flag=True, will_topic="will/f", will_payload=b"boom",
                      will_props={"Will-Delay-Interval": 1})
    ch.terminate("socket_error")
    ch.will_tick(now=now_ms() + 2000)
    pubs = [p for p in watcher.outbox if isinstance(p, P.Publish)]
    assert [p.payload for p in pubs] == [b"boom"]


# -- round 3 v5 conformance (emqx_mqtt_protocol_v5_SUITE gaps) -----------------

def test_will_delay_capped_by_session_expiry():
    """MQTT5 3.1.2.5: the will fires at the EARLIER of Will-Delay and
    Session-Expiry — a 300s delay with a 5s session expires at ~5s."""
    from emqx_tpu.core.message import now_ms
    h = Harness()
    watcher, _ = h.connect("w-cap")
    watcher.handle_in(P.Subscribe(packet_id=1,
                                  topic_filters=[("will/cap", {"qos": 0})]))
    ch, _ = h.connect("dev-cap", clean_start=False, proto=P.MQTT_V5,
                      properties={"Session-Expiry-Interval": 5},
                      will_flag=True, will_topic="will/cap",
                      will_payload=b"capped",
                      will_props={"Will-Delay-Interval": 300})
    t0 = now_ms()
    ch.terminate("socket_error")
    assert ch.pending_will_at is not None
    assert ch.pending_will_at - t0 <= 5_000 + 500, \
        "will delay not capped by session expiry"
    ch.will_tick(now=t0 + 6_000)
    pubs = [p for p in watcher.outbox if isinstance(p, P.Publish)]
    assert [p.payload for p in pubs] == [b"capped"]


def test_session_expiry_discards_state_and_fires_will():
    """MQTT5 3.1.2-23: the session is discarded when the expiry interval
    elapses; a pending delayed will is published no later than that."""
    from emqx_tpu.core.message import now_ms
    h = Harness()
    watcher, _ = h.connect("w-exp")
    watcher.handle_in(P.Subscribe(packet_id=1,
                                  topic_filters=[("will/e", {"qos": 0})]))
    ch, _ = h.connect("dev-exp", clean_start=False, proto=P.MQTT_V5,
                      properties={"Session-Expiry-Interval": 10},
                      will_flag=True, will_topic="will/e",
                      will_payload=b"gone",
                      will_props={"Will-Delay-Interval": 10})
    ch.handle_in(P.Subscribe(packet_id=2,
                             topic_filters=[("keep/x", {"qos": 1})]))
    t0 = now_ms()
    ch.terminate("socket_error")
    assert ch.session is not None                 # held for resume
    assert not ch.expire_tick(now=t0 + 5_000)     # not yet
    assert ch.expire_tick(now=t0 + 11_000)        # expired
    assert ch.session is None
    assert h.cm.lookup_channel("dev-exp") is None
    # will delivered, subscription state cleaned
    pubs = [p for p in watcher.outbox if isinstance(p, P.Publish)]
    assert [p.payload for p in pubs] == [b"gone"]
    assert not h.broker.subscriber.get("keep/x")
    # a resume AFTER expiry starts a fresh session
    ch2, out2 = h.connect("dev-exp", clean_start=False, proto=P.MQTT_V5,
                          properties={"Session-Expiry-Interval": 10})
    assert out2[0].session_present is False


def test_resume_before_expiry_keeps_session():
    from emqx_tpu.core.message import now_ms
    h = Harness()
    ch, _ = h.connect("dev-r", clean_start=False, proto=P.MQTT_V5,
                      properties={"Session-Expiry-Interval": 600})
    ch.handle_in(P.Subscribe(packet_id=1,
                             topic_filters=[("keep/y", {"qos": 1})]))
    ch.terminate("socket_error")
    assert ch.session_expire_at is not None
    ch2, out2 = h.connect("dev-r", clean_start=False, proto=P.MQTT_V5,
                          properties={"Session-Expiry-Interval": 600})
    assert out2[0].session_present is True
    # the old channel's deadline is inert: its session moved
    assert not ch.expire_tick(now=now_ms() + 10**9)
    assert "keep/y" in ch2.session.subscriptions


def test_subscription_identifiers_on_delivery():
    """MQTT5 3.8.3.1.2/3.3.2.3.8: deliveries carry each matching
    subscription's identifier; overlapping subscriptions with different
    ids produce one packet per subscription, each with its own id."""
    h = Harness()
    sub, _ = h.connect("sid-sub", proto=P.MQTT_V5)
    sub.handle_in(P.Subscribe(
        packet_id=1, topic_filters=[("a/+", {"qos": 0})],
        properties={"Subscription-Identifier": [7]}))
    sub.handle_in(P.Subscribe(
        packet_id=2, topic_filters=[("a/#", {"qos": 0})],
        properties={"Subscription-Identifier": [9]}))
    pub, _ = h.connect("sid-pub", proto=P.MQTT_V5)
    deliveries = h.broker.publish(__import__(
        "emqx_tpu.core.message", fromlist=["Message"]).Message(
            topic="a/x", payload=b"m", from_="sid-pub"))
    out = sub.handle_deliver(deliveries["sid-sub"])
    sids = sorted((p.properties or {}).get(
        "Subscription-Identifier", [None])[0] for p in out
        if isinstance(p, P.Publish))
    assert sids == [7, 9]


def test_receive_maximum_exhaustion_rc_0x93():
    """Flow control: QoS2 receives past the receive-maximum window get
    PUBREC 0x93 (RC_RECEIVE_MAXIMUM_EXCEEDED) until quota frees."""
    h = Harness()
    ch = Channel(h.broker, h.cm, session_opts={"max_awaiting_rel": 2})
    ch.handle_in(P.Connect(clientid="fc", proto_ver=P.MQTT_V5))
    rcs = []
    for pid in (11, 12, 13):
        (rec,) = ch.handle_in(P.Publish(
            topic="f/x", payload=b"q2", qos=2, packet_id=pid))
        rcs.append(rec.reason_code)
    assert rcs[:2] == [0, 0]
    assert rcs[2] == P.RC_RECEIVE_MAXIMUM_EXCEEDED
    # releasing one slot restores quota
    ch.handle_in(P.PubRel(packet_id=11))
    (rec4,) = ch.handle_in(P.Publish(
        topic="f/x", payload=b"q2", qos=2, packet_id=14))
    assert rec4.reason_code == 0
