"""Trace, slow-subs, OLP/GC/congestion, exclusive subscriptions —
the emqx_trace_SUITE / emqx_slow_subs_SUITE / emqx_olp_SUITE /
emqx_exclusive_sub mirror."""

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.broker import ExclusiveLocked
from emqx_tpu.broker.olp import Congestion, GcPolicy, Olp
from emqx_tpu.core.message import Message, SubOpts
from emqx_tpu.observe.alarm import AlarmManager
from emqx_tpu.observe.trace import TraceManager
from emqx_tpu.services.slow_subs import SlowSubs


# -- trace ---------------------------------------------------------------------

def test_trace_by_clientid_records_publish_and_lifecycle():
    app = BrokerApp()
    app.trace.start("t1", "clientid", "dev-1")
    app.broker.publish(Message(topic="a/b", payload=b"x", from_="dev-1"))
    app.broker.publish(Message(topic="a/b", payload=b"y", from_="dev-2"))
    lines = app.trace.log_lines("t1")
    assert len(lines) == 1 and "a/b" in lines[0] and "PUBLISH" in lines[0]


def test_trace_by_topic_wildcard_filter():
    tm = TraceManager()
    tm.start("w", "topic", "room/+/temp")
    tm.trace("PUBLISH", "c1", "room/7/temp", "", "m1")
    tm.trace("PUBLISH", "c1", "hall/temp", "", "m2")
    assert len(tm.log_lines("w")) == 1


def test_trace_scheduled_stop_and_limits():
    tm = TraceManager(max_traces=1)
    tm.start("t", "clientid", "c", duration_s=10)
    with pytest.raises(ValueError):
        tm.start("u", "clientid", "c2")
    tm.tick(now=tm.traces["t"].start_at + 11)
    assert tm.traces["t"].status == "stopped"
    tm.trace("PUBLISH", "c", "x", "", "after-stop")
    assert tm.log_lines("t") == []          # stopped traces record nothing
    assert tm.delete("t") and tm.list() == []


# -- slow subs -----------------------------------------------------------------

def test_slow_subs_topk_and_expiry():
    ss = SlowSubs(threshold_ms=100, top_k=2, expire_interval_s=60)
    ss.record("c1", "t1", 150, now=0)
    ss.record("c2", "t2", 500, now=0)
    ss.record("c3", "t3", 300, now=0)      # evicts c1 (fastest of the slow)
    tops = ss.top()
    assert [(e.clientid, e.latency_ms) for e in tops] == [
        ("c2", 500), ("c3", 300)]
    ss.record("c1", "t1", 50, now=0)       # under threshold → ignored
    assert len(ss) == 2
    assert ss.gc(now=61) == 2 and len(ss) == 0


def test_slow_subs_via_delivery_hook():
    app = BrokerApp()
    app.slow_subs.threshold_ms = 0         # record everything
    app.broker.subscribe("s1", "a/#", SubOpts(qos=0))

    class FakeCh:
        conn_state = "connected"
        def handle_deliver(self, items):
            return []
        def send(self, pkts):
            pass
    app.cm.register_channel("s1", FakeCh())
    app.cm.dispatch(app.broker.publish(Message(topic="a/b", payload=b"x")))
    # the hook fires from the real Channel only; emulate its call here
    app.hooks.run("delivery.completed", ("s1", "a/b", 7))
    assert app.slow_subs.top()[0].clientid == "s1"


# -- olp / gc / congestion -----------------------------------------------------

def test_olp_backoff_after_sustained_lag():
    olp = Olp(backoff_delay_ms=50)
    assert not olp.backoff_new_conn()
    for _ in range(20):
        olp.note_lag(500)
    assert olp.is_overloaded() and olp.backoff_new_conn()
    for _ in range(50):
        olp.note_lag(0)
    assert not olp.is_overloaded()


def test_gc_policy_budgets():
    gp = GcPolicy(count=10, bytes_=10_000)
    assert not gp.note(5, 100)
    assert gp.note(5, 100)                 # count budget exhausted → GC
    assert not gp.note(1, 9_000)
    assert gp.note(1, 2_000)               # bytes budget exhausted → GC
    olp = Olp(backoff_delay_ms=1)
    for _ in range(20):
        olp.note_lag(100)
    assert not gp.note(100, 100, olp)      # overloaded → GC skipped


def test_congestion_alarm_lifecycle():
    alarms = AlarmManager()
    c = Congestion(alarms=alarms, high_watermark=1000, low_watermark=100,
                   min_alarm_sustain_s=1.0)
    c.check("peer:1", 5000, now=0.0)
    assert "peer:1" not in c.congested     # not sustained yet
    c.check("peer:1", 5000, now=1.5)
    assert "peer:1" in c.congested
    assert any(a.name.startswith("conn_congestion/")
               for a in alarms.get_alarms("activated"))
    c.check("peer:1", 50, now=2.0)
    assert ("peer:1" not in c.congested
            and not alarms.get_alarms("activated"))


# -- exclusive subscriptions ---------------------------------------------------

def test_exclusive_subscription_single_holder():
    app = BrokerApp()
    ex = SubOpts(qos=1, exclusive=True)
    app.broker.subscribe("c1", "job/1", ex)
    with pytest.raises(ExclusiveLocked):
        app.broker.subscribe("c2", "job/1", ex)
    # resubscribe by the holder is fine
    app.broker.subscribe("c1", "job/1", SubOpts(qos=0, exclusive=True))
    # non-exclusive subscribers of the same topic are unaffected
    app.broker.subscribe("c9", "job/1", SubOpts(qos=0))
    # release frees the slot
    app.broker.unsubscribe("c1", "job/1")
    app.broker.subscribe("c2", "job/1", ex)
    # subscriber_down releases too
    app.broker.subscriber_down("c2")
    app.broker.subscribe("c3", "job/1", ex)


def test_exclusive_channel_strips_prefix_and_delivers():
    """$exclusive/t subscribes the REAL topic t (emqx_topic.erl:225-230);
    publishes to t reach the exclusive holder; second holder gets 0x97;
    disabled cap → 0x8F."""
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.mqtt import packet as P

    app = BrokerApp()
    sent: list = []
    ch = Channel(app.broker, app.cm, send=sent.extend)
    ch.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="ex1"))
    suback = ch.handle_in(P.Subscribe(
        packet_id=1, topic_filters=[("$exclusive/job/9", {"qos": 1})]))
    assert suback[0].reason_codes == [1]
    ch2 = Channel(app.broker, app.cm, send=lambda p: None)
    ch2.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="ex2"))
    suback2 = ch2.handle_in(P.Subscribe(
        packet_id=1, topic_filters=[("$exclusive/job/9", {"qos": 1})]))
    assert suback2[0].reason_codes == [P.RC_QUOTA_EXCEEDED]
    # delivery arrives on the real topic
    app.cm.dispatch(app.broker.publish(Message(topic="job/9", payload=b"m")))
    assert any(getattr(p, "topic", None) == "job/9" for p in sent)
    # unsubscribe with the $exclusive form releases the lock
    ch.handle_in(P.Unsubscribe(packet_id=2,
                               topic_filters=["$exclusive/job/9"]))
    suback3 = ch2.handle_in(P.Subscribe(
        packet_id=2, topic_filters=[("$exclusive/job/9", {"qos": 1})]))
    assert suback3[0].reason_codes == [1]
    # cap disabled → topic filter invalid (emqx_mqtt_caps:do_check_sub)
    app.broker.exclusive_enabled = False
    suback4 = ch2.handle_in(P.Subscribe(
        packet_id=3, topic_filters=[("$exclusive/other", {"qos": 0})]))
    assert suback4[0].reason_codes == [P.RC_TOPIC_FILTER_INVALID]


# -- sysmon --------------------------------------------------------------------

def test_sysmon_watermarks_and_alarms():
    from emqx_tpu.observe.sysmon import SysMon

    alarms = AlarmManager()
    olp = Olp(backoff_delay_ms=50)
    sm = SysMon(alarms, olp=olp, cpu_high=0.8, mem_high=2.0)  # mem never fires
    readings = sm.check()
    # on Linux /proc is present; at minimum mem+fds read back
    assert "mem" in readings and 0 <= readings["mem"] <= 1
    assert "fds" in readings
    assert not alarms.is_active("high_system_memory_usage")
    # overload signal propagates as an alarm
    for _ in range(20):
        olp.note_lag(500)
    sm.check()
    assert alarms.is_active("runtime_overloaded")
    for _ in range(80):
        olp.note_lag(0)
    sm.check()
    assert not alarms.is_active("runtime_overloaded")
    # interval gating
    assert sm.tick(now=0.0) or True
    sm._last_check = 100.0
    assert not sm.tick(now=100.5)


# -- structured logging (emqx_logger_jsonfmt/textfmt + ?SLOG) ------------------

def test_logfmt_json_and_text():
    import io
    import json as _json
    import logging

    from emqx_tpu.observe.logfmt import setup_logging, slog

    buf = io.StringIO()
    setup_logging(level="info", formatter="json", stream=buf,
                  logger_name="emqx_tpu.testlog")
    slog("warning", "client kicked", logger="emqx_tpu.testlog.cm",
         clientid="c-1", topic="t/1")
    rec = _json.loads(buf.getvalue())
    assert rec["level"] == "warning" and rec["msg"] == "client kicked"
    assert rec["clientid"] == "c-1" and rec["topic"] == "t/1"
    assert rec["logger"] == "emqx_tpu.testlog.cm"

    buf2 = io.StringIO()
    setup_logging(level="debug", formatter="text", stream=buf2,
                  logger_name="emqx_tpu.testlog")
    slog("info", "published", logger="emqx_tpu.testlog", qos=1)
    line = buf2.getvalue()
    assert "[info] published" in line and "qos: 1" in line
    # below-level records are filtered
    buf2.truncate(0), buf2.seek(0)
    logging.getLogger("emqx_tpu.testlog").setLevel(logging.WARNING)
    slog("debug", "noise", logger="emqx_tpu.testlog")
    assert buf2.getvalue() == ""
    # exceptions serialize in both formats
    buf3 = io.StringIO()
    setup_logging(level="info", formatter="json", stream=buf3,
                  logger_name="emqx_tpu.testlog")
    try:
        raise ValueError("boom")
    except ValueError:
        logging.getLogger("emqx_tpu.testlog").exception("crashed")
    assert "boom" in _json.loads(buf3.getvalue())["exception"]


def test_logfmt_config_wiring():
    from emqx_tpu.config.config import Config
    conf = Config()
    conf.init_load('log { level = "info", formatter = "json" }')
    assert conf.get("log.formatter") == "json"


def test_logfmt_file_handler(tmp_path):
    import json as _json

    from emqx_tpu.observe.logfmt import setup_logging, slog
    f = tmp_path / "sub" / "emqx.log"
    setup_logging(level="info", formatter="json", to="file",
                  file_path=str(f), logger_name="emqx_tpu.filelog")
    slog("info", "to disk", logger="emqx_tpu.filelog", n=1)
    rec = _json.loads(f.read_text())
    assert rec["msg"] == "to disk" and rec["n"] == 1
    # reconfigure replaces (no duplicate handlers / leaked fds)
    setup_logging(level="info", formatter="text", to="file",
                  file_path=str(f), logger_name="emqx_tpu.filelog")
    slog("info", "second", logger="emqx_tpu.filelog")
    assert f.read_text().count("second") == 1


def test_slog_reserved_field_names_do_not_crash():
    import io
    import json as _json

    from emqx_tpu.observe.logfmt import setup_logging, slog
    buf = io.StringIO()
    setup_logging(level="info", formatter="json", stream=buf,
                  logger_name="emqx_tpu.rsv")
    # `name`/`module` collide with LogRecord attributes; stdlib would
    # raise KeyError from makeRecord without sanitization
    slog("info", "gateway loaded", logger="emqx_tpu.rsv",
         name="stomp", module="gateway", clientid="c1")
    rec = _json.loads(buf.getvalue())
    assert rec["name_"] == "stomp" and rec["module_"] == "gateway"
    assert rec["clientid"] == "c1"
