"""RouterModel end-to-end: match + fan-out, single-device and on the mesh."""

import numpy as np
import pytest

from emqx_tpu.models.router_model import RouterModel
from emqx_tpu.router.index import TrieIndex
from emqx_tpu.router.trie import Trie


def make_model(mesh=None, n_sub_slots=256):
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=n_sub_slots, K=16, M=32, mesh=mesh)
    m.subscribe("a/+/c", 3)
    m.subscribe("a/#", 3)
    m.subscribe("a/#", 7)
    m.subscribe("x/y", 100)
    m.subscribe("#", 200)
    return m


def test_publish_batch_single_device():
    m = make_model()
    matched, aux, slots, fallback = m.publish_batch(["a/b/c", "x/y", "nope", "$SYS/x"])
    assert fallback == []
    assert sorted(matched[0]) == ["#", "a/#", "a/+/c"]
    assert slots[0] == [3, 7, 200]
    assert sorted(matched[1]) == ["#", "x/y"]
    assert slots[1] == [100, 200]
    assert matched[2] == ["#"] and slots[2] == [200]
    assert matched[3] == [] and slots[3] == []


def test_unsubscribe_updates_fanout():
    m = make_model()
    m.unsubscribe("a/#", 3)
    matched, _aux, slots, _ = m.publish_batch(["a/q"])
    assert sorted(matched[0]) == ["#", "a/#"]
    assert slots[0] == [7, 200]
    m.unsubscribe("a/#", 7)   # last subscriber → filter drops out
    matched, _aux, slots, _ = m.publish_batch(["a/q"])
    assert sorted(matched[0]) == ["#"]


def test_batch_padding_no_phantom_matches():
    m = make_model()
    # 3 topics pad to a 64-bucket; padding rows must match nothing
    matched, _aux, slots, _ = m.publish_batch(["q", "q", "q"])
    assert all(mm == ["#"] for mm in matched)
    assert len(matched) == 3


def test_mesh_sharded_equals_single(rng):
    import jax
    from emqx_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8, shape=(4, 2))
    # W=16 words → shards 8 per device over tp=2
    m1 = make_model(mesh=None, n_sub_slots=512)
    m2 = make_model(mesh=mesh, n_sub_slots=512)
    topics = ["a/b/c", "x/y", "a/zz", "$SYS/x"] * 16
    r1 = m1.publish_batch(topics)
    r2 = m2.publish_batch(topics)
    assert r1[0] == r2[0]
    assert r1[1] == r2[1]
    assert r1[2] == r2[2]


def test_randomized_model_vs_oracle(rng):
    oracle = Trie()
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=1024, K=32, M=64)
    subs: dict[str, set[int]] = {}
    words = ["a", "b", "c"]
    for i in range(300):
        ws = [rng.choice(words + ["+"]) for _ in range(rng.randint(1, 5))]
        if rng.random() < 0.3:
            ws.append("#")
        f = "/".join(ws)
        slot = rng.randrange(1024)
        m.subscribe(f, slot)
        if f not in subs:
            subs[f] = set()
            oracle.insert(f)
        subs[f].add(slot)
    topics = ["/".join(rng.choice(words) for _ in range(rng.randint(1, 6))) for _ in range(128)]
    matched, aux, slots, fallback = m.publish_batch(topics)
    for b, t in enumerate(topics):
        if b in fallback:
            continue
        assert sorted(matched[b]) == sorted(oracle.match(t)), t
        expect_slots = sorted(set().union(*[subs[f] for f in matched[b]]) if matched[b] else set())
        assert slots[b] == expect_slots, t


def test_incremental_deltas_vs_oracle(rng):
    """Randomized subscribe/unsubscribe delta sequences applied AFTER the
    first device build must route identically to the host oracle WITHOUT
    any full rebuild — the emqx_trie.erl:113-144 incremental-maintenance
    contract (VERDICT round-1 item 2)."""
    oracle = Trie()
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=1024, K=32, M=64)
    subs: dict[str, set[int]] = {}
    words = ["a", "b", "c", "d"]

    def rand_filter():
        ws = [rng.choice(words + ["+"]) for _ in range(rng.randint(1, 5))]
        if rng.random() < 0.25:
            ws.append("#")
        return "/".join(ws)

    # seed set → first full build
    for _ in range(100):
        f, slot = rand_filter(), rng.randrange(1024)
        m.subscribe(f, slot)
        if f not in subs:
            subs[f] = set()
            oracle.insert(f)
        subs[f].add(slot)
    m.publish_batch(["a"])              # forces initial build
    base_uploads = m.upload_count
    assert base_uploads >= 1

    topics = ["/".join(rng.choice(words) for _ in range(rng.randint(1, 6)))
              for _ in range(64)]
    for _round in range(8):
        # a chunk of random deltas: inserts + deletes
        for _ in range(20):
            if subs and rng.random() < 0.45:
                f = rng.choice(sorted(subs))
                slot = rng.choice(sorted(subs[f]))
                m.unsubscribe(f, slot)
                subs[f].discard(slot)
                if not subs[f]:
                    del subs[f]
                    oracle.delete(f)
            else:
                f, slot = rand_filter(), rng.randrange(1024)
                m.subscribe(f, slot)
                if f not in subs:
                    subs[f] = set()
                    oracle.insert(f)
                subs[f].add(slot)
        matched, aux, slots, fallback = m.publish_batch(topics)
        for b, t in enumerate(topics):
            if b in fallback:
                continue
            assert sorted(matched[b]) == sorted(oracle.match(t)), t
            expect = sorted(set().union(
                *[subs[f] for f in matched[b]]) if matched[b] else set())
            assert slots[b] == expect, t
    # the whole churn went through incremental scatters, not rebuilds
    assert m.upload_count == base_uploads
    assert m.patch_count >= 8


def test_incremental_growth_triggers_rebuild():
    """Node-capacity exhaustion flips needs_rebuild and the next publish
    does one clean double-buffered upload."""
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64, K=16, M=32)
    m.subscribe("seed/x", 1)
    m.publish_batch(["seed/x"])
    uploads0 = m.upload_count
    # pile on distinct filters until the headroom runs out
    for i in range(3000):
        m.subscribe(f"grow/{i}/leaf", i % 64)
    matched, _aux, _, _ = m.publish_batch(["grow/2999/leaf"])
    assert matched[0] == ["grow/2999/leaf"]
    assert m.upload_count > uploads0            # grew via full rebuild
    matched, _aux, _, _ = m.publish_batch(["seed/x"])
    assert matched[0] == ["seed/x"]


def test_incremental_filter_reinsert_after_delete(rng):
    """Delete then re-insert of the same filter (fid reuse) must route
    correctly through the incremental path."""
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64, K=16, M=32)
    m.subscribe("a/b", 1)
    m.subscribe("c/d", 2)
    m.publish_batch(["a/b"])
    m.unsubscribe("a/b", 1)             # filter drops out, fid freed
    matched, _aux, _, _ = m.publish_batch(["a/b"])
    assert matched[0] == []
    m.subscribe("e/f", 3)               # likely reuses the freed fid
    m.subscribe("a/b", 4)
    matched, _aux, slots, _ = m.publish_batch(["a/b", "e/f", "c/d"])
    assert matched[0] == ["a/b"] and slots[0] == [4]
    assert matched[1] == ["e/f"] and slots[1] == [3]
    assert matched[2] == ["c/d"] and slots[2] == [2]


def test_dense_pool_promotion_and_demotion(rng):
    """A filter crossing dense_threshold moves into the device pool and
    back out; routing stays exact through both transitions (the
    emqx_broker_helper >1024-subscriber shard-split analogue)."""
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=512, K=16, M=32,
                    dense_threshold=16)
    for s in range(40):                      # degree 40 > threshold 16
        m.subscribe("hot/topic", s)
    m.subscribe("cold/topic", 7)
    matched, _aux, slots, _ = m.publish_batch(["hot/topic", "cold/topic"])
    fid = m.index.fid_of("hot/topic")
    assert fid in m._dense_row               # promoted
    assert matched[0] == ["hot/topic"] and slots[0] == list(range(40))
    assert matched[1] == ["cold/topic"] and slots[1] == [7]
    # drain below threshold//2 → demotion
    for s in range(36):
        m.unsubscribe("hot/topic", s)
    assert fid not in m._dense_row           # demoted
    matched, _aux, slots, _ = m.publish_batch(["hot/topic"])
    assert slots[0] == [36, 37, 38, 39]
    # pool row was freed and zeroed: a new hot filter reusing it must
    # not inherit stale bits
    for s in range(100, 120):
        m.subscribe("hot2/t", s)
    matched, _aux, slots, _ = m.publish_batch(["hot2/t"])
    assert slots[0] == list(range(100, 120))


def test_hybrid_randomized_vs_oracle(rng):
    """Randomized churn crossing the dense threshold in both directions
    must stay equivalent to the host oracle."""
    oracle = Trie()
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=256, K=32, M=64,
                    dense_threshold=8)
    subs: dict[str, dict[int, int]] = {}
    words = ["a", "b", "c"]

    def rand_filter():
        ws = [rng.choice(words + ["+"]) for _ in range(rng.randint(1, 4))]
        if rng.random() < 0.3:
            ws.append("#")
        return "/".join(ws)

    for _round in range(6):
        for _ in range(60):
            if subs and rng.random() < 0.4:
                f = rng.choice(sorted(subs))
                slot = rng.choice(sorted(subs[f]))
                m.unsubscribe(f, slot)
                subs[f][slot] -= 1
                if subs[f][slot] == 0:
                    del subs[f][slot]
                if not subs[f]:
                    del subs[f]
                    oracle.delete(f)
            else:
                f, slot = rand_filter(), rng.randrange(256)
                m.subscribe(f, slot)
                if f not in subs:
                    subs[f] = {}
                    oracle.insert(f)
                subs[f][slot] = subs[f].get(slot, 0) + 1
        topics = ["/".join(rng.choice(words)
                           for _ in range(rng.randint(1, 5)))
                  for _ in range(64)]
        matched, aux, slots, fallback = m.publish_batch(topics)
        for b, t in enumerate(topics):
            if b in fallback:
                continue
            assert sorted(matched[b]) == sorted(oracle.match(t)), t
            expect = sorted(set().union(
                *[subs[f].keys() for f in matched[b]])
                if matched[b] else set())
            assert slots[b] == expect, t


def test_fixed_slot_space_at_scale():
    """Many more subscribers than slots: the shard space stays fixed and
    device structures don't grow with subscriber count (BASELINE
    config 3's 10M-sub regime in miniature)."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.core.message import Message

    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64, K=16,
                        M=32, dense_threshold=16)
    b = Broker(router_model=model)
    n = 500                                # >> 64 slots
    for i in range(n):
        b.subscribe(f"c{i}", "bcast/all")
        b.subscribe(f"c{i}", f"own/c{i}")
    assert b.slots.capacity == 64
    # pool holds exactly the one hot filter; inline rows cover the rest
    assert len(model._dense_row) == 1
    deliveries = b.publish_batch(
        [Message(topic="bcast/all", payload=b"x"),
         Message(topic="own/c123", payload=b"y")])
    assert len(deliveries[0]) == n          # every client got the bcast
    assert set(deliveries[1]) == {"c123"}   # sharded slot decode exact


def test_inflight_fid_quarantine_prevents_wrong_delivery():
    """submit/collect split: a fid freed while a batch is in flight must
    not be REUSED before the batch decodes — reuse would decode the old
    topic's match as the new filter (wrong-subscriber delivery)."""
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64, K=16,
                        M=32)
    model.subscribe("old/topic", 3)
    model.refresh()
    pending = model.publish_batch_submit(["old/topic"])
    # while in flight: the old filter goes away and a new one arrives
    model.unsubscribe("old/topic", 3)
    old_fid = None
    new_fid = model.subscribe("new/topic", 5)
    matched, _aux, slots, fallback = model.publish_batch_collect(pending)
    # the raced unsubscribe drops the leg; it must NOT become new/topic
    assert matched[0] in ([], ["old/topic"])
    assert "new/topic" not in matched[0]
    # the freed fid is only reusable AFTER collect
    assert model.index._inflight == 0
    f2 = model.publish_batch(["new/topic"])
    assert f2[0][0] == ["new/topic"] and f2[2][0] == [5]
