"""RouterModel end-to-end: match + fan-out, single-device and on the mesh."""

import numpy as np
import pytest

from emqx_tpu.models.router_model import RouterModel
from emqx_tpu.router.index import TrieIndex
from emqx_tpu.router.trie import Trie


def make_model(mesh=None, n_sub_slots=256):
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=n_sub_slots, K=16, M=32, mesh=mesh)
    m.subscribe("a/+/c", 3)
    m.subscribe("a/#", 3)
    m.subscribe("a/#", 7)
    m.subscribe("x/y", 100)
    m.subscribe("#", 200)
    return m


def test_publish_batch_single_device():
    m = make_model()
    matched, slots, fallback = m.publish_batch(["a/b/c", "x/y", "nope", "$SYS/x"])
    assert fallback == []
    assert sorted(matched[0]) == ["#", "a/#", "a/+/c"]
    assert slots[0] == [3, 7, 200]
    assert sorted(matched[1]) == ["#", "x/y"]
    assert slots[1] == [100, 200]
    assert matched[2] == ["#"] and slots[2] == [200]
    assert matched[3] == [] and slots[3] == []


def test_unsubscribe_updates_fanout():
    m = make_model()
    m.unsubscribe("a/#", 3)
    matched, slots, _ = m.publish_batch(["a/q"])
    assert sorted(matched[0]) == ["#", "a/#"]
    assert slots[0] == [7, 200]
    m.unsubscribe("a/#", 7)   # last subscriber → filter drops out
    matched, slots, _ = m.publish_batch(["a/q"])
    assert sorted(matched[0]) == ["#"]


def test_batch_padding_no_phantom_matches():
    m = make_model()
    # 3 topics pad to a 64-bucket; padding rows must match nothing
    matched, slots, _ = m.publish_batch(["q", "q", "q"])
    assert all(mm == ["#"] for mm in matched)
    assert len(matched) == 3


def test_mesh_sharded_equals_single(rng):
    import jax
    from emqx_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8, shape=(4, 2))
    # W=16 words → shards 8 per device over tp=2
    m1 = make_model(mesh=None, n_sub_slots=512)
    m2 = make_model(mesh=mesh, n_sub_slots=512)
    topics = ["a/b/c", "x/y", "a/zz", "$SYS/x"] * 16
    r1 = m1.publish_batch(topics)
    r2 = m2.publish_batch(topics)
    assert r1[0] == r2[0]
    assert r1[1] == r2[1]
    assert r1[2] == r2[2]


def test_randomized_model_vs_oracle(rng):
    oracle = Trie()
    m = RouterModel(TrieIndex(max_levels=8), n_sub_slots=1024, K=32, M=64)
    subs: dict[str, set[int]] = {}
    words = ["a", "b", "c"]
    for i in range(300):
        ws = [rng.choice(words + ["+"]) for _ in range(rng.randint(1, 5))]
        if rng.random() < 0.3:
            ws.append("#")
        f = "/".join(ws)
        slot = rng.randrange(1024)
        m.subscribe(f, slot)
        if f not in subs:
            subs[f] = set()
            oracle.insert(f)
        subs[f].add(slot)
    topics = ["/".join(rng.choice(words) for _ in range(rng.randint(1, 6))) for _ in range(128)]
    matched, slots, fallback = m.publish_batch(topics)
    for b, t in enumerate(topics):
        if b in fallback:
            continue
        assert sorted(matched[b]) == sorted(oracle.match(t)), t
        expect_slots = sorted(set().union(*[subs[f] for f in matched[b]]) if matched[b] else set())
        assert slots[b] == expect_slots, t
