"""Persistent sessions + replayq — mirrors emqx_persistent_session_SUITE
(resume/replay/GC) and the replayq disk-queue contract."""

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.channel import Channel
from emqx_tpu.core.message import Message, SubOpts
from emqx_tpu.mqtt import packet as P
from emqx_tpu.session.persistent import (
    DummyStore, MemStore, NativeDurableStore, PersistentSessions,
    SessionRouter,
)
from emqx_tpu.utils.replayq import ReplayQ


# -- replayq ----------------------------------------------------------------

def test_replayq_mem_fifo():
    q = ReplayQ(mem_only=True)
    q.append([b"a", b"b", b"c"])
    ref, items = q.pop(2)
    assert items == [b"a", b"b"]
    q.ack(ref)
    assert q.pop(5)[1] == [b"c"]
    assert q.count() == 1


def test_replayq_disk_survives_reopen(tmp_path):
    d = str(tmp_path / "q")
    q = ReplayQ(d)
    q.append([b"one", b"two", b"three"])
    ref, items = q.pop(1)
    q.ack(ref)                       # consume "one"
    q.close()
    q2 = ReplayQ(d)
    assert q2.pop(10)[1] == [b"two", b"three"]


def test_replayq_ack_persists_across_segments(tmp_path):
    d = str(tmp_path / "q")
    q = ReplayQ(d, seg_bytes=16)     # force several segments
    q.append([bytes([65 + i]) * 10 for i in range(6)])
    ref, _ = q.pop(4)
    q.ack(ref)
    q2 = ReplayQ(d)
    assert q2.count() == 2
    assert q2.pop(10)[1] == [b"E" * 10, b"F" * 10]


def test_replayq_append_after_full_drain_survives_reopen(tmp_path):
    d = str(tmp_path / "q")
    q = ReplayQ(d)
    q.append([b"a"])
    ref, _ = q.pop(1)
    q.ack(ref)                       # queue fully drained
    q.append([b"b"])                 # must not land below the ack point
    q2 = ReplayQ(d)
    assert q2.pop(10)[1] == [b"b"]


def test_replayq_overflow_drops_new():
    q = ReplayQ(mem_only=True, max_total_bytes=10)
    assert q.append([b"12345", b"67890", b"xxxxx"]) == 2
    assert q.dropped == 1


# -- session router ---------------------------------------------------------

def test_session_router_exact_and_wildcard():
    r = SessionRouter()
    r.add_route("a/b", "s1")
    r.add_route("a/+", "s2")
    r.add_route("a/#", "s3")
    assert r.match("a/b") == {"s1", "s2", "s3"}
    assert r.match("a/c") == {"s2", "s3"}
    r.delete_route("a/+", "s2")
    assert r.match("a/c") == {"s3"}


# -- stores -----------------------------------------------------------------

def test_store_marker_lifecycle(tmp_path):
    s = MemStore()
    s.put_session("c1", {"subs": {"a/+": {}}, "ts": 0})
    s.put_message(7, {"topic": "a/b"})
    s.put_marker("c1", 7, "a/+")
    assert s.pending("c1") == [(7, "a/+")]
    s.consume_marker("c1", 7)
    assert s.pending("c1") == []
    assert s.gc_messages() == 1
    assert 7 not in s.messages


def test_native_store_persist_drain_lifecycle(tmp_path):
    """The unified backend (round 18): persist() writes message +
    markers into the ONE native store; drain() fetches + consumes."""
    s = NativeDurableStore(str(tmp_path / "ps"))
    s.put_session("c1", {"subs": {"a/+": {}}, "ts": 0})
    m = Message(topic="a/b", payload=b"x", qos=1, from_="pub")
    assert s.persist(m, ["c1"]) == 1
    assert s.native.pending(s.native.lookup("c1")) == 1
    rows = s.drain("c1")
    assert len(rows) == 1
    guid, _origin, _ts, qos, _dup, topic, body, _trace, cid = rows[0]
    assert (qos, topic, body, cid) == (1, "a/b", b"x", "pub")
    assert s.native.pending(s.native.lookup("c1")) == 0
    s.close()


def test_native_store_replays_after_reopen(tmp_path):
    d = str(tmp_path / "ps")
    s = NativeDurableStore(d)
    s.put_session("c1", {"subs": {"t": {"qos": 1}}, "ts": 1})
    s.persist(Message(topic="t", payload=b"m", qos=1), ["c1"])
    s.close()
    s2 = NativeDurableStore(d)
    assert s2.get_session("c1")["subs"] == {"t": {"qos": 1}}
    rows = s2.drain("c1")
    assert [(r[5], r[6]) for r in rows] == [("t", b"m")]
    s2.close()


def test_native_store_consume_on_settle_survives_reopen(tmp_path):
    """A consumed (settled) marker stays consumed across reopen; an
    unconsumed one replays."""
    d = str(tmp_path / "ps")
    s = NativeDurableStore(d)
    s.put_session("c1", {"subs": {"t": {}}, "ts": 0})
    m1 = Message(topic="t", payload=b"acked", qos=1)
    m2 = Message(topic="t", payload=b"unacked", qos=1)
    s.persist(m1, ["c1"])
    s.persist(m2, ["c1"])
    s.consume_marker("c1", m1.id)        # the settle seam's spend
    s.close()
    s2 = NativeDurableStore(d)
    rows = s2.drain("c1")
    assert [r[6] for r in rows] == [b"unacked"]
    s2.close()


def test_disk_store_log_boot_migrates_once(tmp_path):
    """A pre-round-18 JSON sessions.log folds into native records at
    boot, exactly once (renamed .migrated)."""
    import json as _json
    import os as _os
    sess_dir = tmp_path / "ps" / "sessions"
    sess_dir.mkdir(parents=True)
    log = sess_dir / "sessions.log"
    m = Message(topic="t", payload=b"old", qos=1)
    from emqx_tpu.session.persistent import msg_to_dict
    ops = [
        {"op": "sess", "sid": "c1", "rec": {"subs": {"t": {"qos": 1}},
                                            "ts": 1}},
        {"op": "msg", "guid": m.id, "m": msg_to_dict(m)},
        {"op": "mark", "sid": "c1", "guid": m.id, "st": "t"},
    ]
    log.write_text("\n".join(_json.dumps(o) for o in ops) + "\n")
    s = NativeDurableStore(str(tmp_path / "ps"))
    assert s.get_session("c1")["subs"] == {"t": {"qos": 1}}
    rows = s.drain("c1")
    assert [r[6] for r in rows] == [b"old"]
    assert not _os.path.exists(str(log))
    assert _os.path.exists(str(log) + ".migrated")
    s.close()
    # second boot: no re-migration (markers were consumed by the drain)
    s2 = NativeDurableStore(str(tmp_path / "ps"))
    assert s2.drain("c1") == []
    assert s2.get_session("c1") is not None
    s2.close()


def test_dummy_store_remembers_nothing():
    s = DummyStore()
    s.put_session("c1", {"subs": {}})
    s.put_message(1, {})
    s.put_marker("c1", 1, "t")
    assert s.get_session("c1") is None
    assert s.pending("c1") == []


# -- service-level persist/resume -------------------------------------------

def _mkmsg(topic, payload=b"x", **kw):
    return Message(topic=topic, payload=payload, **kw)


def test_persist_message_stores_one_marker_per_session():
    ps = PersistentSessions(MemStore())
    ps.router.add_route("a/+", "c1")
    ps.router.add_route("a/b", "c2")
    m = _mkmsg("a/b")
    assert ps.persist_message(m) == 2
    assert ps.store.pending("c1") == [(m.id, "a/+")]


def test_resume_replays_in_publish_order():
    ps = PersistentSessions(MemStore())
    ps.router.add_route("t", "c1")
    m1, m2 = _mkmsg("t", b"1"), _mkmsg("t", b"2")
    ps.persist_message(m1)
    ps.persist_message(m2)
    subs, pending = ps.resume("c1")
    assert [m.payload for m in pending] == [b"1", b"2"]
    # markers consumed: a second resume replays nothing
    assert ps.resume("c1")[1] == []


def test_resume_merges_native_drain_by_timestamp_and_id():
    """The native durable plane's seam (round 10): resume merges the
    below-the-GIL store's pending set into the Python store's, deduped
    by message id (a takeover may already hold a live-dispatched copy)
    and ordered by timestamp across both sources."""
    ps = PersistentSessions(MemStore())
    ps.router.add_route("t", "c1")
    py_msg = _mkmsg("t", b"py", timestamp=200)
    ps.persist_message(py_msg)
    nat_old = _mkmsg("t", b"nat-old", id=(1 << 60) + 1, timestamp=100)
    nat_dup = _mkmsg("t", b"dup", id=py_msg.id, timestamp=150)
    drained = []

    def drain(sid):
        drained.append(sid)
        return [nat_old, nat_dup]

    ps.native_drain = drain
    _subs, pending = ps.resume("c1")
    assert drained == ["c1"]
    assert [m.payload for m in pending] == [b"nat-old", b"py"]


def test_discard_drops_native_markers_too():
    ps = PersistentSessions(MemStore())
    ps.router.add_route("t", "c1")
    dropped = []
    ps.native_discard = dropped.append
    ps.discard("c1")
    assert dropped == ["c1"]


def test_gc_session_expiry_cap(monkeypatch):
    """durable.session_expiry caps every stored session's retention:
    a session with a week-long expiry is discarded once the operator
    bound elapses."""
    ps = PersistentSessions(MemStore())
    ps.store.put_session("c1", {"subs": {}, "ts": 0})
    ps.note_disconnected("c1", expiry_ms=7 * 86400 * 1000, now=1000)
    ps.session_expiry_cap_ms = 10_000
    ps.gc(now=12_000)
    assert ps.lookup("c1") is None


def test_gc_drops_expired_sessions():
    ps = PersistentSessions(MemStore())
    ps.store.put_session("c1", {"subs": {"t": {}}, "ts": 0})
    ps.router.add_route("t", "c1")
    ps.note_disconnected("c1", expiry_ms=1000, now=1_000_000)
    ps.gc(now=1_000_500)
    assert ps.lookup("c1") is not None
    ps.gc(now=1_002_000)
    assert ps.lookup("c1") is None
    assert ps.router.match("t") == set()


# -- end-to-end: broker restart resume --------------------------------------

class Client:
    """Packet-level client bound to an app (the emqtt stand-in)."""

    def __init__(self, app, clientid, **connect_kw):
        self.app = app
        self.ch = Channel(app.broker, app.cm)
        self.out = self.ch.handle_in(P.Connect(
            clientid=clientid, proto_ver=P.MQTT_V5, **connect_kw))

    def subscribe(self, topic, qos=1):
        return self.ch.handle_in(P.Subscribe(
            packet_id=1, topic_filters=[(topic, {"qos": qos})]))

    def publish(self, topic, payload, qos=1, pid=10):
        return self.ch.handle_in(P.Publish(
            topic=topic, payload=payload, qos=qos, packet_id=pid))


def _app(tmp_path):
    return BrokerApp(
        persistent_store=NativeDurableStore(str(tmp_path / "ps")))


def _ack_all(client):
    """Acknowledge every qos1 delivery sitting in the client's window —
    with consume-on-ack (round 18) only the ACK spends the replay
    marker; an unacked delivery deliberately replays after restart."""
    for pid, _entry in client.ch.session.inflight.items():
        client.ch.handle_in(P.PubAck(packet_id=pid))


def test_restart_resume_replays_offline_messages(tmp_path):
    app1 = _app(tmp_path)
    sub = Client(app1, "sub1",
                 properties={"Session-Expiry-Interval": 3600})
    sub.subscribe("news/+")
    # publisher on the same node
    pub = Client(app1, "pub1")
    pub.publish("news/a", b"while-up", qos=1)
    # delivered live AND ACKED → marker settled; now the node "crashes"
    _ack_all(sub)
    app1.persistent.store.close()

    # a second node boots on the same store: only subscriptions survive
    app2 = _app(tmp_path)
    # messages published while sub1's node is gone
    pub2 = Client(app2, "pub2")
    pub2.publish("news/b", b"while-down", qos=1)

    sub2 = Client(app2, "sub1", clean_start=False,
                  properties={"Session-Expiry-Interval": 3600})
    connack = sub2.out[0]
    assert connack.session_present is True
    # the offline message replays; the live-delivered one does not
    pubs = [p for p in sub2.out if isinstance(p, P.Publish)]
    assert [p.payload for p in pubs] == [b"while-down"]
    assert pubs[0].topic == "news/b"
    # subscription itself was restored into the broker
    deliveries = app2.broker.publish(_mkmsg("news/c", b"live"))
    assert "sub1" in deliveries


def test_reconnect_cancels_expiry_clock(tmp_path):
    app = _app(tmp_path)
    c = Client(app, "c1", properties={"Session-Expiry-Interval": 1})
    c.subscribe("t")
    c.ch.terminate("sock_closed")           # starts the expiry clock
    # reconnect (takeover) well before expiry, then stay connected
    c2 = Client(app, "c1", clean_start=False,
                properties={"Session-Expiry-Interval": 1})
    assert c2.ch.conn_state == "connected"
    rec = app.persistent.lookup("c1")
    assert rec is not None and rec.get("disconnected_at") is None
    app.persistent.gc(now=Message(topic="x").timestamp + 10_000_000)
    assert app.persistent.lookup("c1") is not None


def test_takeover_consumes_stored_markers(tmp_path):
    app = _app(tmp_path)
    sub = Client(app, "s1", properties={"Session-Expiry-Interval": 3600})
    sub.subscribe("t")
    sub.ch.terminate("sock_closed")
    pub = Client(app, "p1")
    pub.publish("t", b"offline", qos=1)
    store = app.persistent.store
    tok = store.native.lookup("s1")
    assert store.native.pending(tok) == 1              # marker stored
    sub2 = Client(app, "s1", clean_start=False,
                  properties={"Session-Expiry-Interval": 3600})
    pubs = [p for p in sub2.out if isinstance(p, P.Publish)]
    assert [p.payload for p in pubs] == [b"offline"]   # delivered once
    assert store.native.pending(tok) == 0              # marker consumed


def test_restart_resume_does_not_resend_retained(tmp_path):
    app1 = _app(tmp_path)
    pub = Client(app1, "p1")
    pub.publish("t", b"retained-payload", qos=0, pid=None)
    app1.broker.publish(Message(topic="t", payload=b"r",
                                flags={"retain": True}))
    sub = Client(app1, "s1", properties={"Session-Expiry-Interval": 3600})
    out = sub.subscribe("t")
    app1.persistent.store.close()
    app2 = _app(tmp_path)
    sub2 = Client(app2, "s1", clean_start=False,
                  properties={"Session-Expiry-Interval": 3600})
    # resume is not a SUBSCRIBE: the retained message must not replay
    assert not [p for p in sub2.out if isinstance(p, P.Publish)
                and p.retain]


def test_clean_start_wipes_stored_session(tmp_path):
    app1 = _app(tmp_path)
    sub = Client(app1, "c1", properties={"Session-Expiry-Interval": 3600})
    sub.subscribe("t")
    app1.persistent.store.close()

    app2 = _app(tmp_path)
    c = Client(app2, "c1", clean_start=True)
    assert c.out[0].session_present is False
    assert app2.persistent.lookup("c1") is None
