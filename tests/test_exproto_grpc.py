"""ExProto over real gRPC: the emqx.exproto.v1 ConnectionHandler
(broker→service event streams) + ConnectionAdapter (service→broker
unary ops) against a grpcio protocol-handler host — the
emqx_exproto_SUITE / exproto_echo_svr analogue on the actual wire
(apps/emqx_gateway/src/exproto/protos/exproto.proto)."""

import asyncio
import time

import pytest

grpc = pytest.importorskip("grpc")

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.gateway.exproto_grpc import (RC_DENY, RC_NOT_ALIVE,
                                           RC_SUCCESS, AdapterClient,
                                           GrpcExprotoGateway,
                                           GrpcProtocolHandlerHost)
from emqx_tpu.mqtt.client import MqttClient


class LineProtocol:
    """'AUTH <id>' / 'SUB <t>' / 'PUB <t> <msg>' over the adapter;
    deliveries come back as 'MSG <t> <payload>' lines."""

    def __init__(self):
        self.conninfos = {}

    def on_socket_created(self, conn, conninfo, adapter):
        self.conninfos[conn] = conninfo

    def on_received_bytes(self, conn, data, adapter):
        line = data.decode().strip()
        verb, _, rest = line.partition(" ")
        if verb == "AUTH":
            code, _m = adapter.authenticate(conn, clientid=rest)
            adapter.send(conn, b"OK\n" if code == RC_SUCCESS else b"NO\n")
        elif verb == "SUB":
            adapter.subscribe(conn, rest, qos=0)
            adapter.send(conn, b"OK\n")
        elif verb == "PUB":
            t, _, payload = rest.partition(" ")
            adapter.publish(conn, t, payload.encode())
        elif verb == "QUIT":
            adapter.close(conn)
        else:
            adapter.send(conn, b"ERR\n")

    def on_received_messages(self, conn, messages, adapter):
        for m in messages:
            adapter.send(
                conn,
                b"MSG %s %s\n" % (m["topic"].encode(), m["payload"]))


def test_exproto_grpc_end_to_end():
    async def main():
        impl = LineProtocol()
        host = GrpcProtocolHandlerHost(impl).start()
        app = BrokerApp()
        gw = app.gateway.load(GrpcExprotoGateway(
            handler_port=host.port, port=0))
        await gw.start_listeners()
        host.connect_adapter("127.0.0.1", gw.adapter.port)
        srv = BrokerServer(port=0, app=app)
        await srv.start()
        try:
            r, w = await asyncio.open_connection("127.0.0.1", gw.port)
            w.write(b"AUTH dev-g1\n")
            assert await asyncio.wait_for(r.readline(), 5) == b"OK\n"
            w.write(b"SUB alerts/#\n")
            assert await asyncio.wait_for(r.readline(), 5) == b"OK\n"

            mq = MqttClient(port=srv.port, clientid="m1")
            await mq.connect()
            await mq.subscribe("from-device/#")
            # device → broker over adapter Publish
            w.write(b"PUB from-device/g1 ping\n")
            got = await mq.recv()
            assert got.topic == "from-device/g1"
            assert got.payload == b"ping"
            # broker → device via OnReceivedMessages stream + Send
            await mq.publish("alerts/red", b"evacuate")
            line = await asyncio.wait_for(r.readline(), 5)
            assert line == b"MSG alerts/red evacuate\n"
            # OnSocketCreated carried the REAL peer address
            ci = next(iter(impl.conninfos.values()))
            peer = ci.get("peername") or {}
            assert peer.get("host") == "127.0.0.1"
            assert peer.get("port", 0) > 0
            # adapter Close drops the transport
            w.write(b"QUIT\n")
            assert await asyncio.wait_for(r.read(), 5) == b""
            await mq.close()
        finally:
            await gw.stop_listeners()
            await srv.stop()
            host.stop()

    asyncio.run(main())


def test_adapter_codes_and_auth_gating():
    """Adapter semantics: unknown conn → CONN_PROCESS_NOT_ALIVE;
    publish before authenticate → PERMISSION_DENY; missing clientid →
    REQUIRED_PARAMS_MISSED class errors."""
    async def main():
        host = GrpcProtocolHandlerHost(LineProtocol()).start()
        app = BrokerApp()
        gw = app.gateway.load(GrpcExprotoGateway(
            handler_port=host.port, port=0))
        await gw.start_listeners()
        host.connect_adapter("127.0.0.1", gw.adapter.port)
        try:
            adapter = AdapterClient("127.0.0.1", gw.adapter.port)
            code, msg = adapter.send("no-such-conn", b"x")
            assert code == RC_NOT_ALIVE, (code, msg)

            # open a raw connection to mint a live conn ref
            r, w = await asyncio.open_connection("127.0.0.1", gw.port)
            w.write(b"hello")                  # any bytes spin the channel
            await asyncio.sleep(0.3)
            (conn_ref,) = list(gw.adapter.channels)
            code, _ = adapter.publish(conn_ref, "t", b"x")
            assert code == RC_DENY             # not authenticated yet
            code, _ = adapter.authenticate(conn_ref, clientid="")
            assert code != RC_SUCCESS          # clientid required
            code, _ = adapter.authenticate(conn_ref, clientid="dev-a")
            assert code == RC_SUCCESS
            code, _ = adapter.publish(conn_ref, "t", b"x")
            assert code == RC_SUCCESS
            adapter.close_channel()
            w.close()
        finally:
            await gw.stop_listeners()
            host.stop()

    asyncio.run(main())


def test_banned_clientid_denied_via_adapter():
    """ctx.authenticate folds the broker's access control: a banned
    clientid gets PERMISSION_DENY through the adapter."""
    async def main():
        host = GrpcProtocolHandlerHost(LineProtocol()).start()
        app = BrokerApp()
        app.access.banned.create("clientid", "evil-dev")
        gw = app.gateway.load(GrpcExprotoGateway(
            handler_port=host.port, port=0))
        await gw.start_listeners()
        host.connect_adapter("127.0.0.1", gw.adapter.port)
        try:
            r, w = await asyncio.open_connection("127.0.0.1", gw.port)
            w.write(b"AUTH evil-dev\n")
            assert await asyncio.wait_for(r.readline(), 5) == b"NO\n"
            w.close()
        finally:
            await gw.stop_listeners()
            host.stop()

    asyncio.run(main())
