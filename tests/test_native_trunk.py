"""Native cluster trunk (ISSUE 4): cross-node publish forwarding on the
C++ plane.

Two native hosts on loopback talk trunk records to each other
(native/src/trunk.h wire format): QoS0/1 parity against the Python
``forward_fn`` oracle lane, per-topic ordering across batch flushes,
the degradation ladder (trunk → punt → Python) across a link kill with
reconnect-replay proving zero QoS1 forward loss, receiver-side punts
for non-native local audiences, and route add/remove races.
"""

import asyncio
import socket
import struct
import threading
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp                              # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer    # noqa: E402
from emqx_tpu.cluster.node import ClusterNode                   # noqa: E402
from emqx_tpu.cluster.transport import LocalBus                 # noqa: E402
from emqx_tpu.core.message import Message                       # noqa: E402
from emqx_tpu.mqtt.client import MqttClient                     # noqa: E402


def run(main):
    asyncio.run(main())


def _wait(pred, timeout=8.0, step=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return False


class _TrunkedPair:
    """Two ClusterNodes on a LocalBus, each fronted by a native server;
    ``trunk=True`` opens trunk listeners and lets hello/ping wire the
    links (the product path), ``trunk=False`` is the Python-oracle
    topology (remote routes stay punt markers, forward_fn carries)."""

    def __init__(self, trunk: bool, suffix: str):
        self.fabric = LocalBus.Fabric()
        self.nodes = []
        self.servers = []
        for name in (f"nA{suffix}", f"nB{suffix}"):
            node = ClusterNode(name, LocalBus(name, self.fabric))
            srv = NativeBrokerServer(
                port=0, app=node.app,
                trunk_port=0 if trunk else None)
            if trunk:
                node.attach_native(srv)
            srv.start()
            self.nodes.append(node)
            self.servers.append(srv)
        self.nodes[1].join([self.nodes[0].name])

    @property
    def a(self):
        return self.servers[0]

    @property
    def b(self):
        return self.servers[1]

    def sync(self):
        for n in self.nodes:
            n.flush()

    def wait_trunks_up(self, timeout=8.0):
        def both_up():
            return (self.a.trunk_peer_status().get(self.nodes[1].name)
                    and self.b.trunk_peer_status().get(self.nodes[0].name))
        assert _wait(both_up, timeout), (
            self.a.trunk_peer_status(), self.b.trunk_peer_status())

    def stop(self):
        for s in self.servers:
            s.stop()
        for n in self.nodes:
            n.transport.close()


def _drive_cross_node(pair, topic_fmt, payloads, qos, settle=0.35):
    """Subscriber on node B, publisher on node A; returns the received
    (topic, payload) list in arrival order."""
    got = []

    async def main():
        sub = MqttClient(port=pair.b.port, clientid="xsub")
        await sub.connect()
        await sub.subscribe(topic_fmt.replace("{i}", "+"), qos=qos)
        pair.sync()                    # replicate the route to node A
        pub = MqttClient(port=pair.a.port, clientid="xpub")
        await pub.connect()
        # first publish rides the Python lane and earns the permit
        await pub.publish(topic_fmt.replace("{i}", "0"), b"warm", qos=qos)
        m = await sub.recv(timeout=8)
        got.append((m.topic, m.payload))
        await asyncio.sleep(settle)    # permit grants on an idle step
        for i, p in enumerate(payloads):
            await pub.publish(topic_fmt.replace("{i}", str(i % 4)), p,
                              qos=qos)
        deadline = time.monotonic() + 15
        while len(got) < len(payloads) + 1 and time.monotonic() < deadline:
            try:
                m = await sub.recv(timeout=2)
            except asyncio.TimeoutError:
                continue
            got.append((m.topic, m.payload))
        await pub.close()
        await sub.close()

    run(main)
    return got


def test_qos0_cross_node_parity_vs_python_oracle():
    """The trunked pair must deliver the SAME (topic, payload) multiset
    the Python forward_fn oracle topology delivers — and actually ride
    the trunk for the steady state."""
    payloads = [b"m%03d" % i for i in range(60)]
    trunked = _TrunkedPair(trunk=True, suffix="q0t")
    try:
        trunked.wait_trunks_up()
        got_trunk = _drive_cross_node(trunked, "t0/{i}", payloads, qos=0)
        st = trunked.a.fast_stats()
        assert st["trunk_out"] > 0, st            # the plane was used
        assert trunked.b.fast_stats()["trunk_in"] > 0
    finally:
        trunked.stop()
    oracle = _TrunkedPair(trunk=False, suffix="q0o")
    try:
        got_py = _drive_cross_node(oracle, "t0/{i}", payloads, qos=0)
        assert oracle.a.fast_stats()["trunk_out"] == 0
    finally:
        oracle.stop()
    assert sorted(got_trunk) == sorted(got_py)
    assert len(got_trunk) == len(payloads) + 1    # zero loss either lane


def test_qos1_cross_node_parity_and_forward_split_metrics():
    """QoS1 publishes ride the trunk (publisher acked natively on A,
    subscriber served from B's native ack plane) with zero loss, and
    the messages.forward.native/.slow split accounts the legs."""
    payloads = [b"q%03d" % i for i in range(40)]
    pair = _TrunkedPair(trunk=True, suffix="q1")
    try:
        pair.wait_trunks_up()
        got = _drive_cross_node(pair, "t1/{i}", payloads, qos=1)
        assert sorted(p for _t, p in got) == sorted(payloads + [b"warm"])
        assert pair.a.fast_stats()["trunk_out"] > 0
        # housekeep folds trunk_out into the forward split; force one
        pair.a._merge_fast_metrics()
        m = pair.a.broker.metrics
        assert m.val("messages.forward.native") > 0
        assert m.val("messages.forward.slow") >= 1   # the warm-up leg
        assert m.val("messages.forward") == (
            m.val("messages.forward.native")
            + m.val("messages.forward.slow"))
    finally:
        pair.stop()


def test_per_topic_ordering_across_batch_flushes():
    """Messages interleaved across two topics must arrive per-topic
    ordered on the remote node even as the trunk chops the stream into
    per-cycle batches (one FIFO per peer = total order per link)."""
    pair = _TrunkedPair(trunk=True, suffix="ord")
    try:
        pair.wait_trunks_up()
        n = 150

        async def main():
            sub = MqttClient(port=pair.b.port, clientid="osub")
            await sub.connect()
            await sub.subscribe("ord/+", qos=0)
            pair.sync()
            pub = MqttClient(port=pair.a.port, clientid="opub")
            await pub.connect()
            for t in ("ord/x", "ord/y"):
                await pub.publish(t, b"warm", qos=0)
            for _ in range(2):
                await sub.recv(timeout=8)
            await asyncio.sleep(0.4)
            for i in range(n):
                await pub.publish("ord/x", b"x%04d" % i, qos=0)
                await pub.publish("ord/y", b"y%04d" % i, qos=0)
                if i % 50 == 49:
                    # force >= 2 poll cycles: on a heavily loaded box
                    # the whole pipelined burst can land in ONE read
                    # batch (= one trunk batch), starving the
                    # "really batched" assertion below of its premise
                    await asyncio.sleep(0.02)
            seen = {"ord/x": [], "ord/y": []}
            deadline = time.monotonic() + 20
            while (sum(len(v) for v in seen.values()) < 2 * n
                   and time.monotonic() < deadline):
                try:
                    m = await sub.recv(timeout=2)
                except asyncio.TimeoutError:
                    continue
                seen[m.topic].append(m.payload)
            # per-topic order is strict; qos0 drops are legal under
            # backpressure but must preserve relative order
            for t, prefix in (("ord/x", b"x"), ("ord/y", b"y")):
                idx = [int(p[1:]) for p in seen[t]]
                assert idx == sorted(idx), (t, idx[:20])
                assert len(idx) == n, (t, len(idx))  # loopback: no drops
            await pub.close()
            await sub.close()

        run(main)
        assert pair.a.fast_stats()["trunk_batches_out"] > 1  # really batched
    finally:
        pair.stop()


def test_trunk_loss_punt_fallback_reconnect_replay_no_qos1_loss():
    """The acceptance ladder: a dead link flips remote entries back to
    punt behavior (Python forward lane carries), and the reconnect
    replays the unacked qos1 ring — the union of deliveries is exactly
    the published set (bit-identical to what the oracle would deliver),
    with zero QoS1 loss.

    The first link is a test-controlled sink that reads trunk batches
    but NEVER acks, so the replay ring provably holds the in-flight
    messages when the link dies."""
    app_a, app_b = BrokerApp(), BrokerApp()
    app_a.broker.node = "nodeA"
    app_b.broker.node = "nodeB"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0)
    srv_b = NativeBrokerServer(port=0, app=app_b, trunk_port=0)

    # the Python oracle forward lane (what gen_rpc would do): dispatch
    # straight into B's broker tables
    def forward(dest, filt, msg):
        deliveries = {}
        app_b.broker._dispatch_local(filt, msg, deliveries)
        app_b.cm.dispatch(deliveries)
    app_a.broker.forward_fn = forward

    srv_a.start()
    srv_b.start()

    # dead-end trunk sink: accepts, reads, never acks, then dies
    sink = socket.socket()
    sink.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sink.bind(("127.0.0.1", 0))
    sink.listen(1)
    sink_port = sink.getsockname()[1]
    sink_conns = []

    def sink_loop():
        try:
            c, _ = sink.accept()
            sink_conns.append(c)
            c.settimeout(0.2)
            while True:
                try:
                    if not c.recv(65536):
                        return
                except socket.timeout:
                    continue
                except OSError:
                    return
        except OSError:
            return
    st = threading.Thread(target=sink_loop, daemon=True)
    st.start()

    try:
        run_payloads = [b"k%03d" % i for i in range(12)]

        async def main():
            sub = MqttClient(port=srv_b.port, clientid="ksub")
            await sub.connect()
            await sub.subscribe("kt/x", qos=1)
            pub = MqttClient(port=srv_a.port, clientid="kpub")
            await pub.connect()

            # route + trunk wiring AFTER servers run (observer fires)
            app_a.broker.router.add_route("kt/x", "nodeB")
            srv_a.trunk_register("nodeB", "127.0.0.1", sink_port)
            assert _wait(lambda: srv_a.trunk_peer_status().get("nodeB"))

            # earn the permit through the Python lane
            await pub.publish("kt/x", b"warm", qos=1)
            m = await sub.recv(timeout=8)
            assert m.payload == b"warm"
            await asyncio.sleep(0.4)

            # phase 1: publishes trunk into the sink (never acked, so
            # the replay ring holds them); the subscriber sees nothing
            for p in run_payloads[:6]:
                await pub.publish("kt/x", p, qos=1)
            assert _wait(
                lambda: srv_a.fast_stats()["trunk_out"] >= 6), (
                srv_a.fast_stats())

            # phase 2: kill the link → DOWN → punt fallback: publishes
            # ride forward_fn while the ring is preserved
            sink_conns[0].close()
            sink.close()
            assert _wait(
                lambda: not srv_a.trunk_peer_status().get("nodeB"))
            got_during_down = []
            for p in run_payloads[6:9]:
                await pub.publish("kt/x", p, qos=1)
            while True:
                try:
                    m = await sub.recv(timeout=3)
                except asyncio.TimeoutError:
                    break
                got_during_down.append(m.payload)
            assert sorted(got_during_down) == sorted(run_payloads[6:9])

            # phase 3: re-point at B's REAL trunk and reconnect — the
            # unacked qos1 batches replay into B's fan-out
            srv_a.trunk_register("nodeB", "127.0.0.1", srv_b.trunk_port)
            assert _wait(lambda: srv_a.trunk_peer_status().get("nodeB"))
            assert _wait(
                lambda: srv_a.fast_stats()["trunk_replays"] >= 1), (
                srv_a.fast_stats())
            replayed = []
            deadline = time.monotonic() + 10
            while len(replayed) < 6 and time.monotonic() < deadline:
                try:
                    m = await sub.recv(timeout=2)
                except asyncio.TimeoutError:
                    continue
                replayed.append(m.payload)
            assert sorted(replayed) == sorted(run_payloads[:6]), replayed

            # phase 4: post-reconnect traffic rides the trunk again
            # (permits were flushed on UP; re-earn through one slow leg)
            for p in run_payloads[9:]:
                await pub.publish("kt/x", p, qos=1)
            tail = []
            while len(tail) < 3:
                m = await sub.recv(timeout=8)
                tail.append(m.payload)
            assert sorted(tail) == sorted(run_payloads[9:])
            await pub.close()
            await sub.close()

        run(main)
        # zero QoS1 forward loss across the whole ladder: every payload
        # was delivered exactly through one of the three legs above
    finally:
        srv_a.stop()
        srv_b.stop()
        try:
            sink.close()
        except OSError:
            pass


def _half_open_pair(suffix: str, wire_v0: bool = False):
    """Two manually-wired servers (the kill-test shape) prepared for
    partition testing: forward_fn oracle on A, trunk A->B registered,
    a tight ack-timeout so an up-but-black link resolves fast. Returns
    (srv_a, srv_b, app_a, app_b)."""
    app_a, app_b = BrokerApp(), BrokerApp()
    app_a.broker.node = f"hoA{suffix}"
    app_b.broker.node = f"hoB{suffix}"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0)
    srv_b = NativeBrokerServer(port=0, app=app_b, trunk_port=0)

    def forward(dest, filt, msg):
        deliveries = {}
        app_b.broker._dispatch_local(filt, msg, deliveries)
        app_b.cm.dispatch(deliveries)
    app_a.broker.forward_fn = forward
    if wire_v0:
        # the old-peer twin: A speaks wire v0 — no HELLO, links
        # complete immediately, trace ids stripped
        srv_a.host.set_trunk_wire(0)
    srv_a.start()
    srv_b.start()
    srv_a.set_trunk_ack_timeout(400)
    return srv_a, srv_b, app_a, app_b


def _drive_half_open(srv_a, srv_b, app_a, topic, n_black=8):
    """The partition twin of the kill/replay test: blackhole (not
    kill) the A->B link mid-qos1-stream, assert the silent link DIES
    through the ack watchdog (no FIN/RST ever fires — SIGKILL tests
    cannot make this shape), heal, and prove the replay shadow loses
    nothing: every published payload reaches the subscriber at least
    once (at-least-once: dups legal, silence not)."""
    node_b = srv_b.app.broker.node if srv_b.app else "nodeB"
    got = []

    async def main():
        sub = MqttClient(port=srv_b.port, clientid="hsub" + topic[-1])
        await sub.connect()
        await sub.subscribe(topic, qos=1)
        pub = MqttClient(port=srv_a.port, clientid="hpub" + topic[-1])
        await pub.connect()
        app_a.broker.router.add_route(topic, node_b)
        srv_a.trunk_register(node_b, "127.0.0.1", srv_b.trunk_port)
        assert _wait(lambda: srv_a.trunk_peer_status().get(node_b))
        pid = srv_a._trunk_peers[node_b]["id"]

        await pub.publish(topic, b"warm", qos=1)
        m = await sub.recv(timeout=8)
        assert m.payload == b"warm"
        await asyncio.sleep(0.4)

        # healthy stream first (really on the trunk)
        for i in range(4):
            await pub.publish(topic, b"pre%02d" % i, qos=1)
        assert _wait(lambda: srv_a.fast_stats()["trunk_out"] >= 4)

        # PARTITION mid-stream: both directions of A's link to B go
        # black — writes claim success into the void, reads yield
        # nothing; the socket stays ESTABLISHED
        srv_a.fault_arm("trunk_write", "blackhole", key=pid)
        srv_a.fault_arm("trunk_read", "blackhole", key=pid)
        for i in range(n_black):
            await pub.publish(topic, b"blk%02d" % i, qos=1)

        # the watchdog (ack_timeout 400ms) kills the silent link — the
        # ONLY way an up-but-black partition ever resolves
        assert _wait(
            lambda: not srv_a.trunk_peer_status().get(node_b), 10), (
            srv_a.trunk_peer_status())
        assert srv_a.fault_fired("trunk_write") >= 1

        # publishes during the partition ride the Python oracle lane
        for i in range(3):
            await pub.publish(topic, b"dwn%02d" % i, qos=1)

        # HEAL: disarm; the jittered redial reconnects and the replay
        # shadow delivers every blackholed qos1 batch
        srv_a.fault_disarm("trunk_write")
        srv_a.fault_disarm("trunk_read")
        assert _wait(lambda: srv_a.trunk_peer_status().get(node_b), 15)
        assert _wait(
            lambda: srv_a.fast_stats()["trunk_replays"] >= 1, 10), (
            srv_a.fast_stats())

        want = ({b"pre%02d" % i for i in range(4)}
                | {b"blk%02d" % i for i in range(n_black)}
                | {b"dwn%02d" % i for i in range(3)})
        deadline = time.monotonic() + 20
        seen = set()
        while not want <= seen and time.monotonic() < deadline:
            try:
                m = await sub.recv(timeout=2)
            except asyncio.TimeoutError:
                continue
            got.append(m.payload)
            seen.add(m.payload)
        assert want <= seen, sorted(want - seen)
        await pub.close()
        await sub.close()

    run(main)
    return got


def test_half_open_blackhole_v1_link_replays_on_heal():
    """The partition twin of the kill/replay test on a CURRENT (wire
    v1) link: the HELLO grace expires against the blackholed peer
    (redials inside the partition complete at v0 after 300ms and
    replay into the void — trunk_replays advances while still black),
    the watchdog kills the silent link, and the heal loses nothing."""
    srv_a, srv_b, app_a, _app_b = _half_open_pair("v1")
    try:
        replays_before = srv_a.fast_stats()["trunk_replays"]
        _drive_half_open(srv_a, srv_b, app_a, "ho1/x")
        # at least one replay happened (black-window grace completions
        # and/or the healing reconnect)
        assert srv_a.fast_stats()["trunk_replays"] > replays_before
        # every injected fault is ledger-visible as reason "fault"
        assert srv_a.ledger.totals().get("fault", 0) >= 1
    finally:
        srv_a.stop()
        srv_b.stop()


def test_half_open_blackhole_v0_link_replays_on_heal():
    """The same partition against an OLD peer link (A capped at wire
    v0: no HELLO, immediate completion): the up-but-black machinery
    is wire-version-independent."""
    srv_a, srv_b, app_a, _app_b = _half_open_pair("v0", wire_v0=True)
    try:
        _drive_half_open(srv_a, srv_b, app_a, "ho0/y")
    finally:
        srv_a.stop()
        srv_b.stop()


def test_redial_backoff_jitter_caps_and_resets_on_up():
    """The redial schedule: exponential backoff with ±25% jitter (a
    healed partition must not wake every peer's redial on the same
    capped boundary — the full-mesh thundering herd), capped at 30s,
    reset to the base on UP."""
    from emqx_tpu.broker.native_server import (TRUNK_RETRY_CAP_S,
                                               TRUNK_RETRY_JITTER,
                                               TRUNK_RETRY_S)

    app_a = BrokerApp()
    app_a.broker.node = "joA"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0)
    srv_b = NativeBrokerServer(port=0, app=BrokerApp(), trunk_port=0)
    srv_b.app.broker.node = "joB"
    srv_a.start()
    srv_b.start()
    try:
        # every dial fails (injected): DOWNs accumulate and the
        # backoff doubles toward the cap
        srv_a.fault_arm("trunk_connect", "errno")
        srv_a.trunk_register("joB", "127.0.0.1", srv_b.trunk_port)
        pid = srv_a._trunk_peers["joB"]["id"]

        def backoff():
            with srv_a._mirror_lock:
                return srv_a._trunk_peers["joB"]["backoff"]

        assert _wait(lambda: backoff() >= 4.0, 15), backoff()
        # the next-retry stamp wears the ±25% jitter around the
        # PREVIOUS backoff step (retry_at was scheduled before the
        # doubling): always strictly inside the jitter envelope
        with srv_a._mirror_lock:
            p = dict(srv_a._trunk_peers["joB"])
        delay = p["retry_at"] - time.monotonic()
        assert delay <= p["backoff"] * (1 + TRUNK_RETRY_JITTER), (
            delay, p["backoff"])
        # force the cap and take one more DOWN: it must not exceed 30
        with srv_a._mirror_lock:
            srv_a._trunk_peers["joB"]["backoff"] = TRUNK_RETRY_CAP_S
        assert _wait(lambda: backoff() == TRUNK_RETRY_CAP_S, 10)
        # heal: the injected dial failure lifts, the link comes UP and
        # the backoff resets to the base
        srv_a.fault_disarm("trunk_connect")
        with srv_a._mirror_lock:   # dial now, not at the capped stamp
            srv_a._trunk_peers["joB"]["retry_at"] = 0.0
            srv_a._trunk_retry_at = 0.0
        assert _wait(lambda: srv_a.trunk_peer_status().get("joB"), 15)
        assert backoff() == TRUNK_RETRY_S
        assert srv_a.fault_fired("trunk_connect") >= 2
        assert pid >= 1
    finally:
        srv_a.stop()
        srv_b.stop()


def test_receiver_side_punt_reaches_python_audience():
    """A trunk-received publish whose local match set needs Python (a
    subscriber with no native connection → punt marker) must surface as
    a kind-9 punt and deliver through the receiver's Python dispatch."""
    app_a, app_b = BrokerApp(), BrokerApp()
    app_a.broker.node = "nodeA"
    app_b.broker.node = "nodeB"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0)
    srv_b = NativeBrokerServer(port=0, app=app_b, trunk_port=0)
    app_a.broker.forward_fn = lambda *a: None
    srv_a.start()
    srv_b.start()
    try:
        got = []

        class FakeChannel:
            conn_state = "connected"

            def handle_deliver(self, items):
                got.extend(m for _t, m in items)
                return []

            def send(self, pkts):
                pass

        # a Python-plane audience on B: broker-table subscriber with no
        # native conn (the punt-marker shape) + a local route
        app_b.cm.register_channel("pysub", FakeChannel())
        app_b.broker.subscribe("pysub", "pt/x")
        app_b.broker.router.add_route("pt/x", "nodeB")  # local route

        async def main():
            pub = MqttClient(port=srv_a.port, clientid="ppub")
            await pub.connect()
            app_a.broker.router.add_route("pt/x", "nodeB")
            srv_a.trunk_register("nodeB", "127.0.0.1", srv_b.trunk_port)
            assert _wait(lambda: srv_a.trunk_peer_status().get("nodeB"))
            # the warm-up leg rides A's PYTHON lane, whose forward_fn
            # is a no-op here by design — only trunked messages may
            # reach B, so the punt path is provably what delivered
            await pub.publish("pt/x", b"warm", qos=0)
            await asyncio.sleep(0.4)
            for i in range(5):
                await pub.publish("pt/x", b"p%d" % i, qos=0)
            assert _wait(lambda: len(got) >= 5), [m.payload for m in got]
            await pub.close()

        run(main)
        assert srv_b.fast_stats()["trunk_punts"] >= 1
        payloads = sorted(m.payload for m in got)
        assert payloads == sorted(b"p%d" % i for i in range(5))
    finally:
        srv_a.stop()
        srv_b.stop()


def test_route_add_remove_races_no_loss_no_dup():
    """Trunk route flips racing a publish stream: every message is
    delivered at most once (trunk OR Python lane, never both) and the
    stream delivered while the route exists is loss-free."""
    app_a, app_b = BrokerApp(), BrokerApp()
    app_a.broker.node = "nodeA"
    app_b.broker.node = "nodeB"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0)
    srv_b = NativeBrokerServer(port=0, app=app_b, trunk_port=0)

    def forward(dest, filt, msg):
        deliveries = {}
        app_b.broker._dispatch_local(filt, msg, deliveries)
        app_b.cm.dispatch(deliveries)
    app_a.broker.forward_fn = forward
    srv_a.start()
    srv_b.start()
    try:
        stop = threading.Event()

        def churn():
            # the route flaps while traffic flows: remote entry ↔ punt
            # marker ↔ absent, all through the product observer path
            while not stop.is_set():
                app_a.broker.router.delete_route("rr/x", "nodeB")
                time.sleep(0.002)
                app_a.broker.router.add_route("rr/x", "nodeB")
                time.sleep(0.004)

        async def main():
            sub = MqttClient(port=srv_b.port, clientid="rsub")
            await sub.connect()
            await sub.subscribe("rr/x", qos=1)
            pub = MqttClient(port=srv_a.port, clientid="rpub")
            await pub.connect()
            app_a.broker.router.add_route("rr/x", "nodeB")
            srv_a.trunk_register("nodeB", "127.0.0.1", srv_b.trunk_port)
            assert _wait(lambda: srv_a.trunk_peer_status().get("nodeB"))
            await pub.publish("rr/x", b"warm", qos=1)
            await sub.recv(timeout=8)
            await asyncio.sleep(0.4)
            t = threading.Thread(target=churn, daemon=True)
            t.start()
            n = 120
            for i in range(n):
                await pub.publish("rr/x", b"r%04d" % i, qos=1)
            stop.set()
            t.join(timeout=5)
            app_a.broker.router.add_route("rr/x", "nodeB")
            got = []
            deadline = time.monotonic() + 8
            while time.monotonic() < deadline:
                try:
                    m = await sub.recv(timeout=1.5)
                except asyncio.TimeoutError:
                    break
                got.append(m.payload)
            # no duplicates ever (one delivery mechanism per message)
            assert len(got) == len(set(got)), "duplicate delivery"
            # the flap window may drop messages published while the
            # route was absent (no audience = legal drop), but the
            # plane must stay alive and keep delivering afterwards
            await pub.publish("rr/x", b"after", qos=1)
            m = await sub.recv(timeout=8)
            assert m.payload in (b"after",) or b"after" in got
            await pub.close()
            await sub.close()

        run(main)
    finally:
        srv_a.stop()
        srv_b.stop()


# -- store-backed trunk ring (round 18) ---------------------------------------

def test_trunk_ring_survives_broker_restart_zero_qos1_loss(tmp_path):
    """Tentpole (round 18): the per-peer unacked qos1 ring is
    store-backed — kill/restart of the SENDING node no longer loses
    it. Phase 1 trunks into a never-acking sink (ring provably holds
    the batches, journaled as kRecTrunk records); the node then
    restarts on the same store dir, re-registers the peer at B's REAL
    trunk, and the recovered ring replays from segments: the
    subscriber receives every qos1 payload."""
    from emqx_tpu.session.persistent import NativeDurableStore

    base_a = str(tmp_path / "nodeA")
    app_a = BrokerApp(persistent_store=NativeDurableStore(base_a))
    app_b = BrokerApp()
    app_a.broker.node = "nodeA"
    app_b.broker.node = "nodeB"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0)
    srv_b = NativeBrokerServer(port=0, app=app_b, trunk_port=0)

    def forward(dest, filt, msg):
        deliveries = {}
        app_b.broker._dispatch_local(filt, msg, deliveries)
        app_b.cm.dispatch(deliveries)
    app_a.broker.forward_fn = forward

    srv_a.start()
    srv_b.start()

    sink = socket.socket()
    sink.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sink.bind(("127.0.0.1", 0))
    sink.listen(1)
    sink_port = sink.getsockname()[1]

    def sink_loop():
        try:
            c, _ = sink.accept()
            c.settimeout(0.2)
            while True:
                try:
                    if not c.recv(65536):
                        return
                except socket.timeout:
                    continue
                except OSError:
                    return
        except OSError:
            return
    threading.Thread(target=sink_loop, daemon=True).start()

    payloads = [b"r%03d" % i for i in range(6)]
    try:
        async def phase1():
            pub = MqttClient(port=srv_a.port, clientid="rr-pub")
            await pub.connect()
            app_a.broker.router.add_route("rr/x", "nodeB")
            srv_a.trunk_register("nodeB", "127.0.0.1", sink_port)
            assert _wait(lambda: srv_a.trunk_peer_status().get("nodeB"))
            # earn the permit through the Python lane
            await pub.publish("rr/x", b"warm", qos=1)
            await asyncio.sleep(0.5)
            for p in payloads:
                await pub.publish("rr/x", p, qos=1)
            assert _wait(
                lambda: srv_a.fast_stats()["trunk_out"] >= 6), (
                srv_a.fast_stats())
            await pub.close()

        run(phase1)
        # the ring journaled into the store before any socket write
        assert _wait(
            lambda: srv_a.fast_stats()["trunk_ring_persisted"] >= 1), (
            srv_a.fast_stats())
        store = app_a.persistent.store.native
        assert store.trunk_pending("nodeB") >= 1
        assert store.stats()["trunk_pending"] >= 1
    finally:
        srv_a.stop()
        app_a.persistent.store.close()
        try:
            sink.close()
        except OSError:
            pass

    # ---- restart node A on the same store dir -----------------------------
    app_a2 = BrokerApp(persistent_store=NativeDurableStore(base_a))
    app_a2.broker.node = "nodeA"
    srv_a2 = NativeBrokerServer(port=0, app=app_a2, trunk_port=0)
    srv_a2.start()
    try:
        async def phase2():
            sub = MqttClient(port=srv_b.port, clientid="rr-sub")
            await sub.connect()
            await sub.subscribe("rr/x", qos=1)
            # re-register the peer at B's REAL trunk: trunk_ident binds
            # the node name, the recovered ring replays on UP
            app_a2.broker.router.add_route("rr/x", "nodeB")
            srv_a2.trunk_register("nodeB", "127.0.0.1",
                                  srv_b.trunk_port)
            assert _wait(
                lambda: srv_a2.trunk_peer_status().get("nodeB"))
            assert _wait(
                lambda: srv_a2.fast_stats()["trunk_ring_recovered"]
                >= 1), srv_a2.fast_stats()
            got = []
            deadline = time.monotonic() + 12
            while len(got) < len(payloads) and \
                    time.monotonic() < deadline:
                try:
                    m = await sub.recv(timeout=2)
                except asyncio.TimeoutError:
                    continue
                if m.payload != b"warm":
                    got.append(m.payload)
            assert sorted(got) == sorted(payloads), got
            await sub.close()

        run(phase2)
        # the peer's acks retired the store records with the ring slots
        store2 = app_a2.persistent.store.native
        assert _wait(lambda: store2.trunk_pending("nodeB") == 0)
    finally:
        srv_a2.stop()
        srv_b.stop()
        app_a2.persistent.store.close()


def test_trunk_acks_retire_store_ring_records(tmp_path):
    """Healthy-pair counterpart: every acked batch retires its store
    record (kRecTrunkAck) — the persisted ring tracks the in-memory
    ring, not a grow-forever journal."""
    from emqx_tpu.session.persistent import NativeDurableStore

    base_a = str(tmp_path / "nodeA")
    app_a = BrokerApp(persistent_store=NativeDurableStore(base_a))
    app_b = BrokerApp()
    app_a.broker.node = "nodeA"
    app_b.broker.node = "nodeB"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0)
    srv_b = NativeBrokerServer(port=0, app=app_b, trunk_port=0)

    def forward(dest, filt, msg):
        deliveries = {}
        app_b.broker._dispatch_local(filt, msg, deliveries)
        app_b.cm.dispatch(deliveries)
    app_a.broker.forward_fn = forward

    srv_a.start()
    srv_b.start()
    try:
        async def main():
            sub = MqttClient(port=srv_b.port, clientid="ak-sub")
            await sub.connect()
            await sub.subscribe("ak/x", qos=1)
            pub = MqttClient(port=srv_a.port, clientid="ak-pub")
            await pub.connect()
            app_a.broker.router.add_route("ak/x", "nodeB")
            srv_a.trunk_register("nodeB", "127.0.0.1",
                                 srv_b.trunk_port)
            assert _wait(lambda: srv_a.trunk_peer_status().get("nodeB"))
            await pub.publish("ak/x", b"warm", qos=1)
            await sub.recv(timeout=8)
            await asyncio.sleep(0.5)
            for i in range(8):
                await pub.publish("ak/x", b"a%d" % i, qos=1)
                await sub.recv(timeout=8)
            await pub.close()
            await sub.close()

        run(main)
        st = srv_a.fast_stats()
        assert st["trunk_ring_persisted"] >= 1, st
        store = app_a.persistent.store.native
        # acks retired every journaled record alongside the ring slots
        assert _wait(lambda: store.trunk_pending("nodeB") == 0), (
            store.trunk_pending("nodeB"))
    finally:
        srv_a.stop()
        srv_b.stop()
        app_a.persistent.store.close()
