"""GCP PubSub stack: self-signed service-account JWT (RS256), the REST
publish path against MiniPubSub, and rule → bridge → PubSub end-to-end
(reference: emqx_ee_connector_gcp_pubsub.erl self-signed token auth +
publish_path/1, emqx_ee_bridge_gcp_pubsub.erl payload_template)."""

import json
import time

import pytest

# every case mints an RSA service account, which needs the optional
# `cryptography` dep (absent in the CI container): skip the module
# cleanly instead of erroring six tests at runtime
pytest.importorskip("cryptography")

from emqx_tpu.app import BrokerApp
from emqx_tpu.connector.gcp_pubsub import (PUBSUB_AUD, GcpPubSubConnector,
                                           MiniPubSub, PubSubError,
                                           make_test_service_account,
                                           rs256_sign)
from emqx_tpu.core.message import Message


def _stack(project="proj", topic="up"):
    sa, pub = make_test_service_account(project)
    srv = MiniPubSub(pub, project_id=project).start()
    conn = GcpPubSubConnector(
        sa, topic, base_url=f"http://127.0.0.1:{srv.port}")
    return sa, srv, conn


def test_jwt_self_signed_shape():
    sa, _pub = make_test_service_account()
    tok = rs256_sign({"aud": PUBSUB_AUD, "iss": sa["client_email"]},
                     sa["private_key"].encode(), kid=sa["private_key_id"])
    h, b, s = tok.split(".")
    from emqx_tpu.access.authn import _unb64url
    header = json.loads(_unb64url(h))
    assert header == {"alg": "RS256", "typ": "JWT",
                      "kid": sa["private_key_id"]}
    assert json.loads(_unb64url(b))["aud"] == PUBSUB_AUD


def test_publish_roundtrip_and_auth():
    sa, srv, conn = _stack()
    try:
        conn.on_start({})
        ids = conn.on_query({"messages": [
            {"data": "aGVsbG8=", "attributes": {"k": "v"}},
            {"data": "d29ybGQ=", "orderingKey": "dev-1"}]})
        assert ids == ["1", "2"]
        msgs = srv.topics["up"]
        assert msgs[0]["data"] == b"hello" and msgs[0]["attributes"] == \
            {"k": "v"}
        assert msgs[1]["orderingKey"] == "dev-1"

        # a token signed by a DIFFERENT key is refused (401)
        other_sa, _ = make_test_service_account()
        bad = GcpPubSubConnector(
            {**sa, "private_key": other_sa["private_key"]}, "up",
            base_url=f"http://127.0.0.1:{srv.port}")
        with pytest.raises(PubSubError):
            bad.on_query({"messages": [{"data": ""}]})
        assert srv.auth_failures >= 1
    finally:
        srv.stop()


def test_expired_token_reminted_once():
    sa, srv, conn = _stack()
    try:
        conn.on_query({"messages": [{"data": "eA=="}]})
        # poison the cached token with an expired one: the 401 path must
        # re-mint and the retry must land
        conn._token = rs256_sign(
            {"aud": PUBSUB_AUD, "iss": sa["client_email"],
             "exp": int(time.time()) - 10},
            sa["private_key"].encode())
        ids = conn.on_query({"messages": [{"data": "eQ=="}]})
        assert ids == ["2"]
        assert srv.auth_failures == 1
    finally:
        srv.stop()


def test_batch_query_one_call():
    _sa, srv, conn = _stack()
    try:
        out = conn.on_batch_query([
            {"messages": [{"data": "YQ=="}]},
            {"messages": [{"data": "Yg=="}, {"data": "Yw=="}]}])
        assert out == [["1"], ["2", "3"]]
        assert [m["data"] for m in srv.topics["up"]] == [b"a", b"b", b"c"]
    finally:
        srv.stop()


def test_unknown_project_404():
    _sa, srv, conn = _stack()
    try:
        conn.sa = {**conn.sa, "project_id": "other"}
        with pytest.raises(PubSubError):
            conn.on_query({"messages": [{"data": ""}]})
    finally:
        srv.stop()


def test_rule_to_pubsub_bridge():
    """message.publish → rule → gcp_pubsub bridge: the rendered payload
    template lands base64-decoded with attributes + ordering key."""
    sa, pub = make_test_service_account("iot")
    srv = MiniPubSub(pub, project_id="iot").start()
    try:
        app = BrokerApp()
        app.bridges.create(
            "gcp_pubsub", "up",
            GcpPubSubConnector(sa, "telemetry",
                               base_url=f"http://127.0.0.1:{srv.port}"),
            {"payload_template": '{"t":"${topic}","p":"${payload}"}',
             "attributes_template": {"client": "${clientid}"},
             "ordering_key_template": "${clientid}"},
            batch_size=1, batch_time_s=0.0)
        app.rules.create_rule(
            "to-pubsub", 'SELECT clientid, topic, payload FROM "g/#"',
            [{"function": "gcp_pubsub:up", "args": {}}])
        app.broker.publish(Message(topic="g/1", payload=b"hi",
                                   from_="dev-g"))
        deadline = 50
        while not srv.topics.get("telemetry") and deadline:
            time.sleep(0.1)
            app.bridges.tick()
            deadline -= 1
        (m,) = srv.topics["telemetry"]
        assert m["data"] == b'{"t":"g/1","p":"hi"}'
        assert m["attributes"] == {"client": "dev-g"}
        assert m["orderingKey"] == "dev-g"
    finally:
        srv.stop()
