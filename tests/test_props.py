"""Property-based tests — the PropEr suites of the reference
(apps/emqx/test/props/prop_emqx_frame.erl parse∘serialize roundtrip,
emqx_topic match laws, trie-vs-oracle equivalence) on hypothesis."""

import string

from hypothesis import HealthCheck, given, settings, strategies as st

# CI runs these suites alongside CPU-heavy device benches; wall-clock
# data-generation health checks misfire under that contention
settings.register_profile(
    "contention", suppress_health_check=[HealthCheck.too_slow],
    deadline=None)
settings.load_profile("contention")

from emqx_tpu.core import topic as T
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import Parser, parse_one, serialize
from emqx_tpu.router.trie import Trie

# -- generators ---------------------------------------------------------------

word = st.text(alphabet=string.ascii_lowercase + string.digits,
               min_size=1, max_size=6)
topic_name = st.lists(word, min_size=1, max_size=7).map("/".join)


@st.composite
def topic_filter(draw):
    n = draw(st.integers(1, 7))
    parts = []
    for i in range(n):
        kind = draw(st.integers(0, 9))
        if kind == 0:
            parts.append("+")
        elif kind == 1 and i == n - 1:
            parts.append("#")
        else:
            parts.append(draw(word))
    return "/".join(parts)


qos = st.integers(0, 2)
payload = st.binary(max_size=512)


@st.composite
def publish_packet(draw):
    q = draw(qos)
    return P.Publish(
        topic=draw(topic_name), payload=draw(payload), qos=q,
        retain=draw(st.booleans()), dup=draw(st.booleans()) if q else False,
        packet_id=draw(st.integers(1, 0xFFFF)) if q else None)


@st.composite
def any_packet(draw):
    return draw(st.one_of(
        publish_packet(),
        st.builds(P.Connect, clientid=word, keepalive=st.integers(0, 0xFFFF),
                  clean_start=st.booleans()),
        st.builds(P.Subscribe, packet_id=st.integers(1, 0xFFFF),
                  topic_filters=st.lists(
                      st.tuples(topic_filter(),
                                st.fixed_dictionaries({"qos": qos})),
                      min_size=1, max_size=4)),
        st.builds(P.Unsubscribe, packet_id=st.integers(1, 0xFFFF),
                  topic_filters=st.lists(topic_filter(), min_size=1,
                                         max_size=4)),
        st.builds(P.PubAck, packet_id=st.integers(1, 0xFFFF)),
        st.builds(P.PubRel, packet_id=st.integers(1, 0xFFFF)),
        st.just(P.PingReq()),
        st.just(P.Disconnect()),
    ))


# -- frame codec: parse ∘ serialize == id (prop_emqx_frame) -------------------

@settings(max_examples=200)
@given(any_packet())
def test_frame_roundtrip(pkt):
    wire = serialize(pkt)
    (got,) = Parser().feed(wire)
    assert type(got) is type(pkt)
    assert serialize(got) == wire            # canonical re-serialization


@settings(max_examples=100)
@given(st.lists(any_packet(), min_size=1, max_size=5),
       st.integers(1, 13))
def test_frame_roundtrip_chunked(pkts, chunk):
    """Arbitrary chunking never changes the parse (the {active,N}
    invariant the incremental state machine must hold)."""
    wire = b"".join(serialize(p) for p in pkts)
    parser = Parser()
    got = []
    for i in range(0, len(wire), chunk):
        got.extend(parser.feed(wire[i:i + chunk]))
    assert [type(p) for p in got] == [type(p) for p in pkts]
    assert b"".join(serialize(p) for p in got) == wire
    assert [parse_one(serialize(p)).type for p in pkts] == \
        [p.type for p in pkts]


# -- topic match laws ---------------------------------------------------------

@settings(max_examples=300)
@given(topic_name)
def test_topic_matches_itself(name):
    assert T.match(name, name)


@settings(max_examples=300)
@given(topic_name)
def test_hash_matches_everything_except_sys(name):
    assert T.match(name, "#") == (not name.startswith("$"))


@settings(max_examples=300)
@given(topic_name, topic_filter())
def test_match_equals_wordwise_oracle(name, filt):
    """T.match vs a brute-force recursive matcher."""
    def brute(nw, fw):
        if not fw:
            return not nw
        if fw[0] == "#":
            return True
        if not nw:
            return False
        return (fw[0] == "+" or fw[0] == nw[0]) and brute(nw[1:], fw[1:])

    nw, fw = name.split("/"), filt.split("/")
    expect = brute(nw, fw) and not (
        name.startswith("$") and fw[0] in ("+", "#"))
    assert T.match(name, filt) == expect


# -- trie vs linear-scan oracle ----------------------------------------------

@settings(max_examples=60, deadline=None)
@given(st.lists(topic_filter(), min_size=1, max_size=40, unique=True),
       st.lists(topic_name, min_size=1, max_size=20))
def test_trie_match_equals_linear_scan(filters, names):
    trie = Trie()
    for f in filters:
        if T.wildcard(f):
            trie.insert(f)
    for name in names:
        got = sorted(trie.match(name))
        expect = sorted(f for f in filters
                        if T.wildcard(f) and T.match(name, f))
        assert got == expect


@settings(max_examples=40, deadline=None)
@given(st.lists(topic_filter(), min_size=2, max_size=30, unique=True),
       st.data())
def test_trie_refcounted_delete(filters, data):
    """Insert all, delete a random subset — matches must equal the
    linear scan over survivors (emqx_trie refcount discipline)."""
    wild = [f for f in filters if T.wildcard(f)]
    trie = Trie()
    for f in wild:
        trie.insert(f)
        trie.insert(f)                       # refcount 2
    removed = [f for f in wild if data.draw(st.booleans(), label=f)]
    for f in removed:
        trie.delete(f)
        trie.delete(f)                       # both refs gone
    survivors = [f for f in wild if f not in removed]
    for f in wild:
        name = f.replace("+", "x").replace("#", "tail")
        got = sorted(trie.match(name))
        expect = sorted(s for s in survivors if T.match(name, s))
        assert got == expect
