"""MQTT-over-WebSocket listener (emqx_ws_connection analogue): RFC6455
codec, handshake, and full MQTT flows through the WS transport."""

import asyncio
import base64
import os
import struct

import pytest

from emqx_tpu.broker.ws import (
    OP_BINARY, OP_CLOSE, OP_PING, OP_PONG, FrameDecoder, WsBrokerServer,
    WsError, accept_key, encode_frame,
)
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import Parser, serialize


# -- codec ---------------------------------------------------------------------

def test_frame_roundtrip_masked_and_sizes():
    dec = FrameDecoder(require_mask=True)
    for size in (0, 1, 125, 126, 65535, 65536, 100_000):
        payload = os.urandom(size)
        msgs = dec.feed(encode_frame(OP_BINARY, payload, mask=True))
        assert msgs == [(OP_BINARY, payload)]


def test_frame_fragmentation_and_interleaved_control():
    dec = FrameDecoder(require_mask=False)
    # two fragments with a PING between them
    p1, p2 = b"hello ", b"world"
    f1 = bytearray(encode_frame(OP_BINARY, p1))
    f1[0] &= 0x7F                                  # clear FIN
    ping = encode_frame(OP_PING, b"hb")
    f2 = bytearray(encode_frame(0x0, p2))          # continuation, FIN set
    msgs = dec.feed(bytes(f1) + ping + bytes(f2))
    assert msgs == [(OP_PING, b"hb"), (OP_BINARY, b"hello world")]


def test_frame_unmasked_client_rejected():
    dec = FrameDecoder(require_mask=True)
    with pytest.raises(WsError):
        dec.feed(encode_frame(OP_BINARY, b"x", mask=False))


def test_accept_key_rfc_example():
    # the RFC6455 §1.3 worked example
    assert (accept_key("dGhlIHNhbXBsZSBub25jZQ==")
            == "s3pPLMBiTxaQ9kYGzzhZRbK+xOo=")


# -- live listener -------------------------------------------------------------

class WsTestClient:
    """Minimal masked-frame WS client speaking the mqtt subprotocol."""

    def __init__(self, port: int, path: str = "/mqtt"):
        self.port, self.path = port, path
        self.dec = FrameDecoder(require_mask=False)   # server→client unmasked
        self.parser = Parser()
        self.inbox: list = []

    async def connect_ws(self):
        self.r, self.w = await asyncio.open_connection("127.0.0.1", self.port)
        key = base64.b64encode(os.urandom(16)).decode()
        self.w.write((
            f"GET {self.path} HTTP/1.1\r\nHost: localhost\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n"
            "Sec-WebSocket-Protocol: mqtt\r\n\r\n").encode())
        resp = await self.r.readuntil(b"\r\n\r\n")
        assert b"101" in resp.split(b"\r\n")[0]
        assert accept_key(key).encode() in resp
        return self

    async def send_mqtt(self, pkt, ver=P.MQTT_V4):
        self.w.write(encode_frame(OP_BINARY, serialize(pkt, ver), mask=True))
        await self.w.drain()

    async def recv_mqtt(self, timeout=5.0):
        while not self.inbox:
            data = await asyncio.wait_for(self.r.read(65536), timeout)
            assert data, "server closed"
            for op, payload in self.dec.feed(data):
                if op == OP_BINARY:
                    self.inbox.extend(self.parser.feed(payload))
        return self.inbox.pop(0)

    async def close(self):
        self.w.close()


def run(coro):
    asyncio.run(coro)


def test_mqtt_pubsub_over_websocket():
    async def main():
        server = WsBrokerServer(port=0)
        await server.start()
        try:
            sub = await WsTestClient(server.port).connect_ws()
            await sub.send_mqtt(P.Connect(clientid="ws-sub"))
            assert (await sub.recv_mqtt()).reason_code == 0
            await sub.send_mqtt(P.Subscribe(
                packet_id=1, topic_filters=[("ws/+/t", {"qos": 1})]))
            assert (await sub.recv_mqtt()).reason_codes == [1]

            pub = await WsTestClient(server.port).connect_ws()
            await pub.send_mqtt(P.Connect(clientid="ws-pub"))
            await pub.recv_mqtt()
            await pub.send_mqtt(P.Publish(topic="ws/1/t", payload=b"over-ws",
                                          qos=1, packet_id=7))
            got = await sub.recv_mqtt()
            assert isinstance(got, P.Publish) and got.payload == b"over-ws"
            assert (await pub.recv_mqtt()).packet_id == 7   # puback
            await sub.close()
            await pub.close()
        finally:
            await server.stop()
    run(main())


def test_ws_ping_pong_and_bad_path():
    async def main():
        server = WsBrokerServer(port=0)
        await server.start()
        try:
            c = await WsTestClient(server.port).connect_ws()
            c.w.write(encode_frame(OP_PING, b"x", mask=True))
            data = await asyncio.wait_for(c.r.read(1024), 5)
            assert c.dec.feed(data)[0] == (OP_PONG, b"x")
            await c.close()
            # wrong path → 400, no upgrade
            r, w = await asyncio.open_connection("127.0.0.1", server.port)
            w.write(b"GET /nope HTTP/1.1\r\nHost: x\r\n"
                    b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                    b"Sec-WebSocket-Key: AAAAAAAAAAAAAAAAAAAAAA==\r\n\r\n")
            resp = await asyncio.wait_for(r.read(1024), 5)
            assert b"400" in resp
            w.close()
        finally:
            await server.stop()
    run(main())


def test_ws_mixed_with_tcp_same_broker():
    """One app, two listeners: a WS subscriber receives from a TCP
    publisher (the reference's multi-listener norm)."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    async def main():
        app = BrokerApp()
        tcp = BrokerServer(port=0, app=app)
        ws = WsBrokerServer(port=0, app=app)
        await tcp.start()
        await ws.start()
        try:
            sub = await WsTestClient(ws.port).connect_ws()
            await sub.send_mqtt(P.Connect(clientid="w1"))
            await sub.recv_mqtt()
            await sub.send_mqtt(P.Subscribe(
                packet_id=1, topic_filters=[("x/#", {"qos": 0})]))
            await sub.recv_mqtt()
            c = MqttClient(port=tcp.port, clientid="t1")
            await c.connect()
            await c.publish("x/y", b"cross")
            got = await sub.recv_mqtt()
            assert got.payload == b"cross"
            await c.disconnect()
            await sub.close()
        finally:
            await ws.stop()
            await tcp.stop()
    run(main())
