"""Config system tests: HOCON parsing, schema checking, layering,
update handlers, zones (reference ground: emqx_config_SUITE,
emqx_schema_tests, hocon's own suite)."""

import pytest

from emqx_tpu.config import hocon
from emqx_tpu.config.config import Config, ConfigError
from emqx_tpu.config.hocon import ByteSize, Duration, HoconError
from emqx_tpu.config.schema import Field, SchemaError, Struct, root_schema


# -- hocon -----------------------------------------------------------------

def test_hocon_scalars_and_nesting():
    doc = hocon.loads("""
    # comment
    node {
      name = "emqx@host"        // inline comment
      cookie = secret
    }
    mqtt.max_packet_size = 1MB
    mqtt.retry_interval = 30s
    mqtt.keepalive_backoff = 0.75
    listeners.tcp.default { bind = "0.0.0.0:1883", enabled = true }
    tags = [a, b, "c d"]
    ratio = 80%
    empty = null
    """)
    assert doc["node"]["name"] == "emqx@host"
    assert doc["node"]["cookie"] == "secret"
    assert doc["mqtt"]["max_packet_size"] == 1024 * 1024
    assert isinstance(doc["mqtt"]["max_packet_size"], ByteSize)
    assert doc["mqtt"]["retry_interval"] == 30.0
    assert isinstance(doc["mqtt"]["retry_interval"], Duration)
    assert doc["mqtt"]["keepalive_backoff"] == 0.75
    assert doc["listeners"]["tcp"]["default"]["enabled"] is True
    assert doc["tags"] == ["a", "b", "c d"]
    assert doc["ratio"] == 0.8
    assert doc["empty"] is None


def test_hocon_object_merge_and_substitution():
    doc = hocon.loads("""
    a { x = 1 }
    a { y = 2 }
    a.z = ${a.x}
    arr = [{n = 1}, {n = 2}]
    """)
    assert doc["a"] == {"x": 1, "y": 2, "z": 1}
    assert doc["arr"][1]["n"] == 2


def test_hocon_durations():
    doc = hocon.loads("a=100ms\nb=5m\nc=2h\nd=1d")
    assert doc["a"] == pytest.approx(0.1)
    assert doc["b"] == 300.0
    assert doc["c"] == 7200.0
    assert doc["d"] == 86400.0


def test_hocon_errors():
    with pytest.raises(HoconError):
        hocon.loads("a = ")
    with pytest.raises(HoconError):
        hocon.loads('a = "unterminated')
    with pytest.raises(HoconError):
        hocon.loads("a = ${nope}")


# -- schema ----------------------------------------------------------------

def test_schema_defaults_and_check():
    conf = root_schema().check({})
    assert conf["mqtt"]["max_inflight"] == 32
    assert conf["mqtt"]["session_expiry_interval"] == 7200.0
    assert conf["authorization"]["no_match"] == "allow"
    assert conf["shared_subscription_strategy"] == "round_robin"


def test_schema_rejects_unknown_and_bad_types():
    with pytest.raises(SchemaError, match="unknown config key"):
        root_schema().check({"mqtt": {"max_inflightt": 1}})
    with pytest.raises(SchemaError, match="expected int"):
        root_schema().check({"mqtt": {"max_inflight": "many"}})
    with pytest.raises(SchemaError, match="one of"):
        root_schema().check({"log": {"level": "loud"}})
    with pytest.raises(SchemaError, match="validation failed"):
        root_schema().check({"mqtt": {"max_qos_allowed": 3}})


def test_schema_array_items_and_open_structs():
    s = Struct({"xs": Field("array", default=[], item=Field("int"))})
    assert s.check({"xs": [1, 2]})["xs"] == [1, 2]
    with pytest.raises(SchemaError):
        s.check({"xs": [1, "two"]})
    listeners = root_schema().check(
        {"listeners": {"tcp": {"default": {"bind": "x", "extra": 1}}}})
    assert listeners["listeners"]["tcp"]["default"]["extra"] == 1


def test_schema_to_doc():
    doc = root_schema().to_doc()
    assert doc["fields"]["mqtt"]["fields"]["max_inflight"]["default"] == 32


# -- layered store ---------------------------------------------------------

def test_config_layering_order():
    c = Config()
    c.init_load("mqtt.max_inflight = 10",
                cluster_override={"mqtt": {"max_inflight": 20}},
                local_override={"mqtt": {"max_inflight": 30}})
    assert c.get("mqtt.max_inflight") == 30
    c2 = Config()
    c2.init_load("mqtt.max_inflight = 10",
                 cluster_override={"mqtt": {"max_inflight": 20}})
    assert c2.get("mqtt.max_inflight") == 20


def test_config_put_recheck_and_rollback():
    c = Config()
    c.init_load("")
    c.put("mqtt.max_inflight", 64)
    assert c.get("mqtt.max_inflight") == 64
    with pytest.raises(SchemaError):
        c.put("mqtt.max_inflight", "lots")
    assert c.get("mqtt.max_inflight") == 64        # rolled back
    cluster, _local = c.overrides()
    assert cluster == {"mqtt": {"max_inflight": 64}}


def test_config_update_handler_and_listener():
    c = Config()
    c.init_load("")
    seen = []

    def clamp(path, val, old_root):
        if val > 1000:
            raise ConfigError("too big")
        return val

    c.add_handler("mqtt.max_inflight", clamp)
    c.add_listener(lambda p, v: seen.append((".".join(p), v)))
    c.put("mqtt.max_inflight", 100)
    assert c.get("mqtt.max_inflight") == 100
    with pytest.raises(ConfigError):
        c.put("mqtt.max_inflight", 5000)
    assert c.get("mqtt.max_inflight") == 100
    assert seen == [("mqtt.max_inflight", 100)]
    # deepest-prefix handler also fires for nested paths
    c.add_handler("retainer", lambda p, v, old: v)
    c.put("retainer.enable", False)
    assert c.get("retainer.enable") is False


def test_zone_conf_fallback():
    c = Config()
    c.init_load("""
    mqtt.max_inflight = 32
    zones.iot.max_inflight = 4
    """)
    assert c.get_zone_conf("iot", "max_inflight") == 4
    assert c.get_zone_conf("iot", "max_mqueue_len") == 1000   # global
    assert c.get_zone_conf("other", "max_inflight") == 32


def test_get_raw_vs_checked():
    c = Config()
    c.init_load("mqtt.retry_interval = 10s")
    assert c.get("mqtt.retry_interval") == 10.0
    assert c.get("mqtt.max_inflight") == 32       # default filled
    assert c.get_raw("mqtt.max_inflight") is None  # raw has no default


# -- app boot from config --------------------------------------------------

def test_broker_app_from_config_end_to_end():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.mqtt import packet as P

    c = Config()
    c.init_load("""
    node.name = "tpu1@127.0.0.1"
    shared_subscription_strategy = sticky
    retainer.max_retained_messages = 100
    authorization {
      no_match = deny
      sources = [
        {type = file, rules = "allow all all t/#"}
      ]
    }
    authentication = [
      {mechanism = password_based, backend = built_in_database,
       bootstrap_users = [{user_id = "u1", password = "pw"}]}
    ]
    flapping_detect { enable = true, max_count = 3 }
    """)
    app = BrokerApp.from_config(c)
    assert app.broker.node == "tpu1"
    assert app.shared.strategy == "sticky"
    assert app.retainer.max_retained == 100
    assert app.access.flapping is not None

    ch = Channel(app.broker, app.cm)
    out = ch.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="c1",
                                 username="u1", password=b"pw"))
    assert out[0].reason_code == P.RC_SUCCESS
    bad = Channel(app.broker, app.cm)
    out = bad.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="c2",
                                  username="u1", password=b"wrong"))
    assert out[0].reason_code == P.RC_BAD_USER_NAME_OR_PASSWORD
    # authz from config: t/# allowed, others denied (no_match=deny)
    acks = ch.handle_in(P.Publish(topic="t/1", qos=1, packet_id=1,
                                  payload=b"x"))
    assert acks[0].reason_code == P.RC_SUCCESS
    acks = ch.handle_in(P.Publish(topic="other", qos=1, packet_id=2,
                                  payload=b"x"))
    assert acks[0].reason_code == P.RC_NOT_AUTHORIZED
    # live update: strategy swap applies without restart
    c.put("shared_subscription_strategy", "random")
    assert app.shared.strategy == "random"
