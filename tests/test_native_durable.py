"""Native durable-session plane (round 10).

The C++ host persists publishes matching a persistent session's filters
into a segmented mmap store (native/src/store.h) BELOW the GIL — the
reference's emqx_persistent_session.erl:93-109 persist_message +
:275-310 resume, with the store host-side per SURVEY §5 — while the
publisher and every fast subscriber stay on the fast path (the old
behavior punted the whole topic to asyncio). Covered here:

- the store's own contract: append/fetch/consume/register round trip,
  restart recovery, CRC torn-tail drop (fuzz), segment GC + compaction;
- the data plane: one persistent subscriber no longer collapses the
  fast path (punts stay zero, durable counters move), live delivery
  consumes markers, offline traffic replays on clean_start=false
  resume exactly once;
- crash safety: kill -9 → restart → resume replays every PUBACK'd QoS1
  message exactly once (the PUBACK is only written after the store
  append + fsync — host.cc FlushDirty orders it);
- the escape hatch: EMQX_DURABLE_STORE=0 (and a persistence-less app)
  restore the punt-everything behavior.
"""

import asyncio
import os
import signal
import struct
import subprocess
import sys
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp                              # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer    # noqa: E402
from emqx_tpu.mqtt.client import MqttClient                     # noqa: E402
from emqx_tpu.session.persistent import (                       # noqa: E402
    MemStore, NativeDurableStore)


def run(coro):
    asyncio.run(coro)


def make_server(tmp_path=None, **kw):
    app = BrokerApp(persistent_store=MemStore())
    if tmp_path is not None:
        kw.setdefault("durable_dir", str(tmp_path / "store"))
    server = NativeBrokerServer(port=0, app=app, **kw)
    return server


# -- the store itself ---------------------------------------------------------

def test_store_roundtrip_and_restart_recovery(tmp_path):
    d = str(tmp_path / "s1")
    s = native.NativeStore(d, segment_bytes=1 << 20, fsync="batch")
    tok = s.register("sess-a")
    assert s.register("sess-a") == tok          # stable per sid
    g1 = s.append(7, 1, [tok], "t/a", b"hello")
    g2 = s.append(7, 0, [tok], "t/b", b"world", dup=True)
    assert g2 == g1 + 1
    rows = s.fetch(tok)
    assert [(r[0], r[3], r[4], r[5], r[6]) for r in rows] == [
        (g1, 1, False, "t/a", b"hello"),
        (g2, 0, True, "t/b", b"world")]
    assert s.pending(tok) == 2
    assert s.consume(tok, [g1]) == 1
    assert s.consume(tok, [g1]) == 0            # already spent
    s.close()

    # reopen: registration, the unconsumed message, and the consume
    # journal all survive; guids keep advancing past the recovered max
    s2 = native.NativeStore(d, segment_bytes=1 << 20, fsync="batch")
    assert s2.register("sess-a") == tok
    rows = s2.fetch(tok)
    assert [(r[0], r[5], r[6]) for r in rows] == [(g2, "t/b", b"world")]
    g3 = s2.append(7, 1, [tok], "t/c", b"!")
    assert g3 > g2
    assert s2.stats()["torn_drops"] == 0
    s2.close()


def test_store_lookup_never_registers(tmp_path):
    s = native.NativeStore(str(tmp_path / "lk"))
    assert s.lookup("ghost") == 0               # and no record journaled
    tok = s.register("real")
    assert s.lookup("real") == tok
    assert s.lookup("ghost") == 0
    s.close()


def test_oversized_durable_entry_still_reaches_python():
    """A near-max-size publish matched by several durable sessions
    builds a kind-10 record larger than max_size: the poll buffer's
    durable margin must still deliver it (a dropped record would skip
    live delivery while keeping the markers — a ghost replay later)."""
    import socket

    store = native.NativeStore("")              # anonymous
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.attach_store(store)
    try:
        toks = [store.register(f"s{i}") for i in range(3)]
        ids = []

        def pump(want_opens=0, want_frames=0, deadline_s=5.0):
            frames = []
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                for kind, conn, payload in host.poll(50):
                    if kind == native.EV_OPEN:
                        ids.append(conn)
                    elif kind == native.EV_FRAME:
                        frames.append(payload)
                if len(ids) >= want_opens and len(frames) >= want_frames:
                    break
            return frames

        pub = socket.create_connection(("127.0.0.1", host.port))
        pump(want_opens=1)
        pub_id = ids[0]
        vh = b"\x00\x04MQTT\x04\x02\x00\x3c\x00\x02ov"
        pub.sendall(bytes([0x10, len(vh)]) + vh)
        pump(want_opens=1, want_frames=1)
        host.enable_fast(pub_id, 4, 0)
        for t in toks:
            host.durable_add(t, "ov/t", 1)
        host.permit(pub_id, "ov/t")
        list(host.poll(50))

        payload = b"z" * ((1 << 16) - 64)       # near max_size
        body = struct.pack(">H", 4) + b"ov/t" + payload
        head = bytes([0x30])
        rl, var = len(body), b""
        while True:
            b7 = rl & 0x7F
            rl >>= 7
            var += bytes([b7 | (0x80 if rl else 0)])
            if not rl:
                break
        pub.sendall(head + var + body)
        got = []
        t0 = time.time()
        while not got and time.time() - t0 < 5:
            for kind, conn, p in host.poll(50):
                if kind == native.EV_DURABLE:
                    got.append(native.parse_durable(p))
        assert got, "oversized durable record never surfaced"
        _base, _ts, entries = got[0]
        assert len(entries) == 1
        origin, flags, etoks, topic, ebody, _trace, _cid = entries[0]
        assert sorted(etoks) == sorted(toks)
        assert topic == "ov/t" and ebody == payload
        assert store.stats()["appends"] == 1
        pub.close()
        for _ in range(5):
            list(host.poll(10))
    finally:
        host.destroy()
        store.close()


def test_store_multi_token_marker_fanout(tmp_path):
    s = native.NativeStore(str(tmp_path / "s2"))
    ta, tb = s.register("a"), s.register("b")
    g = s.append(1, 1, [ta, tb], "x", b"one")
    assert s.pending(ta) == 1 and s.pending(tb) == 1
    s.consume(ta, [g])
    assert s.pending(ta) == 0 and s.pending(tb) == 1
    assert s.stats()["messages"] == 1           # b's marker keeps it
    s.consume(tb, [g])
    assert s.stats()["messages"] == 0
    s.close()


def test_store_fuzz_torn_tail_drops_only_the_tail(tmp_path):
    """Truncating / corrupting a segment mid-record must drop ONLY the
    torn record and what follows it in that segment — every record
    before the CRC boundary replays intact (satellite: crash-recovery
    fuzz)."""
    d = str(tmp_path / "fz")
    s = native.NativeStore(d, segment_bytes=1 << 20, fsync="batch")
    tok = s.register("fz")
    guids = [s.append(1, 1, [tok], f"t/{i}", b"p%d" % i)
             for i in range(10)]
    s.close()
    seg = os.path.join(d, sorted(os.listdir(d))[0])
    raw = open(seg, "rb").read()

    # locate each frame boundary by walking the CRC framing
    offs = []
    pos = 0
    while pos + 9 <= len(raw):
        ln = int.from_bytes(raw[pos + 4:pos + 8], "little")
        if ln == 0:
            break
        offs.append(pos)
        pos += 8 + ln
    assert len(offs) >= 11                      # register + 10 batches

    # case 1: truncate mid-way through the 8th message record
    cut = offs[8] + 11                          # inside the frame
    with open(seg, "r+b") as f:
        f.truncate(cut)
    s = native.NativeStore(d, segment_bytes=1 << 20, fsync="batch")
    rows = s.fetch(s.register("fz"))
    assert [r[0] for r in rows] == guids[:7], rows  # 7 intact, tail gone
    assert s.stats()["torn_drops"] >= 1
    s.close()

    # case 2: flip a payload byte mid-record — CRC refuses it and the
    # scan stops THERE (records before it still replay)
    with open(seg, "r+b") as f:
        f.write(raw)                            # restore all 10
        f.flush()
    with open(seg, "r+b") as f:
        f.seek(offs[5] + 20)
        f.write(b"\xff")
    s = native.NativeStore(d, segment_bytes=1 << 20, fsync="batch")
    rows = s.fetch(s.register("fz"))
    assert [r[0] for r in rows] == guids[:4], rows
    assert s.stats()["torn_drops"] >= 1
    s.close()


def test_store_gc_unlinks_consumed_segments(tmp_path):
    d = str(tmp_path / "gc")
    s = native.NativeStore(d, segment_bytes=64 * 1024, fsync="never")
    tok = s.register("g")
    guids = [s.append(1, 1, [tok], "t", b"x" * 4096) for _ in range(64)]
    assert s.stats()["segments"] > 1            # rolled at least once
    s.consume(tok, guids)
    freed = s.gc()
    assert freed > 0
    assert s.stats()["segments"] < 64
    assert s.fetch(tok) == []
    # survivor correctness after GC + reopen
    g = s.append(1, 1, [tok], "t/live", b"live")
    s.close()
    s2 = native.NativeStore(d, segment_bytes=64 * 1024, fsync="never")
    rows = s2.fetch(s2.register("g"))
    assert [(r[0], r[5], r[6]) for r in rows] == [(g, "t/live", b"live")]
    s2.close()


def test_store_gc_after_reopen_keeps_live_messages(tmp_path):
    """Regression: recovery must rebuild per-segment LIVE counts — a
    reopen followed by Gc() used to see live=0 for recovered segments
    and unlink files still holding unconsumed messages."""
    d = str(tmp_path / "rg")
    s = native.NativeStore(d, segment_bytes=64 * 1024, fsync="batch")
    tok = s.register("r")
    guids = [s.append(1, 1, [tok], f"t/{i}", b"z" * 4096)
             for i in range(40)]
    s.close()
    s2 = native.NativeStore(d, segment_bytes=64 * 1024, fsync="batch")
    s2.gc()                                     # must unlink NOTHING live
    rows = s2.fetch(s2.register("r"))
    assert [r[0] for r in rows] == guids
    s2.close()
    s3 = native.NativeStore(d, segment_bytes=64 * 1024, fsync="batch")
    assert [r[0] for r in s3.fetch(s3.register("r"))] == guids
    s3.close()


def test_store_gc_compaction_rehomes_live_tail(tmp_path):
    """Sealed segments holding only a thin live tail get their live
    messages REWRITTEN forward and are unlinked; the re-homed messages
    stay fetchable across a reopen (consumed-marker compaction)."""
    d = str(tmp_path / "cp")
    s = native.NativeStore(d, segment_bytes=64 * 1024, fsync="never")
    tok = s.register("c")
    guids = [s.append(1, 1, [tok], f"t/{i}", b"y" * 4096)
             for i in range(64)]
    segs0 = s.stats()["segments"]
    assert segs0 > 2
    keep = {guids[3], guids[40]}                # thin live tail
    s.consume(tok, [g for g in guids if g not in keep])
    s.gc()
    st = s.stats()
    assert st["segments"] < segs0
    assert st["rewrites"] >= 1 or st["gc_segments"] >= 1
    rows = s.fetch(tok)
    assert {r[0] for r in rows} == keep
    s.close()
    s2 = native.NativeStore(d, segment_bytes=64 * 1024, fsync="never")
    rows = s2.fetch(s2.register("c"))
    assert {r[0] for r in rows} == keep
    s2.close()


def test_store_age_compaction_unpins_huge_live_record(tmp_path):
    """ROADMAP carried edge, closed in round 15: ONE live message
    (alone, so victims never reached 2) used to hold its otherwise-dead
    segment forever across gc cycles. The age trigger re-homes it: a
    sealed segment whose MOSTLY-DEAD live tail has sat past
    compact_age_ms re-homes regardless of the pool-wide thin-tail
    rule — while a fully-live sealed segment (an offline subscriber's
    backlog) is never age-churned."""
    d = str(tmp_path / "age")
    s = native.NativeStore(d, segment_bytes=64 * 1024, fsync="never")
    tok = s.register("a")
    # one big live record in an early segment, then enough consumed
    # junk to seal it mostly-dead (live <= half the used bytes)
    big = s.append(1, 1, [tok], "t/big", b"B" * 20000)
    junk = [s.append(1, 1, [tok], "t/j", b"j" * 4096) for _ in range(30)]
    s.consume(tok, junk)
    assert s.stats()["segments"] > 1
    # the exact pre-fix behavior: gc cycles never free the pinned
    # segment (default age 60s has not elapsed; the thin rule needs
    # victims >= 2) — the big record pins an otherwise-dead segment
    for _ in range(3):
        s.gc()
    pinned = s.stats()["segments"]
    assert pinned >= 2, s.stats()
    assert s.stats()["rewrites"] == 0
    # age trigger: with the threshold down at 1ms the next gc re-homes
    # the big record forward and unlinks the carcass
    s.set_compact_age_ms(1)
    time.sleep(0.05)
    freed = s.gc()
    assert freed >= 1, s.stats()
    rewrites = s.stats()["rewrites"]
    assert rewrites >= 1
    # the PINNED segment file itself is gone (the re-home may roll a
    # fresh active segment, so the total count alone can tie)
    assert "00000001.seg" not in os.listdir(d), os.listdir(d)
    # CHURN BOUND (review finding): a FULLY-LIVE sealed segment — an
    # offline persistent backlog, the store's core workload — must NOT
    # be age-rehomed once a minute forever. Fill sealed segments with
    # live-only records; repeated age-expired gcs re-home nothing new.
    backlog = [s.append(1, 1, [tok], "t/bl", b"L" * 4096)
               for _ in range(30)]
    time.sleep(0.05)
    for _ in range(3):
        s.gc()
    assert s.stats()["rewrites"] == rewrites, s.stats()
    assert len(backlog) == 30
    # ...and the record survives, including across a reopen
    rows = s.fetch(tok)
    assert rows[0][0] == big and rows[0][5] == "t/big"
    assert rows[0][6] == b"B" * 20000
    s.close()
    s2 = native.NativeStore(d, segment_bytes=64 * 1024, fsync="never")
    rows = s2.fetch(s2.register("a"))
    assert rows[0][0] == big and len(rows[0][6]) == 20000
    assert len(rows) == 31            # big + the live backlog
    s2.close()


# -- the data plane -----------------------------------------------------------

def test_persistent_subscriber_no_longer_collapses_the_fast_path():
    """The headline: with the durable plane up, one persistent
    subscriber in the audience leaves the publisher and the fast
    subscriber fully native (punts stay zero) while BOTH subscribers
    receive every message and the store markers get consumed on live
    delivery."""
    server = make_server()
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="dp-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("dp/t", qos=1)
        fs = MqttClient(port=server.port, clientid="dp-fs")
        await fs.connect()
        await fs.subscribe("dp/t", qos=0)
        pub = MqttClient(port=server.port, clientid="dp-pp")
        await pub.connect()
        await pub.publish("dp/t", b"warm", qos=1)   # slow path earns permit
        await fs.recv(timeout=10)
        await ps.recv(timeout=10)
        await asyncio.sleep(0.6)
        punts0 = server.fast_stats()["punts"]
        for i in range(8):
            await pub.publish("dp/t", f"m{i}".encode(), qos=1)
            a = await fs.recv(timeout=10)
            b = await ps.recv(timeout=10)
            assert a.payload == b.payload == f"m{i}".encode()
            # the persistent session's copy rides the Python window
            assert b.packet_id is None or b.packet_id < 32768
        st = server.fast_stats()
        assert st["punts"] == punts0, st            # fast path held
        assert st["durable_in"] >= 8, st
        assert st["store_appends"] >= 8, st
        await asyncio.sleep(0.5)
        ss = server._durable_store.stats()
        assert ss["pending"] == 0, ss               # live delivery consumed
        m = server.broker.metrics
        assert m.val("messages.durable.stored") >= 8
        await ps.close(); await fs.close(); await pub.close()

    run(main())
    server.stop()


def test_offline_storage_and_resume_replays_exactly_once():
    server = make_server()
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="or-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("or/t", qos=1)
        pub = MqttClient(port=server.port, clientid="or-pp")
        await pub.connect()
        await pub.publish("or/t", b"warm", qos=1)
        await ps.recv(timeout=10)
        await asyncio.sleep(0.6)
        await ps.close()                            # offline, session kept
        await asyncio.sleep(0.3)
        for i in range(5):
            await pub.publish("or/t", f"off{i}".encode(), qos=1)
        await asyncio.sleep(0.5)
        assert server.fast_stats()["durable_in"] >= 5
        ps2 = MqttClient(port=server.port, clientid="or-ps",
                         clean_start=False, proto_ver=5,
                         properties={"Session-Expiry-Interval": 300})
        await ps2.connect()
        got = [(await ps2.recv(timeout=10)).payload for _ in range(5)]
        assert got == [f"off{i}".encode() for i in range(5)], got
        with pytest.raises(asyncio.TimeoutError):   # no duplicates
            await ps2.recv(timeout=0.8)
        assert server.broker.metrics.val("messages.durable.replayed") >= 5
        await ps2.close(); await pub.close()

    run(main())
    server.stop()


def test_wildcard_durable_subscription_replays_on_resume():
    """Regression (review finding): the replayed Message must carry the
    MATCHED FILTER as its sub_topic header — a wildcard subscription's
    replay used to miss the session's SubOpts lookup and be dropped as
    'late delivery' after its markers were already consumed."""
    server = make_server()
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="wd-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("wd/+", qos=1)           # WILDCARD filter
        pub = MqttClient(port=server.port, clientid="wd-pp")
        await pub.connect()
        await pub.publish("wd/t", b"warm", qos=1)
        await ps.recv(timeout=10)
        await asyncio.sleep(0.6)
        await ps.close()
        await asyncio.sleep(0.3)
        for i in range(3):
            await pub.publish("wd/t", f"w{i}".encode(), qos=1)
        await asyncio.sleep(0.5)
        assert server.fast_stats()["durable_in"] >= 3
        ps2 = MqttClient(port=server.port, clientid="wd-ps",
                         clean_start=False, proto_ver=5,
                         properties={"Session-Expiry-Interval": 300})
        await ps2.connect()
        got = [(await ps2.recv(timeout=10)).payload for _ in range(3)]
        assert got == [b"w0", b"w1", b"w2"], got
        await ps2.close(); await pub.close()

    run(main())
    server.stop()


def test_restart_installs_durable_entries_for_offline_sessions(tmp_path):
    """Regression (review finding): after a broker restart, a stored
    session that has not resumed yet must STILL have durable entries —
    otherwise fast-path publishes in the restart→resume window bypass
    both stores and are acked-but-lost."""
    sess_dir = str(tmp_path / "sessions")
    store_dir = str(tmp_path / "store")

    app1 = BrokerApp(persistent_store=NativeDurableStore(sess_dir))
    s1 = NativeBrokerServer(port=0, app=app1, durable_dir=store_dir)
    s1.start()

    async def phase1():
        ps = MqttClient(port=s1.port, clientid="rg-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 600})
        await ps.connect()
        await ps.subscribe("rg/t", qos=1)
        await ps.disconnect()

    run(phase1())
    s1.stop()
    app1.persistent.store.close()

    # restart: the subscriber is OFFLINE; fast traffic flows first
    app2 = BrokerApp(persistent_store=NativeDurableStore(sess_dir))
    s2 = NativeBrokerServer(port=0, app=app2, durable_dir=store_dir)
    s2.start()
    try:
        async def phase2():
            fs = MqttClient(port=s2.port, clientid="rg-fs")
            await fs.connect()
            await fs.subscribe("rg/t", qos=0)
            pub = MqttClient(port=s2.port, clientid="rg-pp")
            await pub.connect()
            await pub.publish("rg/t", b"warm", qos=1)   # python plane
            await fs.recv(timeout=10)
            await asyncio.sleep(0.7)                    # permit grant
            for i in range(3):
                await pub.publish("rg/t", f"gap{i}".encode(), qos=1)
                await fs.recv(timeout=10)
            st = s2.fast_stats()
            # the boot-installed durable entry caught the fast traffic
            assert st["durable_in"] >= 3, st
            # ...and the offline session replays EVERYTHING on resume
            ps = MqttClient(port=s2.port, clientid="rg-ps",
                            clean_start=False, proto_ver=5,
                            properties={"Session-Expiry-Interval": 600})
            await ps.connect()
            got = []
            while True:
                try:
                    got.append((await ps.recv(timeout=3)).payload)
                except asyncio.TimeoutError:
                    break
            want = [b"warm", b"gap0", b"gap1", b"gap2"]
            assert sorted(got) == sorted(want), (got, want)
            await ps.close(); await fs.close(); await pub.close()

        run(phase2())
    finally:
        s2.stop()
        app2.persistent.store.close()


def test_clean_start_wipes_native_markers():
    server = make_server()
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="cw-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("cw/t", qos=1)
        pub = MqttClient(port=server.port, clientid="cw-pp")
        await pub.connect()
        await pub.publish("cw/t", b"warm", qos=1)
        await ps.recv(timeout=10)
        await asyncio.sleep(0.6)
        await ps.close()
        await asyncio.sleep(0.3)
        await pub.publish("cw/t", b"stored", qos=1)
        await asyncio.sleep(0.4)
        # clean start discards the stored session AND its markers
        ps2 = MqttClient(port=server.port, clientid="cw-ps",
                         clean_start=True)
        await ps2.connect()
        with pytest.raises(asyncio.TimeoutError):
            await ps2.recv(timeout=0.8)
        await asyncio.sleep(0.3)
        tok = server._durable_tokens.get("cw-ps")
        assert tok is None or server._durable_store.pending(tok) == 0
        await ps2.close(); await pub.close()

    run(main())
    server.stop()


def test_discard_race_orphan_markers_consumed_on_sight():
    """A discard races the ASYNC durable_del (applied only at the next
    ApplyPending): a batch flushed in that window still carries markers
    for the dead token, appended AFTER discard's consume sweep. The
    kind-10 reconciliation must spend those orphans on sight — left
    alone they pin their segment against GC forever, and a later
    clean_start=false life of the same sid would replay pre-wipe
    messages (review finding)."""
    server = make_server()
    server.start()
    try:
        store = server._durable_store
        tok = server._durable_token("rx-ps")
        server._durable_discard("rx-ps")
        assert tok in server._durable_dead
        # simulate the raced flush: the host appends for the still-
        # installed entry and ships the SAME bytes up as kind-10
        guid = store.append(0, 1, [tok], "rx/t", b"late")
        assert store.pending(tok) == 1
        entry = (struct.pack("<QBH", 0, (1 << 1) | 1, 1)
                 + struct.pack("<Q", tok)
                 + struct.pack("<H", 4) + b"rx/t"
                 + struct.pack("<I", 4) + b"late")
        server._on_durable(struct.pack("<QQI", guid, 0, 1) + entry)
        assert store.pending(tok) == 0          # orphan spent
        # round 18: the discard RETIRED the journaled token
        # (unregister) — a fresh persistent life mints a NEW one, and
        # the old token stays dead so straggler batches keep consuming
        # on sight
        new_tok = server._durable_token("rx-ps")
        assert new_tok != tok
        assert tok in server._durable_dead
        assert new_tok not in server._durable_dead
    finally:
        server.stop()


def test_drain_watermark_blocks_double_delivery():
    """When a CONNECT and the publish it raced land in the same poll
    batch, the resume drain (CONNECT handling) replays the message
    BEFORE the queued kind-10 event is folded — _on_durable must then
    skip the already-drained guid or the client sees it twice (review
    finding). Guids are monotonic and the drain fetches the whole
    pending set, so the per-sid watermark is an exact filter."""
    server = make_server()
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="wm-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("wm/t", qos=1)
        await asyncio.sleep(0.3)
        store = server._durable_store
        tok = server._durable_tokens["wm-ps"]
        guid = store.append(0, 1, [tok], "wm/t", b"raced")
        # the drain replays (and consumes) the planted message...
        drained = server._durable_drain("wm-ps")
        assert [m.payload for m in drained] == [b"raced"]
        assert store.pending(tok) == 0
        # ...so folding the SAME batch's kind-10 afterwards must not
        # deliver it a second time through the connected channel
        entry = (struct.pack("<QBH", 0, (1 << 1) | 1, 1)
                 + struct.pack("<Q", tok)
                 + struct.pack("<H", 4) + b"wm/t"
                 + struct.pack("<I", 5) + b"raced")
        server._on_durable(struct.pack("<QQI", guid, 0, 1) + entry)
        with pytest.raises(asyncio.TimeoutError):
            await ps.recv(timeout=0.8)
        await ps.close()

    run(main())
    server.stop()


def test_escape_hatch_restores_punt_behavior(monkeypatch):
    """EMQX_DURABLE_STORE=0 keeps the pre-round-10 shape: persistent
    sessions install punt markers and matching publishes run the
    Python plane (still delivered, zero native persistence)."""
    monkeypatch.setenv("EMQX_DURABLE_STORE", "0")
    server = make_server()
    assert server._durable_store is None
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="eh-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("eh/t", qos=1)
        pub = MqttClient(port=server.port, clientid="eh-pp")
        await pub.connect()
        for i in range(3):
            await pub.publish("eh/t", f"p{i}".encode(), qos=1)
            m = await ps.recv(timeout=10)
            assert m.payload == f"p{i}".encode()
        st = server.fast_stats()
        assert st["durable_in"] == 0 and st["fast_in"] == 0, st
        await ps.close(); await pub.close()

    run(main())
    server.stop()


def test_config_wires_durable_store(tmp_path):
    """durable.enable boots PersistentSessions on the native-backed
    store under <data_dir>/durable and the native server attaches to
    the SAME store instance — one recovery path (round 18)."""
    from emqx_tpu.config.config import Config

    conf = Config()
    conf.put("durable.enable", True)
    conf.put("node.data_dir", str(tmp_path))
    app = BrokerApp.from_config(conf)
    assert app.persistent is not None
    assert isinstance(app.persistent.store, NativeDurableStore)
    server = NativeBrokerServer(port=0, app=app)
    try:
        assert server._durable_store is not None
        # the server shares the app's store instance (no second mmap)
        assert server._durable_store is app.persistent.store.native
        assert server._durable_store.dir == os.path.join(
            str(tmp_path), "durable", "store")
        assert os.path.isdir(server._durable_store.dir)
    finally:
        server.stop()
        app.persistent.store.close()


# -- crash safety -------------------------------------------------------------

_CHILD = r"""
import os, sys, threading
sys.path.insert(0, %(repo)r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.native_server import NativeBrokerServer
from emqx_tpu.session.persistent import NativeDurableStore

app = BrokerApp(persistent_store=NativeDurableStore(%(sess)r))
server = NativeBrokerServer(port=0, app=app, durable_dir=%(store)r,
                            durable_fsync="batch")
server.start()
print("PORT %%d" %% server.port, flush=True)
threading.Event().wait()          # run until killed
"""


def test_kill9_restart_resume_zero_qos1_loss(tmp_path):
    """The acceptance gate: every QoS1 message the broker PUBACK'd
    before a kill -9 replays exactly once after restart + clean_start=
    false resume — the store append (+fsync) is ordered BEFORE the
    PUBACK reaches the wire, so an acked message can never be lost."""
    sess_dir = str(tmp_path / "sessions")
    store_dir = str(tmp_path / "store")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = _CHILD % {"repo": repo, "sess": sess_dir, "store": store_dir}
    proc = subprocess.Popen([sys.executable, "-c", src],
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT "), line
        port = int(line.split()[1])

        async def phase1():
            ps = MqttClient(port=port, clientid="k9-ps",
                            clean_start=False, proto_ver=5,
                            properties={"Session-Expiry-Interval": 600})
            await ps.connect()
            await ps.subscribe("k9/t", qos=1)
            await ps.disconnect()
            pub = MqttClient(port=port, clientid="k9-pp")
            await pub.connect()
            # warm earns the permit (Python plane persists it too)
            await pub.publish("k9/t", b"warm", qos=1)
            await asyncio.sleep(0.8)
            for i in range(20):
                # publish() awaits the broker's PUBACK: every one of
                # these is store-committed by the ordering contract
                await pub.publish("k9/t", f"m{i:02d}".encode(), qos=1)

        run(phase1())
        os.kill(proc.pid, signal.SIGKILL)       # no goodbye
        proc.wait(timeout=10)

        # restart on the same directories, in-process
        app = BrokerApp(persistent_store=NativeDurableStore(sess_dir))
        server = NativeBrokerServer(port=0, app=app, durable_dir=store_dir,
                                    durable_fsync="batch")
        # the native store recovered the acked messages
        assert server._durable_store.stats()["messages"] >= 20
        server.start()
        try:
            async def phase2():
                ps = MqttClient(port=server.port, clientid="k9-ps",
                                clean_start=False, proto_ver=5,
                                properties={"Session-Expiry-Interval": 600})
                await ps.connect()
                got = []
                while True:
                    try:
                        got.append((await ps.recv(timeout=3)).payload)
                    except asyncio.TimeoutError:
                        break
                want = [b"warm"] + [f"m{i:02d}".encode()
                                    for i in range(20)]
                assert sorted(got) == sorted(want), (
                    f"lost={set(want) - set(got)} "
                    f"dup_or_extra={[g for g in got if got.count(g) > 1]}")
                await ps.close()

            run(phase2())
        finally:
            server.stop()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


# -- handoff wire sanity ------------------------------------------------------

def test_parse_handoff_roundtrip_shapes():
    rec1 = bytes([1]) + struct.pack("<I", 2) + struct.pack("<HH", 5, 9) \
        + struct.pack("<I", 1) + struct.pack("<HB", 40000, 3)
    out = native.parse_handoff(rec1)
    assert out["awaiting"] == [5, 9]
    assert out["inflight"] == [(40000, 2, "pubrel")]
    frame = b"\x30\x05\x00\x01tAB"
    rec2 = bytes([2]) + struct.pack("<I", 1) + struct.pack("<I", len(frame)) \
        + frame
    assert native.parse_handoff(rec2)["pending"] == [frame]


# -- one recovery path (round 18) ---------------------------------------------

def test_written_unacked_delivery_retransmits_after_restart(tmp_path):
    """Tentpole acceptance (round 18): a qos1 delivery WRITTEN to the
    subscriber's socket but never ACKED keeps its store marker
    (consume-on-ack) — after a restart, clean_start=false resume
    retransmits it. The pre-round-18 plane consumed the marker at
    delivery-write time and lost exactly this message. Once the
    retransmitted copy IS acked, the marker settles for good: a third
    boot replays nothing."""
    base = str(tmp_path / "ps")
    app1 = BrokerApp(persistent_store=NativeDurableStore(base))
    s1 = NativeBrokerServer(port=0, app=app1)
    s1.start()

    async def phase1():
        ps = MqttClient(port=s1.port, clientid="wu-ps",
                        clean_start=False, proto_ver=5, auto_ack=False,
                        properties={"Session-Expiry-Interval": 600})
        await ps.connect()
        await ps.subscribe("wu/t", qos=1)
        pub = MqttClient(port=s1.port, clientid="wu-pp")
        await pub.connect()
        await pub.publish("wu/t", b"written-not-acked", qos=1)
        pkt = await ps.recv(timeout=10)      # written to the wire...
        assert pkt.payload == b"written-not-acked"
        await ps.close()                     # ...but never acked
        await pub.close()

    run(phase1())
    s1.stop()
    app1.persistent.store.close()

    app2 = BrokerApp(persistent_store=NativeDurableStore(base))
    s2 = NativeBrokerServer(port=0, app=app2)
    s2.start()

    async def phase2():
        ps = MqttClient(port=s2.port, clientid="wu-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 600})
        await ps.connect()
        got = (await ps.recv(timeout=10)).payload   # auto-acked now
        assert got == b"written-not-acked"
        await asyncio.sleep(0.4)                    # ack settles marker
        await ps.close()

    run(phase2())
    s2.stop()
    app2.persistent.store.close()

    app3 = BrokerApp(persistent_store=NativeDurableStore(base))
    s3 = NativeBrokerServer(port=0, app=app3)
    s3.start()
    try:
        async def phase3():
            ps = MqttClient(port=s3.port, clientid="wu-ps",
                            clean_start=False, proto_ver=5,
                            properties={"Session-Expiry-Interval": 600})
            await ps.connect()
            with pytest.raises(asyncio.TimeoutError):   # settled: gone
                await ps.recv(timeout=0.8)
            await ps.close()

        run(phase3())
    finally:
        s3.stop()
        app3.persistent.store.close()


def test_no_local_survives_restart(tmp_path):
    """The persisted origin clientid (entry flags bit5) keeps MQTT5
    no-local honest across a restart: a session's OWN publishes must
    not replay to it, while another publisher's do. Pre-round-18 the
    replay's from_ was "$durable", so the no-local filter never
    matched and the session received its own message back."""
    base = str(tmp_path / "ps")
    app1 = BrokerApp(persistent_store=NativeDurableStore(base))
    s1 = NativeBrokerServer(port=0, app=app1)
    s1.start()

    async def phase1():
        ps = MqttClient(port=s1.port, clientid="nl-ps",
                        clean_start=False, proto_ver=5, auto_ack=False,
                        properties={"Session-Expiry-Interval": 600})
        await ps.connect()
        await ps.subscribe("nl/t", qos=1, nl=1)
        # its own publish: no-local means it must never come back
        await ps.publish("nl/t", b"mine", qos=1)
        # someone else's publish: must replay after the restart
        pub = MqttClient(port=s1.port, clientid="nl-pp")
        await pub.connect()
        await pub.publish("nl/t", b"theirs", qos=1)
        # neither is acked by nl-ps: "theirs" was delivered unacked
        # (marker kept), "mine" was dropped by no-local live
        await asyncio.sleep(0.5)
        await ps.close()
        await pub.close()

    run(phase1())
    s1.stop()
    app1.persistent.store.close()

    app2 = BrokerApp(persistent_store=NativeDurableStore(base))
    s2 = NativeBrokerServer(port=0, app=app2)
    s2.start()
    try:
        async def phase2():
            ps = MqttClient(port=s2.port, clientid="nl-ps",
                            clean_start=False, proto_ver=5,
                            properties={"Session-Expiry-Interval": 600})
            await ps.connect()
            got = []
            while True:
                try:
                    got.append((await ps.recv(timeout=1.5)).payload)
                except asyncio.TimeoutError:
                    break
            assert got == [b"theirs"], got
            await ps.close()

        run(phase2())
    finally:
        s2.stop()
        app2.persistent.store.close()


def test_fast_path_publish_persists_origin_clientid(tmp_path):
    """The C++ durable plane stamps the publisher's clientid into the
    store entry (conn_cids_ bound at enable_fast): after a restart the
    drained rows still name the publisher."""
    base = str(tmp_path / "ps")
    app = BrokerApp(persistent_store=NativeDurableStore(base))
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="oc-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 600})
        await ps.connect()
        await ps.subscribe("oc/t", qos=1)
        await ps.close()                          # offline: markers keep
        await asyncio.sleep(0.3)
        pub = MqttClient(port=server.port, clientid="oc-fast-pub")
        await pub.connect()
        await pub.publish("oc/t", b"warm", qos=1)   # slow: earns permit
        await asyncio.sleep(0.7)
        for i in range(3):
            await pub.publish("oc/t", f"f{i}".encode(), qos=1)
        await asyncio.sleep(0.5)
        st = server.fast_stats()
        assert st["durable_in"] >= 3, st          # fast path persisted
        await pub.close()

    run(main())
    server.stop()
    app.persistent.store.close()

    # reopen the bare store: every entry names the publisher
    store2 = NativeDurableStore(base)
    rows = store2.drain("oc-ps")
    assert len(rows) >= 4
    assert {r[8] for r in rows} == {"oc-fast-pub"}, rows
    store2.close()


def test_session_expiry_gc_retires_register_and_session_records(tmp_path):
    """Satellite (round 18): the expiry GC retires a dead session's
    REGISTER + SESSION records and markers, and the retirement
    SURVIVES a reopen — age compaction can no longer pin a dead
    session's segments."""
    base = str(tmp_path / "ps")
    store = NativeDurableStore(base)
    from emqx_tpu.session.persistent import PersistentSessions
    ps = PersistentSessions(store)
    ps.router.add_route("gc/t", "gc-sid")
    store.put_session("gc-sid", {"subs": {"gc/t": {"qos": 1}}, "ts": 0})
    from emqx_tpu.core.message import Message
    for i in range(4):
        ps.persist_message(Message(topic="gc/t",
                                   payload=f"m{i}".encode(), qos=1))
    tok = store.native.lookup("gc-sid")
    assert tok and store.native.pending(tok) == 4
    assert store.native.stats()["sessions"] == 1
    ps.note_disconnected("gc-sid", expiry_ms=1000, now=1_000_000)
    ps.gc(now=1_002_000)                         # expired: discard
    assert store.native.lookup("gc-sid") == 0    # REGISTER retired
    assert store.native.stats()["sessions"] == 0
    assert store.native.pending(tok) == 0
    store.close()

    store2 = NativeDurableStore(base)
    assert store2.native.lookup("gc-sid") == 0   # retirement persisted
    assert store2.native.stats()["sessions"] == 0
    assert store2.get_session("gc-sid") is None
    # the dead session's records no longer pin segments: GC can reach
    # the all-consumed state and compaction has nothing to re-home
    store2.native.gc()
    assert store2.native.stats()["pending"] == 0
    store2.close()
