"""Native (C++) PUBLISH fast path — the round-4 host data plane.

Covers the correctness seams listed in broker/native_server.py: the
C++ subscription table differentially against the host-oracle trie
(router/trie.py, the emqx_trie.erl semantics), the permit machinery
(slow→fast transition, rules veto, mid-stream rule creation), punt
markers (shared subs, persistent sessions, retained flags, $-topics),
QoS1 with the partitioned packet-id space, no-local, and unsubscribe
teardown. Reference behaviors: emqx_broker.erl:218-232 (publish),
emqx_authz cache (permits), emqx_mqueue.erl (qos1 queue)."""

import asyncio
import random
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp            # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer  # noqa: E402
from emqx_tpu.core.message import Message     # noqa: E402
from emqx_tpu.mqtt.client import MqttClient   # noqa: E402


def run(coro):
    asyncio.run(coro)


async def _wait_fast(server, key="fast_in", least=1, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if server.fast_stats()[key] >= least:
            return True
        await asyncio.sleep(0.05)
    return False


async def _settle(seconds=0.4):
    """Permits grant on the server's next idle poll step."""
    await asyncio.sleep(seconds)


# -- differential: C++ SubTable vs the Python trie oracle --------------------

def _topic_universe(rng, n):
    words = ["a", "b", "c", "dd", "e5", ""]
    topics = []
    for _ in range(n):
        depth = rng.randint(1, 6)
        topics.append("/".join(rng.choice(words) for _ in range(depth)))
    return topics


def test_subtable_matches_python_trie_oracle():
    """Random filters/topics: the C++ table and the host-oracle trie
    (router/trie.py — differentially tested against emqx_trie.erl
    semantics) must return identical match sets."""
    from emqx_tpu.router.trie import Trie

    rng = random.Random(7)
    words = ["a", "b", "c", "dd", "e5", "+", "#", ""]
    filters = set()
    while len(filters) < 400:
        depth = rng.randint(1, 6)
        parts = []
        for lvl in range(depth):
            w = rng.choice(words)
            if w == "#":
                parts.append(w)
                break
            parts.append(w)
        f = "/".join(parts)
        # the python validator's contract: '#' only at the end — the
        # generator above guarantees it
        filters.add(f)
    filters = sorted(filters)

    table = native.NativeSubTable()
    oracle = Trie()
    for i, f in enumerate(filters):
        table.add(i + 1, f)
        oracle.insert(f)

    topics = _topic_universe(rng, 3000)
    for t in topics:
        want = {filters.index(f) + 1 for f in oracle.match(t)}
        got = set(table.match(t))
        assert got == want, (t, sorted(got), sorted(want))

    # removal parity on a random half
    removed = [f for f in filters if rng.random() < 0.5]
    for f in removed:
        assert table.remove(filters.index(f) + 1, f)
        oracle.delete(f)
    for t in topics[:1000]:
        want = {filters.index(f) + 1 for f in oracle.match(t)}
        got = set(table.match(t))
        assert got == want, (t, sorted(got), sorted(want))
    table.close()


def test_subtable_multi_owner_and_upsert():
    table = native.NativeSubTable()
    table.add(1, "x/+", qos=0)
    table.add(2, "x/+", qos=1)
    table.add(1, "x/+", qos=2)          # upsert, not duplicate
    assert sorted(table.match("x/y")) == [1, 2]
    assert table.remove(1, "x/+")
    assert table.match("x/y") == [2]
    assert not table.remove(1, "x/+")   # already gone
    table.close()


# -- end-to-end fast-path semantics ------------------------------------------

def test_fast_transition_and_steady_state():
    """First publish takes the slow path; once the permit lands every
    subsequent publish is handled in C++ — and deliveries stay correct
    across the transition."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="fs")
        await sub.connect()
        await sub.subscribe("ft/+", qos=0)
        pub = MqttClient(port=server.port, clientid="fp")
        await pub.connect()
        for i in range(3):
            await pub.publish("ft/a", f"m{i}".encode(), qos=0)
            m = await sub.recv(timeout=5)
            assert m.payload == f"m{i}".encode()
            await _settle(0.3)
        stats = server.fast_stats()
        assert stats["fast_in"] >= 1, stats   # steady state went native
        await sub.close()
        await pub.close()

    run(main())
    server.stop()


def test_retained_and_sys_topics_punt():
    """retain=1 and $-prefixed topics never fast-path: the retainer
    must store, and $SYS-space semantics stay in Python."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        pub = MqttClient(port=server.port, clientid="rp")
        await pub.connect()
        sub = MqttClient(port=server.port, clientid="rs")
        await sub.connect()
        await sub.subscribe("rt/+", qos=0)
        # earn the permit on rt/a, then a retained publish on the SAME
        # topic must still go slow (flag checked per-message in C++)
        await pub.publish("rt/a", b"live", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("rt/a", b"keep", qos=0, retain=True)
        await sub.recv(timeout=5)
        await _settle(0.3)
        late = MqttClient(port=server.port, clientid="rl")
        await late.connect()
        await late.subscribe("rt/a", qos=0)
        m = await late.recv(timeout=5)
        assert m.payload == b"keep" and m.retain
        await pub.close(); await sub.close(); await late.close()

    run(main())
    server.stop()


def test_shared_group_native_when_all_members_fast():
    """A $share group whose members are all fast native connections is
    served by the C++ dispatcher (round_robin): normal + group
    deliveries both happen natively once the permit lands."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        normal = MqttClient(port=server.port, clientid="sn")
        await normal.connect()
        await normal.subscribe("st/x", qos=0)
        member = MqttClient(port=server.port, clientid="sm")
        await member.connect()
        await member.subscribe("$share/g1/st/x", qos=0)
        pub = MqttClient(port=server.port, clientid="sp")
        await pub.connect()
        for i in range(3):
            await pub.publish("st/x", f"s{i}".encode(), qos=0)
            await _settle(0.2)
        # normal sub saw all three; group member saw all three (single
        # member) — and the steady state ran in C++
        for i in range(3):
            m = await normal.recv(timeout=5)
            assert m.payload == f"s{i}".encode()
            g = await member.recv(timeout=5)
            assert g.payload == f"s{i}".encode()
        stats = server.fast_stats()
        assert stats["fast_in"] >= 1 and stats["shared_dispatch"] >= 1, stats
        await normal.close(); await member.close(); await pub.close()

    run(main())
    server.stop()


def test_shared_group_round_robin_rotates_natively():
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        m1 = MqttClient(port=server.port, clientid="rr1")
        await m1.connect(); await m1.subscribe("$share/g/rr/t", qos=0)
        m2 = MqttClient(port=server.port, clientid="rr2")
        await m2.connect(); await m2.subscribe("$share/g/rr/t", qos=0)
        pub = MqttClient(port=server.port, clientid="rrp")
        await pub.connect()
        await pub.publish("rr/t", b"warm", qos=0)
        await _settle()
        for i in range(8):
            await pub.publish("rr/t", f"n{i}".encode(), qos=0)

        async def drain(c):
            got = []
            while True:
                try:
                    got.append((await c.recv(timeout=0.5)).payload)
                except asyncio.TimeoutError:
                    return got
        g1, g2 = await drain(m1), await drain(m2)
        assert len(g1) + len(g2) == 9, (g1, g2)
        assert abs(len(g1) - len(g2)) <= 2        # rotating, not sticky
        assert server.fast_stats()["shared_dispatch"] >= 8
        await m1.close(); await m2.close(); await pub.close()

    run(main())
    server.stop()


def test_shared_group_mixed_membership_punts():
    """One persistent-session member makes the whole group punt: the
    Python SharedSub owns dispatch (its mqueue/offline semantics)."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        fast = MqttClient(port=server.port, clientid="mxf")
        await fast.connect()
        await fast.subscribe("$share/g/mx/t", qos=0)
        persist = MqttClient(port=server.port, clientid="mxp",
                             clean_start=False, proto_ver=5,
                             properties={"Session-Expiry-Interval": 300})
        await persist.connect()
        await persist.subscribe("$share/g/mx/t", qos=0)
        pub = MqttClient(port=server.port, clientid="mxpub")
        await pub.connect()
        for i in range(4):
            await pub.publish("mx/t", f"p{i}".encode(), qos=0)
            await _settle(0.2)
        stats = server.fast_stats()
        assert stats["shared_dispatch"] == 0, stats  # group stayed punted

        async def drain(c):
            got = []
            while True:
                try:
                    got.append((await c.recv(timeout=0.5)).payload)
                except asyncio.TimeoutError:
                    return got
        g1, g2 = await drain(fast), await drain(persist)
        assert len(g1) + len(g2) == 4, (g1, g2)   # each msg exactly once
        await fast.close(); await persist.close(); await pub.close()

    run(main())
    server.stop()


def test_shared_strategy_change_moves_groups_off_native():
    """Only round_robin runs in C++: flipping the strategy reconciles
    live groups back onto the Python dispatcher."""
    from emqx_tpu.config.config import Config
    conf = Config()
    conf.init_load("")
    app = BrokerApp.from_config(conf)
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        m1 = MqttClient(port=server.port, clientid="sc1")
        await m1.connect(); await m1.subscribe("$share/g/sc/t", qos=0)
        pub = MqttClient(port=server.port, clientid="scp")
        await pub.connect()
        await pub.publish("sc/t", b"w", qos=0)
        await m1.recv(timeout=5)
        await _settle()
        await pub.publish("sc/t", b"n", qos=0)
        await m1.recv(timeout=5)
        assert await _wait_fast(server, "shared_dispatch", 1)
        base = server.fast_stats()["shared_dispatch"]
        conf.put("shared_subscription_strategy", "sticky")
        await _settle(0.3)
        for i in range(3):
            await pub.publish("sc/t", f"s{i}".encode(), qos=0)
            m = await m1.recv(timeout=5)
            assert m.payload == f"s{i}".encode()
            await _settle(0.15)
        assert server.fast_stats()["shared_dispatch"] == base, \
            "sticky strategy must not dispatch natively"
        await m1.close(); await pub.close()

    run(main())
    server.stop()


async def _wait_hits(hits, n, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if len(hits) >= n:
            return True
        await asyncio.sleep(0.05)
    return False


def test_tap_batches_survive_mid_batch_flush_intact():
    """Round-7 regression: a tap batch that overflows the flush cap
    mid-cycle must re-seed the record-header slot before the next
    entry — the first post-flush entry used to land at offset 0 and be
    OVERWRITTEN by the header patch, corrupting every boundary-crossing
    batch. A small max_packet_size shrinks the cap (max_size/2+1) so a
    few hundred fat-payload messages cross many boundaries; every
    entry must reach the rules with its exact topic AND payload."""
    app = BrokerApp()
    hits = []
    app.rules.register_action("sink", lambda cols, a: hits.append(cols))
    app.rules.create_rule("r-tapcap",
                          'SELECT topic, payload FROM "fat/#"',
                          [{"function": "sink", "args": {}}])
    server = NativeBrokerServer(port=0, app=app, max_packet_size=4096)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="fs")
        await sub.connect()
        await sub.subscribe("fat/+", qos=0)
        pub = MqttClient(port=server.port, clientid="fp")
        await pub.connect()
        await pub.publish("fat/t", b"warm", qos=0)     # earns the permit
        await sub.recv(timeout=5)
        await _settle(0.8)
        n = 300
        for i in range(n):
            # ~200B distinct payloads: entries ~230B vs a ~2KB cap →
            # a flush boundary every ~8 entries
            await pub.publish("fat/t", (b"p%04d-" % i) + b"x" * 200,
                              qos=0)
            await sub.recv(timeout=5)
        assert await _wait_fast(server, "taps", n)
        assert await _wait_hits(hits, n + 1, timeout=15), len(hits)
        assert server.tap_dropped == 0
        got = sorted(h["payload"] for h in hits
                     if h["payload"] != b"warm")
        want = sorted((b"p%04d-" % i) + b"x" * 200 for i in range(n))
        assert got == want        # exact topics/payloads, no corruption
        assert all(h["topic"] == "fat/t" for h in hits)
        await sub.close()
        await pub.close()

    run(main())
    server.stop()


def test_ruled_topics_stay_fast_via_taps_and_rules_see_everything():
    """Round-5 contract (VERDICT r4 #5): rules must see EVERY matching
    message WITHOUT de-permitting the fast path. Rule FROM filters
    mirror into the C++ table as non-delivering tap entries; a ruled
    topic still earns its permit, deliveries run natively, and every
    fast-path message is copied to the rule runtime (taps counter).
    Creating a rule mid-stream flushes permits AND installs its tap
    before re-grant, so no message is missed across the transition."""
    app = BrokerApp()
    hits = []
    app.rules.register_action("sink", lambda cols, a: hits.append(cols))
    app.rules.create_rule("r-pre", 'SELECT topic FROM "ruled/#"',
                          [{"function": "sink", "args": {}}])
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="qs")
        await sub.connect()
        await sub.subscribe("ruled/+", qos=0)
        await sub.subscribe("free/+", qos=0)
        pub = MqttClient(port=server.port, clientid="qp")
        await pub.connect()
        # ruled topic: first publish slow (earns permit), then native —
        # and the rule fires for EVERY message either way
        for i in range(5):
            await pub.publish("ruled/t", b"x", qos=0)
            await sub.recv(timeout=5)
            await _settle(0.2)
        assert await _wait_hits(hits, 5), len(hits)
        assert await _wait_fast(server, "fast_in", 1)   # went native
        assert await _wait_fast(server, "taps", 1)      # and was tapped
        # a rule created mid-stream over an already-fast topic installs
        # its tap before the permit flush's re-grants: no missed message
        await pub.publish("free/t", b"f0", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("free/t", b"f1", qos=0)
        await sub.recv(timeout=5)
        app.rules.create_rule("r-live", 'SELECT topic FROM "free/#"',
                              [{"function": "sink", "args": {}}])
        n_before = len(hits)
        await _settle(0.3)
        for i in range(3):
            await pub.publish("free/t", b"f%d" % (2 + i), qos=0)
            await sub.recv(timeout=5)
            await _settle(0.2)
        assert await _wait_hits(hits, n_before + 3), \
            (len(hits), n_before)
        # deleting every rule removes the taps; the plane stays fast
        app.rules.delete_rule("r-pre")
        app.rules.delete_rule("r-live")
        await _settle(0.3)
        taps_before = server.fast_stats()["taps"]
        await pub.publish("ruled/t", b"y", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("ruled/t", b"z", qos=0)
        await sub.recv(timeout=5)
        await _settle(0.2)
        assert server.fast_stats()["taps"] == taps_before
        assert server.tap_dropped == 0
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos1_native_path_pid_partition():
    """QoS1 publish → native PUBACK to the publisher; QoS1 delivery →
    native pid >= 32768, acked by the client and consumed in C++."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="q1s")
        await sub.connect()
        await sub.subscribe("q1/t", qos=1)
        pub = MqttClient(port=server.port, clientid="q1p")
        await pub.connect()
        await pub.publish("q1/t", b"w", qos=1)   # slow path, earns permit
        m0 = await sub.recv(timeout=5)
        assert m0.packet_id is not None and m0.packet_id < 32768
        await _settle()
        for i in range(5):
            await pub.publish("q1/t", f"n{i}".encode(), qos=1)
        got = [await sub.recv(timeout=5) for _ in range(5)]
        assert [g.payload for g in got] == [f"n{i}".encode()
                                           for i in range(5)]
        for g in got:
            assert g.qos == 1 and g.packet_id >= 32768, g
        stats = server.fast_stats()
        assert stats["fast_in"] >= 5 and stats["fast_out"] >= 5
        assert await _wait_fast(server, "native_acks", 5)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_no_local_honored_natively():
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        c = MqttClient(port=server.port, clientid="nl1", proto_ver=5)
        await c.connect()
        await c.subscribe("nl/t", qos=0, nl=1)
        other = MqttClient(port=server.port, clientid="nl2", proto_ver=5)
        await other.connect()
        await other.subscribe("nl/t", qos=0)
        await c.publish("nl/t", b"first", qos=0)     # slow path
        assert (await other.recv(timeout=5)).payload == b"first"
        await _settle()
        await c.publish("nl/t", b"second", qos=0)    # fast path
        assert (await other.recv(timeout=5)).payload == b"second"
        with pytest.raises(asyncio.TimeoutError):
            await c.recv(timeout=0.6)                # no-local: no echo
        await c.close(); await other.close()

    run(main())
    server.stop()


def test_unsubscribe_removes_native_entry():
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="us")
        await sub.connect()
        await sub.subscribe("ut/+", qos=0)
        pub = MqttClient(port=server.port, clientid="up")
        await pub.connect()
        await pub.publish("ut/a", b"m0", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("ut/a", b"m1", qos=0)      # fast
        await sub.recv(timeout=5)
        await sub.unsubscribe("ut/+")
        await _settle(0.3)
        await pub.publish("ut/a", b"m2", qos=0)      # fast, no targets
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.6)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_persistent_session_subscriber_stays_on_python_path():
    """clean_start=False subscribers punt: their mqueue/inflight state
    must stay authoritative in the Python session (offline queueing)."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="ps",
                         clean_start=False, proto_ver=5,
                         properties={"Session-Expiry-Interval": 300})
        await sub.connect()
        await sub.subscribe("pt/t", qos=1)
        pub = MqttClient(port=server.port, clientid="pp")
        await pub.connect()
        for i in range(3):
            await pub.publish("pt/t", f"p{i}".encode(), qos=1)
            m = await sub.recv(timeout=5)
            assert m.payload == f"p{i}".encode()
            assert m.packet_id is None or m.packet_id < 32768
            await _settle(0.2)
        assert server.fast_stats()["fast_in"] == 0
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_fast_metrics_merge_into_node_metrics():
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="ms")
        await sub.connect()
        await sub.subscribe("mm/t", qos=0)
        pub = MqttClient(port=server.port, clientid="mp")
        await pub.connect()
        await pub.publish("mm/t", b"0", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        for i in range(10):
            await pub.publish("mm/t", b"x", qos=0)
        for i in range(10):
            await sub.recv(timeout=5)
        before = app.metrics.val("messages.received")
        server._merge_fast_metrics()
        after = app.metrics.val("messages.received")
        assert after - before >= 10
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_rewrite_topics_never_earn_permits():
    """A topic matching a pub rewrite rule must stay on the slow path —
    a native fan-out on the raw topic would bypass the redirect
    (round-4 review finding: _slow_consumers_watch must cover
    services/rewrite.py)."""
    app = BrokerApp()
    app.rewrite.add_rule("publish", "raw/#", r"^raw/(.+)$", "cooked/$1")
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="ws")
        await sub.connect()
        await sub.subscribe("cooked/+", qos=0)
        pub = MqttClient(port=server.port, clientid="wp")
        await pub.connect()
        for i in range(3):
            await pub.publish("raw/x", f"r{i}".encode(), qos=0)
            m = await sub.recv(timeout=5)
            assert m.topic == "cooked/x" and m.payload == f"r{i}".encode()
            await _settle(0.2)
        assert server.fast_stats()["fast_in"] == 0
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_two_share_groups_punt_markers_are_independent():
    """Two punt-mode $share groups over one real topic own separate
    punt state; unsubscribing one group must NOT remove the marker the
    other still needs (round-4 review finding). A persistent-session
    member keeps both groups in punt mode (not natively served)."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        m1 = MqttClient(port=server.port, clientid="g1m",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await m1.connect()
        await m1.subscribe("$share/ga/sh/t", qos=0)
        await m1.subscribe("$share/gb/sh/t", qos=0)
        pub = MqttClient(port=server.port, clientid="gpb")
        await pub.connect()
        await pub.publish("sh/t", b"both", qos=0)
        # one member in each group: two deliveries
        assert (await m1.recv(timeout=5)).payload == b"both"
        assert (await m1.recv(timeout=5)).payload == b"both"
        await m1.unsubscribe("$share/ga/sh/t")
        await _settle(0.3)
        for i in range(3):
            await pub.publish("sh/t", f"x{i}".encode(), qos=0)
            m = await m1.recv(timeout=5)
            assert m.payload == f"x{i}".encode()
            await _settle(0.15)
        # the surviving group still punts every publish (persistent
        # member => never native)
        stats = server.fast_stats()
        assert stats["fast_in"] == 0 and stats["shared_dispatch"] == 0
        await m1.close(); await pub.close()

    run(main())
    server.stop()


def test_config_driven_native_listener():
    """listeners { n1 { type = native } } boots the C++ host through
    the standard listener supervisor, data plane included."""
    from emqx_tpu.config.config import Config

    conf = Config()
    conf.init_load(
        'listeners { nat { type = native, bind = "127.0.0.1:0" } }')
    app = BrokerApp.from_config(conf)

    async def main():
        ids = await app.listeners.start_all(conf.get("listeners"))
        assert ids == ["native:nat"]
        lst = app.listeners.find("native:nat")
        sub = MqttClient(port=lst.port, clientid="cs")
        await sub.connect()
        await sub.subscribe("cl/+", qos=0)
        pub = MqttClient(port=lst.port, clientid="cp")
        await pub.connect()
        await pub.publish("cl/a", b"m0", qos=0)
        assert (await sub.recv(timeout=5)).payload == b"m0"
        await _settle()
        await pub.publish("cl/a", b"m1", qos=0)
        assert (await sub.recv(timeout=5)).payload == b"m1"
        assert lst.fast_stats()["fast_in"] >= 1
        info = app.listeners.info()
        assert info[0]["type"] == "native" and info[0]["running"]
        await sub.close(); await pub.close()
        await app.listeners.stop_all()

    run(main())


def test_clustered_node_keeps_fast_path_with_remote_punts():
    """A clustered node keeps its C++ data plane: topics with a remote
    audience punt (the route observer mirrors remote routes as
    markers) and get forwarded; local-only topics stay native."""
    from emqx_tpu.cluster.harness import make_cluster, stop, sync
    from emqx_tpu.mqtt import packet as P

    nodes = make_cluster(2)
    n1, n2 = nodes
    server = NativeBrokerServer(port=0, app=n1.app)
    server.start()

    async def main():
        # remote subscriber on node2 via the cluster plane
        ch = _cluster_channel(n2, "rsub")
        ch.handle_in(P.Subscribe(packet_id=1,
                                 topic_filters=[("far/t", {"qos": 0})]))
        sync(nodes)
        assert n1.app.broker.router.has_route("far/t", "node2")

        pub = MqttClient(port=server.port, clientid="np")
        await pub.connect()
        loc = MqttClient(port=server.port, clientid="nl")
        await loc.connect()
        await loc.subscribe("near/t", qos=0)

        # remote-audience topic: every publish punts + forwards
        for i in range(3):
            await pub.publish("far/t", f"f{i}".encode(), qos=0)
            await _settle(0.2)
        got = [p for p in ch.outbox if isinstance(p, P.Publish)]
        assert [p.payload for p in got] == [b"f0", b"f1", b"f2"]

        # local-only topic: still rides the fast path
        await pub.publish("near/t", b"n0", qos=0)
        await loc.recv(timeout=5)
        await _settle()
        await pub.publish("near/t", b"n1", qos=0)
        await loc.recv(timeout=5)
        assert server.fast_stats()["fast_in"] >= 1
        await pub.close(); await loc.close()

    def _cluster_channel(node, clientid):
        from emqx_tpu.broker.channel import Channel

        outbox = []
        ch = Channel(node.app.broker, node.app.cm,
                     send=lambda pkts: outbox.extend(pkts))
        ch.outbox = outbox
        out = ch.handle_in(P.Connect(clientid=clientid, proto_ver=P.MQTT_V5,
                                     clean_start=True))
        assert out[0].reason_code == P.RC_SUCCESS
        return ch

    try:
        run(main())
    finally:
        server.stop()
        stop(nodes)


def test_cross_transport_subscriber_always_served():
    """One app, two transports: a subscriber on the asyncio server must
    receive publishes from a native-listener client forever — its punt
    marker keeps those topics off the native fan-out."""
    from emqx_tpu.broker.server import BrokerServer

    app = BrokerApp()
    nat = NativeBrokerServer(port=0, app=app)
    nat.start()

    async def main():
        aio = BrokerServer(port=0, app=app)
        await aio.start()
        sub_aio = MqttClient(port=aio.port, clientid="xa")
        await sub_aio.connect()
        await sub_aio.subscribe("xt/+", qos=0)
        sub_nat = MqttClient(port=nat.port, clientid="xn")
        await sub_nat.connect()
        await sub_nat.subscribe("xt/+", qos=0)
        pub = MqttClient(port=nat.port, clientid="xp")
        await pub.connect()
        for i in range(4):
            await pub.publish("xt/k", f"x{i}".encode(), qos=0)
            a = await sub_aio.recv(timeout=5)
            n = await sub_nat.recv(timeout=5)
            assert a.payload == n.payload == f"x{i}".encode()
            await _settle(0.2)
        # the asyncio subscriber's punt marker kept the topic slow
        assert nat.fast_stats()["fast_in"] == 0
        await sub_aio.unsubscribe("xt/+")
        await _settle(0.3)
        # with the cross-transport audience gone, the topic can go fast
        await pub.publish("xt/k", b"solo0", qos=0)
        assert (await sub_nat.recv(timeout=5)).payload == b"solo0"
        await _settle()
        await pub.publish("xt/k", b"solo1", qos=0)
        assert (await sub_nat.recv(timeout=5)).payload == b"solo1"
        assert await _wait_fast(nat, "fast_in", 1)
        await sub_aio.close(); await sub_nat.close(); await pub.close()
        await aio.stop()

    run(main())
    nat.stop()


def test_per_topic_ordering_across_permit_transition():
    """A publisher's stream must arrive in order even as its topic
    moves slow→fast mid-stream (permits only apply once the pipeline
    is idle, and host.send enqueues FIFO ahead of fast deliveries)."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="os")
        await sub.connect()
        await sub.subscribe("ord/t", qos=0)
        pub = MqttClient(port=server.port, clientid="op")
        await pub.connect()
        n = 300
        for i in range(n):
            await pub.publish("ord/t", b"%04d" % i, qos=0)
            if i == 20:
                await _settle(0.3)   # let the permit land mid-stream
        got = [await sub.recv(timeout=10) for _ in range(n)]
        assert [g.payload for g in got] == [b"%04d" % i for i in range(n)]
        assert server.fast_stats()["fast_in"] > 0   # transition happened
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos2_stays_on_python_path_until_safe():
    """The round-6 native ack plane owns QoS2 only behind the same
    permit/punt seams as QoS0/1: an UNPERMITTED topic and a topic with
    a punt-class audience (persistent session) must keep the full
    exchange in the Python session — exactly-once state cannot split
    planes mid-audience (tests/test_native_qos2.py covers the native
    side of the seam)."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="q2s")
        await sub.connect()
        await sub.subscribe("q2/t", qos=2)
        # the persistent-session subscriber makes q2/t punt-marked
        ps = MqttClient(port=server.port, clientid="q2-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 60})
        await ps.connect()
        await ps.subscribe("q2/t", qos=2)
        pub = MqttClient(port=server.port, clientid="q2p")
        await pub.connect()
        # no permit yet AND punt audience: every qos2 publish runs the
        # Python exchange (python pids < 32768 toward the subscribers)
        fast0 = server.fast_stats()["fast_in"]
        for i in range(3):
            await pub.publish("q2/t", f"e{i}".encode(), qos=2)
            m = await sub.recv(timeout=5)
            assert m.payload == f"e{i}".encode() and m.qos == 2
            assert m.packet_id < 32768          # python session pid
            mp = await ps.recv(timeout=5)
            assert mp.payload == f"e{i}".encode()
            await _settle(0.2)
        assert server.fast_stats()["fast_in"] == fast0, "qos2 fast-pathed"
        await sub.close(); await ps.close(); await pub.close()

    run(main())
    server.stop()


def test_trace_start_flushes_permits_immediately():
    """Starting a topic trace must immediately pull already-fast topics
    back through Python — a debugging trace cannot wait out the permit
    TTL before seeing messages."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="ts")
        await sub.connect()
        await sub.subscribe("tr/t", qos=0)
        pub = MqttClient(port=server.port, clientid="tp")
        await pub.connect()
        await pub.publish("tr/t", b"w", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("tr/t", b"fast", qos=0)
        await sub.recv(timeout=5)
        assert await _wait_fast(server, "fast_in", 1)
        base = server.fast_stats()["fast_in"]
        app.trace.start("t1", "topic", "tr/#")
        await _settle(0.3)
        for i in range(3):
            await pub.publish("tr/t", f"tr{i}".encode(), qos=0)
            assert (await sub.recv(timeout=5)).payload == f"tr{i}".encode()
            await _settle(0.15)
        assert server.fast_stats()["fast_in"] == base, \
            "traced topic still on the fast path"
        tr = app.trace.traces["t1"]
        assert len(tr.lines) >= 1, "trace captured nothing"
        # stopping the trace frees the topic again
        app.trace.stop("t1")
        await _settle(0.3)
        await pub.publish("tr/t", b"free0", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("tr/t", b"free1", qos=0)
        await sub.recv(timeout=5)
        assert await _wait_fast(server, "fast_in", base + 1)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_duplicate_subscribe_punt_ref_stays_single():
    """Duplicate SUBSCRIBE on a punt-shaped subscription (here: a
    persistent session, the shape a session resume re-fires for every
    restored sub) must not double-count the punt ref — round-4 advisor
    finding: the single ref drop at UNSUBSCRIBE then left the marker in
    the C++ table forever and leaked punt tokens under clientid churn."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="dup-ps",
                         clean_start=False, proto_ver=5,
                         properties={"Session-Expiry-Interval": 300})
        await sub.connect()
        await sub.subscribe("dup/t", qos=1)
        await sub.subscribe("dup/t", qos=1)     # duplicate SUBSCRIBE
        await _settle(0.3)
        assert server._punt_refs and max(
            server._punt_refs.values()) == 1, server._punt_refs
        assert server._token_refs.get("c:dup-ps", 0) == 1
        await sub.unsubscribe("dup/t")
        await _settle(0.3)
        # ONE unsubscribe fully clears the marker and the token refs
        assert not server._punt_refs, server._punt_refs
        assert "c:dup-ps" not in server._token_refs
        await sub.close()

    run(main())
    server.stop()


def test_message_event_rule_blocks_all_permits():
    """A rule on $events/message_delivered consumes per-delivery events
    that only the Python plane fires: while it exists NO topic may hold
    a fast-path permit, or the rule silently misses every fast-path
    delivery (round-4 advisor finding). Creating the rule mid-stream
    must also flush already-granted permits."""
    app = BrokerApp()
    hits = []
    app.rules.register_action("sink", lambda cols, a: hits.append(cols))
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="evs")
        await sub.connect()
        await sub.subscribe("ev/+", qos=0)
        pub = MqttClient(port=server.port, clientid="evp")
        await pub.connect()
        # earn a permit on a rule-free topic
        await pub.publish("ev/t", b"0", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("ev/t", b"1", qos=0)
        await sub.recv(timeout=5)
        assert await _wait_fast(server, "fast_in", 1)
        # a delivered-event rule appears: permits flush, and every
        # subsequent delivery fires the rule (i.e. went through Python)
        app.rules.create_rule(
            "r-ev", 'SELECT topic FROM "$events/message_delivered"',
            [{"function": "sink", "args": {}}])
        await _settle(0.3)
        fast_before = server.fast_stats()["fast_in"]
        n_before = len(hits)
        for i in range(3):
            await pub.publish("ev/t", f"e{i}".encode(), qos=0)
            m = await sub.recv(timeout=5)
            assert m.payload == f"e{i}".encode()
            await _settle(0.2)
        assert len(hits) == n_before + 3, "event rule missed deliveries"
        assert server.fast_stats()["fast_in"] == fast_before
        # deleting the rule re-opens the fast path
        app.rules.delete_rule("r-ev")
        await _settle(0.3)
        await pub.publish("ev/t", b"again", qos=0)
        await sub.recv(timeout=5)
        await _settle()
        await pub.publish("ev/t", b"fast", qos=0)
        await sub.recv(timeout=5)
        assert await _wait_fast(server, "fast_in", fast_before + 1)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_shared_pick_buffer_overflow_and_empty_groups():
    """shared_pick's count and buffer must never desync (round-4
    advisor finding: n advanced even when no pair was written). More
    pickable groups than the buffer holds → the overflowing call writes
    nothing and advances no cursor; the resized retry returns them all,
    exactly once per group (a partial first pass would double-rotate).
    Groups with all members removed are skipped, not emitted as
    garbage."""
    tab = native.NativeSubTable()
    n_groups = 400                       # > the 512-u64 buffer's 256 pairs
    for g in range(1, n_groups + 1):
        tab.shared_add(g, g * 10, "of/+")
        tab.shared_add(g, g * 10 + 1, "of/+")
    # a few emptied groups interleaved: token present, no members
    for g in (5, 77, 300):
        tab.shared_del(g, g * 10, "of/+")
        tab.shared_del(g, g * 10 + 1, "of/+")
    picks = tab.shared_pick("of/x")
    tokens = sorted(p[0] for p in picks)
    want = sorted(g for g in range(1, n_groups + 1) if g not in (5, 77, 300))
    assert tokens == want, (len(tokens), len(want))
    for tok, owner in picks:
        assert owner in (tok * 10, tok * 10 + 1), (tok, owner)
    # each group's cursor advanced EXACTLY once despite the overflow
    # retry: the next pick must rotate to the other 2-member slot
    first = dict(picks)
    for tok, owner in tab.shared_pick("of/x"):
        assert owner != first[tok], (tok, owner, "cursor double-advanced")
    tab.close()


# -- device match lane (VERDICT r4 #2: the device router ON the C++ plane) ---

def _lane_app():
    from emqx_tpu.config.config import Config
    from emqx_tpu.app import BrokerApp

    conf = Config()
    conf.put("router.device.enable", True)
    conf.put("router.device.min_batch", 0)
    return BrokerApp.from_config(conf)


def test_device_lane_end_to_end():
    """Permitted publishes ride the device matcher and fan out in C++:
    lane_in/lane_out advance, qos1 gets a native PUBACK and a pid in
    the native space, and a 150-message burst on one topic arrives in
    order (per-topic FIFO through park → device batch → response)."""
    server = NativeBrokerServer(port=0, app=_lane_app(), device_lane="on")
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="dls")
        await sub.connect()
        await sub.subscribe("dl/+", qos=1)
        pub = MqttClient(port=server.port, clientid="dlp")
        await pub.connect()
        await pub.publish("dl/t", b"warm", qos=0)   # slow path, earns permit
        await sub.recv(timeout=20)
        await _settle(0.5)
        for i in range(4):
            await pub.publish("dl/t", f"q{i}".encode(), qos=1)
            m = await sub.recv(timeout=20)
            assert m.payload == f"q{i}".encode()
            assert m.packet_id is None or m.packet_id >= 32768, m.packet_id
            await asyncio.sleep(0.1)
        st = server.fast_stats()
        assert st["lane_in"] >= 1 and st["lane_out"] >= 1, st
        assert st["native_acks"] >= 1, st
        for i in range(150):
            await pub.publish("dl/t", str(i).encode(), qos=0)
        got = [int((await sub.recv(timeout=20)).payload)
               for _ in range(150)]
        assert got == list(range(150)), got[:10]
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_device_lane_punts_on_punt_class_subscriber():
    """A punt-shaped subscriber (persistent session) joining a laned
    topic flips delivery back to the complete Python fan-out: both the
    native and the punt subscriber receive. The punt is SYNCHRONOUS
    (TryFast consults the punt-only trie before parking — no wasted
    device round trip), so the generic punts counter advances; the
    lane-response punt branch itself is exercised by the sanitizer
    lane driver's flagged responses."""
    server = NativeBrokerServer(port=0, app=_lane_app(), device_lane="on")
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="dps")
        await sub.connect()
        await sub.subscribe("dp/t", qos=0)
        pub = MqttClient(port=server.port, clientid="dpp")
        await pub.connect()
        await pub.publish("dp/t", b"w", qos=0)
        await sub.recv(timeout=20)
        await _settle(0.5)
        await pub.publish("dp/t", b"laned", qos=0)
        await sub.recv(timeout=20)
        assert await _wait_fast(server, "lane_out", 1)
        ps = MqttClient(port=server.port, clientid="dp-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 60})
        await ps.connect()
        await ps.subscribe("dp/t", qos=0)
        await _settle(0.4)
        punts0 = server.fast_stats()["punts"]
        await pub.publish("dp/t", b"both", qos=0)
        assert (await sub.recv(timeout=20)).payload == b"both"
        assert (await ps.recv(timeout=20)).payload == b"both"
        assert await _wait_fast(server, "punts", punts0 + 1)
        await sub.close(); await pub.close(); await ps.close()

    run(main())
    server.stop()


def test_device_lane_disable_drains_to_python():
    """Turning the lane off mid-stream must lose nothing: parked frames
    drain to the Python path in order and delivery continues."""
    server = NativeBrokerServer(port=0, app=_lane_app(), device_lane="on")
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="dds")
        await sub.connect()
        await sub.subscribe("dd/t", qos=0)
        pub = MqttClient(port=server.port, clientid="ddp")
        await pub.connect()
        await pub.publish("dd/t", b"w", qos=0)
        await sub.recv(timeout=20)
        await _settle(0.5)
        for i in range(30):
            await pub.publish("dd/t", str(i).encode(), qos=0)
        server._set_lane(False)        # drains parked frames to Python
        got = [int((await sub.recv(timeout=20)).payload)
               for _ in range(30)]
        assert got == list(range(30)), got[:10]
        # lane off: further traffic walks in C++ (fast_in grows, lane_in
        # stays put)
        lane_in = server.fast_stats()["lane_in"]
        await pub.publish("dd/t", b"walked", qos=0)
        assert (await sub.recv(timeout=20)).payload == b"walked"
        await _settle(0.2)
        assert server.fast_stats()["lane_in"] == lane_in
        assert server.host.lane_backlog() == 0
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_match_filter_union_equals_walk():
    """Differential: for random topics, the union of MatchFilter over
    the oracle's matched filters must equal the walk's match set — the
    invariant the device lane's delivery correctness rests on."""
    from emqx_tpu.router.trie import Trie

    rng = random.Random(11)
    words = ["a", "b", "cc", "d4", "+", "#", ""]
    filters = set()
    while len(filters) < 300:
        parts = []
        for _ in range(rng.randint(1, 6)):
            w = rng.choice(words)
            parts.append(w)
            if w == "#":
                break
        filters.add("/".join(parts))
    filters = sorted(filters)
    table = native.NativeSubTable()
    oracle = Trie()
    for i, f in enumerate(filters):
        table.add(i + 1, f)
        oracle.insert(f)
    for t in _topic_universe(random.Random(12), 2000):
        want = set(table.match(t))
        got = set()
        for f in oracle.match(t):
            got.update(table.match_filter(f))
        assert got == want, (t, sorted(got), sorted(want))
    table.close()


def test_max_qos_cap_enforced_on_fast_path():
    """mqtt.max_qos_allowed must hold even after a topic earns a C++
    permit: an over-cap qos1 publish skips the fast path and gets the
    channel's DISCONNECT 0x9B, never a native PUBACK (round-5 review
    finding)."""
    from emqx_tpu.config.config import Config
    from emqx_tpu.mqtt import packet as P

    conf = Config()
    conf.put("mqtt.max_qos_allowed", 0)
    app = BrokerApp.from_config(conf)
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="mqs")
        await sub.connect()
        await sub.subscribe("cap/t", qos=0)
        pub = MqttClient(port=server.port, clientid="mqp", proto_ver=5)
        await pub.connect()
        # earn the permit at qos0
        for i in range(2):
            await pub.publish("cap/t", f"m{i}".encode(), qos=0)
            await sub.recv(timeout=5)
            await _settle(0.3)
        assert server.fast_stats()["fast_in"] >= 1
        # over-cap publish: raw send (the helper would await a PUBACK
        # that the refusal replaces with DISCONNECT)
        await pub._send(P.Publish(topic="cap/t", payload=b"q1", qos=1,
                                  packet_id=7, properties={}))
        pkt = await pub._expect(P.DISCONNECT, 5)
        assert pkt.reason_code == P.RC_QOS_NOT_SUPPORTED, hex(pkt.reason_code)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_lane_ruled_and_subscribed_filter_delivers_once():
    """Round-5 review finding: a filter that is BOTH subscribed and a
    rule FROM filter appears in the lane response's matched and aux
    lists — without dedup the C++ side delivered the message twice.
    Exactly-once delivery + the rule still firing is the contract."""
    app = _lane_app()
    hits = []
    app.rules.register_action("sink", lambda cols, a: hits.append(cols))
    app.rules.create_rule("same", 'SELECT topic FROM "sr/#"',
                          [{"function": "sink", "args": {}}])
    server = NativeBrokerServer(port=0, app=app, device_lane="on")
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="srs")
        await sub.connect()
        await sub.subscribe("sr/#", qos=0)      # same filter as the rule
        pub = MqttClient(port=server.port, clientid="srp")
        await pub.connect()
        await pub.publish("sr/t", b"w", qos=0)  # slow path, earns permit
        await sub.recv(timeout=20)
        await _settle(0.5)
        for i in range(6):
            await pub.publish("sr/t", f"m{i}".encode(), qos=0)
            m = await sub.recv(timeout=20)
            assert m.payload == f"m{i}".encode()
            await asyncio.sleep(0.15)
        assert await _wait_fast(server, "lane_out", 1)
        # exactly once: no second copy of any payload is queued
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.5)
        assert await _wait_hits(hits, 7), len(hits)   # rule saw them all
        await sub.close(); await pub.close()

    run(main())
    server.stop()
