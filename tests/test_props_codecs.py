"""Property-based differential tests for the round-3 codecs: snappy
(Python vs C++ implementations of one wire format), the exhook proto3
codec (ours vs the official protobuf runtime via dynamic descriptors),
and jq path/arithmetic laws — the prop_emqx_* pattern applied to the
new wire surfaces."""

import json
import string

import pytest
from hypothesis import HealthCheck, given, settings, strategies as st

settings.register_profile(
    "contention", suppress_health_check=[HealthCheck.too_slow],
    deadline=None)
settings.load_profile("contention")

from emqx_tpu.utils.snappy import (compress, decompress, py_compress,
                                   py_decompress)

# -- snappy -------------------------------------------------------------------

blobs = st.one_of(
    st.binary(max_size=4096),
    # repetitive data exercises the copy emitters
    st.builds(lambda chunk, n: chunk * n,
              st.binary(min_size=1, max_size=64),
              st.integers(1, 200)),
)


@given(blobs)
def test_snappy_py_roundtrip(data):
    assert py_decompress(py_compress(data)) == data


@given(blobs)
def test_snappy_cross_implementation(data):
    # each implementation decodes the other's stream
    assert py_decompress(compress(data)) == data
    assert decompress(py_compress(data)) == data


@given(st.binary(max_size=256))
def test_snappy_decoder_never_crashes_on_garbage(data):
    from emqx_tpu.utils.snappy import SnappyError
    for dec in (py_decompress, decompress):
        try:
            dec(data)
        except SnappyError:
            pass                         # rejection is the contract


# -- exhook proto3 codec ------------------------------------------------------

from emqx_tpu.exhook import pbwire

_name = st.text(string.ascii_lowercase, min_size=1, max_size=8)

CLIENT_INFO_VALUES = st.fixed_dictionaries({
    "clientid": _name, "username": _name,
    "peerhost": st.from_regex(r"[0-9]{1,3}\.[0-9]{1,3}", fullmatch=True),
    "sockport": st.integers(0, 65535),
    "is_superuser": st.booleans(), "anonymous": st.booleans(),
})

MESSAGE_VALUES = st.fixed_dictionaries({
    "id": _name, "qos": st.integers(0, 2), "topic": _name,
    "payload": st.binary(max_size=128),
    "timestamp": st.integers(0, 2**63 - 1),
    "headers": st.dictionaries(_name, _name, max_size=4),
})


@given(CLIENT_INFO_VALUES)
def test_pbwire_clientinfo_roundtrip(values):
    out = pbwire.decode(pbwire.CLIENT_INFO,
                        pbwire.encode(pbwire.CLIENT_INFO, values))
    for k, v in values.items():
        assert out[k] == v


@given(MESSAGE_VALUES)
def test_pbwire_message_vs_official_runtime(values):
    google = pytest.importorskip("google.protobuf")
    from google.protobuf import descriptor_pool, message_factory

    from tests.test_exhook_grpc import _dyn_message
    pool = getattr(test_pbwire_message_vs_official_runtime, "_pool", None)
    if pool is None:
        pool = descriptor_pool.DescriptorPool()
        cls = _dyn_message("Message", pbwire.MESSAGE, pool,
                           message_factory)
        test_pbwire_message_vs_official_runtime._pool = pool
        test_pbwire_message_vs_official_runtime._cls = cls
    cls = test_pbwire_message_vs_official_runtime._cls
    official = cls()
    official.ParseFromString(pbwire.encode(pbwire.MESSAGE, values))
    ours = pbwire.decode(pbwire.MESSAGE, official.SerializeToString())
    for k, v in values.items():
        got = dict(getattr(official, k)) if isinstance(v, dict) \
            else getattr(official, k)
        assert got == v, k
        assert ours[k] == v, k


@given(st.binary(max_size=128))
def test_pbwire_decoder_never_crashes_on_garbage(data):
    try:
        pbwire.decode(pbwire.MESSAGE, data)
    except ValueError:
        pass                             # rejection is the contract


# -- jq laws ------------------------------------------------------------------

from emqx_tpu.utils.jq import JqError, jq

json_scalars = st.one_of(st.none(), st.booleans(),
                         st.integers(-10**6, 10**6),
                         st.text(string.printable, max_size=12))
json_values = st.recursive(
    json_scalars,
    lambda inner: st.one_of(
        st.lists(inner, max_size=4),
        st.dictionaries(st.text(string.ascii_lowercase, min_size=1,
                                max_size=6), inner, max_size=4)),
    max_leaves=12)


@given(json_values)
def test_jq_identity_and_tojson_roundtrip(v):
    assert jq(".", v) == [v]
    (s,) = jq("tojson", v)
    assert json.loads(s) == v


@given(st.dictionaries(st.text(string.ascii_lowercase, min_size=1,
                               max_size=6), json_values, max_size=4))
def test_jq_path_equals_direct_access(obj):
    for key in obj:
        assert jq(f'.["{key}"]', obj) == [obj[key]]


@given(st.lists(st.integers(-1000, 1000), max_size=8))
def test_jq_array_laws(xs):
    assert jq("length", xs) == [len(xs)]
    assert jq("reverse | reverse", xs) == [xs]
    assert jq("add", xs) == [sum(xs) if xs else None]
    assert jq("[.[] | . + 1] | length", xs) == [len(xs)]
    (sorted_out,) = jq("sort", xs)
    assert sorted_out == sorted(xs)


@given(st.text(max_size=30))
def test_jq_parser_never_crashes(prog):
    try:
        jq(prog, {})
    except JqError:
        pass                             # rejection is the contract
